"""Jit-boundary validation layer (utils/validate.py): structural checks
and the silent-drop observability report (SURVEY.md §5 race-detection row).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.harness.checkpoint import (
    load_dense_checkpoint,
    save_dense_checkpoint,
)
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.utils.validate import (
    check_ops,
    check_state,
    check_tree_dtype,
    topk_rmv_drop_report,
)


def mk_ops(R=2, B=4, Br=2, D=2, dtype=jnp.int32):
    z = lambda *s: jnp.zeros(s, dtype)  # noqa: E731
    return TopkRmvOps(
        add_key=z(R, B), add_id=z(R, B), add_score=z(R, B),
        add_dc=z(R, B), add_ts=z(R, B),
        rmv_key=z(R, Br), rmv_id=jnp.full((R, Br), -1, dtype),
        rmv_vc=z(R, Br, D),
    )


def test_check_state_accepts_fresh_state():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    check_state(D, D.init(2, 3))  # no raise


def test_check_state_rejects_wrong_capacity():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    D2 = make_dense(n_ids=16, n_dcs=2, size=2, slots_per_id=2)
    with pytest.raises(ValueError, match="shape"):
        check_state(D2, D.init(2, 1))


def test_check_state_rejects_wrong_dtype():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(2, 1)
    # (int64 would silently stay int32 without jax_enable_x64 — use f32.)
    bad = dataclasses.replace(st, slot_ts=st.slot_ts.astype(jnp.float32))
    with pytest.raises(TypeError, match="slot_ts"):
        check_state(D, bad)


def test_check_ops_replica_axis_mismatch():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(3, 1)
    with pytest.raises(ValueError, match="n_replicas"):
        check_ops(st, mk_ops(R=2))
    check_ops(st, mk_ops(R=3))  # no raise


def test_drop_report_separates_padding_from_garbage():
    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(1, 2)
    ops = TopkRmvOps(
        add_key=jnp.asarray([[0, 0, 1, 9]], jnp.int32),
        add_id=jnp.asarray([[1, 7, 2, 0]], jnp.int32),
        add_score=jnp.asarray([[5, 5, 5, 5]], jnp.int32),
        add_dc=jnp.asarray([[0, 0, 5, 0]], jnp.int32),
        add_ts=jnp.asarray([[1, 2, 3, 0]], jnp.int32),  # last = padding
        rmv_key=jnp.asarray([[0, 3]], jnp.int32),
        rmv_id=jnp.asarray([[-1, 1]], jnp.int32),  # first = padding
        rmv_vc=jnp.zeros((1, 2, 2), jnp.int32),
    )
    rep = topk_rmv_drop_report(D, st, ops)
    assert rep["add_padding"] == 1
    assert rep["add_bad_id"] == 1      # id 7 >= I=4
    assert rep["add_bad_dc"] == 1      # dc 5 >= D=2
    assert rep["add_bad_key"] == 0     # key 9 is the padding row
    assert rep["add_dropped_out_of_range"] == 2
    assert rep["rmv_padding"] == 1
    assert rep["rmv_dropped_out_of_range"] == 1  # key 3 >= NK=2
    # The engine itself drops exactly those and converges:
    st2, _ = D.apply_ops(st, ops)
    assert D.value(st2)[0][0] == [(1, 5)]


def test_check_tree_dtype_allows_bool_masks():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    check_tree_dtype(D.init(1, 1), "state")  # lossy is bool: allowed


def test_checkpoint_restore_validates_against_engine():
    import tempfile, os

    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(2, 1)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ck")
        save_dense_checkpoint(p, "topk_rmv", st, step=7)
        step, name, out = load_dense_checkpoint(p, st, dense=D)
        assert step == 7 and name == "topk_rmv"
        # Same bytes, different engine config: restore must refuse.
        D2 = make_dense(n_ids=16, n_dcs=2, size=2, slots_per_id=2)
        with pytest.raises(ValueError):
            load_dense_checkpoint(p, st, dense=D2)


def test_check_ops_engine_dc_width():
    D = make_dense(n_ids=8, n_dcs=2, size=2, slots_per_id=2)
    st = D.init(2, 1)
    bad = mk_ops(R=2, D=5)  # rmv_vc DC width 5 != engine 2
    with pytest.raises(ValueError, match="DC width"):
        check_ops(st, bad, dense=D)
    check_ops(st, mk_ops(R=2, D=2), dense=D)  # no raise
