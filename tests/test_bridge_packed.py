"""Packed-columns grid wire (round 4, `grid_apply_packed`).

The term surface ships one ETF tuple per op; the packed surface ships
one i32-LE binary per COLUMN (server `_PACKED_COLUMNS`). These tests pin
that both wire forms drive the engines identically — exact snapshot
equality, not just observables — and that the packed boundary validates
as loudly as the tuple packers."""

import numpy as np
import pytest
from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)

from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer
from antidote_ccrdt_tpu.bridge.server import _bin_col
from antidote_ccrdt_tpu.core.etf import Atom


@pytest.fixture(scope="module")
def server():
    with BridgeServer() as srv:
        yield srv


@pytest.fixture()
def client(server):
    with BridgeClient(*server.address) as c:
        yield c


def ragged(rng, R, max_b, gen):
    """Per-replica ragged op lists + the matching packed columns."""
    per_replica = []
    counts = rng.integers(0, max_b + 1, R)
    for r in range(R):
        per_replica.append([gen(r) for _ in range(counts[r])])
    return per_replica, counts


def rmv_cols_of(rmvs):
    """topk_rmv rmv group columns (key, id, vc_len, vc_dc, vc_ts) from
    tuple ops — the single place the ragged-vc flattening lives."""
    return cols_of(rmvs, (1, 2)) + [
        np.asarray([len(op[3]) for ops in rmvs for op in ops], np.int32),
        np.asarray(
            [d for ops in rmvs for op in ops for d, _ in op[3]], np.int32),
        np.asarray(
            [t for ops in rmvs for op in ops for _, t in op[3]], np.int32),
    ]


def cols_of(per_replica, fields):
    """Extract packed columns (concatenated in replica order) from tuple
    ops — fields gives each value's position in the tuple."""
    return [
        np.asarray(
            [op[f] for ops in per_replica for op in ops], np.int32
        )
        for f in fields
    ]


TYPE_CASES = {
    "average": dict(
        params=dict(n_replicas=3, n_keys=2),
        gen=lambda rng: lambda r: (
            Atom("add"), int(rng.integers(0, 2)),
            int(rng.integers(-50, 90)), int(rng.integers(0, 4)),
        ),
        tag="add", fields=(1, 2, 3),
    ),
    "topk": dict(
        params=dict(n_replicas=3, n_keys=2, n_ids=32, size=3),
        gen=lambda rng: lambda r: (
            Atom("add"), int(rng.integers(0, 2)),
            int(rng.integers(0, 32)), int(rng.integers(0, 500)),
        ),
        tag="add", fields=(1, 2, 3),
    ),
    "wordcount": dict(
        params=dict(n_replicas=3, n_keys=2, n_buckets=16),
        gen=lambda rng: lambda r: (
            Atom("add"), int(rng.integers(0, 2)), int(rng.integers(0, 16)),
        ),
        tag="add", fields=(1, 2),
    ),
}


@pytest.mark.parametrize("type_name", sorted(TYPE_CASES))
@pytest.mark.parametrize("seed", [0, 1])
def test_packed_matches_tuple_wire_single_tag(client, type_name, seed):
    case = TYPE_CASES[type_name]
    rng = np.random.default_rng(seed)
    R = case["params"]["n_replicas"]
    per_replica, counts = ragged(rng, R, 9, case["gen"](rng))

    gt, gp = f"t_{type_name}_{seed}", f"p_{type_name}_{seed}"
    client.grid_new(gt, type_name, **case["params"])
    client.grid_new(gp, type_name, **case["params"])
    client.grid_apply(gt, per_replica)
    client.grid_apply_packed(
        gp, [(case["tag"], counts, cols_of(per_replica, case["fields"]))]
    )
    assert client.grid_to_binary(gt) == client.grid_to_binary(gp)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_packed_matches_tuple_wire_topk_rmv(client, seed):
    rng = np.random.default_rng(seed)
    R, NK, I, D = 3, 2, 24, 3
    params = dict(n_replicas=R, n_keys=NK, n_ids=I, n_dcs=D, size=4,
                  slots_per_id=2)

    def gen_add(r):
        return (Atom("add"), int(rng.integers(0, NK)),
                int(rng.integers(0, I)), int(rng.integers(0, 300)),
                int(rng.integers(0, D)), int(rng.integers(1, 40)))

    adds, a_counts = ragged(rng, R, 10, gen_add)

    def gen_rmv(r):
        n = int(rng.integers(0, D + 1))
        dcs = rng.permutation(D)[:n]
        return (Atom("rmv"), int(rng.integers(0, NK)),
                int(rng.integers(0, I)),
                [(int(d), int(rng.integers(1, 40))) for d in dcs])

    rmvs, r_counts = ragged(rng, R, 4, gen_rmv)

    gt, gp = f"t_tkr_{seed}", f"p_tkr_{seed}"
    client.grid_new(gt, "topk_rmv", **params)
    client.grid_new(gp, "topk_rmv", **params)
    dom_t = client.grid_apply(
        gt, [a + r for a, r in zip(adds, rmvs)]
    )

    a_cols = cols_of(adds, (1, 2, 3, 4, 5))
    r_cols = rmv_cols_of(rmvs)
    dom_p = client.grid_apply_packed(
        gp, [("add", a_counts, a_cols), ("rmv", r_counts, r_cols)]
    )
    assert dom_t == dom_p
    assert client.grid_to_binary(gt) == client.grid_to_binary(gp)


def test_packed_matches_tuple_wire_leaderboard(client):
    rng = np.random.default_rng(5)
    R, NK, P_ = 2, 1, 16
    params = dict(n_replicas=R, n_keys=NK, n_players=P_, size=3)

    adds, a_counts = ragged(
        rng, R, 8,
        lambda r: (Atom("add"), 0, int(rng.integers(0, P_)),
                   int(rng.integers(0, 200))),
    )
    bans, b_counts = ragged(
        rng, R, 3, lambda r: (Atom("ban"), 0, int(rng.integers(0, P_)))
    )
    client.grid_new("t_lb", "leaderboard", **params)
    client.grid_new("p_lb", "leaderboard", **params)
    client.grid_apply("t_lb", [a + b for a, b in zip(adds, bans)])
    client.grid_apply_packed("p_lb", [
        ("add", a_counts, cols_of(adds, (1, 2, 3))),
        ("ban", b_counts, cols_of(bans, (1, 2))),
    ])
    assert client.grid_to_binary("t_lb") == client.grid_to_binary("p_lb")


def test_packed_matches_tuple_wire_worddoc_device_dedup(client):
    rng = np.random.default_rng(9)
    R, V = 2, 16
    params = dict(n_replicas=R, n_keys=1, n_buckets=V)

    def gen(r):
        return (Atom("doc_add"), 0, int(rng.integers(0, 3)),
                int(rng.integers(0, 12)), int(rng.integers(0, V)))

    docs, counts = ragged(rng, R, 10, gen)
    client.grid_new("t_wd", "worddocumentcount", **params)
    client.grid_new("p_wd", "worddocumentcount", **params)
    client.grid_apply("t_wd", docs)
    client.grid_apply_packed(
        "p_wd", [("doc_add", counts, cols_of(docs, (1, 2, 3, 4)))]
    )
    assert client.grid_to_binary("t_wd") == client.grid_to_binary("p_wd")


def test_packed_validation_is_loud(client):
    client.grid_new("v_tkr", "topk_rmv", n_replicas=2, n_keys=1, n_ids=8,
                    n_dcs=2, size=2, slots_per_id=2)

    def packed(tag, counts, cols):
        return client.grid_apply_packed(
            "v_tkr", [(tag, np.asarray(counts, np.int32),
                       [np.asarray(c, np.int32) for c in cols])]
        )

    with pytest.raises(Exception, match="out of range"):
        packed("add", [1, 0], [[0], [99], [5], [0], [1]])  # id
    with pytest.raises(Exception, match="dc 7 out of range"):
        packed("add", [1, 0], [[0], [1], [5], [7], [1]])
    with pytest.raises(Exception, match="ts 0 out of range"):
        packed("add", [1, 0], [[0], [1], [5], [0], [0]])
    with pytest.raises(Exception, match="replica op counts"):
        packed("add", [1], [[0], [1], [5], [0], [1]])
    with pytest.raises(Exception, match="expected 1"):  # column too long
        packed("add", [1, 0], [[0, 0], [1], [5], [0], [1]])
    with pytest.raises(Exception, match="unknown grid op tag"):
        packed("ban", [1, 0], [[0], [1]])
    with pytest.raises(Exception, match="expected 2"):  # vc cols vs vc_len
        packed("rmv", [1, 0], [[0], [1], [2], [0], [1]])

    client.grid_new("v_wd", "worddocumentcount", n_replicas=1, n_keys=1,
                    n_buckets=8)
    with pytest.raises(Exception, match="mixes doc_add"):
        client.grid_apply_packed("v_wd", [
            ("doc_add", np.asarray([1], np.int32),
             [np.asarray([0], np.int32)] * 4),
            ("add", np.asarray([1], np.int32),
             [np.asarray([0], np.int32), np.asarray([1], np.int32)]),
        ])
    with pytest.raises(Exception, match="multiple of 4"):
        client.call((Atom("grid_apply_packed"), b"v_wd",
                     [(Atom("add"), b"\x01\x00\x00", [b"", b""])]))
    with pytest.raises(Exception, match="duplicate packed group"):
        client.grid_apply_packed("v_wd", [
            ("add", np.asarray([0], np.int32), [np.zeros(0, np.int32)] * 2),
            ("add", np.asarray([0], np.int32), [np.zeros(0, np.int32)] * 2),
        ])


def term_extras_to_packed(extras_per_replica, D):
    """Convert the term surface's per-replica extras lists to the packed
    reply shape for comparison (rmv group then add group, replica-major
    op order — the documented packed emission order)."""
    rmv_counts, add_counts = [], []
    rk, rid, vl, vdc, vts = [], [], [], [], []
    acols = [[] for _ in range(5)]
    for ops in extras_per_replica:
        nr = na = 0
        for op in ops:
            if str(op[0]) == "rmv":
                nr += 1
                rk.append(op[1]); rid.append(op[2])
                vl.append(len(op[3]))
                for d, t in op[3]:
                    vdc.append(d); vts.append(t)
            else:
                na += 1
                for c, v in zip(acols, op[1:]):
                    c.append(v)
        rmv_counts.append(nr); add_counts.append(na)
    return (
        ("rmv", np.asarray(rmv_counts, np.int32),
         [np.asarray(x, np.int32) for x in (rk, rid, vl, vdc, vts)]),
        ("add", np.asarray(add_counts, np.int32),
         [np.asarray(x, np.int32) for x in acols]),
    )


def test_packed_extras_match_term_extras_topk_rmv(client):
    """apply_extras over both wires: identical state AND identical extras
    content; the packed extras feed back through grid_apply_packed to the
    same converged snapshot as the term extras through grid_apply."""
    rng = np.random.default_rng(3)
    R, NK, I, D = 2, 1, 16, 2
    params = dict(n_replicas=R, n_keys=NK, n_ids=I, n_dcs=D, size=3,
                  slots_per_id=2)
    client.grid_new("xt", "topk_rmv", **params)
    client.grid_new("xp", "topk_rmv", **params)

    # Seed both with adds, then a batch whose rmvs uncover (promotions)
    # and whose adds hit fresh tombstones (dominated re-broadcasts).
    seed = [[(Atom("add"), 0, i, 10 * i + r, r, 1 + i) for i in range(6)]
            for r in range(R)]
    client.grid_apply("xt", seed)
    client.grid_apply_packed(
        "xp", [("add", np.full(R, 6, np.int32), cols_of(seed, (1, 2, 3, 4, 5)))]
    )
    batch = [
        [(Atom("rmv"), 0, 3, [(0, 99)]), (Atom("add"), 0, 3, 1, 0, 50)],
        [(Atom("rmv"), 0, 5, [(1, 99)])],
    ]
    ex_term = client.grid_apply_extras("xt", batch)

    a_ops = [[op for op in ops if str(op[0]) == "add"] for ops in batch]
    r_ops = [[op for op in ops if str(op[0]) == "rmv"] for ops in batch]
    a_counts = np.asarray([len(o) for o in a_ops], np.int32)
    r_counts = np.asarray([len(o) for o in r_ops], np.int32)
    ex_packed = client.grid_apply_extras_packed("xp", [
        ("add", a_counts, cols_of(a_ops, (1, 2, 3, 4, 5))),
        ("rmv", r_counts, rmv_cols_of(r_ops)),
    ])
    assert client.grid_to_binary("xt") == client.grid_to_binary("xp")

    want = term_extras_to_packed(ex_term, D)
    assert len(ex_packed) == 2
    for (wtag, wcounts, wcols), (gtag, gcounts, gcols) in zip(want, ex_packed):
        assert wtag == gtag
        np.testing.assert_array_equal(wcounts, gcounts)
        for wc, gc in zip(wcols, gcols):
            np.testing.assert_array_equal(wc, gc)

    # Feedback loop: term extras -> grid_apply; packed extras ->
    # grid_apply_packed; snapshots stay identical.
    if any(ex_term):
        client.grid_apply("xt", ex_term)
        client.grid_apply_packed("xp", ex_packed)
        assert client.grid_to_binary("xt") == client.grid_to_binary("xp")


def test_packed_extras_leaderboard_promotions(client):
    client.grid_new("xlt", "leaderboard", n_replicas=1, n_keys=1,
                    n_players=8, size=2)
    client.grid_new("xlp", "leaderboard", n_replicas=1, n_keys=1,
                    n_players=8, size=2)
    seed = [[(Atom("add"), 0, p, 100 - p) for p in range(4)]]
    client.grid_apply("xlt", seed)
    client.grid_apply_packed(
        "xlp", [("add", np.asarray([4], np.int32), cols_of(seed, (1, 2, 3)))]
    )
    batch = [[(Atom("ban"), 0, 0)]]  # banning the leader promotes
    ex_term = client.grid_apply_extras("xlt", batch)
    ex_packed = client.grid_apply_extras_packed(
        "xlp", [("ban", np.asarray([1], np.int32), cols_of(batch, (1, 2)))]
    )
    assert client.grid_to_binary("xlt") == client.grid_to_binary("xlp")
    assert len(ex_packed) == 1 and ex_packed[0][0] == "add"
    flat = [list(op[1:]) for ops in ex_term for op in ops]
    got = list(zip(*[c.tolist() for c in ex_packed[0][2]]))
    assert [tuple(x) for x in flat] == got


def test_packed_extras_other_types_empty(client):
    client.grid_new("xe_avg", "average", n_replicas=1, n_keys=1)
    out = client.grid_apply_extras_packed("xe_avg", [
        ("add", np.asarray([1], np.int32),
         [np.asarray([0], np.int32), np.asarray([5], np.int32),
          np.asarray([1], np.int32)]),
    ])
    assert out == []


def test_packed_client_rejects_out_of_i32(client):
    """The client must fail loudly on out-of-i32 values — a silent astype
    would truncate 2**40+7 to 7 and corrupt state undetectably (the tuple
    wire's ETF encoder raises on such ints too)."""
    client.grid_new("i32_avg", "average", n_replicas=1, n_keys=1)
    with pytest.raises(ValueError, match="i32 range"):
        client.grid_apply_packed("i32_avg", [
            ("add", np.asarray([1], np.int64),
             [np.asarray([0], np.int64), np.asarray([2**40 + 7], np.int64),
              np.asarray([1], np.int64)]),
        ])


# max_examples=10: every drawn op mix has a different padded batch
# shape, so each example pays a dense-kernel recompile (~3s); 10 keeps
# the duplicate/empty-vc edge coverage at half the wall cost.
@settings(
    max_examples=10, deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    ops=st.lists(
        st.one_of(
            # (add, replica, key, id, score, dc, ts>=1)
            st.tuples(st.just("add"), st.integers(0, 1), st.integers(0, 1),
                      st.integers(0, 11), st.integers(-50, 50),
                      st.integers(0, 2), st.integers(1, 30)),
            # (rmv, replica, key, id, [(dc, ts)])
            st.tuples(st.just("rmv"), st.integers(0, 1), st.integers(0, 1),
                      st.integers(0, 11),
                      st.lists(st.tuples(st.integers(0, 2),
                                         st.integers(1, 30)), max_size=3)),
        ),
        max_size=16,
    ),
)
def test_packed_tuple_parity_property_topk_rmv(ops):
    """Property form of the packed/tuple differential: ANY ragged mix of
    adds and rmvs (duplicate ops, duplicate vc dcs, empty vc lists,
    empty replicas included) drives both wire packers to the identical
    dense state."""
    from antidote_ccrdt_tpu.bridge.server import _Grid

    params = {Atom("n_replicas"): 2, Atom("n_keys"): 2, Atom("n_ids"): 12,
              Atom("n_dcs"): 3, Atom("size"): 3, Atom("slots_per_id"): 2}
    gt, gp = _Grid("topk_rmv", params), _Grid("topk_rmv", params)

    per_replica = [[], []]
    for op in ops:
        if op[0] == "add":
            _, r, k, i, s, d, t = op
            per_replica[r].append((Atom("add"), k, i, s, d, t))
        else:
            _, r, k, i, vc = op
            per_replica[r].append((Atom("rmv"), k, i, vc))
    dom_t = gt.apply(per_replica)

    adds = [[o for o in ops_ if str(o[0]) == "add"] for ops_ in per_replica]
    rmvs = [[o for o in ops_ if str(o[0]) == "rmv"] for ops_ in per_replica]
    groups = [
        ("add", np.asarray([len(a) for a in adds], np.int32),
         cols_of(adds, (1, 2, 3, 4, 5))),
        ("rmv", np.asarray([len(r) for r in rmvs], np.int32),
         rmv_cols_of(rmvs)),
    ]
    wire_groups = [
        (Atom(tag), _bin_col(counts), [_bin_col(c) for c in cols])
        for tag, counts, cols in groups
    ]
    dom_p = gp.apply_packed(wire_groups)
    assert dom_t == dom_p
    assert gt.to_binary() == gp.to_binary()


def test_packed_client_rejects_float_dtype(client):
    """A float column (e.g. 3.7) passes the i32 range check but astype
    would silently truncate it to 3; the tuple wire's ETF encoder rejects
    non-integers, so the packed client must too (ADVICE-r4 #1)."""
    client.grid_new("f_avg", "average", n_replicas=1, n_keys=1)
    with pytest.raises(ValueError, match="integer dtype"):
        client.grid_apply_packed("f_avg", [
            ("add", np.asarray([1], np.int64),
             [np.asarray([0]), np.asarray([3.7]), np.asarray([1])]),
        ])


def test_packed_rmv_duplicate_dc_last_wins(client):
    """Duplicate dc entries within one rmv's vc list must resolve
    last-wins on the packed path, matching the tuple wire's sequential
    overwrite — now explicit in the server scatter (ADVICE-r4 #3), not an
    accident of NumPy fancy-assignment order. The add here (ts=3 at dc 0)
    survives only if the LAST vc entry (ts=1) wins; first-wins (ts=5)
    would remove it, diverging the two snapshots."""
    params = dict(n_replicas=1, n_keys=1, n_ids=8, n_dcs=2, size=2,
                  slots_per_id=2)
    client.grid_new("t_lw", "topk_rmv", **params)
    client.grid_new("p_lw", "topk_rmv", **params)
    add = (Atom("add"), 0, 3, 50, 0, 3)
    rmv = (Atom("rmv"), 0, 3, [(0, 5), (0, 1)])
    client.grid_apply("t_lw", [[add, rmv]])
    client.grid_apply_packed("p_lw", [
        ("add", np.asarray([1], np.int32), cols_of([[add]], (1, 2, 3, 4, 5))),
        ("rmv", np.asarray([1], np.int32), rmv_cols_of([[rmv]])),
    ])
    assert client.grid_to_binary("t_lw") == client.grid_to_binary("p_lw")
    assert client.grid_observe("p_lw") == client.grid_observe("t_lw")


@pytest.mark.parametrize("seed", [0, 1])
def test_packed_multi_matches_sequential(client, seed):
    """grid_apply_packed_multi (one wire call, pipelined dispatches, one
    device sync) must leave the grid in the same state — and return the
    same total dominated count — as the same batches applied through
    sequential grid_apply_packed calls."""
    rng = np.random.default_rng(40 + seed)
    R, NK, I, D = 2, 2, 16, 3
    params = dict(n_replicas=R, n_keys=NK, n_ids=I, n_dcs=D, size=3,
                  slots_per_id=2)
    gs, gm = f"seq_{seed}", f"multi_{seed}"
    client.grid_new(gs, "topk_rmv", **params)
    client.grid_new(gm, "topk_rmv", **params)

    def batch():
        n = rng.integers(1, 6, R)
        adds = [
            [(Atom("add"), int(rng.integers(0, NK)), int(rng.integers(0, I)),
              int(rng.integers(0, 99)), int(rng.integers(0, D)),
              int(rng.integers(1, 30))) for _ in range(n[r])]
            for r in range(R)
        ]
        return [("add", n.astype(np.int32), cols_of(adds, (1, 2, 3, 4, 5)))]

    # Seed tombstones first (high-ts removals on every id) so later adds
    # with lower ts are dominated and a NONZERO count crosses the
    # deferred-count drain — an all-adds mix would pin 0 == 0 only.
    rmvs = [[(Atom("rmv"), 0, i, [(d, 40) for d in range(D)])
             for i in range(I)] for _ in range(R)]
    rmv_batch = [("rmv", np.full(R, I, np.int32), rmv_cols_of(rmvs))]
    batches = [rmv_batch] + [batch() for _ in range(4)]
    total_seq = sum(client.grid_apply_packed(gs, b) for b in batches)
    total_multi = client.grid_apply_packed_multi(gm, batches)
    assert total_multi == total_seq
    assert total_multi > 0  # the deferred path must carry a real count
    assert client.grid_to_binary(gm) == client.grid_to_binary(gs)


@pytest.mark.parametrize("type_name", sorted(TYPE_CASES))
def test_packed_multi_matches_sequential_simple_types(client, type_name):
    """The generic scan-fused multi path must equal sequential
    grid_apply_packed calls for every single-group type, including
    batches of DIFFERENT sizes (exercising the per-plane pad+bucket)."""
    case = TYPE_CASES[type_name]
    rng = np.random.default_rng(11)
    gen = case["gen"](rng)
    R = case["params"]["n_replicas"]
    gs, gm = f"ms_{type_name}", f"mm_{type_name}"
    client.grid_new(gs, type_name, **case["params"])
    client.grid_new(gm, type_name, **case["params"])

    def batch(max_b):
        ops, counts = ragged(rng, R, max_b, gen)
        return [(case["tag"], counts, cols_of(ops, case["fields"]))]

    batches = [batch(3), batch(40), batch(7)]
    seq = sum(client.grid_apply_packed(gs, b) for b in batches)
    multi = client.grid_apply_packed_multi(gm, batches)
    assert multi == seq
    assert client.grid_to_binary(gm) == client.grid_to_binary(gs)


def test_packed_multi_matches_sequential_leaderboard(client):
    rng = np.random.default_rng(12)
    R = 2
    params = dict(n_replicas=R, n_keys=1, n_players=64, size=4)
    client.grid_new("ms_lb", "leaderboard", **params)
    client.grid_new("mm_lb", "leaderboard", **params)

    def batch(na, nb):
        adds = [[(Atom("add"), 0, int(rng.integers(0, 64)),
                  int(rng.integers(1, 999))) for _ in range(na + r)]
                for r in range(R)]
        bans = [[(Atom("ban"), 0, int(rng.integers(0, 64)))
                 for _ in range(nb)] for _ in range(R)]
        return [
            ("add", np.asarray([na, na + 1], np.int32),
             cols_of(adds, (1, 2, 3))),
            ("ban", np.full(R, nb, np.int32), cols_of(bans, (1, 2))),
        ]

    batches = [batch(5, 1), batch(30, 2), batch(2, 0)]
    seq = sum(client.grid_apply_packed("ms_lb", b) for b in batches)
    multi = client.grid_apply_packed_multi("mm_lb", batches)
    assert multi == seq
    assert client.grid_to_binary("mm_lb") == client.grid_to_binary("ms_lb")


def test_packed_multi_worddoc_doc_mode_and_mixed_fallback(client):
    rng = np.random.default_rng(13)
    R, V = 2, 16
    params = dict(n_replicas=R, n_keys=1, n_buckets=V)
    client.grid_new("ms_wd", "worddocumentcount", **params)
    client.grid_new("mm_wd", "worddocumentcount", **params)

    def doc_batch(max_b):
        def gen(r):
            return (Atom("doc_add"), 0, int(rng.integers(0, 3)),
                    int(rng.integers(0, 12)), int(rng.integers(0, V)))
        docs, counts = ragged(rng, R, max_b, gen)
        return [("doc_add", counts, cols_of(docs, (1, 2, 3, 4)))]

    def tok_batch(n):
        toks = [[(Atom("add"), 0, int(rng.integers(0, V)))
                 for _ in range(n)] for _ in range(R)]
        return [("add", np.full(R, n, np.int32), cols_of(toks, (1, 2)))]

    # all-doc_add batches ride the scan; a doc+token mix per CALL falls
    # back to validated sequential applies — both must equal sequential.
    for batches in ([doc_batch(6), doc_batch(20)],
                    [doc_batch(5), tok_batch(9)]):
        seq = sum(client.grid_apply_packed("ms_wd", b) for b in batches)
        multi = client.grid_apply_packed_multi("mm_wd", batches)
        assert multi == seq
        assert client.grid_to_binary("mm_wd") == client.grid_to_binary("ms_wd")


def test_packed_multi_id_packing_and_fallback_agree(client, monkeypatch):
    """The upload-byte id-packing (key/id/dc -> one i32 per add) and the
    unpacked 8-plane fallback must produce identical state and counts;
    the fallback is forced by patching the geometry limit to 0."""
    from antidote_ccrdt_tpu.bridge import server as server_mod

    R, NK, I, D = 2, 2, 16, 3
    params = dict(n_replicas=R, n_keys=NK, n_ids=I, n_dcs=D, size=3,
                  slots_per_id=2)

    def batches():
        rng2 = np.random.default_rng(99)
        out = []
        rmvs = [[(Atom("rmv"), int(rng2.integers(0, NK)), i,
                  [(d, 40) for d in range(D)]) for i in range(I)]
                for _ in range(R)]
        out.append([("rmv", np.full(R, I, np.int32), rmv_cols_of(rmvs))])
        for n in (3, 25):
            adds = [[(Atom("add"), int(rng2.integers(0, NK)),
                      int(rng2.integers(0, I)), int(rng2.integers(0, 99)),
                      int(rng2.integers(0, D)), int(rng2.integers(1, 60)))
                     for _ in range(n + r)] for r in range(R)]
            out.append([("add", np.asarray([n, n + 1], np.int32),
                         cols_of(adds, (1, 2, 3, 4, 5)))])
        return out

    client.grid_new("pk_on", "topk_rmv", **params)
    total_on = client.grid_apply_packed_multi("pk_on", batches())
    snap_on = client.grid_to_binary("pk_on")

    monkeypatch.setattr(server_mod, "_PACKED_IDS_LIMIT", 0)
    client.grid_new("pk_off", "topk_rmv", **params)
    total_off = client.grid_apply_packed_multi("pk_off", batches())
    assert total_on == total_off and total_on > 0
    assert snap_on == client.grid_to_binary("pk_off")


def test_packed_multi_empty_batches_is_noop(client):
    params = dict(n_replicas=1, n_keys=1, n_ids=4, n_dcs=1, size=2,
                  slots_per_id=2)
    client.grid_new("mt_e", "topk_rmv", **params)
    snap = client.grid_to_binary("mt_e")
    assert client.grid_apply_packed_multi("mt_e", []) == 0
    assert client.grid_to_binary("mt_e") == snap


def test_packed_multi_validates_all_batches_before_dispatch(client):
    """A structurally bad batch anywhere in the list rejects the whole
    multi call before ANY batch is applied (the parse pass runs first);
    the error names the failing batch."""
    client.grid_new("mv_av", "average", n_replicas=1, n_keys=1)
    snap = client.grid_to_binary("mv_av")
    good = [("add", np.asarray([1], np.int32),
             [np.zeros(1, np.int32), np.asarray([5], np.int32),
              np.ones(1, np.int32)])]
    bad = [("add", np.asarray([1], np.int32), [np.zeros(1, np.int32)])]
    with pytest.raises(Exception, match="batch 1.*no batch applied"):
        client.grid_apply_packed_multi("mv_av", [good, bad])
    assert client.grid_to_binary("mv_av") == snap


def test_packed_empty_groups_are_noops(client):
    client.grid_new("e_avg", "average", n_replicas=2, n_keys=1)
    snap = client.grid_to_binary("e_avg")
    client.grid_apply_packed("e_avg", [
        ("add", np.zeros(2, np.int32), [np.zeros(0, np.int32)] * 3)
    ])
    assert client.grid_to_binary("e_avg") == snap
