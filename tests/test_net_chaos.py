"""Chaos convergence on the deterministic simulator (net/sim.py).

Four members gossip through a SimNet with seeded loss, duplication,
latency reordering, a partition that forms and heals, and a mid-run
crash — and every survivor must still converge to the sequential
single-process reference digest, for both algebra families:

* topk_rmv (JOIN), gossiped as chained deltas + full anchors
  (`DeltaPublisher` / `sweep_deltas` — lost deltas force the gap->anchor
  resync path under real fault schedules);
* average (MONOID), gossiped as full snapshots through the versioned-row
  lift.

Everything is driven by the drill adapters from scripts/elastic_demo.py
— the exact op streams, adoption discipline, and digests of the real-
process drills — so a convergence failure here is a replication bug, not
a test-harness artifact. Same seed -> bit-identical digests AND
identical fault counters across runs (the simulator owns every
nondeterminism source), which the determinism test pins.
"""

import os
import sys

import pytest

from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import GossipNode
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    my_replicas,
    sweep,
    sweep_deltas,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R, STEPS, reference_digest  # noqa: E402

N = 4  # sim members
DT = 0.1  # virtual seconds per driver round
TIMEOUT = 0.35  # ownership horizon: SUSPECT past this, DEAD past 2x


def run_chaos(type_name, seed, *, loss=0.05, dup=0.05, delta=False):
    """One full chaos run; returns ({member: digest}, fault counters)."""
    net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
    drill = DRILLS[type_name]
    dense = drill.make_engine()
    names = [f"m{i}" for i in range(N)]
    nodes = {m: GossipNode(net.join(m)) for m in names}
    states = {m: drill.init(dense) for m in names}
    cursors = {m: {} for m in names}
    pubs = {
        m: DeltaPublisher(nodes[m], dense, name=drill.publish_name, full_every=4)
        for m in names
    } if delta else {}
    owned = {m: set() for m in names}
    crashed = set()

    def publish_and_sweep(m, seq_hint):
        node = nodes[m]
        view = drill.pub_state(dense, states[m])
        if delta:
            pubs[m].publish(view)
            swept, _ = sweep_deltas(node, dense, view, cursors[m])
        else:
            node.publish(drill.publish_name, view, seq_hint)
            swept, _ = sweep(node, dense, view)
        states[m] = drill.set_view(dense, states[m], swept)

    # Bootstrap: a few fault-free ping rounds so every member knows the
    # full roster before ops start (the drills' start barrier).
    for _ in range(3):
        for m in names:
            nodes[m].heartbeat()
        net.advance(DT)
    for m in names:
        assert set(nodes[m].members()) == set(names), "bootstrap incomplete"

    for step in range(STEPS):
        # The fault schedule (virtual time; entirely seed-deterministic).
        if step == 3:
            net.partition({"m0", "m1"}, {"m2", "m3"})
        if step == 6:
            net.heal()
        if step == 7:
            net.crash("m3")
            crashed.add("m3")
        for m in names:
            if m in crashed:
                continue
            node = nodes[m]
            node.heartbeat()
            # run_worker's discipline: ownership only grows; gained
            # replicas regenerate their full history (deterministic ops).
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), step)
            owned[m] = now_owned
            states[m] = drill.apply(dense, states[m], step, sorted(owned[m]))
            if step % 2 == 0:
                publish_and_sweep(m, step)
        net.advance(DT)

    # Quiescent tail: faults off (the chaos was DURING the run), keep
    # gossiping until every survivor matches the reference. The victim's
    # replicas shift to survivors as its silence crosses confirm-dead.
    net.loss = net.dup = 0.0
    ref = reference_digest(type_name)
    live = [m for m in names if m not in crashed]
    for _ in range(40):
        for m in live:
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), STEPS)
            owned[m] = now_owned
            publish_and_sweep(m, STEPS)
        net.advance(DT)
        if all(drill.digest(dense, states[m]) == ref for m in live):
            break

    digests = {m: drill.digest(dense, states[m]) for m in live}
    return digests, dict(net.metrics.counters)


def test_chaos_join_delta_gossip_converges():
    """JOIN algebra (topk_rmv) over chained-delta gossip under loss +
    duplication + partition + crash: every survivor reaches the exact
    sequential reference, and the fault machinery actually fired."""
    digests, counters = run_chaos("topk_rmv", seed=7, delta=True)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("net.sim_lost", 0) > 0, counters
    assert counters.get("net.sim_duplicated", 0) > 0, counters
    assert counters.get("net.sim_unreachable", 0) > 0, counters  # partition+crash
    assert counters.get("net.dead_events", 0) > 0, counters  # m3 confirmed


def test_chaos_monoid_lift_converges():
    """MONOID algebra (average) through the versioned-row lift survives
    the same fault schedule: duplicated/reordered snapshot delivery must
    not double-count (row-replace is the idempotent join)."""
    digests, counters = run_chaos("average", seed=11, delta=False)
    ref = reference_digest("average")
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("net.sim_lost", 0) > 0, counters


def test_chaos_deterministic_replay():
    """Same seed -> same digests AND same fault counters, bit for bit:
    the property that makes chaos failures replayable."""
    d1, c1 = run_chaos("topk_rmv", seed=3, delta=True)
    d2, c2 = run_chaos("topk_rmv", seed=3, delta=True)
    assert d1 == d2
    assert c1 == c2
    # A different seed draws a different fault schedule (sanity that the
    # seed actually steers the simulation).
    _, c3 = run_chaos("topk_rmv", seed=4, delta=True)
    assert c3 != c1


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_join_snapshot_gossip_seeds(seed):
    """Full-snapshot gossip (no deltas) across several seeds — cheap
    smoke that convergence isn't an artifact of one lucky schedule."""
    digests, _ = run_chaos("topk_rmv", seed=seed, loss=0.1, dup=0.1)
    ref = reference_digest("topk_rmv")
    for m, d in digests.items():
        assert d == ref, f"seed={seed}: {m} diverged\ngot: {d}\nref: {ref}"
