"""Tests for the rebuilt bounded topk (reference: antidote_ccrdt_topk.erl,
rebuilt per SURVEY.md §2 quirk #1 as a real bounded top-K)."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.topk import TopkScalar, TopkState

K = TopkScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_new():
    assert K.new(100) == TopkState({}, 100)
    assert K.new() == TopkState({}, 100)


def test_value_sorted_desc():
    """Port of value_test (topk.erl:178-180): score desc, id desc tiebreak."""
    st = TopkState({"foo": 102, "bar": 101}, 100)
    assert K.value(st) == [("foo", 102), ("bar", 101)]
    st2 = TopkState({1: 5, 2: 5}, 100)
    assert K.value(st2) == [(2, 5), (1, 5)]


def test_downstream_filters():
    """Reference downstream drops ops that can't change state (topk.erl:90-94),
    here with real bounded-top-K semantics."""
    st = TopkState({"foo": 102, "bar": 101}, 2)
    # full and below the min -> noop
    assert K.downstream(("add", ("baz", 1)), st, CTX) is None
    # beats the min -> ships
    assert K.downstream(("add", ("baz", 500)), st, CTX) == ("add", ("baz", 500))
    # dominated update of an existing id -> noop
    assert K.downstream(("add", ("foo", 50)), st, CTX) is None
    # improvement of an existing id -> ships
    assert K.downstream(("add", ("foo", 200)), st, CTX) == ("add", ("foo", 200))
    # room available -> ships
    st_small = TopkState({"foo": 102}, 2)
    assert K.downstream(("add", ("zap", 1)), st_small, CTX) == ("add", ("zap", 1))


def test_update_bounded():
    st = K.new(2)
    st, _ = K.update(("add", (1, 10)), st)
    st, _ = K.update(("add", (2, 20)), st)
    st, _ = K.update(("add", (3, 30)), st)  # evicts id 1
    assert st.entries == {2: 20, 3: 30}
    st, _ = K.update(("add", (2, 5)), st)  # dominated: per-id max keeps 20
    assert st.entries == {2: 20, 3: 30}


def test_update_add_map():
    st = K.new(100)
    st, _ = K.update(("add_map", {"foo": 150, "bar": 200}), st)
    assert st.entries == {"foo": 150, "bar": 200}


def test_compaction_max_merge():
    """Quirk #4 fix: duplicate ids compact to max, not last-wins."""
    dead, merged = K.compact_ops(("add", (1, 50)), ("add", (1, 30)))
    assert dead is None
    assert merged == ("add_map", {1: 50})
    dead, merged = K.compact_ops(("add", (1, 30)), ("add_map", {1: 50, 2: 10}))
    assert merged == ("add_map", {1: 50, 2: 10})
    dead, merged = K.compact_ops(
        ("add_map", {"foo": 150}), ("add_map", {"bar": 200})
    )
    assert merged == ("add_map", {"foo": 150, "bar": 200})


def test_convergence_is_order_independent():
    ops = [("add", (i % 7, (i * 13) % 50)) for i in range(40)]
    st1 = K.new(3)
    for op in ops:
        st1, _ = K.update(op, st1)
    st2 = K.new(3)
    for op in reversed(ops):
        st2, _ = K.update(op, st2)
    assert K.equal(st1, st2)


def test_binary_roundtrip():
    st, _ = K.update(("add", (1, 10)), K.new(5))
    assert K.from_binary(K.to_binary(st)) == st
