"""Tests for the rebuilt bounded topk (reference: antidote_ccrdt_topk.erl,
rebuilt per SURVEY.md §2 quirk #1 as a real bounded top-K)."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.topk import TopkScalar, TopkState

K = TopkScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_new():
    assert K.new(100) == TopkState({}, 100)
    assert K.new() == TopkState({}, 100)


def test_value_sorted_desc():
    """Port of value_test (topk.erl:178-180): score desc, id desc tiebreak."""
    st = TopkState({"foo": 102, "bar": 101}, 100)
    assert K.value(st) == [("foo", 102), ("bar", 101)]
    st2 = TopkState({1: 5, 2: 5}, 100)
    assert K.value(st2) == [(2, 5), (1, 5)]


def test_downstream_filters():
    """Reference downstream drops ops that can't change state (topk.erl:90-94),
    here with real bounded-top-K semantics."""
    st = TopkState({"foo": 102, "bar": 101}, 2)
    # full and below the min -> noop
    assert K.downstream(("add", ("baz", 1)), st, CTX) is None
    # beats the min -> ships
    assert K.downstream(("add", ("baz", 500)), st, CTX) == ("add", ("baz", 500))
    # dominated update of an existing id -> noop
    assert K.downstream(("add", ("foo", 50)), st, CTX) is None
    # improvement of an existing id -> ships
    assert K.downstream(("add", ("foo", 200)), st, CTX) == ("add", ("foo", 200))
    # room available -> ships
    st_small = TopkState({"foo": 102}, 2)
    assert K.downstream(("add", ("zap", 1)), st_small, CTX) == ("add", ("zap", 1))


def test_update_bounded():
    st = K.new(2)
    st, _ = K.update(("add", (1, 10)), st)
    st, _ = K.update(("add", (2, 20)), st)
    st, _ = K.update(("add", (3, 30)), st)  # evicts id 1
    assert st.entries == {2: 20, 3: 30}
    st, _ = K.update(("add", (2, 5)), st)  # dominated: per-id max keeps 20
    assert st.entries == {2: 20, 3: 30}


def test_update_add_map():
    st = K.new(100)
    st, _ = K.update(("add_map", {"foo": 150, "bar": 200}), st)
    assert st.entries == {"foo": 150, "bar": 200}


def test_compaction_max_merge():
    """Quirk #4 fix: duplicate ids compact to max, not last-wins."""
    dead, merged = K.compact_ops(("add", (1, 50)), ("add", (1, 30)))
    assert dead is None
    assert merged == ("add_map", {1: 50})
    dead, merged = K.compact_ops(("add", (1, 30)), ("add_map", {1: 50, 2: 10}))
    assert merged == ("add_map", {1: 50, 2: 10})
    dead, merged = K.compact_ops(
        ("add_map", {"foo": 150}), ("add_map", {"bar": 200})
    )
    assert merged == ("add_map", {"foo": 150, "bar": 200})


def test_convergence_is_order_independent():
    ops = [("add", (i % 7, (i * 13) % 50)) for i in range(40)]
    st1 = K.new(3)
    for op in ops:
        st1, _ = K.update(op, st1)
    st2 = K.new(3)
    for op in reversed(ops):
        st2, _ = K.update(op, st2)
    assert K.equal(st1, st2)


def test_binary_roundtrip():
    st, _ = K.update(("add", (1, 10)), K.new(5))
    assert K.from_binary(K.to_binary(st)) == st


# --- reference-observable compat engine (decision record: VERDICT r1 #4) --


def test_compat_reproduces_reference_quirks():
    from antidote_ccrdt_tpu.models.topk import TopkScalarCompat

    from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext

    C = TopkScalarCompat()
    ctx = ReplicaContext(0, LogicalClock())
    st = C.new()
    assert st.size == 1000  # new/0 -> 1000 (topk.erl:65-66)
    st = C.new(100)
    # "size" is a score THRESHOLD in downstream (topk.erl:164-166)
    assert C.downstream(("add", (1, 100)), st, ctx) is None
    eff = C.downstream(("add", (1, 101)), st, ctx)
    assert eff == ("add", (1, 101))
    st, _ = C.update(eff, st)
    # last-wins update, never prunes (topk.erl:157-158): a LOWER score
    # overwrites (the effect slips through downstream only if > size, but
    # update itself has no guard — apply directly as a replicated effect)
    st, _ = C.update(("add", (1, 50)), st)
    assert st.entries == {1: 50}
    # grow-only beyond "size": 3 more ids than a real top-1 would keep
    for i, s in ((2, 300), (3, 200), (4, 250)):
        st, _ = C.update(("add", (i, s)), st)
    assert len(st.entries) == 4
    assert C.value(st)[0] == (2, 300)


def test_compat_compaction_last_wins_order_dependent():
    from antidote_ccrdt_tpu.models.topk import TopkScalarCompat

    C = TopkScalarCompat()
    # duplicate id: later op's score wins regardless of magnitude
    assert C.can_compact(("add", (7, 900)), ("add", (7, 5)))
    _, op = C.compact_ops(("add", (7, 900)), ("add", (7, 5)))
    assert op == ("add_map", {7: 5})
    _, op = C.compact_ops(("add_map", {7: 5, 8: 1}), ("add_map", {7: 900}))
    assert op == ("add_map", {7: 900, 8: 1})
    # while the rebuilt engine takes max (quirk #4 fix)
    from antidote_ccrdt_tpu.models.topk import TopkScalar

    _, op = TopkScalar().compact_ops(("add", (7, 900)), ("add", (7, 5)))
    assert op == ("add_map", {7: 900})
