"""utils/faults.py: the deterministic fault-injection registry itself.

Replayability is the load-bearing property: every schedule must be a
pure function of (plan seed, per-point hit ordinal), so a failing chaos
run can be replayed bit-identically from its seed. These tests pin that
contract at the registry level; tests/test_fault_matrix.py drives the
same registry through the real transport/bridge/WAL call sites.
"""

import json
import os

import pytest

from antidote_ccrdt_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.uninstall()
    yield
    faults.uninstall()


def test_disabled_is_inert():
    assert faults.ACTIVE is False
    assert faults.fire("anything") == "ok"
    assert faults.mangle("anything", b"abc") == b"abc"
    assert faults.trace() == []
    assert faults.hits("anything") == 0


def test_at_fires_exact_hit_ordinals():
    with faults.injected({"p": [{"action": "drop", "at": [1, 3]}]}):
        got = [faults.fire("p") for _ in range(5)]
    assert got == ["ok", "drop", "ok", "drop", "ok"]


def test_unlisted_point_is_untouched():
    with faults.injected({"p": [{"action": "raise", "at": [0]}]}):
        assert faults.fire("other") == "ok"
        assert faults.hits("other") == 0


def test_raise_is_oserror_subclass():
    with faults.injected({"p": [{"action": "raise", "at": [0], "message": "eio"}]}):
        with pytest.raises(OSError, match="p: eio"):
            faults.fire("p")


def test_truncate_keep_int_and_fraction_and_drop():
    plan = {
        "a": [{"action": "truncate", "at": [0], "keep": 3}],
        "b": [{"action": "truncate", "at": [0], "keep": 0.5}],
        "c": [{"action": "drop", "at": [0]}],
    }
    with faults.injected(plan):
        assert faults.mangle("a", b"abcdef") == b"abc"
        assert faults.mangle("b", b"abcdef") == b"abc"
        assert faults.mangle("c", b"abcdef") is None
        # Past the `at` window the payload flows through untouched.
        assert faults.mangle("a", b"abcdef") == b"abcdef"


def test_max_fires_caps_a_rate_spec():
    plan = {"p": [{"action": "drop", "rate": 1.0, "max_fires": 2}]}
    with faults.injected(plan):
        got = [faults.fire("p") for _ in range(5)]
    assert got == ["drop", "drop", "ok", "ok", "ok"]


def test_rate_schedule_replays_from_seed():
    plan = {"p": [{"action": "drop", "rate": 0.4}]}

    def run():
        with faults.injected(plan, seed=1234):
            out = [faults.fire("p") for _ in range(50)]
            return out, faults.trace()

    out1, tr1 = run()
    out2, tr2 = run()
    assert out1 == out2
    assert tr1 == tr2
    assert 0 < out1.count("drop") < 50  # actually probabilistic
    # The trace carries (point, hit ordinal, action) for each fire.
    for point, hit, action in tr1:
        assert point == "p" and action == "drop" and out1[hit] == "drop"


def test_point_schedules_are_independent():
    """Hitting point B must not shift point A's schedule: each point's
    RNG is seeded from (seed, name) and advanced by its own hits only."""
    plan = {
        "a": [{"action": "drop", "rate": 0.5}],
        "b": [{"action": "drop", "rate": 0.5}],
    }
    with faults.injected(plan, seed=7):
        solo = [faults.fire("a") for _ in range(30)]
    with faults.injected(plan, seed=7):
        interleaved = []
        for _ in range(30):
            faults.fire("b")
            interleaved.append(faults.fire("a"))
            faults.fire("b")
    assert solo == interleaved


def test_different_seed_different_schedule():
    plan = {"p": [{"action": "drop", "rate": 0.5}]}
    with faults.injected(plan, seed=1):
        s1 = [faults.fire("p") for _ in range(64)]
    with faults.injected(plan, seed=2):
        s2 = [faults.fire("p") for _ in range(64)]
    assert s1 != s2


def test_first_matching_spec_wins_but_draws_are_consumed():
    """Spec order resolves conflicts; the rate draw happens per rate-
    bearing spec per hit regardless, keeping later specs' schedules
    independent of earlier specs' `at` lists."""
    plan = {"p": [
        {"action": "delay", "at": [0], "delay_s": 0.0},
        {"action": "drop", "rate": 1.0},
    ]}
    with faults.injected(plan):
        assert faults.fire("p") == "delay"  # first spec shadows the rate spec
        assert faults.fire("p") == "drop"


def test_env_roundtrip(monkeypatch):
    payload = faults.plan_to_env(
        {"wal.fsync": [{"action": "raise", "at": [2]}]}, seed=99
    )
    json.loads(payload)  # valid JSON for a subprocess env
    monkeypatch.setenv(faults.ENV_VAR, payload)
    assert faults.install_from_env() is True
    assert faults.ACTIVE
    assert faults.fire("wal.fsync") == "ok"
    assert faults.fire("wal.fsync") == "ok"
    with pytest.raises(faults.InjectedFault):
        faults.fire("wal.fsync")
    faults.uninstall()
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.install_from_env() is False
    assert not faults.ACTIVE


def test_install_from_env_in_subprocess():
    """The supervisor -> worker path: the env payload alone reproduces
    the schedule in a fresh interpreter (no pickling, no imports of the
    supervisor's state)."""
    import subprocess
    import sys

    payload = faults.plan_to_env(
        {"p": [{"action": "drop", "rate": 0.5}]}, seed=42
    )
    code = (
        "from antidote_ccrdt_tpu.utils import faults\n"
        "faults.install_from_env()\n"
        "print(''.join('d' if faults.fire('p')=='drop' else '.' "
        "for _ in range(40)))\n"
    )
    env = dict(os.environ, **{faults.ENV_VAR: payload})
    env.pop("XLA_FLAGS", None)
    outs = {
        subprocess.run(
            [sys.executable, "-c", code], env=env, text=True,
            capture_output=True, check=True, timeout=120,
        ).stdout
        for _ in range(2)
    }
    assert len(outs) == 1  # identical schedule across processes
    assert "d" in next(iter(outs))


def test_bad_action_rejected():
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultSpec("explode")
