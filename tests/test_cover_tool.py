"""The in-repo coverage/type gates (scripts/cover.py): executable-line
ground truth, shard merge, and subprocess (child) coverage — the gate
itself must be trustworthy since `make all` enforces its number."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.cover import executable_lines  # noqa: E402


def test_executable_lines_ground_truth(tmp_path):
    p = tmp_path / "m.py"
    p.write_text(
        "x = 1\n"
        "\n"
        "def f(a):\n"
        "    if a:\n"
        "        return 2\n"
        "    return 3\n"
    )
    lines = executable_lines(str(p))
    assert {1, 3, 4, 5, 6} <= lines
    assert 2 not in lines  # blank line is not executable


def test_executable_lines_syntax_error_is_empty(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("def f(:\n")
    assert executable_lines(str(p)) == set()


def test_child_cover_dumps_shard(tmp_path):
    code = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from scripts.cover import install_child_cover\n"
        "install_child_cover()\n"
        "from antidote_ccrdt_tpu.models.wordcount import hash_token\n"
        "hash_token('abc', 8)\n"
    )
    env = dict(os.environ, CCRDT_COVER_DIR=str(tmp_path))
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    shards = list(tmp_path.glob("child-*.json"))
    assert len(shards) == 1
    data = json.load(open(shards[0]))
    wc = [fn for fn in data if fn.endswith("wordcount.py")]
    assert wc and len(data[wc[0]]) > 5


def test_child_cover_noop_without_env(tmp_path):
    code = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from scripts.cover import install_child_cover\n"
        "install_child_cover()\n"
        "print('ok')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "CCRDT_COVER_DIR"}
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0 and "ok" in proc.stdout
    assert not list(tmp_path.glob("child-*.json"))
