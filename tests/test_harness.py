"""Multi-DC replay harness: convergence across replicas for every type,
and fault injection demonstrating which delivery guarantees matter."""

import numpy as np
import pytest

from antidote_ccrdt_tpu.harness.opgen import Workload, prepare_stream
from antidote_ccrdt_tpu.harness.replay import FaultInjector, ScalarReplay
from antidote_ccrdt_tpu.models.average import AverageScalar
from antidote_ccrdt_tpu.models.leaderboard import LeaderboardScalar
from antidote_ccrdt_tpu.models.topk import TopkScalar
from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
from antidote_ccrdt_tpu.models.wordcount import WordcountScalar


@pytest.mark.parametrize(
    "crdt,new_args,rmv_frac,rmv_kind",
    [
        (TopkRmvScalar(), (5,), 0.25, "rmv"),
        (TopkRmvScalar(), (3,), 0.5, "rmv"),
        (LeaderboardScalar(), (5,), 0.1, "ban"),
        (TopkScalar(), (5,), 0.0, "rmv"),
    ],
)
def test_scalar_replay_converges(crdt, new_args, rmv_frac, rmv_kind):
    wl = Workload(
        n_replicas=4, n_ids=30, rmv_frac=rmv_frac, rmv_kind=rmv_kind, seed=42
    )
    rp = ScalarReplay(crdt, wl.n_replicas, new_args=new_args)
    rp.run(prepare_stream(wl, 200))
    assert rp.converged(), crdt.type_name


def test_scalar_replay_converges_with_interleaved_syncs():
    """Ops submitted between syncs see partial remote knowledge — the
    concurrent multi-master case; must still converge."""
    crdt = TopkRmvScalar()
    wl = Workload(n_replicas=3, n_ids=20, rmv_frac=0.3, seed=7)
    rp = ScalarReplay(crdt, wl.n_replicas, new_args=(4,))
    ops = list(prepare_stream(wl, 150))
    for chunk in np.array_split(np.arange(len(ops)), 5):
        for j in chunk:
            rp.submit(*ops[j])
        rp.sync()
    assert rp.converged()


def test_average_replay_mean():
    crdt = AverageScalar()
    rp = ScalarReplay(crdt, 2)
    for origin, v in [(0, 4), (1, 8), (0, 6)]:
        rp.submit(origin, ("add", v))
    rp.sync()
    assert rp.converged()
    assert rp.values()[0] == 6.0


def test_wordcount_replay():
    crdt = WordcountScalar()
    rp = ScalarReplay(crdt, 3)
    rp.submit(0, ("add", "a b"))
    rp.submit(1, ("add", "b c"))
    rp.sync()
    assert rp.converged()
    assert rp.values()[0] == {"a": 1, "b": 2, "c": 1}


def test_duplication_breaks_monoid_types():
    """The op-based pipeline relies on exactly-once delivery: duplicating
    non-idempotent effect ops diverges state — the reference's implicit
    host assumption (SURVEY.md §1), made visible."""
    crdt = AverageScalar()
    rp = ScalarReplay(crdt, 2, faults=FaultInjector(dup_prob=1.0, seed=1))
    rp.submit(0, ("add", 10))
    rp.sync()
    # replica 1 saw the op twice
    assert rp.states[0] == (10, 1)
    assert rp.states[1] == (20, 2)
    assert not rp.converged()


def test_duplication_harmless_for_topk_rmv():
    """Add-wins top-K updates are idempotent (set-union masked state), so
    duplicate delivery does not diverge the observable."""
    wl = Workload(n_replicas=3, n_ids=15, rmv_frac=0.3, seed=3)
    rp = ScalarReplay(
        TopkRmvScalar(), wl.n_replicas, new_args=(4,),
        faults=FaultInjector(dup_prob=0.5, seed=2),
    )
    rp.run(prepare_stream(wl, 120))
    assert rp.converged()


def test_drop_breaks_convergence():
    wl = Workload(n_replicas=2, n_ids=10, rmv_frac=0.0, seed=5)
    rp = ScalarReplay(
        TopkRmvScalar(), 2, new_args=(8,),
        faults=FaultInjector(drop_prob=0.7, seed=4),
    )
    rp.run(prepare_stream(wl, 80))
    assert not rp.converged()
