"""Distribution layer: id-space sharding (frontier exchange) and
hierarchical collectives, differentially tested against the unsharded
dense engine on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from antidote_ccrdt_tpu.utils.jaxcompat import shard_map

from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.parallel.dist import lattice_all_reduce, make_mesh
from antidote_ccrdt_tpu.parallel.sharded import (
    hierarchical_all_reduce,
    make_id_sharded_topk_rmv,
    make_mesh2,
)

R, I_GLOBAL, D_DCS, K, M, B, Br = 2, 64, 2, 5, 2, 32, 8


def gen_ops(rng, rounds):
    """Global-id op batches with per-DC monotone clocks (causally plausible)."""
    out = []
    clock = np.zeros(R, np.int64)
    for _ in range(rounds):
        add_id = rng.integers(0, I_GLOBAL, (R, B)).astype(np.int32)
        add_score = rng.integers(1, 1000, (R, B)).astype(np.int32)
        add_dc = np.broadcast_to(np.arange(R, dtype=np.int32)[:, None], (R, B)).copy()
        add_ts = np.zeros((R, B), np.int32)
        for r in range(R):
            add_ts[r] = np.arange(1, B + 1) + clock[r]
        rmv_id = rng.integers(0, I_GLOBAL, (R, Br)).astype(np.int32)
        rmv_vc = np.broadcast_to(clock.astype(np.int32)[None, None, :], (R, Br, R)).copy()
        clock += B
        out.append(
            TopkRmvOps(
                add_key=jnp.zeros((R, B), jnp.int32),
                add_id=jnp.asarray(add_id),
                add_score=jnp.asarray(add_score),
                add_dc=jnp.asarray(add_dc),
                add_ts=jnp.asarray(add_ts),
                rmv_key=jnp.zeros((R, Br), jnp.int32),
                rmv_id=jnp.asarray(rmv_id),
                rmv_vc=jnp.asarray(rmv_vc),
            )
        )
    return out


def obs_tuples(obs):
    """Comparable per-(replica, key) list of (id, score) among valid slots."""
    ids, scores, valid = map(np.asarray, (obs.ids, obs.scores, obs.valid))
    out = []
    for r in range(ids.shape[0]):
        out.append(
            [
                (int(i), int(s))
                for i, s, v in zip(ids[r, 0], scores[r, 0], valid[r, 0])
                if v
            ]
        )
    return out


def test_id_sharded_apply_matches_unsharded():
    mesh = make_mesh(n_dc=2, n_key=2)
    sharded = make_id_sharded_topk_rmv(
        mesh, I_GLOBAL, D_DCS, size=K, slots_per_id=M, n_replicas=R
    )
    ref = make_dense(n_ids=I_GLOBAL, n_dcs=D_DCS, size=K, slots_per_id=M)

    rng = np.random.default_rng(0)
    st_sh = sharded.init()
    st_ref = ref.init(n_replicas=R, n_keys=1)
    for ops in gen_ops(rng, 3):
        st_sh = sharded.apply_ops(st_sh, ops)
        st_ref, _ = ref.apply_ops(st_ref, ops, collect_dominated=False)

    assert obs_tuples(sharded.observe(st_sh)) == obs_tuples(ref.observe(st_ref))


def test_id_sharded_merge_replicas_converges():
    mesh = make_mesh(n_dc=2, n_key=2)
    sharded = make_id_sharded_topk_rmv(
        mesh, I_GLOBAL, D_DCS, size=K, slots_per_id=M, n_replicas=R
    )
    ref = make_dense(n_ids=I_GLOBAL, n_dcs=D_DCS, size=K, slots_per_id=M)

    rng = np.random.default_rng(1)
    st_sh = sharded.init()
    st_ref = ref.init(n_replicas=R, n_keys=1)
    for ops in gen_ops(rng, 2):
        st_sh = sharded.apply_ops(st_sh, ops)
        st_ref, _ = ref.apply_ops(st_ref, ops, collect_dominated=False)

    st_sh = sharded.merge_replicas(st_sh)
    obs = obs_tuples(sharded.observe(st_sh))
    # all replicas converged...
    assert all(row == obs[0] for row in obs)
    # ...to the unsharded pairwise-merge result
    a = jax.tree.map(lambda x: x[:1], st_ref)
    b = jax.tree.map(lambda x: x[1:], st_ref)
    merged_ref = ref.merge(a, b)
    assert obs[0] == obs_tuples(ref.observe(merged_ref))[0]


def test_id_sharded_removal_crosses_shards():
    """A removal generated from one shard's id range must tombstone the
    element wherever it lives (ops are global; each shard masks)."""
    mesh = make_mesh(n_dc=2, n_key=2)
    sharded = make_id_sharded_topk_rmv(
        mesh, I_GLOBAL, D_DCS, size=K, slots_per_id=M, n_replicas=R
    )
    st = sharded.init()
    # id 40 lives in shard 1 (I_local = 32)
    ops_add = TopkRmvOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.full((R, 1), 40, jnp.int32),
        add_score=jnp.full((R, 1), 9, jnp.int32),
        add_dc=jnp.zeros((R, 1), jnp.int32),
        add_ts=jnp.ones((R, 1), jnp.int32),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, D_DCS), jnp.int32),
    )
    st = sharded.apply_ops(st, ops_add)
    assert obs_tuples(sharded.observe(st))[0] == [(40, 9)]
    ops_rmv = TopkRmvOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.zeros((R, 1), jnp.int32),
        add_score=jnp.zeros((R, 1), jnp.int32),
        add_dc=jnp.zeros((R, 1), jnp.int32),
        add_ts=jnp.zeros((R, 1), jnp.int32),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), 40, jnp.int32),
        rmv_vc=jnp.ones((R, 1, D_DCS), jnp.int32),
    )
    st = sharded.apply_ops(st, ops_rmv)
    assert obs_tuples(sharded.observe(st))[0] == []


def test_hierarchical_all_reduce_matches_flat():
    mesh = make_mesh2(n_dcn=2, n_dc=2, n_key=2)

    x = jnp.arange(8, dtype=jnp.int32).reshape(2, 2, 2)

    def hier(v):
        return hierarchical_all_reduce(v, jnp.maximum, mesh)

    def flat_dc_then_dcn(v):
        v = lattice_all_reduce(v, "dc", jnp.maximum, 2)
        return lattice_all_reduce(v, "dcn", jnp.maximum, 2)

    out = jax.jit(
        shard_map(
            hier,
            mesh=mesh,
            in_specs=(P("dcn", "dc", "key"),),
            out_specs=P("dcn", "dc", "key"),
        )
    )(x)
    # every (dcn, dc) member holds the max over both axes for its key shard
    expect = np.asarray(x).max(axis=(0, 1), keepdims=True)
    assert np.array_equal(np.asarray(out), np.broadcast_to(expect, (2, 2, 2)))


# --- player-space-sharded leaderboard -------------------------------------


def _lb_ops(rng, R=4, B=32, Bb=6, P_GLOBAL=64):
    from antidote_ccrdt_tpu.models.leaderboard import LeaderboardOps

    return LeaderboardOps(
        add_key=jnp.zeros((R, B), jnp.int32),
        add_id=jnp.asarray(rng.integers(0, P_GLOBAL, (R, B)).astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
        add_valid=jnp.ones((R, B), bool),
        ban_key=jnp.zeros((R, Bb), jnp.int32),
        ban_id=jnp.asarray(rng.integers(0, P_GLOBAL, (R, Bb)).astype(np.int32)),
        ban_valid=jnp.ones((R, Bb), bool),
    )


@pytest.mark.parametrize("seed", range(3))
def test_id_sharded_leaderboard_matches_unsharded(seed):
    from antidote_ccrdt_tpu.models.leaderboard import make_dense as mk_lb
    from antidote_ccrdt_tpu.parallel.sharded import make_id_sharded_leaderboard

    rng = np.random.default_rng(seed)
    mesh = make_mesh2(1, 4, 2)
    S = make_id_sharded_leaderboard(mesh, n_players_global=64, size=4)
    st = S.init()
    Dref = mk_lb(n_players=64, size=4)
    ref = Dref.init(4, 1)
    for _ in range(3):
        ops = _lb_ops(rng)
        st = S.apply_ops(st, ops)
        ref, _ = Dref.apply_ops(ref, ops)
    st = S.merge_replicas(st)
    folded = jax.tree.map(lambda x: x[:1], ref)
    for r in range(1, 4):
        folded = Dref.merge(folded, jax.tree.map(lambda x: x[r:r + 1], ref))
    ids, scores, valid = S.observe(st)
    rid, rsc, rva = Dref.observe(folded)
    for r in range(4):  # every replica converged to the reference
        assert np.array_equal(
            np.asarray(jnp.where(valid[r], ids[r], -1)),
            np.asarray(jnp.where(rva[0], rid[0], -1)),
        )
        assert np.array_equal(
            np.asarray(jnp.where(valid[r], scores[r], 0)),
            np.asarray(jnp.where(rva[0], rsc[0], 0)),
        )


def test_id_sharded_leaderboard_ban_crosses_shards():
    """A ban originating at one replica kills the player on every shard's
    view after merge: ban-wins (leaderboard.erl:21-27) survives sharding."""
    from antidote_ccrdt_tpu.models.leaderboard import LeaderboardOps
    from antidote_ccrdt_tpu.parallel.sharded import make_id_sharded_leaderboard

    mesh = make_mesh2(1, 4, 2)
    S = make_id_sharded_leaderboard(mesh, n_players_global=64, size=4)
    st = S.init()
    R = 4
    # player 40 (second shard's range) gets the best score from replica 0
    ops = LeaderboardOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.full((R, 1), 40, jnp.int32),
        add_score=jnp.asarray([[500], [400], [300], [200]], jnp.int32),
        add_valid=jnp.ones((R, 1), bool),
        ban_key=jnp.zeros((R, 1), jnp.int32),
        ban_id=jnp.full((R, 1), -1, jnp.int32),
        ban_valid=jnp.zeros((R, 1), bool),
    )
    st = S.apply_ops(st, ops)
    # replica 3 bans player 40
    ban = LeaderboardOps(
        add_key=jnp.zeros((R, 1), jnp.int32),
        add_id=jnp.zeros((R, 1), jnp.int32),
        add_score=jnp.zeros((R, 1), jnp.int32),
        add_valid=jnp.zeros((R, 1), bool),
        ban_key=jnp.zeros((R, 1), jnp.int32),
        ban_id=jnp.full((R, 1), 40, jnp.int32),
        ban_valid=jnp.asarray([[False], [False], [False], [True]]),
    )
    st = S.apply_ops(st, ban)
    st = S.merge_replicas(st)
    ids, scores, valid = S.observe(st)
    flat = np.asarray(jnp.where(valid, ids, -1))
    assert not (flat == 40).any(), "banned player visible after merge"


@pytest.mark.parametrize("seed", range(2))
def test_id_sharded_topk_matches_unsharded(seed):
    from antidote_ccrdt_tpu.models.topk import TopkOps
    from antidote_ccrdt_tpu.models.topk import make_dense as mk_topk
    from antidote_ccrdt_tpu.parallel.sharded import make_id_sharded_topk

    rng = np.random.default_rng(seed)
    mesh = make_mesh2(1, 4, 2)
    S = make_id_sharded_topk(mesh, n_ids_global=64, size=4)
    st = S.init()
    Dref = mk_topk(n_ids=64, size=4)
    ref = Dref.init(4, 1)
    for _ in range(3):
        ops = TopkOps(
            key=jnp.zeros((4, 24), jnp.int32),
            id=jnp.asarray(rng.integers(0, 64, (4, 24)).astype(np.int32)),
            score=jnp.asarray(rng.integers(1, 900, (4, 24)).astype(np.int32)),
            valid=jnp.ones((4, 24), bool),
        )
        st = S.apply_ops(st, ops)
        ref, _ = Dref.apply_ops(ref, ops)
    st = S.merge_replicas(st)
    folded = jax.tree.map(lambda x: x[:1], ref)
    for r in range(1, 4):
        folded = Dref.merge(folded, jax.tree.map(lambda x: x[r:r + 1], ref))
    ids, scores, valid = S.observe(st)
    rid, rsc, rva = Dref.observe(folded)
    for r in range(4):
        assert np.array_equal(
            np.asarray(jnp.where(valid[r], ids[r], -1)),
            np.asarray(jnp.where(rva[0], rid[0], -1)),
        )
        assert np.array_equal(
            np.asarray(jnp.where(valid[r], scores[r], 0)),
            np.asarray(jnp.where(rva[0], rsc[0], 0)),
        )


# --- dist.py primitives ---------------------------------------------------


def test_lattice_all_reduce_non_power_of_two_falls_back():
    """A 3-wide axis must take the gather-reduce path and still produce
    the full merge on every shard (with a non-commutative-looking but
    associative max combiner over pytrees)."""
    devs = jax.devices()[:6]
    mesh = jax.sharding.Mesh(np.asarray(devs).reshape(3, 2), ("dc", "key"))

    def local(x):
        red = lattice_all_reduce(x, "dc", lambda a, b: jax.tree.map(jnp.maximum, a, b), 3)
        return red

    x = jnp.arange(3 * 2 * 4, dtype=jnp.int32).reshape(3, 2, 4)
    out = jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=P("dc", "key"), out_specs=P("dc", "key"),
            check_vma=False,
        )
    )(x)
    out = np.asarray(out)
    expect = np.asarray(jnp.max(x, axis=0))  # every dc row = max over rows
    for r in range(3):
        assert np.array_equal(out[r], expect)


def test_shard_state_and_ops_placement():
    from antidote_ccrdt_tpu.parallel.dist import (
        make_mesh,
        replica_sharding,
        shard_ops,
        shard_state,
    )

    mesh = make_mesh(n_dc=4, n_key=2)
    state = {"t": jnp.zeros((4, 2, 8), jnp.int32)}
    ops = {"a": jnp.zeros((4, 16), jnp.int32)}
    st = shard_state(state, mesh)
    op = shard_ops(ops, mesh)
    assert st["t"].sharding == replica_sharding(mesh)
    assert st["t"].sharding.spec == P("dc", "key")
    assert op["a"].sharding.spec == P("dc")


@pytest.mark.parametrize("seed", range(2))
def test_vocab_sharded_wordcount_matches_unsharded(seed):
    """The MONOID member of the id-space-sharding family: global-token
    batches applied across a (dc, key) mesh, psum reconciliation, must
    equal the unsharded engine's summed rows — including the lost counter
    for out-of-global-range tokens (counted once, not n_shards times)."""
    from antidote_ccrdt_tpu.models.wordcount import WordcountOps
    from antidote_ccrdt_tpu.models.wordcount import make_dense as mk_wc
    from antidote_ccrdt_tpu.parallel.sharded import make_vocab_sharded_wordcount

    rng = np.random.default_rng(seed)
    V_g, R = 64, 4
    mesh = make_mesh2(1, 4, 2)
    S = make_vocab_sharded_wordcount(mesh, n_buckets_global=V_g)
    st = S.init()
    Dref = mk_wc(V_g)
    ref = Dref.init(R, 1)
    for _ in range(3):
        tok = rng.integers(0, V_g, (R, 32)).astype(np.int32)
        tok[:, :3] = -1  # padding
        tok[0, 3] = V_g + 5  # out-of-global-range -> lost, exactly once
        ops = WordcountOps(
            key=jnp.zeros((R, 32), jnp.int32), token=jnp.asarray(tok)
        )
        st = S.apply_ops(st, ops)
        ref, _ = Dref.apply_ops(ref, ops)
    tot = S.global_counts(st)
    counts, lost = tot.counts, tot.lost
    ref_counts = np.asarray(ref.counts).sum(axis=0)  # rows are deltas
    ref_lost = int(np.asarray(ref.lost).sum())
    assert np.array_equal(np.asarray(counts), ref_counts)
    assert int(np.asarray(lost).sum()) == ref_lost == 3
