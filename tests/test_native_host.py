"""Native (C++) host runtime: causal delivery, exactly-once, batching.

Exercises the op-log store + scheduler in native/ccrdt_host.cpp through the
ctypes binding, and end-to-end: native host feeding the dense topk_rmv
kernels with convergence across replicas.
"""

import numpy as np
import pytest

from antidote_ccrdt_tpu.harness import native_host as nh

pytestmark = pytest.mark.skipif(
    not nh.available(), reason=f"native host unavailable: {nh.build_error()}"
)


def test_fifo_and_exactly_once():
    with nh.NativeHost(2) as h:
        ts = [h.submit(0, nh.KIND_ADD, key=0, id_=i, score=10 * i) for i in range(5)]
        assert ts == [1, 2, 3, 4, 5]  # lamport stamps advance
        got = h.drain(1, 10)
        assert list(got["id"]) == [0, 1, 2, 3, 4]  # FIFO per origin
        assert h.drain(1, 10)["id"].size == 0  # exactly once
        assert h.backlog(1) == 0
        assert h.backlog(0) == 5  # origin drains its own ops too


def test_causal_order_across_origins():
    # dc0 adds; dc1 observes it, then removes — no replica may see the rmv
    # before the add it depends on.
    with nh.NativeHost(3) as h:
        h.submit(0, nh.KIND_ADD, key=0, id_=7, score=100)
        # dc1 hasn't drained yet: its next op does NOT depend on dc0's add.
        h.submit(1, nh.KIND_ADD, key=0, id_=8, score=50)
        # now dc1 observes dc0's add, then issues a dependent rmv
        h.drain(1, 10)
        h.submit(1, nh.KIND_RMV, key=0, id_=7, vc=np.array([1, 0, 0], np.int32))
        # replica 2 must receive dc0's add before dc1's rmv
        got = h.drain(2, 10)
        kinds, ids = list(got["kind"]), list(got["id"])
        assert kinds.index(nh.KIND_RMV) > ids.index(7)
        add_pos = [i for i, k in enumerate(kinds) if k == nh.KIND_ADD and ids[i] == 7]
        rmv_pos = [i for i, k in enumerate(kinds) if k == nh.KIND_RMV]
        assert add_pos[0] < rmv_pos[0]


def test_causal_gap_blocks_delivery():
    # An op whose dependency hasn't been delivered must wait, even when the
    # origin's earlier ops are available (dependency via another origin).
    with nh.NativeHost(3) as h:
        h.submit(0, nh.KIND_ADD, key=0, id_=1, score=1)
        h.drain(1, 10)  # dc1 sees dc0's op
        h.submit(1, nh.KIND_ADD, key=0, id_=2, score=2)  # depends on dc0#1
        # Replica 2 can deliver both (dep satisfied by delivering dc0 first).
        got = h.drain(2, 10)
        assert list(got["id"]) == [1, 2]


def test_backpressure_partial_drain():
    with nh.NativeHost(2) as h:
        h.submit_batch(0, kinds=np.zeros(100, np.int32), keys=None,
                       ids=np.arange(100), scores=np.arange(100))
        seen = []
        while True:
            got = h.drain(1, 7)  # tiny batches
            if got["id"].size == 0:
                break
            seen.extend(got["id"].tolist())
        assert seen == list(range(100))
        s = h.stats()
        assert s["submitted"] == 100
        assert s["pending"] == 100  # replica 0 hasn't drained its own ops


def test_submit_batch_stamps():
    with nh.NativeHost(2) as h:
        ts = h.submit_batch(1, kinds=np.zeros(4, np.int32), keys=None,
                            ids=np.arange(4))
        assert list(ts) == [1, 2, 3, 4]


def test_lamport_advances_on_delivery():
    # After draining ops stamped up to ts=5, a replica's next stamp must
    # dominate them (lamport merge on delivery).
    with nh.NativeHost(2) as h:
        for i in range(5):
            h.submit(0, nh.KIND_ADD, key=0, id_=i, score=i)
        h.drain(1, 10)
        ts = h.submit(1, nh.KIND_ADD, key=0, id_=99, score=9)
        assert ts == 6


def test_end_to_end_dense_convergence():
    """3 DCs submit concurrent adds + a causal removal through the native
    host; each replica drains into dense batches and applies them; all
    replicas converge to the same observable top-K."""
    import jax

    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    D = 3
    DT = make_dense(n_ids=32, n_dcs=D, size=4, slots_per_id=4)
    with nh.NativeHost(D) as h:
        rng = np.random.default_rng(0)
        # Round 1: concurrent adds everywhere.
        for dc in range(D):
            n = 10
            h.submit_batch(
                dc,
                kinds=np.zeros(n, np.int32),
                keys=None,
                ids=rng.integers(0, 32, n),
                scores=rng.integers(1, 100, n),
            )
        # Everyone drains round 1 (so the removal below causally depends on
        # all of it), applying as they go.
        states = [DT.init(n_replicas=1, n_keys=1) for _ in range(D)]
        for r in range(D):
            ops, na, nr = h.drain_topk_rmv_ops(r, batch_adds=64, batch_rmvs=8)
            assert na == 30 and nr == 0
            states[r], _ = DT.apply_ops(states[r], ops)
        # Round 2: dc0 removes the current top element, then dc1 re-adds a
        # better one.
        obs = DT.observe(states[0])
        top_id = int(obs.ids[0, 0, 0])
        vc = np.asarray(states[0].vc[0, 0])  # removal vc = everything seen
        h.submit(0, nh.KIND_RMV, key=0, id_=top_id, vc=vc)
        h.drain(1, 0)  # no-op drain; dc1's add is concurrent with the rmv
        h.submit(1, nh.KIND_ADD, key=0, id_=top_id, score=10_000)
        for r in range(D):
            ops, na, nr = h.drain_topk_rmv_ops(r, batch_adds=64, batch_rmvs=8)
            states[r], _ = DT.apply_ops(states[r], ops)
        # All replicas agree; the concurrent re-add wins over the removal.
        for r in range(1, D):
            assert DT.equal(states[0], states[r])
        final = DT.observe(states[0])
        assert int(final.ids[0, 0, 0]) == top_id
        assert int(final.scores[0, 0, 0]) == 10_000
        assert h.stats()["pending"] == 0


def test_drain_split_overflow_carries_without_loss():
    """A drained window whose add/rmv split overflows one side must carry
    the excess to later drains (the drain is exactly-once: raising or
    dropping would lose ops forever). All ops eventually arrive, each
    exactly once."""
    if not nh.available():
        pytest.skip("native toolchain unavailable")
    with nh.NativeHost(2) as h:
        for i in range(50):  # adds only: every drain window is all-adds
            h.submit(0, nh.KIND_ADD, key=0, id_=i, score=i)
        seen = []
        for _ in range(50):
            ops, na, nr = h.drain_topk_rmv_ops(0, batch_adds=8, batch_rmvs=8)
            assert na <= 8 and nr == 0
            ids = [int(x) for x in list(ops.add_id[0])[:na]]
            seen.extend(ids)
            if h.backlog(0) == 0:
                break
        assert sorted(seen) == list(range(50))
        assert h.backlog(0) == 0


def test_zero_capacity_side_raises_instead_of_livelock():
    if not nh.available():
        pytest.skip("native toolchain unavailable")
    with nh.NativeHost(2) as h:
        h.submit(0, nh.KIND_ADD, key=0, id_=1, score=5)
        h.submit(0, nh.KIND_RMV, key=0, id_=1,
                 vc=np.asarray([1, 0], np.int32))
        with pytest.raises(ValueError, match="zero-capacity"):
            for _ in range(5):
                h.drain_topk_rmv_ops(0, batch_adds=4, batch_rmvs=0)
        # ops were carried, not lost: a capable drain delivers them
        ops, na, nr = h.drain_topk_rmv_ops(0, batch_adds=4, batch_rmvs=4)
        assert (na, nr) == (1, 1) and h.backlog(0) == 0
