"""Real-process crash-consistency drill (slow; `make crash-demo`).

Runs scripts/crash_recovery_demo.py: a 3-member shared-directory gossip
fleet with the WAL enabled, the victim SIGKILLed mid-run and restarted.
Asserted twice — recovery through the WAL (checkpoint ⊔ delta suffix,
resume past the last durable step) and, with the WAL deleted, through
the peer-adoption fallback — both converging bit-identically to the
sequential reference.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "scripts", "crash_recovery_demo.py")


def _run(mode):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, DEMO, "--mode", mode],
        capture_output=True, text=True, env=env, timeout=420,
    )
    assert p.returncode == 0, f"drill failed:\n{p.stdout[-4000:]}\n{p.stderr[-2000:]}"
    return json.loads(p.stdout)


@pytest.mark.slow
def test_sigkill_victim_recovers_via_wal():
    (v,) = _run("wal")
    assert v["ok"], v
    assert v["victim_recovered_records"] > 0
    assert v["victim_resume_step"] is not None and v["victim_resume_step"] >= 1


@pytest.mark.slow
def test_sigkill_victim_without_wal_converges_via_adoption():
    (v,) = _run("adopt")
    assert v["ok"], v
    assert v["victim_recovered_records"] == 0
