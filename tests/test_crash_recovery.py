"""Real-process crash-consistency drill (slow; `make crash-demo`).

Runs scripts/crash_recovery_demo.py: a 3-member shared-directory gossip
fleet with the WAL enabled, the victim SIGKILLed mid-run and restarted.
Asserted along two axes — the recovery PATH (through the WAL: checkpoint
⊔ delta suffix, resume past the last durable step; or, with the WAL
deleted, through the peer-adoption fallback) and the DURABILITY
discipline (PR 11: sync fsync-per-append, group commit, and async with
the published-vs-durable watermark — the demo asserts recovery ==
watermark truncation and the obs/audit certifier's durability check).
Every combination converges bit-identically to the sequential reference.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "scripts", "crash_recovery_demo.py")


def _run(mode, durability):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, DEMO, "--mode", mode, "--durability", durability],
        capture_output=True, text=True, env=env, timeout=420,
    )
    assert p.returncode == 0, f"drill failed:\n{p.stdout[-4000:]}\n{p.stderr[-2000:]}"
    return json.loads(p.stdout)


@pytest.mark.slow
@pytest.mark.parametrize("durability", ["sync", "group", "async"])
def test_sigkill_victim_recovers_via_wal(durability):
    (v,) = _run("wal", durability)
    assert v["ok"], v
    assert v["durability"] == durability
    assert v["victim_recovered_records"] > 0
    assert v["victim_resume_step"] is not None and v["victim_resume_step"] >= 1
    if durability in ("group", "async"):
        # The durability-watermark reconciliation must have ACTIVATED
        # (these modes emit wal.durable acks) and passed: any records
        # the SIGKILL dropped past the watermark were audited as
        # re-derived by the restarted incarnation.
        assert v["certifier_checks"].get("durability_watermark") is True, v
    if durability == "async":
        # Recovery == watermark truncation: resume point bracketed by
        # the killed incarnation's last ack and last append.
        assert v["victim_recover_last_step"] >= v["victim_flight_durable"], v
        assert v["victim_recover_last_step"] <= v["victim_flight_last_step"], v


@pytest.mark.slow
def test_sigkill_victim_without_wal_converges_via_adoption():
    (v,) = _run("adopt", "group")
    assert v["ok"], v
    assert v["victim_recovered_records"] == 0
