"""Round-phase span tracer (obs/spans.py): ring bound, the disabled
zero-cost gate, crash-durable spill (SIGKILL drill mirroring
tests/test_obs_events.py), clock-offset estimation under asymmetric RTT
on the sim medium, timeline alignment (BFS over offset edges), Chrome
trace export, and the dispatch-gap attribution math."""

import json
import os
import signal
import subprocess
import sys

import pytest

from antidote_ccrdt_tpu.obs import spans as obs_spans
from antidote_ccrdt_tpu.obs.spans import ClockSync, _union
from antidote_ccrdt_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _plane_down():
    """Every test starts and ends with the span plane disarmed."""
    obs_spans.uninstall()
    yield
    obs_spans.uninstall()


# -- ring + gate --------------------------------------------------------------


def test_ring_is_bounded_and_sids_keep_counting():
    with obs_spans.installed("m", ring=8):
        for i in range(20):
            with obs_spans.span("round.snapshot", i=i):
                pass
        recs = obs_spans.drain()
    # Overflow evicts the OLDEST records — including the clock anchor
    # written at install time; the ring never grows past bound.
    assert len(recs) == 8
    assert all(r["k"] == "span" for r in recs)
    assert [r["i"] for r in recs] == list(range(12, 20))
    # sid is the process ordinal, not a ring index: it keeps counting
    # across eviction.
    assert [r["sid"] for r in recs] == list(range(13, 21))


def test_disabled_plane_is_a_no_op():
    assert obs_spans.ACTIVE is False
    # begin returns None; end(None) is tolerated; span yields; the
    # exchange feed and drain are no-ops. This is the zero-cost contract
    # call sites rely on behind `if spans.ACTIVE:`.
    tok = obs_spans.begin("round.snapshot")
    assert tok is None
    obs_spans.end(tok)
    with obs_spans.span("round.snapshot"):
        pass
    obs_spans.observe_exchange("peer", 0.0, 1.0, 2.0)
    assert obs_spans.drain() == []


def test_install_from_env_gating(tmp_path):
    assert obs_spans.install_from_env("w0", env={}) is False
    assert obs_spans.ACTIVE is False
    assert obs_spans.install_from_env("w0", env={obs_spans.ENV_FLAG: "1"})
    assert obs_spans.ACTIVE is True
    obs_spans.uninstall()
    d = str(tmp_path / "obs")
    assert obs_spans.install_from_env(
        "w0", env={obs_spans.ENV_FLAG: "true", obs_spans.ENV_DIR: d}
    )
    with obs_spans.span("round.snapshot"):
        pass
    spill = os.path.join(d, f"spans-w0-{os.getpid()}.jsonl")
    # Line-buffered: the completed span is on disk before any close.
    recs = obs_spans.read_spans(spill)
    assert [r["k"] for r in recs] == ["clock", "span"]


# -- record shape -------------------------------------------------------------


def test_nesting_parent_links_and_anchor():
    with obs_spans.installed("m"):
        with obs_spans.span("round.e2e", step=3):
            with obs_spans.span("round.device_dispatch", n=7):
                pass
        recs = obs_spans.drain()
    anchor, inner, outer = recs  # children END (and record) first
    assert anchor["k"] == "clock"
    assert anchor["member"] == "m" and anchor["pid"] == os.getpid()
    assert {"wall", "mono"} <= set(anchor)
    assert outer["name"] == "round.e2e" and outer["step"] == 3
    assert outer["parent"] is None
    assert inner["name"] == "round.device_dispatch" and inner["n"] == 7
    assert inner["parent"] == outer["sid"]
    for r in (inner, outer):
        assert r["member"] == "m" and r["m1"] >= r["m0"]
        assert isinstance(r["tid"], int)


def test_non_lexical_end_pops_abandoned_frames():
    with obs_spans.installed("m"):
        a = obs_spans.begin("round.e2e")
        obs_spans.begin("round.gossip_send")  # abandoned (e.g. exception)
        obs_spans.end(a)  # must pop the abandoned child too
        with obs_spans.span("round.snapshot"):
            pass
        recs = obs_spans.drain()
    by_name = {r["name"]: r for r in recs if r["k"] == "span"}
    assert by_name["round.e2e"]["parent"] is None
    # The stack is clean again: the next span is NOT parented under the
    # abandoned frame.
    assert by_name["round.snapshot"]["parent"] is None


def test_installed_restores_previous_tracer():
    obs_spans.install("outer")
    with obs_spans.installed("inner"):
        with obs_spans.span("round.snapshot"):
            pass
        assert obs_spans.drain()[-1]["member"] == "inner"
    # The outer plane is back — armed, with its own ring intact.
    assert obs_spans.ACTIVE is True
    with obs_spans.span("round.lag_update"):
        pass
    assert obs_spans.drain()[-1]["member"] == "outer"


def test_set_metrics_attaches_latency_mirror():
    obs_spans.set_metrics(Metrics())  # plane down: must not raise
    m = Metrics()
    with obs_spans.installed("m"):
        obs_spans.set_metrics(m)  # the tcp-drill arm-early path
        with obs_spans.span("round.wal_append"):
            pass
        obs_spans.observe_exchange("peer", 1.0, 2.0, 1.1)
    snap = m.snapshot()
    assert len(snap["latencies"]["span.round.wal_append"]) == 1
    assert snap["counters"]["clock.exchanges"] == 1
    # set() stores gauges in the counter namespace (last-write-wins).
    assert snap["counters"]["clock.offset_seconds.peer"] == pytest.approx(0.95)


# -- clock sync ---------------------------------------------------------------


def test_clock_sync_keeps_min_rtt_and_discards_negative():
    cs = ClockSync()
    assert cs.note("p", t1=1.0, t2=9.0, t3=0.5) is None  # negative rtt
    assert cs.snapshot() == {}
    cs.note("p", t1=0.0, t2=5.1, t3=0.2)  # offset 5.0, rtt 0.2
    cs.note("p", t1=0.0, t2=5.6, t3=1.0)  # worse rtt: ignored
    off, rtt = cs.snapshot()["p"]
    assert off == pytest.approx(5.0) and rtt == pytest.approx(0.2)
    cs.note("p", t1=0.0, t2=5.05, t3=0.1)  # better rtt: replaces
    off, rtt = cs.snapshot()["p"]
    assert off == pytest.approx(5.0) and rtt == pytest.approx(0.1)


def test_sim_offset_error_bounded_by_rtt_asymmetry():
    """The NTP estimate's error term IS the RTT asymmetry / 2: drive the
    T1/T2/T3 protocol over a sim link that is 10ms one way and 2ms back,
    against a peer skewed +0.75s — then tighten the link and watch the
    min-RTT filter converge on the true skew."""
    from antidote_ccrdt_tpu.net.sim import SimNet

    net = SimNet(
        seed=7,
        link_latency={("a", "b"): (0.010, 0.010), ("b", "a"): (0.002, 0.002)},
    )
    a = net.join("a")
    b = net.join("b")
    b.clock_skew = 0.75
    a.clock_exchange("b")
    net.run_until(1.0)
    off, rtt = a.clock.snapshot()["b"]
    # error = (d_fwd - d_back)/2 = (10ms - 2ms)/2 = +4ms, exactly.
    assert off == pytest.approx(0.75 + 0.004, abs=1e-9)
    assert rtt == pytest.approx(0.012, abs=1e-9)
    # A symmetric low-latency window opens: the min-RTT filter upgrades
    # to the asymmetry-free exchange.
    net.link_latency[("a", "b")] = (0.001, 0.001)
    net.link_latency[("b", "a")] = (0.001, 0.001)
    a.clock_exchange("b")
    net.run_until(2.0)
    off, rtt = a.clock.snapshot()["b"]
    assert off == pytest.approx(0.75, abs=1e-9)
    assert rtt == pytest.approx(0.002, abs=1e-9)


# -- alignment + export -------------------------------------------------------


def test_align_offsets_bfs_sign_conventions():
    # offsets[x][y] = mono_y - mono_x. a observed b directly; c observed
    # b — reaching c from b needs the sign-flipped reverse edge.
    offsets = {
        "a": {"b": (0.5, 0.001)},
        "c": {"b": (0.2, 0.001)},
    }
    shifts = obs_spans.align_offsets(offsets, ["a", "b", "c", "d"])
    assert shifts["a"] == 0.0  # lexicographic ref
    assert shifts["b"] == pytest.approx(-0.5)  # shift[b] = shift[a] - off
    assert shifts["c"] == pytest.approx(-0.3)  # via b: -0.5 - (-0.2)
    assert shifts["d"] == 0.0  # unreachable: renders unaligned


def test_clock_offsets_takes_min_rtt_per_edge():
    recs = [
        {"k": "offset", "peer": "b", "offset": 0.9, "rtt": 0.05},
        {"k": "offset", "peer": "b", "offset": 0.8, "rtt": 0.01},
        {"k": "span", "name": "round.e2e", "m0": 0.0, "m1": 1.0},
    ]
    off = obs_spans.clock_offsets({"a": recs})
    assert off == {"a": {"b": (0.8, 0.01)}}


def test_to_chrome_trace_aligns_and_labels_processes():
    by_member = {
        "b": [{"k": "span", "name": "round.e2e", "sid": 1, "parent": None,
               "member": "b", "tid": 0, "m0": 10.0, "m1": 10.5}],
        "a": [{"k": "span", "name": "round.e2e", "sid": 1, "parent": None,
               "member": "a", "tid": 0, "m0": 100.0, "m1": 100.2}],
    }
    # shift maps local mono onto the reference timeline: b's 10.0 lands
    # at aligned 110.0 — 10s AFTER a's span, not 90s before.
    trace = obs_spans.to_chrome_trace(by_member, shifts={"a": 0.0, "b": 100.0})
    names = {e["args"]["name"]: e["pid"] for e in trace["traceEvents"]
             if e["ph"] == "M"}
    assert names == {"a": 1, "b": 2}  # pids follow sorted member order
    xs = {e["pid"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert xs[1]["ts"] == 0.0  # zero-based at the earliest aligned span
    assert xs[2]["ts"] == pytest.approx(10.0 * 1e6)  # microseconds
    assert xs[1]["dur"] == pytest.approx(0.2 * 1e6)
    assert trace["otherData"]["aligned_members"] == ["a", "b"]
    assert trace["displayTimeUnit"] == "ms"


# -- attribution --------------------------------------------------------------


def test_union_merges_overlaps_and_skips_empty():
    assert _union([]) == 0.0
    assert _union([(0.0, 1.0), (2.0, 3.0)]) == pytest.approx(2.0)
    assert _union([(0.0, 2.0), (1.0, 3.0), (3.0, 4.0)]) == pytest.approx(4.0)
    assert _union([(1.0, 1.0), (2.0, 1.0)]) == 0.0  # empty/inverted


def _span(name, m0, m1, tid=0, **fields):
    return {"k": "span", "name": name, "sid": 0, "parent": None,
            "member": "m", "tid": tid, "m0": m0, "m1": m1, **fields}


def test_attribute_serial_overlap_gap_and_clipping():
    recs = [
        _span("round.e2e", 0.0, 1.0, tid=0),
        # Same-thread phases: serial, interval-UNION (the overlap between
        # these two must not double-count).
        _span("round.wal_append", 0.0, 0.3, tid=0),
        _span("round.device_dispatch", 0.2, 0.5, tid=0),
        # Other-thread phase: overlappable — work the round did not wait on.
        _span("round.gossip_send", 0.0, 0.4, tid=1),
        # Phase straddling the window end: clipped for the per-round
        # sample, full extent in the totals ledger.
        _span("round.snapshot", 0.9, 1.5, tid=0),
        # Entirely outside the round: no round sample, still in totals
        # (overlap-mode host stages run between e2e windows too).
        _span("round.delta_apply", 2.0, 2.1, tid=0),
    ]
    att = obs_spans.attribute({"m": recs})
    row = att["members"]["m"]
    assert row["rounds"] == 1
    assert row["e2e_ms_p50"] == pytest.approx(1000.0)
    # serial union: [0,0.5) ∪ [0.9,1.0) = 0.6s
    assert row["serial_ms_p50"] == pytest.approx(600.0)
    assert row["overlap_ms_p50"] == pytest.approx(400.0)
    # covered = serial ∪ overlappable; here the overlap interval is
    # subsumed by serial, so covered stays 0.6s.
    assert row["gap_ms_p50"] == pytest.approx(400.0)
    assert row["coverage_p50"] == pytest.approx(0.6)
    totals = row["phases_ms_total"]
    assert totals["round.snapshot"] == pytest.approx(600.0)  # unclipped
    assert totals["round.delta_apply"] == pytest.approx(100.0)
    assert row["phases_ms_p50"]["round.snapshot"] == pytest.approx(100.0)
    assert "round.delta_apply" not in row["phases_ms_p50"]
    # critical path ranks by total phase time: snapshot 600ms leads,
    # the out-of-window delta_apply sliver trails.
    assert row["critical_path"][0] == "round.snapshot"
    assert row["critical_path"][-1] == "round.delta_apply"
    fleet = att["fleet"]
    assert fleet["rounds"] == 1
    assert fleet["coverage_p50"] == pytest.approx(0.6)
    # The report renders without blowing up on the same structure.
    assert "coverage" in obs_spans.format_report(att)


def test_attribute_counts_overlappable_phases_toward_coverage():
    # An overlapped round: the round thread only dispatches (0.0-0.2);
    # WAL append + gossip send run on the host-stage thread across the
    # rest of the window. Union coverage must credit both classes.
    recs = [
        _span("round.e2e", 0.0, 1.0, tid=0),
        _span("round.device_dispatch", 0.0, 0.2, tid=0),
        _span("round.wal_append", 0.2, 0.6, tid=7),
        _span("round.gossip_send", 0.5, 1.0, tid=7),
    ]
    att = obs_spans.attribute({"m": recs})
    row = att["members"]["m"]
    assert row["serial_ms_p50"] == pytest.approx(200.0)
    assert row["overlap_ms_p50"] == pytest.approx(800.0)
    assert row["gap_ms_p50"] == pytest.approx(0.0)
    assert row["coverage_p50"] == pytest.approx(1.0)


def test_attribute_skips_members_without_rounds():
    recs = [_span("round.wal_append", 0.0, 0.1)]
    att = obs_spans.attribute({"m": recs})
    assert att["members"] == {}
    assert att["fleet"]["rounds"] == 0


# -- spill + scan -------------------------------------------------------------


def test_spill_torn_tail_skipped_and_scan_dir_groups(tmp_path):
    d = str(tmp_path / "obs")
    with obs_spans.installed("w0", spill_dir=d):
        with obs_spans.span("round.e2e", step=0):
            pass
    spill = os.path.join(d, f"spans-w0-{os.getpid()}.jsonl")
    with open(spill, "a") as f:
        f.write('{"k": "span", "name": "torn-ha')
    # A second incarnation of the same member: scan_dir concatenates.
    with open(os.path.join(d, "spans-w0-99999.jsonl"), "w") as f:
        f.write(json.dumps(
            {"k": "span", "name": "round.e2e", "sid": 1, "parent": None,
             "member": "w0", "tid": 0, "m0": 5.0, "m1": 5.1}) + "\n")
    by_member = obs_spans.scan_dir(d)
    assert list(by_member) == ["w0"]
    names = [r.get("name") for r in by_member["w0"] if r["k"] == "span"]
    assert names == ["round.e2e", "round.e2e"]  # torn tail dropped


# -- real-subprocess crash durability ----------------------------------------

_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
from antidote_ccrdt_tpu.obs import spans as obs_spans

assert obs_spans.install_from_env("victim")
for i in range(5):
    with obs_spans.span("round.e2e", step=i):
        pass
obs_spans.observe_exchange("peer", 1.0, 2.0, 1.5)
print("READY", flush=True)
time.sleep(30)
"""


def test_sigkill_leaves_readable_span_spill(tmp_path):
    """The crash-durability contract the merged timeline depends on:
    kill -9 a worker and its spill still holds the clock anchor, every
    completed span, and the offset record — nothing buffered is lost."""
    obs_dir = str(tmp_path / "obs")
    env = dict(os.environ)
    env[obs_spans.ENV_FLAG] = "1"
    env[obs_spans.ENV_DIR] = obs_dir
    p = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=REPO)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
    )
    try:
        assert p.stdout.readline().strip() == "READY"
        os.kill(p.pid, signal.SIGKILL)  # no handler can observe this
        p.wait(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()
    recs = obs_spans.read_spans(
        os.path.join(obs_dir, f"spans-victim-{p.pid}.jsonl")
    )
    assert recs[0]["k"] == "clock" and recs[0]["pid"] == p.pid
    spans_ = [r for r in recs if r["k"] == "span"]
    assert [r["step"] for r in spans_] == list(range(5))
    offs = [r for r in recs if r["k"] == "offset"]
    assert len(offs) == 1 and offs[0]["peer"] == "peer"
    # And the merge side reads it as a one-member fleet.
    assert list(obs_spans.scan_dir(obs_dir)) == ["victim"]
