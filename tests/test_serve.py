"""Unit tests for the read-serving plane (serve/): replica double
buffering, per-type query kernels, hot-key caching with staleness
fall-through, the bounded coalescing batcher, the canonical codec, and
env gating."""

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from antidote_ccrdt_tpu import serve
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.serve.plane import _Batcher, _ceil6
from antidote_ccrdt_tpu.utils.metrics import Metrics

R, NK, I, DCS, K, M, B, Br = 2, 1, 8, 2, 10, 2, 4, 2


def _engine():
    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def _ops(ids, scores, replica=0, ts0=1):
    """Adds on one replica (everything else padding: ts=0 / rmv_id=-1)."""
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    a_id[replica, : len(ids)] = ids
    a_score[replica, : len(ids)] = scores
    a_ts[replica, : len(ids)] = np.arange(ts0, ts0 + len(ids))
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.zeros((R, Br), jnp.int32),
        rmv_id=jnp.full((R, Br), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, Br, DCS), jnp.int32),
    )


def _apply(dense, state, ids, scores, **kw):
    state, _ = dense.apply_ops(
        state, _ops(ids, scores, **kw), collect_dominated=False
    )
    return state


def _fake_clock(t0=100.0):
    cell = [t0]
    return cell, (lambda: cell[0])


# --- replica ----------------------------------------------------------------


def test_replica_double_buffer_and_snapshot_isolation():
    dense = _engine()
    m = Metrics()
    plane = serve.ServePlane(dense, member="w0", metrics=m)
    s0 = _apply(dense, dense.init(R, NK), [1, 2], [50, 40])
    plane.swap(s0, 0)
    v0 = plane.query([{"op": "value", "key": 0}])["results"][0]["value"]

    # Advancing the worker's own state does NOT move the live snapshot:
    # the replica owns a device copy, not a reference.
    s1 = _apply(dense, s0, [3], [99], ts0=10)
    assert plane.query([{"op": "value", "key": 0}])["results"][0]["value"] == v0

    plane.swap(s1, 1)
    live, prev = plane.replica.live(), plane.replica.previous()
    assert live.seq == 1 and prev.seq == 0
    v1 = plane.query([{"op": "value", "key": 0}])["results"][0]["value"]
    assert [3, 99] in v1 and [3, 99] not in v0
    assert m.snapshot()["counters"]["serve.swaps"] == 2


def test_answers_match_engine_value_at_as_of_seq():
    """The bit-identity core: served value == dense.value() of the
    folded snapshot, reshaped to JSON."""
    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows

    dense = _engine()
    state = _apply(dense, dense.init(R, NK), [1, 2, 3], [50, 40, 30])
    state = _apply(dense, state, [4], [45], replica=1)
    plane = serve.ServePlane(dense, member="w0")
    plane.swap(state, 7)
    doc = plane.query([
        {"op": "value", "key": 0},
        {"op": "topk", "key": 0, "k": 2},
        {"op": "range", "key": 0, "lo": 35, "hi": 50},
    ])
    ref = [[int(i), int(s)] for i, s in
           dense.value(fold_rows(dense, state, range(R)))[0][0]]
    r = doc["results"]
    assert all(x["as_of_seq"] == 7 for x in r)
    assert r[0]["value"] == ref
    assert r[1]["value"] == ref[:2]
    assert r[2]["value"] == [p for p in ref if 35 <= p[1] <= 50]


def test_monoid_kernels_average_and_wordcount():
    from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps
    from antidote_ccrdt_tpu.models.wordcount import WordcountDense, WordcountOps
    from antidote_ccrdt_tpu.parallel.monoid import MonoidContributor, MonoidLift

    # average (scalar observable): value only, topk is a per-result error.
    lift = MonoidLift(AverageDense())
    contrib = MonoidContributor(lift, R, 2)
    key = np.zeros((R, B), np.int32)
    val = np.zeros((R, B), np.int32)
    cnt = np.zeros((R, B), np.int32)
    val[0], cnt[0] = [10, 20, 30, 40], 1
    contrib.apply(
        AverageOps(key=jnp.asarray(key), value=jnp.asarray(val),
                   count=jnp.asarray(cnt)),
        owned=[0],
    )
    plane = serve.ServePlane(lift, member="w0")
    plane.swap(contrib.view, 0)
    doc = plane.query([{"op": "value", "key": 0}, {"op": "topk", "key": 0}])
    assert doc["results"][0]["value"] == pytest.approx(25.0)
    assert "error" in doc["results"][1]

    # wordcount (vocab observable): nonzero (token, count) pairs; topk
    # ranks by count then token; range filters counts.
    V = 8
    wlift = MonoidLift(WordcountDense(V))
    wc = MonoidContributor(wlift, R, 1)
    tok = np.full((R, B), -1, np.int32)
    tok[0] = [3, 3, 5, 3]
    wc.apply(
        WordcountOps(key=jnp.zeros((R, B), jnp.int32), token=jnp.asarray(tok)),
        owned=[0],
    )
    wplane = serve.ServePlane(wlift, member="w0")
    wplane.swap(wc.view, 0)
    doc = wplane.query([
        {"op": "value", "key": 0},
        {"op": "topk", "key": 0, "k": 1},
        {"op": "range", "key": 0, "lo": 1, "hi": 1},
    ])
    assert doc["results"][0]["value"] == [[3, 3], [5, 1]]
    assert doc["results"][1]["value"] == [[3, 3]]
    assert doc["results"][2]["value"] == [[5, 1]]


def test_bad_queries_degrade_per_result():
    dense = _engine()
    plane = serve.ServePlane(dense, member="w0")
    plane.swap(dense.init(R, NK), 0)
    doc = plane.query([
        {"op": "value", "key": 999},    # out of range
        {"op": "nope", "key": 0},       # unknown op
        {"op": "value", "key": 0},      # still answered
    ])
    assert "error" in doc["results"][0]
    assert "error" in doc["results"][1]
    assert doc["results"][2]["value"] == []


def test_no_snapshot_and_bad_request():
    plane = serve.ServePlane(_engine(), member="w0")
    assert plane.query([{"op": "value", "key": 0}])["results"][0] == {
        "error": "no snapshot"
    }
    out = json.loads(plane.handle(b"not json").decode())
    assert "bad request" in out["error"]
    out = json.loads(plane.handle(b'{"queries": 7}').decode())
    assert "bad request" in out["error"]
    assert plane.health_fields()["serve_seq"] == -1


# --- staleness + cache ------------------------------------------------------


def test_max_staleness_cache_fallthrough_and_reject():
    dense = _engine()
    m = Metrics()
    cell, mono = _fake_clock()
    plane = serve.ServePlane(dense, member="w0", metrics=m, mono=mono)
    plane.swap(_apply(dense, dense.init(R, NK), [1], [5]), 0)

    q = [{"op": "value", "key": 0}]
    r = plane.query(q, max_staleness_s=1.0)["results"][0]
    assert r["value"] == [[1, 5]] and r["staleness_bound_s"] <= 1.0
    # Second ask is a cache hit (still within the bound).
    assert plane.query(q, max_staleness_s=1.0)["results"][0]["value"] == [[1, 5]]
    c = m.snapshot()["counters"]
    assert c["serve.cache_hits"] == 1 and c["serve.cache_misses"] == 1

    # Age the snapshot past the knob: cached entry no longer qualifies,
    # the fresh replica is just as old -> stale reject, never a lie.
    cell[0] += 5.0
    r = plane.query(q, max_staleness_s=1.0)["results"][0]
    assert r["error"] == "stale" and r["staleness_bound_s"] >= 5.0
    c = m.snapshot()["counters"]
    assert c["serve.stale_rejects"] == 1
    assert c["serve.cache_misses"] == 1  # a reject is not a miss

    # No knob -> the aged answer is still served, bound honestly large.
    r = plane.query(q)["results"][0]
    assert r["value"] == [[1, 5]] and r["staleness_bound_s"] >= 5.0

    # A fresh swap satisfies the strict knob again (cache fall-through
    # re-fills at the new seq).
    plane.swap(_apply(dense, dense.init(R, NK), [1], [5]), 1)
    r = plane.query(q, max_staleness_s=1.0)["results"][0]
    assert r["as_of_seq"] == 1 and r["staleness_bound_s"] <= 1.0


def test_lag_bound_feeds_staleness_pedigree():
    class FakeLag:
        def report(self):
            return {"peer": {"lag_s": 2.0, "staleness_s": 1.5}}

    cell, mono = _fake_clock()
    plane = serve.ServePlane(
        _engine(), member="w0", lag_tracker=FakeLag(), mono=mono
    )
    plane.swap(_engine().init(R, NK), 0)
    cell[0] += 0.25
    r = plane.query([{"op": "value", "key": 0}])["results"][0]
    # bound = age (0.25) + lag bound at swap (3.5), rounded UP.
    assert r["staleness_bound_s"] >= 3.75
    h = plane.health_fields()
    assert h["serve_seq"] == 0 and h["serve_staleness_bound_s"] >= 3.75


def test_cache_lru_eviction_and_purge():
    m = Metrics()
    cache = serve.HotKeyCache(cap=2, metrics=m)
    cache.put(("a",), 1, 0)
    cache.put(("b",), 2, 1)
    assert cache.get(("a",)) == (1, 0)  # refresh: b becomes LRU
    cache.put(("c",), 3, 2)
    assert cache.get(("b",)) is None
    assert m.snapshot()["counters"]["serve.cache_evictions"] == 1
    assert cache.purge_below(2) == 1  # drops ("a",) seq 0
    assert len(cache) == 1 and cache.get(("c",)) == (3, 2)


def test_cache_purged_past_pedigree_horizon():
    dense = _engine()
    plane = serve.ServePlane(dense, member="w0", meta_keep=2)
    state = dense.init(R, NK)
    plane.swap(state, 0)
    plane.query([{"op": "value", "key": 0}])  # fills cache at seq 0
    assert len(plane.cache) == 1
    plane.swap(state, 1)
    plane.swap(state, 2)  # horizon now 1: the seq-0 answer is unboundable
    assert len(plane.cache) == 0


# --- batcher ----------------------------------------------------------------


def test_batcher_coalesces_concurrent_callers():
    execd = []
    gate = threading.Event()

    def exec_batch(batch):
        if not execd:
            gate.wait(5.0)  # hold the first drain open
        execd.append([len(p.queries) for p in batch])
        for p in batch:
            p.results = [None] * len(p.queries)
            p.done = True

    b = _Batcher(exec_batch, queue_max=100, metrics=Metrics())
    results = []
    t0 = threading.Thread(target=lambda: results.append(b.run([{}], None)))
    t0.start()
    time.sleep(0.1)  # t0 is the busy drainer now
    ts = [
        threading.Thread(target=lambda: results.append(b.run([{}, {}], None)))
        for _ in range(3)
    ]
    for t in ts:
        t.start()
    time.sleep(0.2)  # followers enqueue behind the held drain
    gate.set()
    for t in [t0] + ts:
        t.join(5.0)
    assert len(results) == 4
    # First drain took the lone request; one follower drained the rest
    # as a single coalesced batch.
    assert execd[0] == [1]
    assert sorted(len(x) for x in execd[1:]) in ([3], [1, 2], [1, 1, 1], [2, 1])
    assert sum(len(x) for x in execd) == 4


def test_batcher_sheds_overflow_loudly():
    m = Metrics()
    dense = _engine()
    plane = serve.ServePlane(dense, member="w0", metrics=m, queue_max=2)
    plane.swap(dense.init(R, NK), 0)
    doc = plane.query([{"key": 0}, {"key": 0}, {"key": 0}])
    assert "overloaded" in doc["error"]
    assert m.snapshot()["counters"]["serve.queue_shed"] == 1
    # Within bounds still serves.
    assert plane.query([{"key": 0}])["results"][0]["value"] == []


def test_batcher_aborted_drain_strands_nobody():
    def exec_batch(batch):
        raise RuntimeError("kernel exploded")

    b = _Batcher(exec_batch, queue_max=10, metrics=Metrics())
    with pytest.raises(RuntimeError):
        b.run([{}], None)
    assert not b._busy and not b._pending  # next caller starts clean


# --- codec ------------------------------------------------------------------


def test_codec_canonical_and_ceil6_conservative():
    assert serve.encode({"b": 1, "a": 2}) == b'{"a":2,"b":1}\n'
    assert serve.request_bytes([{"op": "value", "key": 3}], 0.5) == (
        b'{"max_staleness_s":0.5,"queries":[{"key":3,"op":"value"}]}\n'
    )
    for x in (0.0, 1e-9, 0.1234567, 3.9999999):
        assert _ceil6(x) >= x
    assert _ceil6(-1.0) == 0.0


def test_query_key_normalizes_identical_questions():
    from antidote_ccrdt_tpu.serve.kernels import query_key

    assert query_key({"op": "value", "key": 1}) == query_key(
        {"key": 1, "op": "value", "extra": "ignored"}
    )
    assert query_key({}) == ("value", 0, None, None, None)
    assert query_key({"op": "topk", "key": 1, "k": 3}) != query_key(
        {"op": "topk", "key": 1, "k": 4}
    )


# --- env gating -------------------------------------------------------------


def test_install_from_env_gating():
    dense = _engine()
    assert serve.install_from_env(dense, "w0", env={}) is None
    assert serve.install_from_env(
        dense, "w0", env={serve.ENV_FLAG: "0"}) is None
    plane = serve.install_from_env(dense, "w0", env={serve.ENV_FLAG: "1"})
    assert isinstance(plane, serve.ServePlane) and plane.member == "w0"
