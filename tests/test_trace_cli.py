"""Trace query CLI (scripts/ccrdt_trace.py) over synthetic flight logs:
path reconstruction with per-hop latency, peer-pair percentiles,
never-applied detection, straggler flagging, and the CLI exit codes the
obs-demo smoke gate relies on."""

import importlib.util
import json
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "ccrdt_trace",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "ccrdt_trace.py",
    ),
)
trace = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(trace)


def _write_log(obs_dir, member, events):
    os.makedirs(obs_dir, exist_ok=True)
    path = os.path.join(obs_dir, f"flight-{member}-1.jsonl")
    with open(path, "w") as f:
        for seq, ev in enumerate(events):
            f.write(json.dumps({"member": member, "seq": seq, **ev}) + "\n")


@pytest.fixture
def fleet_dir(tmp_path):
    """Three-member synthetic fleet. Delta w0/1: full path, applied by
    both peers (w2 slow: 300ms vs w1's 60ms). Delta w0/2: published but
    never applied. Delta w1/1: normal."""
    d = str(tmp_path / "obs")
    _write_log(d, "w0", [
        {"kind": "delta.publish", "origin": "w0", "dseq": 1, "t": 100.0,
         "bytes": 64},
        {"kind": "frame.send", "fkind": "delta", "origin": "w0", "dseq": 1,
         "t": 100.01, "bytes": 64},
        {"kind": "delta.publish", "origin": "w0", "dseq": 2, "t": 101.0,
         "bytes": 32},
        {"kind": "delta.apply", "origin": "w1", "dseq": 1, "t": 100.55},
    ])
    _write_log(d, "w1", [
        {"kind": "frame.recv", "fkind": "delta", "origin": "w0", "dseq": 1,
         "t": 100.05, "bytes": 64},
        {"kind": "delta.apply", "origin": "w0", "dseq": 1, "t": 100.06},
        {"kind": "delta.publish", "origin": "w1", "dseq": 1, "t": 100.5,
         "bytes": 48},
    ])
    _write_log(d, "w2", [
        {"kind": "frame.recv", "fkind": "delta", "origin": "w0", "dseq": 1,
         "t": 100.28, "bytes": 64},
        {"kind": "delta.apply", "origin": "w0", "dseq": 1, "t": 100.3},
    ])
    return d


def test_path_timeline_hops_and_latency(fleet_dir):
    paths = trace.load_paths(fleet_dir)
    hops = trace.path_timeline(paths[("w0", 1)])
    assert [h["stage"] for h in hops] == [
        "publish", "send", "recv", "apply", "recv", "apply"]
    assert [h["member"] for h in hops] == ["w0", "w0", "w1", "w1", "w2", "w2"]
    assert hops[0]["hop_ms"] is None and hops[0]["total_ms"] == 0.0
    assert abs(hops[1]["hop_ms"] - 10.0) < 1e-6   # publish -> send
    assert abs(hops[2]["hop_ms"] - 40.0) < 1e-6   # send -> recv on w1
    assert abs(hops[3]["total_ms"] - 60.0) < 1e-6  # publish -> apply on w1
    assert abs(hops[5]["total_ms"] - 300.0) < 1e-6  # publish -> apply on w2


def test_completeness_and_never_applied(fleet_dir):
    paths = trace.load_paths(fleet_dir)
    assert trace.is_complete(paths[("w0", 1)])
    assert not trace.is_complete(paths[("w0", 2)])
    assert trace.never_applied(paths) == [("w0", 2)]
    assert trace.fleet_members(fleet_dir) == ["w0", "w1", "w2"]


def test_pair_stats_percentiles(fleet_dir):
    rows = trace.apply_latencies(trace.load_paths(fleet_dir))
    stats = trace.pair_stats(rows)
    assert abs(stats[("w0", "w1")]["p50_ms"] - 60.0) < 1e-6
    assert abs(stats[("w0", "w2")]["p50_ms"] - 300.0) < 1e-6
    assert abs(stats[("w1", "w0")]["p50_ms"] - 50.0) < 1e-6
    assert stats[("w0", "w2")]["n"] == 1


def test_stragglers(fleet_dir):
    rows = trace.apply_latencies(trace.load_paths(fleet_dir))
    med, slow = trace.find_stragglers(rows, factor=3.0)
    assert abs(med - 60.0) < 1e-6  # sorted latencies: 50, 60, 300
    assert [(r["origin"], r["dseq"], r["applier"]) for r in slow] == [
        ("w0", 1, "w2")]
    # Raise the bar: nothing is 10x the median.
    assert trace.find_stragglers(rows, factor=10.0)[1] == []


def test_cli_summary_and_exit_codes(fleet_dir, capsys):
    assert trace.main(["summary", fleet_dir, "--require-complete"]) == 0
    out = capsys.readouterr().out
    assert "deltas traced   : 3" in out
    assert "complete paths  : 2" in out
    assert "never applied   : 1" in out
    assert "w0 -> w2" in out.replace("      ", " ").replace("  ", " ") or \
        "w2" in out  # pair table rendered
    # Empty dir fails the gate but succeeds without it.
    empty = fleet_dir + "-none"
    os.makedirs(empty)
    assert trace.main(["summary", empty, "--require-complete"]) == 1
    assert trace.main(["summary", empty]) == 0


def test_cli_path_and_stragglers(fleet_dir, capsys):
    assert trace.main(["path", fleet_dir, "w0", "1"]) == 0
    out = capsys.readouterr().out
    assert "publish" in out and "apply" in out and "total=" in out
    assert trace.main(["path", fleet_dir, "w0", "99"]) == 1
    capsys.readouterr()
    assert trace.main(["path", fleet_dir, "w0", "2"]) == 0
    assert "path incomplete" in capsys.readouterr().out
    assert trace.main(["stragglers", fleet_dir, "--factor", "3"]) == 0
    assert "w0/1 -> w2" in capsys.readouterr().out


def test_cli_json_output(fleet_dir, capsys):
    assert trace.main(["summary", fleet_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["deltas_traced"] == 3
    assert doc["complete_paths"] == 2
    assert doc["never_applied"] == [["w0", 2]]
    assert abs(doc["pairs"]["w0->w2"]["p50_ms"] - 300.0) < 1e-6
    assert trace.main(["stragglers", fleet_dir, "--factor", "3", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert abs(doc["median_ms"] - 60.0) < 1e-6
    assert [(r["origin"], r["dseq"], r["applier"])
            for r in doc["stragglers"]] == [("w0", 1, "w2")]


# -- apply-order audit --------------------------------------------------------


def _apply(origin, dseq, seq):
    return {"kind": "delta.apply", "origin": origin, "dseq": dseq, "seq": seq}


def test_audit_contiguous_streams_pass():
    logs = {
        # Baseline is the FIRST dseq seen (ring truncation / mid-stream
        # join), not 0.
        "flight-a-1.jsonl": [
            {"member": "a", **_apply("o", 5, 0)},
            {"member": "a", **_apply("o", 6, 1)},
            {"member": "a", **_apply("p", 0, 2)},
            {"member": "a", **_apply("o", 7, 3)},
        ],
        # A snap.apply at step S is the one legitimate jump.
        "flight-b-1.jsonl": [
            {"member": "b", **_apply("o", 1, 0)},
            {"member": "b", "kind": "snap.apply", "origin": "o", "step": 9,
             "seq": 1},
            {"member": "b", **_apply("o", 10, 2)},
        ],
    }
    assert trace.audit_apply_order(logs) == []


def test_audit_orders_by_recorder_seq_not_list_position():
    # Events listed out of order; the per-process seq axis restores the
    # true apply order, so no violation.
    logs = {"flight-a-1.jsonl": [
        {"member": "a", **_apply("o", 2, 1)},
        {"member": "a", **_apply("o", 1, 0)},
    ]}
    assert trace.audit_apply_order(logs) == []


def test_audit_flags_gap_skip_and_double_apply():
    logs = {"flight-a-1.jsonl": [
        {"member": "a", **_apply("o", 1, 0)},
        {"member": "a", **_apply("o", 2, 1)},
        {"member": "a", **_apply("o", 5, 2)},   # gap: 3,4 silently lost
        {"member": "a", **_apply("o", 6, 3)},   # cursor resumed at 5: fine
        {"member": "a", **_apply("o", 6, 4)},   # cursor went backwards
    ]}
    vs = trace.audit_apply_order(logs)
    assert [(v["kind"], v["prev_dseq"], v["dseq"]) for v in vs] == [
        ("gap-skip", 2, 5), ("double-apply", 6, 6)]
    assert all(v["applier"] == "a" and v["origin"] == "o" for v in vs)


def test_audit_incarnations_are_independent():
    # Recovery re-applies the delta suffix: the restarted pid's log
    # restarts the stream and must NOT read as a double-apply.
    logs = {
        "flight-a-100.jsonl": [{"member": "a", **_apply("o", 3, 0)},
                               {"member": "a", **_apply("o", 4, 1)}],
        "flight-a-200.jsonl": [{"member": "a", **_apply("o", 3, 0)},
                               {"member": "a", **_apply("o", 4, 1)}],
    }
    assert trace.audit_apply_order(logs) == []


def test_audit_snap_jumps_interleaved_with_partition_wal_records():
    # A snapshot fold mid-stream, with partition-tagged WAL records (and
    # other wal.* noise) interleaved: the auditor must key only on the
    # apply/snapshot kinds and never read a wal record's seq-ish fields
    # as apply-stream state.
    logs = {"flight-a-1.jsonl": [
        {"member": "a", **_apply("o", 1, 0)},
        {"member": "a", "kind": "wal.append", "origin": "o", "part": 3,
         "dseq": 40, "seq": 1},
        {"member": "a", **_apply("o", 2, 2)},
        {"member": "a", "kind": "wal.fsync", "part": 3, "seq": 3},
        {"member": "a", "kind": "snap.apply", "origin": "o", "step": 7,
         "seq": 4},
        {"member": "a", "kind": "wal.append", "origin": "o", "part": 1,
         "dseq": 41, "seq": 5},
        {"member": "a", **_apply("o", 8, 6)},
        {"member": "a", **_apply("o", 9, 7)},
    ]}
    assert trace.audit_apply_order(logs) == []


def test_audit_shed_hole_heal_via_psnap_not_flagged():
    # Load-shed drops deltas 3..9; partial anti-entropy heals the hole
    # with a psnap carrying the publisher's digest seq; the stream then
    # resumes at dig_seq+1. No gap-skip — psnap.resync is a legitimate
    # cursor jump, exactly like snap.apply.
    logs = {"flight-a-1.jsonl": [
        {"member": "a", **_apply("o", 1, 0)},
        {"member": "a", **_apply("o", 2, 1)},
        {"member": "a", "kind": "psnap.resync", "origin": "o", "dig_seq": 9,
         "parts": [2, 5], "seq": 2},
        {"member": "a", "kind": "wal.append", "origin": "o", "part": 5,
         "dseq": 77, "seq": 3},
        {"member": "a", **_apply("o", 10, 4)},
        {"member": "a", **_apply("o", 11, 5)},
    ]}
    assert trace.audit_apply_order(logs) == []
    # A STALE psnap (dig_seq behind the cursor) must not rewind it:
    # re-applying 10,11 after one would still be a double-apply.
    logs["flight-a-1.jsonl"].append(
        {"member": "a", "kind": "psnap.resync", "origin": "o", "dig_seq": 4,
         "seq": 6})
    logs["flight-a-1.jsonl"].append({"member": "a", **_apply("o", 11, 7)})
    vs = trace.audit_apply_order(logs)
    assert [(v["kind"], v["dseq"]) for v in vs] == [("double-apply", 11)]


def test_cli_audit_exit_codes_and_json(fleet_dir, capsys):
    # The synthetic fleet's apply streams are clean.
    assert trace.main(["audit", fleet_dir]) == 0
    assert "OK" in capsys.readouterr().out
    # Corrupt one stream: a worker skips dseq 2 of origin w9.
    _write_log(fleet_dir, "w3", [
        {"kind": "delta.apply", "origin": "w9", "dseq": 1, "t": 1.0},
        {"kind": "delta.apply", "origin": "w9", "dseq": 3, "t": 2.0},
    ])
    assert trace.main(["audit", fleet_dir]) == 1
    out = capsys.readouterr().out
    assert "gap-skip" in out and "FAIL" in out
    assert trace.main(["audit", fleet_dir, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["violations"][0]["kind"] == "gap-skip"
    assert doc["violations"][0]["applier"] == "w3"


def test_subprocess_entrypoint(fleet_dir):
    import subprocess

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "ccrdt_trace.py")
    r = subprocess.run(
        [sys.executable, script, "summary", fleet_dir, "--require-complete"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "example complete path" in r.stdout
