"""Overlapped round pipeline (parallel/overlap.py, PR 7).

Three layers of proof, mirroring the module's three mechanisms:

* batched-dispatch entry points (`core.batch_merge.fold_states` /
  `merge_into`) produce BIT-IDENTICAL results to the serial merge chain
  — donation changes buffer lifetimes, never values — and never donate
  the caller's arg0 (DeltaPublisher._prev and the WAL pre-image hold
  references to it across the call);
* the `HostStage` / `ApplyQueue` / `DeltaPrefetcher` pieces keep their
  contracts under direct unit drive (FIFO + fail-stop; shed-with-hole +
  anchor healing; chain/anchor cursor walk);
* the whole pipeline converges to the sequential reference through
  seeded simulator chaos (net/sim.py) with a queue small enough to
  FORCE the overflow path — `overlap.dropped_deltas` must be nonzero,
  and the digests must still land exactly on the reference, because
  every shed is healed by an anchor and all payloads are joins.

The real-process leg (SIGKILL mid-window with CCRDT_OVERLAP=1) rides
the crash_recovery_demo machinery and is marked slow like its serial
twin in test_crash_recovery.py.
"""

import functools
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from antidote_ccrdt_tpu.core.batch_merge import (
    fold_states,
    merge_into,
    merge_slots,
)
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import GossipNode
from antidote_ccrdt_tpu.parallel.elastic import DeltaPublisher, my_replicas
from antidote_ccrdt_tpu.parallel.overlap import (
    ApplyQueue,
    HostStage,
    OverlapPipeline,
    enabled,
)
from antidote_ccrdt_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R, STEPS, reference_digest  # noqa: E402


def _trees_equal(a, b) -> bool:
    import jax

    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b)
    )


def _parts(drill, dense, n=3, steps=2):
    """n partial views, each having applied `steps` rounds of its own
    replica's deterministic op stream — join of all == applied-all."""
    out = []
    for i in range(n):
        st = drill.init(dense)
        for step in range(steps):
            st = drill.apply(dense, st, step, [i])
        out.append(drill.pub_state(dense, st))
    return out


# -- batched dispatch: bit-identical + donation discipline --------------------


def test_fold_states_bit_identical_to_serial_chain():
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    parts = _parts(drill, dense)
    serial = functools.reduce(dense.merge, parts)
    folded = fold_states(dense.merge, list(parts))
    assert _trees_equal(serial, folded)
    # ...and both equal the state that applied every op stream directly
    # (the batch_merge ground truth, dense edition).
    allst = drill.init(dense)
    for step in range(2):
        allst = drill.apply(dense, allst, step, [0, 1, 2])
    got = drill.set_view(dense, drill.init(dense), folded)
    assert drill.digest(dense, got) == drill.digest(dense, allst)


def test_merge_into_matches_plain_merge_and_spares_arg0():
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a, b, c = _parts(drill, dense)
    plain = dense.merge(a, b)
    donated = merge_into(dense.merge, a, b, donate_incoming=True)
    assert _trees_equal(plain, donated)
    # arg0 is NEVER donated: a must still be readable after the call —
    # DeltaPublisher._prev and the WAL pre-image alias it across rounds.
    # (c is donated and dead afterwards, so the expectation is computed
    # first — the same single-use discipline the pipeline follows.)
    expected = dense.merge(a, c)
    again = merge_into(dense.merge, a, c)
    assert _trees_equal(again, expected)


def test_merge_slots_cached_per_bound_method():
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    s1 = merge_slots(dense.merge)
    s2 = merge_slots(dense.merge)
    assert s1 is s2  # same engine -> same jitted slots (no recompiles)
    assert set(s1) == {"plain", "donate_rhs", "donate_both"}


def test_fold_states_rejects_empty_and_passes_singleton_through():
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    (only,) = _parts(drill, dense, n=1)
    assert fold_states(dense.merge, [only]) is only
    with pytest.raises(ValueError):
        fold_states(dense.merge, [])


# -- HostStage: FIFO + fail-stop ----------------------------------------------


def test_host_stage_runs_in_submission_order():
    m = Metrics()
    stage = HostStage(m, depth=4)
    seen = []
    for i in range(20):
        stage.submit(seen.append, i)
    stage.drain()
    stage.close()
    assert seen == list(range(20))  # the WAL append-before-publish order
    assert m.counters["overlap.host_tasks"] == 20


def test_host_stage_fail_stop_reraises_and_closes():
    stage = HostStage(Metrics(), depth=4)

    def boom():
        raise RuntimeError("durability failure")

    stage.submit(boom)
    with pytest.raises(RuntimeError, match="durability failure"):
        stage.drain()
    with pytest.raises(RuntimeError):  # closed after fail-stop
        stage.submit(lambda: None)
    stage.close()


def test_host_stage_blocks_not_drops_when_full():
    m = Metrics()
    stage = HostStage(m, depth=1)
    gate = threading.Event()
    seen = []
    stage.submit(gate.wait)  # parks the worker
    t = threading.Thread(
        target=lambda: [stage.submit(seen.append, i) for i in range(3)]
    )
    t.start()
    time.sleep(0.05)
    gate.set()  # release: every queued task must still run, in order
    t.join(timeout=5)
    stage.drain()
    stage.close()
    assert seen == [0, 1, 2]
    assert m.counters.get("overlap.stalls", 0) > 0


# -- ApplyQueue: shed, hole, heal ---------------------------------------------


def test_apply_queue_shed_purges_member_chain_and_opens_hole():
    m = Metrics()
    q = ApplyQueue(depth=3, metrics=m)
    assert q.put_delta("a", 0, "a0")
    assert q.put_delta("a", 1, "a1")
    assert q.put_delta("b", 0, "b0")
    # Overflow: oldest delta is a0; a1 rides the same chain and is
    # useless without it — both go, the hole records the highest seq.
    assert q.put_delta("b", 1, "b1")
    assert q.dirty_floor("a") == 1
    assert m.counters["overlap.dropped_deltas"] == 2
    assert [e.member for e in q.pop_all()] == ["b", "b"]
    # Holed member: deltas refused until an anchor covers the gap.
    assert not q.put_delta("a", 2, "a2")
    assert not q.put_snap("a", 0, "old-anchor")  # below the hole: useless
    assert q.dirty_floor("a") == 1
    assert q.put_snap("a", 1, "anchor")  # covers the hole: heals
    assert q.dirty_floor("a") is None
    assert q.put_delta("a", 2, "a2")


def test_apply_queue_keeps_anchor_with_deltas_chained_behind_it():
    # A stale snap with same-member deltas queued AFTER it is load-
    # bearing: those deltas chained from its seq, and popping them
    # without it would emit delta.apply events past a gap the flight-
    # log causal audit reads as a gap-skip (the mesh drill caught this
    # live: anchor 8 replaced by anchor 11 landing after delta 9).
    q = ApplyQueue(depth=8, metrics=Metrics())
    assert q.put_snap("a", 8, "anchor8")
    assert q.put_delta("a", 9, "a9")
    assert q.put_snap("a", 11, "anchor11")  # must NOT displace anchor8
    got = [(e.kind, e.seq) for e in q.pop_all()]
    assert got == [("snap", 8), ("delta", 9), ("snap", 11)]
    # With no deltas behind it, latest-wins replacement still applies.
    assert q.put_snap("b", 1, "b-old")
    assert q.put_snap("b", 2, "b-new")
    assert [(e.kind, e.seq) for e in q.pop_all()] == [("snap", 2)]


def test_apply_queue_snapshots_latest_wins_and_all_snap_overflow():
    m = Metrics()
    q = ApplyQueue(depth=2, metrics=m)
    assert q.put_snap("a", 3, "a-old")
    assert q.put_snap("a", 5, "a-new")  # replaces, not appends
    assert len(q) == 1
    assert q.put_snap("b", 1, "b1")
    # All-snaps overflow: the oldest snap goes, holed for refetch.
    assert q.put_snap("c", 2, "c2")
    assert m.counters["overlap.dropped_snaps"] == 1
    assert q.dirty_floor("a") == 5
    got = {e.member: e.seq for e in q.pop_all()}
    assert got == {"b": 1, "c": 2}


# -- the pipeline under seeded sim chaos --------------------------------------

N = 4
DT = 0.1
TIMEOUT = 0.35


def run_overlap_chaos(type_name, seed, *, loss=0.05, dup=0.05, depth=3,
                      drain_every=3):
    """test_net_chaos.run_chaos with the inbound half routed through an
    OverlapPipeline per member: threadless `poll()` every driver round
    (determinism — the sim owns every clock), `drain_into` only every
    `drain_every` rounds so the tiny queue overflows FOR REAL, and
    publishes kept synchronous (the HostStage is unit-tested above; a
    live thread here would race the virtual clock)."""
    net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
    drill = DRILLS[type_name]
    dense = drill.make_engine()
    names = [f"m{i}" for i in range(N)]
    nodes = {m: GossipNode(net.join(m)) for m in names}
    states = {m: drill.init(dense) for m in names}
    pubs = {
        m: DeltaPublisher(nodes[m], dense, name=drill.publish_name,
                          full_every=4)
        for m in names
    }
    owned = {m: set() for m in names}
    crashed = set()

    for _ in range(3):
        for m in names:
            nodes[m].heartbeat()
        net.advance(DT)
    for m in names:
        assert set(nodes[m].members()) == set(names), "bootstrap incomplete"

    ovls = {
        m: OverlapPipeline(
            nodes[m], dense, drill.pub_state(dense, states[m]),
            depth=depth, start_thread=False,
        )
        for m in names
    }

    def drain(m):
        view = drill.pub_state(dense, states[m])
        swept = ovls[m].drain_into(view)
        if swept is not view:
            states[m] = drill.set_view(dense, states[m], swept)

    for step in range(STEPS):
        if step == 3:
            net.partition({"m0", "m1"}, {"m2", "m3"})
        if step == 6:
            net.heal()
        if step == 7:
            net.crash("m3")
            crashed.add("m3")
        for m in names:
            if m in crashed:
                continue
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), step)
            owned[m] = now_owned
            states[m] = drill.apply(dense, states[m], step, sorted(owned[m]))
            if step % 2 == 0:
                pubs[m].publish(drill.pub_state(dense, states[m]))
            ovls[m].prefetch.poll()
            if step % drain_every == drain_every - 1:
                drain(m)
        net.advance(DT)

    net.loss = net.dup = 0.0
    ref = reference_digest(type_name)
    live = [m for m in names if m not in crashed]
    for _ in range(40):
        for m in live:
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), STEPS)
            owned[m] = now_owned
            pubs[m].publish(drill.pub_state(dense, states[m]))
            ovls[m].prefetch.poll()
            drain(m)
        net.advance(DT)
        if all(drill.digest(dense, states[m]) == ref for m in live):
            break

    for m in names:
        ovls[m].host.close()
    digests = {m: drill.digest(dense, states[m]) for m in live}
    counters = dict(net.metrics.counters)
    for m in live:
        for k, v in nodes[m].metrics.snapshot()["counters"].items():
            if k.startswith("overlap."):
                counters[k] = counters.get(k, 0.0) + v
    return digests, counters


def test_overlap_chaos_converges_and_bills_the_shed():
    """Queue depth 3 against 3 gossiping peers with drains withheld for
    3 rounds: the overflow path MUST fire (dropped deltas billed, holes
    opened) and every survivor must still reach the exact sequential
    reference — anchors heal every hole, joins lose nothing."""
    digests, counters = run_overlap_chaos("topk_rmv", seed=7)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("overlap.prefetched_deltas", 0) > 0, counters
    assert counters.get("overlap.dropped_deltas", 0) > 0, counters
    assert counters.get("overlap.windows", 0) > 0, counters
    assert counters.get("overlap.folds", 0) > 0, counters


def test_overlap_chaos_deterministic_replay():
    """Same seed -> identical digests and counters: the pipeline adds no
    nondeterminism when driven threadless (the property that keeps chaos
    failures replayable)."""
    d1, c1 = run_overlap_chaos("topk_rmv", seed=3)
    d2, c2 = run_overlap_chaos("topk_rmv", seed=3)
    assert d1 == d2
    assert c1 == c2


def test_overlap_matches_serial_digests():
    """Overlap on vs the serial sweep path (test_net_chaos.run_chaos),
    same seed and fault schedule: bit-identical survivor digests."""
    from test_net_chaos import run_chaos

    d_serial, _ = run_chaos("topk_rmv", seed=5, delta=True)
    d_overlap, _ = run_overlap_chaos("topk_rmv", seed=5)
    assert d_overlap == d_serial


def test_env_flag_default_on():
    assert enabled(True) and not enabled(False)
    old = os.environ.pop("CCRDT_OVERLAP", None)
    try:
        assert enabled(None)
        for off in ("0", "false", "no", "off", " OFF "):
            os.environ["CCRDT_OVERLAP"] = off
            assert not enabled(None)
        os.environ["CCRDT_OVERLAP"] = "1"
        assert enabled(None)
    finally:
        if old is None:
            os.environ.pop("CCRDT_OVERLAP", None)
        else:
            os.environ["CCRDT_OVERLAP"] = old


# -- the real-process crash drill, overlap armed ------------------------------


@pytest.mark.slow
def test_sigkill_mid_window_with_overlap_recovers_via_wal():
    """The crash_recovery_demo WAL drill with CCRDT_OVERLAP=1 forced:
    the victim dies mid-window with host tasks in flight; recovery must
    still replay checkpoint ⊔ delta suffix and converge bit-identically
    (append-before-publish holds because the HostStage is FIFO)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CCRDT_OVERLAP"] = "1"
    p = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "crash_recovery_demo.py"),
         "--mode", "wal", "--durability", "group"],
        capture_output=True, text=True, env=env, timeout=420,
    )
    assert p.returncode == 0, (
        f"drill failed:\n{p.stdout[-4000:]}\n{p.stderr[-2000:]}"
    )
    (v,) = json.loads(p.stdout)
    assert v["ok"], v
    assert v["victim_recovered_records"] > 0
