"""SWIM membership (net/membership.py) on an injected fake clock: the
ALIVE -> SUSPECT -> CONFIRM-DEAD progression, re-alive on fresh evidence,
stale-evidence rejection, and transitive piggybacked ages."""

from antidote_ccrdt_tpu.net.membership import ALIVE, DEAD, SUSPECT, Membership
from antidote_ccrdt_tpu.obs import events as obs_events
from antidote_ccrdt_tpu.utils.metrics import Metrics


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_alive_suspect_dead_progression():
    clk = Clock()
    m = Metrics()
    ms = Membership("a", now=clk, confirm_factor=2.0, metrics=m)
    ms.observe("b")
    assert ms.state_of("b", 1.0) == ALIVE

    clk.t = 1.5  # past timeout, inside confirm window
    assert ms.state_of("b", 1.0) == SUSPECT
    # SUSPECT keeps its replicas: still in the ownership-feeding set.
    assert ms.alive(1.0) == ["a", "b"]

    clk.t = 2.5  # past confirm_factor * timeout
    assert ms.state_of("b", 1.0) == DEAD
    assert ms.alive(1.0) == ["a"]

    # Edge-triggered events: repeated polls count each transition once.
    ms.state_of("b", 1.0)
    ms.state_of("b", 1.0)
    assert m.counters["net.suspect_events"] == 1
    assert m.counters["net.dead_events"] == 1


def test_fresh_evidence_realives():
    clk = Clock()
    ms = Membership("a", now=clk)
    ms.observe("b")
    clk.t = 10.0
    assert ms.state_of("b", 1.0) == DEAD
    ms.observe("b")  # b's next frame refutes (no incarnation numbers needed)
    assert ms.state_of("b", 1.0) == ALIVE
    assert ms.alive(1.0) == ["a", "b"]


def test_stale_evidence_ignored():
    clk = Clock()
    ms = Membership("a", now=clk)
    clk.t = 10.0
    ms.observe("b")  # heard directly at t=10
    ms.observe("b", age=5.0)  # older secondhand claim: t=5 — ignored
    assert ms.last_heard["b"] == 10.0


def test_ancient_gossip_does_not_realive_the_dead():
    clk = Clock()
    ms = Membership("a", now=clk)
    ms.observe("b")
    clk.t = 10.0
    assert ms.state_of("b", 1.0) == DEAD
    # Evidence newer than what we hold but still ancient (age 8 -> t=2)
    # must not clear the dead flag — only a recent sighting refutes.
    ms.absorb({"b": 8.0})
    assert ms.state_of("b", 1.0) == DEAD


def test_transitive_piggyback():
    """C has never exchanged a frame with B, yet A's piggybacked ages keep
    B alive in C's view — the SWIM indirection without ping-req rounds."""
    clk = Clock()
    a = Membership("a", now=clk)
    c = Membership("c", now=clk)
    a.observe("b")
    clk.t = 0.5
    c.absorb(a.heard_ages())  # what A would put on a frame to C
    assert c.state_of("b", 1.0) == ALIVE
    assert c.state_of("a", 1.0) == ALIVE  # sender's self-age is 0
    clk.t = 3.0
    assert c.state_of("b", 1.0) == DEAD


def test_transition_events_are_edge_triggered_with_evidence():
    """Each SWIM transition lands exactly one typed flight-recorder
    event carrying the heartbeat age that crossed the horizon — the
    operator-facing counterpart of the edge-triggered counters."""
    obs_events.reset("a")
    clk = Clock()
    ms = Membership("a", now=clk, confirm_factor=2.0)
    ms.observe("b")

    clk.t = 1.5
    ms.state_of("b", 1.0)
    ms.state_of("b", 1.0)  # repeated poll: no second event
    sus = obs_events.events("peer.suspect")
    assert len(sus) == 1
    assert sus[0]["peer"] == "b" and sus[0]["member"] == "a"
    assert sus[0]["age"] == 1.5 and sus[0]["timeout_s"] == 1.0

    clk.t = 2.5
    ms.state_of("b", 1.0)
    ms.state_of("b", 1.0)
    dead = obs_events.events("peer.dead")
    assert len(dead) == 1
    assert dead[0]["peer"] == "b" and dead[0]["age"] == 2.5

    # Fresh evidence refutes: one realive event, recording what the
    # peer was (dead) when the refutation arrived.
    ms.observe("b")
    rea = obs_events.events("peer.realive")
    assert len(rea) == 1
    assert rea[0]["peer"] == "b" and rea[0]["was"] == "dead"
    obs_events.reset()


def test_realive_from_suspect_records_prior_state():
    obs_events.reset("a")
    clk = Clock()
    ms = Membership("a", now=clk, confirm_factor=2.0)
    ms.observe("b")
    clk.t = 1.5
    assert ms.state_of("b", 1.0) == SUSPECT
    ms.observe("b")  # refuted while merely suspected
    rea = obs_events.events("peer.realive")
    assert len(rea) == 1 and rea[0]["was"] == "suspect"
    assert obs_events.events("peer.dead") == []
    obs_events.reset()


def test_self_is_always_alive():
    clk = Clock()
    ms = Membership("a", now=clk)
    clk.t = 1000.0
    assert ms.state_of("a", 0.1) == ALIVE
    assert "a" in ms.alive(0.1)
    assert ms.heard_ages()["a"] == 0.0
