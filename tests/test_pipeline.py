"""Prefetcher / stream_apply: ordering, error propagation, early close,
and result equivalence with the synchronous loop."""

import threading
import time

import pytest

from antidote_ccrdt_tpu.harness.pipeline import Prefetcher, stream_apply


def test_preserves_order_and_exhausts():
    assert list(Prefetcher(range(100), depth=3)) == list(range(100))


def test_producer_exception_propagates():
    def gen():
        yield 1
        raise RuntimeError("producer boom")

    pf = Prefetcher(gen())
    assert next(pf) == 1
    with pytest.raises(RuntimeError, match="producer boom"):
        next(pf)


def test_early_close_joins_thread():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    with Prefetcher(gen(), depth=2) as pf:
        assert next(pf) == 0
    # closed early: producer stopped far before exhaustion
    assert len(produced) < 10_000


def test_prefetch_runs_ahead():
    """With depth 2, the producer gets ahead of a slow consumer."""
    timeline = []

    def gen():
        for i in range(4):
            timeline.append(("produced", i))
            yield i

    pf = Prefetcher(gen(), depth=2)
    time.sleep(0.2)  # consumer idle; producer should fill the queue
    assert ("produced", 0) in timeline and ("produced", 1) in timeline
    assert list(pf) == [0, 1, 2, 3]


def test_stream_apply_equals_sync_loop():
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense

    D = make_dense(n_ids=64, n_dcs=2, size=8, slots_per_id=2)
    rng = np.random.default_rng(0)

    def mk_batch(seed):
        r = np.random.default_rng(seed)
        return TopkRmvOps(
            add_key=jnp.zeros((2, 16), jnp.int32),
            add_id=jnp.asarray(r.integers(0, 64, (2, 16)).astype(np.int32)),
            add_score=jnp.asarray(r.integers(1, 500, (2, 16)).astype(np.int32)),
            add_dc=jnp.asarray(r.integers(0, 2, (2, 16)).astype(np.int32)),
            add_ts=jnp.asarray(r.integers(1, 100, (2, 16)).astype(np.int32)),
            rmv_key=jnp.zeros((2, 2), jnp.int32),
            rmv_id=jnp.asarray(r.integers(0, 64, (2, 2)).astype(np.int32)),
            rmv_vc=jnp.asarray(r.integers(0, 50, (2, 2, 2)).astype(np.int32)),
        )

    batches = [mk_batch(i) for i in range(6)]
    ref = D.init(2, 1)
    for b in batches:
        ref, _ = D.apply_ops(ref, b, collect_dominated=False)

    got, n = stream_apply(
        D,
        D.init(2, 1),
        iter(batches),
        apply_kwargs={"collect_dominated": False},
    )
    assert n == 6
    assert D.equal(got, ref)


def test_stream_apply_reconcile_hook():
    calls = []

    class Eng:
        def apply_ops(self, state, ops):
            return state + ops, None

    def rec(state):
        calls.append(state)
        return state

    out, n = stream_apply(
        Eng(), 0, iter([1, 2, 3, 4, 5]), reconcile_every=2, reconcile=rec
    )
    assert out == 15 and n == 5
    assert calls == [3, 10]


def test_close_with_depth1_does_not_stall():
    t0 = time.time()
    with Prefetcher(iter(range(1000)), depth=1) as pf:
        assert next(pf) == 0
    assert time.time() - t0 < 2.0  # no 5s join timeout / leaked thread


def test_exhausted_iterator_keeps_raising():
    pf = Prefetcher(range(3))
    assert list(pf) == [0, 1, 2]
    with pytest.raises(StopIteration):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)
