"""Tests for scalar wordcount / worddocumentcount, ported from
antidote_ccrdt_wordcount.erl:90-98 and worddocumentcount.erl:91-101."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.wordcount import (
    WordcountScalar,
    WordDocumentCountScalar,
    tokenize,
)

W = WordcountScalar()
D = WordDocumentCountScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_new():
    assert W.new() == {}
    assert D.new() == {}


def test_wordcount_file():
    """Port of file_test (wordcount.erl:95-98)."""
    st, _ = W.update(("add", "foo bar baz baz"), W.new())
    assert st == {"foo": 1, "bar": 1, "baz": 2}


def test_worddocumentcount_file():
    """Port of file_test (worddocumentcount.erl:96-101): per-document dedup."""
    st, _ = D.update(("add", "foo bar baz baz"), D.new())
    assert st == {"foo": 1, "bar": 1, "baz": 1}
    st, _ = D.update(("add", "foo bar baz baz hello"), st)
    assert st == {"foo": 2, "bar": 2, "baz": 2, "hello": 1}


def test_tokenize_keeps_empties():
    """Erlang binary:split/3 [global] parity: empty segments are words."""
    assert tokenize("foo  bar") == ["foo", "", "bar"]
    assert tokenize("a\nb c") == ["a", "b", "c"]
    assert tokenize("") == [""]


def test_newline_split():
    st, _ = W.update(("add", "a\nb a"), W.new())
    assert st == {"a": 2, "b": 1}


def test_downstream_passthrough():
    assert W.downstream(("add", "doc"), W.new(), CTX) == ("add", "doc")
    assert not W.require_state_downstream(("add", "doc"))


def test_compaction_fuses_counts():
    """Quirk #3 fix: the reference drops both ops (wordcount.erl:70-72);
    we fuse them into one add_counts op."""
    dead, merged = W.compact_ops(("add", "foo bar"), ("add", "bar baz"))
    assert dead is None
    assert merged == ("add_counts", {"foo": 1, "bar": 2, "baz": 1})
    # applying the fused op equals applying both originals
    st1, _ = W.update(("add", "foo bar"), W.new())
    st1, _ = W.update(("add", "bar baz"), st1)
    st2, _ = W.update(merged, W.new())
    assert st1 == st2


def test_document_compaction_respects_dedup():
    dead, merged = D.compact_ops(("add", "x x y"), ("add", "y"))
    assert merged == ("add_counts", {"x": 1, "y": 2})


def test_binary_roundtrip():
    st, _ = W.update(("add", "hello world"), W.new())
    assert W.from_binary(W.to_binary(st)) == st


def test_is_operation():
    assert W.is_operation(("add", "doc"))
    assert not W.is_operation(("add", 5))
    assert not W.is_replicate_tagged(("add", "doc"))
