"""Tests for scalar wordcount / worddocumentcount, ported from
antidote_ccrdt_wordcount.erl:90-98 and worddocumentcount.erl:91-101."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.wordcount import (
    WordcountScalar,
    WordDocumentCountScalar,
    tokenize,
)

W = WordcountScalar()
D = WordDocumentCountScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_new():
    assert W.new() == {}
    assert D.new() == {}


def test_wordcount_file():
    """Port of file_test (wordcount.erl:95-98)."""
    st, _ = W.update(("add", "foo bar baz baz"), W.new())
    assert st == {"foo": 1, "bar": 1, "baz": 2}


def test_worddocumentcount_file():
    """Port of file_test (worddocumentcount.erl:96-101): per-document dedup."""
    st, _ = D.update(("add", "foo bar baz baz"), D.new())
    assert st == {"foo": 1, "bar": 1, "baz": 1}
    st, _ = D.update(("add", "foo bar baz baz hello"), st)
    assert st == {"foo": 2, "bar": 2, "baz": 2, "hello": 1}


def test_tokenize_keeps_empties():
    """Erlang binary:split/3 [global] parity: empty segments are words."""
    assert tokenize("foo  bar") == ["foo", "", "bar"]
    assert tokenize("a\nb c") == ["a", "b", "c"]
    assert tokenize("") == [""]


def test_newline_split():
    st, _ = W.update(("add", "a\nb a"), W.new())
    assert st == {"a": 2, "b": 1}


def test_downstream_passthrough():
    assert W.downstream(("add", "doc"), W.new(), CTX) == ("add", "doc")
    assert not W.require_state_downstream(("add", "doc"))


def test_compaction_fuses_counts():
    """Quirk #3 fix: the reference drops both ops (wordcount.erl:70-72);
    we fuse them into one add_counts op."""
    dead, merged = W.compact_ops(("add", "foo bar"), ("add", "bar baz"))
    assert dead is None
    assert merged == ("add_counts", {"foo": 1, "bar": 2, "baz": 1})
    # applying the fused op equals applying both originals
    st1, _ = W.update(("add", "foo bar"), W.new())
    st1, _ = W.update(("add", "bar baz"), st1)
    st2, _ = W.update(merged, W.new())
    assert st1 == st2


def test_document_compaction_respects_dedup():
    dead, merged = D.compact_ops(("add", "x x y"), ("add", "y"))
    assert merged == ("add_counts", {"x": 1, "y": 2})


def test_binary_roundtrip():
    st, _ = W.update(("add", "hello world"), W.new())
    assert W.from_binary(W.to_binary(st)) == st


def test_is_operation():
    assert W.is_operation(("add", "doc"))
    assert not W.is_operation(("add", 5))
    assert not W.is_replicate_tagged(("add", "doc"))


def test_device_side_doc_dedup_matches_scalar():
    """apply_doc_ops (dedup on device) == scalar worddocumentcount on the
    same corpus, via the no-dedup native loader when available, else a
    pure-Python pair builder."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from antidote_ccrdt_tpu.core.behaviour import registry
    from antidote_ccrdt_tpu.harness import native_tokenizer as nt
    from antidote_ccrdt_tpu.models.wordcount import (
        WordDocOps,
        hash_token,
        make_dense,
        tokenize,
    )

    docs = [
        "a b b a\nc",
        "b b b",
        "",
        "x y x  z",  # double space -> empty token, counted by reference
    ]
    V = 1 << 12
    S = registry.scalar("worddocumentcount")
    st = S.new()
    for d in docs:
        st, _ = S.update(("add", d), st)
    want = {}
    for w, c in S.value(st).items():  # sum per bucket: collisions conflate
        h = hash_token(w, V)
        want[h] = want.get(h, 0) + c

    if nt.available():
        ops = nt.worddoc_ops_from_docs([docs], n_buckets=V)
    else:
        vocab = {}
        pairs = []
        for i, d in enumerate(docs):
            for t in tokenize(d):
                uniq = vocab.setdefault(t, len(vocab))
                pairs.append((i, uniq, hash_token(t, V)))
        B = len(pairs)
        ops = WordDocOps(
            key=jnp.zeros((1, B), jnp.int32),
            doc=jnp.asarray([[p[0] for p in pairs]], dtype=jnp.int32),
            uniq=jnp.asarray([[p[1] for p in pairs]], dtype=jnp.int32),
            token=jnp.asarray([[p[2] for p in pairs]], dtype=jnp.int32),
        )
    D = make_dense(V)
    state, _ = D.apply_doc_ops(D.init(1, 1), ops)
    counts = np.asarray(jax.device_get(state.counts))[0, 0]
    got = {i: int(c) for i, c in enumerate(counts) if c}
    assert got == want


def test_device_doc_dedup_random_differential():
    import jax
    import numpy as np

    from antidote_ccrdt_tpu.core.behaviour import registry
    from antidote_ccrdt_tpu.harness import native_tokenizer as nt
    from antidote_ccrdt_tpu.models.wordcount import hash_token, make_dense

    if not nt.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(3)
    V = 1 << 10
    docs = [
        " ".join(f"w{rng.integers(0, 40)}" for _ in range(int(rng.integers(0, 30))))
        for _ in range(50)
    ]
    S = registry.scalar("worddocumentcount")
    st = S.new()
    for d in docs:
        st, _ = S.update(("add", d), st)
    want = {}
    for w, c in S.value(st).items():
        want[hash_token(w, V)] = want.get(hash_token(w, V), 0) + c
    # hash collisions possible at V=1024: compare total mass and per-bucket
    D = make_dense(V)
    state, _ = D.apply_doc_ops(D.init(1, 1), nt.worddoc_ops_from_docs([docs], n_buckets=V))
    counts = np.asarray(jax.device_get(state.counts))[0, 0]
    got = {i: int(c) for i, c in enumerate(counts) if c}
    assert got == want


# --- hashed-vocab collision accounting (VERDICT r1 #9) --------------------


def _find_colliding_pair(V=8):
    from antidote_ccrdt_tpu.models.wordcount import hash_token

    seen = {}
    i = 0
    while True:
        w = f"w{i}"
        b = hash_token(w, V)
        if b in seen and seen[b] != w:
            return seen[b], w, b
        seen[b] = w
        i += 1


def test_hashed_vocab_detects_collisions():
    from antidote_ccrdt_tpu.models.wordcount import HashedVocab

    a, b, bucket = _find_colliding_pair(V=8)
    hv = HashedVocab(8)
    assert hv.encode_token(a) == bucket
    rep0 = hv.report()
    assert rep0["buckets_collided"] == 0 and rep0["conflated_ops"] == 0
    # same word again: no collision (idempotent ownership)
    hv.encode_token(a)
    assert hv.report()["conflated_ops"] == 0
    # a DIFFERENT word in the same bucket: detected and attributed
    assert hv.encode_token(b) == bucket
    rep = hv.report()
    assert rep["buckets_collided"] == 1
    assert rep["conflated_ops"] == 1
    assert sorted(rep["collided_words"][bucket]) == sorted([a, b])
    # once flagged, the OWNER's ops on the bucket count as conflated too
    hv.encode_token(a)
    assert hv.report()["conflated_ops"] == 2


def test_hashed_vocab_decode_marks_conflated_counts():
    import numpy as np

    from antidote_ccrdt_tpu.models.wordcount import HashedVocab

    a, b, bucket = _find_colliding_pair(V=8)
    hv = HashedVocab(8)
    counts = np.zeros(8, np.int64)
    for w in (a, a, b):
        counts[hv.encode_token(w)] += 1
    decoded = hv.decode_counts(counts)
    # the conflated bucket reports ALL member words, not a silent winner
    key = next(k for k in decoded if isinstance(k, tuple))
    assert sorted(key) == sorted([a, b]) and decoded[key] == 3


def test_hashed_vocab_end_to_end_against_dense_engine():
    import numpy as np

    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.wordcount import (
        HashedVocab,
        WordcountOps,
        make_dense,
    )

    a, b, _ = _find_colliding_pair(V=16)
    hv = HashedVocab(16)
    doc = f"{a} {b} {a} unique1 unique2"
    toks = hv.encode(doc)
    D = make_dense(16)
    st = D.init(1, 1)
    ops = WordcountOps(
        key=jnp.zeros((1, len(toks)), jnp.int32),
        token=jnp.asarray([toks], jnp.int32),
    )
    st, _ = D.apply_ops(st, ops)
    counts = np.asarray(st.counts[0, 0])
    decoded = hv.decode_counts(counts)
    assert decoded[next(k for k in decoded if isinstance(k, tuple))] == 3
    assert hv.report()["buckets_collided"] == 1


def test_vocab_collision_audit_exact():
    from antidote_ccrdt_tpu.models.wordcount import (
        hash_token,
        vocab_collision_audit,
    )

    words = [f"word{i}" for i in range(500)]
    V = 1024
    audit = vocab_collision_audit(words, V)
    # ground truth by direct hashing
    from collections import Counter

    c = Counter(hash_token(w, V) for w in words)
    truth_buckets = sum(1 for n in c.values() if n > 1)
    truth_words = sum(n for n in c.values() if n > 1)
    assert audit["buckets_collided"] == truth_buckets
    assert audit["words_in_collided_buckets"] == truth_words
    assert audit["n_words"] == 500 and 0 < audit["word_collision_rate"] < 1


def test_hashed_vocab_merge_reveals_cross_encoder_collision():
    import numpy as np

    from antidote_ccrdt_tpu.models.wordcount import HashedVocab

    a, b, bucket = _find_colliding_pair(V=8)
    # two ingest pipelines, each sees ONE of the colliding words:
    # neither can detect the collision alone
    h1, h2 = HashedVocab(8), HashedVocab(8)
    h1.encode_token(a)
    h2.encode_token(b)
    assert h1.report()["buckets_collided"] == 0
    assert h2.report()["buckets_collided"] == 0
    # the other pipeline's bucket shows up unattributed, never silent
    counts = np.zeros(8, np.int64)
    counts[bucket] = 2
    h_only_a = HashedVocab(8)
    h_only_a.encode_token("unrelated")
    assert any(
        str(k).startswith("<unattributed") for k in h_only_a.decode_counts(counts)
    )
    # encoder merge (the count-merge counterpart) reveals the collision
    h1.merge(h2)
    rep = h1.report()
    assert rep["buckets_collided"] == 1
    assert sorted(rep["collided_words"][bucket]) == sorted([a, b])
    decoded = h1.decode_counts(counts)
    key = next(k for k in decoded if isinstance(k, tuple))
    assert sorted(key) == sorted([a, b]) and decoded[key] == 2
