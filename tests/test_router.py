"""Unit + chaos tests for the fleet read tier (serve/router.py +
serve/session.py): circuit-breaker transitions on a fake clock,
SWIM-death-mid-query failover, hedged requests, shed propagation with
retry-after hints, session-token routing/enforcement, the flight-log
session certifier, and a seeded `net/sim.py` drill asserting
deterministic replay and zero duplicate-answer divergence."""

import json
import threading
import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from antidote_ccrdt_tpu import serve
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.obs import audit
from antidote_ccrdt_tpu.serve.router import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    FleetRouter,
)
from antidote_ccrdt_tpu.serve.session import ClientSession, SessionToken, covers
from antidote_ccrdt_tpu.topo import rendezvous_order
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.uninstall()
    yield
    faults.uninstall()


def _fake_clock(t0=100.0):
    cell = [t0]
    return cell, (lambda: cell[0])


def _resp(member, value=1, wm=None, **extra):
    doc = {
        "member": member, "n": 1,
        "results": [{"value": value, "as_of_seq": 1,
                     "staleness_bound_s": 0.0}],
    }
    if wm is not None:
        doc["watermarks"] = wm
    doc.update(extra)
    return (json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n").encode()


def _router(peers, query_fn, **kw):
    kw.setdefault("hedge", False)
    kw.setdefault("retries", 1)
    kw.setdefault("timeout_s", 2.0)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("poll_s", 0.001)
    return FleetRouter(peers, query_fn, metrics=Metrics(), **kw)


# --- circuit breaker --------------------------------------------------------


def test_breaker_transitions_on_fake_clock():
    cell, mono = _fake_clock()
    br = CircuitBreaker(fail_threshold=3, cooldown_s=5.0, mono=mono)
    assert br.state == CLOSED and br.allow()
    assert not br.record_failure()
    assert not br.record_failure()
    assert br.record_failure()          # threshold crossed -> OPEN
    assert br.state == OPEN and not br.allow()
    cell[0] += 4.9
    assert not br.allow()               # still cooling down
    cell[0] += 0.2
    assert br.state == HALF_OPEN
    assert br.allow()                   # the single half-open probe
    assert not br.allow()               # second probe refused
    assert br.record_success()          # probe succeeded -> CLOSED
    assert br.state == CLOSED and br.allow()
    # A failed half-open probe re-opens immediately (no threshold).
    for _ in range(3):
        br.record_failure()
    cell[0] += 5.1
    assert br.allow()
    assert br.record_failure()
    assert br.state == OPEN and not br.allow()


def test_route_eligibility_is_read_only_on_half_open_probe():
    """route() must filter with the read-only `would_allow()`: a
    half-open peer that is listed but never actually tried must NOT
    consume the probe slot, or one breaker-open would exclude the peer
    from routing permanently."""
    cell, mono = _fake_clock()
    r = _router(["a", "b"], lambda *a: _resp("x"), mono=mono,
                sleep=lambda s: None)
    order = rendezvous_order("k", ["a", "b"])
    peer = order[0]
    for _ in range(5):
        r.breaker(peer).record_failure()        # -> OPEN
    cell[0] += 10.0                             # past cooldown -> HALF_OPEN
    assert r.breaker(peer).state == HALF_OPEN
    for _ in range(10):
        got, _ = r.route("k")
        assert peer in got                      # still eligible every pass
    assert r.breaker(peer).allow()              # probe slot never consumed


def test_wasted_hedge_releases_half_open_probe():
    """A hedge reaped undone when the primary wins held the half-open
    probe; _settle must give the slot back, not leak it (which would
    silently drop the peer from routing forever)."""
    order = rendezvous_order("k", ["a", "b"])

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            time.sleep(0.08)           # slow enough to trigger the hedge
            return _resp(peer, wm={})
        cancel.wait(timeout=5.0)       # the hedge never answers
        raise ConnectionError("cancelled")

    r = _router(["a", "b"], qfn, hedge=True, hedge_after_s=0.02,
                timeout_s=3.0, retries=0, breaker_cooldown_s=0.0)
    for _ in range(5):
        r.breaker(order[1]).record_failure()    # cooldown 0 -> HALF_OPEN
    assert r.breaker(order[1]).state == HALF_OPEN
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[0]
    assert r.metrics.snapshot()["counters"]["router.hedge_wasted"] == 1
    assert r.breaker(order[1]).would_allow()    # probe released
    got, _ = r.route("k")
    assert order[1] in got                      # peer still routable


def test_dead_reroute_counted_once_and_breaker_resolved():
    """While a hedge finishes out the deadline after the primary's SWIM
    death, the dead branch must be one-shot (no per-poll-tick counter
    inflation) and the dead primary's breaker must still be resolved —
    failure billed, half-open probe not leaked."""
    order = rendezvous_order("k", ["a", "b"])
    hedge_started = threading.Event()
    dead = threading.Event()

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            hedge_started.wait(timeout=5.0)
            dead.set()                  # SWIM verdict lands mid-query
            cancel.wait(timeout=10.0)
            raise ConnectionError("peer died")
        hedge_started.set()
        time.sleep(0.1)                 # many 1ms poll ticks post-verdict
        return _resp(peer, wm={})

    def verdict(peer):
        return "dead" if (peer == order[0] and dead.is_set()) else "alive"

    r = _router(["a", "b"], qfn, hedge=True, hedge_after_s=0.01,
                verdict_fn=verdict, timeout_s=5.0, retries=0)
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[1]
    c = r.metrics.snapshot()["counters"]
    assert c["router.dead_reroutes"] == 1       # one death, one count
    assert c["router.hedge_wins"] == 1
    br = r.breaker(order[0])
    assert br._consec_failures >= 1             # failure billed, not skipped
    assert not br._probing                      # no leaked probe slot


def test_consecutive_failures_only_successes_reset():
    cell, mono = _fake_clock()
    br = CircuitBreaker(fail_threshold=3, cooldown_s=5.0, mono=mono)
    for _ in range(10):
        br.record_failure()
        br.record_failure()
        br.record_success()             # never 3 in a row
    assert br.state == CLOSED


# --- candidate ordering -----------------------------------------------------


def test_hrw_order_stable_under_removal():
    members = [f"w{i}" for i in range(6)]
    full = rendezvous_order("key-7", members)
    survivors = [m for m in full if m != full[1]]
    again = rendezvous_order("key-7", [m for m in members if m != full[1]])
    assert again == survivors  # dead candidate never reorders the rest


def test_route_skips_dead_open_breaker_and_demotes_stale():
    order = rendezvous_order("k", ["a", "b", "c"])
    verdicts = {order[0]: "dead"}
    stale = {order[1]: 9.9}
    r = _router(
        ["a", "b", "c"], lambda *a: _resp("x"),
        verdict_fn=lambda p: verdicts.get(p, "alive"),
        staleness_fn=lambda p: stale.get(p, 0.0),
        stale_soft_s=1.0,
    )
    got, starved = r.route("k")
    # Dead head dropped; stale candidate demoted behind the fresh one.
    assert got == [order[2], order[1]] and not starved
    for _ in range(5):
        r.breaker(order[2]).record_failure()
    got2, _ = r.route("k")
    assert got2 == [order[1]]  # open breaker skipped too


# --- failover / retries / timeouts -----------------------------------------


def test_failover_on_error_then_success():
    order = rendezvous_order("k", ["a", "b", "c"])
    calls = []

    def qfn(peer, payload, timeout, cancel):
        calls.append(peer)
        if peer == order[0]:
            raise ConnectionError("boom")
        return _resp(peer, wm={})

    r = _router(["a", "b", "c"], qfn)
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[1] and calls == order[:2]
    c = r.metrics.snapshot()["counters"]
    assert c["router.failovers"] == 1 and c["router.successes"] == 1


def test_never_answering_peer_times_out_and_fails_over():
    """Satellite: a peer that accepts the query but never answers must
    surface a timeout to the router — which fails over, not hangs."""
    order = rendezvous_order("k", ["a", "b"])
    release = threading.Event()

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            # Hung peer: blocks until cancelled (never answers).
            cancel.wait(timeout=10.0)
            raise ConnectionError("cancelled")
        return _resp(peer, wm={})

    r = _router(["a", "b"], qfn, timeout_s=0.15, retries=0)
    t0 = time.monotonic()
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[1]
    assert time.monotonic() - t0 < 5.0  # bounded, not a hang
    c = r.metrics.snapshot()["counters"]
    assert c["router.timeouts"] >= 1 and c["router.failovers"] >= 1
    release.set()


def test_all_peers_down_returns_unavailable_not_hang():
    def qfn(peer, payload, timeout, cancel):
        raise ConnectionError("down")

    r = _router(["a", "b"], qfn, retries=1)
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["error"] == "unavailable"
    c = r.metrics.snapshot()["counters"]
    assert c["router.retries"] == 1 and c["router.exhausted"] == 1


# --- SWIM death mid-query ---------------------------------------------------


def test_dead_verdict_mid_query_cancels_and_reroutes():
    order = rendezvous_order("k", ["a", "b"])
    started = threading.Event()
    cancelled = threading.Event()
    dead = threading.Event()

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            started.set()
            cancel.wait(timeout=10.0)
            cancelled.set()
            raise ConnectionError("peer died")
        return _resp(peer, wm={})

    def verdict(peer):
        if peer == order[0] and dead.is_set():
            return "dead"
        return "alive"

    def arm():
        started.wait(timeout=5.0)
        dead.set()  # SWIM confirms death while the query is in flight

    threading.Thread(target=arm, daemon=True).start()
    r = _router(["a", "b"], qfn, verdict_fn=verdict, timeout_s=5.0, retries=0)
    t0 = time.monotonic()
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[1]
    # Rerouted on the verdict, way before the 5s transport deadline.
    assert time.monotonic() - t0 < 3.0
    assert cancelled.wait(timeout=2.0)  # the in-flight loser was reaped
    c = r.metrics.snapshot()["counters"]
    assert c["router.dead_reroutes"] >= 1 and c["router.successes"] == 1


# --- hedging ----------------------------------------------------------------


def test_hedge_fires_on_slow_peer_and_wins():
    order = rendezvous_order("k", ["a", "b"])

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            cancel.wait(timeout=1.0)  # slow primary
            raise ConnectionError("cancelled")
        return _resp(peer, wm={})

    r = _router(
        ["a", "b"], qfn, hedge=True, hedge_after_s=0.02,
        timeout_s=3.0, retries=0,
    )
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[1]
    c = r.metrics.snapshot()["counters"]
    assert c["router.hedges"] == 1 and c["router.hedge_wins"] == 1
    assert "router.hedge_wasted" not in c


def test_hedge_loser_billed_when_primary_wins():
    order = rendezvous_order("k", ["a", "b"])
    hedge_asked = threading.Event()

    def qfn(peer, payload, timeout, cancel):
        if peer == order[0]:
            time.sleep(0.08)  # slow enough to trigger the hedge...
            return _resp(peer, wm={})
        hedge_asked.set()
        cancel.wait(timeout=5.0)  # ...but the hedge is slower still
        raise ConnectionError("cancelled")

    r = _router(
        ["a", "b"], qfn, hedge=True, hedge_after_s=0.02,
        timeout_s=3.0, retries=0,
    )
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["peer"] == order[0]
    assert hedge_asked.is_set()
    c = r.metrics.snapshot()["counters"]
    assert c["router.hedges"] == 1 and c["router.hedge_wasted"] == 1
    assert "router.hedge_wins" not in c


# --- admission control ------------------------------------------------------


def test_fleet_wide_shed_propagates_retry_after():
    def qfn(peer, payload, timeout, cancel):
        return (json.dumps({
            "member": peer, "error": "overloaded: queue full",
            "retry_after_ms": 120 if peer == "a" else 40,
        }) + "\n").encode()

    r = _router(["a", "b"], qfn, retries=1)
    out = r.query([{"op": "value", "key": 0}], key="k")
    assert out["error"] == "overloaded"
    assert out["retry_after_ms"] == 120  # the largest hint wins
    c = r.metrics.snapshot()["counters"]
    assert c["router.sheds"] >= 2 and c["router.shed_returns"] == 1
    # Shedding is load, not sickness: breakers stay closed.
    assert r.breaker("a").state == CLOSED


# --- sessions ---------------------------------------------------------------


def test_session_token_covers_and_merge():
    t = SessionToken()
    t.advance("w0", 5)
    t.absorb({"w1": 3, "w0": 2})  # absorb never regresses
    assert t.floor() == {"w0": 5, "w1": 3}
    assert covers({"w0": 5, "w1": 3}, t.floor())
    assert not covers({"w0": 4, "w1": 9}, t.floor())


def test_router_routes_around_uncovered_peer():
    order = rendezvous_order("k", ["a", "b"])
    wm = {order[0]: {"w0": 1}, order[1]: {"w0": 9}}

    def qfn(peer, payload, timeout, cancel):
        req = json.loads(payload.decode())
        tok = req.get("session") or {}
        if not covers(wm[peer], tok):
            return (json.dumps({
                "member": peer,
                "error": "session_uncovered: w0 behind",
                "watermarks": wm[peer],
            }) + "\n").encode()
        return _resp(peer, wm=wm[peer])

    r = _router(["a", "b"], qfn, retries=0)
    sess = ClientSession("s-test")
    sess.note_write("w0", 5)
    out = r.query([{"op": "value", "key": 0}], key="k", session=sess)
    assert out["peer"] == order[1]
    # The rejection taught the router the stale peer's watermarks:
    # the next query skips it at routing time.
    assert r.peer_watermarks(order[0]) == {"w0": 1}
    got, _ = r.route("k", sess.requirement())
    assert got == [order[1]]


def test_session_unsatisfiable_fails_honestly_with_gaps():
    clock = [0.0]

    def qfn(peer, payload, timeout, cancel):
        return (json.dumps({
            "member": peer, "error": "session_uncovered: behind",
            "watermarks": {"w0": 2},
        }) + "\n").encode()

    r = _router(
        ["a"], qfn, retries=0, session_wait_s=0.05, session_poll_s=0.01,
    )
    out = r.query(
        [{"op": "value", "key": 0}], key="k", session={"w0": 10},
    )
    assert out["error"] == "session_unsatisfiable"
    assert out["gaps"] == {"w0": {"have": 2, "want": 10}}
    c = r.metrics.snapshot()["counters"]
    assert c["router.session_waits"] >= 1
    assert c["router.session_unsatisfiable"] == 1


# --- the serve plane's side -------------------------------------------------

R, NK, I, DCS, K, M, B, Br = 2, 1, 8, 2, 10, 2, 4, 2


def _engine():
    return make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def _ops(ids, scores, replica=0, ts0=1):
    a_key = np.zeros((R, B), np.int32)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_dc = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    a_id[replica, : len(ids)] = ids
    a_score[replica, : len(ids)] = scores
    a_ts[replica, : len(ids)] = np.arange(ts0, ts0 + len(ids))
    return TopkRmvOps(
        add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.zeros((R, Br), jnp.int32),
        rmv_id=jnp.full((R, Br), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, Br, DCS), jnp.int32),
    )


class _FakeLag:
    def __init__(self, applied):
        self.applied = applied

    def report(self):
        return {
            p: {"published": a, "applied": a, "lag_ops": 0,
                "lag_s": 0.0, "staleness_s": 0.0}
            for p, a in self.applied.items()
        }


def _plane(member, applied, seq=5, mono=None):
    dense = _engine()
    state, _ = dense.apply_ops(
        dense.init(R, NK), _ops([1, 2], [50, 40]), collect_dominated=False
    )
    kw = {} if mono is None else {"mono": mono}
    p = serve.ServePlane(
        dense, member=member, lag_tracker=_FakeLag(applied), **kw
    )
    p.swap(state, seq)
    return p


def test_plane_responses_carry_applied_watermarks():
    p = _plane("w0", {"w1": 7, "w2": 3})
    doc = p.query([{"op": "value", "key": 0}])
    assert doc["watermarks"] == {"w0": 5, "w1": 7, "w2": 3}


def test_plane_enforces_session_token():
    p = _plane("w0", {"w1": 2})
    m = p.metrics
    ok = p.query([{"op": "value", "key": 0}], session={"w1": 2})
    assert "error" not in ok
    bad = p.query([{"op": "value", "key": 0}], session={"w1": 8})
    assert bad["error"].startswith("session_uncovered")
    assert bad["watermarks"] == {"w0": 5, "w1": 2}
    assert m.snapshot()["counters"]["serve.session_uncovered"] == 1


def test_plane_shed_carries_retry_after_and_surface_label():
    p = _plane("w0", {})
    p._batcher.queue_max = 2
    handler = p.handler_for("tcp")
    raw = handler(serve.request_bytes(
        [{"op": "value", "key": 0}] * 5
    ))
    doc = json.loads(raw.decode())
    assert doc["error"].startswith("overloaded")
    assert isinstance(doc["retry_after_ms"], int) and doc["retry_after_ms"] >= 1
    c = p.metrics.snapshot()["counters"]
    assert c["serve.queue_shed"] == 1
    assert c["serve.queue_shed.tcp"] == 1


# --- certification ----------------------------------------------------------


def _w(seq, sid, origin, wseq):
    return {"kind": "session.write", "seq": seq, "session": sid,
            "origin": origin, "wseq": wseq}


def _r(seq, sid, peer, served, rw=True, mono=True):
    return {"kind": "session.read", "seq": seq, "session": sid,
            "peer": peer, "served": served, "rw": rw, "mono": mono}


def test_certify_sessions_clean_and_violating():
    logs = {"local": [
        # Clean session: write lands at w0:4, read served with w0:5.
        _w(0, "clean", "w0", 4),
        _r(1, "clean", "peer1", {"w0": 5}),
        # Violating session: write at w0:9 but served only w0:2.
        _w(2, "viol", "w0", 9),
        _r(3, "viol", "peer2", {"w0": 2}),
    ]}
    cert = audit.certify_sessions(logs=logs)
    assert not cert["ok"]
    assert cert["checks"]["monotonic_reads"]
    assert not cert["checks"]["read_your_writes"]
    cx = cert["counterexample"]["read_your_writes"]
    assert cx["session"] == "viol" and cx["peer"] == "peer2"
    assert cx["origin"] == "w0" and (cx["have"], cx["want"]) == (2, 9)
    assert audit.verify_certificate(cert)
    cert["n_reads"] = 999
    assert not audit.verify_certificate(cert)  # tamper-evident


def test_certify_sessions_monotonic_reads_violation():
    logs = {"local": [
        _r(0, "mono", "p1", {"w0": 7}, rw=False),
        _r(1, "mono", "p2", {"w0": 3}, rw=False),  # observes LESS
    ]}
    cert = audit.certify_sessions(logs=logs)
    assert not cert["checks"]["monotonic_reads"]
    cx = cert["counterexample"]["monotonic_reads"]
    assert cx["peer"] == "p2" and (cx["have"], cx["want"]) == (3, 7)


def test_client_session_events_feed_certifier():
    """The live emit path: field names ClientSession writes are exactly
    what certify_sessions replays (guards the recorder's seq-clobber
    convention — writes carry `wseq`, never `seq`)."""
    from antidote_ccrdt_tpu.obs import events as obs_events

    s = ClientSession("rt-evt-clean")
    s.note_write("o1", 3)
    s.note_read("pX", {"o1": 3})
    evs = [e for e in obs_events.events()
           if e.get("session") == "rt-evt-clean"]
    cert = audit.certify_sessions(logs={"x": evs})
    assert cert["ok"] and cert["n_reads"] == 1 and cert["n_writes"] == 1


# --- router.route fault point -----------------------------------------------


def test_router_route_fault_point_fails_over_and_replays():
    order = rendezvous_order("k", ["a", "b"])

    def qfn(peer, payload, timeout, cancel):
        return _resp(peer, wm={})

    plan = {"router.route": [{"action": "raise", "at": [0]}]}
    with faults.injected(plan, seed=7):
        r = _router(["a", "b"], qfn, retries=0)
        out = r.query([{"op": "value", "key": 0}], key="k")
        trace1 = faults.trace()
    assert out["peer"] == order[1]  # injected failure -> failover
    with faults.injected(plan, seed=7):
        r2 = _router(["a", "b"], qfn, retries=0)
        r2.query([{"op": "value", "key": 0}], key="k")
        trace2 = faults.trace()
    assert trace1 == trace2 and trace1  # seeded schedule replays


# --- seeded sim chaos drill -------------------------------------------------


@pytest.mark.slow
def test_sim_query_chaos_deterministic_replay_no_duplicate_divergence():
    """The seeded net/sim drill: three serving members + one querier on
    a lossy, duplicating medium. Two runs with the same seed must
    produce byte-identical response streams; cancelled qids must never
    surface an answer; duplicated deliveries must never produce two
    DIFFERENT answers for one qid (zero duplicate-answer divergence)."""

    def run(seed):
        net = SimNet(seed=seed, latency=(0.001, 0.05), loss=0.15, dup=0.2)
        servers = {}
        for w in ("w0", "w1", "w2"):
            tr = net.join(w)
            plane = _plane(w, {}, seq=3, mono=(lambda: net.time))
            tr.install_serve(plane)
            servers[w] = tr
        q = net.join("client")
        divergence = []
        seen = {}
        cancelled = set()
        for i in range(40):
            qid = b"q%d" % i
            payload = serve.request_bytes([{"op": "value", "key": 0}])
            q.query(f"w{i % 3}", payload, qid=qid)
            if i % 5 == 4:
                q.cancel_query(qid)
                cancelled.add(qid)
            net.advance(0.03)
            for k, v in q.query_results.items():
                if k in seen and seen[k] != v:
                    divergence.append(k)
                seen[k] = v
        net.advance(5.0)
        for k, v in q.query_results.items():
            if k in seen and seen[k] != v:
                divergence.append(k)
        return q, divergence, cancelled

    q1, div1, cancelled = run(42)
    q2, div2, _ = run(42)
    # Deterministic replay: identical response streams, byte for byte.
    assert q1.query_resps == q2.query_resps
    assert q1.query_results == q2.query_results
    # Zero duplicate-answer divergence despite dup=0.2.
    assert div1 == [] and div2 == []
    # Cancelled queries never surface an answer.
    assert not (cancelled & set(q1.query_results))
    counters = q1.net.metrics.snapshot()["counters"]
    assert counters.get("net.sim_duplicated", 0) > 0  # chaos actually ran
    assert counters.get("net.query_cancelled_drops", 0) >= 0
