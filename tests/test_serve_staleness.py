"""Staleness-bound conservatism under seeded sim chaos (net/sim.py).

The serving contract says every served value carries a
``staleness_bound_s`` that is CONSERVATIVE: the snapshot it came from is
never older than the bound claims, no matter how skewed the fleet's
clocks are or how nasty the links get. The bound is built purely from
differences of the serving worker's own monotonic clock plus its lag
bound, so constant cross-host skew cancels by construction — this test
pins that the implementation really does stay on one clock by running a
two-writer gossip over a `SimNet` with asymmetric per-link latency,
seeded loss/dup, and large asymmetric `clock_skew` on every member, then
checking every served result against the simulator's global virtual
time (ground truth no real deployment has).

Bit-identity rides along: the served "value" for key k at claimed
``as_of_seq`` s must equal the engine's own `value()` of the snapshot
that was swapped in at s — recorded at swap time, compared at serve
time.

`run_serve_chaos` is also the chaos-gate leg (scripts/chaos_gate.py):
same run, machine-checkable summary.
"""

import json
import os
import sys

from antidote_ccrdt_tpu import serve
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import GossipNode
from antidote_ccrdt_tpu.obs.lag import LagTracker

from tests.conftest import given, settings, st

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, R  # noqa: E402

STEPS = 8
DT = 0.05


def run_serve_chaos(seed: int, *, loss: float = 0.05, dup: float = 0.05):
    """Two writers gossip under chaos; one serves. Returns the audit:
    served counts, bound violations (must be 0), identity mismatches
    (must be 0), and the server's counters for the chaos gate."""
    net = SimNet(
        seed=seed,
        latency=(0.001, 0.02),
        loss=loss,
        dup=dup,
        # Asymmetric pipes: m0 -> m1 is slow, the reverse fast — the
        # server's view of the writer lags more than round-trips suggest.
        link_latency={("m0", "m1"): (0.04, 0.12), ("m1", "m0"): (0.002, 0.01)},
    )
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    t0, t1 = net.join("m0"), net.join("m1")
    # Large asymmetric skew: any accidental cross-clock arithmetic in
    # the bound would show up as a violation thousands of times over.
    t0.clock_skew = -47.3
    t1.clock_skew = +212.9
    n0, n1 = GossipNode(t0), GossipNode(t1)
    s0, s1 = drill.init(dense), drill.init(dense)

    lt = LagTracker("m1", clock=t1.local_clock, mono=t1.local_clock)
    plane = serve.ServePlane(
        dense, member="m1", metrics=n1.metrics, lag_tracker=lt,
        mono=t1.local_clock,
    )
    t1.install_serve(plane)
    q = net.join("q")
    q.clock_skew = +3.1

    from antidote_ccrdt_tpu.harness.dense_replay import fold_rows
    from antidote_ccrdt_tpu.parallel.elastic import sweep

    def ref_values(state):
        per_key = dense.value(fold_rows(dense, state, range(R)))[0]
        return [[[int(i), int(s)] for i, s in row] for row in per_key]

    rng_seed = seed * 7919
    truth = {}  # seq -> (global swap time, per-key reference values)
    audit = {"served": 0, "rejected": 0, "violations": 0,
             "identity_mismatches": 0, "wire_responses": 0}

    for _ in range(3):  # roster bootstrap
        n0.heartbeat(), n1.heartbeat()
        net.advance(DT)

    for step in range(STEPS):
        n0.heartbeat(), n1.heartbeat()
        s0 = drill.apply(dense, s0, step, [0, 1])
        s1 = drill.apply(dense, s1, step, [2, 3])
        n0.publish(drill.publish_name, s0, step)
        swept, _ = sweep(n1, dense, s1)
        s1 = swept
        n1.publish(drill.publish_name, s1, step)
        hi = n1.snapshot_seq("m0")
        if hi is not None:
            lt.observe_published("m0", hi)
            lt.observe_applied("m0", hi)  # sweep just merged it
        plane.swap(s1, step)
        truth[step] = (net.time, ref_values(s1))

        # Chaos flows while queries land: a few direct serves at known
        # virtual instants, plus wire queries through the lossy net.
        import random as _random

        prng = _random.Random(rng_seed + step)
        q.query("m1", serve.request_bytes(
            [{"op": "value", "key": 0}], max_staleness_s=120.0))
        for _ in range(4):
            net.advance(DT)
            key = 0  # demo geometry: NK=1
            ms = prng.choice([None, 120.0, 1e-7])
            doc = json.loads(plane.handle(serve.request_bytes(
                [{"op": "value", "key": key}], max_staleness_s=ms,
            )).decode())
            r = doc["results"][0]
            if "error" in r:
                if r["error"] == "stale":
                    audit["rejected"] += 1
                continue
            audit["served"] += 1
            s = r["as_of_seq"]
            swap_t, vals = truth[s]
            # Conservatism vs the simulator's global clock: the snapshot
            # is (net.time - swap_t) old for real; the bound may only
            # ever exceed that, skew or no skew.
            if r["staleness_bound_s"] + 1e-9 < net.time - swap_t:
                audit["violations"] += 1
            if r["value"] != vals[key]:
                audit["identity_mismatches"] += 1
    net.advance(1.0)
    audit["wire_responses"] = len(q.query_resps)
    for peer, raw in q.query_resps:
        doc = json.loads(raw.decode())
        assert doc.get("member") == "m1"
    audit["counters"] = dict(n1.metrics.snapshot()["counters"])
    return audit


def test_bounds_conservative_and_bit_identical_under_chaos():
    audit = run_serve_chaos(seed=11)
    assert audit["served"] >= 10
    assert audit["rejected"] >= 1  # the 1e-7 knob must actually reject
    assert audit["violations"] == 0
    assert audit["identity_mismatches"] == 0
    assert audit["wire_responses"] >= 1  # lossy, but some got through
    c = audit["counters"]
    assert c["serve.swaps"] == STEPS
    assert c["serve.requests"] >= audit["served"]


def test_chaos_run_is_seed_deterministic():
    a = run_serve_chaos(seed=23)
    b = run_serve_chaos(seed=23)
    assert a == b


@settings(max_examples=30, deadline=None)
@given(
    age=st.floats(min_value=0.0, max_value=1e4, allow_nan=False),
    lag=st.floats(min_value=0.0, max_value=1e4, allow_nan=False),
    skew=st.floats(min_value=-1e6, max_value=1e6, allow_nan=False),
)
def test_bound_covers_true_age_for_any_skew(age, lag, skew):
    """Property: with the worker's clock offset by an arbitrary constant
    skew, the advertised bound still covers (true snapshot age + lag
    bound at swap) — the bound is differences of ONE clock plus lag."""
    from tests.test_serve import _engine

    cell = [1000.0 + skew]

    class Lag:
        def report(self):
            return {"p": {"lag_s": lag, "staleness_s": 0.0}}

    plane = serve.ServePlane(
        _engine(), member="w", lag_tracker=Lag(), mono=lambda: cell[0]
    )
    plane.swap(plane.dense.init(2, 1), 0)
    cell[0] += age
    r = plane.query([{"op": "value", "key": 0}])["results"][0]
    assert r["staleness_bound_s"] >= age + lag - 1e-6
