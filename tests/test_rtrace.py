"""Tests for the request-scoped tracing plane (obs/rtrace.py, PR 18):
core mint/sample/commit semantics, tri-surface propagation parity (the
trace context and its echo ride the canonical JSON doc byte-identically
over tcp / sim / bridge / HTTP; legacy peers without the field still
interop; the ``rtrace.record`` fault point degrades a trace to untraced
without ever failing the request), end-to-end read/write waterfalls
with attribution coverage, forced commits for shed/failed outcomes,
OpenMetrics exemplars resolving to stored traces, request-flood
eviction isolation in the flight recorder, and the seeded
`run_rtrace_chaos` drill scripts/chaos_gate.py re-runs as leg 11."""

import json
import threading
import time
import urllib.request

import pytest

jnp = pytest.importorskip("jax.numpy")

from antidote_ccrdt_tpu import serve
from antidote_ccrdt_tpu.bridge.client import BridgeClient
from antidote_ccrdt_tpu.bridge.server import BridgeServer
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.tcp import TcpTransport, query_peer
from antidote_ccrdt_tpu.obs import events as obs_events
from antidote_ccrdt_tpu.obs import export as obs_export
from antidote_ccrdt_tpu.obs import http as obs_http
from antidote_ccrdt_tpu.obs import rtrace
from antidote_ccrdt_tpu.serve import FleetRouter
from antidote_ccrdt_tpu.serve.ingest import (
    ACK_DURABLE,
    ACK_REPLICATED,
    IngestPlane,
    WriteRouter,
)
from antidote_ccrdt_tpu.utils import faults
from antidote_ccrdt_tpu.utils.metrics import Metrics

from tests.test_serve import R, _apply, _engine


@pytest.fixture(autouse=True)
def _clean_planes():
    faults.uninstall()
    rtrace.uninstall()
    yield
    faults.uninstall()
    rtrace.uninstall()


# -- fixtures ----------------------------------------------------------------


def _live_plane(member="w0", metrics=None, **kw):
    dense = _engine()
    plane = serve.ServePlane(
        dense, member=member, metrics=metrics or Metrics(), **kw
    )
    state = _apply(dense, dense.init(R, 1), [1, 2, 3], [50, 40, 30])
    plane.swap(state, 4)
    return plane


def _traced_frozen_plane(metrics=None):
    """Like test_serve_parity's frozen plane, but the clock must freeze
    BEFORE construction: the batcher binds `mono` at init, and the echo
    stage marks it stamps must be identical across surface calls."""
    t = time.monotonic()
    dense = _engine()
    plane = serve.ServePlane(
        dense, member="w0", metrics=metrics or Metrics(), mono=lambda: t
    )
    state = _apply(dense, dense.init(R, 1), [1, 2, 3], [50, 40, 30])
    plane.swap(state, 4)
    return plane


class _DrainLoop:
    def __init__(self, plane, period_s=0.002):
        self.plane = plane
        self.applied = []
        self.seq = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            self.seq += 1
            self.plane.drain(self.seq, self.applied.extend)
            time.sleep(0.002)

    def stop(self):
        self._stop.set()
        self._t.join(2.0)


def _ingest_plane(member="w0", **kw):
    kw.setdefault("durable_fn", lambda: 10**9)
    kw.setdefault("ack_timeout_s", 2.0)
    kw.setdefault("poll_s", 0.001)
    return IngestPlane(member, **kw)


def _router(peers, query_fn, **kw):
    kw.setdefault("metrics", Metrics())
    kw.setdefault("hedge", False)
    kw.setdefault("retries", 1)
    kw.setdefault("timeout_s", 2.0)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("poll_s", 0.001)
    return FleetRouter(peers, query_fn, **kw)


def _wrouter(peers, write_fn, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_max_s", 0.0)
    kw.setdefault("poll_s", 0.001)
    return WriteRouter(peers, write_fn, **kw)


OPS = [["add", [1, 5, [0, 1000001]]]]


# -- core plane semantics ----------------------------------------------------


def test_dark_plane_mints_nothing():
    assert rtrace.begin("read", "k0") is None
    assert rtrace.ACTIVE is False
    assert rtrace.counters() == {}
    assert rtrace.traces() == []


def test_kill_switch_wins_over_explicit_install():
    assert rtrace.install("w0", env={"CCRDT_RTRACE": "0"}) is None
    assert not rtrace.installed()
    assert rtrace.install_from_env("w0", env={"CCRDT_RTRACE": "0"}) is False
    assert rtrace.install_from_env("w0", env={}) is False
    assert rtrace.install_from_env(
        "w0", env={"CCRDT_RTRACE": "1", "CCRDT_RTRACE_SAMPLE": "0.25"}
    ) is True
    assert rtrace._PLANE.sample == 0.25


def test_sampling_is_deterministic_in_the_trace_id():
    rtrace.install("w0", sample=0.5)
    a = [rtrace.begin("read", f"k{i}") for i in range(200)]
    rtrace.install("w0", sample=0.5)  # same member+pid -> same ids
    b = [rtrace.begin("read", f"k{i}") for i in range(200)]
    assert [t.sampled for t in a] == [t.sampled for t in b]
    assert 0 < sum(t.sampled for t in a) < 200


def test_commit_ring_slow_ring_and_forced_outcomes():
    rtrace.install("w0", sample=0.0, slow=4)
    # Unsampled ok traces survive only through the slow ring: the 4
    # slowest of these 10 must be the ones kept.
    for i in range(10):
        tr = rtrace.begin("read", f"k{i}")
        tr.hop("route", 0.0, 0.001, candidates=["w0"])
        assert tr.wire() is None  # unsampled: servers asked to do nothing
        rtrace.commit(tr, "ok", float(i))
    slow = rtrace.slowest(10)
    assert [t["ms"] for t in slow] == [9.0, 8.0, 7.0, 6.0]
    assert rtrace.traces() == []  # main ring: nothing sampled or forced
    # A shed outcome commits regardless of sampling.
    tr = rtrace.begin("read", "k-shed")
    tr.hop("route", 0.0, 0.001, candidates=[])
    rtrace.commit(tr, "shed", 0.5)
    kept = rtrace.traces()
    assert [t["outcome"] for t in kept] == ["shed"]
    c = rtrace.counters()
    assert c["minted"] == 11 and c["forced"] == 1
    assert c["committed"] == 11 and c.get("skipped", 0) == 0
    # ...and the flight recorder saw one rtrace.trace event per commit.
    assert len(obs_events.events("rtrace.trace")) >= 11


def test_record_fault_degrades_trace_not_caller():
    rtrace.install("w0", sample=1.0)
    faults.install({"rtrace.record": [{"action": "raise", "at": [1]}]},
                   seed=7)
    tr = rtrace.begin("read", "k0")
    tr.hop("route", 0.0, 0.001)      # fires ok
    tr.hop("attempt", 0.001, 0.002)  # injected raise -> degrade
    assert tr.dead is True
    tr.hop("attempt", 0.002, 0.003)  # silently ignored
    assert tr.wire() is None
    assert rtrace.commit(tr, "ok", 1.0) is False
    assert rtrace.counters()["degraded"] == 1
    assert rtrace.traces() == []


# -- tri-surface propagation parity (satellite) ------------------------------


TRACED_CTX = {"id": "t-parity-1", "hs": 3}
QS = [{"op": "value", "key": 0}, {"op": "topk", "key": 0, "k": 2}]
REQ_PLAIN = serve.request_bytes(QS, max_staleness_s=60.0)
REQ_TRACED = serve.request_bytes(QS, max_staleness_s=60.0, trace=TRACED_CTX)


def _post(addr, payload, timeout=5.0):
    return urllib.request.urlopen(
        urllib.request.Request(
            f"http://{addr[0]}:{addr[1]}/query", data=payload, method="POST"
        ),
        timeout=timeout,
    )


def test_traced_request_byte_identical_over_all_four_surfaces():
    plane = _traced_frozen_plane()
    want = plane.handle(REQ_TRACED)
    echo = json.loads(want.decode())["rtrace"]
    assert echo["id"] == "t-parity-1" and echo["peer"] == "w0"
    assert {"m_in", "m_out", "m_q", "m_drain", "m_done"} <= set(echo)

    t = TcpTransport("w0")
    t.install_serve(plane)
    try:
        member, tcp_resp = query_peer(t.address, REQ_TRACED, timeout=5.0)
        assert member == "w0"
    finally:
        t.close()

    with obs_http.MetricsHttpServer(
        plane.metrics, "w0", query_handler=plane.handle
    ) as srv:
        with _post(srv.address, REQ_TRACED) as r:
            assert r.status == 200
            http_resp = r.read()

    bs = BridgeServer(port=0).start()
    bs.install_serve(plane)
    try:
        cl = BridgeClient("127.0.0.1", bs.address[1])
        bridge_resp = cl.query(REQ_TRACED)
        cl.close()
    finally:
        bs.close()

    net = SimNet(seed=3)
    a, b = net.join("a"), net.join("b")
    b.install_serve(plane)
    a.query("b", REQ_TRACED)
    net.advance(1.0)

    assert tcp_resp == want
    assert http_resp == want
    assert bridge_resp == want
    assert a.query_resps == [("b", want)]


def test_untraced_request_stays_byte_identical_to_legacy_wire_format():
    plane = _traced_frozen_plane()
    plain = json.loads(plane.handle(REQ_PLAIN).decode())
    assert "rtrace" not in plain
    traced = json.loads(plane.handle(REQ_TRACED).decode())
    # The echo is the ONLY delta a trace context introduces.
    traced.pop("rtrace")
    assert traced == plain


def test_kill_switch_suppresses_the_echo(monkeypatch):
    plane = _traced_frozen_plane()
    monkeypatch.setenv("CCRDT_RTRACE", "0")
    assert plane.handle(REQ_TRACED) == plane.handle(REQ_PLAIN)


def test_legacy_peer_without_echo_still_interops():
    """An armed client routing at a pre-trace peer: the query succeeds
    and the trace commits; only waterfall completeness honestly degrades
    (no server echo to attach)."""
    rtrace.install("client", sample=1.0)

    def qfn(peer, payload, timeout_s, cancel):
        doc = json.loads(payload.decode())
        assert "trace" in doc  # context rode the wire...
        return (json.dumps({   # ...but the legacy peer ignores it
            "member": peer, "n": 1, "watermarks": {peer: 9},
            "results": [{"value": [], "as_of_seq": 9,
                         "staleness_bound_s": 0.0}],
        }) + "\n").encode()

    r = _router(["w0"], qfn)
    out = r.query([{"op": "value", "key": 0}], key="k0")
    assert out.get("error") is None and out["peer"] == "w0"
    (tr,) = rtrace.traces("read")
    assert tr["outcome"] == "ok" and tr["server"] == []
    ok, why = rtrace.complete(tr)
    assert ok is False and "no server echo" in why


def test_record_fault_never_fails_the_routed_query():
    rtrace.install("client", sample=1.0)
    plane = _live_plane("w0")
    faults.install({"rtrace.record": [{"action": "raise", "at": [0]}]},
                   seed=7)
    r = _router(["w0"],
                lambda p, payload, t, c: plane.handle(payload))
    out = r.query([{"op": "value", "key": 0}], key="k0")
    assert out.get("error") is None
    assert out["results"][0]["value"]
    assert rtrace.counters()["degraded"] == 1
    assert rtrace.traces() == []  # degraded to untraced, noted, no commit


# -- end-to-end waterfalls ---------------------------------------------------


def test_read_trace_end_to_end_waterfall_and_attribution():
    rtrace.install("client", sample=1.0)
    plane = _live_plane("w0")
    r = _router(["w0"],
                lambda p, payload, t, c: plane.handle(payload))
    out = r.query(QS, key="k0", max_staleness_s=60.0)
    assert out.get("error") is None
    (tr,) = rtrace.traces("read")
    ok, why = rtrace.complete(tr)
    assert ok, why
    kinds = [h["k"] for h in tr["hops"]]
    assert kinds[0] == "route" and "attempt" in kinds
    (echo,) = tr["server"]
    assert echo["peer"] == "w0" and echo["m_drain"] >= echo["m_q"]
    attr = rtrace.attribute(tr)
    assert attr["total"] == tr["ms"] > 0
    assert attr["coverage"] > 0.5
    known = sum(attr[b] for b in rtrace.BUCKETS if b != "hedge_overlap")
    assert known == pytest.approx(attr["coverage"] * attr["total"])
    rows = rtrace.waterfall(tr)
    names = {row["name"] for row in rows}
    assert {"route", "attempt", "server", "queue_wait", "kernel"} <= names
    # Server rows were mapped onto the client axis: they sit inside the
    # request window, not at raw monotonic offsets.
    for row in rows:
        assert -50.0 <= row["t0_ms"] <= tr["ms"] + 50.0
    rep = rtrace.attribution_report([tr])
    assert rep["n"] == 1 and rep["p99_trace_id"] == tr["id"]
    assert rep["p99_dominant_bucket"] in rtrace.BUCKETS
    assert "rtrace attribution" in rtrace.format_report(rep)


def test_read_exemplar_links_p99_to_a_stored_trace():
    rtrace.install("client", sample=1.0)
    plane = _live_plane("w0")
    metrics = Metrics()
    r = _router(["w0"],
                lambda p, payload, t, c: plane.handle(payload),
                metrics=metrics)
    for _ in range(3):
        assert r.query(QS, key="k0").get("error") is None
    fam = rtrace.exemplars()["router.read"]
    assert rtrace.find(fam[0]) is not None  # resolves to a stored trace
    text = obs_export.prometheus_text(metrics)
    assert f'# {{trace_id="{fam[0]}"}}' in text
    # Dark plane -> byte-identical pre-exemplar output.
    rtrace.uninstall()
    assert "trace_id" not in obs_export.prometheus_text(metrics)


def test_failed_and_shed_reads_are_always_traced():
    rtrace.install("client", sample=0.0)  # head sampling fully off

    def down(peer, payload, timeout_s, cancel):
        raise ConnectionError("injected outage")

    r = _router(["w0", "w1"], down, retries=1)
    out = r.query(QS, key="k0")
    assert out["error"] == "unavailable"
    # queue_max=1 with a 2-query batch: deterministic shed.
    shed_plane = _live_plane("w0", queue_max=1)
    rs = _router(["w0"],
                 lambda p, payload, t, c: shed_plane.handle(payload),
                 retries=0)
    out = rs.query(QS, key="k0")
    assert out["error"] == "overloaded"
    got = sorted(t["outcome"] for t in rtrace.traces("read"))
    assert got == ["failed", "shed"]
    c = rtrace.counters()
    assert c["forced"] == 2 and c.get("sampled", 0) == 0
    for tr in rtrace.traces("read"):
        ok, why = rtrace.complete(tr)
        assert ok, why  # failure traces need no server cooperation


def test_write_trace_end_to_end_with_ingest_echo():
    rtrace.install("client", sample=1.0)
    p = _ingest_plane("w0")
    loop = _DrainLoop(p)
    try:
        r = _wrouter(
            ["w0"],
            lambda peer, payload, t, c: p.handle(payload, surface="test"),
        )
        out = r.write(OPS, key="k0", ack=ACK_DURABLE, write_id="c:1")
    finally:
        loop.stop()
    assert out.get("write_ack") and out["peer"] == "w0"
    (tr,) = rtrace.traces("write")
    ok, why = rtrace.complete(tr)
    assert ok, why
    (echo,) = tr["server"]
    assert {"m_in", "m_out", "m_stage", "m_fold"} <= set(echo)
    assert "durable_wait_ms" in echo
    attr = rtrace.attribute(tr)
    assert attr["coverage"] > 0.5
    assert p.metrics.snapshot()["latencies"]["ingest.ack_ms.durable"]


def test_replicated_ack_probe_rides_the_waterfall():
    rtrace.install("client", sample=1.0)
    p = _ingest_plane("w0")
    loop = _DrainLoop(p)
    probes = []

    def wfn(peer, payload, timeout_s, cancel):
        doc, _ = p._decode(payload)
        if doc.get("probe"):
            probes.append(peer)
            return (json.dumps({
                "member": peer, "covers": True,
            }) + "\n").encode()
        return p.handle(payload, surface="test")

    try:
        r = _wrouter(["w0", "w1"], wfn, replication_wait_s=0.2,
                     replication_poll_s=0.005)
        out = r.write(OPS, key="k0", ack=ACK_REPLICATED, k=2,
                      write_id="c:2")
    finally:
        loop.stop()
    assert out.get("write_ack"), out
    (tr,) = rtrace.traces("write")
    probe_hops = [h for h in tr["hops"] if h["k"] == "ack_probe"]
    assert probe_hops and probe_hops[0]["want"] == 2
    assert probes  # the peers really were probed
    attr = rtrace.attribute(tr)
    assert attr["ack_probe"] > 0.0


def test_parallel_replication_probes_confirm_k_from_slow_peers():
    """Satellite regression: with k-1 peers each ~60ms from confirming,
    the parallel probe fan-out confirms inside ~one peer's wait; the old
    sequential walk would need the sum and blow the window."""
    rtrace.install("client", sample=1.0)
    p = _ingest_plane("w0")
    loop = _DrainLoop(p)
    t0 = time.monotonic()

    def wfn(peer, payload, timeout_s, cancel):
        doc, _ = p._decode(payload)
        if doc.get("probe"):
            return (json.dumps({
                "member": peer,
                "covers": time.monotonic() - t0 > 0.06,
            }) + "\n").encode()
        return p.handle(payload, surface="test")

    try:
        r = _wrouter(["w0", "w1", "w2", "w3"], wfn,
                     replication_wait_s=0.15, replication_poll_s=0.005)
        out = r.write(OPS, key="k0", ack=ACK_REPLICATED, k=4,
                      write_id="c:3")
    finally:
        loop.stop()
    assert out.get("write_ack"), out
    rep = out.get("replication") or {}
    assert rep.get("confirmed", 0) >= 4, out
    assert out["level"] == ACK_REPLICATED


# -- request-flood eviction isolation (satellite) ----------------------------


def test_request_flood_cannot_evict_audit_evidence():
    obs_events.reset("iso", ring=64, req_ring=128)
    try:
        obs_events.emit("ingest.fold", write_id="c:1", origin="iso", wseq=1)
        obs_events.emit("ingest.ack", origin="iso", wseq=1,
                        level="durable", write_id="c:1")
        obs_events.emit("delta.apply", origin="peer", dseq=4)
        for i in range(4096):
            obs_events.emit("serve.query", n=1)
        for i in range(4096):
            obs_events.emit("rtrace.trace", id=f"t{i}", outcome="ok")
        # Every per-kind ring is bounded...
        assert len(obs_events.events("serve.query")) == 128
        assert len(obs_events.events("rtrace.trace")) == 128
        # ...and the flood evicted NOTHING outside its own kind: the
        # certifiers' audit evidence and the control-plane ring survive.
        assert [e["write_id"] for e in obs_events.events("ingest.fold")] \
            == ["c:1"]
        assert [e["level"] for e in obs_events.events("ingest.ack")] \
            == ["durable"]
        assert [e["kind"] for e in obs_events.recorder().ring
                if e["kind"] == "delta.apply"] == ["delta.apply"]
        # The merged view stays totally ordered on the shared seq axis.
        merged = obs_events.events()
        assert [e["seq"] for e in merged] == sorted(e["seq"] for e in merged)
    finally:
        obs_events.reset("?")


# -- seeded chaos drill (chaos_gate leg) -------------------------------------


def run_rtrace_chaos(seed=7, n=80):
    """Seeded rtrace chaos drill, shared by the test below and
    scripts/chaos_gate.py leg 11: a 3-peer read fleet under injected
    serve stalls + a flaky peer + rtrace.record degradation, then
    all-down and shed arms. Returns counters + waterfall completeness +
    forced-trace coverage for the gate to assert on."""
    import random

    faults.uninstall()
    rtrace.uninstall()
    obs_events.reset("rtrace-chaos")
    rtrace.install("rtrace-chaos", sample=0.5)
    rng = random.Random(seed)
    peers = ["w0", "w1", "w2"]
    planes = {m: _live_plane(m) for m in peers}
    faults.install({
        "serve.query": [{"action": "delay", "rate": 0.05,
                         "delay_s": 0.001}],
        "rtrace.record": [{"action": "raise", "at": [40]}],
    }, seed=seed)

    def qfn(peer, payload, timeout_s, cancel):
        if peer == "w1" and rng.random() < 0.3:
            raise ConnectionError("injected flake")
        return planes[peer].handle(payload)

    r = _router(peers, qfn, retries=2, seed=seed)
    n_ok = n_err = 0
    for i in range(n):
        out = r.query([{"op": "value", "key": 0}], key=f"k{i % 16}",
                      max_staleness_s=60.0)
        if out.get("error") is None:
            n_ok += 1
        else:
            n_err += 1
    faults.uninstall()

    # Failure arms: every shed/failed request must commit a trace even
    # with head sampling at 50%.
    def down(peer, payload, timeout_s, cancel):
        raise ConnectionError("injected outage")

    n_forced_reqs = 0
    rf = _router(peers, down, retries=0)
    for i in range(6):
        assert rf.query([{"op": "value", "key": 0}], key=f"f{i}")["error"] \
            == "unavailable"
        n_forced_reqs += 1
    shed_plane = _live_plane("w0", queue_max=1)
    rs = _router(["w0"],
                 lambda p, payload, t, c: shed_plane.handle(payload),
                 retries=0)
    for i in range(6):
        assert rs.query(QS, key=f"s{i}")["error"] == "overloaded"
        n_forced_reqs += 1

    trs = rtrace.traces("read")
    sampled_ok = [t for t in trs
                  if t["outcome"] == "ok" and t.get("sampled")]
    n_complete = sum(1 for t in sampled_ok if rtrace.complete(t)[0])
    forced = [t for t in trs if t["outcome"] in rtrace.FORCED_OUTCOMES]
    rep = rtrace.attribution_report(sampled_ok)
    return {
        "counters": rtrace.counters(),
        "n_ok": n_ok,
        "n_err": n_err,
        "n_sampled_ok": len(sampled_ok),
        "n_complete": n_complete,
        "complete_frac": (n_complete / len(sampled_ok))
        if sampled_ok else 0.0,
        "n_forced_reqs": n_forced_reqs,
        "n_forced_traces": len(forced),
        "coverage_p50": rep.get("coverage_p50", 0.0),
        "report": rep,
    }


def test_rtrace_chaos_drill_holds_the_gate():
    res = run_rtrace_chaos(seed=7)
    c = res["counters"]
    for k in ("minted", "sampled", "committed", "forced", "degraded"):
        assert c.get(k, 0) > 0, (k, c)
    assert res["n_ok"] > 0 and res["n_sampled_ok"] > 0
    assert res["complete_frac"] >= 0.99, res
    assert res["n_forced_traces"] == res["n_forced_reqs"], res
    assert res["coverage_p50"] >= 0.9, res["report"]
