"""Full-stack integration (scripts/end_to_end_demo.py at small sizes):
native C++ host -> causal drain -> dense apply -> checkpoint/resume ->
reconcile, cross-checked against the scalar reference engine."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
)

from antidote_ccrdt_tpu.harness import native_host as nh


@pytest.mark.skipif(not nh.available(), reason="native toolchain unavailable")
def test_end_to_end_stack():
    from end_to_end_demo import run

    from antidote_ccrdt_tpu.harness.orbax_ckpt import available as orbax_available

    out = run(
        n_dcs=3,
        n_ids=128,
        k=8,
        m=8,
        rounds=3,
        adds_per_round=40,
        rmvs_per_round=6,
        verbose=False,
    )
    assert out["per_replica_match"]
    assert out["joined_size"] == 8  # instance saturated: full top-K observable
    assert out["backlogs"] == [0, 0, 0]  # causal delivery drained everything
    # checkpoint/resume runs exactly when the optional orbax extra exists
    assert out["resumed"] == orbax_available()
