"""Transport protocol conformance + the GossipNode state facade
(net/transport.py): every medium satisfies the same surface, blob
formats stay checkpoint-compatible, and fetches are total."""

import os
import struct

import jax.numpy as jnp

from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode, Transport
from antidote_ccrdt_tpu.parallel.elastic import GossipStore


def _engine_and_state():
    D = make_dense(n_ids=16, n_dcs=2, size=4, slots_per_id=2)
    st = D.init(2, 1)
    ops = TopkRmvOps(
        add_key=jnp.zeros((2, 1), jnp.int32),
        add_id=jnp.asarray([[3], [7]], jnp.int32),
        add_score=jnp.asarray([[50], [90]], jnp.int32),
        add_dc=jnp.asarray([[0], [1]], jnp.int32),
        add_ts=jnp.asarray([[1], [1]], jnp.int32),
        rmv_key=jnp.zeros((2, 1), jnp.int32),
        rmv_id=jnp.zeros((2, 1), jnp.int32) - 1,
        rmv_vc=jnp.zeros((2, 1, 2), jnp.int32),
    )
    st, _ = D.apply_ops(st, ops, collect_dominated=False)
    return D, st


def test_protocol_conformance(tmp_path):
    """All three media satisfy the runtime-checkable Transport protocol."""
    fs = FsTransport(str(tmp_path), "a")
    sim = SimNet(seed=0).join("a")
    assert isinstance(fs, Transport)
    assert isinstance(sim, Transport)
    from antidote_ccrdt_tpu.net.tcp import TcpTransport

    tcp = TcpTransport("a")
    try:
        assert isinstance(tcp, Transport)
    finally:
        tcp.close()


def test_fs_blob_surface(tmp_path):
    t = FsTransport(str(tmp_path), "a")
    t.publish(b"\x01" * 16)
    assert t.fetch("a") == b"\x01" * 16
    assert t.fetch_head("a", 8) == b"\x01" * 8
    assert t.fetch("ghost") is None
    assert t.snapshot_members() == ["a"]
    for s in range(6):
        t.publish_delta(s, bytes([s]), keep=3)
    assert t.delta_seqs("a") == [3, 4, 5]  # pruned to the keep window
    assert t.fetch_delta("a", 4) == b"\x04"
    assert t.fetch_delta("a", 1) is None
    assert t.delta_members() == ["a"]


def test_snapshot_blob_is_checkpoint_compatible(tmp_path):
    """The gossip snapshot blob must be byte-identical to
    harness.checkpoint.save_dense_checkpoint output: on-disk artifacts
    from older rounds stay readable, checkpoints are gossipable."""
    from antidote_ccrdt_tpu.harness.checkpoint import save_dense_checkpoint

    D, st = _engine_and_state()
    node = GossipStore(str(tmp_path / "g"), "a")
    node.publish("topk_rmv", st, step=7)

    ckpt = str(tmp_path / "ckpt.bin")
    save_dense_checkpoint(ckpt, "topk_rmv", st, step=7)
    with open(ckpt, "rb") as f:
        assert node.transport.fetch("a") == f.read()


def test_gossip_node_roundtrip_and_headers(tmp_path):
    D, st = _engine_and_state()
    node = GossipNode(FsTransport(str(tmp_path), "a"))
    node.publish("topk_rmv", st, step=3)
    assert node.snapshot_seq("a") == 3
    got = node.fetch("a", st, dense=D)
    assert got is not None
    step, state = got
    assert step == 3 and D.equal(state, st)
    assert node.metrics.counters["net.snap_publishes"] == 1
    assert node.metrics.counters["net.snap_fetches"] == 1


def test_gossip_node_fetch_is_total(tmp_path):
    """Garbage blobs (torn writes, foreign writers) read as None — the
    gossip loop skips and retries, never crashes."""
    D, st = _engine_and_state()
    node = GossipNode(FsTransport(str(tmp_path), "a"))
    with open(os.path.join(str(tmp_path), "snap-evil"), "wb") as f:
        f.write(struct.pack("<Q", 1) + b"not a checkpoint")
    assert node.fetch("evil", st, dense=D) is None
    assert node.snapshot_seq("evil") == 1  # header alone is still readable
    with open(os.path.join(str(tmp_path), "delta-evil-00000001"), "wb") as f:
        f.write(b"garbage")
    assert node.fetch_delta("evil", 1, st) is None


def test_gossip_store_back_compat(tmp_path):
    """The historical constructor and attributes survive the net/ split."""
    store = GossipStore(str(tmp_path), "w0")
    assert store.root == str(tmp_path)
    assert store.member == "w0"
    assert os.path.exists(os.path.join(str(tmp_path), "hb-w0"))
    assert store.members() == ["w0"]
    assert store.alive_members(10.0) == ["w0"]


def test_sim_transport_same_surface_as_fs():
    """The simulated medium honors the same blob surface (snapshot
    latest-wins via step header, delta keep-window pruning)."""
    net = SimNet(seed=1)
    a, b = net.join("a"), net.join("b")
    blob5 = struct.pack("<Q", 5) + b"newer"
    blob3 = struct.pack("<Q", 3) + b"older"
    a.publish(blob5)
    net.run_until(1.0)
    assert b.fetch("a") == blob5
    # A stale (reordered/duplicated) older anchor must not replace.
    b._deliver(("snap", "a", blob3, {}))
    assert b.fetch("a") == blob5
    for s in range(6):
        a.publish_delta(s, bytes([s]), keep=3)
    net.run_until(2.0)
    assert b.delta_seqs("a") == [3, 4, 5]
    assert b.fetch_delta("a", 4) == b"\x04"


def test_crashed_publish_tmp_files_are_invisible(tmp_path):
    """A process dying between the tmp write and the atomic replace (the
    window publish/publish_delta fsync in) leaves `.tmp` debris: none of
    the listing surfaces may ever show it as a member/seq."""
    t = FsTransport(str(tmp_path), "a")
    t.publish(struct.pack("<Q", 1) + b"good")
    t.publish_delta(0, b"d0")
    # Simulated crash debris, both namespaces.
    for leftover in ("snap-ghost.tmp", "delta-ghost-00000003.tmp", "hb-ghost.tmp-77"):
        with open(os.path.join(str(tmp_path), leftover), "wb") as f:
            f.write(b"partial")
    assert t.snapshot_members() == ["a"]
    assert t.delta_members() == ["a"]
    assert t.delta_seqs("ghost") == []
    assert t.members() == ["a"]
    assert t.fetch("ghost") is None
