"""The partition plane (core/partition.py) end to end.

Pinned here:

* the id->partition map is a pure stable function of (id, P) — the
  property every persisted artifact (psnap shards, WAL tags, digest
  vectors) depends on;
* per-partition digest vectors are exactly as discriminating as the
  whole-instance digest: a change confined to partition p moves only
  entries {p, meta}, and two states disagree on some vector entry iff
  they differ at all;
* `PartialAntiEntropy` repairs a divergent partition by fetching ONLY
  that partition's psnap (plus meta), never an agreeing one, and the
  repair is bit-identical to the whole-snapshot merge;
* the CCPT container keeps both versions decodable (v1 raw / v2
  deflated) and untagged legacy WAL records still recover — the
  mixed-version compatibility surface;
* a rejoin interrupted mid-stream (the SIGKILL drill, modeled as an
  abandoned streamer) resumes from the last durable shard: the next
  incarnation's plan is exactly the partitions that were still in
  flight;
* a seeded sim chaos run (loss + duplication + a partition that forms
  and heals + a crash) with the partition plane on converges to the
  sequential reference with partial resyncs lit and ZERO wasted psnap
  fetches (`scripts/chaos_gate.py` runs the same drill as a gate).
"""

import os
import sys
import zlib

import numpy as np
import pytest

from antidote_ccrdt_tpu.core import partition as pt
from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.net.sim import SimNet
from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    PartialAntiEntropy,
    my_replicas,
    sweep_deltas,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from elastic_demo import DRILLS, I, R, STEPS, reference_digest  # noqa: E402

P = 8


# --- id -> partition map ----------------------------------------------------


def test_part_of_is_stable_and_total():
    """Same id -> same partition, forever: the map is a pure function
    with no hidden state, every output is in range, and the exact
    assignment is pinned against the published constant (changing the
    hash silently would orphan every persisted shard/tag)."""
    ids = np.arange(4096)
    a = pt.part_of(ids, P)
    b = pt.part_of(ids, P)
    assert np.array_equal(a, b)
    assert a.min() >= 0 and a.max() < P
    # Scalar and array calls agree.
    for i in (0, 1, 63, 4095):
        assert int(pt.part_of(np.asarray([i]), P)[0]) == int(a[i])
    # Pinned Knuth multiplicative assignment (the on-disk contract).
    expect = ((ids.astype(np.uint64) * np.uint64(2654435761))
              & np.uint64(0xFFFFFFFF)) % np.uint64(P)
    assert np.array_equal(a.astype(np.uint64), expect)
    # Every partition is populated at this scale (no degenerate bucket).
    assert len(set(int(x) for x in a)) == P


def test_part_of_spreads_under_different_p():
    ids = np.arange(1024)
    for n in (2, 4, 16):
        parts = pt.part_of(ids, n)
        assert parts.max() < n
        counts = np.bincount(parts, minlength=n)
        assert counts.min() > 0


# --- digest vectors ---------------------------------------------------------


def _drill_state(extra_hot=None, steps=4):
    """A topk_rmv state from the shared drill ops; optionally applies an
    extra batch touching only `extra_hot` ids (numpy [k])."""
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    state = drill.init(dense)
    for s in range(steps):
        state = drill.apply(dense, state, s, range(R))
    if extra_hot is not None:
        import jax.numpy as jnp

        from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

        B = len(extra_hot)
        a_id = np.zeros((R, B), np.int32)
        a_score = np.zeros((R, B), np.int32)
        a_ts = np.zeros((R, B), np.int32)
        a_id[0] = np.asarray(extra_hot, np.int32)
        a_score[0] = 900 + np.arange(B)
        a_ts[0] = 10_000 + np.arange(B)
        z = np.zeros((R, B), np.int32)
        ops = TopkRmvOps(
            add_key=jnp.asarray(z), add_id=jnp.asarray(a_id),
            add_score=jnp.asarray(a_score), add_dc=jnp.asarray(z),
            add_ts=jnp.asarray(a_ts),
            rmv_key=jnp.asarray(np.zeros((R, 1), np.int32)),
            rmv_id=jnp.asarray(np.full((R, 1), -1, np.int32)),
            rmv_vc=jnp.asarray(np.zeros((R, 1, 4), np.int32)),
        )
        state, _ = dense.apply_ops(state, ops, collect_dominated=False)
    return dense, state


def test_digest_vector_localizes_changes_and_matches_whole():
    """A perturbation confined to ids of partition p moves only vector
    entries {p, meta}; and the vector disagrees somewhere iff the states
    differ at all (same discriminating power as one whole digest)."""
    part_map = pt.part_of(np.arange(I), P)
    p_star = int(np.bincount(part_map, minlength=P).argmax())
    hot = np.arange(I)[part_map == p_star][:4]

    _, base = _drill_state()
    _, same = _drill_state()
    dense, touched = _drill_state(extra_hot=hot)

    v_base = pt.state_digests(base, P)
    assert v_base.shape == (P + 1,)
    assert np.array_equal(v_base, pt.state_digests(same, P))  # deterministic

    v_touch = pt.state_digests(touched, P)
    div = set(pt.divergent_parts(v_base, v_touch))
    assert p_star in div
    assert div <= {p_star, pt.meta_part(P)}
    # Whole-instance equivalence: any difference shows up in the vector.
    b_blob = serial.dumps_dense("topk_rmv", base)
    t_blob = serial.dumps_dense("topk_rmv", touched)
    assert (zlib.crc32(b_blob) != zlib.crc32(t_blob)) == bool(div)


# --- CCPT container + legacy compat -----------------------------------------


def test_ccpt_codec_versions_round_trip():
    payload = serial.dumps_dense("topk_rmv_psnap_probe", {"x": np.arange(64)})
    blob = pt.encode_psnap_blob(9, 3, payload)
    assert pt.is_partition_blob(blob)
    seq, part, got = pt.decode_psnap_blob(blob)
    assert (seq, part, got) == (9, 3, payload)
    # The redundant flat-serial envelope deflates: v2 is the common case.
    assert blob[4] == 2 and len(blob) < len(payload) + 18
    # A v1 (raw) blob — what a pre-deflate writer produced — still decodes.
    v1 = (pt.PART_MAGIC + bytes([1, pt.KIND_PSNAP])
          + blob[6:18] + payload)
    assert pt.decode_psnap_blob(v1) == (9, 3, payload)
    # Digest vectors stay raw v1 (they are 4(P+1) bytes already).
    dig = pt.encode_digest_blob(5, np.arange(P + 1, dtype=np.uint32))
    dseq, vec = pt.decode_digest_blob(dig)
    assert dseq == 5 and np.array_equal(vec, np.arange(P + 1))
    # Future versions are refused loudly, not misparsed.
    with pytest.raises(ValueError):
        pt.decode_psnap_blob(pt.PART_MAGIC + bytes([9, pt.KIND_PSNAP]) + blob[6:])
    # Legacy whole-instance snapshot blobs are NOT partition blobs.
    assert not pt.is_partition_blob(b"\x00" * 8 + serial.MAGIC)


def test_legacy_untagged_wal_records_recover(tmp_path):
    """A WAL written without partition tags (3-tuple records) recovers
    under a partition-aware reader, and vice versa — the record arity IS
    the version marker, mirroring the CCPT magic dispatch."""
    from antidote_ccrdt_tpu.harness.wal import ElasticWal

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()

    def write_log(root, member, partitions):
        wal = ElasticWal(
            str(root), member, dense, drill.publish_name,
            partitions=partitions,
        )
        prev = st = drill.init(dense)
        for s in range(3):
            st = drill.apply(dense, st, s, [0])
            wal.log_step(s, [0], prev, st)
            prev = st
        wal.close()
        return st

    # Legacy writer (3-tuple records) -> partition-aware reader.
    final = write_log(tmp_path, "w0", None)
    tagged_reader = ElasticWal(
        str(tmp_path), "w0", dense, drill.publish_name, partitions=P
    )
    state, last_step, owned = tagged_reader.recover(drill.init(dense))
    assert last_step == 2 and owned == {0}
    assert np.array_equal(pt.state_digests(state, P), pt.state_digests(final, P))
    tagged_reader.close()

    # Tagged writer (4-tuple records) -> legacy reader.
    final = write_log(tmp_path / "t", "w1", P)
    legacy_reader = ElasticWal(
        str(tmp_path / "t"), "w1", dense, drill.publish_name
    )
    state, last_step, owned = legacy_reader.recover(drill.init(dense))
    assert last_step == 2 and owned == {0}
    assert np.array_equal(pt.state_digests(state, P), pt.state_digests(final, P))
    legacy_reader.close()


# --- partial anti-entropy ---------------------------------------------------


def _fs_pair(root):
    a = GossipNode(FsTransport(str(root), "a"))
    b = GossipNode(FsTransport(str(root), "b"))
    a.heartbeat(), b.heartbeat()
    return a, b


def test_partial_resync_fetches_only_divergent_partitions(tmp_path):
    """b diverges from a on ONE partition; the partial path must repair
    it with psnap fetches < P+1, zero wasted fetches, and a state whose
    digest vector equals the whole-snapshot merge bit for bit."""
    part_map = pt.part_of(np.arange(I), P)
    p_star = int(np.bincount(part_map, minlength=P).argmax())
    hot = np.arange(I)[part_map == p_star][:4]

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a, b = _fs_pair(tmp_path)
    # Shared prefix, published+swept so b's cursor is current.
    pub = DeltaPublisher(a, dense, name="topk_rmv", full_every=1, partitions=P)
    st_a = drill.init(dense)
    for s in range(3):
        st_a = drill.apply(dense, st_a, s, range(R))
    pub.publish(st_a)
    curs = {}
    st_b, _ = sweep_deltas(b, dense, drill.init(dense), curs)
    assert np.array_equal(pt.state_digests(st_b, P), pt.state_digests(st_a, P))

    # a alone advances, confined to partition p*.
    dense2, st_a = _apply_hot(dense, st_a, hot)
    pub.publish(st_a)

    partial = PartialAntiEntropy(b, partitions=P)
    whole = dense.merge(st_b, st_a)
    c0 = dict(b.metrics.counters)
    st_b2, stats = sweep_deltas(b, dense, st_b, curs, partial=partial)
    c1 = dict(b.metrics.counters)
    fetched = c1.get("net.psnap_fetches", 0) - c0.get("net.psnap_fetches", 0)
    assert stats.get("partials", 0) == 1 and stats.get("fulls", 0) == 0
    assert 0 < fetched < P + 1, fetched
    assert c1.get("net.partition_resyncs", 0) == 1
    assert c1.get("net.psnap_wasted", 0) == 0
    assert np.array_equal(pt.state_digests(st_b2, P), pt.state_digests(whole, P))

    # Next sweep: vectors agree -> zero-fetch cursor advance.
    pub.publish(st_a)
    st_b3, _ = sweep_deltas(b, dense, st_b2, curs, partial=partial)
    c2 = dict(b.metrics.counters)
    assert c2.get("net.partition_agree_advances", 0) >= 1
    assert c2.get("net.psnap_fetches", 0) == c1.get("net.psnap_fetches", 0)
    assert np.array_equal(pt.state_digests(st_b3, P), pt.state_digests(st_a, P))


def _apply_hot(dense, state, hot):
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    B = len(hot)
    a_id = np.zeros((R, B), np.int32)
    a_score = np.zeros((R, B), np.int32)
    a_ts = np.zeros((R, B), np.int32)
    a_id[0] = np.asarray(hot, np.int32)
    a_score[0] = 700 + np.arange(B)
    a_ts[0] = 20_000 + np.arange(B)
    z = np.zeros((R, B), np.int32)
    ops = TopkRmvOps(
        add_key=jnp.asarray(z), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(a_score), add_dc=jnp.asarray(z),
        add_ts=jnp.asarray(a_ts),
        rmv_key=jnp.asarray(np.zeros((R, 1), np.int32)),
        rmv_id=jnp.asarray(np.full((R, 1), -1, np.int32)),
        rmv_vc=jnp.asarray(np.zeros((R, 1, 4), np.int32)),
    )
    state, _ = dense.apply_ops(state, ops, collect_dominated=False)
    return dense, state


def test_partial_resync_falls_back_for_legacy_peer(tmp_path):
    """A peer that never published digests (legacy fleet member) must
    route through the whole-snapshot path — no stall, no crash."""
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a, b = _fs_pair(tmp_path)
    pub = DeltaPublisher(a, dense, name="topk_rmv", full_every=1)  # no plane
    st_a = drill.init(dense)
    st_a = drill.apply(dense, st_a, 0, range(R))
    pub.publish(st_a)
    partial = PartialAntiEntropy(b, partitions=P)
    st_b, stats = sweep_deltas(
        b, dense, drill.init(dense), {}, partial=partial
    )
    assert stats["fulls"] == 1
    assert np.array_equal(pt.state_digests(st_b, P), pt.state_digests(st_a, P))


# --- rejoin streaming (the SIGKILL drill) -----------------------------------


def test_rejoin_stream_resumes_from_durable_shards(tmp_path):
    """Kill the streamer mid-stream (abandon it after k shards — the
    in-process SIGKILL model); the next incarnation must plan EXACTLY
    the partitions that never became durable, and finish to the peer's
    digest vector."""
    from antidote_ccrdt_tpu.harness.checkpoint import RejoinStreamer

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a, b = _fs_pair(tmp_path / "net")
    pub = DeltaPublisher(a, dense, name="topk_rmv", full_every=1, partitions=P)
    st_a = drill.init(dense)
    for s in range(STEPS):
        st_a = drill.apply(dense, st_a, s, range(R))
    pub.publish(st_a)

    root = str(tmp_path / "ckpt")
    s1 = RejoinStreamer(root, "topk_rmv", dense, b, "a", partitions=P)
    st = s1.start(drill.init(dense))
    plan_full = list(s1.plan)
    assert plan_full, "fresh rejoin must plan divergent partitions"
    killed_after = max(1, len(plan_full) // 2)
    for _ in range(killed_after):
        st, part, _done = s1.step(st)
        assert part is not None  # pull medium serves immediately
    # SIGKILL: s1 is abandoned; everything it persisted is durable,
    # everything else never happened.

    s2 = RejoinStreamer(root, "topk_rmv", dense, b, "a", partitions=P)
    st2 = s2.start(drill.init(dense))
    assert s2.plan == plan_full[killed_after:], (
        "resume must exclude durable shards and keep the rest, in order"
    )
    st2 = s2.run(st2)
    assert not s2.plan
    assert np.array_equal(pt.state_digests(st2, P), pt.state_digests(st_a, P))
    assert b.metrics.counters.get("rejoin.parts_streamed", 0) == len(plan_full)

    # A third incarnation has nothing left to do — and nothing to fetch.
    c0 = dict(b.metrics.counters)
    s3 = RejoinStreamer(root, "topk_rmv", dense, b, "a", partitions=P)
    st3 = s3.start(drill.init(dense))
    assert s3.plan == []
    assert np.array_equal(pt.state_digests(st3, P), pt.state_digests(st_a, P))
    assert b.metrics.counters.get("net.psnap_fetches", 0) == c0.get(
        "net.psnap_fetches", 0
    )


def test_rejoin_skips_torn_shard(tmp_path):
    """A torn shard (truncated write at SIGKILL) is not durable: the
    loader skips it and the next plan re-streams that partition."""
    from antidote_ccrdt_tpu.harness.checkpoint import (
        RejoinStreamer, _shard_path, load_partitioned_checkpoint,
    )

    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    a, b = _fs_pair(tmp_path / "net")
    pub = DeltaPublisher(a, dense, name="topk_rmv", full_every=1, partitions=P)
    st_a = drill.init(dense)
    for s in range(4):
        st_a = drill.apply(dense, st_a, s, range(R))
    pub.publish(st_a)

    root = str(tmp_path / "ckpt")
    s1 = RejoinStreamer(root, "topk_rmv", dense, b, "a", partitions=P)
    st = s1.start(drill.init(dense))
    st = s1.run(st)
    assert not s1.plan

    victim = None
    for p in range(P + 1):
        path = _shard_path(root, p)
        if os.path.exists(path) and os.path.getsize(path) > 30:
            with open(path, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(blob[: len(blob) // 2])
            victim = p
            break
    assert victim is not None
    _step, _name, _st, durable = load_partitioned_checkpoint(
        root, drill.init(dense), dense
    )
    assert victim not in durable
    s2 = RejoinStreamer(root, "topk_rmv", dense, b, "a", partitions=P)
    st2 = s2.start(drill.init(dense))
    st2 = s2.run(st2)
    assert np.array_equal(pt.state_digests(st2, P), pt.state_digests(st_a, P))


# --- seeded sim chaos with the partition plane on ---------------------------

N = 4
DT = 0.1
TIMEOUT = 0.35


def run_partition_chaos(seed, *, loss=0.03, dup=0.03):
    """tests/test_net_chaos.py's `run_chaos` with the partition plane
    wired: partitioned publishers + `PartialAntiEntropy` on every sweep.
    Returns ({member: digest}, fleet counters). Also the chaos_gate leg
    (scripts/chaos_gate.py imports this)."""
    net = SimNet(seed=seed, latency=(0.001, 0.02), loss=loss, dup=dup)
    drill = DRILLS["topk_rmv"]
    dense = drill.make_engine()
    names = [f"m{i}" for i in range(N)]
    nodes = {m: GossipNode(net.join(m)) for m in names}
    states = {m: drill.init(dense) for m in names}
    cursors = {m: {} for m in names}
    pubs = {
        m: DeltaPublisher(
            nodes[m], dense, name=drill.publish_name, full_every=4,
            keep=4, partitions=P,
        )
        for m in names
    }
    partials = {
        m: PartialAntiEntropy(nodes[m], partitions=P, max_tries=6)
        for m in names
    }
    owned = {m: set() for m in names}
    crashed = set()

    def publish_and_sweep(m):
        pubs[m].publish(states[m])
        states[m], _ = sweep_deltas(
            nodes[m], dense, states[m], cursors[m], partial=partials[m]
        )

    for _ in range(3):
        for m in names:
            nodes[m].heartbeat()
        net.advance(DT)
    for m in names:
        assert set(nodes[m].members()) == set(names), "bootstrap incomplete"

    for step in range(STEPS):
        if step == 3:
            net.partition({"m0", "m1"}, {"m2", "m3"})
        if step == 6:
            net.heal()
        if step == 7:
            net.crash("m3")
            crashed.add("m3")
        for m in names:
            if m in crashed:
                continue
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), step)
            owned[m] = now_owned
            states[m] = drill.apply(dense, states[m], step, sorted(owned[m]))
            if step % 2 == 0:
                publish_and_sweep(m)
        net.advance(DT)

    net.loss = net.dup = 0.0
    ref = reference_digest("topk_rmv")
    live = [m for m in names if m not in crashed]
    for _ in range(40):
        for m in live:
            node = nodes[m]
            node.heartbeat()
            now_owned = owned[m] | set(my_replicas(node, R, TIMEOUT))
            gained = now_owned - owned[m]
            if gained:
                states[m] = drill.adopt(dense, states[m], sorted(gained), STEPS)
            owned[m] = now_owned
            publish_and_sweep(m)
        net.advance(DT)
        if all(drill.digest(dense, states[m]) == ref for m in live):
            break

    digests = {m: drill.digest(dense, states[m]) for m in live}
    return digests, dict(net.metrics.counters)


def test_partition_chaos_converges_with_partial_resyncs():
    """Partition loss + heal + crash with the plane on: every survivor
    reaches the sequential reference, partial repairs actually happened
    (counters lit), and no psnap was fetched for an agreeing partition."""
    digests, counters = run_partition_chaos(seed=7)
    ref = reference_digest("topk_rmv")
    assert ref, "reference observable is empty — drill is vacuous"
    for m, d in digests.items():
        assert d == ref, f"{m} diverged\ngot: {d}\nref: {ref}"
    assert counters.get("net.sim_lost", 0) > 0, counters
    assert counters.get("net.partition_resyncs", 0) > 0, counters
    assert counters.get("net.psnap_bytes", 0) > 0, counters
    assert counters.get("net.psnap_wasted", 0) == 0, counters


def test_partition_chaos_deterministic_replay():
    d1, c1 = run_partition_chaos(seed=3)
    d2, c2 = run_partition_chaos(seed=3)
    assert d1 == d2
    assert c1 == c2
