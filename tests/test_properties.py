"""Property-based convergence and algebra tests (hypothesis).

SURVEY.md §4 calls for property-based convergence testing the reference
lacks: random op streams over N simulated DCs with random sync points
under causal delivery must leave every pair of replicas observably equal.
On top of convergence, this file checks the algebraic laws the batched
TPU path depends on:

* dense merge is commutative/associative, and idempotent for JOIN types;
* dense apply_ops is invariant to op order within a batch;
* pairwise op compaction preserves final state (the reference's
  can_compact/compact_ops contract, antidote_ccrdt.erl:55-56);
* reference-wire serialization round-trips arbitrary reachable states.
"""

import jax
import numpy as np
import pytest
from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)

from antidote_ccrdt_tpu.core import wire
from antidote_ccrdt_tpu.core.behaviour import registry
from antidote_ccrdt_tpu.harness.replay import ScalarReplay
from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

from test_topk_rmv_dense import gen_effect_log, pack_ops

SETTINGS = dict(
    deadline=None, suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large]
)


# --- op-stream strategies -------------------------------------------------

ids = st.integers(0, 14)
scores = st.integers(1, 99)


def stream(n_replicas, op_strategy, max_size=60):
    """[(origin, prepare_op)] with interspersed sync markers (origin=-1)."""
    item = st.one_of(
        st.tuples(st.integers(0, n_replicas - 1), op_strategy),
        st.just((-1, None)),
    )
    return st.lists(item, max_size=max_size)


topk_rmv_ops = st.one_of(
    st.tuples(st.just("add"), st.tuples(ids, scores)),
    st.tuples(st.just("rmv"), ids),
)
leaderboard_ops = st.one_of(
    st.tuples(st.just("add"), st.tuples(ids, scores)),
    st.tuples(st.just("ban"), ids),
)
topk_ops = st.tuples(st.just("add"), st.tuples(ids, scores))
average_ops = st.one_of(
    st.tuples(st.just("add"), st.integers(-50, 50)),
    st.tuples(st.just("add"), st.tuples(st.integers(-50, 50), st.integers(0, 5))),
)
word_ops = st.tuples(
    st.just("add"),
    st.lists(st.sampled_from(["a", "b", "cc", "dd"]), max_size=6).map(" ".join),
)


def run_stream(name, new_args, items, n_replicas=3):
    crdt = registry.scalar(name)
    rp = ScalarReplay(crdt, n_replicas, new_args=new_args)
    for origin, op in items:
        if origin < 0:
            rp.sync()
        else:
            rp.submit(origin, op)
    rp.sync()
    return crdt, rp


CONVERGENCE_CASES = [
    ("topk_rmv", (3,), topk_rmv_ops),
    ("leaderboard", (3,), leaderboard_ops),
    ("topk", (4,), topk_ops),
    ("average", (), average_ops),
    ("wordcount", (), word_ops),
    ("worddocumentcount", (), word_ops),
]


def assert_topk_rmv_converged(rp):
    """Convergence at the level the REFERENCE guarantees. Its cmp ignores
    dc (topk_rmv.erl:392-395) and recompute_observed keeps the incumbent
    on a tie (:306), so when two concurrent adds tie on (score, id, ts)
    from DIFFERENT DCs, the observed representative's dc is arrival-order
    dependent — in the reference exactly as here (hypothesis found the
    example: three dc-distinct adds of (id 0, score 1) at ts 1). Every
    other plane converges fully: the value/1 observable, the observed
    keys and their (score, ts), the masked sets, removal vcs, and clocks.
    (The dense engine is deliberately STRONGER: its slot order adds a dc
    tiebreak, so it has no such corner.)"""
    ref = rp.states[0]
    for s in rp.states[1:]:
        assert sorted(s.observed) == sorted(ref.observed)
        for k in ref.observed:
            # (score, id, ts) equal; dc may legitimately differ on ties.
            sa, ia, (_, ta) = ref.observed[k]
            sb, ib, (_, tb) = s.observed[k]
            assert (sa, ia, ta) == (sb, ib, tb)
        assert s.masked == ref.masked
        assert s.removals == ref.removals
        assert s.vc == ref.vc
        assert s.size == ref.size


@pytest.mark.parametrize("name,new_args,ops", CONVERGENCE_CASES, ids=[c[0] for c in CONVERGENCE_CASES])
def test_convergence_random_interleavings(name, new_args, ops):
    @settings(max_examples=60, **SETTINGS)
    @given(items=stream(3, ops))
    def prop(items):
        crdt, rp = run_stream(name, new_args, items)
        if name == "topk_rmv":
            assert_topk_rmv_converged(rp)
        else:
            assert rp.converged(), (name, rp.values())

    prop()


@settings(max_examples=40, **SETTINGS)
@given(items=stream(4, topk_rmv_ops, max_size=80))
def test_topk_rmv_four_dc_convergence_and_wire(items):
    crdt, rp = run_stream("topk_rmv", (2,), items, n_replicas=4)
    assert_topk_rmv_converged(rp)
    for s in rp.states:
        blob = wire.to_reference_binary("topk_rmv", s)
        back = wire.from_reference_binary("topk_rmv", blob)
        assert wire.state_to_term("topk_rmv", back) == wire.state_to_term("topk_rmv", s)


@settings(max_examples=40, **SETTINGS)
@given(items=stream(3, leaderboard_ops))
def test_leaderboard_wire_roundtrip_reachable_states(items):
    crdt, rp = run_stream("leaderboard", (3,), items)
    for s in rp.states:
        blob = wire.to_reference_binary("leaderboard", s)
        assert crdt.equal(s, wire.from_reference_binary("leaderboard", blob))


# --- compaction soundness -------------------------------------------------


def _apply_seq(crdt, state, effects):
    for e in effects:
        if e is None:
            continue
        state, extras = crdt.update(e, state)
        for x in extras:
            state, _ = crdt.update(x, state)
    return state


@settings(max_examples=80, **SETTINGS)
@given(
    ops=st.lists(st.tuples(st.integers(0, 2), topk_rmv_ops), min_size=2, max_size=12),
    i=st.integers(0, 10),
    j=st.integers(0, 11),
)
def test_compaction_preserves_state_topk_rmv(ops, i, j):
    """Compacting any compactible pair in an effect log must not change the
    state the log folds to (same-origin logs: compaction happens inside one
    DC's op log before shipping)."""
    crdt = registry.scalar("topk_rmv")
    rng = np.random.default_rng(0)
    _, log = gen_effect_log(rng, len(ops), n_ids=6, n_dcs=3, size=3, rmv_frac=0.3)
    if len(log) < 2:
        return
    i, j = i % len(log), j % len(log)
    if i == j:
        return
    i, j = min(i, j), max(i, j)
    if not crdt.can_compact(log[i], log[j]):
        return
    c1, c2 = crdt.compact_ops(log[i], log[j])
    compacted = list(log)
    compacted[i], compacted[j] = c1, c2
    a = _apply_seq(crdt, crdt.new(3), log)
    b = _apply_seq(crdt, crdt.new(3), compacted)
    assert crdt.equal(a, b)
    assert crdt.value(a) == crdt.value(b) or set(crdt.value(a)) == set(crdt.value(b))


@settings(max_examples=60, **SETTINGS)
@given(
    vals=st.lists(
        st.tuples(st.integers(-20, 20), st.integers(0, 4)), min_size=2, max_size=6
    )
)
def test_compaction_preserves_state_average(vals):
    crdt = registry.scalar("average")
    log = [("add", v) for v in vals]
    while True:
        for i in range(len(log)):
            hit = False
            for j in range(i + 1, len(log)):
                if log[i] and log[j] and crdt.can_compact(log[i], log[j]):
                    log[i], log[j] = crdt.compact_ops(log[i], log[j])
                    hit = True
                    break
            if hit:
                break
        else:
            break
    expect_sum = sum(v for v, n in vals if n > 0)
    expect_n = sum(n for _, n in vals)
    state = _apply_seq(crdt, crdt.new(), log)
    assert state == (expect_sum, expect_n)


# --- dense algebra laws ---------------------------------------------------

_D = make_dense(n_ids=16, n_dcs=3, size=4, slots_per_id=3)
_apply = jax.jit(_D.apply_ops)
_merge = jax.jit(_D.merge)


def _state_from_log(log):
    s = _D.init(n_replicas=1, n_keys=1)
    out, _ = _apply(s, pack_ops(log, n_dcs=3, add_pad=24, rmv_pad=8))
    return out


def _obs(state):
    return set(map(tuple, _D.value(state)[0][0]))


@settings(max_examples=15, **SETTINGS)
@given(seed=st.integers(0, 10_000), n=st.integers(2, 18))
def test_dense_merge_laws(seed, n):
    rng = np.random.default_rng(seed)
    _, log = gen_effect_log(rng, n, n_ids=16, n_dcs=3, size=4, rmv_frac=0.3)
    cut1, cut2 = len(log) // 3, 2 * len(log) // 3
    a = _state_from_log(log[:cut1])
    b = _state_from_log(log[cut1:cut2])
    c = _state_from_log(log[cut2:])
    ab = _merge(a, b)
    # commutative + associative + idempotent (JOIN lattice)
    assert _obs(ab) == _obs(_merge(b, a))
    assert _obs(_merge(ab, c)) == _obs(_merge(a, _merge(b, c)))
    assert _obs(_merge(ab, ab)) == _obs(ab)


@settings(max_examples=15, **SETTINGS)
@given(seed=st.integers(0, 10_000), n=st.integers(2, 16))
def test_dense_batch_order_invariance(seed, n):
    """Applying a permuted effect batch yields the same observable — the
    property that makes one-dispatch batching sound (SURVEY.md §7 hard
    part (a))."""
    rng = np.random.default_rng(seed)
    _, log = gen_effect_log(rng, n, n_ids=16, n_dcs=3, size=4, rmv_frac=0.3)
    if not log:
        return
    perm = list(rng.permutation(len(log)))
    a = _state_from_log(log)
    b = _state_from_log([log[p] for p in perm])
    assert _obs(a) == _obs(b)


# --- batch_merge properties -----------------------------------------------


@given(
    data=st.data(),
    n_states=st.integers(2, 6),
)
@settings(max_examples=25, **SETTINGS)
def test_batch_merge_join_types_tolerate_overlap(data, n_states):
    """For JOIN types, batch_merge over ANY covering assignment of the op
    stream (each op delivered to >= 1 state, possibly several) equals the
    state that saw every op — overlap is absorbed by idempotence."""
    from antidote_ccrdt_tpu.core.batch_merge import batch_merge
    from antidote_ccrdt_tpu.core.clock import make_contexts

    name = data.draw(st.sampled_from(["topk", "leaderboard", "topk_rmv"]))
    eng = registry.scalar(name)
    ctxs = make_contexts(2)
    s_all = eng.new(5)
    n_ops = data.draw(st.integers(1, 30))
    effects = []
    for step in range(n_ops):
        if name == "topk_rmv" and s_all.observed and data.draw(st.booleans()):
            target = data.draw(st.sampled_from(sorted(s_all.observed)))
            op = ("rmv", target)
        elif name == "leaderboard" and data.draw(st.integers(0, 9)) == 0:
            op = ("ban", data.draw(ids))
        else:
            op = ("add", (data.draw(ids), data.draw(scores)))
        eff = eng.downstream(op, s_all, ctxs[step % 2])
        if eff is None:
            continue
        effects.append(eff)
        s_all, extras = eng.update(eff, s_all)
        for e in extras:
            effects.append(e)
            s_all, _ = eng.update(e, s_all)

    states = [eng.new(5) for _ in range(n_states)]
    for eff in effects:
        # every op lands on at least one state; overlap is free
        members = [
            i for i in range(n_states) if data.draw(st.booleans())
        ] or [data.draw(st.integers(0, n_states - 1))]
        for i in members:
            states[i], _ = eng.update(eff, states[i])

    merged = batch_merge(name, states)
    ref_obs = sorted(map(tuple, eng.value(s_all)))
    got_obs = sorted(map(tuple, eng.value(merged)))
    assert got_obs == ref_obs


def test_topk_rmv_cmp_tie_corner_is_reference_faithful():
    """The corner assert_topk_rmv_converged documents, pinned explicitly:
    concurrent adds of the same (id, score) at the same logical ts from
    different DCs leave the observed representative's dc arrival-order
    dependent — reference behavior (cmp ignores dc, topk_rmv.erl:392-395;
    the incumbent wins ties, :306) — while value/1 and every other state
    plane still converge."""
    crdt = registry.scalar("topk_rmv")
    a = ("add", (0, 1, ("dc_a", 1)))
    b = ("add", (0, 1, ("dc_b", 1)))
    s_ab = _apply_seq(crdt, crdt.new(2), [a, b])
    s_ba = _apply_seq(crdt, crdt.new(2), [b, a])
    assert s_ab.observed[0][2][0] == "dc_a"  # incumbent won the tie...
    assert s_ba.observed[0][2][0] == "dc_b"  # ...in each arrival order
    assert not crdt.equal(s_ab, s_ba)  # observed-map equal: dc differs
    assert crdt.value(s_ab) == crdt.value(s_ba) == [(0, 1)]
    assert s_ab.masked == s_ba.masked and s_ab.vc == s_ba.vc
