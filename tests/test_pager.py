"""Out-of-core partition pager (core/pager.py).

The invariant under test everywhere: **logical state = device state ⊔
cold substrate**, bit-exactly. Residency is a pure performance axis —
demote/hydrate in any order must never change a digest, a psnap, a
checkpoint, or a converged peer. Covers the CCPT residency round-trip
(demote → digests/psnaps answered from the stored blob → hydrate →
bit-identical vs never-demoted), cold folds vs an all-resident
reference, the queue-until-hydration mode, clock eviction under an HBM
budget, the kill-switch, mixed-residency partitioned checkpoints, the
partial anti-entropy surface serving cold psnaps straight from blobs,
the disk spill tier, a SIGKILL-mid-hydration drill (recovery must
discard — never resurrect — spill blobs), and a hypothesis property
over arbitrary demote/hydrate interleavings.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import jax.numpy as jnp
import pytest
from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)
from conftest import cpu_subprocess_env

from antidote_ccrdt_tpu.core import pager as pg
from antidote_ccrdt_tpu.core import partition as pt
from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.parallel.delta import (
    apply_any_delta, like_delta_for, make_delta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

R, NK, I, DCS, K, M, B, P = 2, 1, 64, 4, 8, 2, 32, 8

DENSE = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def gen_ops(step, rng, ids=None):
    a_id = (
        rng.integers(0, I, (R, B)).astype(np.int32)
        if ids is None
        else ids[rng.integers(0, len(ids), (R, B))].astype(np.int32)
    )
    return TopkRmvOps(
        add_key=jnp.zeros((R, B), jnp.int32),
        add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
        add_dc=jnp.zeros((R, B), jnp.int32),
        add_ts=jnp.asarray(np.broadcast_to(
            step * B + np.arange(B) + 1, (R, B)
        ).astype(np.int32)),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
    )


def seeded_state(steps=4, seed=0, ids=None):
    rng = np.random.default_rng(seed)
    state = DENSE.init(R, NK)
    for s in range(steps):
        state, _ = DENSE.apply_ops(
            state, gen_ops(s, rng, ids), collect_dominated=False
        )
    return state


def leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


# --- CCPT residency round-trip ---------------------------------------------


def test_demote_serves_digests_and_psnaps_from_blob():
    state = seeded_state()
    ref = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), state)
    ref_dig = pt.state_digests(state, P)
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    for p in (0, 2, 4, 6):
        state = pager.demote(state, p)
    assert pager.cold_parts() == {0, 2, 4, 6}
    # Mixed-residency digest vector is bit-equal to the all-resident one
    # (the device state's own digests are NOT — the content moved out).
    assert np.array_equal(pager.digest_vector(state), ref_dig)
    assert not np.array_equal(pt.state_digests(state, P), ref_dig)
    # Cold psnap blobs answer straight from storage, round-tripping the
    # CCPT container, and decode to the partition's exact content.
    blob = pager.psnap_blob(state, 7, 0)
    seq, part, payload = pt.decode_psnap_blob(blob)
    assert (seq, part) == (7, 0)
    _name, psnap = serial.loads_dense(payload, like_delta_for(DENSE, state))
    fresh = pt.apply_psnap(DENSE, DENSE.init(R, NK), psnap)
    assert pt.digest_entries(fresh, P, [0])[0] == int(ref_dig[0])
    assert pager.metrics.counters.get("pager.blob_serves", 0) >= 1
    # full_state reassembles the logical state bit-identically, without
    # changing residency.
    assert leaves_equal(pager.full_state(state), ref)
    assert pager.cold_parts() == {0, 2, 4, 6}


def test_hydrate_all_is_bit_identical_to_never_demoted():
    state = seeded_state()
    ref = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), state)
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    for p in range(P):
        state = pager.demote(state, p)
    assert pager.cold_parts() == set(range(P))
    for p in sorted(pager.cold_parts()):
        state = pager.hydrate(state, p)
    assert not pager.has_cold()
    assert leaves_equal(state, ref)
    assert pager.metrics.counters.get("pager.hydrations") == P
    # Every hydration billed a miss-latency sample (milliseconds).
    assert len(pager.metrics.latencies["pager.miss_ms"].samples) == P


def test_hot_writes_keep_mixed_digests_consistent():
    """Ops against RESIDENT partitions while others are cold: the mixed
    digest vector must track the all-resident reference exactly."""
    state = seeded_state()
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    part_map = pt.part_of(np.arange(I), P)
    hot = int(sorted(set(range(P)) - {0, 1, 2})[0])
    for p in (0, 1, 2):
        state = pager.demote(state, p)
    rng = np.random.default_rng(41)
    hot_ids = np.arange(I)[part_map == hot]
    state, _ = DENSE.apply_ops(
        state, gen_ops(9, rng, hot_ids), collect_dominated=False
    )
    full = pager.full_state(state)
    assert np.array_equal(pager.digest_vector(state), pt.state_digests(full, P))


# --- gossip: cold folds and queueing ---------------------------------------


def test_cold_fold_matches_all_resident_reference():
    state = seeded_state()
    ref = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), state)
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    for p in (0, 2, 4, 6):
        state = pager.demote(state, p)
    # A peer delta over the whole id space: cold half folds host-side,
    # hot half joins on device — no hydration.
    rng = np.random.default_rng(99)
    peer0 = DENSE.init(R, NK)
    peer1, _ = DENSE.apply_ops(
        peer0, gen_ops(10, rng), collect_dominated=False
    )
    delta = make_delta(DENSE, peer0, peer1)
    ref2 = apply_any_delta(
        DENSE, DENSE.merge(DENSE.init(R, NK), jax.tree_util.tree_map(jnp.asarray, ref)),
        delta,
    )
    state = pager.apply_delta(state, delta)
    assert pager.cold_parts() == {0, 2, 4, 6}  # never hydrated
    assert pager.metrics.counters.get("pager.cold_folds", 0) >= 1
    assert leaves_equal(pager.full_state(state), ref2)
    assert np.array_equal(pager.digest_vector(state), pt.state_digests(ref2, P))


def test_queue_mode_defers_cold_deltas_until_hydration():
    state = seeded_state()
    pager = pg.PartitionPager(
        DENSE, state, P=P, name="topk_rmv", fold_cold=False
    )
    cold = (0, 2, 4, 6)
    for p in cold:
        state = pager.demote(state, p)
    rng = np.random.default_rng(99)
    peer0 = DENSE.init(R, NK)
    peer1, _ = DENSE.apply_ops(peer0, gen_ops(10, rng), collect_dominated=False)
    delta = make_delta(DENSE, peer0, peer1)
    ref2 = apply_any_delta(DENSE, pager.full_state(state), delta)
    state = pager.apply_delta(state, delta)
    assert pager.metrics.counters.get("pager.queued_deltas", 0) >= 1
    assert pager.metrics.counters.get("pager.cold_folds", 0) == 0
    # Hydration drains the queue: the deferred cold slices land then.
    for p in cold:
        state = pager.hydrate(state, p)
    assert not pager._queued
    assert pager.metrics.counters.get("pager.queue_drains", 0) >= 1
    assert leaves_equal(pager.full_state(state), ref2)


def test_partial_antientropy_serves_cold_psnaps_without_hydrating(tmp_path):
    """A cold-heavy writer repairs an empty reader through the partition
    surface: digest vector and psnaps come from the pager (cold entries
    straight from stored CCPT blobs), the reader converges to the full
    LOGICAL state, and the writer never hydrates."""
    from antidote_ccrdt_tpu.net.transport import FsTransport, GossipNode
    from antidote_ccrdt_tpu.parallel.elastic import (
        DeltaPublisher, PartialAntiEntropy, sweep_deltas,
    )

    state = seeded_state()
    ref_dig = pt.state_digests(state, P)
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    for p in (0, 2, 4, 6):
        state = pager.demote(state, p)

    a = GossipNode(FsTransport(str(tmp_path), "a"))
    b = GossipNode(FsTransport(str(tmp_path), "b"))
    a.heartbeat(), b.heartbeat()
    pub = DeltaPublisher(
        a, DENSE, name="topk_rmv", full_every=1, partitions=P, pager=pager
    )
    hydr0 = pager.metrics.counters.get("pager.hydrations", 0)
    pub.publish(state)
    partial = PartialAntiEntropy(b, partitions=P)
    st_b, _ = sweep_deltas(b, DENSE, DENSE.init(R, NK), {}, partial=partial)
    assert np.array_equal(pt.state_digests(st_b, P), ref_dig)
    assert pager.cold_parts() == {0, 2, 4, 6}
    assert pager.metrics.counters.get("pager.hydrations", 0) == hydr0
    assert b.metrics.counters.get("net.psnap_wasted", 0) == 0


# --- policy: budget, clock, accounting --------------------------------------


def test_budget_eviction_and_hit_accounting():
    state = seeded_state()
    probe = pg.PartitionPager(DENSE, state, P=P, name="probe")
    ref_dig = pt.state_digests(state, P)
    budget = probe.meta_bytes + sum(probe.part_bytes[p] for p in range(4))
    pager = pg.PartitionPager(
        DENSE, state, P=P, name="topk_rmv", hbm_budget_bytes=budget
    )
    state = pager.enforce_budget(state)
    assert pager.resident_bytes() <= budget
    assert pager.has_cold()
    want = sorted(pager.cold_parts())[:2]
    state = pager.ensure_resident(state, want)
    assert pager.misses == 2
    assert all(pager.is_resident(p) for p in want)
    # Re-enforced: paging the misses in paged something else out.
    assert pager.resident_bytes() <= budget
    state = pager.ensure_resident(state, want)
    assert pager.misses == 2 and pager.hits >= 2
    assert 0.0 < pager.hit_rate() < 1.0
    assert np.array_equal(
        pt.state_digests(pager.full_state(state), P), ref_dig
    )


def test_kill_switch_and_budget_gate(monkeypatch):
    state = seeded_state(steps=1)
    # Kill-switch: CCRDT_PAGER=0 forces the all-resident legacy path
    # even with a budget configured.
    monkeypatch.setenv(pg.ENV_FLAG, "0")
    monkeypatch.setenv(pg.ENV_HBM, "64k")
    assert pg.maybe_pager(DENSE, state, P=P) is None
    # Default-off without a budget: no CCRDT_PAGER_HBM_BUDGET, no pager.
    monkeypatch.delenv(pg.ENV_FLAG)
    monkeypatch.delenv(pg.ENV_HBM)
    assert pg.maybe_pager(DENSE, state, P=P) is None
    assert pg.maybe_pager(DENSE, state, P=P, require_budget=False) is not None
    # Budget parsing: k/m/g suffixes land in hbm_budget.
    monkeypatch.setenv(pg.ENV_HBM, "64k")
    pager = pg.maybe_pager(DENSE, state, P=P)
    assert pager is not None and pager.hbm_budget == 64 << 10


def test_unpageable_engines_are_rejected():
    from antidote_ccrdt_tpu.models.average import AverageDense

    avg = AverageDense()
    st_avg = avg.init(R, NK)
    with pytest.raises(ValueError):
        pg.PartitionPager(avg, st_avg, P=P)
    assert pg.maybe_pager(avg, st_avg, P=P, require_budget=False) is None


# --- persistence: checkpoints and the spill tier ----------------------------


def test_partitioned_checkpoint_round_trips_mixed_residency(tmp_path):
    from antidote_ccrdt_tpu.harness.checkpoint import (
        load_partitioned_checkpoint, save_partitioned_checkpoint,
    )

    state = seeded_state()
    ref_dig = pt.state_digests(state, P)
    pager = pg.PartitionPager(DENSE, state, P=P, name="topk_rmv")
    for p in (1, 3, 5):
        state = pager.demote(state, p)
    save_partitioned_checkpoint(
        str(tmp_path), "topk_rmv", state, DENSE, step=5,
        partitions=P, pager=pager,
    )
    step, name, restored, parts = load_partitioned_checkpoint(
        str(tmp_path), DENSE.init(R, NK), DENSE
    )
    assert (step, name) == (5, "topk_rmv")
    assert set(parts) >= set(range(P))
    assert np.array_equal(pt.state_digests(restored, P), ref_dig)


def test_spill_tier_round_trips_and_discard(tmp_path):
    state = seeded_state()
    ref_dig = pt.state_digests(state, P)
    pager = pg.PartitionPager(
        DENSE, state, P=P, name="topk_rmv",
        spill_dir=str(tmp_path), host_budget_bytes=1,
    )
    for p in (0, 2):
        state = pager.demote(state, p)
    spilled = [f for f in os.listdir(tmp_path) if f.startswith(pg.SPILL_PREFIX)]
    assert len(spilled) == 2  # host budget of 1 byte spills every payload
    assert pager.metrics.counters.get("pager.spills", 0) >= 2
    # Hydration reads the blob back from disk and deletes the file.
    state = pager.hydrate(state, 0)
    state = pager.hydrate(state, 2)
    assert not [f for f in os.listdir(tmp_path) if f.startswith(pg.SPILL_PREFIX)]
    assert np.array_equal(pt.state_digests(state, P), ref_dig)
    # discard_spill: the recovery-path sweep removes every pager blob.
    for p in (4, 6):
        state = pager.demote(state, p)
    assert pg.discard_spill(str(tmp_path)) == 2
    assert pg.discard_spill(str(tmp_path)) == 0


# --- SIGKILL mid-hydration drill -------------------------------------------

_SIGKILL_CHILD = r"""
import json, os, sys
import numpy as np
import jax.numpy as jnp

from antidote_ccrdt_tpu.core import pager as pg
from antidote_ccrdt_tpu.core import partition as pt
from antidote_ccrdt_tpu.harness.wal import ElasticWal
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.utils import faults

root = os.environ["CCRDT_DRILL_ROOT"]
R, NK, I, DCS, K, M, B, P = 2, 1, 64, 4, 8, 2, 32, 8
dense = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)

def gen_ops(step, rng):
    a_id = rng.integers(0, I, (R, B)).astype(np.int32)
    return TopkRmvOps(
        add_key=jnp.zeros((R, B), jnp.int32), add_id=jnp.asarray(a_id),
        add_score=jnp.asarray(rng.integers(1, 500, (R, B)).astype(np.int32)),
        add_dc=jnp.zeros((R, B), jnp.int32),
        add_ts=jnp.asarray(np.broadcast_to(
            step * B + np.arange(B) + 1, (R, B)).astype(np.int32)),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
    )

rng = np.random.default_rng(7)
wal = ElasticWal(root, "victim", dense, "topk_rmv", partitions=P,
                 durability="sync")
state = dense.init(R, NK)
for s in range(4):
    prev = state
    state, _ = dense.apply_ops(state, gen_ops(s, rng), collect_dominated=False)
    wal.log_step(s, [0], prev, state)
ref = [int(x) for x in pt.state_digests(state, P)]
with open(os.path.join(root, "ref.json"), "w") as f:
    json.dump(ref, f)
    f.flush()
    os.fsync(f.fileno())

# Spill every demoted payload to disk under the WAL dir (the recovery
# sweep's search root), then stall inside a hydration so the parent's
# SIGKILL lands mid-page-in.
pager = pg.PartitionPager(dense, state, P=P, name="topk_rmv",
                          spill_dir=wal.dir, host_budget_bytes=1)
for p in (0, 2, 4):
    state = pager.demote(state, p)
assert pager._spilled, "expected disk spill files"
faults.install(
    {"pager.hydrate": [{"action": "delay", "at": [0], "delay_s": 120.0}]}
)
open(os.path.join(root, "hydrating"), "w").close()
state = pager.hydrate(state, 0)  # stalls 120s; SIGKILL arrives here
print("UNREACHABLE: hydration completed before the kill", file=sys.stderr)
sys.exit(3)
"""


def test_sigkill_mid_hydration_recovery_discards_spill(tmp_path):
    """Kill a worker inside a page-in (the `pager.hydrate` fault point
    stalls it there). Recovery must rebuild all-resident from the WAL
    and DISCARD the dead incarnation's spill blobs — never resurrect a
    possibly-torn resident copy."""
    from antidote_ccrdt_tpu.harness.wal import ElasticWal

    env = cpu_subprocess_env(
        CCRDT_DRILL_ROOT=str(tmp_path), PYTHONPATH=REPO
    )
    log = open(tmp_path / "child.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGKILL_CHILD],
        env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
    )
    try:
        marker = tmp_path / "hydrating"
        deadline = time.time() + 180
        while time.time() < deadline and not marker.exists():
            assert proc.poll() is None, (
                f"child died before hydrating:\n{(tmp_path / 'child.log').read_text()[-3000:]}"
            )
            time.sleep(0.05)
        assert marker.exists(), "child never reached the hydration stall"
        time.sleep(0.3)  # let it enter the injected delay
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        log.close()
        if proc.poll() is None:
            proc.kill()

    wal_dir = tmp_path / "wal-victim"
    spills = [
        f for f in os.listdir(wal_dir) if f.startswith(pg.SPILL_PREFIX)
    ]
    assert spills, "the killed incarnation should have left spill blobs"
    # Simulate a tear on one blob for good measure: recovery must not
    # even look at the content.
    with open(wal_dir / spills[0], "ab") as f:
        f.write(b"\x00garbage")

    ref = json.loads((tmp_path / "ref.json").read_text())
    dense = DENSE
    wal2 = ElasticWal(str(tmp_path), "victim", dense, "topk_rmv", partitions=P)
    recovered, last_step, _owned = wal2.recover(dense.init(R, NK))
    wal2.close()
    assert last_step == 3
    assert wal2.metrics.counters.get("pager.spills_discarded", 0) >= len(spills)
    assert not [
        f for f in os.listdir(wal_dir) if f.startswith(pg.SPILL_PREFIX)
    ]
    assert [int(x) for x in pt.state_digests(recovered, P)] == ref


# --- hypothesis: arbitrary interleavings ------------------------------------

_BASE = None


def _base():
    global _BASE
    if _BASE is None:
        state = seeded_state(seed=3)
        _BASE = (state, pt.state_digests(state, P))
    return _BASE


@settings(
    max_examples=12, deadline=None,
    suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large],
)
@given(st.lists(st.tuples(st.booleans(), st.integers(0, P - 1)), max_size=16))
def test_property_interleavings_preserve_digests(script):
    """Any demote/hydrate interleaving (including no-op repeats) leaves
    the logical digest vector untouched at every step, and the final
    full_state reassembles bit-identically."""
    base, ref_dig = _base()
    pager = pg.PartitionPager(DENSE, base, P=P, name="topk_rmv")
    state = base
    for is_demote, p in script:
        state = pager.demote(state, p) if is_demote else pager.hydrate(state, p)
        assert np.array_equal(pager.digest_vector(state), ref_dig)
    assert leaves_equal(pager.full_state(state), base)
