"""Metrics/observability layer: counters, latency percentiles, harness
integration, and profiler-hook smoke tests."""

import time

from antidote_ccrdt_tpu.harness.opgen import Workload, prepare_stream
from antidote_ccrdt_tpu.harness.replay import ScalarReplay
from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
from antidote_ccrdt_tpu.utils.metrics import Metrics, device_trace


def test_counters_and_timers():
    m = Metrics()
    m.count("x")
    m.count("x", 4)
    with m.timer("op"):
        time.sleep(0.005)
    with m.timer("op"):
        pass
    s = m.summary()
    assert s["x"] == 5
    assert s["op"]["n"] == 2
    assert s["op"]["p50_ms"] >= 0
    assert s["op"]["p99_ms"] >= s["op"]["p50_ms"]
    assert m.rate("x", "op") > 0


def test_empty_metrics_summary():
    m = Metrics()
    assert m.summary() == {}
    assert m.rate("missing") >= 0  # wall-clock denominator, no crash
    assert m.rate("x", "never-recorded") == 0


def test_replay_records_metrics():
    wl = Workload(n_replicas=3, n_ids=10, rmv_frac=0.2, seed=1)
    rp = ScalarReplay(TopkRmvScalar(), 3, new_args=(4,))
    rp.run(prepare_stream(wl, 50))
    s = rp.metrics.summary()
    assert s["syncs"] == 1
    assert s["merges"] > 0
    assert s["sync"]["n"] == 1
    assert rp.metrics.rate("merges", "sync") > 0


def test_device_trace_is_cheap_noop_without_capture():
    import jax.numpy as jnp

    with device_trace("annotated-region"):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_dense_replay_drop_reporting():
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.harness.dense_replay import DenseReplay
    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense

    D = make_dense(n_ids=4, n_dcs=2, size=2, slots_per_id=2)
    rp = DenseReplay(D, n_replicas=1, n_keys=1)
    ops = TopkRmvOps(
        add_key=jnp.asarray([[0, 0, 0]], jnp.int32),
        add_id=jnp.asarray([[1, 9, 2]], jnp.int32),   # 9 out of range
        add_score=jnp.asarray([[5, 5, 5]], jnp.int32),
        add_dc=jnp.asarray([[0, 0, 0]], jnp.int32),
        add_ts=jnp.asarray([[1, 2, 0]], jnp.int32),   # last = padding
        rmv_key=jnp.asarray([[0]], jnp.int32),
        rmv_id=jnp.asarray([[-1]], jnp.int32),        # padding
        rmv_vc=jnp.zeros((1, 1, 2), jnp.int32),
    )
    rp.apply(ops, report_drops=True)
    assert rp.metrics.counters["ops_dropped_out_of_range"] == 1
    assert rp.metrics.counters["ops_padding"] == 2
