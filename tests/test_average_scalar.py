"""Tests for scalar average, ported from antidote_ccrdt_average.erl:144-189."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.average import AverageScalar

A = AverageScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_new():
    assert A.new() == (0, 0)
    assert A.new(4, 5) == (4, 5)


def test_value():
    assert A.value((4, 5)) == 4 / 5
    # Deliberate fix of quirk #2: the reference divides by zero on a fresh
    # state (average.erl:69-70); we define value(new()) = 0.0.
    assert A.value(A.new()) == 0.0


def test_update_add():
    st = A.new()
    st, _ = A.update(("add", 1), st)
    st, _ = A.update(("add", 2), st)
    st, _ = A.update(("add", 1), st)
    assert A.value(st) == 4 / 3


def test_update_add_parameters():
    st, _ = A.update(("add", (7, 2)), A.new())
    assert A.value(st) == 7 / 2


def test_update_negative_params():
    st, _ = A.update(("add", -7), A.new())
    st, _ = A.update(("add", (-5, 5)), st)
    assert A.value(st) == -12 / 6


def test_zero_count_noop():
    st = (5, 2)
    st2, _ = A.update(("add", (100, 0)), st)
    assert st2 == st


def test_downstream():
    assert A.downstream(("add", 3), A.new(), CTX) == ("add", (3, 1))
    assert A.downstream(("add", (3, 4)), A.new(), CTX) == ("add", (3, 4))
    assert not A.require_state_downstream(("add", 3))


def test_equal():
    assert not A.equal((4, 1), (4, 2))
    assert A.equal((4, 2), (4, 2))


def test_binary_roundtrip():
    st = (4, 1)
    assert A.from_binary(A.to_binary(st)) == st


def test_compaction():
    assert A.can_compact(("add", (1, 1)), ("add", (2, 3)))
    dead, merged = A.compact_ops(("add", (1, 1)), ("add", (2, 3)))
    assert dead is None
    assert merged == ("add", (3, 4))


def test_is_operation():
    assert A.is_operation(("add", 1))
    assert A.is_operation(("add", (1, 2)))
    assert not A.is_operation(("sub", 1))
    assert not A.is_operation(("add", "x"))
