"""Test rig: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against XLA's host-platform device-count override instead (the same compiled
programs run unchanged on a real TPU mesh). Must run before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
