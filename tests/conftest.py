"""Test rig: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests
run against 8 virtual CPU devices instead (the same compiled programs run
unchanged on a real TPU mesh).

Note: this environment's axon TPU plugin force-selects ``jax_platforms=
"axon,cpu"`` from sitecustomize, overriding the JAX_PLATFORMS env var —
so the platform override must go through jax.config, before any backend
initialization (conftest imports early enough).

The virtual device COUNT needs two paths: newer JAX has the
``jax_num_cpu_devices`` config option; older JAX (e.g. 0.4.37) only
honors ``XLA_FLAGS=--xla_force_host_platform_device_count=N``, which must
be in the environment before ``import jax`` triggers backend setup —
hence the env mutation above the import.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402  (after XLA_FLAGS on purpose, see docstring)

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older JAX: the XLA_FLAGS fallback above already took effect


# --- hypothesis shim --------------------------------------------------------
# The property tests use hypothesis when the image ships it; images without
# it (no egress to install) must still COLLECT every module — a bare
# module-level `from hypothesis import ...` turns one missing dependency
# into a whole-file collection error, losing all the non-property tests in
# the file. Test modules import the names from here instead; when
# hypothesis is absent, @given marks the test skipped and the strategy /
# settings objects become inert stand-ins.
try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
except ModuleNotFoundError:
    import pytest as _pytest

    class HealthCheck:  # attribute targets for suppress_health_check=[...]
        too_slow = data_too_large = filter_too_much = None

    def settings(*_a, **_k):
        return lambda f: f

    def given(*_a, **_k):
        # Must skip at CALL time, not via a mark: property bodies are
        # often inner functions invoked directly by the test (`prop()`),
        # where a skip mark would never be seen by the collector.
        def deco(f):
            # No functools.wraps: it would forward f's signature and make
            # pytest hunt for the strategy kwargs as fixtures.
            def skipper(*a, **k):
                _pytest.skip("hypothesis not installed")

            skipper.__name__ = getattr(f, "__name__", "property")
            return skipper

        return deco

    class _AnyStrategy:
        """Inert strategy stand-in: modules build strategies at import
        time (`st.lists(...).map(...)`, `.filter(...)`), so the stub
        must absorb any call/attribute chain — the value never
        materializes, @given already skipped the test."""

        def __call__(self, *_a, **_k):
            return self

        def __getattr__(self, _name):
            return self

    st = _AnyStrategy()

__all__ = [
    "HealthCheck",
    "given",
    "settings",
    "st",
    "cpu_subprocess_env",
    "cpu_mesh_subprocess_env",
]


def cpu_subprocess_env(**extra):
    """Env for a subprocess that must REALLY run on the CPU backend.

    The jax.config workaround above cannot reach a subprocess, and the
    axon sitecustomize (PYTHONPATH-injected, triggered by
    PALLAS_AXON_POOL_IPS) force-registers the TPU platform and ignores
    JAX_PLATFORMS — strip the trigger so the child is hermetic (no
    dependency on the tunnel being up)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def cpu_mesh_subprocess_env(n: int = 8, **extra):
    """Env for a subprocess that needs a FORCED n-device CPU mesh.

    Same hermetic CPU isolation as `cpu_subprocess_env`, but instead of
    stripping XLA_FLAGS it pins exactly the virtual-device-count flag —
    any other inherited XLA flags are dropped so the child's backend
    state matches this test process's (which got its 8 devices from the
    module-top env mutation above), not whatever wrapper launched
    pytest. Mesh drills that fork workers (multichip demo, chaos leg 8)
    build their worker envs through this."""
    env = cpu_subprocess_env(**extra)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={int(n)}"
    return env
