"""Test rig: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests
run against 8 virtual CPU devices instead (the same compiled programs run
unchanged on a real TPU mesh).

Note: this environment's axon TPU plugin force-selects ``jax_platforms=
"axon,cpu"`` from sitecustomize, overriding JAX_PLATFORMS/XLA_FLAGS env
vars — so the override must go through jax.config, before any backend
initialization (conftest imports early enough).
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
