"""Test rig: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests
run against 8 virtual CPU devices instead (the same compiled programs run
unchanged on a real TPU mesh).

Note: this environment's axon TPU plugin force-selects ``jax_platforms=
"axon,cpu"`` from sitecustomize, overriding JAX_PLATFORMS/XLA_FLAGS env
vars — so the override must go through jax.config, before any backend
initialization (conftest imports early enough).
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


def cpu_subprocess_env(**extra):
    """Env for a subprocess that must REALLY run on the CPU backend.

    The jax.config workaround above cannot reach a subprocess, and the
    axon sitecustomize (PYTHONPATH-injected, triggered by
    PALLAS_AXON_POOL_IPS) force-registers the TPU platform and ignores
    JAX_PLATFORMS — strip the trigger so the child is hermetic (no
    dependency on the tunnel being up)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env
