"""Golden tests for scalar leaderboard, ported from the reference EUnit
suite (antidote_ccrdt_leaderboard.erl:316-655)."""

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models.leaderboard import (
    NIL,
    LeaderboardScalar,
    LeaderboardState,
    _cmp,
    _largest,
    _min_pair,
)

L = LeaderboardScalar()
CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


def test_create():
    assert L.new() == LeaderboardState({}, {}, frozenset(), NIL, 100)
    assert L.new(100) == L.new()


def test_cmp():
    """Port of cmp_test (leaderboard.erl:326-334)."""
    assert not _cmp(NIL, NIL)
    assert not _cmp(NIL, (1, 2))
    assert _cmp((1, 2), NIL)
    assert not _cmp((1, 2), (1, 2))
    assert not _cmp((1, 2), (1, 3))
    assert not _cmp((1, 2), (2, 2))
    assert _cmp((1, 3), (1, 2))
    assert _cmp((2, 2), (1, 2))


def test_mixed():
    """Port of mixed_test (leaderboard.erl:339-417)."""
    size = 2
    lb = L.new(size)

    assert L.downstream(("add", (1, 2)), lb, CTX) == ("add", (1, 2))
    lb1, _ = L.update(("add", (1, 2)), lb)
    assert lb1 == LeaderboardState({1: 2}, {}, frozenset(), (1, 2), size)

    assert L.downstream(("add", (2, 2)), lb1, CTX) == ("add", (2, 2))
    lb2, _ = L.update(("add", (2, 2)), lb1)
    assert lb2 == LeaderboardState({1: 2, 2: 2}, {}, frozenset(), (1, 2), size)

    # dominated add -> noop
    assert L.downstream(("add", (1, 0)), lb2, CTX) is None

    # ban of an unseen player
    assert L.downstream(("ban", 42), lb2, CTX) == ("ban", 42)
    lb4, extras = L.update(("ban", 42), lb2)
    assert extras == []
    assert lb4 == LeaderboardState({1: 2, 2: 2}, {}, frozenset([42]), (1, 2), size)

    # full board, score below min -> tagged add
    assert L.downstream(("add", (100, 1)), lb4, CTX) == ("add_r", (100, 1))
    lb5, _ = L.update(("add_r", (100, 1)), lb4)
    assert lb5 == LeaderboardState(
        {1: 2, 2: 2}, {100: 1}, frozenset([42]), (1, 2), size
    )

    # ban of an observed player promotes the largest masked and emits an
    # extra add (leaderboard.erl:279-283)
    assert L.downstream(("ban", 2), lb5, CTX) == ("ban", 2)
    lb6, extras = L.update(("ban", 2), lb5)
    assert extras == [("add", (100, 1))]
    assert lb6 == LeaderboardState(
        {1: 2, 100: 1}, {}, frozenset([42, 2]), (100, 1), size
    )

    # adds/bans of banned players are noops at the origin
    assert L.downstream(("add", (42, 50)), lb6, CTX) is None
    assert L.downstream(("ban", 42), lb6, CTX) is None


def test_ban_after_add():
    """Port of ban_after_add_test (leaderboard.erl:420-447)."""
    lb = L.new(2)
    lb1, _ = L.update(("add", (1, 2)), lb)
    assert lb1 == LeaderboardState({1: 2}, {}, frozenset(), (1, 2), 2)
    lb2, extras = L.update(("ban", 1), lb1)
    assert extras == []
    assert lb2 == LeaderboardState({}, {}, frozenset([1]), NIL, 2)


def test_ban_min_no_replacement():
    """Port of ban_test (leaderboard.erl:450-491)."""
    lb = L.new(2)
    lb1, _ = L.update(("add", (1, 2)), lb)
    lb2, _ = L.update(("add", (2, 1)), lb1)
    assert lb2 == LeaderboardState({1: 2, 2: 1}, {}, frozenset(), (2, 1), 2)
    lb3, extras = L.update(("ban", 1), lb2)
    assert extras == []
    assert lb3 == LeaderboardState({2: 1}, {}, frozenset([1]), (2, 1), 2)


def test_add_after_ban():
    """Port of add_after_ban_test (leaderboard.erl:494-499)."""
    lb = L.new()
    lb2, _ = L.update(("ban", 5), lb)
    lb3, _ = L.update(("add", (5, 30)), lb2)
    assert lb2 == lb3


def test_noop_adds():
    """Port of noop_add_test (leaderboard.erl:503-513)."""
    lb = L.new(1)
    lb2, _ = L.update(("add", (5, 10)), lb)
    lb3, _ = L.update(("add", (5, 5)), lb2)
    assert lb3 == lb2
    lb4, _ = L.update(("add", (10, 9)), lb3)
    lb5, _ = L.update(("add", (10, 6)), lb4)
    assert lb4 == lb5


def test_ban_min_with_replacement():
    """Port of ban_min_with_replacement_test (leaderboard.erl:516-572)."""
    lb = L.new(2)
    lb1, _ = L.update(("add", (1, 2)), lb)
    lb2, _ = L.update(("add", (2, 1)), lb1)
    # add(3, 100): full board, beats min -> min (2,1) demoted to masked
    assert L.downstream(("add", (3, 100)), lb2, CTX) == ("add", (3, 100))
    lb3, _ = L.update(("add", (3, 100)), lb2)
    assert lb3 == LeaderboardState(
        {3: 100, 1: 2}, {2: 1}, frozenset(), (1, 2), 2
    )
    lb4, extras = L.update(("ban", 1), lb3)
    assert extras == [("add", (2, 1))]
    assert lb4 == LeaderboardState(
        {3: 100, 2: 1}, {}, frozenset([1]), (2, 1), 2
    )


def test_add_several():
    """Port of add_several_test (leaderboard.erl:575-627)."""
    lb1 = L.new(2)
    lb2, _ = L.update(("add", (5, 50)), lb1)
    assert lb2 == LeaderboardState({5: 50}, {}, frozenset(), (5, 50), 2)
    assert L.downstream(("add", (6, 60)), lb2, CTX) == ("add", (6, 60))
    lb3, _ = L.update(("add", (6, 60)), lb2)
    assert lb3 == LeaderboardState({5: 50, 6: 60}, {}, frozenset(), (5, 50), 2)
    assert L.downstream(("add", (3, 30)), lb3, CTX) == ("add_r", (3, 30))
    lb4, _ = L.update(("add_r", (3, 30)), lb3)
    assert lb4 == LeaderboardState({5: 50, 6: 60}, {3: 30}, frozenset(), (5, 50), 2)
    assert L.downstream(("add", (5, 100)), lb4, CTX) == ("add", (5, 100))
    lb5, _ = L.update(("add", (5, 100)), lb4)
    assert lb5 == LeaderboardState({5: 100, 6: 60}, {3: 30}, frozenset(), (6, 60), 2)
    assert L.downstream(("add", (3, 40)), lb5, CTX) == ("add_r", (3, 40))
    lb6, _ = L.update(("add_r", (3, 40)), lb5)
    assert lb6 == LeaderboardState({5: 100, 6: 60}, {3: 40}, frozenset(), (6, 60), 2)
    assert L.downstream(("add", (3, 10)), lb6, CTX) is None


def test_value():
    """Port of value_test (leaderboard.erl:630-636)."""
    lb = L.new()
    assert L.value(lb) == []
    lb2, _ = L.update(("add", (50, 5)), lb)
    assert L.value(lb2) == [(50, 5)]
    lb3, _ = L.update(("add", (45, 6)), lb2)
    assert L.value(lb3) == [(45, 6), (50, 5)]


def test_min_and_largest():
    """Ports of min_test / largest_test (leaderboard.erl:639-648)."""
    assert _min_pair({}) == NIL
    assert _min_pair({1: 1}) == (1, 1)
    assert _min_pair({1: 1, 2: 5}) == (1, 1)
    assert _largest({}) == NIL
    assert _largest({1: 1}) == (1, 1)
    assert _largest({1: 1, 2: 5}) == (2, 5)


def test_binary_roundtrip():
    """Port of binary_test (leaderboard.erl:651-655)."""
    lb = L.new()
    lb2, _ = L.update(("add", (1, 10)), lb)
    lb3, _ = L.update(("ban", 9), lb2)
    restored = L.from_binary(L.to_binary(lb3))
    assert L.equal(lb3, restored)
    assert restored == lb3


def test_compaction():
    a1, a2 = ("add", (1, 10)), ("add_r", (1, 20))
    assert L.can_compact(a1, a2)
    assert L.compact_ops(a1, a2) == (None, a2)
    assert L.compact_ops(a2, a1) == (a2, None)
    assert not L.can_compact(a1, ("add", (2, 5)))
    b = ("ban", 1)
    assert L.can_compact(a1, b)
    assert L.compact_ops(a1, b) == (None, b)
    assert L.can_compact(b, b)
    assert L.compact_ops(b, b) == (None, b)
    assert not L.can_compact(("ban", 1), ("ban", 2))
