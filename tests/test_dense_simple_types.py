"""Dense leaderboard / topk / wordcount kernels: differential tests against
the scalar (reference-semantics) implementations."""

import numpy as np

import jax.numpy as jnp

from antidote_ccrdt_tpu.core.clock import LogicalClock, ReplicaContext
from antidote_ccrdt_tpu.models import leaderboard as lb
from antidote_ccrdt_tpu.models import topk as tk
from antidote_ccrdt_tpu.models import wordcount as wc

CTX = ReplicaContext(dc_id=0, clock=LogicalClock())


# --- leaderboard ----------------------------------------------------------

def lb_pack(effects, pad=64):
    adds = [e[1] for e in effects if e[0] in ("add", "add_r")]
    bans = [e[1] for e in effects if e[0] == "ban"]
    B, Bb = max(pad, len(adds)), max(8, len(bans))
    a_id = np.zeros(B, np.int32)
    a_sc = np.zeros(B, np.int32)
    a_v = np.zeros(B, bool)
    for j, (i, s) in enumerate(adds):
        a_id[j], a_sc[j], a_v[j] = i, s, True
    b_id = np.zeros(Bb, np.int32)
    b_v = np.zeros(Bb, bool)
    for j, i in enumerate(bans):
        b_id[j], b_v[j] = i, True
    z = np.zeros_like
    return lb.LeaderboardOps(
        add_key=jnp.asarray(z(a_id)[None]),
        add_id=jnp.asarray(a_id[None]),
        add_score=jnp.asarray(a_sc[None]),
        add_valid=jnp.asarray(a_v[None]),
        ban_key=jnp.asarray(z(b_id)[None]),
        ban_id=jnp.asarray(b_id[None]),
        ban_valid=jnp.asarray(b_v[None]),
    )


def gen_lb_log(rng, n_ops, n_players, size, ban_frac=0.1):
    S = lb.LeaderboardScalar()
    origin = S.new(size)
    log = []
    for _ in range(n_ops):
        if rng.random() < ban_frac:
            op = ("ban", int(rng.integers(n_players)))
        else:
            op = ("add", (int(rng.integers(n_players)), int(rng.integers(1, 500))))
        eff = S.downstream(op, origin, CTX)
        if eff is None:
            continue
        origin, extras = S.update(eff, origin)
        log.append(eff)
        # extras (promotions) would re-ship; locally already applied
    return origin, log


def test_leaderboard_differential():
    S = lb.LeaderboardScalar()
    rng = np.random.default_rng(2)
    for trial in range(5):
        n_players, size = 40, 5
        origin, log = gen_lb_log(rng, 150, n_players, size)
        D = lb.make_dense(n_players=n_players, size=size)
        st = D.init(1, 1)
        st, _ = D.apply_ops(st, lb_pack(log, pad=256))
        assert set(D.value(st)[0][0]) == set(S.value(origin)), f"trial {trial}"


def test_leaderboard_ban_wins_any_order():
    D = lb.make_dense(n_players=8, size=2)
    a = D.init(1, 1)
    b = D.init(1, 1)
    add = [("add", (3, 50))]
    ban = [("ban", 3)]
    a, _ = D.apply_ops(a, lb_pack(add))
    a, _ = D.apply_ops(a, lb_pack(ban))
    b, _ = D.apply_ops(b, lb_pack(ban))
    b, _ = D.apply_ops(b, lb_pack(add))
    assert D.equal(a, b)
    assert D.value(a)[0][0] == []


def test_leaderboard_merge_laws():
    rng = np.random.default_rng(9)
    D = lb.make_dense(n_players=20, size=4)

    def rand_state(seed):
        r = np.random.default_rng(seed)
        _, log = gen_lb_log(r, 60, 20, 4)
        st = D.init(1, 1)
        st, _ = D.apply_ops(st, lb_pack(log, pad=128))
        return st

    a, b, c = rand_state(1), rand_state(2), rand_state(3)
    assert D.equal(D.merge(a, b), D.merge(b, a))
    assert D.equal(D.merge(D.merge(a, b), c), D.merge(a, D.merge(b, c)))
    assert D.equal(D.merge(a, a), a)


def test_leaderboard_promotion_collected():
    """Dense analogue of ban_min_with_replacement_test (leaderboard.erl:
    516-572): banning an observed player uncovers the masked one."""
    D = lb.make_dense(n_players=8, size=2)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, lb_pack([("add", (1, 2)), ("add", (2, 1)), ("add", (3, 100))]))
    assert set(D.value(st)[0][0]) == {(3, 100), (1, 2)}
    st, promoted = D.apply_ops(st, lb_pack([("ban", 1)]), collect_promotions=True)
    assert set(D.value(st)[0][0]) == {(3, 100), (2, 1)}
    ids, scores, valid = promoted
    got = [
        (int(ids[0, 0, j]), int(scores[0, 0, j]))
        for j in range(ids.shape[-1])
        if bool(valid[0, 0, j])
    ]
    assert got == [(2, 1)]


def test_leaderboard_promotion_not_suppressed_cross_instance():
    """Regression: an add to one instance must not mask a same-(id,score)
    promotion in another instance (promotion matching is key-aware)."""
    import jax.numpy as jnp

    D = lb.make_dense(n_players=8, size=2)
    st = D.init(1, 2)
    # instance 1: full board {1:100, 2:50} with masked 3:10
    setup = lb.LeaderboardOps(
        add_key=jnp.asarray([[1, 1, 1]], jnp.int32),
        add_id=jnp.asarray([[1, 2, 3]], jnp.int32),
        add_score=jnp.asarray([[100, 50, 10]], jnp.int32),
        add_valid=jnp.asarray([[True, True, True]]),
        ban_key=jnp.zeros((1, 1), jnp.int32),
        ban_id=jnp.zeros((1, 1), jnp.int32),
        ban_valid=jnp.asarray([[False]]),
    )
    st, _ = D.apply_ops(st, setup)
    # One batch: ban id=1 in instance 1 AND add (3, 10) to instance 0.
    batch = lb.LeaderboardOps(
        add_key=jnp.asarray([[0]], jnp.int32),
        add_id=jnp.asarray([[3]], jnp.int32),
        add_score=jnp.asarray([[10]], jnp.int32),
        add_valid=jnp.asarray([[True]]),
        ban_key=jnp.asarray([[1]], jnp.int32),
        ban_id=jnp.asarray([[1]], jnp.int32),
        ban_valid=jnp.asarray([[True]]),
    )
    st, promoted = D.apply_ops(st, batch, collect_promotions=True)
    ids, scores, valid = promoted
    got_inst1 = [
        (int(ids[0, 1, j]), int(scores[0, 1, j]))
        for j in range(ids.shape[-1])
        if bool(valid[0, 1, j])
    ]
    assert got_inst1 == [(3, 10)]


# --- topk -----------------------------------------------------------------

def tk_pack(items, pad=64):
    B = max(pad, len(items))
    i_ = np.zeros(B, np.int32)
    s_ = np.zeros(B, np.int32)
    v_ = np.zeros(B, bool)
    for j, (i, s) in enumerate(items):
        i_[j], s_[j], v_[j] = i, s, True
    return tk.TopkOps(
        key=jnp.asarray(np.zeros_like(i_)[None]),
        id=jnp.asarray(i_[None]),
        score=jnp.asarray(s_[None]),
        valid=jnp.asarray(v_[None]),
    )


def test_topk_differential():
    S = tk.TopkScalar()
    rng = np.random.default_rng(4)
    for trial in range(5):
        n_ids, size = 30, 4
        scalar = S.new(size)
        items = []
        for _ in range(100):
            op = ("add", (int(rng.integers(n_ids)), int(rng.integers(1, 300))))
            eff = S.downstream(op, scalar, CTX)
            if eff is None:
                continue
            scalar, _ = S.update(eff, scalar)
            items.append(eff[1])
        D = tk.make_dense(n_ids=n_ids, size=size)
        st = D.init(1, 1)
        st, _ = D.apply_ops(st, tk_pack(items, pad=128))
        assert set(D.value(st)[0][0]) == set(
            (i, s) for i, s in S.value(scalar)
        ), f"trial {trial}"


def test_topk_merge_is_join():
    D = tk.make_dense(n_ids=10, size=3)
    a = D.init(1, 1)
    a, _ = D.apply_ops(a, tk_pack([(1, 10), (2, 20)]))
    b = D.init(1, 1)
    b, _ = D.apply_ops(b, tk_pack([(1, 15), (3, 5)]))
    m = D.merge(a, b)
    assert set(D.value(m)[0][0]) == {(1, 15), (2, 20), (3, 5)}
    assert D.equal(D.merge(m, a), m)  # idempotent absorption


# --- wordcount ------------------------------------------------------------

def wc_pack(token_ids, pad=256):
    B = max(pad, len(token_ids))
    t = np.full(B, -1, np.int32)
    t[: len(token_ids)] = token_ids
    return wc.WordcountOps(
        key=jnp.asarray(np.zeros(B, np.int32)[None]), token=jnp.asarray(t[None])
    )


def test_wordcount_differential():
    S = wc.WordcountScalar()
    enc = wc.VocabEncoder()
    docs = ["foo bar baz baz", "foo  bar", "a\nb a", ""]
    scalar = S.new()
    tokens = []
    for d in docs:
        scalar, _ = S.update(("add", d), scalar)
        tokens.extend(enc.encode(d))
    D = wc.make_dense(n_buckets=64)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, wc_pack(tokens))
    counts = np.asarray(st.counts[0, 0])
    assert enc.decode_counts(counts) == S.value(scalar)


def test_worddocumentcount_differential():
    S = wc.WordDocumentCountScalar()
    enc = wc.VocabEncoder()
    docs = ["foo bar baz baz", "foo bar baz baz hello"]
    scalar = S.new()
    tokens = []
    for d in docs:
        scalar, _ = S.update(("add", d), scalar)
        tokens.extend(enc.encode(d, per_document=True))
    D = wc.make_dense(n_buckets=64)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, wc_pack(tokens))
    assert enc.decode_counts(np.asarray(st.counts[0, 0])) == S.value(scalar)


def test_wordcount_monoid_merge():
    """Per-replica deltas combine exactly once across replicas."""
    enc = wc.VocabEncoder()
    D = wc.make_dense(n_buckets=32)
    a = D.init(1, 1)
    a, _ = D.apply_ops(a, wc_pack(enc.encode("x y")))
    b = D.init(1, 1)
    b, _ = D.apply_ops(b, wc_pack(enc.encode("y z")))
    m = D.merge(a, b)
    assert enc.decode_counts(np.asarray(m.counts[0, 0])) == {"x": 1, "y": 2, "z": 1}


def test_wordcount_overflow_tracked():
    """Token ids beyond the table must be counted as lost, not silently
    dropped (regression)."""
    D = wc.make_dense(n_buckets=4)
    st = D.init(1, 1)
    st, _ = D.apply_ops(st, wc_pack([0, 1, 4, 5, 2], pad=8))
    assert st.counts[0, 0].tolist() == [1, 1, 1, 0]
    assert int(st.lost[0, 0]) == 2
    m = D.merge(st, st)
    assert int(m.lost[0, 0]) == 4


def test_hash_token_stable():
    assert wc.hash_token("hello", 1024) == wc.hash_token("hello", 1024)
    assert 0 <= wc.hash_token("hello", 1024) < 1024
    # distinct under a reasonable bucket count for these tokens
    assert wc.hash_token("hello", 1 << 20) != wc.hash_token("world", 1 << 20)
