"""Live-scrape surfaces under fault injection: the in-band `{metrics}`
bridge op and the gossip-TCP `{metrics_req}` frame must DEGRADE to an
error the scraper sees within its own timeout — never hang, never
corrupt the registry they were reading."""

import pytest

from antidote_ccrdt_tpu.net.tcp import TcpTransport, scrape_metrics
from antidote_ccrdt_tpu.utils import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    faults.uninstall()
    yield
    faults.uninstall()


# -- gossip TCP ({metrics_req} frame) ---------------------------------------


def test_tcp_inband_scrape_happy_path():
    t = TcpTransport("w0")
    try:
        t.metrics.count("net.frames_sent", 5)
        member, text = scrape_metrics(t.address, timeout=5.0)
        assert member == "w0"
        lines = text.splitlines()
        assert 'ccrdt_net_frames_sent{member="w0"} 5' in lines
        assert t.metrics.counters["net.scrapes"] == 1
        # Scraping is not membership traffic: no ghost member appeared.
        assert "?" not in t.membership.heard_ages()
    finally:
        t.close()


def test_tcp_scrape_under_send_drop_degrades_then_recovers():
    t = TcpTransport("w0")
    try:
        t.metrics.count("net.frames_sent", 5)
        with faults.injected(
            {"tcp.send": [{"action": "drop", "at": [0]}]}
        ):
            # The reply frame is dropped and the connection closed: the
            # scraper gets a bounded error, not a hang.
            with pytest.raises((OSError, ValueError)):
                scrape_metrics(t.address, timeout=2.0)
        # Registry intact, transport still serving: the next scrape
        # succeeds and reflects the failed attempt's counters.
        member, text = scrape_metrics(t.address, timeout=5.0)
        assert member == "w0"
        assert 'ccrdt_net_frames_sent{member="w0"} 5' in text.splitlines()
        assert t.metrics.counters["net.fault_drops"] >= 1
        assert t.metrics.counters["net.scrapes"] == 2
    finally:
        t.close()


def test_tcp_scrape_under_send_raise_degrades_then_recovers():
    t = TcpTransport("w0")
    try:
        with faults.injected(
            {"tcp.send": [{"action": "raise", "at": [0],
                           "message": "connection reset"}]}
        ):
            with pytest.raises((OSError, ValueError)):
                scrape_metrics(t.address, timeout=2.0)
        member, _text = scrape_metrics(t.address, timeout=5.0)
        assert member == "w0"
    finally:
        t.close()


# -- bridge ({metrics} op) ---------------------------------------------------


def test_bridge_metrics_op_happy_path():
    from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer

    with BridgeServer() as srv:
        with BridgeClient(*srv.address, timeout=10.0) as c:
            c.new("average")
            text = c.metrics_text()
            lines = text.splitlines()
            assert "ccrdt_bridge_scrapes 1" in lines
            # Second scrape sees the first one counted: live registry.
            assert "ccrdt_bridge_scrapes 2" in c.metrics_text().splitlines()


def test_bridge_scrape_under_read_fault_degrades_then_recovers():
    from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer

    with BridgeServer() as srv:
        c = BridgeClient(*srv.address, timeout=5.0)  # retries=0: poisons
        try:
            with faults.injected(
                {"bridge.read": [{"action": "raise", "at": [0],
                                  "message": "connection reset"}]}
            ):
                with pytest.raises(Exception):
                    c.metrics_text()
        finally:
            c.close()
        # The failed scrape corrupted nothing server-side (the op ran;
        # only the client's read of the reply died): a fresh client
        # scrapes a healthy, still-consistent registry.
        with BridgeClient(*srv.address, timeout=10.0) as c2:
            h = c2.new("average")
            lines = c2.metrics_text().splitlines()
            scrapes = [
                int(ln.rsplit(" ", 1)[1])
                for ln in lines
                if ln.startswith("ccrdt_bridge_scrapes ")
            ]
            assert scrapes and scrapes[0] >= 2  # faulted scrape + this one
            assert c2.equal(h, h)  # data plane still works post-fault
