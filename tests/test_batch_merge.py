"""batch_merge ground truth: for op-based CRDT states, the join of states
that saw op sets A1..An must equal one state that saw A1 ∪ ... ∪ An
(delivered causally). Every type is checked against exactly that, with the
partial states built through the real downstream/update pipeline."""

import numpy as np
import pytest

from antidote_ccrdt_tpu.core.batch_merge import batch_merge
from antidote_ccrdt_tpu.core.behaviour import registry
from antidote_ccrdt_tpu.core.clock import make_contexts


def _apply_all(eng, state, effects):
    for eff in effects:
        state, extras = eng.update(eff, state)
        for e in extras:
            state, _ = eng.update(e, state)
    return state


def test_average():
    eng = registry.scalar("average")
    effects = [("add", (v, 1)) for v in (5, 10, -3, 8, 9)]
    parts = [
        _apply_all(eng, eng.new(), effects[i::3]) for i in range(3)
    ]
    merged = batch_merge("average", parts)
    assert merged == _apply_all(eng, eng.new(), effects)


@pytest.mark.parametrize("name", ["wordcount", "worddocumentcount"])
def test_wordcounts(name):
    eng = registry.scalar(name)
    docs = ["a b b c", "b d", "a a\nc d d", "", "x  y"]
    effects = [("add", d) for d in docs]
    parts = [_apply_all(eng, eng.new(), effects[i::2]) for i in range(2)]
    merged = batch_merge(name, parts)
    assert merged == _apply_all(eng, eng.new(), effects)


def test_topk():
    eng = registry.scalar("topk")
    rng = np.random.default_rng(0)
    effects = [
        ("add", (int(rng.integers(0, 40)), int(rng.integers(1, 1000))))
        for _ in range(200)
    ]
    parts = [_apply_all(eng, eng.new(8), effects[i::4]) for i in range(4)]
    merged = batch_merge("topk", parts)
    ref = _apply_all(eng, eng.new(8), effects)
    assert eng.equal(merged, ref)


def test_topk_size_mismatch_rejected():
    eng = registry.scalar("topk")
    with pytest.raises(ValueError):
        batch_merge("topk", [eng.new(4), eng.new(8)])


def test_leaderboard():
    eng = registry.scalar("leaderboard")
    rng = np.random.default_rng(1)
    effects = []
    for _ in range(150):
        effects.append(
            ("add", (int(rng.integers(0, 30)), int(rng.integers(1, 10_000))))
        )
    for pid in (3, 7, 11):
        effects.append(("ban", pid))
    parts = [_apply_all(eng, eng.new(5), effects[i::3]) for i in range(3)]
    merged = batch_merge("leaderboard", parts)
    ref = _apply_all(eng, eng.new(5), effects)
    # observable + bans must agree (masked layout may legally differ only
    # in players the sequential path evicted pre-ban; compare the lattice
    # content: observable, bans, and per-player best among non-banned)
    assert eng.value(merged) == eng.value(ref)
    assert merged.bans == ref.bans
    assert merged.min == ref.min


def test_topk_rmv():
    eng = registry.scalar("topk_rmv")
    n_dcs = 3
    ctxs = make_contexts(n_dcs)
    rng = np.random.default_rng(2)
    # Build effect streams through real downstream at rotating origins,
    # including removals (vc = origin's current knowledge: apply-as-we-go
    # on a staging state so removal vcs are causally meaningful).
    staging = eng.new(6)
    effects = []
    for step in range(120):
        origin = step % n_dcs
        if rng.random() < 0.15 and staging.observed:
            target = list(staging.observed)[int(rng.integers(0, len(staging.observed)))]
            eff = eng.downstream(("rmv", target), staging, ctxs[origin])
        else:
            eff = eng.downstream(
                ("add", (int(rng.integers(0, 25)), int(rng.integers(1, 5000)))),
                staging,
                ctxs[origin],
            )
        if eff is None:
            continue
        effects.append(eff)
        staging = _apply_all(eng, staging, [eff])
    parts = [_apply_all(eng, eng.new(6), effects[i::4]) for i in range(4)]
    merged = batch_merge("topk_rmv", parts)
    ref = _apply_all(eng, eng.new(6), effects)
    assert merged.masked == ref.masked
    assert merged.removals == ref.removals
    assert merged.vc == ref.vc
    assert merged.observed == ref.observed
    assert merged.min == ref.min


def test_accepts_binary_blobs():
    eng = registry.scalar("average")
    a = _apply_all(eng, eng.new(), [("add", (5, 1))])
    b = _apply_all(eng, eng.new(), [("add", (7, 2))])
    merged = batch_merge("average", [eng.to_binary(a), b])
    assert merged == (12, 3)


def test_single_state_identity():
    eng = registry.scalar("topk")
    st = _apply_all(eng, eng.new(4), [("add", (1, 10))])
    assert batch_merge("topk", [st]) is st


def test_empty_rejected():
    with pytest.raises(ValueError):
        batch_merge("topk", [])


def test_accepts_reference_etf_blobs():
    """Real Erlang term_to_binary snapshots (ETF, 0x83 magic) decode too —
    the README's 'live states or term_to_binary blobs' claim, Python path."""
    from antidote_ccrdt_tpu.core import wire

    eng = registry.scalar("topk")
    a = _apply_all(eng, eng.new(4), [("add", (1, 10))])
    b = _apply_all(eng, eng.new(4), [("add", (2, 20))])
    merged = batch_merge(
        "topk",
        [wire.to_reference_binary("topk", a), wire.to_reference_binary("topk", b)],
    )
    ref = _apply_all(eng, eng.new(4), [("add", (1, 10)), ("add", (2, 20))])
    assert eng.equal(merged, ref)
