"""The Erlang-side bridge client (bridge/erl/antidote_ccrdt_tpu.erl).

Three layers of proof that the BEAM host surface is real:

1. **Golden bytes, no OTP needed.** Every request the .erl module sends is
   `term_to_binary` of a plain tuple. A local `term_to_binary` stand-in
   (below, implementing the published ETF spec the way OTP emits it — both
   modern >=26 SMALL_ATOM_UTF8 and legacy ATOM_EXT atom encodings) vendors
   the exact frames; the test asserts `bridge/protocol.py` decodes them to
   the expected op terms, and that the repo's own canonical encoder
   produces byte-identical frames for the modern encoding.
2. **Raw-socket session.** The vendored literal bytes of a full session
   (new -> downstream -> update -> value -> to_binary/from_binary ->
   batch_merge -> free) drive a LIVE BridgeServer over a plain socket; the
   replies must decode to the expected results. No Python client code in
   the loop — exactly what a gen_tcp {packet,4} client experiences.
3. **Live escript** (gated on `escript` in PATH): runs the .erl module's
   main/1 smoke test against a live server.
"""

import os
import shutil
import socket
import struct
import subprocess
import sys

import pytest

from antidote_ccrdt_tpu.bridge import BridgeServer
from antidote_ccrdt_tpu.bridge import protocol as P
from antidote_ccrdt_tpu.core import etf
from antidote_ccrdt_tpu.core.etf import Atom

ERL_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "antidote_ccrdt_tpu", "bridge", "erl", "antidote_ccrdt_tpu.erl",
)


# --- a minimal term_to_binary stand-in (spec-faithful, OTP-style) ---------


def t2b(term, legacy_atoms=False):
    """term_to_binary for the protocol's term subset. `legacy_atoms=True`
    emits ATOM_EXT (OTP < 26 default); False emits SMALL_ATOM_UTF8_EXT
    (OTP >= 26)."""
    out = bytearray([131])
    _enc(term, out, legacy_atoms)
    return bytes(out)


def _enc(x, out, legacy):
    if isinstance(x, bool):
        _enc(Atom("true" if x else "false"), out, legacy)
    elif isinstance(x, Atom):
        b = str(x).encode("utf-8")
        if legacy:
            out += bytes([100]) + struct.pack(">H", len(b)) + b
        else:
            out += bytes([119, len(b)]) + b
    elif isinstance(x, int):
        if 0 <= x <= 255:
            out += bytes([97, x])
        else:
            out += bytes([98]) + struct.pack(">i", x)
    elif isinstance(x, bytes):
        out += bytes([109]) + struct.pack(">I", len(x)) + x
    elif isinstance(x, tuple):
        assert len(x) <= 255
        out += bytes([104, len(x)])
        for e in x:
            _enc(e, out, legacy)
    elif isinstance(x, list):
        if not x:
            out += bytes([106])
        elif all(
            isinstance(e, int) and not isinstance(e, bool) and 0 <= e <= 255
            for e in x
        ):
            # OTP encodes byte lists as STRING_EXT
            out += bytes([107]) + struct.pack(">H", len(x)) + bytes(x)
        else:
            out += bytes([108]) + struct.pack(">I", len(x))
            for e in x:
                _enc(e, out, legacy)
            out += bytes([106])
    elif isinstance(x, dict):
        out += bytes([116]) + struct.pack(">I", len(x))
        for k in sorted(x.keys(), key=etf._term_sort_key):
            _enc(k, out, legacy)
            _enc(x[k], out, legacy)
    else:  # pragma: no cover
        raise TypeError(f"cannot encode {type(x)!r}")


def frame(term, legacy_atoms=False):
    payload = t2b(term, legacy_atoms)
    return struct.pack(">I", len(payload)) + payload


# One representative request per protocol op, exactly as the .erl
# module's wrappers construct them.
A = Atom
REQUESTS = [
    (A("new"), A("average"), []),
    (A("new"), A("topk_rmv"), [2]),
    (A("from_binary"), A("average"), b"\x83h\x02a\x05a\x01"),
    (A("downstream"), 1, (A("add"), 5), (A("replica1"), 0), 1),
    (A("downstream"), 2, (A("add"), (1, 42)), (A("dc1"), 0), 1),
    (A("update"), 1, (A("add"), (5, 1))),
    (A("value"), 1),
    (A("to_binary"), 1),
    (A("equal"), 1, 2),
    (A("compact"), 1, [(A("add"), (5, 1)), (A("add"), (3, 1))]),
    (A("free"), 1),
    (A("batch_merge"), A("average"), [1, 2]),
    (A("is_type"), A("average")),
    (A("generates_extra_operations"), A("topk_rmv")),
    (A("is_operation"), A("average"), (A("add"), 5)),
    (A("require_state_downstream"), A("topk_rmv"), (A("add"), (1, 2))),
    (A("is_replicate_tagged"), A("topk_rmv"), (A("add_r"), (1, 2, (A("dc1"), 3)))),
    (A("grid_new"), A("g"), A("topk_rmv"),
     {A("n_replicas"): 2, A("n_keys"): 1, A("n_ids"): 64}),
    (A("grid_apply"), A("g"),
     [[(A("add"), 0, 1, 10, 0, 1)], [(A("rmv"), 0, 1, [(0, 1)])]]),
    # Round-3 widening: every registered dense type gets the grid surface;
    # one golden request per new op shape.
    (A("grid_new"), A("ga"), A("average"), {A("n_replicas"): 2}),
    (A("grid_apply"), A("ga"), [[(A("add"), 0, 10, 1)], []]),
    (A("grid_new"), A("gw"), A("wordcount"),
     {A("n_replicas"): 2, A("n_buckets"): 64}),
    (A("grid_apply"), A("gw"), [[(A("add"), 0, 3)], []]),
    (A("grid_apply"), A("gd"), [[(A("doc_add"), 0, 1, 7, 3)], []]),
    (A("grid_new"), A("gt"), A("topk"),
     {A("n_replicas"): 2, A("n_ids"): 64, A("size"): 4}),
    (A("grid_apply"), A("gt"), [[(A("add"), 0, 1, 10)], []]),
    (A("grid_new"), A("gl"), A("leaderboard"),
     {A("n_replicas"): 2, A("n_players"): 64, A("size"): 4}),
    (A("grid_apply"), A("gl"), [[(A("add"), 0, 1, 10)], [(A("ban"), 0, 1)]]),
    (A("grid_apply_extras"), A("g"), [[(A("add"), 0, 1, 10, 0, 1)], []]),
    (A("grid_merge_all"), A("g")),
    (A("grid_observe"), A("g"), 0, 0),
    (A("grid_to_binary"), A("g")),
    (A("grid_from_binary"), A("g"), b"\x83h\x02t\x00\x00\x00\x00m\x00\x00\x00\x00"),
]


@pytest.mark.parametrize("legacy", [False, True], ids=["otp26+", "otp<26"])
@pytest.mark.parametrize("op", REQUESTS, ids=lambda op: str(op[0]))
def test_vendored_request_bytes_decode(op, legacy):
    req = (A("call"), 7, op)
    buf = bytearray(frame(req, legacy_atoms=legacy))
    terms = list(P.unpack_frames(buf))
    assert terms == [req]
    assert not buf  # frame fully consumed


@pytest.mark.parametrize("op", REQUESTS, ids=lambda op: str(op[0]))
def test_modern_encoding_is_byte_identical_to_ours(op):
    # The repo's canonical encoder (core/etf.py) deliberately matches what
    # modern OTP emits; pin that the erl client's frames ARE our frames.
    req = (A("call"), 7, op)
    assert P.pack_frame(req) == frame(req)


def test_every_protocol_op_appears_in_erl_module():
    # Drift guard: the .erl wrappers must cover every op exercised here.
    src = open(ERL_PATH).read()
    for op in REQUESTS:
        assert f"{{{op[0]}," in src.replace(" ", ""), f"{op[0]} not in .erl"


# --- raw-socket session: literal Erlang bytes against a live server -------


@pytest.fixture()
def server():
    srv = BridgeServer(host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.close()


def _roundtrip(sock, buf, req_id, op, legacy=False):
    sock.sendall(frame((A("call"), req_id, op), legacy_atoms=legacy))
    while True:
        for term in P.unpack_frames(buf):
            rid, ok, payload = P.parse_reply(term)
            assert rid == req_id
            assert ok, payload
            return payload
        chunk = sock.recv(1 << 16)
        assert chunk, "server closed connection"
        buf += chunk


@pytest.mark.parametrize("legacy", [False, True], ids=["otp26+", "otp<26"])
def test_raw_socket_session_like_an_erlang_client(server, legacy):
    with socket.create_connection(server.address, timeout=30) as sock:
        buf = bytearray()
        rt = lambda i, op: _roundtrip(sock, buf, i, op, legacy)  # noqa: E731

        assert rt(1, (A("is_type"), A("average"))) is True
        h = rt(2, (A("new"), A("average"), []))
        eff = rt(3, (A("downstream"), h, (A("add"), 5), (A("replica1"), 0), 1))
        assert eff == (A("add"), (5, 1))
        assert rt(4, (A("update"), h, eff)) == []
        assert rt(5, (A("value"), h)) == 5.0
        blob = rt(6, (A("to_binary"), h))
        assert isinstance(blob, bytes)
        h2 = rt(7, (A("from_binary"), A("average"), blob))
        assert rt(8, (A("equal"), h, h2)) is True
        h3 = rt(9, (A("batch_merge"), A("average"), [h, blob]))
        assert rt(10, (A("value"), h3)) == 5.0  # (5+5)/(1+1)
        assert rt(11, (A("free"), h3)) is True

        # Dense grids beyond the flagship, raw bytes end to end: a MONOID
        # grid (average) and a JOIN grid (leaderboard).
        assert rt(12, (A("grid_new"), A("ga"), A("average"),
                       {A("n_replicas"): 2, A("n_keys"): 1})) is True
        assert rt(13, (A("grid_apply"), A("ga"),
                       [[(A("add"), 0, 10, 1)], [(A("add"), 0, 20, 1)]])) == 0
        assert rt(14, (A("grid_merge_all"), A("ga"))) is True
        assert rt(15, (A("grid_observe"), A("ga"), 0, 0)) == (30, 2)
        assert rt(16, (A("grid_new"), A("gl"), A("leaderboard"),
                       {A("n_replicas"): 2, A("n_players"): 8,
                        A("size"): 2})) is True
        assert rt(17, (A("grid_apply"), A("gl"),
                       [[(A("add"), 0, 1, 10)], [(A("ban"), 0, 1),
                                                 (A("add"), 0, 2, 5)]])) == 0
        assert rt(18, (A("grid_merge_all"), A("gl"))) is True
        assert rt(19, (A("grid_observe"), A("gl"), 0, 0)) == [(2, 5)]


# --- live escript (only when OTP is present) ------------------------------


@pytest.mark.skipif(
    shutil.which("escript") is None, reason="no escript in image"
)
def test_escript_smoke_against_live_server(server):
    host, port = server.address
    proc = subprocess.run(
        ["escript", ERL_PATH, host, str(port)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "bridge smoke OK" in proc.stdout
