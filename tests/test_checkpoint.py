"""Checkpoint/resume: WAL journal + snapshot => bit-identical recovery.

The crash-recovery property under test: for any crash point, restoring the
latest snapshot and replaying the journal suffix yields exactly the state
of a run that never crashed — including re-derived effect timestamps
(clocks are restored) and pending undelivered effects.
"""

import numpy as np
import pytest

from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.harness.checkpoint import (
    CheckpointingReplay,
    Journal,
    load_dense_checkpoint,
    resume,
    save_dense_checkpoint,
)
from antidote_ccrdt_tpu.harness.opgen import Workload, prepare_stream
from antidote_ccrdt_tpu.models.topk_rmv import TopkRmvScalar
from antidote_ccrdt_tpu.models.leaderboard import LeaderboardScalar


def drive(rp, ops, sync_every=7):
    for i, (origin, op) in enumerate(ops):
        rp.submit(origin, op)
        if (i + 1) % sync_every == 0:
            rp.sync()


def make_ops(n=60, seed=11, rmv_kind="rmv"):
    wl = Workload(n_replicas=3, n_ids=12, rmv_frac=0.3, rmv_kind=rmv_kind, seed=seed)
    return list(prepare_stream(wl, n))


@pytest.mark.parametrize("crash_at", [0, 5, 23, 59])
def test_resume_is_bit_identical(crash_at, tmp_path):
    crdt = TopkRmvScalar()
    ops = make_ops()

    # uninterrupted run
    ref = CheckpointingReplay(crdt, 3, new_args=(4,))
    drive(ref, ops)
    ref.sync()

    # crashed run: journal to disk, snapshot at `crash_at` submissions
    jpath = str(tmp_path / "wal.bin")
    with Journal(jpath) as j:
        rp = CheckpointingReplay(crdt, 3, new_args=(4,), journal=j)
        drive(rp, ops[:crash_at])
        snap = rp.snapshot()
        # ops after the snapshot reach the journal but the "process" dies
        # before any further snapshot
        drive(rp, ops[crash_at:])
        rp.sync()
        # recovery: snapshot + journal suffix
        with Journal(jpath) as j2:
            rec = resume(crdt, snap, j2)
            # bring both to the same final sync boundary
            assert rec.seq == rp.seq
            for a, b in zip(rp.states, rec.states):
                assert a == b  # full internal state, not just observable
            assert rp.effect_log == rec.effect_log
            assert [c.clock.get_time() for c in rp.ctxs] == [
                c.clock.get_time() for c in rec.ctxs
            ]


def test_resume_without_snapshot_replays_everything(tmp_path):
    crdt = LeaderboardScalar()
    ops = make_ops(40, seed=3, rmv_kind="ban")
    jpath = str(tmp_path / "wal.bin")
    with Journal(jpath) as j:
        rp = CheckpointingReplay(crdt, 3, new_args=(4,), journal=j)
        drive(rp, ops)
        rp.sync()
    with Journal(jpath) as j2:
        rec = resume(crdt, None, j2, n_replicas=3, new_args=(4,))
    for a, b in zip(rp.states, rec.states):
        assert a == b


def test_snapshot_rejects_wrong_type_and_version():
    crdt = TopkRmvScalar()
    rp = CheckpointingReplay(crdt, 2, new_args=(4,))
    snap = rp.snapshot()
    with pytest.raises(ValueError, match="leaderboard"):
        resume(LeaderboardScalar(), snap, Journal())
    with pytest.raises(ValueError, match="bad magic"):
        resume(crdt, b"XXXX" + snap[4:], Journal())
    bad = bytearray(snap)
    bad[4] = 99
    with pytest.raises(ValueError, match="newer"):
        resume(crdt, bytes(bad), Journal())


def test_journal_file_roundtrip(tmp_path):
    jpath = str(tmp_path / "wal.bin")
    recs = [(0, ("add", (1, 5))), (-1, None), (2, ("rmv", 1))]
    with Journal(jpath) as j:
        for o, op in recs:
            j.append(o, op)
    with Journal(jpath) as j2:
        assert list(j2.entries()) == recs
        assert list(j2.entries(start=2)) == recs[2:]
        assert len(j2) == 3


def test_closed_journal_reads_file_and_refuses_append(tmp_path):
    jpath = str(tmp_path / "wal.bin")
    j = Journal(jpath)
    j.append(0, ("add", 1))
    j.close()
    # records stay visible after close (they are the durable log)
    assert list(j.entries()) == [(0, ("add", 1))]
    assert len(j) == 1
    with pytest.raises(ValueError, match="closed"):
        j.append(1, ("add", 2))


def test_average_compaction_refuses_cancelling_n():
    from antidote_ccrdt_tpu.models.average import AverageScalar

    crdt = AverageScalar()
    e1, e2 = ("add", (5, -1)), ("add", (7, 1))
    # fusing would yield ('add', (12, 0)) which update's n=0 guard drops
    assert not crdt.can_compact(e1, e2)
    # zero-sum cancellation is fine (fused op is a genuine no-op)
    assert crdt.can_compact(("add", (5, -1)), ("add", (-5, 1)))
    assert crdt.can_compact(("add", (3, 2)), ("add", (4, 5)))
    assert crdt.compact_ops(("add", (3, 2)), ("add", (4, 5))) == (None, ("add", (7, 7)))


def test_journal_detects_truncation(tmp_path):
    jpath = str(tmp_path / "wal.bin")
    with Journal(jpath) as j:
        j.append(0, ("add", (1, 5)))
    with open(jpath, "r+b") as f:
        f.truncate(f.seek(0, 2) - 1)
    with Journal(jpath) as j2, pytest.raises(ValueError, match="truncated"):
        list(j2.entries())


def test_dense_checkpoint_roundtrip(tmp_path):
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    D = make_dense(n_ids=16, n_dcs=2, size=4, slots_per_id=2)
    state = D.init(n_replicas=2, n_keys=2)
    path = str(tmp_path / "dense.ckpt")
    save_dense_checkpoint(path, "topk_rmv", state, step=17)
    step, name, back = load_dense_checkpoint(path, state)
    assert (step, name) == (17, "topk_rmv")
    import jax

    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_resume_truncates_torn_tail_and_replays_prefix(tmp_path):
    """Crash mid-append: the final journal record is torn. `resume` must
    repair first (truncate the tail in place), replay the intact prefix
    bit-identically, and leave the journal appendable after the last
    good record — while `entries()` alone stays strict."""
    crdt = TopkRmvScalar()
    ops = make_ops(n=20)
    jpath = str(tmp_path / "wal.bin")
    with Journal(jpath) as j:
        rp = CheckpointingReplay(crdt, 3, new_args=(4,), journal=j)
        drive(rp, ops)

    # Reference: replay only the intact prefix (all but the last record).
    ref = CheckpointingReplay(crdt, 3, new_args=(4,))
    drive(ref, ops[:-1])

    import os

    size = os.path.getsize(jpath)
    os.truncate(jpath, size - 3)  # tear the last record mid-payload

    with Journal(jpath) as j2:
        rec = resume(crdt, None, j2, n_replicas=3, new_args=(4,))
        # The tail is gone, the prefix replayed exactly.
        assert len(j2) == len(ops) - 1 + (len(ops) - 1) // 7
        ref.sync()
        rec.sync()
        for a, b in zip(ref.states, rec.states):
            assert crdt.equal(a, b)
        # Post-repair appends land after the last good frame.
        origin, op = ops[-1]
        rec.submit(origin, op)
    with Journal(jpath) as j3:
        assert list(j3.entries())  # every frame decodes cleanly


def test_resume_repairs_torn_header_tail(tmp_path):
    """A crash can also tear mid-HEADER (fewer than 4 length bytes)."""
    crdt = TopkRmvScalar()
    ops = make_ops(n=6)
    jpath = str(tmp_path / "wal.bin")
    with Journal(jpath) as j:
        rp = CheckpointingReplay(crdt, 3, new_args=(4,), journal=j)
        for origin, op in ops:
            rp.submit(origin, op)
    with open(jpath, "ab") as f:
        f.write(b"\xff\xff")  # two stray header bytes
    with Journal(jpath) as j2:
        assert j2.repair() == 2
        assert len(list(j2.entries())) == len(ops)
