"""Delta-state replication (parallel/delta.py + elastic.py delta gossip):
the join-decomposition law, receiver equivalence, payload shrinkage, and
chained publish/sweep with gap resync."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.core import serial
from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps, make_dense
from antidote_ccrdt_tpu.parallel.delta import (
    apply_delta,
    delta_nbytes,
    expand_delta,
    state_delta,
)
from antidote_ccrdt_tpu.parallel.elastic import (
    DeltaPublisher,
    GossipStore,
    empty_delta,
    sweep_deltas,
)

R, NK, I, DCS, K, M = 2, 2, 256, 4, 8, 2
D = make_dense(n_ids=I, n_dcs=DCS, size=K, slots_per_id=M)


def rand_ops(rng, B=24, Br=6, ts_base=1):
    return TopkRmvOps(
        add_key=jnp.asarray(rng.integers(0, NK, (R, B)).astype(np.int32)),
        add_id=jnp.asarray(rng.integers(0, I, (R, B)).astype(np.int32)),
        add_score=jnp.asarray(rng.integers(1, 900, (R, B)).astype(np.int32)),
        add_dc=jnp.asarray(rng.integers(0, DCS, (R, B)).astype(np.int32)),
        add_ts=jnp.asarray(
            (ts_base + rng.integers(0, 50, (R, B))).astype(np.int32)
        ),
        rmv_key=jnp.asarray(rng.integers(0, NK, (R, Br)).astype(np.int32)),
        rmv_id=jnp.asarray(rng.integers(0, I, (R, Br)).astype(np.int32)),
        rmv_vc=jnp.asarray(rng.integers(0, 40, (R, Br, DCS)).astype(np.int32)),
    )


def states_equal(a, b) -> bool:
    return all(
        bool(jnp.array_equal(x, y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


@pytest.mark.parametrize("seed", range(4))
def test_join_decomposition_law(seed):
    # prev ⊔ expand(delta(prev, cur)) == cur, exactly (canonical slots).
    rng = np.random.default_rng(seed)
    prev = D.init(R, NK)
    prev, _ = D.apply_ops(prev, rand_ops(rng))
    cur, _ = D.apply_ops(prev, rand_ops(rng, ts_base=100))
    delta = state_delta(D, prev, cur)
    rejoined = D.merge(prev, expand_delta(D, delta))
    assert states_equal(rejoined, cur)


@pytest.mark.parametrize("seed", range(4))
def test_receiver_equivalence(seed):
    # A receiver that holds >= prev gets the same result from the delta
    # as from the full state.
    rng = np.random.default_rng(100 + seed)
    prev = D.init(R, NK)
    prev, _ = D.apply_ops(prev, rand_ops(rng))
    cur, _ = D.apply_ops(prev, rand_ops(rng, ts_base=100))
    theirs = D.init(R, NK)
    theirs, _ = D.apply_ops(theirs, rand_ops(rng, ts_base=200))
    theirs = D.merge(theirs, prev)  # receiver saw the previous publish
    via_delta = apply_delta(D, theirs, state_delta(D, prev, cur))
    via_full = D.merge(theirs, cur)
    assert states_equal(via_delta, via_full)


def test_payload_shrinks():
    rng = np.random.default_rng(7)
    prev = D.init(R, NK)
    prev, _ = D.apply_ops(prev, rand_ops(rng))
    cur, _ = D.apply_ops(prev, rand_ops(rng, B=8, Br=2, ts_base=100))
    delta = state_delta(D, prev, cur)
    full_bytes = sum(np.asarray(x).nbytes for x in jax.tree.leaves(cur))
    assert delta_nbytes(delta) < full_bytes / 5, (
        delta_nbytes(delta), full_bytes
    )
    # And it survives the wire format with shapes intact.
    blob = serial.dumps_dense("topk_rmv_delta", delta)
    _, back = serial.loads_dense(blob, empty_delta(D))
    assert states_equal(back, delta)


def test_chained_delta_gossip_with_gap_resync(tmp_path):
    rng = np.random.default_rng(11)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    # keep=2 prunes aggressively so the receiver is forced through the
    # full-snapshot resync path mid-run.
    pub = DeltaPublisher(a, D, full_every=100, keep=2)
    state_a = D.init(R, NK)
    state_b = D.init(R, NK)
    cursors: dict = {}
    kinds = []
    for step in range(7):
        state_a, _ = D.apply_ops(state_a, rand_ops(rng, ts_base=1 + 60 * step))
        kinds.append(pub.publish(state_a)["kind"])
        if step == 2:  # receiver keeps up early...
            state_b, stats = sweep_deltas(b, D, state_b, cursors)
            assert stats["deltas"] >= 1
    # ...then falls behind past the retention window: deltas 3..6 minus
    # pruning leaves a gap, but full_every=100 means no newer snapshot —
    # publish one so resync has an anchor.
    a.publish("topk_rmv", state_a, pub.seq)
    state_b, stats = sweep_deltas(b, D, state_b, cursors)
    assert stats["fulls"] >= 1
    assert states_equal(state_b, state_a) or D.equal(state_b, state_a)
    assert kinds[0] == "full" and "delta" in kinds[1:]


def test_gap_resync_via_periodic_anchor(tmp_path):
    """The production resync path, end to end: the consumer's cursor
    falls off the keep window while it isn't sweeping, and the
    publisher's own periodic full anchor (full_every) — not a manual
    snapshot — closes the gap; chaining then RESUMES from the anchor
    (deltas published after it still apply)."""
    rng = np.random.default_rng(23)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    pub = DeltaPublisher(a, D, full_every=5, keep=2)
    state_a = D.init(R, NK)
    state_b = D.init(R, NK)
    cursors: dict = {}
    state_b, stats = sweep_deltas(b, D, state_b, cursors)
    assert stats == {"deltas": 0, "fulls": 0, "skipped": 0}  # nothing yet
    for step in range(12):
        state_a, _ = D.apply_ops(state_a, rand_ops(rng, ts_base=1 + 60 * step))
        pub.publish(state_a)
    # Seqs 0..11: anchors at 0/5/10, deltas pruned to the keep=2 window —
    # the consumer's cursor (-1) is far off the retained chain.
    assert len(a.delta_seqs("a")) <= 2
    state_b, stats = sweep_deltas(b, D, state_b, cursors)
    assert stats["fulls"] == 1  # resynced from the seq-10 anchor
    assert stats["deltas"] == 1  # ...and chained the post-anchor delta 11
    assert cursors["a"] == pub.seq == 11
    assert D.equal(state_b, state_a)
    # Idempotence: a second sweep over the same artifacts is a no-op.
    state_b2, stats2 = sweep_deltas(b, D, state_b, cursors)
    assert stats2["deltas"] == 0 and D.equal(state_b2, state_b)


def test_torn_delta_skipped(tmp_path):
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    rng = np.random.default_rng(3)
    st = D.init(R, NK)
    st, _ = D.apply_ops(st, rand_ops(rng))
    pub = DeltaPublisher(a, D, full_every=100)
    pub.publish(st)  # full (seq 0)
    # A garbage delta at seq 1 must not crash or advance the chain.
    with open(os.path.join(str(tmp_path), "delta-a-00000001"), "wb") as f:
        f.write(b"\x00garbage")
    state_b = D.init(R, NK)
    cursors: dict = {}
    state_b, stats = sweep_deltas(b, D, state_b, cursors)
    assert stats["fulls"] == 1
    assert cursors["a"] == 0  # chain stopped before the torn seq 1
    assert D.equal(state_b, st)


def test_mismatched_config_delta_skipped(tmp_path):
    # A peer on a different engine config publishes deltas that decode
    # (treedef matches) but must be rejected, not crash the sweep.
    D_big = make_dense(n_ids=2 * I, n_dcs=DCS, size=K, slots_per_id=M)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    rng = np.random.default_rng(5)
    big_prev = D_big.init(R, NK)
    ops = TopkRmvOps(
        add_key=jnp.zeros((R, 4), jnp.int32),
        add_id=jnp.asarray(rng.integers(I, 2 * I, (R, 4)).astype(np.int32)),
        add_score=jnp.full((R, 4), 9, jnp.int32),
        add_dc=jnp.zeros((R, 4), jnp.int32),
        add_ts=jnp.asarray(rng.integers(1, 50, (R, 4)).astype(np.int32)),
        rmv_key=jnp.zeros((R, 1), jnp.int32),
        rmv_id=jnp.full((R, 1), -1, jnp.int32),
        rmv_vc=jnp.zeros((R, 1, DCS), jnp.int32),
    )
    big_cur, _ = D_big.apply_ops(big_prev, ops)
    pub = DeltaPublisher(a, D_big, full_every=1000)
    pub.publish(big_cur)          # full snap (skipped by check_state)
    big_cur2, _ = D_big.apply_ops(big_cur, ops)
    pub.publish(big_cur2)         # delta with rows >= local R*NK*I
    state_b = D.init(R, NK)
    cursors: dict = {}
    state_b, stats = sweep_deltas(b, D, state_b, cursors)  # must not raise
    assert stats["deltas"] == 0
    assert D.equal(state_b, D.init(R, NK))


from conftest import HealthCheck, given, settings, st  # noqa: E402  (hypothesis or skip-stub)


@settings(
    max_examples=20, deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    script=st.lists(
        st.tuples(st.integers(0, 1), st.sampled_from(["ops", "publish", "sweep"])),
        min_size=1, max_size=24,
    ),
    keep=st.integers(1, 4),
    full_every=st.integers(2, 6),
)
def test_delta_gossip_arbitrary_interleavings(script, keep, full_every):
    """Protocol soundness under ANY schedule of op application, delta/full
    publishing (with aggressive pruning), and sweeping: after a final
    publish + sweep everyone equals the sequential reference."""
    import tempfile

    with tempfile.TemporaryDirectory() as root:
        names = ["a", "b"]
        stores = [GossipStore(root, n) for n in names]
        pubs = [
            DeltaPublisher(s, D, full_every=full_every, keep=keep)
            for s in stores
        ]
        states = [D.init(R, NK) for _ in names]
        cursors: list = [{}, {}]
        ref = D.init(R, NK)
        counters = [0, 0]

        def member_ops(m, k):
            # Deterministic per (member, k); member m touches row m only.
            rng = np.random.default_rng(7_000 + 97 * m + k)
            ops = rand_ops(rng, B=6, Br=2, ts_base=1 + 50 * k)
            row_mask = (np.arange(R) == m)[:, None]
            return TopkRmvOps(
                add_key=ops.add_key,
                add_id=ops.add_id,
                add_score=ops.add_score,
                add_dc=ops.add_dc,
                add_ts=ops.add_ts * jnp.asarray(row_mask, jnp.int32),
                rmv_key=ops.rmv_key,
                rmv_id=jnp.where(jnp.asarray(row_mask), ops.rmv_id, -1),
                rmv_vc=ops.rmv_vc,
            )

        for m, action in script:
            if action == "ops":
                ops = member_ops(m, counters[m])
                counters[m] += 1
                states[m], _ = D.apply_ops(states[m], ops)
                ref, _ = D.apply_ops(ref, ops)
            elif action == "publish":
                pubs[m].publish(states[m])
            else:
                states[m], _ = sweep_deltas(stores[m], D, states[m], cursors[m])
        for m in range(2):
            pubs[m].publish(states[m])
        # Everyone must have a full anchor for final convergence (the last
        # publish may have been a delta the peer's cursor can't reach if
        # earlier deltas were pruned) — publish full explicitly.
        for m in range(2):
            stores[m].publish("topk_rmv", states[m], pubs[m].seq)
        for m in range(2):
            states[m], _ = sweep_deltas(stores[m], D, states[m], cursors[m])
        for m in range(2):
            assert D.equal(states[m], ref), f"member {m} diverged"


# --- generic entrywise deltas (topk / leaderboard / wordcount) ------------


from antidote_ccrdt_tpu.parallel.delta import (  # noqa: E402
    apply_table_delta,
    expand_table_delta,
    table_delta,
)


def _leaderboard_pair(seed):
    from antidote_ccrdt_tpu.models.leaderboard import LeaderboardOps
    from antidote_ccrdt_tpu.models.leaderboard import make_dense as mk_lb

    rng = np.random.default_rng(seed)
    Dl = mk_lb(n_players=128, size=4)

    def ops(n, nb):
        return LeaderboardOps(
            add_key=jnp.zeros((2, n), jnp.int32),
            add_id=jnp.asarray(rng.integers(0, 128, (2, n)).astype(np.int32)),
            add_score=jnp.asarray(rng.integers(1, 500, (2, n)).astype(np.int32)),
            add_valid=jnp.ones((2, n), bool),
            ban_key=jnp.zeros((2, nb), jnp.int32),
            ban_id=jnp.asarray(rng.integers(0, 128, (2, nb)).astype(np.int32)),
            ban_valid=jnp.ones((2, nb), bool),
        )

    prev = Dl.init(2, 1)
    prev, _ = Dl.apply_ops(prev, ops(20, 3))
    cur, _ = Dl.apply_ops(prev, ops(6, 1))
    return Dl, prev, cur


def _wordcount_pair(seed):
    from antidote_ccrdt_tpu.models.wordcount import WordcountOps
    from antidote_ccrdt_tpu.models.wordcount import make_dense as mk_wc

    rng = np.random.default_rng(seed)
    Dw = mk_wc(256)

    def ops(n):
        return WordcountOps(
            key=jnp.zeros((2, n), jnp.int32),
            token=jnp.asarray(rng.integers(0, 256, (2, n)).astype(np.int32)),
        )

    prev = Dw.init(2, 1)
    prev, _ = Dw.apply_ops(prev, ops(40))
    cur, _ = Dw.apply_ops(prev, ops(10))
    return Dw, prev, cur


@pytest.mark.parametrize("mk", [_leaderboard_pair, _wordcount_pair])
@pytest.mark.parametrize("seed", range(3))
def test_table_delta_decomposition_law(mk, seed):
    # prev (+ or ⊔) expand(delta(prev, cur)) == cur, per the merge algebra.
    Deng, prev, cur = mk(seed)
    delta = table_delta(Deng, prev, cur)
    rejoined = apply_table_delta(Deng, prev, delta)
    assert states_equal(rejoined, cur)


def test_table_delta_join_receiver_equivalence():
    Dl, prev, cur = _leaderboard_pair(42)
    Dl2, other, _ = _leaderboard_pair(43)
    theirs = Dl.merge(other, prev)  # receiver holds >= prev
    via_delta = apply_table_delta(Dl, theirs, table_delta(Dl, prev, cur))
    via_full = Dl.merge(theirs, cur)
    assert states_equal(via_delta, via_full)


def test_table_delta_payload_and_wire():
    Dw, prev, cur = _wordcount_pair(9)
    delta = table_delta(Dw, prev, cur)
    full = sum(np.asarray(x).nbytes for x in jax.tree.leaves(cur))
    assert delta_nbytes(delta) < full / 3
    blob = serial.dumps_dense("wordcount_delta", delta)
    _, back = serial.loads_dense(blob, delta)
    assert states_equal(back, delta)


def test_delta_gossip_generic_join_engine(tmp_path):
    # The same chained gossip protocol over a TABLE engine (leaderboard).
    from antidote_ccrdt_tpu.models.leaderboard import LeaderboardOps
    from antidote_ccrdt_tpu.models.leaderboard import make_dense as mk_lb

    Dl = mk_lb(n_players=64, size=4)
    rng = np.random.default_rng(21)

    def ops(n):
        return LeaderboardOps(
            add_key=jnp.zeros((2, n), jnp.int32),
            add_id=jnp.asarray(rng.integers(0, 64, (2, n)).astype(np.int32)),
            add_score=jnp.asarray(rng.integers(1, 900, (2, n)).astype(np.int32)),
            add_valid=jnp.ones((2, n), bool),
            ban_key=jnp.zeros((2, 1), jnp.int32),
            ban_id=jnp.asarray(rng.integers(0, 64, (2, 1)).astype(np.int32)),
            ban_valid=jnp.ones((2, 1), bool),
        )

    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    pub = DeltaPublisher(a, Dl, name="leaderboard", full_every=100)
    sa = Dl.init(2, 1)
    sb = Dl.init(2, 1)
    cursors: dict = {}
    kinds = []
    for _ in range(5):
        sa, _ = Dl.apply_ops(sa, ops(12))
        kinds.append(pub.publish(sa)["kind"])
    sb, stats = sweep_deltas(b, Dl, sb, cursors)
    assert stats["deltas"] == 4 and stats["fulls"] == 1, (stats, kinds)
    assert states_equal(sb, sa)


def test_delta_gossip_lifts_monoid_engine(tmp_path):
    """Round 2 refused MONOID engines outright; round 3 auto-wraps them
    in the versioned-row lift (parallel/monoid.py) — but still rejects a
    raw (unversioned) monoid state at publish time."""
    from antidote_ccrdt_tpu.models.wordcount import make_dense as mk_wc
    from antidote_ccrdt_tpu.parallel.monoid import MonoidLift

    store = GossipStore(str(tmp_path), "a")
    pub = DeltaPublisher(store, mk_wc(64), name="wordcount_lifted")
    assert isinstance(pub.dense, MonoidLift)
    with pytest.raises(TypeError, match="MonoidLift"):
        pub.publish(mk_wc(64).init(2, 1))
    pub.publish(pub.dense.init(2, 1))  # lifted state sails through


@pytest.mark.parametrize("seed", range(2))
def test_table_delta_average_whole_leaf_monoid(seed):
    # average has no O(P) table planes — the delta is the (sum, num)
    # difference shipped whole, applied via the monoid +.
    from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps

    rng = np.random.default_rng(seed)
    Da = AverageDense()

    def ops(n):
        return AverageOps(
            key=jnp.asarray(rng.integers(0, 3, (2, n)).astype(np.int32)),
            value=jnp.asarray(rng.integers(-50, 50, (2, n)).astype(np.int32)),
            count=jnp.asarray(rng.integers(1, 3, (2, n)).astype(np.int32)),
        )

    prev = Da.init(2, 3)
    prev, _ = Da.apply_ops(prev, ops(16))
    cur, _ = Da.apply_ops(prev, ops(8))
    delta = table_delta(Da, prev, cur)
    assert np.asarray(delta["idx"]).size == 0
    rejoined = apply_table_delta(Da, prev, delta)
    assert states_equal(rejoined, cur)


# --- ADVICE round-1 hardening: bounds validation + total sweep policy -----


def test_replica_dim_mismatch_delta_rejected():
    # A peer with the same I/M/D but n_replicas=1 produces a delta whose
    # vc/lossy leading dims differ; before the full-shape check it passed
    # validation and jnp-broadcast its single replica row into ALL local
    # replicas inside merge.
    from antidote_ccrdt_tpu.parallel.delta import delta_in_bounds, state_delta

    rng = np.random.default_rng(17)
    prev1 = jax.tree.map(lambda x: x[:1], D.init(R, NK))
    ops1 = jax.tree.map(lambda x: x[:1], rand_ops(rng))
    cur1, _ = D.apply_ops(prev1, ops1)
    peer_delta = state_delta(D, prev1, cur1)
    local = D.init(R, NK)
    assert not delta_in_bounds(D, local, peer_delta)


def test_row_payload_length_mismatch_rejected():
    from antidote_ccrdt_tpu.parallel.delta import delta_in_bounds, state_delta
    import dataclasses as dc

    rng = np.random.default_rng(19)
    prev = D.init(R, NK)
    cur, _ = D.apply_ops(prev, rand_ops(rng))
    delta = state_delta(D, prev, cur)
    assert delta_in_bounds(D, cur, delta)
    torn = dc.replace(delta, slot_score=delta.slot_score[:-1])
    assert not delta_in_bounds(D, cur, torn)


def test_table_delta_payload_length_mismatch_rejected():
    from antidote_ccrdt_tpu.parallel.delta import delta_in_bounds

    Dw, prev, cur = _wordcount_pair(23)
    delta = table_delta(Dw, prev, cur)
    assert delta_in_bounds(Dw, cur, delta)
    p = next(iter(delta["table"]))
    torn = {
        "idx": delta["idx"],
        "table": {**delta["table"], p: delta["table"][p][:-1]},
        "whole": delta["whole"],
    }
    assert not delta_in_bounds(Dw, cur, torn)


def test_sweep_deltas_survives_apply_failure(tmp_path, monkeypatch):
    # Total-failure policy: a delta that passes bounds but still explodes
    # inside apply must be counted skipped, not crash the gossip loop.
    import antidote_ccrdt_tpu.parallel.delta as delta_mod

    rng = np.random.default_rng(29)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    pub = DeltaPublisher(a, D, full_every=100)
    st = D.init(R, NK)
    st, _ = D.apply_ops(st, rand_ops(rng))
    pub.publish(st)  # full (seq 0)
    st, _ = D.apply_ops(st, rand_ops(rng, ts_base=100))
    pub.publish(st)  # delta (seq 1)

    def boom(dense, state, delta):
        raise RuntimeError("malformed beyond bounds check")

    monkeypatch.setattr(delta_mod, "apply_any_delta", boom)
    state_b = D.init(R, NK)
    cursors: dict = {}
    state_b, stats = sweep_deltas(b, D, state_b, cursors)  # must not raise
    assert stats["skipped"] >= 1
    assert cursors["a"] == 0  # chain stopped at the failing delta


def test_snapshot_sweep_rejects_raw_monoid_state(tmp_path):
    """Sweeps auto-lift a raw MONOID engine but a raw state stays a
    TypeError — versions are required protocol information (the lifted
    path itself is exercised in tests/test_monoid_lift.py)."""
    from antidote_ccrdt_tpu.models.wordcount import make_dense as mk_wc
    from antidote_ccrdt_tpu.parallel.elastic import sweep

    store = GossipStore(str(tmp_path), "a")
    Dw = mk_wc(64)
    with pytest.raises(TypeError, match="MonoidLift"):
        sweep(store, Dw, Dw.init(1, 1))
    with pytest.raises(TypeError, match="MonoidLift"):
        sweep_deltas(store, Dw, Dw.init(1, 1), {})
