"""ETF codec + reference-wire state conversion tests.

Golden byte vectors are hand-assembled from the ETF spec (the distribution
protocol's external term format) and match OTP's term_to_binary output for
the flatmap/small-atom-utf8 era (OTP >= 26 defaults).
"""

import pytest

from antidote_ccrdt_tpu.core import etf, wire
from antidote_ccrdt_tpu.core.etf import Atom
from antidote_ccrdt_tpu.core.behaviour import registry
from antidote_ccrdt_tpu.core.clock import make_contexts

GOLDEN = [
    # term_to_binary({3, 2})
    ((3, 2), bytes([131, 104, 2, 97, 3, 97, 2])),
    # term_to_binary(#{}) / #{1 => 2}
    ({}, bytes([131, 116, 0, 0, 0, 0])),
    ({1: 2}, bytes([131, 116, 0, 0, 0, 1, 97, 1, 97, 2])),
    # term_to_binary(-1), term_to_binary(1000)
    (-1, bytes([131, 98, 255, 255, 255, 255])),
    (1000, bytes([131, 98, 0, 0, 3, 232])),
    # term_to_binary(<<"hi">>)
    (b"hi", bytes([131, 109, 0, 0, 0, 2, 104, 105])),
    # lists of bytes are STRING_EXT; other lists are LIST_EXT
    ([1, 2, 3], bytes([131, 107, 0, 3, 1, 2, 3])),
    ([1000], bytes([131, 108, 0, 0, 0, 1, 98, 0, 0, 3, 232, 106])),
    ([], bytes([131, 106])),
    # term_to_binary(1.5)
    (1.5, bytes([131, 70, 63, 248, 0, 0, 0, 0, 0, 0])),
    # term_to_binary(1 bsl 64)
    (1 << 64, bytes([131, 110, 9, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1])),
    # term_to_binary(nil) — SMALL_ATOM_UTF8_EXT
    (Atom("nil"), bytes([131, 119, 3, 110, 105, 108])),
    (True, bytes([131, 119, 4]) + b"true"),
    (False, bytes([131, 119, 5]) + b"false"),
    # topk:new(100) state: {#{}, 100}
    ((({}), 100), bytes([131, 104, 2, 116, 0, 0, 0, 0, 97, 100])),
]


@pytest.mark.parametrize("term,blob", GOLDEN, ids=[repr(t)[:40] for t, _ in GOLDEN])
def test_golden_encode(term, blob):
    assert etf.encode(term) == blob


@pytest.mark.parametrize("term,blob", GOLDEN, ids=[repr(t)[:40] for t, _ in GOLDEN])
def test_golden_decode(term, blob):
    assert etf.decode(blob) == term


def test_decode_legacy_atom_ext():
    # ATOM_EXT (100) with 2-byte length — what older OTP emits.
    assert etf.decode(bytes([131, 100, 0, 3]) + b"nil") == Atom("nil")
    assert etf.decode(bytes([131, 100, 0, 4]) + b"true") is True


def test_compressed_roundtrip():
    term = {i: list(range(20)) for i in range(50)}
    blob = etf.encode(term, compressed=True)
    assert blob[1] == etf.COMPRESSED
    assert etf.decode(blob) == term


def test_roundtrip_nested():
    term = (
        {Atom("a"): (1, -5, 1 << 80), b"bin": [1.25, [], [300, (True, False)]]},
        Atom("x"),
        [],
    )
    assert etf.decode(etf.encode(term)) == term


def test_map_key_order_is_erlang_term_order():
    # number < atom < tuple < binary — OTP flatmap serialization order.
    blob = etf.encode({b"bin": 1, Atom("a"): 2, 5: 3, (1, 2): 4})
    # decode preserves insertion order of the encoded stream
    keys = list(etf.decode(blob).keys())
    assert keys == [5, Atom("a"), (1, 2), b"bin"]


def test_malformed_inputs_raise_valueerror():
    with pytest.raises(ValueError):
        etf.decode(bytes([131]))  # truncated after magic
    with pytest.raises(ValueError):
        etf.decode(b"")
    with pytest.raises(ValueError):
        etf.decode(bytes([130, 97, 1]))  # bad magic
    with pytest.raises(ValueError):
        etf.decode(etf.encode((1, 2)) + b"junk")  # trailing bytes
    z = etf.encode({i: i for i in range(64)}, compressed=True)
    assert z[1] == etf.COMPRESSED
    with pytest.raises(ValueError):
        etf.decode(z + b"junk")  # trailing bytes after zlib stream


def test_map_key_with_charlist_inside_tuple():
    # #{{"ab", 5} => 1}: STRING_EXT inside a tuple key must still hash.
    blob = bytes([131, 116, 0, 0, 0, 1, 104, 2, 107, 0, 2, 97, 98, 97, 5, 97, 1])
    assert etf.decode(blob) == {((97, 98), 5): 1}


def test_bool_atom_sort_order():
    # atom term order: 'apple' < 'true'
    blob = etf.encode({True: 1, Atom("apple"): 2})
    assert list(etf.decode(blob).keys()) == [Atom("apple"), True]


def test_gb_sets_roundtrip_matches_from_ordset():
    # gb_sets:from_ordset([1,2,3]) = {3, {2, {1,nil,nil}, {3,nil,nil}}}
    nil = Atom("nil")
    assert etf.gb_set_from_list([3, 1, 2]) == (3, (2, (1, nil, nil), (3, nil, nil)))
    assert etf.gb_set_to_list(etf.gb_set_from_list(range(100))) == list(range(100))
    assert etf.gb_set_to_list((0, nil)) == []


def test_sets_v1_record_decode():
    # A sets:new() (v1) record with two elements placed structurally:
    # {set, Size, N, MaxN, BSo, ESo, Con, Empty, Segs}.
    empty_seg = tuple([[] for _ in range(16)])
    seg = tuple([[10] if i == 0 else ([20] if i == 3 else []) for i in range(16)])
    rec = (Atom("set"), 2, 16, 16, 8, 80, 48, empty_seg, (seg,))
    assert sorted(etf.set_to_list(rec)) == [10, 20]
    # v2 map form
    assert sorted(etf.set_to_list({10: [], 20: []})) == [10, 20]
    assert etf.set_from_list([10, 20]) == {10: [], 20: []}


# --- wire: state round-trips over every type ------------------------------


def _run_ops(name, ops, new_args=()):
    crdt = registry.scalar(name)
    (ctx,) = make_contexts(1)
    state = crdt.new(*new_args)
    for op in ops:
        eff = crdt.downstream(op, state, ctx)
        if eff is not None:
            state, extras = crdt.update(eff, state)
            for e in extras:
                state, _ = crdt.update(e, state)
    return crdt, state


CASES = [
    ("average", [("add", 5), ("add", (10, 2))], ()),
    ("topk", [("add", (1, 42)), ("add", (2, 7)), ("add", (1, 50))], (5,)),
    (
        "topk_rmv",
        [("add", (1, 42)), ("add", (2, 7)), ("rmv", 2), ("add", (3, 99))],
        (2,),
    ),
    ("leaderboard", [("add", (1, 42)), ("add", (2, 7)), ("ban", 2)], (2,)),
    ("wordcount", [("add", "a b b\nc")], ()),
    ("worddocumentcount", [("add", "a a b"), ("add", "a c")], ()),
]


@pytest.mark.parametrize("name,ops,new_args", CASES, ids=[c[0] for c in CASES])
def test_wire_roundtrip(name, ops, new_args):
    crdt, state = _run_ops(name, ops, new_args)
    blob = wire.to_reference_binary(name, state)
    back = wire.from_reference_binary(name, blob)
    assert crdt.equal(state, back)
    # full-state equality, not just observable
    assert wire.state_to_term(name, back) == wire.state_to_term(name, state)
    # compressed flavour decodes identically
    blob_z = wire.to_reference_binary(name, state, compressed=True)
    assert wire.state_to_term(name, wire.from_reference_binary(name, blob_z)) == \
        wire.state_to_term(name, state)


def test_wire_golden_topk_state():
    # topk state {#{1 => 42}, 10} after one add
    crdt, state = _run_ops("topk", [("add", (1, 42))], (10,))
    assert wire.to_reference_binary("topk", state) == bytes(
        [131, 104, 2, 116, 0, 0, 0, 1, 97, 1, 97, 42, 97, 10]
    )


def test_wire_accepts_beam_style_ids_and_dcids():
    # A topk_rmv snapshot whose dcid is Antidote-style {atom, int} and whose
    # ids are binaries — decodes into a usable scalar state.
    dc = (Atom("replica1"), 0)
    term = (
        {b"player": (42, b"player", (dc, 7))},
        {b"player": etf.gb_set_from_list([(42, b"player", (dc, 7))])},
        {},
        {dc: 7},
        (42, b"player", (dc, 7)),
        100,
    )
    state = wire.state_from_term("topk_rmv", term)
    crdt = registry.scalar("topk_rmv")
    # utf-8 binary ids normalize to str in Python...
    assert crdt.value(state) == [("player", 42)]
    # ...but re-encode to the identical BEAM term
    assert wire.state_to_term("topk_rmv", state) == term


def test_atom_is_type_strict():
    assert Atom("x") != "x"
    assert "x" != Atom("x")
    assert Atom("x") == Atom("x")
    assert hash(Atom("x")) != hash("x")
    # atom x and binary <<"x">> coexist as distinct map keys end to end
    term = ({Atom("x"): 1, b"x": 2}, 5)
    state = wire.state_from_term("topk", term)
    assert len(state.entries) == 2
    assert wire.state_to_term("topk", state) == term
    assert etf.decode(etf.encode({Atom("x"): 1, b"x": 2})) == {Atom("x"): 1, b"x": 2}


def test_wire_str_ids_roundtrip_identity():
    crdt, state = _run_ops("topk", [("add", ("player", 42))], (5,))
    back = wire.from_reference_binary("topk", wire.to_reference_binary("topk", state))
    assert back == state  # str keys survive, not mutated to bytes
    # non-utf8 binary ids stay bytes
    raw = wire.state_from_term("topk", ({b"\xff\xfe": 1}, 5))
    assert list(raw.entries) == [b"\xff\xfe"]
