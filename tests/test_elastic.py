"""Elastic membership tier (parallel/elastic.py): unit tests for the
failure detector / ownership / gossip pieces, plus the real-process
recovery drill — three workers, one crashes mid-run, survivors detect it,
adopt its replicas, and converge to the sequential reference."""

import json
import os
import subprocess
import sys
import time

import pytest

import jax

from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
from antidote_ccrdt_tpu.parallel.elastic import (
    GossipStore,
    my_replicas,
    owners,
    sweep,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "scripts", "elastic_demo.py")


def test_owners_deterministic_and_total():
    assert owners(["b", "a"], 4) == {0: "a", 1: "b", 2: "a", 3: "b"}
    assert owners(["only"], 3) == {0: "only", 1: "only", 2: "only"}
    assert owners([], 3) == {}


def test_failure_detector_and_ownership_shift(tmp_path):
    import struct

    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    assert a.alive_members(10.0) == ["a", "b"]
    assert set(my_replicas(a, 4, 10.0)) == {0, 2}
    # b goes silent: backdate its heartbeat PAYLOAD past the timeout (the
    # payload, not mtime, is the liveness source — mtime is flaky on
    # coarse-granularity/object-store filesystems).
    hb = os.path.join(str(tmp_path), "hb-b")
    with open(hb, "wb") as f:
        f.write(struct.pack("<d", time.time() - 60))
    assert a.alive_members(1.0) == ["a"]
    assert set(my_replicas(a, 4, 1.0)) == {0, 1, 2, 3}
    # b still considers itself alive (never self-suspects).
    assert "b" in b.alive_members(1.0)


def test_heartbeat_mtime_fallback(tmp_path):
    """A payload-less heartbeat file (pre-payload writer, or a torn
    write) still reads via mtime — forward compatibility with foreign
    members on the old format."""
    a = GossipStore(str(tmp_path), "a")
    hb_c = os.path.join(str(tmp_path), "hb-c")
    with open(hb_c, "wb"):
        pass  # empty: no payload
    assert a.alive_members(10.0) == ["a", "c"]  # fresh mtime counts
    past = time.time() - 60
    os.utime(hb_c, (past, past))
    assert a.alive_members(1.0) == ["a"]  # stale mtime ages out


def test_gossip_sweep_merges_peer_snapshots(tmp_path):
    D = make_dense(n_ids=16, n_dcs=2, size=4, slots_per_id=2)
    a = GossipStore(str(tmp_path), "a")
    b = GossipStore(str(tmp_path), "b")
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

    def add(state, store_owner_row, id_, score, ts, dc=0):
        z = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
        ops = TopkRmvOps(
            add_key=z(2, 1), add_id=jnp.asarray([[id_]], jnp.int32).repeat(2, 0),
            add_score=jnp.asarray([[score]], jnp.int32).repeat(2, 0),
            add_dc=z(2, 1) + dc,
            add_ts=jnp.asarray(
                [[ts if r == store_owner_row else 0] for r in range(2)], jnp.int32
            ),
            rmv_key=z(2, 1), rmv_id=z(2, 1) - 1, rmv_vc=z(2, 1, 2),
        )
        return D.apply_ops(state, ops, collect_dominated=False)[0]

    sa = add(D.init(2, 1), 0, id_=3, score=50, ts=1)
    sb = add(D.init(2, 1), 1, id_=7, score=90, ts=2)
    a.publish("topk_rmv", sa, step=1)
    b.publish("topk_rmv", sb, step=1)
    merged, n = sweep(a, D, sa)
    assert n == 1
    v = D.value(merged)
    assert v[0][0] == [(3, 50)] and v[1][0] == [(7, 90)]
    # Idempotence: sweeping the same snapshots again changes nothing.
    again, _ = sweep(a, D, merged)
    assert D.equal(again, merged)


def _run_drill(tmp_path, spec, n_members, type_name, timeout=180):
    """Launch drill workers per `spec` [(member, extra_args)], wait, and
    return ({member: returncode}, {member: output})."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = {}
    for member, extra in spec:
        procs[member] = subprocess.Popen(
            [sys.executable, DEMO, "--root", str(tmp_path), "--member", member,
             "--n-members", str(n_members), "--type", type_name, *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
    rcs, outs = {}, {}
    for member, p in procs.items():
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            pytest.fail(f"worker {member} timed out:\n{out}")
        rcs[member], outs[member] = p.returncode, out
    return rcs, outs


def _drill_reference(type_name):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import elastic_demo

    return elastic_demo.reference_digest(type_name)


def test_real_process_crash_recovery(tmp_path):
    """Three workers; w1 crashes at step 4; w0/w2 must adopt its replicas
    and both converge to the sequential single-process reference."""
    rcs, outs = _run_drill(
        tmp_path, (("w0", []), ("w1", ["--die-at", "4"]), ("w2", [])),
        3, "topk_rmv",
    )
    assert rcs["w1"] == 1, f"victim should crash:\n{outs['w1']}"
    ref = [list(t) for t in _drill_reference("topk_rmv")]  # JSON: lists
    assert ref, "reference observable is empty — drill is vacuous"
    for m in ("w0", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged from the sequential reference\n"
            f"got:  {got['digest']}\nref: {ref}\nlog:\n{outs[m]}"
        )
        assert "w1" not in got["alive"], "crashed member still considered alive"


def test_real_process_scale_up_late_joiner(tmp_path):
    """Two founding workers + one that joins ~1s into the run: ownership
    rebalances onto the joiner, everyone converges to the reference."""
    rcs, outs = _run_drill(
        tmp_path, (("w0", []), ("w1", []), ("w2", ["--join-late", "1.0"])),
        2, "topk_rmv",
    )
    ref = [list(t) for t in _drill_reference("topk_rmv")]
    for m in ("w0", "w1", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged\ngot:  {got['digest']}\nref: {ref}\nlog:\n{outs[m]}"
        )
    # The founders must have seen (and waited for) the joiner; the joiner's
    # own exit-time view may no longer list the founders — they are allowed
    # to exit as soon as everyone's FINAL state is published.
    with open(os.path.join(str(tmp_path), "final-w0.json")) as f:
        assert "w2" in json.load(f)["alive"]


def test_real_process_crash_recovery_delta_gossip(tmp_path):
    """The crash drill with --delta: chained delta publishes + full
    anchors carry the gossip; recovery and convergence must be identical."""
    rcs, outs = _run_drill(
        tmp_path,
        (("w0", ["--delta"]), ("w1", ["--delta", "--die-at", "4"]),
         ("w2", ["--delta"])),
        3, "topk_rmv",
    )
    assert rcs["w1"] == 1
    ref = [list(t) for t in _drill_reference("topk_rmv")]
    for m in ("w0", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged (delta mode)\ngot: {got['digest']}\nref: {ref}\n"
            f"log:\n{outs[m]}"
        )
    # Delta files were actually exchanged (not just full anchors).
    assert any(
        f.startswith("delta-") for f in os.listdir(str(tmp_path))
    ), os.listdir(str(tmp_path))


@pytest.mark.parametrize("type_name", ["average", "wordcount"])
def test_real_process_crash_recovery_monoid(tmp_path, type_name):
    """The MONOID half of the host delivery contract
    (antidote_ccrdt.erl:47-59 replicates without type distinction): both
    monoid types ride the versioned-row lift through the SAME crash drill
    the JOIN flagship runs — w1 dies at step 4, survivors adopt its rows
    by regenerating history into their own contribution state, and
    converge to the exact sequential totals (any double count is a
    digest diff)."""
    rcs, outs = _run_drill(
        tmp_path,
        (("w0", []), ("w1", ["--die-at", "4"]), ("w2", [])),
        3, type_name,
    )
    assert rcs["w1"] == 1, f"victim should crash:\n{outs['w1']}"
    ref = _drill_reference(type_name)
    for m in ("w0", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged (monoid {type_name})\ngot: {got['digest']}\n"
            f"ref: {ref}\nlog:\n{outs[m]}"
        )
        assert "w1" not in got["alive"]


@pytest.mark.parametrize("type_name", ["average", "wordcount"])
def test_real_process_late_joiner_monoid_delta(tmp_path, type_name):
    """Scale-up elasticity + row-replace delta gossip for both MONOID
    engines: a member joins ~1s in, ownership rebalances onto it,
    deltas (self-contained whole-row payloads) carry the anti-entropy,
    and every member converges to the exact sequential counts."""
    rcs, outs = _run_drill(
        tmp_path,
        (("w0", ["--delta"]), ("w1", ["--delta"]),
         ("w2", ["--join-late", "1.0", "--delta"])),
        2, type_name,
    )
    ref = _drill_reference(type_name)
    for m in ("w0", "w1", "w2"):
        assert rcs[m] == 0, f"worker {m} failed:\n{outs[m]}"
        with open(os.path.join(str(tmp_path), f"final-{m}.json")) as f:
            got = json.load(f)
        assert got["digest"] == ref, (
            f"{m} diverged (monoid {type_name} delta)\ngot: {got['digest']}\n"
            f"ref: {ref}\nlog:\n{outs[m]}"
        )
    assert any(
        f.startswith("delta-") for f in os.listdir(str(tmp_path))
    ), os.listdir(str(tmp_path))


def test_ownership_grows_covers_every_step_under_view_flaps():
    """The invariant behind the scale-up fix, modeled as the drill
    implements it: per-member views may disagree arbitrarily while
    membership churns, ownership only GROWS, and a member that gains a
    replica retroactively re-applies its whole history. Then, as soon as
    views stabilize to a common alive set for the tail of the run, every
    (replica, step) op has been applied by someone. The drop-on-view-change
    variant (the original bug) loses trailing steps under asymmetric views
    even WITH stabilization."""
    import numpy as np

    rng = np.random.default_rng(0)
    R_, STEPS_, STABLE_TAIL = 6, 12, 3
    members = ["a", "b", "c"]
    full = {(r, s) for r in range(R_) for s in range(STEPS_)}
    drop_ever_lost = False
    for _trial in range(200):
        applied = set()
        applied_drop = set()
        for m in members:
            owned: set = set()
            for s in range(STEPS_):
                if s < STEPS_ - STABLE_TAIL:
                    view = sorted({m} | {x for x in members if rng.random() < 0.7})
                else:
                    view = members  # heartbeats settled: common view
                mine = {r for r in range(R_) if view[r % len(view)] == m}
                gained = mine - owned
                owned |= mine  # ownership only grows
                # retroactive full-history re-apply on gain:
                applied |= {(r, t) for r in gained for t in range(s)}
                applied |= {(r, s) for r in owned}
                applied_drop |= {(r, t) for r in gained for t in range(s)}
                applied_drop |= {(r, s) for r in mine}  # buggy: drops
        assert applied == full, "ownership-grows lost coverage"
        drop_ever_lost = drop_ever_lost or (applied_drop != full)
    assert drop_ever_lost, (
        "chaos schedule never exercised the drop-variant hazard — weaken "
        "the view-flap probability so the test stays meaningful"
    )
