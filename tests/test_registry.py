"""Registry parity: is_type / generates_extra_operations / dense factories.

Mirrors ``antidote_ccrdt.erl``: the type whitelist (:28-35), ``is_type/1``
(:61-62) and ``generates_extra_operations/1`` (:37-40, :64-65) — extended
with the dense (TPU) level, which every type must also expose.
"""

import jax.numpy as jnp
import pytest

import antidote_ccrdt_tpu as ccrdt
from antidote_ccrdt_tpu.core.behaviour import registry

ALL_TYPES = [
    "average",
    "topk",
    "topk_rmv",
    "leaderboard",
    "wordcount",
    "worddocumentcount",
]

DENSE_PARAMS = {
    "average": {},
    "topk": {"n_ids": 64, "size": 8},
    "topk_rmv": {"n_ids": 64, "n_dcs": 4, "size": 8, "slots_per_id": 2},
    "leaderboard": {"n_players": 64, "size": 8},
    "wordcount": {"n_buckets": 128},
    "worddocumentcount": {"n_buckets": 128},
}


def test_is_type_whitelist():
    for name in ALL_TYPES:
        assert ccrdt.is_type(name)
    assert not ccrdt.is_type("riak_dt_gcounter")
    assert not ccrdt.is_type(None)
    assert not ccrdt.is_type(("topk",))


def test_generates_extra_operations():
    # antidote_ccrdt.erl:37-40: exactly topk_rmv and leaderboard.
    assert ccrdt.generates_extra_operations("topk_rmv")
    assert ccrdt.generates_extra_operations("leaderboard")
    for name in ("average", "topk", "wordcount", "worddocumentcount"):
        assert not ccrdt.generates_extra_operations(name)
    assert not ccrdt.generates_extra_operations("nope")


@pytest.mark.parametrize("name", ALL_TYPES)
def test_every_type_has_scalar_and_dense(name):
    import jax
    import numpy as np

    scalar = registry.scalar(name)
    assert scalar.type_name == name
    dense = registry.make_dense(name, **DENSE_PARAMS[name])
    assert hasattr(dense, "merge_kind")
    state = dense.init(n_replicas=2, n_keys=1)
    # Fresh states must merge to a fresh state under either algebra: JOIN
    # is idempotent on equal states, and fresh MONOID deltas are zeros.
    merged = dense.merge(state, state)
    for leaf_a, leaf_b in zip(jax.tree.leaves(state), jax.tree.leaves(merged)):
        np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))


def test_dense_types_lists_all():
    assert set(ALL_TYPES) <= set(registry.dense_types())
