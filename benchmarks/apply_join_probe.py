"""Does the merge path's union join help the APPLY round too?

Measured verdict (v5e, north-star shapes, round 4): YES — 56.4 -> 51.7
ms/round (~8%), end-state array-equal; the union join became the
production join for BOTH hot paths on the strength of this probe.

The apply round's join is different from merge: the delta side is
sparse (most ids empty), and the pairwise join's prefix-count rank was
originally chosen for it. Since production now runs the union join,
this probe reproduces the comparison by patching `_join_slots_union`
BACK to the pairwise reference `_join_slots` for the baseline arm —
same scan-fused window methodology as bench.py.

Run: python benchmarks/apply_join_probe.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import antidote_ccrdt_tpu.models.topk_rmv_dense as trd
from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.utils.benchtime import stack_rounds, sync

R, NK, I, D_DCS, K, M = 32, 1, 100_000, 32, 100, 4
B, Br, W = 32768, 2048, 8


def build():
    D = trd.make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    state = D.init(n_replicas=R, n_keys=1)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
    )
    batches = [
        stack_rounds([gen.next_batch(B, Br) for _ in range(W)])
        for _ in range(3)
    ]
    return D, state, batches


def time_window(D, state, batches):
    @jax.jit
    def run_window(state, stacked):
        def body(st, ops):
            st2, _ = D.apply_ops(st, ops, collect_dominated=False)
            return st2, ()
        out, _ = lax.scan(body, state, stacked)
        return out

    state = run_window(state, batches[0])
    sync(state)
    best = []
    for b in batches[1:]:
        t0 = time.perf_counter()
        state = run_window(state, b)
        sync(state)
        best.append((time.perf_counter() - t0) / W * 1e3)
    return min(best), state


def main():
    print(f"# backend={jax.default_backend()} B={B} Br={Br} W={W}")
    # Baseline arm: production engine with the union join patched back to
    # the pairwise reference join (production calls _join_slots_union
    # directly since round 4 — patching the OTHER direction would time
    # the union join against itself).
    orig = trd._join_slots_union
    trd._join_slots_union = lambda a, b, rmv_vc, m: trd._join_slots(
        a, b, rmv_vc, m
    )
    try:
        D, state, batches = build()
        pairwise_ms, s1 = time_window(D, state, batches)
    finally:
        trd._join_slots_union = orig
    print(f"apply round, pairwise reference join  {pairwise_ms:8.2f} ms")

    D2, state2, _ = build()  # fresh engine -> fresh jit cache entry
    union_ms, s2 = time_window(D2, state2, batches)
    print(f"apply round, union join (production)  {union_ms:8.2f} ms")

    eq = all(
        bool(jnp.array_equal(x, y))
        for x, y in zip(jax.tree.leaves(s1), jax.tree.leaves(s2))
    )
    print(f"# end-state equivalence: {'OK' if eq else 'MISMATCH'}")
    assert eq
    print(f"# delta (union - pairwise): {union_ms - pairwise_ms:+.2f} ms/round")


if __name__ == "__main__":
    main()
