"""Ablation timing of the topk_rmv apply round at NORTH-STAR bench shapes.

Unlike profile_topk_rmv_pieces.py (which times pieces in isolation at
B=4096), this measures the FULL apply with one piece removed at a time.
Because XLA fuses across pieces, removal deltas are the honest attribution
of round time. Shapes are B=16384/Br=1024 — the operating point where the
kernel-choice attributions recorded in the model docstrings were taken;
bench.py's default batch has since moved to B=32768/Br=2048, so scale
attributions accordingly (B-linear pieces roughly double).

Same measurement discipline: scan-fused windows, host-readback sync.

The inline variants track production: since round 4 they join through
`_join_slots_union` (the adopted production join on both hot paths —
benchmarks/apply_join_probe.py), so removal deltas ablate the kernel
that actually runs.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _join_slots_union,
    _sort_adds,
    make_dense,
)

R, NK, I, D_DCS, K, M = 32, 1, 100_000, 32, 100, 4
# Batch shapes overridable from the env: the docstring attributions were
# taken at B=16384/Br=1024; bench.py's north star is B=32768/Br=2048
# (ABLATE_B=32768 ABLATE_BR=2048 reproduces the compute-block numbers).
B = int(os.environ.get("ABLATE_B", 16384))
Br = int(os.environ.get("ABLATE_BR", 1024))
REPS = int(os.environ.get("ABLATE_REPS", 12))
D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
state0 = D.init(n_replicas=R, n_keys=1)
gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7))
warm = gen.next_batch(B, Br)
state0, _ = D.apply_ops(state0, warm, collect_dominated=False)
stacked = jax.tree.map(
    lambda *xs: jnp.stack(xs), *[gen.next_batch(B, Br) for _ in range(REPS)]
)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


SELECT = sys.argv[1:]  # substring filters; empty = run all


def timeit(name, step_fn):
    if SELECT and not any(s in name for s in SELECT):
        return None

    @jax.jit
    def run(c, seq):
        def body(c, ops):
            return step_fn(c, ops), ()
        out, _ = lax.scan(body, c, seq)
        return out

    sync(run(state0, stacked))
    t0 = time.perf_counter()
    out = run(state0, stacked)
    sync(out)
    print(f"{name:56s} {(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")
    return out


def make_variant(
    tombstones=True, vc_track=True, delta=True, join=True, scatter_fields=3
):
    from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu

    def one(state, ops):
        NKl, Il, Ml, Dl = NK, I, M, D_DCS
        if tombstones:
            rmv_valid = ops.rmv_id >= 0
            rrow = jnp.where(rmv_valid, ops.rmv_key * Il + ops.rmv_id, NKl * Il)
            rmv_vc = scatter_max_rows_mxu(
                state.rmv_vc.reshape(NKl * Il, Dl), rrow, ops.rmv_vc
            ).reshape(NKl, Il, Dl)
        else:
            rmv_vc = state.rmv_vc

        if vc_track:
            add_valid = (
                (ops.add_ts > 0)
                & (ops.add_key >= 0) & (ops.add_key < NKl)
                & (ops.add_dc >= 0) & (ops.add_dc < Dl)
            )
            slot = ops.add_key * Dl + ops.add_dc
            hit = slot[:, None] == jnp.arange(NKl * Dl, dtype=slot.dtype)[None, :]
            contrib = jnp.where(hit & add_valid[:, None], ops.add_ts[:, None], 0)
            vc = jnp.maximum(state.vc, jnp.max(contrib, axis=0).reshape(NKl, Dl))
        else:
            vc = state.vc

        d_score = jnp.full((NKl, Il, Ml), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NKl, Il, Ml), dtype=jnp.int32)
        d_ts = jnp.zeros((NKl, Il, Ml), dtype=jnp.int32)
        if delta:
            sk = jnp.where(ops.add_ts > 0, ops.add_key, NKl)
            (s_key, s_id, _, _), (s_score, s_ts, s_dc) = _sort_adds(
                sk, ops.add_id, ops.add_score, ops.add_ts, ops.add_dc
            )
            dup = (
                (s_key == jnp.roll(s_key, 1))
                & (s_id == jnp.roll(s_id, 1))
                & (s_score == jnp.roll(s_score, 1))
                & (s_ts == jnp.roll(s_ts, 1))
                & (s_dc == jnp.roll(s_dc, 1))
            )
            dup = dup.at[0].set(False)
            live = (s_key < NKl) & ~dup
            grp_start = (
                (s_key != jnp.roll(s_key, 1)) | (s_id != jnp.roll(s_id, 1))
            ).at[0].set(True)
            c = jnp.cumsum(live.astype(jnp.int32))
            base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
            rank = c - live.astype(jnp.int32) - base
            rank = jnp.where(live & (rank < Ml), rank, Ml)
            sk3 = jnp.where(live, s_key, NKl)
            if scatter_fields >= 1:
                d_score = d_score.at[sk3, s_id, rank].set(s_score, mode="drop")
            if scatter_fields >= 2:
                d_dc = d_dc.at[sk3, s_id, rank].set(s_dc, mode="drop")
            if scatter_fields >= 3:
                d_ts = d_ts.at[sk3, s_id, rank].set(s_ts, mode="drop")

        if join:
            f_score, f_dc, f_ts, n_live = _join_slots_union(
                (state.slot_score, state.slot_dc, state.slot_ts),
                (d_score, d_dc, d_ts),
                rmv_vc,
                Ml,
            )
            lossy = state.lossy | jnp.any(n_live > Ml, axis=-1)
        else:
            # keep everything live so no piece is dead-code-eliminated
            f_score = jnp.maximum(state.slot_score, d_score)
            f_dc = jnp.maximum(state.slot_dc, d_dc)
            f_ts = jnp.maximum(state.slot_ts, d_ts)
            lossy = state.lossy
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)

    def step(st, ops):
        return jax.vmap(one)(st, ops)

    return step


def current(st, ops):
    s, _ = D.apply_ops(st, ops, collect_dominated=False)
    return s


timeit("FULL apply_ops (current code)", current)
timeit("variant: full re-impl (sanity, ~= current)", make_variant())
timeit("  - tombstone MXU scatter", make_variant(tombstones=False))
timeit("  - vc one-hot tracking", make_variant(vc_track=False))
timeit("  - delta build entirely (sort+rank+scatter)", make_variant(delta=False))
timeit("  - 2 of 3 delta scatters", make_variant(scatter_fields=1))
timeit("  - join (elementwise max instead)", make_variant(join=False))

timeit("tombstones ONLY (XLA path, + slot max)",
       make_variant(vc_track=False, delta=False, join=False))


def make_pallas_tomb():
    from antidote_ccrdt_tpu.ops.pallas_kernels import scatter_max_rows_onehot_pallas

    def step(state, ops):
        rmv_valid = ops.rmv_id >= 0
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        R_ = state.rmv_vc.shape[0]
        rmv_vc = scatter_max_rows_onehot_pallas(
            state.rmv_vc.reshape(R_, NK * I, D_DCS), rrow, ops.rmv_vc
        ).reshape(R_, NK, I, D_DCS)
        f_score = jnp.maximum(state.slot_score, ops.add_score[:, None, :M].reshape(R_, NK, 1, M) * 0 + state.slot_score)
        return TopkRmvDenseState(f_score, state.slot_dc, state.slot_ts, rmv_vc, state.vc, state.lossy)

    return step


timeit("tombstones ONLY (pallas, + slot max)", make_pallas_tomb())


def make_hoisted(use_pallas):
    from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu
    from antidote_ccrdt_tpu.ops.pallas_kernels import scatter_max_rows_onehot_pallas

    def step(state, ops):
        R_ = state.rmv_vc.shape[0]
        rmv_valid = ops.rmv_id >= 0
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        tab = state.rmv_vc.reshape(R_, NK * I, D_DCS)
        if use_pallas:
            out = scatter_max_rows_onehot_pallas(tab, rrow, ops.rmv_vc)
        else:
            out = jax.vmap(scatter_max_rows_mxu)(tab, rrow, ops.rmv_vc)
        rmv_vc_new = out.reshape(R_, NK, I, D_DCS)
        return jax.vmap(D._apply_one_replica)(state, ops, rmv_vc_new)[0]

    return step


timeit("hoisted XLA tombstones + vmap apply", make_hoisted(False))
timeit("hoisted PALLAS tombstones + vmap apply", make_hoisted(True))


def step_identity_tomb(state, ops):
    # rmv_vc passed through untouched: isolates the cost of CONSUMING a
    # materialized table in the join vs a fused producer.
    return jax.vmap(D._apply_one_replica)(state, ops, state.rmv_vc)[0]


timeit("vmap apply, identity (materialized) tombstones", step_identity_tomb)


def timeit_unrolled(name, step_fn):
    if SELECT and not any(s in name for s in SELECT):
        return None

    @jax.jit
    def run(c, seq):
        for i in range(REPS):
            c = step_fn(c, jax.tree.map(lambda x: x[i], seq))
        return c

    try:
        sync(run(state0, stacked))
        t0 = time.perf_counter()
        out = run(state0, stacked)
        sync(out)
    except jax.errors.JaxRuntimeError as e:
        # Known at ABLATE_B=32768 since the union join: the unrolled
        # graph keeps every iteration's [R, NK*I, 5D] conv output alive
        # as remat temps (9 x 1.91G measured) and exceeds HBM. Only the
        # runtime/compile error is tolerated — real code breakage in the
        # variants must still fail loudly. The scan-fused variants above
        # are the load-bearing measurements.
        first = (str(e).splitlines() or ["<no message>"])[0][:80]
        print(f"{name:56s}    SKIPPED ({type(e).__name__}: {first})")
        return None
    print(f"{name:56s} {(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")
    return out


timeit_unrolled("UNROLLED hoisted PALLAS tombstones + vmap apply", make_hoisted(True))
timeit_unrolled("UNROLLED full re-impl XLA", make_variant())


def make_flat_scatter_variant():
    """Delta scatter via flat 1-D indices (kid*M + rank) instead of 2-D."""
    def one(state, ops):
        NKl, Il, Ml, Dl = NK, I, M, D_DCS
        from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu
        rmv_valid = ops.rmv_id >= 0
        rrow = jnp.where(rmv_valid, ops.rmv_key * Il + ops.rmv_id, NKl * Il)
        rmv_vc = scatter_max_rows_mxu(
            state.rmv_vc.reshape(NKl * Il, Dl), rrow, ops.rmv_vc
        ).reshape(NKl, Il, Dl)
        add_valid = (ops.add_ts > 0) & (ops.add_key >= 0) & (ops.add_key < NKl)
        kid = jnp.where(add_valid, ops.add_key * Il + ops.add_id, NKl * Il)
        s_kid, ns, nt, s_dc = lax.sort(
            (kid, -ops.add_score, -ops.add_ts, ops.add_dc), num_keys=4)
        s_score, s_ts = -ns, -nt
        dup = ((s_kid == jnp.roll(s_kid, 1)) & (s_score == jnp.roll(s_score, 1))
               & (s_ts == jnp.roll(s_ts, 1)) & (s_dc == jnp.roll(s_dc, 1))).at[0].set(False)
        live = (s_kid < NKl * Il) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
        rank = c - live.astype(jnp.int32) - base
        rank = jnp.where(live & (rank < Ml), rank, Ml)
        flat = jnp.where(live & (rank < Ml), s_kid * Ml + rank, NKl * Il * Ml)
        d_score = jnp.full((NKl * Il * Ml,), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NKl * Il * Ml,), dtype=jnp.int32)
        d_ts = jnp.zeros((NKl * Il * Ml,), dtype=jnp.int32)
        d_score = d_score.at[flat].set(s_score, mode="drop").reshape(NKl, Il, Ml)
        d_dc = d_dc.at[flat].set(s_dc, mode="drop").reshape(NKl, Il, Ml)
        d_ts = d_ts.at[flat].set(s_ts, mode="drop").reshape(NKl, Il, Ml)
        f_score, f_dc, f_ts, n_live = _join_slots_union(
            (state.slot_score, state.slot_dc, state.slot_ts),
            (d_score, d_dc, d_ts), rmv_vc, Ml)
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, state.vc,
                                 state.lossy | jnp.any(n_live > Ml, axis=-1))
    def step(st, ops):
        return jax.vmap(one)(st, ops)
    return step


timeit("FLAT 1-D delta scatter variant", make_flat_scatter_variant())
