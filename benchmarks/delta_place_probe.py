"""Round-5 delta-placement probe: the 15.4ms scatter slice, attacked again.

VERDICT-r4 item 1 names the 3 delta scalar scatters (~15.4ms of the
~53.5ms apply round at north-star shapes) as the largest remaining slice
and asks for structural attempts beyond the hint-level probes already
rejected (residual_probe.py, delta_probe.py). Variants here:

  * scatter3 (production)  — baseline: vmapped 3x scalar 2-D scatter.
  * scatter3_flat_replica  — the SAME writes as ONE un-vmapped scatter
    with replica-global indices [R*B]: tests whether the vmap batching
    dimension (not the writes) is what XLA serializes.
  * scatter1_concat3       — all three fields through ONE scatter call
    into a [3*NK*I, M] table (indices offset per field): tests per-call
    vs per-element cost at the exact r5 shapes.
  * scatter3_hinted        — indices_are_sorted + unique_indices on the
    production formulation (r2 tested these on an older path; re-pinned
    here at the exact current shapes).
  * pallas_carry_walk      — the structural rewrite: compaction-sort the
    kept entries by output address o = kid*M + rank (o is unique and
    strictly increasing over kept entries, so each 128-address output
    block is served by <= 128 CONSECUTIVE stream entries), then a Mosaic
    kernel walks the stream with a carried offset per replica: per
    128-address sub-block, one [128, 128] iota-compare one-hot and one
    s8 MXU matmul against the 11 seven-bit value planes (score 5 planes
    u32-wrapped against the NEG_INF background, ts 5, dc 1) — placement
    with zero data-dependent gathers and zero serialized scatter loops
    (ops/delta_place.py).

Timing discipline: scan-fused REPS with the shared sort included
(identical across variants, so deltas isolate the build), host-readback
sync (utils/benchtime).

VERDICT (measured v5e, tunneled backend, REPS=12, all equivalence-OK;
sort included in every number, so deltas isolate the build step):

    scatter3 (production r4)        28.1  ms/round
    scatter3_hinted                 21.9  ms/round  (UNSOUND - see below)
    scatter3_unique                 24.3  ms/round  <- production r5
    scatter3_flat_replica           32.7  ms/round  (rejected)
    scatter1_concat3                32.2  ms/round  (rejected)
    pallas_carry_walk               57.2  ms/round  (rejected)

* The r2 "hints neutral" result does NOT hold on the current kid-packed
  path: hints move the build. But indices_are_sorted's promise is FALSE
  here — duplicate-delivery ops keep their sentinel row mid-stream — so
  the 21.9 number is an implementation-defined upper bound, not a
  candidate. unique_indices alone (made formally true via per-position
  dropped columns) is sound and takes -3.8ms/round.
* The carry-walk kernel is correct first-compile (equivalence OK at
  full north-star shapes) but 2x SLOWER than the scatters: its
  per-sub-block work is 4 tiny (256-entry) dynamic VMEM loads + one
  [128,256] one-hot + a small s8 dot — ~3,125 sub-blocks x 32 replicas
  = ~400k tiny dynamic loads per round, each ~0.1-0.2us under Mosaic,
  plus an SMEM carry that serializes consecutive grid steps (no block
  pipelining). The structure is load-latency-bound, not flop-bound;
  growing GROUP only converges to ~14-16ms of fixed per-sub-block cost.
  This also prices the same pattern out for the tombstone one-hot conv
  (T/4096 x 32 steps of identical shape — est. ~15ms vs the 11.2ms XLA
  conv it would replace). Kernel kept in ops/delta_place.py as verified
  infrastructure; the XLA unique-hint scatters stay production.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import NEG_INF
from antidote_ccrdt_tpu.utils.benchtime import stack_rounds

R, NK, I, D_DCS, M = 32, 1, 100_000, 32, 4
B, Br = 32768, 2048
REPS = int(os.environ.get("DELTA_REPS", 12))

gen = TopkRmvEffectGen(
    Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
)
stacked = stack_rounds([gen.next_batch(B, Br) for _ in range(REPS)])
one = jax.tree.map(lambda x: x[0], stacked)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def sorted_adds(ops):
    """The shared sort + rank stage (verbatim semantics of
    _apply_one_replica steps 3a-3c), vmapped over replicas."""
    def per_replica(key, id_, score, ts, dc):
        add_valid = (
            (ts > 0)
            & (key >= 0) & (key < NK)
            & (id_ >= 0) & (id_ < I)
            & (dc >= 0) & (dc < D_DCS)
        )
        kid = jnp.where(add_valid, key * I + id_, NK * I)
        s_kid, ns, nt, s_dc = lax.sort((kid, -score, -ts, dc), num_keys=4)
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(
            jnp.where(grp_start, c - live.astype(jnp.int32), -1)
        )
        rank = c - live.astype(jnp.int32) - base
        keep = live & (rank < M)
        rank = jnp.where(keep, rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)
        return s_score, s_ts, s_dc, kid3, rank, keep

    return jax.vmap(per_replica)(
        ops.add_key, ops.add_id, ops.add_score, ops.add_ts, ops.add_dc
    )


def scatter3(s_score, s_ts, s_dc, kid3, rank, keep):
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_score = d_score.at[kid3, rank].set(s_score, mode="drop")
        d_dc = d_dc.at[kid3, rank].set(s_dc, mode="drop")
        d_ts = d_ts.at[kid3, rank].set(s_ts, mode="drop")
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def scatter3_flat_replica(s_score, s_ts, s_dc, kid3, rank, keep):
    """Same writes, one un-vmapped scatter per field with replica-global
    row indices: [R*B] scalar writes into [R*(NK*I+1), M]."""
    T1 = NK * I + 1  # per-replica sentinel row rides along
    Rl = kid3.shape[0]
    roff = jnp.arange(Rl, dtype=jnp.int32)[:, None] * T1
    rows = (kid3 + roff).ravel()
    cols = rank.ravel()

    def place(vals, empty):
        d = jnp.full((Rl * T1, M), empty, dtype=jnp.int32)
        d = d.at[rows, cols].set(vals.ravel(), mode="drop")
        return d.reshape(Rl, T1, M)[:, : NK * I]

    return place(s_score, NEG_INF), place(s_dc, 0), place(s_ts, 0)


def scatter1_concat3(s_score, s_ts, s_dc, kid3, rank, keep):
    """All three fields in ONE scatter call into a [3*(NK*I+1), M] table
    (per-replica under vmap): tests per-call vs per-element cost."""
    T1 = NK * I + 1

    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        d = jnp.concatenate(
            [
                jnp.full((T1, M), NEG_INF, dtype=jnp.int32),
                jnp.zeros((T1, M), dtype=jnp.int32),
                jnp.zeros((T1, M), dtype=jnp.int32),
            ]
        )
        rows = jnp.concatenate([kid3, kid3 + T1, kid3 + 2 * T1])
        cols = jnp.concatenate([rank, rank, rank])
        vals = jnp.concatenate([s_score, s_dc, s_ts])
        d = d.at[rows, cols].set(vals, mode="drop")
        return (
            d[: NK * I],
            d[T1 : T1 + NK * I],
            d[2 * T1 : 2 * T1 + NK * I],
        )

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def scatter3_hinted(s_score, s_ts, s_dc, kid3, rank, keep):
    """Both hints. NOT production: the sorted promise is false (duplicate
    ops keep their sentinel row mid-stream) — kept as the measured upper
    bound the sound variant below is compared against."""
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        kw = dict(mode="drop", indices_are_sorted=True, unique_indices=True)
        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_score = d_score.at[kid3, rank].set(s_score, **kw)
        d_dc = d_dc.at[kid3, rank].set(s_dc, **kw)
        d_ts = d_ts.at[kid3, rank].set(s_ts, **kw)
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def scatter3_unique(s_score, s_ts, s_dc, kid3, rank, keep):
    """PRODUCTION (round 5): unique_indices only, made formally true by
    giving every dropped entry a distinct out-of-range column."""
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        kw = dict(mode="drop", unique_indices=True)
        rank3 = jnp.where(
            keep, rank, M + jnp.arange(rank.shape[0], dtype=jnp.int32)
        )
        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_score = d_score.at[kid3, rank3].set(s_score, **kw)
        d_dc = d_dc.at[kid3, rank3].set(s_dc, **kw)
        d_ts = d_ts.at[kid3, rank3].set(s_ts, **kw)
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


VARIANTS = {
    "scatter3 (production)": scatter3,
    "scatter3_flat_replica": scatter3_flat_replica,
    "scatter1_concat3": scatter1_concat3,
    "scatter3_hinted": scatter3_hinted,
    "scatter3_unique": scatter3_unique,
}

try:
    from antidote_ccrdt_tpu.ops.delta_place import delta_place_pallas

    def pallas_carry_walk(s_score, s_ts, s_dc, kid3, rank, keep):
        return delta_place_pallas(
            s_score, s_ts, s_dc, kid3, rank, keep, NK * I, M, D_DCS
        )

    VARIANTS["pallas_carry_walk"] = pallas_carry_walk
except ImportError:
    pass


def main():
    print(f"# backend={jax.default_backend()} R={R} B={B} REPS={REPS}")
    sel = sys.argv[1:]
    results = {}

    srt = jax.tree.map(lambda x: x[:1], sorted_adds(one))
    want = scatter3(*srt)
    for name, fn in VARIANTS.items():
        if name == "scatter3 (production)":
            continue
        if sel and not any(s in name for s in sel):
            continue
        got = fn(*srt)
        ok = all(bool(jnp.array_equal(g, w)) for g, w in zip(got, want))
        print(f"# equivalence {name}: {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    for name, fn in VARIANTS.items():
        if sel and not any(s in name for s in sel):
            continue

        @jax.jit
        def run(stacked, fn=fn):
            def body(carry, ops):
                srt = sorted_adds(ops)
                ds, dd, dt = fn(*srt)
                return carry + jnp.sum(ds) + jnp.sum(dd) + jnp.sum(dt), ()
            out, _ = lax.scan(body, jnp.zeros((), jnp.int32), stacked)
            return out

        sync(run(stacked))
        t0 = time.perf_counter()
        sync(run(stacked))
        ms = (time.perf_counter() - t0) / REPS * 1e3
        results[name] = round(ms, 3)
        print(f"{name:32s} {ms:9.3f} ms/round (sort included)", flush=True)

    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "delta_place_results.json"
    )
    with open(out_path, "w") as f:
        json.dump(
            {"backend": jax.default_backend(), "R": R, "B": B,
             "reps": REPS, "ms_per_round_sort_included": results},
            f, indent=1,
        )
    print(f"# wrote {out_path}")


if __name__ == "__main__":
    main()