"""Micro-bench: tombstone scatter-max kernel variants at bench shapes."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from antidote_ccrdt_tpu.ops import dense_table
from antidote_ccrdt_tpu.ops.pallas_kernels import scatter_max_rows_onehot_pallas

R, T, D, Br, REPS = 32, 100_000, 32, 1024, 10
rng = np.random.default_rng(0)
table0 = jnp.asarray(rng.integers(0, 1000, (R, T, D)).astype(np.int32))
rows_seq = jnp.asarray(rng.integers(0, T, (REPS, R, Br)).astype(np.int32))
upd_seq = jnp.asarray(rng.integers(0, 100_000, (REPS, R, Br, D)).astype(np.int32))


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def timeit(name, fn):
    @jax.jit
    def run(tab, rows, upds):
        def body(t, ru):
            r, u = ru
            return fn(t, r, u), ()
        out, _ = lax.scan(body, tab, (rows, upds))
        return out

    sync(run(table0, rows_seq, upd_seq))
    t0 = time.perf_counter()
    out = run(table0, rows_seq, upd_seq)
    sync(out)
    print(f"{name:56s} {(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")
    return out


timeit("XLA one-hot MXU (current prod)",
       lambda t, r, u: jax.vmap(dense_table.scatter_max_rows_mxu)(t, r, u))
timeit("pallas s8 tiled one-hot",
       lambda t, r, u: scatter_max_rows_onehot_pallas(t, r, u))


# bf16 variant of the pallas kernel, defined inline for comparison
def _kern_bf16(G, n_planes, D, Tt, rows_ref, planes_ref, tab_ref, out_ref):
    rows = rows_ref[0, 0]
    base = pl.program_id(1) * Tt
    local = (rows // G) - base
    ohT = (
        jax.lax.broadcasted_iota(jnp.int32, (Tt, rows.shape[0]), 0)
        == local[None, :]
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        ohT, planes_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = acc.astype(jnp.int32)
    PD = n_planes * D
    cols = []
    for g in range(G):
        col = jnp.zeros((Tt, D), jnp.int32)
        for k in range(n_planes):
            col = col | (acc[:, g * PD + k * D : g * PD + (k + 1) * D] << (7 * k))
        cols.append(col)
    out_ref[0] = jnp.maximum(tab_ref[0], jnp.concatenate(cols, axis=-1))


@jax.jit
def pallas_bf16(table, rows, upd):
    G, n_planes = 4, 5
    T4 = T // G
    Tt = 1000
    head_rows, total = jax.vmap(
        functools.partial(dense_table.dedup_rows_run_max, n_rows=T)
    )(rows, upd)
    g_of = (head_rows % G)[..., None]
    planes = jnp.concatenate(
        [((total >> (7 * k)) & 0x7F).astype(jnp.bfloat16) for k in range(n_planes)],
        axis=-1,
    )
    gsel = g_of == jnp.arange(G, dtype=jnp.int32)[None, None, :]
    planes_wide = jnp.where(
        gsel[..., :, None], planes[..., None, :], jnp.bfloat16(0)
    ).reshape(R, Br, G * n_planes * D)
    tab4 = table.reshape(R, T4, G * D)
    out4 = pl.pallas_call(
        functools.partial(_kern_bf16, G, n_planes, D, Tt),
        grid=(R, T4 // Tt),
        in_specs=[
            pl.BlockSpec((1, 1, Br), lambda r, t: (r, 0, 0)),
            pl.BlockSpec((1, Br, G * n_planes * D), lambda r, t: (r, 0, 0)),
            pl.BlockSpec((1, Tt, G * D), lambda r, t: (r, t, 0)),
        ],
        out_specs=pl.BlockSpec((1, Tt, G * D), lambda r, t: (r, t, 0)),
        out_shape=jax.ShapeDtypeStruct((R, T4, G * D), jnp.int32),
    )(head_rows[:, None, :], planes_wide, tab4)
    return out4.reshape(R, T, D)


timeit("pallas bf16 tiled one-hot", pallas_bf16)

# correctness cross-check (one step)
a = jax.vmap(dense_table.scatter_max_rows_mxu)(table0, rows_seq[0], upd_seq[0])
b = scatter_max_rows_onehot_pallas(table0, rows_seq[0], upd_seq[0])
c = pallas_bf16(table0, rows_seq[0], upd_seq[0])
print("s8 kernel matches XLA:", bool(jnp.all(a == b)))
print("bf16 kernel matches XLA:", bool(jnp.all(a == c)))
