"""XLA device-timeline profile of ONE north-star apply window (VERDICT-r3
item 1): name every HLO slice inside the round, especially the ~25ms
"residual_fusion" the round-3 removal-ablation attribution could not
assign to any piece.

Method: capture a `jax.profiler` trace around one scan-fused window
(W rounds of `TopkRmvDense.apply_ops` at bench.py's north-star shapes),
then aggregate the DEVICE-side trace events (the TPU timeline comes
through the tunneled backend — verified: fusion-level events appear under
the /device:TPU pid) by HLO op name, divide by W, and map each fusion
name to its computation body from the compiled HLO text so every slice
has a human-readable "what it computes".

Outputs:
  benchmarks/profile_r05.json  — per-slice table (ms/round, share, body)
  stdout                       — the same table, human-readable

Env knobs: PROF_B / PROF_BR / PROF_W (default north-star 32768/2048/10),
PROF_EXTRAS=table to profile the extras-on configuration.

CAVEAT discovered round 5: on this tunneled AOT backend the "device
timeline" is a DETERMINISTIC MODELED schedule, not measured hardware
events — the r4 and r5 captures (different sessions, different compiled
code after the scatter-hint change) reproduce slice times to +-0.001ms,
which real silicon cannot do. The table is therefore trustworthy for
STRUCTURE (which fusions exist, their relative cost model, what each
computes) but blind to runtime-only effects: the r5 unique-indices
scatter hint measurably moves wall-clock (benchmarks/delta_place_probe
-3.8ms isolated; bench.py p50 ~53.5 -> ~51-53 across sessions) while
leaving this modeled timeline byte-stable. Treat removal-delta
ablations + host-synced wall clock (ablate_apply.py, bench.py) as
ground truth for magnitudes; use this artifact to NAME the slices.
(The `while` wrapper line is the scan body measured inclusively — it
approximates the whole round and double-counts its children.)
"""

import collections
import gzip
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
from antidote_ccrdt_tpu.utils.benchtime import stack_rounds, sync

R, NK, I, D_DCS, K, M = 32, 1, 100_000, 32, 100, 4
B = int(os.environ.get("PROF_B", 32768))
Br = int(os.environ.get("PROF_BR", 2048))
W = int(os.environ.get("PROF_W", 10))
EXTRAS = os.environ.get("PROF_EXTRAS", "")  # "" (off) or "table"
TRACE_DIR = os.environ.get("PROF_TRACE_DIR", "/tmp/ns_trace")
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "profile_r05.json")


def build_runner():
    D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
    state = D.init(n_replicas=R, n_keys=1)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
    )
    batches = [
        stack_rounds([gen.next_batch(B, Br) for _ in range(W)]) for _ in range(2)
    ]
    mode = EXTRAS if EXTRAS else False

    @jax.jit
    def run_window(state, stacked):
        def body(st, ops):
            st2, extras = D.apply_ops(st, ops, collect_dominated=mode)
            if mode == "table":
                return st2, jnp.sum(extras.dominated_tbl)
            return st2, ()
        out, tail = lax.scan(body, state, stacked)
        if mode == "table":
            return out, jnp.sum(tail)
        return out

    return D, state, batches, run_window


def capture(state, batches, run_window):
    out = run_window(state, batches[0])  # compile + warm
    sync(out)
    jax.profiler.start_trace(TRACE_DIR)
    out = run_window(out if not EXTRAS else out[0], batches[1])
    sync(out)
    jax.profiler.stop_trace()
    return out


def newest_trace_json(root):
    cands = []
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f.endswith(".trace.json.gz"):
                p = os.path.join(dirpath, f)
                cands.append((os.path.getmtime(p), p))
    return max(cands)[1]


def device_slices(trace_path):
    """Aggregate device-pid complete events by (deduped) HLO op name."""
    with gzip.open(trace_path) as f:
        d = json.load(f)
    ev = d.get("traceEvents", [])
    dev_pids = {
        e["pid"]
        for e in ev
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "TPU" in e.get("args", {}).get("name", "")
    }
    # Device timelines nest (e.g. a module event spanning its fusions) and
    # split across "XLA Ops"/"XLA Modules" threads; keep the op-level line
    # only: drop events whose name looks like a module (jit_*).
    agg = collections.Counter()
    hits = collections.Counter()
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        if name.startswith("jit_") or name.startswith("buffer"):
            continue
        agg[name] += e.get("dur", 0)  # microseconds
        hits[name] += 1
    return agg, hits


BODY_OPS = re.compile(r"^\s+(?:ROOT\s+)?\S+\s+=\s+\S+\s+([a-z0-9_-]+)\(", re.M)


def fusion_bodies(hlo_text):
    """Map each fusion's computation name -> a compressed op census of its
    body, e.g. 'sort x2, scatter x3, add x41'. HLO text layout: computations
    are `%name (args) -> type {' blocks; fusions reference `calls=%comp`."""
    comps = {}
    cur = None
    ops = None
    for line in hlo_text.splitlines():
        m = re.match(r"^(?:ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if m and line.rstrip().endswith("{"):
            cur = m.group(1)
            ops = collections.Counter()
            comps[cur] = ops
            continue
        if cur is not None:
            if line.startswith("}"):
                cur = None
                continue
            m2 = re.match(r"^\s+(?:ROOT\s+)?\S+\s+=\s+\S+\s+([a-z0-9\-]+)\(", line)
            if m2 and ops is not None:
                ops[m2.group(1)] += 1
    # fusion instruction name -> called computation
    fuse_map = {}
    for m in re.finditer(
        r"%?([\w.\-]+)\s+=\s+\S+\s+fusion\(.*?calls=%?([\w.\-]+)", hlo_text
    ):
        fuse_map[m.group(1)] = m.group(2)
    out = {}
    for fname, comp in fuse_map.items():
        census = comps.get(comp)
        if not census:
            continue
        major = [
            f"{op} x{n}"
            for op, n in sorted(census.items(), key=lambda kv: -kv[1])
            if op
            not in ("parameter", "constant", "tuple", "get-tuple-element", "bitcast")
        ][:8]
        out[fname] = ", ".join(major)
    return out


def main():
    D, state, batches, run_window = build_runner()
    lowered = run_window.lower(state, batches[0])
    hlo_text = lowered.compile().as_text()
    bodies = fusion_bodies(hlo_text)

    capture(state, batches, run_window)
    trace_path = newest_trace_json(TRACE_DIR)
    agg, hits = device_slices(trace_path)

    total_us = sum(agg.values())
    rows = []
    for name, us in agg.most_common():
        base = name.split(".")[0]
        rows.append(
            {
                "hlo": name,
                "ms_per_round": round(us / 1e3 / W, 3),
                "calls_per_round": round(hits[name] / W, 1),
                "share": round(us / total_us, 4),
                "body": bodies.get(name, bodies.get(base, "")),
            }
        )
    # Collapse the tail for the committed artifact; keep every slice >=1%.
    head = [r for r in rows if r["share"] >= 0.01]
    tail_ms = round(sum(r["ms_per_round"] for r in rows if r["share"] < 0.01), 3)
    artifact = {
        "config": {
            "R": R, "I": I, "B": B, "Br": Br, "W": W,
            "extras": EXTRAS or "off",
            "backend": jax.default_backend(),
        },
        "device_total_ms_per_round": round(total_us / 1e3 / W, 2),
        "slices": head,
        "tail_under_1pct_ms": tail_ms,
        "trace": trace_path,
    }
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"device total: {artifact['device_total_ms_per_round']:.2f} ms/round")
    print(f"{'ms/rnd':>8} {'share':>6} {'calls':>6}  name  |  body")
    for r in head:
        print(
            f"{r['ms_per_round']:8.3f} {r['share']*100:5.1f}% {r['calls_per_round']:6.1f}"
            f"  {r['hlo'][:48]:48s}| {r['body'][:70]}"
        )
    print(f"{tail_ms:8.3f}        (tail: slices under 1%)")
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
