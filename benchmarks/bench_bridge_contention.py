"""Bridge contention benchmark: scalar op throughput while a dense grid
dispatch is in flight.

Round 1 had one global lock: a north-star-sized grid dispatch (~60ms)
stalled every client. Round 2 locks per object, so scalar traffic should
be unaffected by a concurrent grid op. This measures both configurations'
observable effect: scalar round-trips/sec with (a) an idle server and
(b) a server continuously running slow grid applies on another
connection.

Run: python benchmarks/bench_bridge_contention.py  [grid_ms=200]
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer
from antidote_ccrdt_tpu.bridge.client import add
from antidote_ccrdt_tpu.core.etf import Atom


def scalar_rate(addr, seconds=2.0):
    with BridgeClient(*addr) as c:
        h = c.new("average")
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            c.update(h, (Atom("add"), (1, 1)))
            n += 1
        return n / (time.perf_counter() - t0)


def main():
    grid_ms = float(sys.argv[1]) if len(sys.argv) > 1 else 200.0
    with BridgeServer() as srv:
        with BridgeClient(*srv.address) as setup:
            setup.grid_new("g", n_replicas=2, n_keys=1, n_ids=256, n_dcs=2)
        grid = srv._grids[b"g"]
        orig = grid.apply
        grid.apply = lambda ops: (time.sleep(grid_ms / 1e3), orig(ops))[1]

        idle = scalar_rate(srv.address)

        stop = threading.Event()

        def grind():
            with BridgeClient(*srv.address) as c:
                while not stop.is_set():
                    c.grid_apply("g", [[add(0, 1, 50, 0, 1)], []])

        th = threading.Thread(target=grind)
        th.start()
        time.sleep(0.2)  # let the grinder hold the grid lock
        contended = scalar_rate(srv.address)
        stop.set()
        th.join()

    print(
        f"scalar round-trips/sec: idle={idle:.0f}  "
        f"with {grid_ms:.0f}ms grid ops in flight={contended:.0f}  "
        f"ratio={contended / idle:.2f} (1.0 = no interference; the round-1 "
        f"global lock gave ~{1e3 / grid_ms:.0f}/sec here)"
    )


if __name__ == "__main__":
    main()
