"""Honest (device_get-synced) per-piece costs of the topk_rmv apply round.

Measurement rules learned the hard way on the tunneled TPU backend:

1. `jax.block_until_ready` does NOT block — it returns while the device is
   still executing, so naive timings measure dispatch (~0.03ms) or queue
   backpressure, not compute. Every timing must end with a real
   device->host readback (`sync` below).
2. Each per-dispatch round trip costs 10-30ms, so pieces must be timed as
   many iterations inside ONE jit (lax.scan).
3. The scanned iterations must consume *distinct per-iteration inputs* and
   thread a carry through the piece — otherwise XLA hoists the
   loop-invariant work out of the scan and the loop measures nothing.
4. Big arrays must arrive as arguments/carries, never closures: closed-over
   device arrays are serialized into the remote-compile request as
   constants (HTTP 413 past ~100MB).

Reference numbers (v5e, R=32, I=100k, D=32, M=4, B=4096, Br=256) that
drove the kernel choices in models/topk_rmv_dense.py are recorded in that
module's `_apply_one_replica` docstring."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF, _filter_slots, _sort_adds, _sort_slots, make_dense,
)
from antidote_ccrdt_tpu.ops.segment import group_rank

R, NK, I, D_DCS, K, M, B, Br, REPS = 32, 1, 100_000, 32, 100, 4, 4096, 256, 20
D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
state = D.init(n_replicas=R, n_keys=1)
gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7))
warm = gen.next_batch(B, Br)
state, _ = D.apply_ops(state, warm)
batch_seq = [gen.next_batch(B, Br) for _ in range(REPS)]
stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batch_seq)
warm_seq = jax.tree.map(lambda x: x, stacked)  # same shapes for warmup


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def true_time(name, step_fn, carry_init):
    """step_fn(carry, ops) -> carry. ops leaves have [R, ...] shapes."""

    @jax.jit
    def run(c, seq):
        def body(c, ops):
            return step_fn(c, ops), ()
        out, _ = lax.scan(body, c, seq)
        return out

    sync(run(carry_init, stacked))
    t0 = time.perf_counter()
    out = run(carry_init, stacked)
    sync(out)
    print(f"{name:52s} {(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")
    return out


st = state

# 1 rmv scatter: XLA scatter vs matmul (within full-state carry shapes)
def rmv_scatter(c, ops):
    def one(t, rk, ri, u):
        rkk = jnp.where(ri >= 0, rk, NK)
        return t.at[rkk, ri].max(u, mode="drop")
    return jax.vmap(one)(c, ops.rmv_key, ops.rmv_id, ops.rmv_vc)

true_time("1a rmv tombstone XLA scatter", rmv_scatter, st.rmv_vc)

# 2 vc one-hot (tiny)
def vc_onehot(c, ops):
    def one(v, k, d, t, valid):
        slot = k * D_DCS + d
        hit = slot[:, None] == jnp.arange(NK * D_DCS, dtype=slot.dtype)[None, :]
        contrib = jnp.where(hit & valid[:, None], t[:, None], 0)
        return jnp.maximum(v, jnp.max(contrib, axis=0).reshape(NK, D_DCS))
    return jax.vmap(one)(c, ops.add_key, ops.add_dc, ops.add_ts, ops.add_ts > 0)

true_time("2 vc one-hot", vc_onehot, st.vc)

# 3 whole-table filter (carry slot_score; dc/ts as captured consts via carry tuple)
def filt(c, ops):
    score, dc, ts, rmv = c
    rmv2 = rmv_scatter(rmv, ops)
    s, d, t = _filter_slots(score, dc, ts, rmv2)
    return (s, d, t, rmv2)

true_time("3 filter_slots (incl 1a cost)", filt,
          (st.slot_score, st.slot_dc, st.slot_ts, st.rmv_vc))

# 4 dominated row gather (table rides the carry to avoid const upload)
def domg(c, ops):
    tab, acc = c
    def one(t, k, i, d, ts):
        row = t.reshape(NK * I, D_DCS)[k * I + i]
        dom = jnp.take_along_axis(row, d[:, None], axis=-1)[:, 0]
        return dom >= ts
    dom = jax.vmap(one)(tab, ops.add_key, ops.add_id, ops.add_dc, ops.add_ts)
    return (tab, jnp.maximum(acc, dom.sum(-1, keepdims=True).astype(jnp.int32)))

true_time("4 dominated row-gather (B rows)", domg,
          (st.rmv_vc, jnp.zeros((R, 1), jnp.int32)))

# 5 sort adds (two 7-operand sorts + rank)
def sortadds(c, ops):
    def one(akey, aid, ascore, ats, adc):
        (s_key, s_id, _, _), (s_score, s_ts, s_dc) = _sort_adds(akey, aid, ascore, ats, adc)
        rank = group_rank((s_key, s_id))
        return rank.sum()
    return jnp.maximum(c, jax.vmap(one)(ops.add_key, ops.add_id, ops.add_score,
                                        ops.add_ts, ops.add_dc)[:, None])

true_time("5 sort adds x2 + rank", sortadds, jnp.zeros((R, 1), jnp.int32))

# 6 window + head-row scatter (delta build, minus sort)
def delta_rows(c, ops):
    def one(akey, aid, ascore, ats, adc):
        (s_key, s_id, _, _), (s_score, s_ts, s_dc) = _sort_adds(akey, aid, ascore, ats, adc)
        rank = group_rank((s_key, s_id))
        Bn = s_key.shape[0]
        startp = jnp.arange(Bn, dtype=jnp.int32) - rank
        in_b = (jnp.arange(Bn, dtype=jnp.int32)[:, None]
                + jnp.arange(M, dtype=jnp.int32)[None, :]) < Bn
        same = (jnp.stack([jnp.roll(startp, -j) for j in range(M)], axis=-1)
                == startp[:, None]) & in_b
        w = jnp.where(same, jnp.stack([jnp.roll(s_score, -j) for j in range(M)], -1), NEG_INF)
        is_head = (rank == 0) & (s_key < NK)
        head_row = jnp.where(is_head, s_key * I + s_id, NK * I)
        return (jnp.full((NK * I, M), NEG_INF, jnp.int32)
                .at[head_row].set(w, mode="drop", unique_indices=True)
                .reshape(NK, I, M))
    d = jax.vmap(one)(ops.add_key, ops.add_id, ops.add_score, ops.add_ts, ops.add_dc)
    return jnp.maximum(c, d)

true_time("6 delta: sort+window+ROW scatter (1 field)", delta_rows,
          jnp.full((R, NK, I, M), NEG_INF, jnp.int32))

# 6b old scalar-scatter delta
def delta_scalar(c, ops):
    def one(akey, aid, ascore, ats, adc):
        (s_key, s_id, _, _), (s_score, s_ts, s_dc) = _sort_adds(akey, aid, ascore, ats, adc)
        rank = group_rank((s_key, s_id))
        rank2 = jnp.where(rank < M, rank, M)
        return (jnp.full((NK, I, M), NEG_INF, jnp.int32)
                .at[s_key, s_id, rank2].set(s_score, mode="drop"))
    d = jax.vmap(one)(ops.add_key, ops.add_id, ops.add_score, ops.add_ts, ops.add_dc)
    return jnp.maximum(c, d)

true_time("6b delta: sort+SCALAR scatter (1 field)", delta_scalar,
          jnp.full((R, NK, I, M), NEG_INF, jnp.int32))

# 7 join sort
def join(c, ops):
    score, dc, ts = c
    c_s = jnp.concatenate([score, score], axis=-1)
    c_d = jnp.concatenate([dc, dc], axis=-1)
    c_t = jnp.concatenate([ts, ts + ops.add_ts[0, 0]], axis=-1)
    f_s, f_d, f_t, _ = _sort_slots(c_s, c_d, c_t, M)
    return (f_s, f_d, f_t)

true_time("7 join sort 2M->M", join, (st.slot_score, st.slot_dc, st.slot_ts))

# 8 FULL apply (current code)
def full(c, ops):
    s, _ = D.apply_ops(c, ops)
    return s

true_time("8 FULL apply_ops (current code)", full, st)

# 9 observe (state rides the carry)
def obs(c, ops):
    stc, acc = c
    o = D.observe(stc)
    return (stc, jnp.maximum(acc, o.scores[..., 0] + ops.add_ts[:, :1] * 0))

out9 = true_time("9 observe (full I sort)", obs, (st, jnp.zeros((R, NK), jnp.int32)))
