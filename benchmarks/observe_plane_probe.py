"""Probe: slot-plane layout for the observe/read path (VERDICT-r2 task 4).

`observe` reads slot 0 of the [R, NK, I, M] slot arrays — 1 of M=4 words
per 16B line, ~4x read amplification by layout (BASELINE.md roofline:
26.4 GB/s achieved, 3.2% of peak). Candidates measured at north-star
shapes:

  strided   — production: masked_topk over state.slot_score[..., 0]
  planes    — slot-0 pre-split into contiguous [R, NK, I] planes (what a
              plane-split state layout would give observe for free); the
              split cost itself is measured separately (split_ms) since a
              real adoption would pay it in apply/merge writes instead
  planes+ts — contiguous planes for the ts/dc positional gathers too

Also reports the pure traffic floor: 38.4MB useful at 819GB/s = 47us, so
anything in the ~1ms range is latency/sort-bound, not bandwidth-bound —
the number that decides whether the layout change can pay at all.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import Observed, make_dense
from antidote_ccrdt_tpu.ops.dense_table import masked_topk

R, NK, I, D_DCS, K, M, REPS = 32, 1, 100_000, 32, 100, 4, 50

D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7))
state = D.init(n_replicas=R, n_keys=1)
state, _ = D.apply_ops(state, gen.next_batch(32768, 2048), collect_dominated=False)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def timeit(name, fn, *args):
    @jax.jit
    def run(*a):
        def body(c, _):
            out = fn(*a)
            # fold the output into the carry so the scan can't hoist it
            return c + out.scores[0, 0, 0], ()
        out, _ = lax.scan(body, jnp.int32(0), None, length=REPS)
        return out

    sync(run(*args))
    t0 = time.perf_counter()
    out = run(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / REPS * 1e3
    print(f"{name:32s} {dt:9.3f} ms   ({38.4 / dt:8.1f} GB/s useful)")
    return dt


def observe_planes(score0, dc0, ts0):
    id_f, score_f, _ = masked_topk(score0, min(K, I))
    gi = jnp.clip(id_f, 0)
    ts_f = jnp.take_along_axis(ts0, gi, axis=-1)
    dc_f = jnp.take_along_axis(dc0, gi, axis=-1)
    valid = (ts_f > 0) & (id_f >= 0)
    return Observed(id_f, score_f, dc_f, ts_f, valid)


if __name__ == "__main__":
    timeit("strided (production observe)", D.observe, state)
    # Pre-materialized contiguous planes (copy cost excluded — a plane
    # layout would produce them as the natural state).
    score0 = jnp.copy(state.slot_score[..., 0])
    dc0 = jnp.copy(state.slot_dc[..., 0])
    ts0 = jnp.copy(state.slot_ts[..., 0])
    sync((score0, dc0, ts0))
    timeit("planes (contiguous slot-0)", observe_planes, score0, dc0, ts0)

    # The split cost a non-plane state would pay per observe instead.
    @jax.jit
    def split(st):
        return (
            jnp.copy(st.slot_score[..., 0]),
            jnp.copy(st.slot_dc[..., 0]),
            jnp.copy(st.slot_ts[..., 0]),
        )

    sync(split(state))
    t0 = time.perf_counter()
    for _ in range(8):
        out = split(state)
    sync(out)
    print(f"{'split cost (3 strided copies)':32s} {(time.perf_counter()-t0)/8*1e3:9.3f} ms")
