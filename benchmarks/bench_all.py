"""Benchmarks for every BASELINE.md config beyond the north star (bench.py
covers topk_rmv): average, topk, leaderboard, wordcount, and the
worddocumentcount streaming-corpus ingest (native tokenizer -> device).

Measurement discipline is shared with bench.py via
`antidote_ccrdt_tpu.utils.benchtime`: scan-fused multi-round windows (one
dispatch per window), distinct per-round op batches (defeats loop-invariant
hoisting), and host-readback syncs (block_until_ready does not block on
tunneled backends). Prints one JSON line per config.

Run: python benchmarks/bench_all.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from antidote_ccrdt_tpu.utils.benchtime import (  # noqa: E402
    stack_rounds,
    sync,
    windowed,
)


def on_cpu() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def sized(tpu, cpu):
    """Pick config by backend: full sizes on an accelerator, shrunk sizes
    on CPU so CI / no-accelerator runs still complete (cf. bench.py main).
    CCRDT_BENCH_TINY additionally clamps every dimension to <=256 — the
    smoke-test mode (tests/test_benchall_smoke.py): exercises every
    config's full path in seconds, numbers meaningless. 256 keeps every
    table at least as wide as the default board size (100)."""
    if os.environ.get("CCRDT_BENCH_TINY"):
        return tuple(min(c, 256) for c in cpu)
    return cpu if on_cpu() else tpu


def bench_average():
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.average import AverageDense, AverageOps

    R, NK, B, W, NW = sized((2, 1000, 1048576, 8, 4), (2, 1000, 1024, 3, 3))
    D = AverageDense()
    state = D.init(R, NK)
    rng = np.random.default_rng(0)

    def batch():
        return AverageOps(
            key=jnp.asarray(rng.integers(0, NK, (R, B)).astype(np.int32)),
            value=jnp.asarray(rng.integers(-100, 100, (R, B)).astype(np.int32)),
            count=jnp.asarray(rng.integers(1, 3, (R, B)).astype(np.int32)),
        )

    wins = [stack_rounds([batch() for _ in range(W)]) for _ in range(NW + 1)]
    rate, p50 = windowed(lambda s, o: D.apply_ops(s, o)[0], state, wins, R * B)
    return {"metric": f"average adds/sec ({NK} keys x {R} replicas)",
            "value": round(rate), "unit": "ops/sec", "p50_round_ms": round(p50, 2)}


def bench_topk():
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.topk import TopkOps, make_dense

    R, I, B, W, NW = sized((8, 10_000, 524288, 8, 4), (4, 2_000, 1024, 3, 3))
    D = make_dense(n_ids=I, size=100)
    state = D.init(R, 1)
    rng = np.random.default_rng(0)

    def batch():
        return TopkOps(
            key=jnp.zeros((R, B), jnp.int32),
            id=jnp.asarray(rng.integers(0, I, (R, B)).astype(np.int32)),
            score=jnp.asarray(rng.integers(1, 10**6, (R, B)).astype(np.int32)),
            valid=jnp.ones((R, B), bool),
        )

    wins = [stack_rounds([batch() for _ in range(W)]) for _ in range(NW + 1)]
    rate, p50 = windowed(lambda s, o: D.apply_ops(s, o)[0], state, wins, R * B)
    return {"metric": f"topk adds/sec ({I//1000}k ids x {R} replicas, K=100)",
            "value": round(rate), "unit": "ops/sec", "p50_round_ms": round(p50, 2)}


def bench_leaderboard():
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.leaderboard import LeaderboardOps, make_dense

    R, P, B, Bb, W, NW = sized(
        (16, 1_000_000, 131072, 1024, 8, 4), (4, 50_000, 1024, 16, 3, 3)
    )
    D = make_dense(n_players=P, size=100)
    state = D.init(R, 1)
    rng = np.random.default_rng(0)

    def zipf_ids(n):
        raw = rng.zipf(1.2, size=n)
        return ((raw - 1) % P).astype(np.int32)

    def batch():
        return LeaderboardOps(
            add_key=jnp.zeros((R, B), jnp.int32),
            add_id=jnp.asarray(np.stack([zipf_ids(B) for _ in range(R)])),
            add_score=jnp.asarray(rng.integers(1, 10**6, (R, B)).astype(np.int32)),
            add_valid=jnp.ones((R, B), bool),
            ban_key=jnp.zeros((R, Bb), jnp.int32),
            ban_id=jnp.asarray(np.stack([zipf_ids(Bb) for _ in range(R)])),
            ban_valid=jnp.ones((R, Bb), bool),
        )

    wins = [stack_rounds([batch() for _ in range(W)]) for _ in range(NW + 1)]
    rate, p50 = windowed(
        lambda s, o: D.apply_ops(s, o)[0], state, wins, R * (B + Bb)
    )
    players = f"{P//10**6}M" if P >= 10**6 else f"{P//1000}k"
    return {"metric": f"leaderboard ops/sec ({players} players x {R} replicas, Zipf)",
            "value": round(rate), "unit": "ops/sec", "p50_round_ms": round(p50, 2)}


def bench_wordcount():
    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.wordcount import WordcountOps, make_dense

    R, V, B, W, NW = sized((64, 1 << 16, 65536, 8, 4), (8, 1 << 12, 1024, 3, 3))
    D = make_dense(V)
    state = D.init(R, 1)
    rng = np.random.default_rng(0)

    def batch():
        # Zipf token stream (ragged-vocab stand-in, already hashed)
        raw = rng.zipf(1.1, size=(R, B))
        return WordcountOps(
            key=jnp.zeros((R, B), jnp.int32),
            token=jnp.asarray(((raw - 1) % V).astype(np.int32)),
        )

    wins = [stack_rounds([batch() for _ in range(W)]) for _ in range(NW + 1)]
    rate, p50 = windowed(lambda s, o: D.apply_ops(s, o)[0], state, wins, R * B)
    return {"metric": f"wordcount tokens/sec ({R} replicas, V={V>>10}k hashed)",
            "value": round(rate), "unit": "tokens/sec", "p50_round_ms": round(p50, 2)}


def bench_worddocumentcount():
    """Streaming-corpus ingest end to end: raw document strings -> native
    tokenizer (tokenize, per-document dedup, FNV-1a hash) -> device
    scatter-add. This is the half of the BASELINE 64-replica config that
    bench_wordcount's pre-hashed token stream does not exercise."""
    import jax

    from antidote_ccrdt_tpu.harness import native_tokenizer as nt
    from antidote_ccrdt_tpu.models.wordcount import hash_token, make_dense

    R, V, DOCS, WORDS = sized((64, 1 << 16, 512, 64), (8, 1 << 12, 32, 16))
    D = make_dense(V)
    state = D.init(R, 1)
    rng = np.random.default_rng(0)

    # Synthetic ragged corpus: Zipf word frequencies, known raw token count.
    def make_docs():
        out = []
        for _ in range(R):
            ids = (rng.zipf(1.1, size=(DOCS, WORDS)) - 1) % 50_000
            out.append([" ".join(f"w{t}" for t in row) for row in ids])
        return out

    docs = make_docs()
    raw_tokens = R * DOCS * WORDS

    import jax.numpy as jnp

    from antidote_ccrdt_tpu.models.wordcount import WordcountOps, tokenize

    t0 = time.perf_counter()
    if nt.available():
        # threads=0: hardware thread count (bit-identical output at any
        # count; this box has 1 CPU, multi-core hosts scale the pool).
        tok = nt.NativeTokenizer(V)
        enc = [
            tok.encode_batch(per_r, per_document=True, threads=0)[0]
            for per_r in docs
        ]
        path = "native"
    else:  # pure-Python fallback (toolchain unavailable)
        enc = [
            np.asarray(
                [hash_token(t, V) for d in per_r for t in set(tokenize(d))],
                np.int32,
            )
            for per_r in docs
        ]
        path = "python-fallback"
    B = max(len(e) for e in enc)
    counts_np = np.asarray([len(e) for e in enc], np.int32)
    t_encode = time.perf_counter() - t0

    # Wire format: ingest is TUNNEL-UPLOAD-bound here (round-3 measured:
    # ~8-10MB/s effective through the remote-device tunnel vs ~55ms of
    # host encode), so the token batch ships as u16 halves of the i32 it
    # used to be whenever V fits — padding is reconstructed on device
    # from per-row counts (a -1 sentinel would need V+1 code points).
    # Keys are all-zero: materialized device-side, never uploaded.
    if V <= 65536:
        wire_np = np.zeros((R, B), np.uint16)
        for r, e in enumerate(enc):
            wire_np[r, : len(e)] = e.astype(np.uint16)
        wire = "u16+row-counts"
    else:
        wire_np = np.full((R, B), -1, np.int32)
        for r, e in enumerate(enc):
            wire_np[r, : len(e)] = e
        wire = "i32"

    # The apply leg runs as CHUNKS async dispatches with NO intermediate
    # sync: uploads of chunk i+1 pipeline with chunk i's dispatch through
    # the wire, halving the leg on the tunneled device (round-3 measured:
    # 332 -> 167ms at 4 chunks). This does NOT contradict the round-2
    # streaming negative result below — that pipeline SYNCED per chunk,
    # paying the full RTT every time; the async queue pays it once.
    CHUNKS = 4
    Bc = -(-B // CHUNKS)
    if wire_np.shape[1] < CHUNKS * Bc:
        wire_np = np.concatenate(
            [wire_np, np.zeros((R, CHUNKS * Bc - B), wire_np.dtype)], axis=1
        )

    @jax.jit
    def apply_chunk(s, tok_wire, counts, base):
        live = (
            jnp.arange(Bc, dtype=jnp.int32)[None, :] + base
        ) < counts[:, None]
        token = jnp.where(live, tok_wire.astype(jnp.int32), -1)
        ops = WordcountOps(key=jnp.zeros((R, Bc), jnp.int32), token=token)
        return D.apply_ops(s, ops)[0]

    # Fresh jnp.asarray each call so the timed region pays the host->device
    # upload of the token batch (benchtime rule #3: never reuse resident ops).
    def run_chunked(s, mk_chunk):
        for i in range(CHUNKS):
            s = apply_chunk(s, *mk_chunk(i), i * Bc)
        return s

    def fresh_chunk(i):
        return (
            jnp.asarray(wire_np[:, i * Bc : (i + 1) * Bc]),
            jnp.asarray(counts_np),
        )

    state = run_chunked(state, fresh_chunk)  # compile + warm
    sync(state)
    t0 = time.perf_counter()
    state = run_chunked(state, fresh_chunk)
    sync(state)
    t_apply = time.perf_counter() - t0
    # Decomposition: resident-input apply isolates device compute + RTT;
    # the async-hidden upload remainder is the difference. sync() forces
    # ONE array's transfer (single-leaf readback — benchtime.py), so every
    # resident array is synced individually; a single sync(resident) would
    # leave chunks 1..N uploading inside the timed window.
    resident = [fresh_chunk(i) for i in range(CHUNKS)]
    for tok_c, cnt_c in resident:
        sync(tok_c)
        sync(cnt_c)
    t0 = time.perf_counter()
    state = run_chunked(state, lambda i: resident[i])
    sync(state)
    t_device = time.perf_counter() - t0
    # Wire calibration must be UN-overlapped (the async queue exists to
    # hide transfers, so t_apply - t_device is the un-hidden remainder,
    # not bandwidth): one dedicated sequential upload of the whole wire.
    t0 = time.perf_counter()
    for i in range(CHUNKS):
        sync(jnp.asarray(wire_np[:, i * Bc : (i + 1) * Bc]))
    t_wire = time.perf_counter() - t0

    out = [{
        "metric": f"worddocumentcount corpus tokens/sec ({R} replicas, "
                  f"{DOCS} docs/replica, ingest={path}, host dedup)",
        "value": round(raw_tokens / (t_encode + t_apply)),
        "unit": "tokens/sec",
        "encode_ms": round(t_encode * 1e3, 2),
        "apply_ms": round(t_apply * 1e3, 2),
        "device_ms": round(t_device * 1e3, 2),
        # The async-hidden remainder, NOT wire time (uploads overlap
        # dispatch by design); clamped — noise can push it negative.
        "upload_unhidden_ms": round(max(0.0, t_apply - t_device) * 1e3, 2),
        "wire": wire,
        "wire_mb": round(wire_np.nbytes / 1e6, 2),
        "apply_chunks_async": CHUNKS,
        "host_tokenizer_tokens_per_sec": round(raw_tokens / t_encode),
        "device_idle_frac": round(max(0.0, 1 - t_device / t_apply), 3),
        # Dedicated un-overlapped transfer calibration: comparable across
        # sessions (the tunnel varies ~5x run to run); host-attached TPUs
        # upload at PCIe rates and the config is host-tokenizer-bound
        # instead (see BASELINE.md ingest note).
        "wire_mb_per_s": (
            round(wire_np.nbytes / 1e6 / t_wire, 1)
            if t_wire > 1e-4 else None  # below measurement noise
        ),
    }]

    # NOTE (negative result, measured round 2; refined round 3): chunking
    # through the streaming pipeline (harness.pipeline.stream_apply, 8
    # chunks, depth-2 prefetch) ran 8x SLOWER end to end on the tunneled
    # v5e — because it SYNCED per chunk, paying the fixed upload+dispatch
    # round trip (~0.5s) every time. The async chunk queue above (no
    # intermediate sync) is the shape that wins on a tunnel: transfers
    # pipeline with dispatch and the RTT is paid once at the final sync
    # (332 -> 167ms measured at 4 chunks). stream_apply's prefetch remains
    # the right tool only where dispatch is cheap and host encode overlaps
    # device apply (tests/test_pipeline.py on local backends).
    if nt.available():
        # Device-side dedup: host only splits and ids (1 CPU here); the
        # string-identity per-document dedup is one sort on the TPU
        # (apply_doc_ops).
        t0 = time.perf_counter()
        arrs = nt.worddoc_arrays_from_docs(docs, n_buckets=V)
        t_encode2 = time.perf_counter() - t0

        from antidote_ccrdt_tpu.models.wordcount import WordDocOps

        # Same u16 wire as the host-dedup path — all four planes fit when
        # the exact vocab, bucket table and doc count do (the -1 padding
        # sentinel of uniq/token is reconstructed from per-row counts).
        B2 = arrs["token"].shape[1]
        counts2 = (arrs["token"] >= 0).sum(axis=1).astype(np.int32)
        fits = (
            V <= 65536
            and int(arrs["uniq"].max(initial=0)) < 65536
            and DOCS <= 65536
        )
        if fits:
            wire2 = {
                k: np.where(arrs[k] < 0, 0, arrs[k]).astype(np.uint16)
                for k in ("doc", "uniq", "token")
            }
        else:
            wire2 = {k: arrs[k] for k in ("doc", "uniq", "token")}

        @jax.jit
        def apply_doc_wire(s, doc, uniq, token, counts):
            live = jnp.arange(B2, dtype=jnp.int32)[None, :] < counts[:, None]
            ops = WordDocOps(
                key=jnp.zeros((R, B2), jnp.int32),
                doc=doc.astype(jnp.int32),
                uniq=jnp.where(live, uniq.astype(jnp.int32), -1),
                token=jnp.where(live, token.astype(jnp.int32), -1),
            )
            return D.apply_doc_ops(s, ops)[0]

        def mk_wire2():
            return (
                jnp.asarray(wire2["doc"]), jnp.asarray(wire2["uniq"]),
                jnp.asarray(wire2["token"]), jnp.asarray(counts2),
            )

        state2 = D.init(R, 1)
        state2 = apply_doc_wire(state2, *mk_wire2())  # compile + warm
        sync(state2)
        t0 = time.perf_counter()
        state2 = apply_doc_wire(state2, *mk_wire2())
        sync(state2)
        t_apply2 = time.perf_counter() - t0
        out.append({
            "metric": f"worddocumentcount corpus tokens/sec ({R} replicas, "
                      f"{DOCS} docs/replica, ingest=native, device dedup)",
            "value": round(raw_tokens / (t_encode2 + t_apply2)),
            "unit": "tokens/sec",
            "encode_ms": round(t_encode2 * 1e3, 2),
            "apply_ms": round(t_apply2 * 1e3, 2),
            "wire": "u16+row-counts" if fits else "i32",
            "wire_mb": round(sum(w.nbytes for w in wire2.values()) / 1e6, 2),
        })

        # Compact device-dedup wire (VERDICT-r3 item 6): the doc plane is
        # the run-length expansion of per-doc lengths and the token plane
        # is bucket_table[uniq] — both rebuilt ON DEVICE
        # (apply_doc_ops_compact), so the wire ships one token-length
        # plane instead of three. The bucket table uploads once per
        # corpus (resident, like weights) and is counted in wire_mb.
        t0 = time.perf_counter()
        carr = nt.worddoc_compact_arrays_from_docs(docs, n_buckets=V)
        t_encode3 = time.perf_counter() - t0
        # Independent of the raw wire's `fits` (which also demands doc IDS
        # fit u16 — a plane the compact wire never ships): compact needs
        # only bucket values (V), uniq ids, doc LENGTHS and the table
        # length in range.
        fits3 = (
            V <= 65536
            and int(carr["uniq"].max(initial=0)) < 65536
            and int(carr["doc_lens"].max(initial=0)) < 65536
            and int(carr["bucket_table"].shape[0]) <= 65536
        )
        wdt = np.uint16 if fits3 else np.int32
        wire3 = {
            "uniq": carr["uniq"].astype(wdt),
            "doc_lens": carr["doc_lens"].astype(wdt),
            "bucket_table": carr["bucket_table"].astype(wdt),
            "counts": carr["counts"],  # [R] i32 — negligible
        }
        # The bucket table is RESIDENT (uploaded once per corpus, like
        # weights) — hoisted out of the timed window; it still counts in
        # wire_mb, which is per-corpus bytes, not per-apply bytes.
        tbl_res = jnp.asarray(wire3["bucket_table"])
        sync(tbl_res)

        def mk_wire3():
            return dict(
                uniq=jnp.asarray(wire3["uniq"]),
                doc_lens=jnp.asarray(wire3["doc_lens"]),
                counts=jnp.asarray(wire3["counts"]),
                bucket_table=tbl_res,
            )

        state3 = D.init(R, 1)
        state3, _ = D.apply_doc_ops_compact(state3, **mk_wire3())  # warm
        sync(state3)
        t0 = time.perf_counter()
        state3, _ = D.apply_doc_ops_compact(state3, **mk_wire3())
        sync(state3)
        t_apply3 = time.perf_counter() - t0
        # Both paths warmed+timed on the same accumulating state (2x the
        # corpus each) — so equality here is a real differential.
        assert jnp.array_equal(state3.counts, state2.counts), (
            "compact wire diverged from raw device-dedup wire"
        )
        out.append({
            "metric": f"worddocumentcount corpus tokens/sec ({R} replicas, "
                      f"{DOCS} docs/replica, ingest=native, device dedup, "
                      "compact wire)",
            "value": round(raw_tokens / (t_encode3 + t_apply3)),
            "unit": "tokens/sec",
            "encode_ms": round(t_encode3 * 1e3, 2),
            "apply_ms": round(t_apply3 * 1e3, 2),
            "wire": "u16 uniq+doc_lens+bucket_table" if fits3 else "i32",
            "wire_mb": round(sum(w.nbytes for w in wire3.values()) / 1e6, 2),
            "wire_mb_raw_planes": round(
                sum(w.nbytes for w in wire2.values()) / 1e6, 2
            ),
            # The trade is wire bytes vs device rebuild cost (searchsorted
            # doc plane + bucket-table gather): measured r4, the rebuild
            # added ~155ms while saving ~8.2MB — net win whenever the
            # tunnel's effective upload runs below ~50MB/s (the dedicated
            # calibration typically reads 5-10MB/s; only an unusually
            # fast session inverts it, and the record self-describes via
            # encode_ms/apply_ms/wire_mb either way).
            "note": "device plane-rebuild vs wire trade; see apply_ms",
        })
    return out


def bench_compaction():
    """Whole-log compaction as a production pass (VERDICT-r3 item 2): k op
    batches coalesced into one compacted batch (`ops.compaction.
    coalesce_topk_rmv_ops` via the engine's `coalesce_ops`), reporting ops
    in -> out, the compaction cost, and the measured effect on downstream
    apply time (k raw rounds vs 1 compacted round) with an observable-
    equality check. Shrink comes from rmv fusion, dominated/duplicate-add
    deletion, and per-id truncation to the engine's slot capacity M (the
    capacity-aligned mode — the state join truncates there anyway)."""
    import jax

    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense

    R, I, B, Br, K_BATCHES = sized(
        (32, 100_000, 32768, 2048, 4), (4, 4096, 1024, 64, 4)
    )
    D = make_dense(n_ids=I, n_dcs=R, size=100, slots_per_id=4)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=13)
    )
    # Device-resident inputs for BOTH paths: the coalesce here is the
    # device-side pre-apply pass, so the comparison isolates compute (the
    # raw batches upload identically either way; the WIRE-side saving of
    # shipping 4.3x fewer ops belongs to grid_compact, measured via
    # ops_in/ops_out below).
    batches = [
        jax.tree.map(jax.device_put, gen.next_batch(B, Br))
        for _ in range(K_BATCHES)
    ]
    for b in batches:
        sync(b.add_key)

    # Raw path: K sequential rounds (one dispatch each, like a host that
    # ships its log uncompacted).
    state_raw = D.init(n_replicas=R)
    for ops in batches:  # warm the compile
        state_raw, _ = D.apply_ops(state_raw, ops, collect_dominated=False)
    raw_samples = []
    for _rep in range(3):  # median-of-3: single dispatches ride the tunnel
        state_raw = D.init(n_replicas=R)
        t0 = time.perf_counter()
        for ops in batches:
            state_raw, _ = D.apply_ops(state_raw, ops, collect_dominated=False)
        sync(state_raw)
        raw_samples.append((time.perf_counter() - t0) * 1e3)
    raw_apply_ms = float(np.median(raw_samples))

    # Compacted path: one coalesce + one apply. First pass with roomy
    # windows to learn the live counts, then a tight re-coalesce (rounded
    # up to 1024 lanes) so the single downstream apply runs at the
    # genuinely smaller batch shape — that is where compaction pays.
    _, n_add0, n_rmv0 = D.coalesce_ops(batches)
    tight_a = max(1024, (int(n_add0.max()) + 1023) // 1024 * 1024)
    tight_r = max(256, (int(n_rmv0.max()) + 255) // 256 * 256)
    fused, n_add, n_rmv = D.coalesce_ops(batches, out_adds=tight_a, out_rmvs=tight_r)
    sync(fused.add_key)  # compile warm
    c_samples = []
    for _rep in range(3):
        t0 = time.perf_counter()
        fused, n_add, n_rmv = D.coalesce_ops(batches, out_adds=tight_a, out_rmvs=tight_r)
        sync(fused.add_key)
        c_samples.append((time.perf_counter() - t0) * 1e3)
    compact_ms = float(np.median(c_samples))
    state_c = D.init(n_replicas=R)
    state_c, _ = D.apply_ops(state_c, fused, collect_dominated=False)
    sync(state_c)
    a_samples = []
    for _rep in range(3):
        state_c = D.init(n_replicas=R)
        t0 = time.perf_counter()
        state_c, _ = D.apply_ops(state_c, fused, collect_dominated=False)
        sync(state_c)
        a_samples.append((time.perf_counter() - t0) * 1e3)
    c_apply_ms = float(np.median(a_samples))

    ops_in = K_BATCHES * R * (B + Br)
    ops_out = int(n_add.sum() + n_rmv.sum())
    return {
        "metric": (
            f"topk_rmv whole-log compaction ({K_BATCHES} batches x {B}+{Br} "
            f"x {R} replicas)"
        ),
        "value": round(ops_in / ops_out, 2),
        "unit": "x ops reduction",
        "ops_in": ops_in,
        "ops_out": ops_out,
        "compacted_batch": f"{tight_a} adds + {tight_r} rmvs",
        "compact_ms": round(compact_ms, 1),
        "raw_apply_ms_k_rounds": round(raw_apply_ms, 1),
        "compacted_apply_ms_1_round": round(c_apply_ms, 1),
        "downstream_speedup_x": round(raw_apply_ms / (compact_ms + c_apply_ms), 2),
        "observable_equal": bool(D.equal(state_raw, state_c)),
        # vc intentionally not compared: compaction deletes dominated adds,
        # which the raw path lets advance the clock — the same divergence
        # the reference's add/rmv compaction rule accepts (:182-187).
    }


def bench_grid_wire():
    """End-to-end grid-surface throughput over real TCP + ETF framing, per
    type (VERDICT-r3 item 5): ops/sec a host sustains through
    `grid_apply` / `grid_apply_extras` — the stand-in for Antidote's
    host->library call path (antidote_ccrdt.erl:47-59) — plus the scalar
    `batch_merge` entry point. The device-native apply rate for the same
    type is orders of magnitude higher (bench.py / the lines above); the
    interesting number is what fraction survives ETF encode + TCP + the
    server's term packing on one host CPU."""
    from antidote_ccrdt_tpu.bridge import BridgeClient, BridgeServer
    from antidote_ccrdt_tpu.core.etf import Atom
    from antidote_ccrdt_tpu.core import wire as wire_mod
    from antidote_ccrdt_tpu.core.behaviour import registry

    R, B, CALLS = sized((8, 4096, 3), (2, 256, 2))
    rng = np.random.default_rng(5)
    out = []

    def timed_calls(client, gname, batches, extras_batches=()):
        # warm both surfaces (the first call per shape remote-compiles)
        client.grid_apply(gname, batches[0])
        if extras_batches:
            client.grid_apply_extras(gname, extras_batches[0])
        n_ops = 0
        t0 = time.perf_counter()
        for b in batches:
            client.grid_apply(gname, b)
            n_ops += sum(len(x) for x in b)
        for b in extras_batches:
            client.grid_apply_extras(gname, b)
            n_ops += sum(len(x) for x in b)
        dt = time.perf_counter() - t0
        return n_ops / dt

    # timeout: the first call per (type, shape) remote-compiles the dense
    # kernels (~20-60s on the tunneled backend) before replying.
    with BridgeServer() as srv, BridgeClient(*srv.address, timeout=300) as client:
        # topk_rmv: 15/16 adds + 1/16 rmvs; one extras call in the mix.
        I = 100_000
        client.grid_new("w_tr", "topk_rmv", n_replicas=R, n_ids=I, n_dcs=R,
                        size=100)
        frontier = [dict() for _ in range(R)]

        def tr_batch():
            per = []
            for r in range(R):
                ops = []
                for j in range(B):
                    d = int(rng.integers(0, R))
                    i = int(rng.integers(0, I))
                    if j % 16 == 15:
                        vc = dict(frontier[r])
                        ops.append((Atom("rmv"), 0, i,
                                    [(k, v) for k, v in vc.items()]))
                    else:
                        frontier[r][d] = frontier[r].get(d, 0) + 1
                        ops.append((Atom("add"), 0, i,
                                    int(rng.integers(1, 10**6)), d,
                                    frontier[r][d]))
                per.append(ops)
            return per

        rate = timed_calls(
            client, "w_tr", [tr_batch() for _ in range(CALLS)], [tr_batch()]
        )
        out.append({
            "metric": f"grid wire topk_rmv ops/sec (TCP+ETF, {R}x{B}/call)",
            "value": round(rate), "unit": "ops/sec",
        })

        # topk
        client.grid_new("w_tk", "topk", n_replicas=R, n_ids=10_000, size=100)
        tk = lambda: [  # noqa: E731
            [(Atom("add"), 0, int(rng.integers(0, 10_000)),
              int(rng.integers(1, 10**6))) for _ in range(B)]
            for _ in range(R)
        ]
        out.append({
            "metric": f"grid wire topk ops/sec (TCP+ETF, {R}x{B}/call)",
            "value": round(timed_calls(client, "w_tk", [tk() for _ in range(CALLS)])),
            "unit": "ops/sec",
        })

        # leaderboard: adds + a few bans
        client.grid_new("w_lb", "leaderboard", n_replicas=R,
                        n_players=100_000, size=100)

        def lb():
            return [
                [(Atom("add"), 0, int(rng.integers(0, 100_000)),
                  int(rng.integers(1, 10**6))) for _ in range(B - 16)]
                + [(Atom("ban"), 0, int(rng.integers(0, 100_000)))
                   for _ in range(16)]
                for _ in range(R)
            ]

        out.append({
            "metric": f"grid wire leaderboard ops/sec (TCP+ETF, {R}x{B}/call)",
            "value": round(timed_calls(client, "w_lb", [lb() for _ in range(CALLS)])),
            "unit": "ops/sec",
        })

        # average
        client.grid_new("w_av", "average", n_replicas=R, n_keys=64)
        av = lambda: [  # noqa: E731
            [(Atom("add"), int(rng.integers(0, 64)),
              int(rng.integers(-100, 100)), 1) for _ in range(B)]
            for _ in range(R)
        ]
        out.append({
            "metric": f"grid wire average ops/sec (TCP+ETF, {R}x{B}/call)",
            "value": round(timed_calls(client, "w_av", [av() for _ in range(CALLS)])),
            "unit": "ops/sec",
        })

        # wordcount + worddocumentcount (pre-hashed token adds)
        for tname, gname in (("wordcount", "w_wc"), ("worddocumentcount", "w_wd")):
            client.grid_new(gname, tname, n_replicas=R, n_buckets=4096)
            wc = lambda: [  # noqa: E731
                [(Atom("add"), 0, int(t)) for t in
                 (rng.zipf(1.1, size=B) - 1) % 4096]
                for _ in range(R)
            ]
            out.append({
                "metric": f"grid wire {tname} ops/sec (TCP+ETF, {R}x{B}/call)",
                "value": round(timed_calls(client, gname, [wc() for _ in range(CALLS)])),
                "unit": "ops/sec",
            })

        # Packed-columns surface (round 4, grid_apply_packed): the same
        # op mixes as the tuple lines above, but generated as column
        # arrays directly — how a native producer (or a BEAM client with
        # one binary comprehension per column) would feed the wire. The
        # timed region covers column->binary packing + ETF + TCP + the
        # server's vectorized unpack + device dispatch.
        def timed_packed(gname, groups_batches):
            client.grid_apply_packed(gname, groups_batches[0])  # warm
            n_ops = 0
            t0 = time.perf_counter()
            for groups in groups_batches:
                client.grid_apply_packed(gname, groups)
                n_ops += sum(
                    int(np.asarray(counts).sum())
                    for _, counts, _ in groups
                )
            return n_ops / (time.perf_counter() - t0)

        def seq_ts(dcs, base):
            """Per-dc running timestamps (1-based past `base[dc]`),
            mirroring the tuple lines' PERSISTENT frontier counters
            (base carries across calls — restarting at 1 every call
            would replay stale (dc, ts) pairs the tuple line never
            generates), vectorized."""
            order = np.argsort(dcs, kind="stable")
            sorted_dcs = dcs[order]
            grp = np.r_[True, sorted_dcs[1:] != sorted_dcs[:-1]]
            c = np.arange(dcs.size) - np.maximum.accumulate(
                np.where(grp, np.arange(dcs.size), 0)
            )
            ts = np.empty_like(c)
            ts[order] = c + 1
            return ts + base[dcs]

        Ba = B - B // 16
        counts_a = np.full(R, Ba, np.int32)
        frontier_base = np.zeros((R, R), np.int64)  # [replica, dc]

        def tr_packed():
            dc = rng.integers(0, R, R * Ba).astype(np.int32)
            ts_parts = []
            for r in range(R):
                dcr = dc[r * Ba:(r + 1) * Ba]
                ts_parts.append(seq_ts(dcr, frontier_base[r]))
                frontier_base[r] += np.bincount(dcr, minlength=R)
            ts = np.concatenate(ts_parts).astype(np.int32)
            adds = ("add", counts_a, [
                np.zeros(R * Ba, np.int32),
                rng.integers(0, I, R * Ba).astype(np.int32),
                rng.integers(1, 10**6, R * Ba).astype(np.int32),
                dc, ts,
            ])
            nr = B // 16
            counts_r = np.full(R, nr, np.int32)
            vc_len = np.full(R * nr, R, np.int32)  # dense vc rows
            vc_dc = np.tile(np.arange(R, dtype=np.int32), R * nr)
            vc_ts = rng.integers(1, 50, R * nr * R).astype(np.int32)
            rmvs = ("rmv", counts_r, [
                np.zeros(R * nr, np.int32),
                rng.integers(0, I, R * nr).astype(np.int32),
                vc_len, vc_dc, vc_ts,
            ])
            return [adds, rmvs]

        # Device-native ceiling for the SAME grid and batch shape: K async
        # apply_ops dispatches + one sync — what the server's dispatch
        # loop could sustain over a zero-cost wire. The packed lines below
        # report their fraction of this rate (VERDICT-r4 item 4).
        import jax.numpy as jnp
        from antidote_ccrdt_tpu.models.topk_rmv_dense import TopkRmvOps

        def tr_ops_of(groups):
            (_, _, a_cols), (_, _, r_cols) = groups
            Ba_, nr_ = a_cols[0].size // R, r_cols[0].size // R
            vc = np.zeros((R * nr_, R), np.int32)
            vc[np.repeat(np.arange(R * nr_), r_cols[2]),
               r_cols[3]] = r_cols[4]
            return TopkRmvOps(
                add_key=jnp.asarray(a_cols[0].reshape(R, Ba_)),
                add_id=jnp.asarray(a_cols[1].reshape(R, Ba_)),
                add_score=jnp.asarray(a_cols[2].reshape(R, Ba_)),
                add_dc=jnp.asarray(a_cols[3].reshape(R, Ba_)),
                add_ts=jnp.asarray(a_cols[4].reshape(R, Ba_)),
                rmv_key=jnp.asarray(r_cols[0].reshape(R, nr_)),
                rmv_id=jnp.asarray(r_cols[1].reshape(R, nr_)),
                rmv_vc=jnp.asarray(vc.reshape(R, nr_, R)),
            )

        g_tr = srv._grids[b"w_tr"]  # server keys grids by wire (bytes) name
        dev_ops = tr_ops_of(tr_packed())
        st_dev, _ = g_tr.dense.apply_ops(g_tr.state, dev_ops)  # warm
        np.asarray(st_dev.slot_ts.ravel()[0])
        KDEV = CALLS * 4
        t0 = time.perf_counter()
        st_dev = g_tr.state
        for _ in range(KDEV):
            st_dev, _ = g_tr.dense.apply_ops(st_dev, dev_ops)
        np.asarray(st_dev.slot_ts.ravel()[0])
        # Each dispatch applies R replicas x B ops (the packed lines'
        # counts.sum() counts the same R*B), so the rates compare 1:1.
        native_rate = KDEV * R * B / (time.perf_counter() - t0)
        out.append({
            "metric": f"grid device-native topk_rmv ops/sec (same shape, "
                      f"{R}x{B}/dispatch, async chain + 1 sync)",
            "value": round(native_rate), "unit": "ops/sec",
        })

        rate = timed_packed("w_tr", [tr_packed() for _ in range(CALLS)])
        out.append({
            "metric": f"grid wire topk_rmv ops/sec (packed columns, "
                      f"{R}x{B}/call)",
            "value": round(rate), "unit": "ops/sec",
            "pct_of_device_native": round(100 * rate / native_rate, 1),
        })

        # Pipelined multi-batch surface (round 5, grid_apply_packed_multi):
        # ONE wire call ships MB packed batches; the server validates all,
        # stacks them, and runs the sequential rounds as ONE scan-fused
        # dispatch with a single dominated-count readback. Measured r5
        # progression at this shape: per-call dispatch 265-354k ops/sec
        # (~10% of native, dispatch+sync-bound) -> per-batch deferred
        # dispatches 611k (19%) -> scan-fused 0.96-1.01M (36%), at which
        # point the remaining gap IS the host->device upload of the op
        # planes through the tunnel (~0.9MB/batch; MB=16 does not raise
        # the fraction over MB=8, the signature of a per-byte, not
        # per-call, bound) — the bytes/upload_ms fields below record the
        # decomposition so the fraction reads against the session's
        # tunnel bandwidth, which varies ~5-7x between sessions
        # (BASELINE.md). A PCIe-attached host pays ~0.06ms/batch for the
        # same bytes and would sit at the native ceiling.
        MB = 8

        def timed_packed_multi(gname, calls):
            client.grid_apply_packed_multi(gname, calls[0])  # warm
            n_ops = 0
            t0 = time.perf_counter()
            for batches in calls:
                client.grid_apply_packed_multi(gname, batches)
                n_ops += sum(
                    int(np.asarray(c).sum()) for b in batches for _, c, _ in b
                )
            return n_ops / (time.perf_counter() - t0)

        from antidote_ccrdt_tpu.bridge.server import _bin_col

        built = g_tr._build_topk_rmv_arrays(
            g_tr._parse_packed(
                [(tag, _bin_col(counts), [_bin_col(c) for c in cols])
                 for tag, counts, cols in tr_packed()]
            )
        )[1]

        def pow2_bucket(n, floor=64):
            w = floor
            while w < n:
                w *= 2
            return w

        # The scan path pads each plane's width to the next power of two
        # before upload, so the bytes actually crossing the tunnel per
        # batch are the BUCKETED planes — with the r5 id-packing (key/id/
        # dc -> one i32 per add, key/id -> one per rmv; this grid's
        # NK*I*D fits) that is 3 add planes + 1 rmv plane + the vc rows.
        Ba_b = pow2_bucket(built[0].shape[1])
        Br_b = pow2_bucket(built[5].shape[1])
        one_batch_bytes = 4 * R * (3 * Ba_b + 1 * Br_b + Br_b * g_tr.dense.D)
        rate_m = timed_packed_multi(
            "w_tr", [[tr_packed() for _ in range(MB)] for _ in range(CALLS)]
        )
        out.append({
            "metric": f"grid wire topk_rmv ops/sec (packed multi, "
                      f"{MB}x{R}x{B}/call, scan-fused)",
            "value": round(rate_m), "unit": "ops/sec",
            "pct_of_device_native": round(100 * rate_m / native_rate, 1),
            "upload_bytes_per_batch": one_batch_bytes,
            "bound_by": "host->device upload bandwidth (tunnel)",
        })

        counts_b = np.full(R, B, np.int32)
        packed_simple = {
            "w_tk": lambda: [("add", counts_b, [
                np.zeros(R * B, np.int32),
                rng.integers(0, 10_000, R * B).astype(np.int32),
                rng.integers(1, 10**6, R * B).astype(np.int32),
            ])],
            "w_lb": lambda: [
                ("add", np.full(R, B - 16, np.int32), [
                    np.zeros(R * (B - 16), np.int32),
                    rng.integers(0, 100_000, R * (B - 16)).astype(np.int32),
                    rng.integers(1, 10**6, R * (B - 16)).astype(np.int32),
                ]),
                ("ban", np.full(R, 16, np.int32), [
                    np.zeros(R * 16, np.int32),
                    rng.integers(0, 100_000, R * 16).astype(np.int32),
                ]),
            ],
            "w_av": lambda: [("add", counts_b, [
                rng.integers(0, 64, R * B).astype(np.int32),
                rng.integers(-100, 100, R * B).astype(np.int32),
                np.ones(R * B, np.int32),
            ])],
            "w_wc": lambda: [("add", counts_b, [
                np.zeros(R * B, np.int32),
                ((rng.zipf(1.1, size=R * B) - 1) % 4096).astype(np.int32),
            ])],
            "w_wd": lambda: [("add", counts_b, [
                np.zeros(R * B, np.int32),
                ((rng.zipf(1.1, size=R * B) - 1) % 4096).astype(np.int32),
            ])],
        }
        for gname, tname in (("w_tk", "topk"), ("w_lb", "leaderboard"),
                             ("w_av", "average"), ("w_wc", "wordcount"),
                             ("w_wd", "worddocumentcount")):
            mk = packed_simple[gname]
            rate = timed_packed(gname, [mk() for _ in range(CALLS)])
            out.append({
                "metric": f"grid wire {tname} ops/sec (packed columns, "
                          f"{R}x{B}/call)",
                "value": round(rate), "unit": "ops/sec",
            })

        # batch_merge: N scalar replica states shipped as reference
        # binaries, merged in one batched device pass (the north-star
        # bridge entry point).
        N, NADD = sized((32, 200), (4, 20))
        S = registry.scalar("topk_rmv")
        blobs = []
        for r in range(N):
            st = S.new(100)
            for j in range(NADD):
                st, _ = S.update(
                    ("add", (int(rng.integers(0, 1000)),
                             int(rng.integers(1, 10**6)),
                             (r, j + 1))), st)
            blobs.append(wire_mod.to_reference_binary("topk_rmv", st))
        h = client.batch_merge("topk_rmv", blobs)  # warm compile
        client.free(h)
        t0 = time.perf_counter()
        h = client.batch_merge("topk_rmv", blobs)
        dt = time.perf_counter() - t0
        client.free(h)
        out.append({
            "metric": f"grid wire batch_merge states/sec ({N} binaries)",
            "value": round(N / dt, 1), "unit": "states/sec",
        })
    return out


def bench_delta_payload():
    """Delta-state replication payload at north-star state scale: bytes
    shipped per gossip publish for one op round, vs the full state
    (parallel/delta.py — the inter-DC bandwidth lever)."""
    from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
    from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
    from antidote_ccrdt_tpu.parallel.delta import delta_nbytes, state_delta

    import jax

    R, I, D_DCS, B, Br = sized(
        (32, 100_000, 32, 2048, 128), (4, 5_000, 4, 256, 16)
    )
    D = make_dense(n_ids=I, n_dcs=D_DCS, size=100, slots_per_id=4)
    gen = TopkRmvEffectGen(
        Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=3)
    )
    st = D.init(R, 1)
    st, _ = D.apply_ops(st, gen.next_batch(8192, 512))  # populated baseline
    prev = st
    st, _ = D.apply_ops(st, gen.next_batch(B, Br))
    delta = state_delta(D, prev, st)
    full = sum(np.asarray(x).nbytes for x in jax.tree.leaves(st))
    d = delta_nbytes(delta)
    return {
        "metric": f"delta-state publish payload ({I//1000}k ids x {R} "
                  f"replicas, {B}+{Br} op round)",
        "value": round(full / d, 1),
        "unit": "x smaller than full-state publish",
        "delta_mb": round(d / 1e6, 2),
        "full_mb": round(full / 1e6, 2),
    }


def bench_monoid_delta_payload():
    """Gossip bandwidth for the MONOID plane (parallel/monoid.py): a
    member's row-replace delta publish (its owned rows, whole-row
    payload, self-contained — no chaining) vs the full lifted state.
    Host-side arithmetic over real delta objects; backend-independent."""
    import jax

    from antidote_ccrdt_tpu.models.wordcount import WordcountOps, make_dense
    from antidote_ccrdt_tpu.parallel.delta import delta_nbytes
    from antidote_ccrdt_tpu.parallel.monoid import MonoidLift, monoid_row_delta

    import jax.numpy as jnp

    R, V, B = sized((64, 1 << 16, 4096), (8, 1 << 12, 256))
    lift = MonoidLift(make_dense(V))
    st = lift.init(R, 1)
    rng = np.random.default_rng(0)
    tok = np.full((R, B), -1, np.int32)
    tok[0] = ((rng.zipf(1.1, size=B) - 1) % V).astype(np.int32)
    warm = WordcountOps(key=jnp.zeros((R, B), jnp.int32), token=jnp.asarray(tok))
    st, _ = lift.apply_ops(st, warm, owned=[0])  # member owns row 0
    prev = st
    st, _ = lift.apply_ops(st, warm, owned=[0])
    delta = monoid_row_delta(lift, prev, st)
    full = sum(np.asarray(x).nbytes for x in jax.tree.leaves(st))
    d = delta_nbytes(delta)
    return {
        "metric": f"monoid row-replace delta payload (wordcount V={V>>10}k "
                  f"x {R} replicas, 1 owned row/publish)",
        "value": round(full / d, 1),
        "unit": "x smaller than full-state publish",
        "delta_mb": round(d / 1e6, 3),
        "full_mb": round(full / 1e6, 2),
    }


def main():
    import jax

    tiny = bool(os.environ.get("CCRDT_BENCH_TINY"))
    for fn in (bench_average, bench_topk, bench_leaderboard, bench_wordcount,
               bench_compaction, bench_grid_wire, bench_delta_payload,
               bench_monoid_delta_payload, bench_worddocumentcount):
        out = fn()
        for rec in out if isinstance(out, list) else [out]:
            rec["backend"] = jax.default_backend()
            if tiny:
                # Smoke-mode records must never read as real measurements
                # (clamped dims also floor the "Nk"-style labels to 0k).
                rec["tiny"] = True
                rec["metric"] = "[TINY SMOKE] " + rec["metric"]
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
