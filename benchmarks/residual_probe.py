"""Round-4 probes attacking the named slices of the apply round (the
round-3 attribution's "residual_fusion" is now decomposed by the XLA
device-timeline profile — benchmarks/profile_north_star.py /
profile_r04.json):

    11.2ms  tombstone one-hot MXU matmul        (dense_table.py:96)
    15.4ms  3x delta scalar scatters            (topk_rmv_dense.py:533-535)
     3.9ms  tombstone 7-bit plane unpack + max  (dense_table.py:102-103)
     4.7ms  32x per-DC slices of rmv_vc feeding the D-step dom lookup
            (_live_mask/_filter_slots, topk_rmv_dense.py:147/163) +
     2.3ms  their select chains
     3.7ms  the 4-key add sort
     4.0ms  join cross-compares + rank one-hot placement
     ~.9ms  rmv dedup (argsort custom calls)
     1.4ms  conv input slice/pad
     4.2ms  486 slices under 0.15ms

Probes (each is the FULL apply with one piece restructured — composition
timing, same discipline as ablate_apply.py):

  A. baseline: current apply_ops.
  B. delta scatters with indices_are_sorted=True + unique_indices=True —
     (kid3, rank) IS sorted-unique by construction (sorted by kid asc,
     rank asc within group; rank collisions impossible).
  C. dom lookup via one-hot multiply-reduce over D instead of the 32-step
     slice/select chain (one fused [.., M, D] reduce; no T(1,128) slices).
  D. dom lookup via a log2(D) binary select tree on the bits of dc.
  E. tombstones via XLA scatter-max over row-sorted updates with
     indices_are_sorted=True (replacing the one-hot MXU matmul + unpack).
  F. best combination of the winners.

Run: [PROBE_B=32768 PROBE_BR=2048] python benchmarks/residual_probe.py [filters]
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _cmp_better,
    make_dense,
)
from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu

R, NK, I, D_DCS, K, M = 32, 1, 100_000, 32, 100, 4
B = int(os.environ.get("PROBE_B", 32768))
Br = int(os.environ.get("PROBE_BR", 2048))
REPS = int(os.environ.get("PROBE_REPS", 12))

D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
state0 = D.init(n_replicas=R, n_keys=1)
gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7))
warm = gen.next_batch(B, Br)
state0, _ = D.apply_ops(state0, warm, collect_dominated=False)
stacked = jax.tree.map(
    lambda *xs: jnp.stack(xs), *[gen.next_batch(B, Br) for _ in range(REPS)]
)

SELECT = sys.argv[1:]


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def timeit(name, step_fn, expect=None):
    if SELECT and not any(s in name for s in SELECT):
        return None

    @jax.jit
    def run(c, seq):
        def body(c, ops):
            return step_fn(c, ops), ()
        out, _ = lax.scan(body, c, seq)
        return out

    out = run(state0, stacked)
    sync(out)
    t0 = time.perf_counter()
    out = run(state0, stacked)
    sync(out)
    ms = (time.perf_counter() - t0) / REPS * 1e3
    ok = ""
    if expect is not None:
        same = all(
            bool(jnp.array_equal(a, b))
            for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(expect))
        )
        ok = "  state==baseline" if same else "  STATE MISMATCH"
    print(f"{name:58s} {ms:9.2f} ms{ok}")
    return out


# --- dom lookup variants ---------------------------------------------------


def dom_select_loop(dc, rmv_vc):
    """Current production path: D-step broadcast-select."""
    dom = jnp.zeros(dc.shape, jnp.int32)
    for d in range(rmv_vc.shape[-1]):
        dom = jnp.where(dc == d, rmv_vc[..., d : d + 1], dom)
    return dom


def dom_onehot_reduce(dc, rmv_vc):
    """One fused one-hot multiply + reduce over D (no strided slices)."""
    Dd = rmv_vc.shape[-1]
    oh = dc[..., None] == jnp.arange(Dd, dtype=dc.dtype)  # [.., M, D]
    return jnp.max(
        jnp.where(oh, rmv_vc[..., None, :], 0), axis=-1
    )


def dom_bit_tree(dc, rmv_vc):
    """log2(D) binary select tree on dc's bits. Level k halves the
    candidate table along D by selecting on bit k (little-endian)."""
    Dd = rmv_vc.shape[-1]
    cand = jnp.broadcast_to(
        rmv_vc[..., None, :], (*dc.shape, Dd)
    )  # [.., M, D]
    bit = 0
    while cand.shape[-1] > 1:
        half = cand.shape[-1] // 2
        lo = cand[..., 0::2]
        hi = cand[..., 1::2]
        sel = ((dc >> bit) & 1).astype(bool)[..., None]
        cand = jnp.where(sel, hi, lo)
        bit += 1
    return cand[..., 0]


def make_variant(dom_fn=dom_select_loop, scatter_hints=False, tomb="mxu"):
    def live_mask(dcs, ts, rmv_vc):
        return ts > dom_fn(dcs, rmv_vc)

    def join_slots(a, b, rmv_vc, m_keep):
        a_s, a_d, a_t = a
        b_s, b_d, b_t = b
        live_a = live_mask(a_d, a_t, rmv_vc)
        live_b = live_mask(b_d, b_t, rmv_vc)
        A = lambda x: x[..., :, None]  # noqa: E731
        Bx = lambda x: x[..., None, :]  # noqa: E731
        a_beats_b = _cmp_better(A(a_s), A(a_t), A(a_d), Bx(b_s), Bx(b_t), Bx(b_d))
        eq = (A(a_s) == Bx(b_s)) & (A(a_t) == Bx(b_t)) & (A(a_d) == Bx(b_d))
        live_b = live_b & ~jnp.any(eq & A(live_a), axis=-2)
        b_beats_a = ~a_beats_b & ~eq
        la = live_a.astype(jnp.int32)
        lb = live_b.astype(jnp.int32)
        pref_a = jnp.cumsum(la, axis=-1) - la
        pref_b = jnp.cumsum(lb, axis=-1) - lb
        r_a = pref_a + jnp.sum(b_beats_a & Bx(live_b), axis=-1)
        r_b = pref_b + jnp.sum(a_beats_b & A(live_a), axis=-2)
        r_a = jnp.where(live_a, r_a, 2 * a_s.shape[-1])
        r_b = jnp.where(live_b, r_b, 2 * b_s.shape[-1])
        ranks = jnp.arange(m_keep, dtype=jnp.int32)
        oh_a = r_a[..., :, None] == ranks
        oh_b = r_b[..., :, None] == ranks

        def place(xa, xb, empty):
            out = jnp.sum(jnp.where(oh_a, xa[..., :, None], 0), axis=-2) + jnp.sum(
                jnp.where(oh_b, xb[..., :, None], 0), axis=-2
            )
            filled = jnp.any(oh_a, axis=-2) | jnp.any(oh_b, axis=-2)
            return jnp.where(filled, out, empty)

        f_score = place(a_s, b_s, NEG_INF)
        f_dc = place(a_d, b_d, 0)
        f_ts = place(a_t, b_t, 0)
        n_live = jnp.sum(la, axis=-1) + jnp.sum(lb, axis=-1)
        return f_score, f_dc, f_ts, n_live

    def tombstones(state, ops):
        rmv_valid = (
            (ops.rmv_id >= 0) & (ops.rmv_id < I)
            & (ops.rmv_key >= 0) & (ops.rmv_key < NK)
        )
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        table = state.rmv_vc.reshape(NK * I, D_DCS)
        if tomb == "mxu":
            out = scatter_max_rows_mxu(table, rrow, ops.rmv_vc)
        else:  # sorted XLA scatter-max
            order = jnp.argsort(rrow)
            r_s = jnp.take_along_axis(rrow, order, axis=0)
            u_s = jnp.take_along_axis(ops.rmv_vc, order[:, None], axis=0)
            out = table.at[r_s].max(
                u_s, mode="drop", indices_are_sorted=True
            )
        return out.reshape(NK, I, D_DCS)

    def one(state, ops):
        rmv_vc = tombstones(state, ops)
        add_valid = (
            (ops.add_ts > 0)
            & (ops.add_key >= 0) & (ops.add_key < NK)
            & (ops.add_id >= 0) & (ops.add_id < I)
            & (ops.add_dc >= 0) & (ops.add_dc < D_DCS)
        )
        slot = ops.add_key * D_DCS + ops.add_dc
        hit = slot[:, None] == jnp.arange(NK * D_DCS, dtype=slot.dtype)[None, :]
        contrib = jnp.where(hit & add_valid[:, None], ops.add_ts[:, None], 0)
        vc = jnp.maximum(state.vc, jnp.max(contrib, axis=0).reshape(NK, D_DCS))

        kid = jnp.where(add_valid, ops.add_key * I + ops.add_id, NK * I)
        s_kid, ns, nt, s_dc = lax.sort(
            (kid, -ops.add_score, -ops.add_ts, ops.add_dc), num_keys=4
        )
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
        rank = c - live.astype(jnp.int32) - base
        overflow = live & (rank >= M)
        s_key = s_kid // I
        key_hit = s_key[:, None] == jnp.arange(NK, dtype=s_key.dtype)[None, :]
        lossy = state.lossy | jnp.any(overflow[:, None] & key_hit, axis=0)
        rank = jnp.where(live & (rank < M), rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)
        hints = (
            dict(indices_are_sorted=True, unique_indices=True)
            if scatter_hints
            else {}
        )
        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_score = d_score.at[kid3, rank].set(s_score, mode="drop", **hints).reshape(NK, I, M)
        d_dc = d_dc.at[kid3, rank].set(s_dc, mode="drop", **hints).reshape(NK, I, M)
        d_ts = d_ts.at[kid3, rank].set(s_ts, mode="drop", **hints).reshape(NK, I, M)

        f_score, f_dc, f_ts, n_live = join_slots(
            (state.slot_score, state.slot_dc, state.slot_ts),
            (d_score, d_dc, d_ts),
            rmv_vc,
            M,
        )
        lossy = lossy | jnp.any(n_live > M, axis=-1)
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)

    def step(st, ops):
        return jax.vmap(one)(st, ops)

    return step


def current(st, ops):
    s, _ = D.apply_ops(st, ops, collect_dominated=False)
    return s


base = timeit("A. baseline apply_ops (current code)", current)
timeit("A'. re-impl sanity (should ~= A)", make_variant(), expect=base)
timeit("B. sorted+unique hints on delta scatters", make_variant(scatter_hints=True), expect=base)
timeit("C. dom via one-hot multiply-reduce", make_variant(dom_fn=dom_onehot_reduce), expect=base)
timeit("D. dom via log-D bit select tree", make_variant(dom_fn=dom_bit_tree), expect=base)
timeit("E. tombstones via sorted XLA scatter-max", make_variant(tomb="sorted_scatter"), expect=base)
timeit("F. B+C", make_variant(dom_fn=dom_onehot_reduce, scatter_hints=True), expect=base)
timeit("G. B+C+E", make_variant(dom_fn=dom_onehot_reduce, scatter_hints=True, tomb="sorted_scatter"), expect=base)


# --- H: two delta scatters via (ts << 5) | dc packing ----------------------
# dc < 32 needs 5 bits; ts fits 26 bits in the overwhelmingly common case
# (logical clocks; i32 state bounds ts < 2^31 already). The packed path
# runs when max(ts) < 2^26, guarded by a lax.cond that falls back to the
# 3-scatter path — correctness is unconditional, the win is conditional.


def make_two_scatter(dom_fn=dom_onehot_reduce):
    base_variant = make_variant(dom_fn=dom_fn)

    def one(state, ops):
        rmv_valid = (
            (ops.rmv_id >= 0) & (ops.rmv_id < I)
            & (ops.rmv_key >= 0) & (ops.rmv_key < NK)
        )
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        table = state.rmv_vc.reshape(NK * I, D_DCS)
        rmv_vc = scatter_max_rows_mxu(table, rrow, ops.rmv_vc).reshape(NK, I, D_DCS)

        add_valid = (
            (ops.add_ts > 0)
            & (ops.add_key >= 0) & (ops.add_key < NK)
            & (ops.add_id >= 0) & (ops.add_id < I)
            & (ops.add_dc >= 0) & (ops.add_dc < D_DCS)
        )
        slot = ops.add_key * D_DCS + ops.add_dc
        hit = slot[:, None] == jnp.arange(NK * D_DCS, dtype=slot.dtype)[None, :]
        contrib = jnp.where(hit & add_valid[:, None], ops.add_ts[:, None], 0)
        vc = jnp.maximum(state.vc, jnp.max(contrib, axis=0).reshape(NK, D_DCS))

        kid = jnp.where(add_valid, ops.add_key * I + ops.add_id, NK * I)
        s_kid, ns, nt, s_dc = lax.sort(
            (kid, -ops.add_score, -ops.add_ts, ops.add_dc), num_keys=4
        )
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
        rank = c - live.astype(jnp.int32) - base
        overflow = live & (rank >= M)
        s_key = s_kid // I
        key_hit = s_key[:, None] == jnp.arange(NK, dtype=s_key.dtype)[None, :]
        lossy = state.lossy | jnp.any(overflow[:, None] & key_hit, axis=0)
        rank = jnp.where(live & (rank < M), rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)

        def packed(_):
            d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
            d_td = jnp.zeros((NK * I, M), dtype=jnp.int32)
            d_score = d_score.at[kid3, rank].set(s_score, mode="drop")
            d_td = d_td.at[kid3, rank].set((s_ts << 5) | s_dc, mode="drop")
            return d_score, d_td >> 5, d_td & 31

        def unpacked(_):
            d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
            d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
            d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
            d_score = d_score.at[kid3, rank].set(s_score, mode="drop")
            d_dc = d_dc.at[kid3, rank].set(s_dc, mode="drop")
            d_ts = d_ts.at[kid3, rank].set(s_ts, mode="drop")
            return d_score, d_ts, d_dc

        d_score, d_ts, d_dc = lax.cond(
            jnp.max(s_ts) < (1 << 26), packed, unpacked, operand=None
        )
        d_score = d_score.reshape(NK, I, M)
        d_ts = d_ts.reshape(NK, I, M)
        d_dc = d_dc.reshape(NK, I, M)

        def live_mask(dcs, ts, rv):
            return ts > dom_fn(dcs, rv)

        from antidote_ccrdt_tpu.models.topk_rmv_dense import _join_slots
        f_score, f_dc, f_ts, n_live = _join_slots(
            (state.slot_score, state.slot_dc, state.slot_ts),
            (d_score, d_dc, d_ts),
            rmv_vc,
            M,
        )
        lossy = lossy | jnp.any(n_live > M, axis=-1)
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)

    def step(st, ops):
        return jax.vmap(one)(st, ops)

    return step


timeit("H. 2-scatter (ts<<5|dc) + cond fallback (dom=select)", make_two_scatter(dom_fn=dom_select_loop), expect=base)
timeit("I. 2-scatter + dom one-hot reduce", make_two_scatter(), expect=base)


# --- J: M-major delta scatter ----------------------------------------------
# The compiled HLO lays slot tables out I-minor/M-major ([4][R][100k]
# physical), so the 2-D scalar scatters into logical [NK*I, M] each pay
# two transposes inside the scatter fusion. Scatter into [M, NK*I] with
# (rank, kid) indices instead — matching the physical layout — and hand
# the join a moveaxis view.


def make_mmajor(dom_fn=dom_onehot_reduce):
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _join_slots

    def one(state, ops):
        rmv_valid = (
            (ops.rmv_id >= 0) & (ops.rmv_id < I)
            & (ops.rmv_key >= 0) & (ops.rmv_key < NK)
        )
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        table = state.rmv_vc.reshape(NK * I, D_DCS)
        rmv_vc = scatter_max_rows_mxu(table, rrow, ops.rmv_vc).reshape(NK, I, D_DCS)

        add_valid = (
            (ops.add_ts > 0)
            & (ops.add_key >= 0) & (ops.add_key < NK)
            & (ops.add_id >= 0) & (ops.add_id < I)
            & (ops.add_dc >= 0) & (ops.add_dc < D_DCS)
        )
        slot = ops.add_key * D_DCS + ops.add_dc
        hit = slot[:, None] == jnp.arange(NK * D_DCS, dtype=slot.dtype)[None, :]
        contrib = jnp.where(hit & add_valid[:, None], ops.add_ts[:, None], 0)
        vc = jnp.maximum(state.vc, jnp.max(contrib, axis=0).reshape(NK, D_DCS))

        kid = jnp.where(add_valid, ops.add_key * I + ops.add_id, NK * I)
        s_kid, ns, nt, s_dc = lax.sort(
            (kid, -ops.add_score, -ops.add_ts, ops.add_dc), num_keys=4
        )
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
        rank = c - live.astype(jnp.int32) - base
        overflow = live & (rank >= M)
        s_key = s_kid // I
        key_hit = s_key[:, None] == jnp.arange(NK, dtype=s_key.dtype)[None, :]
        lossy = state.lossy | jnp.any(overflow[:, None] & key_hit, axis=0)
        rank = jnp.where(live & (rank < M), rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)

        # [M, NK*I] tables, (rank, kid) indices: no transposes needed to
        # reach the I-minor physical layout the join consumes.
        d_score = jnp.full((M, NK * I), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((M, NK * I), dtype=jnp.int32)
        d_ts = jnp.zeros((M, NK * I), dtype=jnp.int32)
        d_score = d_score.at[rank, kid3].set(s_score, mode="drop")
        d_dc = d_dc.at[rank, kid3].set(s_dc, mode="drop")
        d_ts = d_ts.at[rank, kid3].set(s_ts, mode="drop")
        mm = lambda x: jnp.moveaxis(x.reshape(M, NK, I), 0, -1)  # noqa: E731

        f_score, f_dc, f_ts, n_live = _join_slots(
            (state.slot_score, state.slot_dc, state.slot_ts),
            (mm(d_score), mm(d_dc), mm(d_ts)),
            rmv_vc,
            M,
        )
        lossy = lossy | jnp.any(n_live > M, axis=-1)
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)

    def step(st, ops):
        return jax.vmap(one)(st, ops)

    return step


timeit("J. M-major delta scatters + dom select-loop", make_mmajor(dom_fn=dom_select_loop), expect=base)
timeit("K. M-major delta scatters + dom one-hot reduce", make_mmajor(), expect=base)


# --- L: two scatters via i64 (ts << 5) | dc, static under x64 --------------
# (ts < 2^31) | dc < 32 always fits 36 bits: no range cliff, no cond.
# Needs JAX_ENABLE_X64=1; the probe self-skips otherwise.
# --- M: hand-rolled log-step run-max replacing associative_scan ------------


def dedup_logstep(rows, upd, n_rows):
    order = jnp.argsort(rows)
    r_s = jnp.take_along_axis(rows, order, axis=0)
    u_s = jnp.take_along_axis(upd, order[:, None], axis=0)
    total = u_s
    k = 1
    n = rows.shape[0]
    while k < n:
        # suffix run-max: pull from k ahead while still in the same run
        r_shift = jnp.concatenate([r_s[k:], jnp.full((k,), -1, r_s.dtype)])
        t_shift = jnp.concatenate([total[k:], jnp.zeros((k, upd.shape[1]), total.dtype)])
        same = (r_s == r_shift)[:, None]
        total = jnp.where(same, jnp.maximum(total, t_shift), total)
        k *= 2
    is_head = jnp.concatenate([jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
    head_rows = jnp.where(is_head, r_s, n_rows)
    return head_rows, total


def scatter_max_rows_mxu_logstep(table, rows, upd):
    T, Dd = table.shape
    head_rows, total = dedup_logstep(rows, upd, T)
    onehot = (head_rows[:, None] == jnp.arange(T, dtype=jnp.int32)[None, :]).astype(jnp.int8)
    n_planes = 5
    planes = jnp.concatenate(
        [((total >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(n_planes)], axis=-1
    )
    out = lax.dot_general(
        onehot, planes, (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    delta = jnp.zeros((T, Dd), jnp.int32)
    for k in range(n_planes):
        delta = delta | (out[:, k * Dd : (k + 1) * Dd] << (7 * k))
    return jnp.maximum(table, delta)


def make_l_or_m(i64_pack=False, logstep_dedup=False):
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _join_slots

    def one(state, ops):
        rmv_valid = (
            (ops.rmv_id >= 0) & (ops.rmv_id < I)
            & (ops.rmv_key >= 0) & (ops.rmv_key < NK)
        )
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NK * I)
        table = state.rmv_vc.reshape(NK * I, D_DCS)
        fn = scatter_max_rows_mxu_logstep if logstep_dedup else scatter_max_rows_mxu
        rmv_vc = fn(table, rrow, ops.rmv_vc).reshape(NK, I, D_DCS)

        add_valid = (
            (ops.add_ts > 0)
            & (ops.add_key >= 0) & (ops.add_key < NK)
            & (ops.add_id >= 0) & (ops.add_id < I)
            & (ops.add_dc >= 0) & (ops.add_dc < D_DCS)
        )
        slot = ops.add_key * D_DCS + ops.add_dc
        hit = slot[:, None] == jnp.arange(NK * D_DCS, dtype=slot.dtype)[None, :]
        contrib = jnp.where(hit & add_valid[:, None], ops.add_ts[:, None], 0)
        vc = jnp.maximum(state.vc, jnp.max(contrib, axis=0).reshape(NK, D_DCS))

        kid = jnp.where(add_valid, ops.add_key * I + ops.add_id, NK * I)
        s_kid, ns, nt, s_dc = lax.sort(
            (kid, -ops.add_score, -ops.add_ts, ops.add_dc), num_keys=4
        )
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - live.astype(jnp.int32), -1))
        rank = c - live.astype(jnp.int32) - base
        overflow = live & (rank >= M)
        s_key = s_kid // I
        key_hit = s_key[:, None] == jnp.arange(NK, dtype=s_key.dtype)[None, :]
        lossy = state.lossy | jnp.any(overflow[:, None] & key_hit, axis=0)
        rank = jnp.where(live & (rank < M), rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)

        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_score = d_score.at[kid3, rank].set(s_score, mode="drop").reshape(NK, I, M)
        if i64_pack:
            tsdc = (s_ts.astype(jnp.int64) << 5) | s_dc.astype(jnp.int64)
            d_tsdc = jnp.zeros((NK * I, M), dtype=jnp.int64)
            d_tsdc = d_tsdc.at[kid3, rank].set(tsdc, mode="drop").reshape(NK, I, M)
            d_ts = (d_tsdc >> 5).astype(jnp.int32)
            d_dc = (d_tsdc & 31).astype(jnp.int32)
        else:
            d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
            d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
            d_dc = d_dc.at[kid3, rank].set(s_dc, mode="drop").reshape(NK, I, M)
            d_ts = d_ts.at[kid3, rank].set(s_ts, mode="drop").reshape(NK, I, M)

        f_score, f_dc, f_ts, n_live = _join_slots(
            (state.slot_score, state.slot_dc, state.slot_ts),
            (d_score, d_dc, d_ts),
            rmv_vc,
            M,
        )
        lossy = lossy | jnp.any(n_live > M, axis=-1)
        return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)

    def step(st, ops):
        return jax.vmap(one)(st, ops)

    return step


if jax.config.jax_enable_x64:
    timeit("L. i64-packed tsdc scatter (x64, static)", make_l_or_m(i64_pack=True), expect=base)
    timeit("L'. x64 on, 3-scatter control", make_l_or_m(), expect=base)
timeit("M. log-step run-max dedup (no associative_scan)", make_l_or_m(logstep_dedup=True), expect=base)
