"""Delta-build restructuring probe: can the 3 scalar scatters go?

The round-4 device profile (benchmarks/profile_r04.json) puts the three
delta scalar scatters at ~5.13ms EACH at north-star shapes — the largest
attackable slice of the apply round (~15.4 of ~52ms). residual_probe.py
already rejected scatter-shape variants (triple-window, flat 1-D, M-major,
sorted/unique hints, i64 packing); this probe tests formulations that
REPLACE scatters with gathers (TPU gathers parallelize; XLA's scatter
loop serializes):

  * scatter3 (baseline) — the production build: 3 scalar 2-D scatters
    over identical (kid, rank) indices.
  * scatter1_gather3 — ONE scatter of the sorted POSITION index into the
    [NK*I, M] table, then three flat gathers s_field[pos] (the payload
    table is only B elements — the gather source fits VMEM).
  * search_gather3 — ZERO scatters: output addresses o = kid*M + rank are
    strictly increasing over kept entries, so cummax(where(keep, o, -1))
    is sorted and p(a) = searchsorted(om, a) recovers the source position
    for every output address by binary search; 3 flat gathers follow.

Each variant is timed in a scan over fresh op batches with the sort
included (the sort is shared by all variants, so deltas isolate the
build step), and every variant is checked element-equal against the
baseline tables before timing.

VERDICT (measured v5e, tunneled backend, REPS=12, all equivalence-OK):

    scatter3 (production)          23.9  ms/round
    scatter1_gather3              829.2  ms/round   (35x)
    search_gather3               3101.2  ms/round  (130x)
    sort_block_expand_128         806.8  ms/round   (34x)
    sort_block_expand_500         207.7  ms/round    (9x)

Data-dependent gathers and vmap(dynamic_slice) windows are poison on
this backend at these shapes — even ~800 block-slices per replica cost
~8x the whole scatter build, and scaling block size shows the cost is
per-slice, not per-byte. The production 3-scatter build stands; this
file is the measured rejection protecting it (VERDICT-r3 discipline:
negative results committed next to the code they protect).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import NEG_INF
from antidote_ccrdt_tpu.utils.benchtime import stack_rounds

R, NK, I, D_DCS, M = 32, 1, 100_000, 32, 4
B, Br = 32768, 2048
REPS = int(os.environ.get("DELTA_REPS", 12))

gen = TopkRmvEffectGen(
    Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
)
stacked = stack_rounds([gen.next_batch(B, Br) for _ in range(REPS)])
one = jax.tree.map(lambda x: x[0], stacked)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def sorted_adds(ops):
    """The shared sort + rank stage (verbatim semantics of
    _apply_one_replica steps 3a-3c), vmapped over replicas."""
    def per_replica(key, id_, score, ts, dc):
        add_valid = (
            (ts > 0)
            & (key >= 0) & (key < NK)
            & (id_ >= 0) & (id_ < I)
            & (dc >= 0) & (dc < D_DCS)
        )
        kid = jnp.where(add_valid, key * I + id_, NK * I)
        s_kid, ns, nt, s_dc = lax.sort((kid, -score, -ts, dc), num_keys=4)
        s_score, s_ts = -ns, -nt
        dup = (
            (s_kid == jnp.roll(s_kid, 1))
            & (s_score == jnp.roll(s_score, 1))
            & (s_ts == jnp.roll(s_ts, 1))
            & (s_dc == jnp.roll(s_dc, 1))
        )
        dup = dup.at[0].set(False)
        live = (s_kid < NK * I) & ~dup
        grp_start = (s_kid != jnp.roll(s_kid, 1)).at[0].set(True)
        c = jnp.cumsum(live.astype(jnp.int32))
        base = lax.cummax(
            jnp.where(grp_start, c - live.astype(jnp.int32), -1)
        )
        rank = c - live.astype(jnp.int32) - base
        keep = live & (rank < M)
        rank = jnp.where(keep, rank, M)
        kid3 = jnp.where(live, s_kid, NK * I)
        return s_score, s_ts, s_dc, kid3, rank, keep

    return jax.vmap(per_replica)(
        ops.add_key, ops.add_id, ops.add_score, ops.add_ts, ops.add_dc
    )


def scatter3(s_score, s_ts, s_dc, kid3, rank, keep):
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        d_score = jnp.full((NK * I, M), NEG_INF, dtype=jnp.int32)
        d_dc = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_ts = jnp.zeros((NK * I, M), dtype=jnp.int32)
        d_score = d_score.at[kid3, rank].set(s_score, mode="drop")
        d_dc = d_dc.at[kid3, rank].set(s_dc, mode="drop")
        d_ts = d_ts.at[kid3, rank].set(s_ts, mode="drop")
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def scatter1_gather3(s_score, s_ts, s_dc, kid3, rank, keep):
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        Bl = s_score.shape[0]
        pos = jnp.full((NK * I, M), Bl, dtype=jnp.int32)  # B = "no entry"
        p = jnp.arange(Bl, dtype=jnp.int32)
        pos = pos.at[kid3, rank].set(p, mode="drop")
        hit = pos < Bl
        gp = jnp.where(hit, pos, 0)
        d_score = jnp.where(hit, s_score[gp], NEG_INF)
        d_dc = jnp.where(hit, s_dc[gp], 0)
        d_ts = jnp.where(hit, s_ts[gp], 0)
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def search_gather3(s_score, s_ts, s_dc, kid3, rank, keep):
    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        Bl = s_score.shape[0]
        o = jnp.where(keep, kid3 * M + rank, -1)
        om = lax.cummax(o)  # sorted: o strictly increases over kept entries
        addr = jnp.arange(NK * I * M, dtype=jnp.int32)
        p = jnp.searchsorted(om, addr, side="left").astype(jnp.int32)
        gp = jnp.minimum(p, Bl - 1)
        hit = (om[gp] == addr) & (p < Bl)
        d_score = jnp.where(hit, s_score[gp], NEG_INF).reshape(NK * I, M)
        d_dc = jnp.where(hit, s_dc[gp], 0).reshape(NK * I, M)
        d_ts = jnp.where(hit, s_ts[gp], 0).reshape(NK * I, M)
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


def sort_block_expand(s_score, s_ts, s_dc, kid3, rank, keep, blk=128):
    """Zero data-dependent scatters/gathers: one extra sort compacts the
    kept entries by output address o = kid*M + rank (o is unique, so the
    kept stream is strictly increasing); then each BLK-address output
    block holds AT MOST BLK entries (one per address), so a
    vmap(dynamic_slice) window of BLK entries starting at
    searchsorted(o, block_start) covers every block, and the expansion
    is a bounded [BLK x BLK] one-hot select-sum per block."""
    OUT = NK * I * M
    assert OUT % blk == 0, f"blk must divide the output size {OUT}"
    SENT = jnp.int32(2**30)

    def per_replica(s_score, s_ts, s_dc, kid3, rank, keep):
        Bl = s_score.shape[0]
        o = jnp.where(keep, kid3 * M + rank, SENT)
        o_s, sc_s, dc_s, ts_s = lax.sort(
            (o, s_score, s_dc, s_ts), num_keys=1
        )
        nb = OUT // blk
        starts = jnp.arange(nb, dtype=jnp.int32) * blk
        offs = jnp.searchsorted(o_s, starts, side="left").astype(jnp.int32)
        offs = jnp.minimum(offs, Bl - blk)

        def window(x):
            return jax.vmap(
                lambda off: lax.dynamic_slice(x, (off,), (blk,))
            )(offs)  # [nb, blk]

        wo, wsc, wdc, wts = window(o_s), window(sc_s), window(dc_s), window(ts_s)
        addr = starts[:, None] + jnp.arange(blk, dtype=jnp.int32)[None, :]

        def expand(wx, empty):
            # The one-hot is recomputed PER FIELD on purpose: a shared
            # `oh` becomes a CSE'd materialized [nb, blk, blk] i32
            # intermediate (measured: 24.4G HBM request, OOM); duplicated
            # compares let XLA fuse each select-reduce into its own loop.
            oh = wo[:, :, None] == addr[:, None, :]
            out = jnp.sum(jnp.where(oh, wx[:, :, None], 0), axis=1)
            return jnp.where(jnp.any(oh, axis=1), out, empty)

        d_score = expand(wsc, NEG_INF).reshape(NK * I, M)
        d_dc = expand(wdc, 0).reshape(NK * I, M)
        d_ts = expand(wts, 0).reshape(NK * I, M)
        return d_score, d_dc, d_ts

    return jax.vmap(per_replica)(s_score, s_ts, s_dc, kid3, rank, keep)


VARIANTS = {
    "scatter3 (production)": scatter3,
    "scatter1_gather3": scatter1_gather3,
    "search_gather3": search_gather3,
    "sort_block_expand_128": sort_block_expand,
    "sort_block_expand_500": lambda *a: sort_block_expand(*a, blk=500),
}


def main():
    print(f"# backend={jax.default_backend()} R={R} B={B} REPS={REPS}")
    sel = sys.argv[1:]

    # Correctness first: every variant must reproduce the baseline tables.
    # One replica only — some variants' unfused equivalence graphs would
    # otherwise materialize [R, nb, blk, blk] intermediates and OOM.
    srt = jax.tree.map(lambda x: x[:1], sorted_adds(one))
    want = scatter3(*srt)
    for name, fn in VARIANTS.items():
        if name == "scatter3 (production)":
            continue
        if sel and not any(s in name for s in sel):
            continue
        got = fn(*srt)
        ok = all(bool(jnp.array_equal(g, w)) for g, w in zip(got, want))
        print(f"# equivalence {name}: {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    for name, fn in VARIANTS.items():
        if sel and not any(s in name for s in sel):
            continue

        @jax.jit
        def run(stacked, fn=fn):
            def body(carry, ops):
                srt = sorted_adds(ops)
                ds, dd, dt = fn(*srt)
                # Opaque reduction keeps all three tables live.
                return carry + jnp.sum(ds) + jnp.sum(dd) + jnp.sum(dt), ()
            out, _ = lax.scan(body, jnp.zeros((), jnp.int32), stacked)
            return out

        sync(run(stacked))
        t0 = time.perf_counter()
        sync(run(stacked))
        ms = (time.perf_counter() - t0) / REPS * 1e3
        print(f"{name:32s} {ms:9.3f} ms/round (sort included)", flush=True)


if __name__ == "__main__":
    main()
