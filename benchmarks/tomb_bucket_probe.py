"""Probe: block-bucketed one-hot tombstone update vs the full one-hot.

VERDICT-r2 task 2 candidate: the production `scatter_max_rows_mxu`
multiplies a [Br, T] one-hot against T=100k rows to update <= Br=2048 —
MACs = Br * T * 5D per replica. A two-level decomposition buckets the
(deduped, sorted) update rows by table block first:

  level 1: route each update into its block bucket [NB, CAP, ...]
           (NB = T/Bk blocks, CAP slots per block; rank-within-block via
           the same segmented-rank idiom as the delta build);
  level 2: expand each bucket onto its block's rows and max into the
           table — one small batched matmul over planes, contracting CAP
           instead of Br: MACs = T * CAP * 5D.

MAC ratio vs full: Br / CAP (2048/64 = 32x fewer). CAP overflow (an
adversarial batch concentrating > CAP distinct removal ids in one
512-row block) falls back to the full one-hot via lax.cond — both
branches return the same [T, D] table, typical batches take the fast
path.

Variants measured INSIDE the full apply at north-star shapes (the pallas
lesson: isolated wins can compose into regressions):
  full      — production scatter_max_rows_mxu
  bucketM   — bucket via small one-hot matmul, expand via planes matmul
  bucketS   — bucket via scalar 2-D scatters, expand via planes matmul

Honest timing: scan-fused windows + host-readback sync (benchtime).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import make_dense
from antidote_ccrdt_tpu.ops.dense_table import dedup_rows_run_max

R, NK, I, D_DCS, K, M, B, Br, REPS = 32, 1, 100_000, 32, 100, 4, 32768, 2048, 8
BK, CAP = 512, 64
N_PLANES = 5

D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)
state0 = D.init(n_replicas=R, n_keys=1)
gen = TopkRmvEffectGen(Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7))
warm = gen.next_batch(B, Br)
state0, _ = D.apply_ops(state0, warm, collect_dominated=False)
stacked = jax.tree.map(
    lambda *xs: jnp.stack(xs), *[gen.next_batch(B, Br) for _ in range(REPS)]
)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def bucketed_scatter_max(table, rows, upd, via_matmul):
    """table.at[rows].max(upd) via block bucketing; exact fallback to the
    full one-hot when any block overflows CAP."""
    from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu

    T, Dl = table.shape
    NB = (T + BK - 1) // BK
    head_rows, total = dedup_rows_run_max(rows, upd, T)  # sorted by row
    # Compact: stable-sort by block so dedup sentinels (head_rows == T,
    # block NB) move to the end and same-block heads become contiguous —
    # otherwise sentinel interludes reset the segmented rank mid-block
    # and two heads collide on (block, rank).
    blk0 = jnp.where(head_rows < T, head_rows // BK, NB)
    order = jnp.argsort(blk0)  # stable: row order preserved within block
    blk = blk0[order]
    hr = head_rows[order]
    total = total[order]
    valid = hr < T
    off = jnp.where(valid, hr % BK, BK)
    grp_start = (blk != jnp.roll(blk, 1)).at[0].set(True)
    c = jnp.cumsum(valid.astype(jnp.int32))
    base = lax.cummax(jnp.where(grp_start, c - valid.astype(jnp.int32), -1))
    rank = c - valid.astype(jnp.int32) - base
    overflow = jnp.any(valid & (rank >= CAP))
    slot = jnp.where(valid & (rank < CAP), blk * CAP + rank, NB * CAP)
    head_rows = hr

    def fast(args):
        table, head_rows, total = args
        planes = jnp.stack(
            [((total >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(N_PLANES)],
            axis=-1,
        ).reshape(Br, N_PLANES * Dl)  # [Br, 5D] (plane-major per lane)
        if via_matmul:
            onehot = (
                slot[:, None] == jnp.arange(NB * CAP, dtype=jnp.int32)[None, :]
            ).astype(jnp.int8)  # [Br, NB*CAP]
            val_tbl = lax.dot_general(
                onehot, planes, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.int8).reshape(NB, CAP, N_PLANES * Dl)
            # off in [0, BK) needs two s8 planes (BK=512 > 127).
            off_pl = jnp.stack(
                [((off + 1) & 0x7F).astype(jnp.int8),
                 (((off + 1) >> 7) & 0x7F).astype(jnp.int8)], axis=-1
            )  # [Br, 2]
            op_out = lax.dot_general(
                onehot, off_pl, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [NB*CAP, 2]
            off_tbl = (
                (op_out[:, 0] | (op_out[:, 1] << 7)) - 1
            ).reshape(NB, CAP)  # empty -> -1
        else:
            val_tbl = (
                jnp.zeros((NB * CAP + 1, N_PLANES * Dl), jnp.int8)
                .at[slot].set(planes, mode="drop")[: NB * CAP]
                .reshape(NB, CAP, N_PLANES * Dl)
            )
            off_tbl = (
                jnp.full((NB * CAP + 1,), -1, jnp.int32)
                .at[slot].set(off, mode="drop")[: NB * CAP]
                .reshape(NB, CAP)
            )
        # level 2: expand buckets onto block rows (contract CAP on the MXU)
        onehot2 = (
            off_tbl[:, :, None] == jnp.arange(BK, dtype=jnp.int32)[None, None, :]
        ).astype(jnp.int8)  # [NB, CAP, BK]
        out = lax.dot_general(
            onehot2, val_tbl,
            (((1,), (1,)), ((0,), (0,))),  # contract CAP, batch NB
            preferred_element_type=jnp.int32,
        )  # [NB, BK, 5D]
        delta = jnp.zeros((NB * BK, Dl), jnp.int32)
        flat = out.reshape(NB * BK, N_PLANES, Dl)
        for k in range(N_PLANES):
            delta = delta | (flat[:, k, :] << (7 * k))
        return jnp.maximum(table, delta[:T])

    def slow(args):
        table, head_rows, total = args
        return scatter_max_rows_mxu(table, head_rows, total)

    return lax.cond(overflow, slow, fast, (table, head_rows, total))


def adaptive_scatter_max(table, rows, upd):
    """Full one-hot, but with a runtime-adaptive plane count: vc entries
    (logical-clock timestamps) usually fit 21 bits, so 3 of the 5 planes
    carry zeros — skip them via lax.cond (same output shape either way).
    MACs and the s32 out intermediate both scale with plane count."""
    T, Dl = table.shape
    head_rows, total = dedup_rows_run_max(rows, upd, T)
    onehot = (
        head_rows[:, None] == jnp.arange(T, dtype=jnp.int32)[None, :]
    ).astype(jnp.int8)
    fits = jnp.max(total) < (1 << 21)

    def mk(n_planes):
        def f(_):
            planes = jnp.concatenate(
                [((total >> (7 * k)) & 0x7F).astype(jnp.int8)
                 for k in range(n_planes)], axis=-1,
            )
            out = lax.dot_general(
                onehot, planes, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            delta = jnp.zeros((T, Dl), jnp.int32)
            for k in range(n_planes):
                delta = delta | (out[:, k * Dl : (k + 1) * Dl] << (7 * k))
            return jnp.maximum(table, delta)
        return f

    return lax.cond(fits, mk(3), mk(5), None)


def make_step(mode):
    def tombstones(state, ops):
        Rl, NKl = state.rmv_vc.shape[:2]
        rmv_valid = (
            (ops.rmv_id >= 0) & (ops.rmv_id < I)
            & (ops.rmv_key >= 0) & (ops.rmv_key < NKl)
        )
        rrow = jnp.where(rmv_valid, ops.rmv_key * I + ops.rmv_id, NKl * I)
        table = state.rmv_vc.reshape(Rl, NKl * I, D_DCS)
        if mode == "none":
            out = table
        elif mode == "full":
            from antidote_ccrdt_tpu.ops.dense_table import scatter_max_rows_mxu

            out = jax.vmap(scatter_max_rows_mxu)(table, rrow, ops.rmv_vc)
        elif mode == "adaptive":
            out = jax.vmap(adaptive_scatter_max)(table, rrow, ops.rmv_vc)
        else:
            out = jax.vmap(
                lambda t, r, u: bucketed_scatter_max(t, r, u, mode == "bucketM")
            )(table, rrow, ops.rmv_vc)
        return out.reshape(Rl, NKl, I, D_DCS)

    def step(st, ops):
        import functools

        rmv_vc_new = tombstones(st, ops)
        new_state, _ = jax.vmap(
            functools.partial(D._apply_one_replica, want_dominated_tbl=False)
        )(st, ops, rmv_vc_new)
        return new_state

    return step


def timeit(name, step_fn):
    @jax.jit
    def run(c, seq):
        def body(c, ops):
            return step_fn(c, ops), ()
        out, _ = lax.scan(body, c, seq)
        return out

    sync(run(state0, stacked))
    t0 = time.perf_counter()
    out = run(state0, stacked)
    sync(out)
    dt = (time.perf_counter() - t0) / REPS * 1e3
    print(f"{name:40s} {dt:9.2f} ms")
    return out


if __name__ == "__main__":
    modes = sys.argv[1:] or ["full", "bucketM", "bucketS"]
    outs = {}
    for m in modes:
        outs[m] = timeit(f"apply round, tombstones={m}", make_step(m))
    # Equivalence: every variant must produce the identical state.
    if "full" in outs:
        ref = outs["full"]
        for m, got in outs.items():
            same = all(
                bool(jnp.array_equal(a, b))
                for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got))
            )
            print(f"state[{m}] == state[full]: {same}")
