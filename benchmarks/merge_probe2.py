"""Merge probe round 2: RTT calibration + placement restructurings.

merge_probe.py's REPS=32 numbers carry ~RTT/32 of tunnel overhead per
rep (the trap that also under-read bench.py's state-merge rate until its
MERGE_REPS went 64 -> 192 in round 4); this probe adds a null-scan
calibration and runs the survivors at higher REPS so the per-piece
attribution is device time, not tunnel time.

Placement restructurings (the ~4-5ms piece — ~20x its 154MB write
floor):
  * place2m  — concatenate both sides into [.., 2M] planes and compute
    ONE global rank per candidate from a single 2M x 2M compare matrix
    (dedup folded in as a position tie-break), then ONE one-hot
    placement (2M x M) instead of two (M x M) + two masked sums per
    plane. ~2x the compare flops (same-side pairs are recomputed
    by value instead of prefix counts) but roughly half the HLO chain
    for XLA to schedule — testing whether the piece is flop-bound or
    schedule-bound.
  * placedot — the two one-hot masks contracted against the value
    planes with dot_general (batched [M, m] x [M] matvec) instead of
    where+sum, testing whether reduce-of-select chains are the cost.

Run: [MERGE_REPS=128] python benchmarks/merge_probe2.py [filter ...]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _cmp_better,
    _live_mask,
)

from benchmarks.merge_probe import (  # noqa: E402 — reuses the warmed sides
    D,
    M,
    RESULTS,
    _live_dom,
    _merge_variant,
    full,
    side_a,
    side_b,
    timeit,
)


def null_scan(a, b):
    """Near-zero device work with a live carry: measures per-rep overhead
    of the scan+dispatch harness itself (tunnel RTT / REPS + scan cost)."""
    return TopkRmvDenseState(
        a.slot_score, a.slot_dc, a.slot_ts, a.rmv_vc, a.vc, ~a.lossy
    )


def place2m(a, b):
    rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)
    vc = jnp.maximum(a.vc, b.vc)
    c_s = jnp.concatenate([a.slot_score, b.slot_score], axis=-1)
    c_d = jnp.concatenate([a.slot_dc, b.slot_dc], axis=-1)
    c_t = jnp.concatenate([a.slot_ts, b.slot_ts], axis=-1)
    live = _live_mask(c_d, c_t, rmv_vc)

    X = lambda x: x[..., :, None]  # noqa: E731 — candidate axis
    Y = lambda x: x[..., None, :]  # noqa: E731 — opponent axis
    beats = _cmp_better(Y(c_s), Y(c_t), Y(c_d), X(c_s), X(c_t), X(c_d))
    eq = (X(c_s) == Y(c_s)) & (X(c_t) == Y(c_t)) & (X(c_d) == Y(c_d))
    # Cross-side exact duplicates: the a copy (positions 0..M-1) wins;
    # the b copy dies (idempotence), same as _join_slots.
    pos = jnp.arange(2 * M, dtype=jnp.int32)
    a_side = pos < M
    dup = jnp.any(eq & Y(live) & Y(a_side), axis=-1) & ~a_side
    live = live & ~dup
    # Global rank = live opponents that strictly beat me, + live EQUAL
    # opponents at an earlier position (only same-side "us" remain after
    # the dup kill, and within a side equal triples cannot occur — the
    # term is the standard stable tie-break and keeps ranks a permutation).
    earlier = Y(pos) < X(pos)
    r = jnp.sum((beats | (eq & earlier)) & Y(live), axis=-1)
    r = jnp.where(live, r, 2 * M)

    ranks = jnp.arange(M, dtype=jnp.int32)
    oh = r[..., :, None] == ranks  # [.., 2M, M]

    def place_one(x, empty):
        out = jnp.sum(jnp.where(oh, x[..., :, None], 0), axis=-2)
        return jnp.where(jnp.any(oh, axis=-2), out, empty)

    n_live = jnp.sum(live.astype(jnp.int32), axis=-1)
    lossy = a.lossy | b.lossy | jnp.any(n_live > M, axis=-1)
    return TopkRmvDenseState(
        place_one(c_s, NEG_INF), place_one(c_d, 0), place_one(c_t, 0),
        rmv_vc, vc, lossy,
    )


def placedot(a, b):
    """_merge_variant with the one-hot contraction done by einsum
    (batched [M, m] x [M] matvec) instead of where+sum."""
    return _merge_variant(
        a, b, _live_dom,
        contract=lambda oh, x: jnp.einsum("...km,...k->...m", oh, x),
    )


def main():
    # timeit() scans merge_probe.REPS — print the value actually used
    # (set MERGE_REPS; merge_probe's default is 32).
    from benchmarks.merge_probe import REPS as reps
    print(f"# backend={jax.default_backend()} REPS={reps}")
    timeit("null_scan (per-rep harness overhead)", null_scan)
    timeit("full_merge", full)
    timeit("variant_baseline", lambda a, b: _merge_variant(a, b, _live_dom))
    timeit("restructure: place2m", place2m)
    timeit("restructure: placedot", placedot)

    ref = D.merge(side_a, side_b)
    for name, fn in (("place2m", place2m), ("placedot", placedot)):
        got = fn(side_a, side_b)
        ok = all(
            bool(jnp.array_equal(x, y))
            for x, y in zip(jax.tree.leaves(ref), jax.tree.leaves(got))
        )
        print(f"# equivalence {name}: {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    null = RESULTS.get("null_scan (per-rep harness overhead)")
    if null is not None:
        print(f"# per-rep harness overhead: {null:.3f} ms — subtract from "
              "every row above for device time")


if __name__ == "__main__":
    main()
