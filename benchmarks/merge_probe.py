"""Piece ablation + restructuring probes for the batched replica-state
merge at NORTH-STAR bench shapes (VERDICT-r3 item 3: give `merge` the
treatment `apply` got in round 3).

The merge (`TopkRmvDense.merge`) is three pieces:
  * maxes   — elementwise rmv_vc/vc max: pure bandwidth, and the rmv_vc
              plane is 400MB of the 563MB state, so this is most of the
              bytes floor.
  * dom     — the add-wins live masks: two one-hot max-reduces of each
              side's (dc, ts) slots against the merged rmv_vc
              (`_dom_lookup`), broadcasting rmv_vc over the M slot axis.
  * join    — M x M cross-compares, rank arithmetic, one-hot placement
              (`_join_slots` minus the dom part).

Methodology is ablate_apply.py's: the full merge is timed with one piece
removed at a time; because XLA fuses across pieces, removal deltas are
the honest attribution. NOTE: after round 4 adopted the union join,
`full_merge` (D.merge -> `_join_slots_union`) and `variant_baseline`
(an inline copy of the PRE-union pairwise join) are different kernels —
the removal variants ablate the pairwise join the attribution was taken
on; compare restructurings against `variant_baseline`, and `full_merge`
against it to see the adopted delta. Scan-fused reps with a carried state keep every
iteration live; host-readback sync (utils/benchtime.py).

Restructuring probes (VERDICT-r3 asked for at least one attempt,
committed either way):
  * packedcmp — fold the lexicographic (score desc, ts desc, dc asc)
    compare + the equality test into one sign-combine integer
    (r = 4*sgn(ds) + 2*sgn(dt) + sgn(-dd); better <=> r > 0,
    eq <=> r == 0) — fewer VPU lanes than the boolean chain.
  * domdist — dom(dc, max(a_rmv, b_rmv)) == max(dom(dc, a_rmv),
    dom(dc, b_rmv)) (one-hot max-reduce distributes over elementwise
    max), so the live masks can be computed from the two INPUT rmv
    planes without re-reading the merged plane the maxes piece writes —
    breaks the dom -> maxes data dependency.
  * fusedpair — one one-hot reduce over the concatenated [.., 2M] slot
    planes instead of two M-wide reduces (same flops, half the
    broadcast-iota/where chains for XLA to schedule).

Run: [MERGE_REPS=32] python benchmarks/merge_probe.py [name-filter ...]

PALLAS KERNEL — measured design rejection (round 4). A single-pass
Mosaic kernel (read both states once, write merged state: exactly the
2.06ms bytes floor) founders on layout at the pallas boundary:

* Pallas forces row-major inputs, so the [.., I, M=4] slot planes tile
  as 4-lane blocks (97% lane waste), and the in-kernel group-of-4 ops
  on a flat [.., I*M] view cannot align with the [.., I*D] tombstone
  pitch without cross-lane-width reshapes (Mosaic relayouts).
* The escape — transposing to [.., M, I] / [.., D, I] at the boundary —
  was MEASURED: the 12-transpose set (6 slot in + 2 rmv in + 3 slot out
  + 1 rmv out) costs 2.99ms/rep (~3.4GB traffic) by itself, so the
  best conceivable kernel lands at ~6.5ms vs the 8.04ms XLA merge —
  a thin upside against the backend's record of pallas composition
  regressions (ablate_apply: pallas tombstones win isolated, lose
  composed).
* The presumed real unlock — storing the dense state M-major/D-major
  globally — was then MEASURED before anyone refactored toward it
  (benchmarks/merge_layout_probe.py: the full union-join merge
  re-expressed on [.., M, I] / [.., D, I] RESIDENT states, exact
  equivalence asserted): -6.7% (10.21 -> 9.53 ms harness, ~8.3 -> 7.6
  device). The merge is schedule-bound regardless of layout; the
  cross-engine layout refactor is a measured dead end, not a future
  direction.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.harness.opgen import TopkRmvEffectGen, Workload
from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _join_slots,
    make_dense,
)

R, NK, I, D_DCS, K, M = 32, 1, 100_000, 32, 100, 4
REPS = int(os.environ.get("MERGE_REPS", 32))
D = make_dense(n_ids=I, n_dcs=D_DCS, size=K, slots_per_id=M)

# Two realistically divergent sides: a common warm prefix, then disjoint
# op suffixes per side (so slots are populated, tombstones nonzero, and
# the join has real cross-side work to do — an empty-vs-empty merge would
# let XLA's `where` chains short-circuit into broadcast constants).
gen = TopkRmvEffectGen(
    Workload(n_replicas=R, n_ids=I, zipf_a=1.2, score_max=100_000, seed=7)
)
state0 = D.init(n_replicas=R, n_keys=1)
for _ in range(2):
    state0, _ = D.apply_ops(state0, gen.next_batch(32768, 2048), collect_dominated=False)
side_a, _ = D.apply_ops(state0, gen.next_batch(32768, 2048), collect_dominated=False)
side_b, _ = D.apply_ops(state0, gen.next_batch(32768, 2048), collect_dominated=False)
# Peer rows rolled like bench.py so replica r merges a genuinely foreign row.
side_b = jax.tree.map(lambda x: jnp.roll(x, 1, axis=0), side_b)


def sync(x):
    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


SELECT = sys.argv[1:]
RESULTS = {}


def timeit(name, step_fn, peer=None):
    """Time REPS scan-fused applications of step_fn(carry, peer)."""
    if SELECT and not any(s in name for s in SELECT):
        return None
    peer = side_b if peer is None else peer

    @jax.jit
    def run(c, p):
        def body(c, _):
            return step_fn(c, p), ()
        out, _ = lax.scan(body, c, None, length=REPS)
        return out

    sync(run(side_a, peer))
    t0 = time.perf_counter()
    out = run(side_a, peer)
    sync(out)
    ms = (time.perf_counter() - t0) / REPS * 1e3
    RESULTS[name] = ms
    print(f"{name:44s} {ms:9.3f} ms/merge", flush=True)
    return out


# ---------------------------------------------------------------------------
# Pieces (removal variants of the full merge)
# ---------------------------------------------------------------------------

def full(a, b):
    return D.merge(a, b)


def maxes_only(a, b):
    """Only the elementwise maxes; slots carried through untouched."""
    return TopkRmvDenseState(
        a.slot_score, a.slot_dc, a.slot_ts,
        jnp.maximum(a.rmv_vc, b.rmv_vc),
        jnp.maximum(a.vc, b.vc),
        a.lossy | b.lossy,
    )


def _merge_variant(a, b, live_fn, place=True, contract=None):
    """The full merge with the live-mask computation (dom piece) replaced
    by `live_fn`, the one-hot placement optionally dropped, and the
    one-hot contraction optionally swapped (`contract(oh, x) -> [.., m]`,
    e.g. merge_probe2's einsum placement)."""
    rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)
    vc = jnp.maximum(a.vc, b.vc)
    a_s, a_d, a_t = a.slot_score, a.slot_dc, a.slot_ts
    b_s, b_d, b_t = b.slot_score, b.slot_dc, b.slot_ts
    live_a, live_b0 = live_fn(a, b, rmv_vc)

    A = lambda x: x[..., :, None]  # noqa: E731
    B_ = lambda x: x[..., None, :]  # noqa: E731
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _cmp_better

    a_beats_b = _cmp_better(A(a_s), A(a_t), A(a_d), B_(b_s), B_(b_t), B_(b_d))
    eq = (A(a_s) == B_(b_s)) & (A(a_t) == B_(b_t)) & (A(a_d) == B_(b_d))
    live_b = live_b0 & ~jnp.any(eq & A(live_a), axis=-2)
    b_beats_a = ~a_beats_b & ~eq

    la = live_a.astype(jnp.int32)
    lb = live_b.astype(jnp.int32)
    pref_a = jnp.cumsum(la, axis=-1) - la
    pref_b = jnp.cumsum(lb, axis=-1) - lb
    r_a = pref_a + jnp.sum(b_beats_a & B_(live_b), axis=-1)
    r_b = pref_b + jnp.sum(a_beats_b & A(live_a), axis=-2)
    n_live = jnp.sum(la, axis=-1) + jnp.sum(lb, axis=-1)

    if not place:
        # Keep the rank computation live via an OPAQUE data dependency:
        # (r_a + r_b) < -1 is always false (ranks are >= 0) but XLA's
        # algebraic simplifier cannot prove it, so the compare/rank chain
        # survives DCE. (A first cut used a_s + (r_a - r_a), which folds
        # to a_s and silently ablated compare+ranks along with placement.)
        f_score = jnp.where((r_a + r_b) < -1, r_b, a_s)
        f_dc = jnp.where((r_a + r_b) < -1, r_a, a_d)
        f_ts = a_t
    else:
        r_a = jnp.where(live_a, r_a, 2 * M)
        r_b = jnp.where(live_b, r_b, 2 * M)
        ranks = jnp.arange(M, dtype=jnp.int32)
        oh_a = r_a[..., :, None] == ranks
        oh_b = r_b[..., :, None] == ranks

        if contract is None:
            def place_one(xa, xb, empty):
                out = jnp.sum(
                    jnp.where(oh_a, xa[..., :, None], 0), axis=-2
                ) + jnp.sum(jnp.where(oh_b, xb[..., :, None], 0), axis=-2)
                filled = jnp.any(oh_a, axis=-2) | jnp.any(oh_b, axis=-2)
                return jnp.where(filled, out, empty)
        else:
            oha_i = oh_a.astype(jnp.int32)
            ohb_i = oh_b.astype(jnp.int32)

            def place_one(xa, xb, empty):
                out = contract(oha_i, xa) + contract(ohb_i, xb)
                filled = (
                    jnp.max(oha_i, axis=-2) + jnp.max(ohb_i, axis=-2)
                ) > 0
                return jnp.where(filled, out, empty)

        f_score = place_one(a_s, b_s, NEG_INF)
        f_dc = place_one(a_d, b_d, 0)
        f_ts = place_one(a_t, b_t, 0)

    lossy = a.lossy | b.lossy | jnp.any(n_live > M, axis=-1)
    return TopkRmvDenseState(f_score, f_dc, f_ts, rmv_vc, vc, lossy)


def _live_dom(a, b, rmv_vc):
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _live_mask
    return (
        _live_mask(a.slot_dc, a.slot_ts, rmv_vc),
        _live_mask(b.slot_dc, b.slot_ts, rmv_vc),
    )


def _live_ts_only(a, b, rmv_vc):
    """Dom piece removed: live = any nonempty slot (no tombstone lookup)."""
    return a.slot_ts > 0, b.slot_ts > 0


# ---------------------------------------------------------------------------
# Restructurings
# ---------------------------------------------------------------------------

def packedcmp(a, b):
    """Sign-combine compare: one small-int recombination replaces the
    boolean lexicographic chain AND the equality test."""
    rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)
    vc = jnp.maximum(a.vc, b.vc)
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _live_mask

    a_s, a_d, a_t = a.slot_score, a.slot_dc, a.slot_ts
    b_s, b_d, b_t = b.slot_score, b.slot_dc, b.slot_ts
    live_a = _live_mask(a_d, a_t, rmv_vc)
    live_b = _live_mask(b_d, b_t, rmv_vc)

    A = lambda x: x[..., :, None]  # noqa: E731
    B_ = lambda x: x[..., None, :]  # noqa: E731

    def sgn(x, y):  # sign(x - y) without subtraction overflow
        return (x > y).astype(jnp.int32) - (x < y).astype(jnp.int32)

    r = (
        4 * sgn(A(a_s), B_(b_s))
        + 2 * sgn(A(a_t), B_(b_t))
        + sgn(B_(b_d), A(a_d))  # dc ASC: smaller dc is better
    )
    a_beats_b = r > 0
    eq = r == 0
    live_b = live_b & ~jnp.any(eq & A(live_a), axis=-2)
    b_beats_a = (r < 0) & ~eq

    la = live_a.astype(jnp.int32)
    lb = live_b.astype(jnp.int32)
    pref_a = jnp.cumsum(la, axis=-1) - la
    pref_b = jnp.cumsum(lb, axis=-1) - lb
    r_a = pref_a + jnp.sum(b_beats_a & B_(live_b), axis=-1)
    r_b = pref_b + jnp.sum(a_beats_b & A(live_a), axis=-2)
    n_live = jnp.sum(la, axis=-1) + jnp.sum(lb, axis=-1)
    r_a = jnp.where(live_a, r_a, 2 * M)
    r_b = jnp.where(live_b, r_b, 2 * M)
    ranks = jnp.arange(M, dtype=jnp.int32)
    oh_a = r_a[..., :, None] == ranks
    oh_b = r_b[..., :, None] == ranks

    def place_one(xa, xb, empty):
        out = jnp.sum(jnp.where(oh_a, xa[..., :, None], 0), axis=-2) + jnp.sum(
            jnp.where(oh_b, xb[..., :, None], 0), axis=-2
        )
        filled = jnp.any(oh_a, axis=-2) | jnp.any(oh_b, axis=-2)
        return jnp.where(filled, out, empty)

    lossy = a.lossy | b.lossy | jnp.any(n_live > M, axis=-1)
    return TopkRmvDenseState(
        place_one(a_s, b_s, NEG_INF), place_one(a_d, b_d, 0),
        place_one(a_t, b_t, 0), rmv_vc, vc, lossy,
    )


def domdist(a, b):
    """Live masks from max(dom(a_rmv), dom(b_rmv)) — never broadcasts the
    merged rmv plane, decoupling the join from the maxes piece."""
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _dom_lookup

    def live_fn(a, b, rmv_vc):
        dom_a = jnp.maximum(
            _dom_lookup(a.slot_dc, a.rmv_vc), _dom_lookup(a.slot_dc, b.rmv_vc)
        )
        dom_b = jnp.maximum(
            _dom_lookup(b.slot_dc, a.rmv_vc), _dom_lookup(b.slot_dc, b.rmv_vc)
        )
        return a.slot_ts > dom_a, b.slot_ts > dom_b

    return _merge_variant(a, b, live_fn)


def fusedpair(a, b):
    """One 2M-wide dom reduce over the concatenated slot planes."""
    from antidote_ccrdt_tpu.models.topk_rmv_dense import _dom_lookup

    def live_fn(a, b, rmv_vc):
        dc2 = jnp.concatenate([a.slot_dc, b.slot_dc], axis=-1)
        ts2 = jnp.concatenate([a.slot_ts, b.slot_ts], axis=-1)
        live2 = ts2 > _dom_lookup(dc2, rmv_vc)
        return live2[..., :M], live2[..., M:]

    return _merge_variant(a, b, live_fn)


def main():
    backend = jax.default_backend()
    print(f"# backend={backend} R={R} I={I} D={D_DCS} M={M} REPS={REPS}")
    state_mb = sum(x.nbytes for x in jax.tree.leaves(side_a)) / 1e6
    print(f"# state={state_mb:.1f}MB; 3x-state bytes floor = "
          f"{3 * state_mb / 819.0:.2f} ms (v5e 819GB/s)")

    timeit("full_merge", full)
    timeit("maxes_only (bandwidth part)", maxes_only)
    timeit("no_dom (live = ts>0)", lambda a, b: _merge_variant(a, b, _live_ts_only))
    timeit("no_place (ranks, no one-hot output)",
           lambda a, b: _merge_variant(a, b, _live_dom, place=False))
    timeit("variant_baseline (pre-union pairwise join)",
           lambda a, b: _merge_variant(a, b, _live_dom))
    timeit("restructure: packedcmp", packedcmp)
    timeit("restructure: domdist", domdist)
    timeit("restructure: fusedpair", fusedpair)

    # Equivalence spot-check: restructurings must produce the identical
    # merged state (one application, not the scan tower).
    ref = D.merge(side_a, side_b)
    for name, fn in (("packedcmp", packedcmp), ("domdist", domdist),
                     ("fusedpair", fusedpair)):
        if SELECT and not any(s in name for s in SELECT):
            continue
        got = fn(side_a, side_b)
        ok = all(
            bool(jnp.array_equal(x, y))
            for x, y in zip(jax.tree.leaves(ref), jax.tree.leaves(got))
        )
        print(f"# equivalence {name}: {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    if RESULTS:
        print("# removal deltas (ms):")
        fullms = RESULTS.get("full_merge")
        for k, v in RESULTS.items():
            if fullms and k.startswith("no_"):
                print(f"#   {k}: {fullms - v:+.3f}")


if __name__ == "__main__":
    main()
