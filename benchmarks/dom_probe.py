"""Round-5 probe: the dom one-hot reduce — merge's top residual.

VERDICT-r4 item 5: the add-wins filter's tombstone lookup
(`_dom_lookup`: dom[.., m] = rmv_vc[.., dc[.., m]], computed as a fused
one-hot where+max over the D axis) is the replica-state merge's largest
residual (~3.7ms of ~9ms, ~2.5x its bytes floor) and the same family as
apply's filter. Variants here re-express the LOOKUP only — everything
else in the union join is byte-identical — so deltas isolate the piece:

  * production  — where(oh, vc, 0) + max over D (_dom_lookup).
  * dom_sum     — where(oh, vc, 0) + SUM over D: the one-hot has a
    single nonzero, so sum == the selected value exactly; tests whether
    max-reduce chains schedule worse than add-reduce chains.
  * dom_mul     — oh.astype(i32) * vc + sum: multiply instead of
    select, the form XLA can turn into a (batched) integer dot.
  * dom_dot     — the lookup contracted with einsum('...md,...d->...m'),
    letting XLA choose the dot lowering outright.
  * dom_tree    — 5-level binary select on the bits of dc (D=32):
    ~D-1 selects per slot instead of D compares + D selects + a D-wide
    max tree. (The r2 'bit tree' probe was on the APPLY path; this
    re-tests the idea on the merge's 2M-wide filter.)

Run: [MERGE_REPS=128] python benchmarks/dom_probe.py [filter ...]

VERDICT (measured v5e, REPS=128, null harness overhead 1.08 ms/rep,
all equivalence-OK; benchmarks/dom_probe_results.json):

    full_merge (production)        8.87  ms/merge
    union+dom_production           8.87
    union+dom_sum                  8.81
    union+dom_mul                  8.82
    union+dom_dot                  8.81
    union+dom_tree                19.49  (2.2x REGRESSION)

Every dot/sum/mul reformulation lands within noise of the production
where+max — XLA already fuses the lookup into one select-reduce and the
expression form does not change the schedule — and the bit tree's 5
dependent select levels cost 2.2x despite ~3x fewer ops. This closes
VERDICT-r4 item 5 as a measured rejection: the dom reduce residual
(~2.2ms above its bytes floor) is schedule-bound like the rest of the
merge (merge_probe.py's pallas/layout rejections), and the s8-plane
idea is priced out before implementation by dom_mul/dom_dot sitting at
baseline — the multiply/accumulate form they would feed is not the
bottleneck.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _cmp_better,
)

from benchmarks.merge_probe import (  # noqa: E402 — reuses the warmed sides
    D,
    M,
    RESULTS,
    full,
    side_a,
    side_b,
    timeit,
)
from benchmarks.merge_probe2 import null_scan  # noqa: E402


def dom_production(dc, rmv_vc):
    Dd = rmv_vc.shape[-1]
    oh = dc[..., None] == jnp.arange(Dd, dtype=dc.dtype)
    return jnp.max(jnp.where(oh, rmv_vc[..., None, :], 0), axis=-1)


def dom_sum(dc, rmv_vc):
    Dd = rmv_vc.shape[-1]
    oh = dc[..., None] == jnp.arange(Dd, dtype=dc.dtype)
    return jnp.sum(jnp.where(oh, rmv_vc[..., None, :], 0), axis=-1)


def dom_mul(dc, rmv_vc):
    Dd = rmv_vc.shape[-1]
    oh = (dc[..., None] == jnp.arange(Dd, dtype=dc.dtype)).astype(jnp.int32)
    return jnp.sum(oh * rmv_vc[..., None, :], axis=-1)


def dom_dot(dc, rmv_vc):
    Dd = rmv_vc.shape[-1]
    oh = (dc[..., None] == jnp.arange(Dd, dtype=dc.dtype)).astype(jnp.int32)
    return jnp.einsum("...md,...d->...m", oh, rmv_vc)


def dom_tree(dc, rmv_vc):
    """Binary select over dc's bits; assumes D a power of two <= 32."""
    Dd = rmv_vc.shape[-1]
    v = jnp.broadcast_to(
        rmv_vc[..., None, :], dc.shape + (Dd,)
    )
    width, bit = Dd, 0
    while width > 1:
        half = width // 2
        take_hi = ((dc >> bit) & 1)[..., None].astype(bool)
        v = jnp.where(take_hi, v[..., half:width], v[..., :half])
        width, bit = half, bit + 1
    return v[..., 0]


def union_merge(dom_fn):
    """The production union join (_join_slots_union semantics, verbatim)
    with only the dom lookup swapped."""

    def merge(a, b):
        rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)
        vc = jnp.maximum(a.vc, b.vc)
        c_s = jnp.concatenate([a.slot_score, b.slot_score], axis=-1)
        c_d = jnp.concatenate([a.slot_dc, b.slot_dc], axis=-1)
        c_t = jnp.concatenate([a.slot_ts, b.slot_ts], axis=-1)
        live = c_t > dom_fn(c_d, rmv_vc)

        X = lambda x: x[..., :, None]  # noqa: E731
        Y = lambda x: x[..., None, :]  # noqa: E731
        beats = _cmp_better(Y(c_s), Y(c_t), Y(c_d), X(c_s), X(c_t), X(c_d))
        eq = (X(c_s) == Y(c_s)) & (X(c_t) == Y(c_t)) & (X(c_d) == Y(c_d))
        pos = jnp.arange(2 * M, dtype=jnp.int32)
        a_side = pos < M
        dup = jnp.any(eq & Y(live) & Y(a_side), axis=-1) & ~a_side
        live = live & ~dup
        earlier = Y(pos) < X(pos)
        r = jnp.sum((beats | (eq & earlier)) & Y(live), axis=-1)
        r = jnp.where(live, r, 2 * M)

        ranks = jnp.arange(M, dtype=jnp.int32)
        oh = r[..., :, None] == ranks

        def place_one(x, empty):
            out = jnp.sum(jnp.where(oh, x[..., :, None], 0), axis=-2)
            return jnp.where(jnp.any(oh, axis=-2), out, empty)

        n_live = jnp.sum(live.astype(jnp.int32), axis=-1)
        lossy = a.lossy | b.lossy | jnp.any(n_live > M, axis=-1)
        return TopkRmvDenseState(
            place_one(c_s, NEG_INF), place_one(c_d, 0), place_one(c_t, 0),
            rmv_vc, vc, lossy,
        )

    return merge


VARIANTS = {
    "dom_production": dom_production,
    "dom_sum": dom_sum,
    "dom_mul": dom_mul,
    "dom_dot": dom_dot,
    "dom_tree": dom_tree,
}


def main():
    from benchmarks.merge_probe import REPS as reps

    print(f"# backend={jax.default_backend()} REPS={reps}")
    sel = sys.argv[1:]

    ref = D.merge(side_a, side_b)
    for name, fn in VARIANTS.items():
        if sel and not any(s in name for s in sel):
            continue
        got = union_merge(fn)(side_a, side_b)
        ok = all(
            bool(jnp.array_equal(x, y))
            for x, y in zip(jax.tree.leaves(ref), jax.tree.leaves(got))
        )
        print(f"# equivalence {name}: {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    timeit("null_scan (per-rep harness overhead)", null_scan)
    timeit("full_merge (production)", full)
    for name, fn in VARIANTS.items():
        if sel and not any(s in name for s in sel):
            continue
        timeit(f"union+{name}", union_merge(fn))

    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "dom_probe_results.json"
    )
    with open(out, "w") as f:
        json.dump(
            {"backend": jax.default_backend(), "reps": reps,
             "ms_per_rep": {k: round(v, 3) for k, v in RESULTS.items()}},
            f, indent=1,
        )
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
