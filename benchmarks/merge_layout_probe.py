"""Would the M-major state layout actually pay? (round-5 decision probe)

merge_probe.py's round-4 verdict left one named future direction: store
the slot planes [.., M, I] and the tombstone table [.., D, I] (small dim
major) so a single-pass kernel — or plain XLA — stops fighting the
minor-dim-4 tiling. Refactoring the whole engine on a hunch is exactly
what this repo doesn't do, so this probe measures the merge itself on
BOTH layouts with states RESIDENT in each (no per-rep transposes —
the ~3ms boundary-transpose cost only applies to a mixed design):

  * imajor — the production union join on [G, I, M] / [G, I, D] states
    (D.merge's exact kernel, timed on the same harness for a same-RTT
    baseline).
  * mmajor — the same union-join semantics re-expressed on [G, M, I] /
    [G, D, I] states: candidate axis is -2, the dom one-hot reduce runs
    over the D-major axis, placement one-hots over (2M, m_keep) with I
    riding minor — every elementwise op now has the long axis in lanes.

Equivalence is asserted against the production merge (transposing the
mmajor result back once, outside timing). The delta answers whether the
round-5 cross-engine layout refactor has real headroom behind it or the
merge is schedule-bound regardless of layout.

Run: [MERGE_REPS=64] python benchmarks/merge_layout_probe.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from antidote_ccrdt_tpu.models.topk_rmv_dense import (
    NEG_INF,
    TopkRmvDenseState,
    _cmp_better,
)
from benchmarks.merge_probe import D, M, REPS, side_a, side_b, sync


def to_mmajor(st):
    """[R, NK, I, M] -> [R, NK, M, I] and rmv [.., I, D] -> [.., D, I]."""
    sw = lambda x: jnp.swapaxes(x, -1, -2)  # noqa: E731
    return TopkRmvDenseState(
        sw(st.slot_score), sw(st.slot_dc), sw(st.slot_ts),
        sw(st.rmv_vc), st.vc, st.lossy,
    )


def merge_mmajor(a, b):
    """Union join on M-major planes: same semantics as
    `_join_slots_union` + the elementwise maxes, long axis minor."""
    rmv_vc = jnp.maximum(a.rmv_vc, b.rmv_vc)  # [.., D, I]
    vc = jnp.maximum(a.vc, b.vc)
    c_s = jnp.concatenate([a.slot_score, b.slot_score], axis=-2)  # [.., 2M, I]
    c_d = jnp.concatenate([a.slot_dc, b.slot_dc], axis=-2)
    c_t = jnp.concatenate([a.slot_ts, b.slot_ts], axis=-2)

    Dd = rmv_vc.shape[-2]
    # dom[.., c, i] = rmv_vc[.., dc[c, i], i]: one-hot over the D axis,
    # broadcast [.., 2M, 1, I] x [.., 1, D, I] -> reduce D.
    oh = c_d[..., :, None, :] == jnp.arange(Dd, dtype=c_d.dtype)[:, None]
    dom = jnp.max(
        jnp.where(oh, rmv_vc[..., None, :, :], 0), axis=-2
    )  # [.., 2M, I]
    live = c_t > dom

    X = lambda x: x[..., :, None, :]  # noqa: E731 — candidate axis
    Y = lambda x: x[..., None, :, :]  # noqa: E731 — opponent axis
    beats = _cmp_better(Y(c_s), Y(c_t), Y(c_d), X(c_s), X(c_t), X(c_d))
    eq = (X(c_s) == Y(c_s)) & (X(c_t) == Y(c_t)) & (X(c_d) == Y(c_d))
    pos = jnp.arange(2 * M, dtype=jnp.int32)[:, None]
    a_side = pos < M
    dup = jnp.any(eq & Y(live) & Y(a_side), axis=-2) & ~a_side
    live = live & ~dup
    earlier = Y(pos) < X(pos)
    r = jnp.sum((beats | (eq & earlier)) & Y(live), axis=-2)
    r = jnp.where(live, r, 2 * M)

    ranks = jnp.arange(M, dtype=jnp.int32)[:, None]
    oh_r = r[..., :, None, :] == ranks  # [.., 2M, m_keep, I]

    def place(x, empty):
        out = jnp.sum(jnp.where(oh_r, x[..., :, None, :], 0), axis=-3)
        return jnp.where(jnp.any(oh_r, axis=-3), out, empty)

    n_live = jnp.sum(live.astype(jnp.int32), axis=-2)  # [.., I]
    lossy = a.lossy | b.lossy | jnp.any(n_live > M, axis=-1)
    return TopkRmvDenseState(
        place(c_s, NEG_INF), place(c_d, 0), place(c_t, 0), rmv_vc, vc, lossy,
    )


def timeit(name, step_fn, a0, peer):
    @jax.jit
    def run(c, p):
        def body(c, _):
            return step_fn(c, p), ()
        out, _ = lax.scan(body, c, None, length=REPS)
        return out

    sync(run(a0, peer))
    t0 = time.perf_counter()
    out = run(a0, peer)
    sync(out)
    ms = (time.perf_counter() - t0) / REPS * 1e3
    print(f"{name:44s} {ms:9.3f} ms/merge", flush=True)
    return ms


def main():
    print(f"# backend={jax.default_backend()} REPS={REPS}")
    am, bm = to_mmajor(side_a), to_mmajor(side_b)
    for x in jax.tree.leaves(am) + jax.tree.leaves(bm):
        sync(x)

    # Equivalence first: transpose the mmajor result back once.
    ref = D.merge(side_a, side_b)
    got = to_mmajor(merge_mmajor(am, bm))  # to_mmajor is its own inverse
    ok = all(
        bool(jnp.array_equal(x, y))
        for x, y in zip(jax.tree.leaves(ref), jax.tree.leaves(got))
    )
    print(f"# equivalence mmajor: {'OK' if ok else 'MISMATCH'}")
    assert ok

    imaj = timeit("imajor (production union join)", D.merge, side_a, side_b)
    mmaj = timeit("mmajor (long axis minor)", merge_mmajor, am, bm)
    print(f"# layout delta: {mmaj - imaj:+.3f} ms/merge "
          f"({(mmaj / imaj - 1) * 100:+.1f}%)")


if __name__ == "__main__":
    main()
