// Native host runtime: op-log store + causal delivery scheduler + batcher.
//
// The reference library delegates replication to its host (Antidote): the
// host logs effect ops, ships them between DCs, and delivers them to every
// replica causally, exactly once (SURVEY.md §1 — the contract the library
// leans on but does not implement). This is that host, rebuilt as a native
// component: the Python/JAX side hands it effect ops and drains dense
// batches; everything between — per-origin append-only logs, vector-clock
// dependency tracking, per-replica causal delivery cursors, struct-of-array
// batch building — runs in C++ so the op pipeline never bottlenecks on the
// Python interpreter between TPU dispatches.
//
// Model
// -----
// * D replicas, each also a DC (multi-master geo-replication).
// * submit(origin, op): stamps the op with the origin's lamport time and a
//   per-origin sequence number, snapshots the origin's delivered-vc as the
//   op's causal dependency, appends to the origin's log. O(1) amortized.
// * drain(replica, max_n): delivers ops to `replica` in causal order —
//   op (origin, seq) is deliverable iff seq is the next undelivered from
//   origin AND dep_vc <= replica.delivered_vc componentwise. Fills caller
//   provided SoA buffers (the dense op-batch layout) and returns the count.
//   Exactly-once by construction (cursor per (replica, origin)).
// * Origins deliver their own ops through drain like everyone else: an
//   op's dep_vc equals the origin's delivered snapshot, so it is
//   immediately deliverable at its origin — no special case.
//
// Single-threaded by design: one host instance per pipeline thread (the
// Erlang reference serializes through gen_server mailboxes; here the
// batching amortizes instead).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct OpRecord {
  int32_t kind;   // type-specific; by convention 0/1 add-ish, 2/3 rmv-ish
  int32_t key;    // CRDT instance index
  int32_t id;     // element / player / token id
  int32_t score;  // add score | wordcount count | average value
  int32_t aux;    // second payload (average n, etc.)
  int32_t dc;     // origin DC
  int32_t ts;     // origin lamport stamp
  // followed in the flat log by dep_vc[D] then payload vc[D]
};

constexpr int kFixed = 7;  // int32 fields before the two vcs

class Host {
 public:
  explicit Host(int n_dcs)
      : d_(n_dcs),
        stride_(kFixed + 2 * n_dcs),
        logs_(n_dcs),
        lamport_(n_dcs, 0),
        delivered_(n_dcs, std::vector<int64_t>(n_dcs, 0)),
        submitted_(0),
        delivered_total_(0) {}

  int32_t Submit(int origin, int32_t kind, int32_t key, int32_t id,
                 int32_t score, int32_t aux, const int32_t* vc) {
    int32_t ts = ++lamport_[origin];
    auto& log = logs_[origin];
    size_t base = log.size();
    log.resize(base + stride_);
    int32_t* rec = log.data() + base;
    rec[0] = kind;
    rec[1] = key;
    rec[2] = id;
    rec[3] = score;
    rec[4] = aux;
    rec[5] = origin;
    rec[6] = ts;
    // Causal dependency: everything the origin has delivered so far.
    for (int i = 0; i < d_; ++i)
      rec[kFixed + i] = static_cast<int32_t>(delivered_[origin][i]);
    int32_t* pvc = rec + kFixed + d_;
    if (vc) {
      std::memcpy(pvc, vc, sizeof(int32_t) * d_);
    } else {
      std::memset(pvc, 0, sizeof(int32_t) * d_);
    }
    ++submitted_;
    return ts;
  }

  // Deliver up to max_n causally-ready ops for `replica` into SoA buffers.
  // out_vc is [max_n, D] row-major. Returns the number delivered.
  int Drain(int replica, int max_n, int32_t* out_kind, int32_t* out_key,
            int32_t* out_id, int32_t* out_score, int32_t* out_aux,
            int32_t* out_dc, int32_t* out_ts, int32_t* out_vc) {
    auto& seen = delivered_[replica];
    int n = 0;
    bool progressed = true;
    while (n < max_n && progressed) {
      progressed = false;
      for (int origin = 0; origin < d_ && n < max_n; ++origin) {
        // Deliver as many consecutive ready ops from this origin as fit.
        while (n < max_n) {
          int64_t next = seen[origin];  // 0-based index of next op
          if (static_cast<size_t>(next) * stride_ >= logs_[origin].size())
            break;
          const int32_t* rec = logs_[origin].data() + next * stride_;
          const int32_t* dep = rec + kFixed;
          bool ready = true;
          for (int i = 0; i < d_; ++i) {
            if (static_cast<int64_t>(dep[i]) > seen[i]) {
              ready = false;
              break;
            }
          }
          if (!ready) break;
          out_kind[n] = rec[0];
          out_key[n] = rec[1];
          out_id[n] = rec[2];
          out_score[n] = rec[3];
          out_aux[n] = rec[4];
          out_dc[n] = rec[5];
          out_ts[n] = rec[6];
          std::memcpy(out_vc + static_cast<size_t>(n) * d_, rec + kFixed + d_,
                      sizeof(int32_t) * d_);
          ++n;
          ++seen[origin];
          ++delivered_total_;
          // Delivering an add advances the replica's lamport view so later
          // local stamps dominate everything it has seen.
          if (rec[6] > lamport_[replica]) lamport_[replica] = rec[6];
          progressed = true;
        }
      }
    }
    return n;
  }

  int64_t Backlog(int replica) const {
    int64_t pending = 0;
    for (int origin = 0; origin < d_; ++origin) {
      int64_t total = static_cast<int64_t>(logs_[origin].size() / stride_);
      pending += total - delivered_[replica][origin];
    }
    return pending;
  }

  void Stats(int64_t* out) const {
    out[0] = submitted_;
    out[1] = delivered_total_;
    int64_t pending = 0;
    for (int r = 0; r < d_; ++r) pending += Backlog(r);
    out[2] = pending;
  }

  int n_dcs() const { return d_; }

 private:
  int d_;
  int stride_;
  std::vector<std::vector<int32_t>> logs_;     // per-origin flat op log
  std::vector<int32_t> lamport_;               // per-DC lamport clock
  std::vector<std::vector<int64_t>> delivered_;  // [replica][origin] counts
  int64_t submitted_;
  int64_t delivered_total_;
};

}  // namespace

extern "C" {

void* ccrdt_host_new(int n_dcs) {
  if (n_dcs <= 0) return nullptr;
  return new Host(n_dcs);
}

void ccrdt_host_free(void* h) { delete static_cast<Host*>(h); }

int32_t ccrdt_host_submit(void* h, int origin, int32_t kind, int32_t key,
                          int32_t id, int32_t score, int32_t aux,
                          const int32_t* vc) {
  return static_cast<Host*>(h)->Submit(origin, kind, key, id, score, aux, vc);
}

// Batched submit: arrays of length n; vcs is [n, D] row-major or null.
// out_ts (length n) receives the lamport stamps; may be null.
void ccrdt_host_submit_batch(void* h, int origin, int n, const int32_t* kinds,
                             const int32_t* keys, const int32_t* ids,
                             const int32_t* scores, const int32_t* auxs,
                             const int32_t* vcs, int32_t* out_ts) {
  Host* host = static_cast<Host*>(h);
  int d = host->n_dcs();
  for (int i = 0; i < n; ++i) {
    const int32_t* vc = vcs ? vcs + static_cast<size_t>(i) * d : nullptr;
    int32_t ts = host->Submit(origin, kinds[i], keys[i], ids[i], scores[i],
                              auxs ? auxs[i] : 0, vc);
    if (out_ts) out_ts[i] = ts;
  }
}

int ccrdt_host_drain(void* h, int replica, int max_n, int32_t* out_kind,
                     int32_t* out_key, int32_t* out_id, int32_t* out_score,
                     int32_t* out_aux, int32_t* out_dc, int32_t* out_ts,
                     int32_t* out_vc) {
  return static_cast<Host*>(h)->Drain(replica, max_n, out_kind, out_key,
                                      out_id, out_score, out_aux, out_dc,
                                      out_ts, out_vc);
}

int64_t ccrdt_host_backlog(void* h, int replica) {
  return static_cast<Host*>(h)->Backlog(replica);
}

void ccrdt_host_stats(void* h, int64_t* out3) {
  static_cast<Host*>(h)->Stats(out3);
}

}  // extern "C"
