// Native corpus tokenizer / data-loader for the wordcount CCRDTs.
//
// The reference tokenizes inside update/2: binary:split(Doc, ["\n", " "],
// [global]) — splitting on '\n' and ' ' and KEEPING empty segments, which
// it then counts like any word (antidote_ccrdt_wordcount.erl:76-85).
// worddocumentcount additionally dedupes tokens within one document
// through a gb_set (antidote_ccrdt_worddocumentcount.erl:76-86).
//
// In the TPU pipeline documents are tokenized host-side into int32 token
// ids and the device only sees id batches (models/wordcount.py). This file
// moves that host-side hot loop out of Python: a whole corpus chunk
// (documents concatenated into one buffer + offsets) is tokenized, deduped
// and encoded in one C call.
//
// Two encoding modes, matching the Python encoders exactly:
//  * hashed  (n_buckets > 0): FNV-1a 32-bit % n_buckets — byte-identical
//    to models/wordcount.py:hash_token (stable across runs/processes);
//  * exact   (n_buckets == 0): grow-on-demand token -> dense id vocabulary
//    (VocabEncoder parity), dumpable for host-side decode.
//
// Per-document dedup happens on the token STRING before hashing/encoding
// (two distinct words colliding in hashed mode still contribute 2 to the
// shared bucket — same as the Python path).

#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

inline uint32_t Fnv1a(const char* s, size_t n) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < n; ++i) {
    h = (h ^ static_cast<uint8_t>(s[i])) * 16777619u;
  }
  return h;
}

struct StringPiece {
  const char* data;
  size_t len;
  bool operator==(const StringPiece& o) const {
    return len == o.len && std::memcmp(data, o.data, len) == 0;
  }
};

struct PieceHash {
  size_t operator()(const StringPiece& p) const {
    return Fnv1a(p.data, p.len);
  }
};

class Tokenizer {
 public:
  explicit Tokenizer(int32_t n_buckets) : buckets_(n_buckets) {}

  // Tokenize [buf, buf+len): emit one id per token (empties included).
  // per_document: dedupe token strings within this document first, in
  // first-appearance order (the gb_set sorts, but counts are order-
  // independent so first-appearance is equivalent for the CRDT).
  // Returns the number of ids written (never exceeds cap; the true count
  // is returned so callers can detect truncation).
  int64_t Encode(const char* buf, int64_t len, int per_document,
                 int32_t* out, int64_t cap) {
    int64_t n_out = 0;
    seen_.clear();
    const char* p = buf;
    const char* end = buf + len;
    const char* tok = p;
    for (;; ++p) {
      if (p == end || *p == '\n' || *p == ' ') {
        StringPiece piece{tok, static_cast<size_t>(p - tok)};
        bool emit = true;
        if (per_document) emit = seen_.insert(piece).second;
        if (emit) {
          int32_t id = EncodeToken(piece);
          if (n_out < cap) out[n_out] = id;
          ++n_out;
        }
        tok = p + 1;
      }
      if (p == end) break;
    }
    return n_out;
  }

  int32_t EncodeToken(const StringPiece& piece) {
    if (buckets_ > 0) {
      return static_cast<int32_t>(Fnv1a(piece.data, piece.len) %
                                  static_cast<uint32_t>(buckets_));
    }
    auto it = vocab_.find(piece);
    if (it != vocab_.end()) return it->second;
    // Own the bytes: the piece points into the caller's buffer.
    storage_.emplace_back(piece.data, piece.len);
    const std::string& owned = storage_.back();
    int32_t id = static_cast<int32_t>(storage_.size()) - 1;
    vocab_.emplace(StringPiece{owned.data(), owned.size()}, id);
    return id;
  }

  int64_t VocabSize() const {
    return buckets_ > 0 ? buckets_ : static_cast<int64_t>(storage_.size());
  }

  // Dump the exact-mode vocabulary as id-ordered tokens joined by '\n'
  // (tokens never contain '\n' or ' ' — they are split on them; the empty
  // token round-trips as an empty line). Returns the required byte count;
  // writes at most cap bytes.
  int64_t VocabDump(char* out, int64_t cap) const {
    int64_t need = 0;
    for (size_t i = 0; i < storage_.size(); ++i) {
      need += static_cast<int64_t>(storage_[i].size()) + (i ? 1 : 0);
    }
    if (out == nullptr || cap < need) return need;
    char* w = out;
    for (size_t i = 0; i < storage_.size(); ++i) {
      if (i) *w++ = '\n';
      std::memcpy(w, storage_[i].data(), storage_[i].size());
      w += storage_[i].size();
    }
    return need;
  }

 private:
  int32_t buckets_;
  // Exact mode: vocabulary keyed by pieces pointing into storage_. A deque
  // never relocates elements on push_back, so the StringPiece keys stay
  // valid (a vector<string> would move short SSO strings on growth and
  // dangle their inline character buffers).
  std::unordered_map<StringPiece, int32_t, PieceHash> vocab_;
  std::deque<std::string> storage_;
  std::unordered_set<StringPiece, PieceHash> seen_;
};

}  // namespace

extern "C" {

void* ccrdt_tok_new(int32_t n_buckets) { return new Tokenizer(n_buckets); }

void ccrdt_tok_free(void* t) { delete static_cast<Tokenizer*>(t); }

int64_t ccrdt_tok_encode(void* t, const char* buf, int64_t len,
                         int per_document, int32_t* out, int64_t cap) {
  return static_cast<Tokenizer*>(t)->Encode(buf, len, per_document, out, cap);
}

// Batch ingest: n_docs documents concatenated in `buf`, document i spanning
// [offsets[i], offsets[i+1]). Token ids append into `out` (capacity `cap`);
// out_doc_end[i] receives the cumulative token count after document i.
// Returns the total token count (callers compare with cap for truncation).
int64_t ccrdt_tok_encode_batch(void* t, const char* buf,
                               const int64_t* offsets, int n_docs,
                               int per_document, int32_t* out, int64_t cap,
                               int64_t* out_doc_end) {
  Tokenizer* tok = static_cast<Tokenizer*>(t);
  int64_t total = 0;
  for (int i = 0; i < n_docs; ++i) {
    const char* doc = buf + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t room = cap > total ? cap - total : 0;
    total += tok->Encode(doc, len, per_document, out + total, room);
    if (out_doc_end) out_doc_end[i] = total;
  }
  return total;
}

int64_t ccrdt_tok_vocab_size(void* t) {
  return static_cast<Tokenizer*>(t)->VocabSize();
}

int64_t ccrdt_tok_vocab_dump(void* t, char* out, int64_t cap) {
  return static_cast<Tokenizer*>(t)->VocabDump(out, cap);
}

}  // extern "C"
