// Native corpus tokenizer / data-loader for the wordcount CCRDTs.
//
// The reference tokenizes inside update/2: binary:split(Doc, ["\n", " "],
// [global]) — splitting on '\n' and ' ' and KEEPING empty segments, which
// it then counts like any word (antidote_ccrdt_wordcount.erl:76-85).
// worddocumentcount additionally dedupes tokens within one document
// through a gb_set (antidote_ccrdt_worddocumentcount.erl:76-86).
//
// In the TPU pipeline documents are tokenized host-side into int32 token
// ids and the device only sees id batches (models/wordcount.py). This file
// moves that host-side hot loop out of Python: a whole corpus chunk
// (documents concatenated into one buffer + offsets) is tokenized, deduped
// and encoded in one C call.
//
// Two encoding modes, matching the Python encoders exactly:
//  * hashed  (n_buckets > 0): FNV-1a 32-bit % n_buckets — byte-identical
//    to models/wordcount.py:hash_token (stable across runs/processes);
//  * exact   (n_buckets == 0): grow-on-demand token -> dense id vocabulary
//    (VocabEncoder parity), dumpable for host-side decode.
//
// Per-document dedup happens on the token STRING before hashing/encoding
// (two distinct words colliding in hashed mode still contribute 2 to the
// shared bucket — same as the Python path). The dedup set is a
// generation-stamped open-addressing scratch table: resetting between
// documents is one counter bump, no clears, no per-token allocation —
// the unordered_set it replaced dominated the per-token cost.
//
// ccrdt_tok_encode_batch_mt runs the batch across a thread pool
// (documents are independent). Hashed mode is embarrassingly parallel.
// Exact mode runs two phases: threads tokenize against the (frozen)
// global vocabulary, assigning thread-local ids to unseen tokens; a
// serial remap pass then walks the output in document order and folds
// the thread-local vocabularies into the global one — so global ids are
// assigned in first-appearance order, bit-identical to the
// single-threaded encode. Callers on a 1-CPU host lose nothing: the
// n_threads <= 1 path is the plain loop.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

inline uint32_t Fnv1a(const char* s, size_t n) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < n; ++i) {
    h = (h ^ static_cast<uint8_t>(s[i])) * 16777619u;
  }
  return h;
}

struct StringPiece {
  const char* data;
  size_t len;
  bool operator==(const StringPiece& o) const {
    return len == o.len && std::memcmp(data, o.data, len) == 0;
  }
};

struct PieceHash {
  size_t operator()(const StringPiece& p) const {
    return Fnv1a(p.data, p.len);
  }
};

// Per-document string dedup without per-token allocation or clearing:
// slots carry a generation stamp, so resetting between documents is one
// counter bump. Linear probing over a power-of-two table kept at <= 50%
// load, GROWN on demand — sizing by document length would preallocate
// O(bytes) scratch (a 100MB single-document corpus would reserve GBs)
// where distinct tokens are what bounds the live set, exactly like the
// unordered_set this replaced.
class DedupScratch {
 public:
  // Reset for a new document (capacity is retained across documents).
  void Begin(size_t /*max_tokens_hint*/) {
    if (gen_.empty()) Alloc(1 << 10);
    if (++cur_ == 0) {  // generation wrap: one real clear every 2^32 docs
      std::fill(gen_.begin(), gen_.end(), 0u);
      cur_ = 1;
    }
    count_ = 0;
  }

  // True if `p` was not yet in this document's set (and inserts it).
  bool Insert(const StringPiece& p, uint32_t h) {
    if ((count_ + 1) * 2 > gen_.size()) Grow();
    size_t i = h & mask_;
    while (gen_[i] == cur_) {
      if (keys_[i] == p) return false;
      i = (i + 1) & mask_;
    }
    gen_[i] = cur_;
    keys_[i] = p;
    hashes_[i] = h;
    ++count_;
    return true;
  }

 private:
  void Alloc(size_t n) {
    gen_.assign(n, 0u);
    keys_.resize(n);
    hashes_.resize(n);
    mask_ = n - 1;
    cur_ = 1;
  }

  void Grow() {
    std::vector<uint32_t> old_gen;
    old_gen.swap(gen_);
    std::vector<StringPiece> old_keys;
    old_keys.swap(keys_);
    std::vector<uint32_t> old_hashes;
    old_hashes.swap(hashes_);
    uint32_t old_cur = cur_;
    Alloc(old_gen.size() * 2);
    for (size_t i = 0; i < old_gen.size(); ++i) {
      if (old_gen[i] != old_cur) continue;  // other documents' leftovers
      size_t j = old_hashes[i] & mask_;
      while (gen_[j] == cur_) j = (j + 1) & mask_;
      gen_[j] = cur_;
      keys_[j] = old_keys[i];
      hashes_[j] = old_hashes[i];
    }
  }

  std::vector<uint32_t> gen_;
  std::vector<StringPiece> keys_;
  std::vector<uint32_t> hashes_;
  uint32_t cur_ = 0;
  size_t mask_ = 0;
  size_t count_ = 0;
};

class Tokenizer {
 public:
  explicit Tokenizer(int32_t n_buckets) : buckets_(n_buckets) {}

  // Tokenize [buf, buf+len): emit one id per token (empties included).
  // per_document: dedupe token strings within this document first, in
  // first-appearance order (the gb_set sorts, but counts are order-
  // independent so first-appearance is equivalent for the CRDT).
  // Returns the number of ids written (never exceeds cap; the true count
  // is returned so callers can detect truncation).
  int64_t Encode(const char* buf, int64_t len, int per_document,
                 int32_t* out, int64_t cap) {
    int64_t n_out = 0;
    if (per_document) scratch_.Begin(static_cast<size_t>(len) + 1);
    const char* p = buf;
    const char* end = buf + len;
    const char* tok = p;
    for (;; ++p) {
      if (p == end || *p == '\n' || *p == ' ') {
        StringPiece piece{tok, static_cast<size_t>(p - tok)};
        uint32_t h = Fnv1a(piece.data, piece.len);
        bool emit = true;
        if (per_document) emit = scratch_.Insert(piece, h);
        if (emit) {
          int32_t id = EncodeTokenHashed(piece, h);
          if (n_out < cap) out[n_out] = id;
          ++n_out;
        }
        tok = p + 1;
      }
      if (p == end) break;
    }
    return n_out;
  }

  // Encode with the token's FNV already computed (the dedup needed it).
  // Exact mode probes an open-addressed flat table with the SAME hash —
  // the unordered_map it replaced re-hashed every token on lookup, which
  // dominated exact-mode encode (measured ~2x the hashed mode's cost).
  int32_t EncodeTokenHashed(const StringPiece& piece, uint32_t h) {
    if (buckets_ > 0) {
      return static_cast<int32_t>(h % static_cast<uint32_t>(buckets_));
    }
    if (vocab_ids_.empty()) GrowVocabTable(1 << 12);
    size_t i = h & vocab_mask_;
    while (vocab_ids_[i] >= 0) {
      int32_t cand = vocab_ids_[i];
      const std::string& owned = storage_[cand];
      if (vocab_hashes_[i] == h && owned.size() == piece.len &&
          std::memcmp(owned.data(), piece.data, piece.len) == 0) {
        return cand;
      }
      i = (i + 1) & vocab_mask_;
    }
    // Own the bytes: the piece points into the caller's buffer.
    storage_.emplace_back(piece.data, piece.len);
    int32_t id = static_cast<int32_t>(storage_.size()) - 1;
    vocab_ids_[i] = id;
    vocab_hashes_[i] = h;
    if ((storage_.size() + 1) * 2 > vocab_ids_.size()) {
      GrowVocabTable(vocab_ids_.size() * 2);
    }
    return id;
  }

  void GrowVocabTable(size_t n) {
    // Reinsert occupied slots using their SAVED hashes (cf.
    // DedupScratch::Grow) — recomputing FNV over the stored strings
    // would redo exactly the hashing work this table exists to avoid.
    std::vector<int32_t> old_ids;
    old_ids.swap(vocab_ids_);
    std::vector<uint32_t> old_hashes;
    old_hashes.swap(vocab_hashes_);
    vocab_ids_.assign(n, -1);
    vocab_hashes_.assign(n, 0u);
    vocab_mask_ = n - 1;
    for (size_t i = 0; i < old_ids.size(); ++i) {
      if (old_ids[i] < 0) continue;
      size_t j = old_hashes[i] & vocab_mask_;
      while (vocab_ids_[j] >= 0) j = (j + 1) & vocab_mask_;
      vocab_ids_[j] = old_ids[i];
      vocab_hashes_[j] = old_hashes[i];
    }
  }

  int32_t EncodeToken(const StringPiece& piece) {
    return EncodeTokenHashed(piece, Fnv1a(piece.data, piece.len));
  }

  int64_t VocabSize() const {
    return buckets_ > 0 ? buckets_ : static_cast<int64_t>(storage_.size());
  }

  // Dump the exact-mode vocabulary as id-ordered tokens joined by '\n'
  // (tokens never contain '\n' or ' ' — they are split on them; the empty
  // token round-trips as an empty line). Returns the required byte count;
  // writes at most cap bytes.
  int64_t VocabDump(char* out, int64_t cap) const {
    int64_t need = 0;
    for (size_t i = 0; i < storage_.size(); ++i) {
      need += static_cast<int64_t>(storage_[i].size()) + (i ? 1 : 0);
    }
    if (out == nullptr || cap < need) return need;
    char* w = out;
    for (size_t i = 0; i < storage_.size(); ++i) {
      if (i) *w++ = '\n';
      std::memcpy(w, storage_[i].data(), storage_[i].size());
      w += storage_[i].size();
    }
    return need;
  }

  int32_t buckets() const { return buckets_; }

  // Read-only lookup (safe concurrently while no inserts run). Callers
  // pass the piece's FNV so the probe reuses it.
  bool Find(const StringPiece& p, uint32_t h, int32_t* out) const {
    if (vocab_ids_.empty()) return false;
    size_t i = h & vocab_mask_;
    while (vocab_ids_[i] >= 0) {
      int32_t cand = vocab_ids_[i];
      const std::string& owned = storage_[cand];
      if (vocab_hashes_[i] == h && owned.size() == p.len &&
          std::memcmp(owned.data(), p.data, p.len) == 0) {
        *out = cand;
        return true;
      }
      i = (i + 1) & vocab_mask_;
    }
    return false;
  }

  int64_t EncodeBatch(const char* buf, const int64_t* offsets, int n_docs,
                      int per_document, int32_t* out, int64_t cap,
                      int64_t* out_doc_end) {
    int64_t total = 0;
    for (int i = 0; i < n_docs; ++i) {
      const char* doc = buf + offsets[i];
      int64_t len = offsets[i + 1] - offsets[i];
      int64_t room = cap > total ? cap - total : 0;
      total += Encode(doc, len, per_document, out + total, room);
      if (out_doc_end) out_doc_end[i] = total;
    }
    return total;
  }

  int64_t EncodeBatchMT(const char* buf, const int64_t* offsets, int n_docs,
                        int per_document, int32_t* out, int64_t cap,
                        int64_t* out_doc_end, int n_threads);

 private:
  int32_t buckets_;
  // Exact mode: open-addressed (hash, id) table probing into storage_ (a
  // deque never relocates on push_back, so the string bytes referenced
  // by lookups stay put). Power-of-two sized, <= 50% load.
  std::vector<int32_t> vocab_ids_;
  std::vector<uint32_t> vocab_hashes_;
  size_t vocab_mask_ = 0;
  std::deque<std::string> storage_;
  DedupScratch scratch_;
};

// Per-thread output of the parallel batch encode. Exact-mode unseen
// tokens get ids encoded as ~local_id (negative — distinguishable from
// global ids without a second array); `local` owns their bytes.
struct ThreadShard {
  std::vector<int32_t> ids;
  std::vector<int64_t> doc_end;  // cumulative within the shard
  std::deque<std::string> local;
  std::unordered_map<StringPiece, int32_t, PieceHash> local_vocab;
};

int64_t Tokenizer::EncodeBatchMT(const char* buf, const int64_t* offsets,
                                 int n_docs, int per_document, int32_t* out,
                                 int64_t cap, int64_t* out_doc_end,
                                 int n_threads) {
  if (n_threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = hw ? static_cast<int>(hw) : 1;
  }
  if (n_threads > n_docs) n_threads = n_docs > 0 ? n_docs : 1;
  if (n_threads <= 1) {
    return EncodeBatch(buf, offsets, n_docs, per_document, out, cap,
                       out_doc_end);
  }

  // Split documents into contiguous ranges of roughly equal byte size so
  // one long document cannot serialize the pool.
  std::vector<int> starts(n_threads + 1, n_docs);
  const int64_t total_bytes = offsets[n_docs] - offsets[0];
  starts[0] = 0;
  for (int t = 1; t < n_threads; ++t) {
    int64_t want = offsets[0] + total_bytes * t / n_threads;
    int lo = starts[t - 1];
    int d = lo;
    while (d < n_docs && offsets[d] < want) ++d;
    starts[t] = d;
  }
  starts[n_threads] = n_docs;

  std::vector<ThreadShard> shards(n_threads);
  const Tokenizer* self = this;
  auto work = [&](int t) {
    ThreadShard& sh = shards[t];
    DedupScratch scratch;
    sh.doc_end.reserve(starts[t + 1] - starts[t]);
    for (int d = starts[t]; d < starts[t + 1]; ++d) {
      const char* doc = buf + offsets[d];
      const char* end = buf + offsets[d + 1];
      if (per_document) {
        scratch.Begin(static_cast<size_t>(end - doc) + 1);
      }
      const char* tok = doc;
      for (const char* p = doc;; ++p) {
        if (p == end || *p == '\n' || *p == ' ') {
          StringPiece piece{tok, static_cast<size_t>(p - tok)};
          uint32_t h = Fnv1a(piece.data, piece.len);
          bool emit = true;
          if (per_document) emit = scratch.Insert(piece, h);
          if (emit) {
            int32_t id;
            if (self->buckets_ > 0) {
              id = static_cast<int32_t>(
                  h % static_cast<uint32_t>(self->buckets_));
            } else if (int32_t g; self->Find(piece, h, &g)) {
              id = g;  // global vocab is frozen while threads run
            } else {
              auto it = sh.local_vocab.find(piece);
              if (it != sh.local_vocab.end()) {
                id = ~it->second;
              } else {
                sh.local.emplace_back(piece.data, piece.len);
                const std::string& owned = sh.local.back();
                int32_t lid =
                    static_cast<int32_t>(sh.local.size()) - 1;
                sh.local_vocab.emplace(
                    StringPiece{owned.data(), owned.size()}, lid);
                id = ~lid;
              }
            }
            sh.ids.push_back(id);
          }
          tok = p + 1;
        }
        if (p == end) break;
      }
      sh.doc_end.push_back(static_cast<int64_t>(sh.ids.size()));
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(n_threads - 1);
  for (int t = 1; t < n_threads; ++t) pool.emplace_back(work, t);
  work(0);
  for (auto& th : pool) th.join();

  // Serial stitch in document order. Exact mode folds thread-local
  // vocabularies into the global one here, so global ids are assigned in
  // first-appearance order — identical to the single-threaded encode.
  int64_t total = 0;
  for (int t = 0; t < n_threads; ++t) {
    ThreadShard& sh = shards[t];
    std::vector<int32_t> remap;  // local id -> global id, -1 = unassigned
    if (buckets_ <= 0) remap.assign(sh.local.size(), -1);
    size_t di = 0;
    for (size_t j = 0; j < sh.ids.size(); ++j) {
      int32_t id = sh.ids[j];
      if (id < 0) {
        int32_t lid = ~id;
        if (remap[lid] < 0) {
          const std::string& s = sh.local[lid];
          remap[lid] = EncodeToken(StringPiece{s.data(), s.size()});
        }
        id = remap[lid];
      }
      if (total < cap) out[total] = id;
      ++total;
      while (di < sh.doc_end.size() &&
             static_cast<int64_t>(j) + 1 == sh.doc_end[di]) {
        if (out_doc_end) out_doc_end[starts[t] + di] = total;
        ++di;
      }
    }
    // Empty documents at the shard tail (or an all-empty shard).
    while (di < sh.doc_end.size()) {
      if (out_doc_end) out_doc_end[starts[t] + di] = total;
      ++di;
    }
  }
  return total;
}

}  // namespace

extern "C" {

void* ccrdt_tok_new(int32_t n_buckets) { return new Tokenizer(n_buckets); }

void ccrdt_tok_free(void* t) { delete static_cast<Tokenizer*>(t); }

int64_t ccrdt_tok_encode(void* t, const char* buf, int64_t len,
                         int per_document, int32_t* out, int64_t cap) {
  return static_cast<Tokenizer*>(t)->Encode(buf, len, per_document, out, cap);
}

// Batch ingest: n_docs documents concatenated in `buf`, document i spanning
// [offsets[i], offsets[i+1]). Token ids append into `out` (capacity `cap`);
// out_doc_end[i] receives the cumulative token count after document i.
// Returns the total token count (callers compare with cap for truncation).
int64_t ccrdt_tok_encode_batch(void* t, const char* buf,
                               const int64_t* offsets, int n_docs,
                               int per_document, int32_t* out, int64_t cap,
                               int64_t* out_doc_end) {
  return static_cast<Tokenizer*>(t)->EncodeBatch(buf, offsets, n_docs,
                                                 per_document, out, cap,
                                                 out_doc_end);
}

// Parallel batch ingest (same contract as ccrdt_tok_encode_batch).
// n_threads <= 0 uses the hardware thread count; output (ids, doc ends,
// exact-mode vocabulary id assignment) is bit-identical to the serial
// call for every thread count.
int64_t ccrdt_tok_encode_batch_mt(void* t, const char* buf,
                                  const int64_t* offsets, int n_docs,
                                  int per_document, int32_t* out, int64_t cap,
                                  int64_t* out_doc_end, int n_threads) {
  return static_cast<Tokenizer*>(t)->EncodeBatchMT(buf, offsets, n_docs,
                                                   per_document, out, cap,
                                                   out_doc_end, n_threads);
}

int64_t ccrdt_tok_vocab_size(void* t) {
  return static_cast<Tokenizer*>(t)->VocabSize();
}

int64_t ccrdt_tok_vocab_dump(void* t, char* out, int64_t cap) {
  return static_cast<Tokenizer*>(t)->VocabDump(out, cap);
}

}  // extern "C"
