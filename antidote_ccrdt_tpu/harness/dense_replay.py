"""Batched multi-DC replay over the dense (TPU) engines.

`ScalarReplay` (replay.py) ships individual effect ops between replicas —
the faithful rebuild of the reference's op-based pipeline, which *requires*
the host's causal exactly-once delivery (SURVEY.md §1). `DenseReplay` is
the TPU-native counterpart at batch granularity: every replica (simulated
DC) applies its own op batch in one vectorized dispatch across all
replicas, and reconciliation is a *state-level* exchange whose protocol
depends on the type's declared merge algebra (`MergeKind`):

* **JOIN** (topk, topk_rmv, leaderboard): replica rows are full states in a
  join-semilattice; `sync` folds all rows with the CRDT join and broadcasts
  the result back. Because the join is idempotent, the exchange tolerates
  duplicated and reordered contributions by construction — the property the
  op-based pipeline must *assume* from its host, demonstrated here as a
  fault-model test surface (`sync(contributors=...)`).

* **MONOID** (average, wordcount, worddocumentcount): replica rows are
  *deltas* accumulated since the last sync (the reference relies on the
  host applying each op exactly once, SURVEY.md §1; summing full states
  would double-count). `sync` all-reduces the deltas onto a shared
  converged base and resets them — exactly-once by construction, and a
  duplicated contribution measurably corrupts the result (the dual test
  surface).

On hardware the fold in `sync` is the intra-chip stand-in for the mesh
collective: `parallel.dist.lattice_all_reduce` runs the same combiner over
the 'dc' mesh axis (see __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.behaviour import DenseCCRDT, MergeKind
from ..obs import devprof, profile
from ..utils.metrics import Metrics


def _rows(state: Any, idx) -> Any:
    return jax.tree.map(lambda x: x[idx], state)


def fold_rows(dense: DenseCCRDT, state: Any, contributors: Sequence[int]) -> Any:
    """Fold the given replica rows (with repetition allowed) with the CRDT
    merge. `merge` is batched over the leading replica axis, so the tree
    reduction halves the whole stack at once: log2(n) dispatches total.
    Public: the read-side reconciliation primitive (elastic_demo, embedders)
    as well as this replay's sync step."""
    idx = np.asarray(list(contributors), dtype=np.int32)
    acc = _rows(state, idx)  # [C, ...]
    n = len(idx)
    while n > 1:
        half = n // 2
        lhs, rhs = _rows(acc, slice(0, half)), _rows(acc, slice(half, 2 * half))
        if profile.ACTIVE or devprof.ACTIVE:
            # dense.merge is the engine's class-level jitted method, so
            # the observatory watches its real compilation cache here.
            with profile.dispatch(
                "dense_replay.fold_rows", fn=dense.merge, operands=(lhs, rhs)
            ):
                merged = dense.merge(lhs, rhs)
        else:
            merged = dense.merge(lhs, rhs)
        if n % 2:
            merged = jax.tree.map(
                lambda m, t: jnp.concatenate([m, t], axis=0),
                merged,
                _rows(acc, slice(2 * half, n)),
            )
        acc = merged
        n = half + n % 2
    return acc


def _broadcast_rows(folded: Any, n: int) -> Any:
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[:1], (n,) + x.shape[1:]), folded
    )


class DenseReplay:
    """Round-based multi-DC pipeline over a dense engine.

    state layout: [n_replicas, n_keys, ...] — replica r's row is DC r.
    """

    def __init__(
        self,
        dense: DenseCCRDT,
        n_replicas: int,
        n_keys: int = 1,
        metrics: Optional[Metrics] = None,
    ):
        self.dense = dense
        self.n = n_replicas
        self.nk = n_keys
        self.metrics = metrics if metrics is not None else Metrics()
        if dense.merge_kind == MergeKind.MONOID:
            # base: the converged state as of the last sync (one row,
            # broadcast on read); rows of `state` are per-replica deltas.
            self.base = dense.init(n_replicas=1, n_keys=n_keys)
        else:
            self.base = None
        self.state = dense.init(n_replicas=n_replicas, n_keys=n_keys)
        self.extras_log: List[Any] = []

    # -- local application -------------------------------------------------

    def apply(self, ops: Any, report_drops: bool = False) -> Any:
        """Apply one op batch (replica r's ops in row r) locally at every
        replica — a single vectorized dispatch; collects generated extras
        (promotions / rmv re-broadcasts) for the types that emit them.

        `report_drops` feeds the jit-boundary silent-drop counters
        (utils.validate.topk_rmv_drop_report) into this replay's metrics,
        separating padding from genuine out-of-range garbage — wire an
        alarm on `ops_dropped_out_of_range` to catch a corrupted feed."""
        if report_drops and hasattr(ops, "rmv_vc"):
            from ..utils.validate import topk_rmv_drop_report

            rep = topk_rmv_drop_report(self.dense, self.state, ops)
            self.metrics.count(
                "ops_dropped_out_of_range",
                rep["add_dropped_out_of_range"]
                + rep["rmv_dropped_out_of_range"],
            )
            self.metrics.count(
                "ops_padding", rep["add_padding"] + rep["rmv_padding"]
            )
        with self.metrics.timer("apply"):
            # Engines declare their replication-realistic extras mode (e.g.
            # topk_rmv's id-keyed dominated table instead of the op-aligned
            # gather that dominates the round — measured numbers in
            # models/topk_rmv_dense.py apply_ops docstring).
            kwargs = getattr(self.dense, "replication_extras_kwargs", {})
            self.state, extras = self.dense.apply_ops(self.state, ops, **kwargs)
        if extras is not None:
            self.extras_log.append(extras)
        self.metrics.count("rounds")
        return extras

    def apply_coalesced(self, ops_list: Sequence[Any], **coalesce_kw: Any) -> Any:
        """Whole-log compaction as a pre-apply pass: fuse several op
        batches into one compacted batch via the engine's `coalesce_ops`
        (reference: the host compacts its log before shipping,
        antidote_ccrdt.erl:55-56), then apply it as a single round.

        Note the extras caveat: compaction deletes dominated adds, so
        their re-broadcast extras are not generated — use on logs whose
        dominated extras are not consumed (see
        ops.compaction.coalesce_topk_rmv_ops)."""
        coalesce = getattr(self.dense, "coalesce_ops", None)
        if coalesce is None:
            raise TypeError(
                f"{type(self.dense).__name__} does not support batch "
                "coalescing (no coalesce_ops)"
            )
        with self.metrics.timer("coalesce"):
            ops, n_add, n_rmv = coalesce(ops_list, **coalesce_kw)
        self.metrics.count("coalesce_ops_in", sum(
            o.add_key.shape[0] * (o.add_key.shape[1] + o.rmv_key.shape[1])
            for o in ops_list
        ))
        self.metrics.count("coalesce_ops_out", int(n_add.sum() + n_rmv.sum()))
        return self.apply(ops)

    # -- reconciliation ----------------------------------------------------

    def sync(self, contributors: Optional[Sequence[int]] = None) -> None:
        """Inter-DC reconciliation.

        `contributors` is the delivery fault surface: the list of replica
        rows whose contribution reaches the exchange (default: each exactly
        once). Duplicates model duplicated delivery, omissions model loss.
        JOIN types absorb duplicates (idempotent join); MONOID types
        double-count them — mirroring which guarantees each pipeline needs.
        """
        if contributors is None:
            contributors = range(self.n)
        contributors = list(contributors)
        with self.metrics.timer("sync"):
            if not contributors:
                # Total loss: nothing reaches the exchange. JOIN replicas
                # learn nothing and keep their local state; MONOID replicas
                # have shipped (and lost) their deltas — base unchanged.
                if self.dense.merge_kind == MergeKind.MONOID:
                    self.state = self.dense.init(n_replicas=self.n, n_keys=self.nk)
            elif self.dense.merge_kind == MergeKind.JOIN:
                folded = fold_rows(self.dense, self.state, contributors)
                self.state = _broadcast_rows(folded, self.n)
            else:
                summed = fold_rows(self.dense, self.state, contributors)
                self.base = self.dense.merge(self.base, summed)
                self.state = self.dense.init(n_replicas=self.n, n_keys=self.nk)
        self.metrics.count("syncs")

    # -- observation -------------------------------------------------------

    def full_state(self) -> Any:
        """Per-replica effective state: deltas on top of the shared base
        for MONOID types, the replica rows themselves for JOIN types."""
        if self.base is None:
            return self.state
        return self.dense.merge(_broadcast_rows(self.base, self.n), self.state)

    def observe(self) -> Any:
        return self.dense.observe(self.full_state())

    def converged(self, atol: float = 0.0) -> bool:
        """All replicas report the same observable (bitwise by default;
        atol > 0 allows absolute float slack, with no relative component —
        a silent rtol would mask exactly the small divergences the fault
        tests exist to catch)."""
        obs = self.observe()
        leaves = obs if isinstance(obs, (tuple, list)) else (obs,)
        for leaf in jax.tree.leaves(tuple(leaves)):
            arr = np.asarray(leaf)
            if atol > 0.0 and arr.dtype.kind == "f":
                if not np.allclose(arr, arr[:1], rtol=0.0, atol=atol):
                    return False
            elif not (arr == arr[:1]).all():
                return False
        return True
