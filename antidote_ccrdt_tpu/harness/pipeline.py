"""Streaming ingest pipeline: overlap host batch-building with device work.

JAX dispatch is async, so a plain loop already overlaps *dispatch* with
host work — but a host-side producer that only yields the next batch after
the previous `apply` was dispatched still serializes its own work (op
generation, native-host drains, tokenization) with the device sync at the
loop head. `Prefetcher` runs the producer on a background thread with a
bounded queue: the C ingest calls (`native_host.drain`,
`native_tokenizer.encode_batch`) release the GIL, so batch k+1 is built
while batch k executes on the TPU.

`stream_apply` is the standard consume loop: prefetch -> apply -> periodic
reconcile, returning the final state. Used standalone or as the template
for embedders.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

_SENTINEL = object()


class Prefetcher:
    """Iterate `source` on a background thread, `depth` batches ahead.

    Exceptions in the producer propagate to the consumer at the point of
    `next()`. Close (or exhaust) to join the thread; usable as a context
    manager and safely re-entrant for one pass only."""

    def __init__(self, source: Iterable[Any], depth: int = 2):
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._done = False

        def worker():
            try:
                for item in source:
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                self._err = e
            finally:
                # Never block on the sentinel: a closing consumer stops
                # draining, and an unbounded put here would deadlock the
                # join in close() (the queue can be full at depth=1).
                while not self._stop.is_set():
                    try:
                        self._q.put(_SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._done:  # iterator protocol: keep raising after exhaustion
            if self._err is not None:
                raise self._err
            raise StopIteration
        item = self._q.get()
        if item is _SENTINEL:
            self._done = True
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # Drain so the producer's pending put can finish, then join.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def stream_apply(
    engine: Any,
    state: Any,
    batches: Iterable[Any],
    *,
    depth: int = 2,
    reconcile_every: int = 0,
    reconcile: Optional[Callable[[Any], Any]] = None,
    apply_kwargs: Optional[dict] = None,
    coalesce: int = 0,
    coalesce_kwargs: Optional[dict] = None,
):
    """Fold a stream of op batches into `state` with prefetch overlap:
    ``state = engine.apply_ops(state, batch)[0]`` per batch, calling
    `reconcile(state)` every `reconcile_every` batches (0 = never).

    `coalesce=k` buffers k batches and pre-compacts them into ONE batch
    via the engine's whole-log `coalesce_ops` (ops/compaction.py) before
    applying — the pre-ship log-compaction pass (the reference host's
    can_compact/compact_ops walk, antidote_ccrdt.erl:55-56). The final
    partial group is coalesced too. `reconcile_every` then counts
    coalesced applications. Returns (state, n_batches) with n_batches
    the RAW batch count consumed."""
    kw = apply_kwargs or {}
    n = 0
    applied = 0

    def do_apply(ops):
        nonlocal state, applied
        state, _ = engine.apply_ops(state, ops, **kw)
        applied += 1
        if reconcile_every and reconcile is not None and applied % reconcile_every == 0:
            state = reconcile(state)

    buf = []
    with Prefetcher(batches, depth=depth) as pf:
        for ops in pf:
            n += 1
            if coalesce and coalesce > 1:
                buf.append(ops)
                if len(buf) == coalesce:
                    fused, _, _ = engine.coalesce_ops(buf, **(coalesce_kwargs or {}))
                    buf = []
                    do_apply(fused)
            else:
                do_apply(ops)
    if buf:
        fused, _, _ = engine.coalesce_ops(buf, **(coalesce_kwargs or {}))
        do_apply(fused)
    return state, n
