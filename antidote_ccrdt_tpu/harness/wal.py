"""Per-replica write-ahead delta log: crash-consistent worker recovery.

The elastic tier (parallel/elastic.py) survives crashes by PEER adoption:
op generation is deterministic, so survivors regenerate a dead member's
whole history. That is the fallback of last resort — it costs a full
re-apply of every adopted replica's stream and only works while op
streams are regenerable. This module gives each worker its own durable
recovery path, the way a database pairs WAL + checkpoint:

* `WriteAheadLog` — generic append-only segmented log of (seq, payload)
  records. Framing per record::

      u32le frame_len | u32le crc32(frame) | frame
      frame := u64le seq ++ payload

  CRC covers seq+payload, so a torn OR bit-rotted tail is detected, not
  replayed. Records fsync per append (`wal.fsync` fault point); segments
  rotate at a byte threshold; `compact(watermark)` drops whole segments
  whose records are all <= the watermark (the caller ties the watermark
  to state already captured by a checkpoint AND acked by the gossip
  medium). On open, a torn tail is truncated in place and any segments
  after the tear are dropped — bytes after a torn frame were never
  acknowledged to anyone.

* `ElasticWal` — the elastic-worker discipline on top: each applied op
  batch is logged as a join-decomposed delta (`parallel.delta
  .make_delta`) BEFORE the state is published, and a periodic full
  checkpoint (`save_dense_checkpoint` format) anchors compaction.
  `recover` rebuilds state = checkpoint ⊔ WAL-delta suffix — safe by
  exactly the delta-chaining argument from parallel/delta.py: every
  record was cut against the direct ancestor lineage of the checkpoint,
  so joining the expanded deltas in seq order reproduces the pre-crash
  state (records older than the checkpoint re-join harmlessly).

A `kill -9` mid-run therefore costs a worker nothing it had appended:
it restores checkpoint ⊔ suffix, rejoins gossip, and continues at the
step after its last durable record — peer adoption remains the fallback
when the WAL itself is lost (tests pin both paths).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from ..core import serial
from ..obs import events as obs_events
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.metrics import Metrics
from .checkpoint import load_dense_checkpoint, save_dense_checkpoint

_HDR = struct.Struct("<II")  # frame_len, crc32
_SEQ = struct.Struct("<Q")
_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".wal"


def _seg_name(idx: int) -> str:
    return f"{_SEG_PREFIX}{idx:08d}{_SEG_SUFFIX}"


class WriteAheadLog:
    """Segmented, CRC-framed, fsync-per-append write-ahead log."""

    def __init__(
        self,
        root: str,
        segment_bytes: int = 1 << 20,
        sync: bool = True,
        metrics: Optional[Metrics] = None,
    ):
        self.root = root
        self.segment_bytes = int(segment_bytes)
        self.sync = sync
        self.metrics = metrics if metrics is not None else Metrics()
        os.makedirs(root, exist_ok=True)
        self._seg_max: Dict[int, int] = {}  # segment idx -> max seq in it
        self.last_seq = -1
        self.torn_bytes = 0
        self._scan_and_repair()
        self._cur = max(self._seg_max) if self._seg_max else 0
        self._fh = open(self._path(self._cur), "ab")

    # -- layout ------------------------------------------------------------

    def _path(self, idx: int) -> str:
        return os.path.join(self.root, _seg_name(idx))

    def _segments(self) -> List[int]:
        out = []
        for f in os.listdir(self.root):
            if f.startswith(_SEG_PREFIX) and f.endswith(_SEG_SUFFIX):
                try:
                    out.append(int(f[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(out)

    # -- open-time repair --------------------------------------------------

    def _scan_and_repair(self) -> None:
        """Validate every segment in order; on the first torn/corrupt
        frame, truncate that segment there and DELETE all later segments
        (a record is only durable if every byte before it is — bytes
        past a tear were never acknowledged)."""
        segs = self._segments()
        for pos, idx in enumerate(segs):
            good, max_seq, n = self._scan_segment(self._path(idx))
            size = os.path.getsize(self._path(idx))
            if n:
                self._seg_max[idx] = max_seq
                self.last_seq = max(self.last_seq, max_seq)
            if good < size:
                self.torn_bytes += size - good
                os.truncate(self._path(idx), good)
                for later in segs[pos + 1:]:
                    self.torn_bytes += os.path.getsize(self._path(later))
                    os.remove(self._path(later))
                break
        if self.torn_bytes:
            self.metrics.count("wal.torn_bytes", self.torn_bytes)
            obs_events.emit(
                "wal.torn", dir=self.root, bytes=self.torn_bytes
            )

    @staticmethod
    def _scan_segment(path: str) -> Tuple[int, int, int]:
        """-> (valid_prefix_bytes, max_seq, n_records)."""
        good, max_seq, n = 0, -1, 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) != _HDR.size:
                    break
                ln, crc = _HDR.unpack(hdr)
                frame = f.read(ln)
                if len(frame) != ln or zlib.crc32(frame) != crc:
                    break
                max_seq = max(max_seq, _SEQ.unpack(frame[:_SEQ.size])[0])
                n += 1
                good += _HDR.size + ln
        return good, max_seq, n

    # -- append / rotate ---------------------------------------------------

    def append(self, seq: int, payload: bytes) -> None:
        frame = _SEQ.pack(seq) + payload
        rec = _HDR.pack(len(frame), zlib.crc32(frame)) + frame
        if self._fh.tell() + len(rec) > self.segment_bytes and self._fh.tell() > 0:
            self._rotate()
        self._fh.write(rec)
        self._fh.flush()
        if self.sync:
            # Fault point `wal.fsync`: an injected EIO surfaces to the
            # caller exactly like a dying disk — the record is NOT
            # durable and the caller must not publish past it.
            if faults.ACTIVE:
                faults.fire("wal.fsync")
            os.fsync(self._fh.fileno())
        self._seg_max[self._cur] = max(self._seg_max.get(self._cur, -1), seq)
        self.last_seq = max(self.last_seq, seq)
        self.metrics.count("wal.appends")
        self.metrics.count("wal.bytes", len(rec))
        # Durable watermark gauge + event AFTER the fsync: the flight
        # log's last wal.append IS the crash-recovery watermark (what
        # `make crash-demo` cross-checks against the victim's resume).
        self.metrics.set("wal.last_seq", float(self.last_seq))
        obs_events.emit("wal.append", wseq=seq, bytes=len(rec))

    def _rotate(self) -> None:
        self._fh.close()
        self._cur += 1
        self._fh = open(self._path(self._cur), "ab")
        self.metrics.count("wal.rotations")
        obs_events.emit("wal.rotate", segment=self._cur)

    # -- read / compact ----------------------------------------------------

    def records(self) -> Iterator[Tuple[int, bytes]]:
        """All (seq, payload) records in segment+offset order. The open-
        time repair already removed any tear; a frame going bad AFTER
        open (concurrent corruption) stops iteration at the last valid
        prefix, mirroring the open-time policy."""
        self._fh.flush()
        for idx in sorted(self._seg_max) if self._seg_max else []:
            with open(self._path(idx), "rb") as f:
                while True:
                    hdr = f.read(_HDR.size)
                    if len(hdr) != _HDR.size:
                        break
                    ln, crc = _HDR.unpack(hdr)
                    frame = f.read(ln)
                    if len(frame) != ln or zlib.crc32(frame) != crc:
                        return
                    yield _SEQ.unpack(frame[:_SEQ.size])[0], frame[_SEQ.size:]

    def compact(self, watermark: int) -> int:
        """Remove closed segments whose every record seq <= watermark.
        The ACTIVE segment never goes (truncating the file under the
        append handle is not crash-safe); rotation keeps it bounded.
        Returns the number of segments removed."""
        removed = 0
        for idx in sorted(self._seg_max):
            if idx == self._cur:
                continue
            if self._seg_max[idx] <= watermark:
                os.remove(self._path(idx))
                del self._seg_max[idx]
                removed += 1
        if removed:
            self.metrics.count("wal.segments_compacted", removed)
        return removed

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- elastic-worker discipline ---------------------------------------------


class ElasticWal:
    """Checkpoint + delta WAL for one elastic gossip worker.

    Record payload: ``encode_term((step, owned_list)) is framed inside
    the ETF term together with the delta blob`` — concretely
    ``encode_term((step, owned, delta_blob))`` where ``delta_blob`` is
    the same `dumps_dense(f"{name}_delta", delta)` encoding the gossip
    tier ships, so WAL records and wire deltas stay one format.

    With `partitions` set, records are tagged with the partition set
    their delta touches (``encode_term((step, owned, blob, parts))`` —
    a 4-tuple; `core.partition.delta_parts`), so recovery and rejoin
    tooling can reason per partition. `recover` branches on the tuple
    arity, so un-tagged legacy records and tagged records interleave
    freely in one log (the mixed-version compat contract).
    """

    SNAP = "snap.ckpt"

    def __init__(
        self,
        root: str,
        member: str,
        dense: Any,
        name: str,
        segment_bytes: int = 256 << 10,
        metrics: Optional[Metrics] = None,
        partitions: Optional[int] = None,
    ):
        self.dir = os.path.join(root, f"wal-{member}")
        self.member = member
        self.dense = dense
        self.name = name
        self.partitions = partitions
        self.metrics = metrics if metrics is not None else Metrics()
        self.log = WriteAheadLog(
            self.dir, segment_bytes=segment_bytes, metrics=self.metrics
        )

    # -- write path --------------------------------------------------------

    def log_step(self, step: int, owned, prev_view: Any, view: Any) -> int:
        """Append this step's join-decomposed delta (prev_view -> view)
        plus its ownership record. MUST run before the step's publish:
        write-ahead means the durable record precedes any externally
        visible effect. Returns the appended payload size."""
        from ..parallel.delta import make_delta

        if obs_spans.ACTIVE:
            # The whole write-ahead cost — delta extraction, encode,
            # CRC framing, fsync — is one serial round phase.
            with obs_spans.span("round.wal_append", step=int(step)):
                delta = make_delta(self.dense, prev_view, view)
                blob = serial.dumps_dense(f"{self.name}_delta", delta)
                payload = self._encode_record(step, owned, view, delta, blob)
                self.log.append(step, payload)
            return len(payload)
        delta = make_delta(self.dense, prev_view, view)
        blob = serial.dumps_dense(f"{self.name}_delta", delta)
        payload = self._encode_record(step, owned, view, delta, blob)
        self.log.append(step, payload)
        return len(payload)

    def _encode_record(
        self, step: int, owned, view: Any, delta: Any, blob: bytes
    ) -> bytes:
        """Legacy 3-tuple record, or the partition-tagged 4-tuple when
        this WAL runs with a partition count."""
        base = (int(step), [int(r) for r in owned], blob)
        if not self.partitions:
            return serial.encode_term(base)
        from ..core import partition as pt

        try:
            parts = sorted(
                pt.delta_parts(self.dense, view, delta, self.partitions)
            )
        except Exception:  # noqa: BLE001 — a tag failure must not block
            parts = []     # durability; empty tag = "unknown partitions"
        return serial.encode_term(base + (parts,))

    def checkpoint(self, view: Any, step: int) -> None:
        """Anchor: durable full state at `step`, then compact every
        closed segment fully covered by it. Call only for state already
        PUBLISHED at this step — the watermark must never pass gossip
        acks, or a crash between checkpoint and publish could discard
        deltas peers have not seen."""
        save_dense_checkpoint(
            os.path.join(self.dir, self.SNAP), self.name, view, step=step
        )
        self.log.compact(step)
        self.metrics.count("wal.checkpoints")
        obs_events.emit("wal.checkpoint", step=step)

    # -- recovery ----------------------------------------------------------

    def recover(self, like_view: Any) -> Tuple[Optional[Any], int, Set[int]]:
        """-> (recovered_view_or_None, last_step, owned_union).

        recovered_view = checkpoint ⊔ WAL-delta suffix (joined in seq
        order on top of `like_view`'s structure); last_step is the
        highest durable step (-1 = nothing recovered); owned_union is
        every replica id the lost incarnation logged ownership of."""
        from ..parallel.delta import apply_any_delta, like_delta_for

        state: Optional[Any] = None
        last_step = -1
        snap_path = os.path.join(self.dir, self.SNAP)
        if os.path.exists(snap_path):
            try:
                step, _name, state = load_dense_checkpoint(
                    snap_path, like_view, dense=self.dense
                )
                last_step = max(last_step, int(step))
                self.metrics.count("wal.recovered_snapshot")
            except Exception:  # noqa: BLE001 — a torn/foreign checkpoint
                state = None   # must not block WAL replay (total recovery)
        like_delta = like_delta_for(self.dense, like_view)
        owned: Set[int] = set()
        parts_touched: Set[int] = set()
        n = 0
        for seq, payload in self.log.records():
            try:
                rec = serial.decode_term(payload)
                # Arity is the version marker: legacy records are
                # (step, owned, blob); partition-tagged ones append the
                # partition list. Both replay identically — the tag is
                # metadata, the delta blob is the state.
                if len(rec) == 4:
                    step, rec_owned, blob, rec_parts = rec
                else:
                    step, rec_owned, blob = rec
                    rec_parts = ()
                _name, delta = serial.loads_dense(blob, like_delta)
                base = like_view if state is None else state
                state = apply_any_delta(self.dense, base, delta)
            except Exception:  # noqa: BLE001 — skip undecodable record,
                continue       # the join tolerates gaps (next snapshot wins)
            owned.update(int(r) for r in rec_owned)
            parts_touched.update(int(p) for p in rec_parts)
            last_step = max(last_step, int(step))
            n += 1
        if n:
            self.metrics.count("wal.recovered_records", n)
        obs_events.emit(
            "wal.recover",
            records=n,
            last_step=last_step,
            owned=sorted(owned),
            parts=sorted(parts_touched),
            had_checkpoint=os.path.exists(snap_path),
        )
        return state, last_step, owned

    def close(self) -> None:
        self.log.close()
