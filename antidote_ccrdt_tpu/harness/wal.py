"""Per-replica write-ahead delta log: crash-consistent worker recovery.

The elastic tier (parallel/elastic.py) survives crashes by PEER adoption:
op generation is deterministic, so survivors regenerate a dead member's
whole history. That is the fallback of last resort — it costs a full
re-apply of every adopted replica's stream and only works while op
streams are regenerable. This module gives each worker its own durable
recovery path, the way a database pairs WAL + checkpoint:

* `WriteAheadLog` — generic append-only segmented log of (seq, payload)
  records. Framing per record::

      u32le frame_len | u32le crc32(frame) | frame
      frame := u64le seq ++ payload

  CRC covers seq+payload, so a torn OR bit-rotted tail is detected, not
  replayed. Appends either fsync inline (`sync=True`, the `wal.fsync`
  fault point) or stage — write+flush to the OS, fsync deferred to
  `fsync_if_dirty()` so a caller can batch many appends under ONE fsync
  (group commit). Segments rotate at a byte threshold;
  `compact(watermark)` drops whole segments whose records are all <= the
  watermark; `truncate_after(watermark)` physically removes the record
  tail PAST a watermark (async-durability recovery). On open, a torn
  tail is truncated in place and any segments after the tear are dropped
  — bytes after a torn frame were never acknowledged to anyone.

* `ElasticWal` — the elastic-worker discipline on top, now with three
  durability modes (`CCRDT_WAL_DURABILITY`, default ``group``):

  - ``sync``  — legacy: one fsync per append, durable == appended.
  - ``group`` — group commit: appends stage; `flush()` (called at every
    publish boundary, plus byte/time backstops) fsyncs the whole batch
    once per dirty segment stream, so consecutive rounds share one
    fsync. Durable-before-visible is preserved because the boundary
    flushes BEFORE the publish.
  - ``async`` — opt-in: gossip may ship a delta BEFORE its fsync. The
    log publishes a per-member durability watermark (`wal.durable_seq`
    gauge + `wal.durable` flight events); fsyncs happen lazily (bounds)
    and at checkpoints. Recovery truncates the log to the watermark
    recorded in a tiny fsync'd mini-log (`wm/`), and the obs/audit
    certifier reconciles published-vs-durable from the flight log — so
    relaxed-path speed stays *audited* (zero unaudited loss).

  With `partitions` set the log is sharded into per-partition segment
  STREAMS (stream 0 keeps the legacy top-level layout; streams 1..S-1
  live in ``stream-NN/`` subdirs), records routed by their partition
  tag and fsync'd by a small writer pool so independent partitions never
  serialize behind one fd. Recovery merges streams by seq; `compact()`
  works per stream (a fully-covered stream compacts independently).
  Legacy single-stream logs are just the S=1 case and open unchanged.

  Each applied op batch is logged as a join-decomposed delta
  (`parallel.delta.make_delta`) BEFORE the state is published (sync /
  group modes), and a periodic full checkpoint anchors compaction.
  `recover` rebuilds state = checkpoint ⊔ WAL-delta suffix — safe by
  exactly the delta-chaining argument from parallel/delta.py.

A `kill -9` mid-run therefore costs a worker nothing it had appended
(sync), nothing past the last group flush (group), or nothing past the
published watermark (async — and the certifier proves exactly that from
the flight log). Peer adoption remains the fallback when the WAL itself
is lost (tests pin both paths).
"""

from __future__ import annotations

import os
import shutil
import struct
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from ..core import serial
from ..obs import events as obs_events
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.metrics import Metrics
from .checkpoint import load_dense_checkpoint, save_dense_checkpoint

_HDR = struct.Struct("<II")  # frame_len, crc32
_SEQ = struct.Struct("<Q")
_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".wal"

_STREAM_PREFIX = "stream-"
_WM_DIR = "wm"

MODES = ("sync", "group", "async")


def durability_mode(override: Optional[str] = None) -> str:
    """Resolve the durability mode: explicit override > env > 'group'."""
    m = (override or os.environ.get("CCRDT_WAL_DURABILITY", "")).strip().lower()
    return m if m in MODES else "group"


def _seg_name(idx: int) -> str:
    return f"{_SEG_PREFIX}{idx:08d}{_SEG_SUFFIX}"


class WriteAheadLog:
    """Segmented, CRC-framed write-ahead log (fsync per append, or
    staged appends + batched `fsync_if_dirty` for group commit)."""

    def __init__(
        self,
        root: str,
        segment_bytes: int = 1 << 20,
        sync: bool = True,
        metrics: Optional[Metrics] = None,
        fault_point: Optional[str] = "wal.fsync",
    ):
        self.root = root
        self.segment_bytes = int(segment_bytes)
        self.sync = sync
        self.metrics = metrics if metrics is not None else Metrics()
        self.fault_point = fault_point
        os.makedirs(root, exist_ok=True)
        self._seg_max: Dict[int, int] = {}  # segment idx -> max seq in it
        self.last_seq = -1
        self.torn_bytes = 0
        self._dirty = False  # bytes written+flushed but not yet fsync'd
        self._scan_and_repair()
        self._cur = max(self._seg_max) if self._seg_max else 0
        self._fh = open(self._path(self._cur), "ab")

    # -- layout ------------------------------------------------------------

    def _path(self, idx: int) -> str:
        return os.path.join(self.root, _seg_name(idx))

    def _segments(self) -> List[int]:
        out = []
        for f in os.listdir(self.root):
            if f.startswith(_SEG_PREFIX) and f.endswith(_SEG_SUFFIX):
                try:
                    out.append(int(f[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(out)

    # -- open-time repair --------------------------------------------------

    def _scan_and_repair(self) -> None:
        """Validate every segment in order; on the first torn/corrupt
        frame, truncate that segment there and DELETE all later segments
        (a record is only durable if every byte before it is — bytes
        past a tear were never acknowledged)."""
        segs = self._segments()
        for pos, idx in enumerate(segs):
            good, max_seq, n = self._scan_segment(self._path(idx))
            size = os.path.getsize(self._path(idx))
            if n:
                self._seg_max[idx] = max_seq
                self.last_seq = max(self.last_seq, max_seq)
            if good < size:
                self.torn_bytes += size - good
                os.truncate(self._path(idx), good)
                for later in segs[pos + 1:]:
                    self.torn_bytes += os.path.getsize(self._path(later))
                    os.remove(self._path(later))
                break
        if self.torn_bytes:
            self.metrics.count("wal.torn_bytes", self.torn_bytes)
            obs_events.emit(
                "wal.torn", dir=self.root, bytes=self.torn_bytes
            )

    @staticmethod
    def _scan_segment(path: str) -> Tuple[int, int, int]:
        """-> (valid_prefix_bytes, max_seq, n_records)."""
        good, max_seq, n = 0, -1, 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) != _HDR.size:
                    break
                ln, crc = _HDR.unpack(hdr)
                frame = f.read(ln)
                if len(frame) != ln or zlib.crc32(frame) != crc:
                    break
                max_seq = max(max_seq, _SEQ.unpack(frame[:_SEQ.size])[0])
                n += 1
                good += _HDR.size + ln
        return good, max_seq, n

    # -- append / rotate ---------------------------------------------------

    def append(self, seq: int, payload: bytes, sync: Optional[bool] = None) -> None:
        """Append one record. ``sync=True`` fsyncs inline (the legacy
        fsync-per-append discipline and its `wal.fsync` fault point);
        ``sync=False`` STAGES the record — written+flushed to the OS so
        readers see it, but durable only after `fsync_if_dirty()` (the
        group-commit path, where the caller fires the fault point once
        per batch instead)."""
        do_sync = self.sync if sync is None else sync
        frame = _SEQ.pack(seq) + payload
        rec = _HDR.pack(len(frame), zlib.crc32(frame)) + frame
        if self._fh.tell() + len(rec) > self.segment_bytes and self._fh.tell() > 0:
            self._rotate()
        self._fh.write(rec)
        self._fh.flush()
        if do_sync:
            # Fault point `wal.fsync`: an injected EIO surfaces to the
            # caller exactly like a dying disk — the record is NOT
            # durable and the caller must not publish past it.
            if faults.ACTIVE and self.fault_point:
                faults.fire(self.fault_point)
            os.fsync(self._fh.fileno())
        else:
            self._dirty = True
        self._seg_max[self._cur] = max(self._seg_max.get(self._cur, -1), seq)
        self.last_seq = max(self.last_seq, seq)
        self.metrics.count("wal.appends")
        self.metrics.count("wal.bytes", len(rec))
        # Appended watermark gauge + event at WRITE time in every mode:
        # the flight log's wal.append trail is the certifier's exposure
        # axis (what COULD have been published), durable acknowledgement
        # is the separate wal.durable trail.
        self.metrics.set("wal.last_seq", float(self.last_seq))
        obs_events.emit("wal.append", wseq=seq, bytes=len(rec))

    def fsync_if_dirty(self) -> bool:
        """Group-commit fsync: one fsync covering every staged append on
        this stream. Deliberately does NOT fire the fault point — the
        batch-level caller (`ElasticWal.flush`) fires it exactly once so
        one injected EIO poisons the whole batch fail-stop rather than
        partial-acking some streams."""
        if not self._dirty:
            return False
        os.fsync(self._fh.fileno())
        self._dirty = False
        return True

    def _rotate(self) -> None:
        # A dirty (staged, unfsync'd) segment is fsync'd before it is
        # closed — we would otherwise lose the fd we need for the group
        # fsync. Durability is still only ACKED at the next flush():
        # under-claiming is always safe.
        if self._dirty:
            os.fsync(self._fh.fileno())
            self._dirty = False
        self._fh.close()
        self._cur += 1
        self._fh = open(self._path(self._cur), "ab")
        self.metrics.count("wal.rotations")
        obs_events.emit("wal.rotate", segment=self._cur)

    # -- read / compact / truncate ------------------------------------------

    def records(self) -> Iterator[Tuple[int, bytes]]:
        """All (seq, payload) records in segment+offset order (staged
        records included — they are flushed to the OS). The open-time
        repair already removed any tear; a frame going bad AFTER open
        (concurrent corruption) stops iteration at the last valid
        prefix, mirroring the open-time policy."""
        self._fh.flush()
        for idx in sorted(self._seg_max) if self._seg_max else []:
            with open(self._path(idx), "rb") as f:
                while True:
                    hdr = f.read(_HDR.size)
                    if len(hdr) != _HDR.size:
                        break
                    ln, crc = _HDR.unpack(hdr)
                    frame = f.read(ln)
                    if len(frame) != ln or zlib.crc32(frame) != crc:
                        return
                    yield _SEQ.unpack(frame[:_SEQ.size])[0], frame[_SEQ.size:]

    def compact(self, watermark: int) -> int:
        """Remove closed segments whose every record seq <= watermark.
        The ACTIVE segment never goes (truncating the file under the
        append handle is not crash-safe); rotation keeps it bounded.
        Returns the number of segments removed."""
        removed = 0
        for idx in sorted(self._seg_max):
            if idx == self._cur:
                continue
            if self._seg_max[idx] <= watermark:
                os.remove(self._path(idx))
                del self._seg_max[idx]
                removed += 1
        if removed:
            self.metrics.count("wal.segments_compacted", removed)
        return removed

    def truncate_after(self, watermark: int) -> int:
        """Physically remove every record with seq > watermark (async-
        durability recovery: the tail past the durable watermark was
        published-but-never-acked, and leaving it would let a restarted
        incarnation's re-appended seqs interleave with a stale divergent
        timeline). Within a stream seqs ascend, so the cut is a single
        truncate + drop-later-segments. Returns records removed."""
        self._fh.flush()
        removed = 0
        cut_at: Optional[Tuple[int, int]] = None  # (segment idx, offset)
        for idx in self._segments():
            path = self._path(idx)
            off = 0
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(_HDR.size)
                    if len(hdr) != _HDR.size:
                        break
                    ln, crc = _HDR.unpack(hdr)
                    frame = f.read(ln)
                    if len(frame) != ln or zlib.crc32(frame) != crc:
                        break
                    seq = _SEQ.unpack(frame[:_SEQ.size])[0]
                    if seq > watermark:
                        if cut_at is None:
                            cut_at = (idx, off)
                        removed += 1
                    off += _HDR.size + ln
            if cut_at is not None:
                break  # ascending seqs: every later record is past the mark
        if cut_at is None:
            return 0
        cut_idx, cut_off = cut_at
        self._fh.close()
        segs = self._segments()
        os.truncate(self._path(cut_idx), cut_off)
        for later in segs[segs.index(cut_idx) + 1:]:
            # Count the records in segments dropped whole.
            _, _, n = self._scan_segment(self._path(later))
            removed += n if later != cut_idx else 0
            os.remove(self._path(later))
        # Rebuild the index from what survived, then re-open for append.
        self._seg_max = {}
        self.last_seq = -1
        for idx in self._segments():
            _, max_seq, n = self._scan_segment(self._path(idx))
            if n:
                self._seg_max[idx] = max_seq
                self.last_seq = max(self.last_seq, max_seq)
        self._cur = max(self._segments() or [0])
        self._fh = open(self._path(self._cur), "ab")
        os.fsync(self._fh.fileno())
        self._dirty = False
        self.metrics.count("wal.truncated_records", removed)
        obs_events.emit(
            "wal.truncate", dir=self.root, watermark=int(watermark),
            records=removed,
        )
        return removed

    def close(self) -> None:
        if self._fh is not None:
            if self._dirty:
                os.fsync(self._fh.fileno())
                self._dirty = False
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- elastic-worker discipline ---------------------------------------------


class ElasticWal:
    """Checkpoint + delta WAL for one elastic gossip worker.

    Record payload: ``encode_term((step, owned_list)) is framed inside
    the ETF term together with the delta blob`` — concretely
    ``encode_term((step, owned, delta_blob))`` where ``delta_blob`` is
    the same `dumps_dense(f"{name}_delta", delta)` encoding the gossip
    tier ships, so WAL records and wire deltas stay one format.

    With `partitions` set, records are tagged with the partition set
    their delta touches (``encode_term((step, owned, blob, parts))`` —
    a 4-tuple; `core.partition.delta_parts`), so recovery and rejoin
    tooling can reason per partition — and the tag doubles as the
    STREAM ROUTE: the log shards into `nstreams` per-partition segment
    streams (stream 0 = the legacy top-level dir, so untagged/legacy
    logs are just the single-stream case). `recover` merges streams by
    seq and branches on the tuple arity, so un-tagged legacy records
    and tagged records interleave freely (the mixed-version contract).

    Durability modes — see the module docstring. The group/async write
    path stages appends and `flush()` commits the batch: one
    `wal.fsync` fault fire for the WHOLE batch (fail-stop, never a
    partial ack), parallel per-stream fsyncs via a small writer pool,
    then (async) one fsync'd watermark record in the `wm/` mini-log.
    `durable_seq` is the highest seq with every record at or below it
    fsync-acked.
    """

    SNAP = "snap.ckpt"

    def __init__(
        self,
        root: str,
        member: str,
        dense: Any,
        name: str,
        segment_bytes: int = 256 << 10,
        metrics: Optional[Metrics] = None,
        partitions: Optional[int] = None,
        durability: Optional[str] = None,
        streams: Optional[int] = None,
        mesh_plan: Optional[Any] = None,
    ):
        self.dir = os.path.join(root, f"wal-{member}")
        self.member = member
        self.dense = dense
        self.name = name
        self.partitions = partitions
        self.metrics = metrics if metrics is not None else Metrics()
        self.durability = durability_mode(durability)
        # mesh/plan.MeshPlan: stream count follows the key-shard count
        # and routing follows shard ownership, so each stream holds
        # exactly one shard's partitions — a shard's WAL slice is
        # self-contained (the per-shard recombination test_mesh.py pins).
        # Explicit `streams`/env still wins: operators outrank the plan.
        self.mesh_plan = mesh_plan
        env_streams = os.environ.get("CCRDT_WAL_STREAMS", "")
        if streams is None and env_streams:
            try:
                streams = int(env_streams)
            except ValueError:
                streams = None
        if streams is None and mesh_plan is not None:
            streams = mesh_plan.n_key
        if streams is None:
            streams = min(4, partitions) if partitions else 1
        # A reader must open every stream that EXISTS on disk, however
        # it was configured itself — a legacy (single-stream) reopen of
        # a multi-stream log still recovers/truncates all streams; its
        # own new appends simply all route to stream 0.
        disk_streams = 1
        if os.path.isdir(self.dir):
            for f in os.listdir(self.dir):
                if f.startswith(_STREAM_PREFIX):
                    try:
                        disk_streams = max(
                            disk_streams, int(f[len(_STREAM_PREFIX):]) + 1
                        )
                    except ValueError:
                        continue
        self.nstreams = max(1, int(streams), disk_streams)
        # Group-commit bounds: a staged batch is force-flushed once it
        # exceeds either bound, even if no publish boundary arrives.
        self.group_bytes = int(
            os.environ.get("CCRDT_WAL_GROUP_BYTES", str(1 << 20))
        )
        self.group_ms = float(os.environ.get("CCRDT_WAL_GROUP_MS", "100"))
        sync = self.durability == "sync"
        self.streams: List[WriteAheadLog] = []
        for s in range(self.nstreams):
            sroot = (
                self.dir if s == 0
                else os.path.join(self.dir, f"{_STREAM_PREFIX}{s:02d}")
            )
            self.streams.append(
                WriteAheadLog(
                    sroot, segment_bytes=segment_bytes, sync=sync,
                    metrics=self.metrics,
                )
            )
        self.log = self.streams[0]  # legacy alias (tests, tooling)
        # --- durability watermark mini-log (async mode) -----------------
        # The wm log holds fsync'd (watermark, b"") records; its last
        # seq after crash-repair IS the durable watermark. Open-time
        # discipline: an existing wm truncates every data stream past
        # its watermark (regardless of the CURRENT mode — the stale
        # tail was never acked no matter how we reopen); then a
        # non-async reopen deletes the wm dir so a stale watermark can
        # never truncate records a later sync/group run made durable.
        self._wm: Optional[WriteAheadLog] = None
        wm_dir = os.path.join(self.dir, _WM_DIR)
        had_wm = os.path.isdir(wm_dir)
        if had_wm:
            wm_scan = WriteAheadLog(
                wm_dir, segment_bytes=4 << 10, sync=True,
                metrics=self.metrics, fault_point=None,
            )
            watermark = wm_scan.last_seq
            wm_scan.close()
            truncated = 0
            for st in self.streams:
                truncated += st.truncate_after(watermark)
            if truncated:
                self.metrics.set("wal.recover_truncated", truncated)
        if self.durability == "async":
            self._wm = WriteAheadLog(
                wm_dir, segment_bytes=4 << 10, sync=True,
                metrics=self.metrics, fault_point=None,
            )
            # Fresh wm over pre-existing data (a sync/group log reopened
            # as async): everything on disk now was durable at open
            # (repair already pruned tears), so seed the watermark —
            # otherwise a crash before the first flush would truncate
            # records an earlier run legitimately made durable.
            last = self._last_on_disk()
            if last >= 0 and self._wm.last_seq < last:
                self._wm.append(last, b"", sync=True)
        elif had_wm:
            shutil.rmtree(wm_dir, ignore_errors=True)
        # --- group-commit state -----------------------------------------
        self._pending: Set[int] = set()   # staged seqs awaiting fsync ack
        self._staged_bytes = 0
        self._last_flush = time.monotonic()
        self._first_staged = self._last_flush  # opens with the group
        self._last_appended = self._last_on_disk()
        self._pool = None  # lazy writer pool for parallel stream fsyncs
        self._publish_gauges()

    # -- bookkeeping --------------------------------------------------------

    def _last_on_disk(self) -> int:
        return max((st.last_seq for st in self.streams), default=-1)

    @property
    def durable_seq(self) -> int:
        """Highest seq S such that every record <= S is fsync-acked."""
        if not self._pending:
            return self._last_appended
        return min(self._pending) - 1

    def _publish_gauges(self) -> None:
        d = self.durable_seq
        self.metrics.set("wal.durable_seq", float(d))
        self.metrics.set(
            "wal.durability_lag", float(max(0, self._last_appended - d))
        )

    def stream_for_part(self, part: int) -> int:
        """Partition -> stream index. With a mesh plan this is shard
        ownership (`MeshPlan.shard_of`, clamped to the streams that
        exist); without one it is the same `% nstreams` fold — identical
        routes when nstreams == n_key, by construction of `shard_of`."""
        if self.mesh_plan is not None:
            return self.mesh_plan.shard_of(int(part)) % self.nstreams
        return int(part) % self.nstreams

    def _stream_for(self, parts) -> WriteAheadLog:
        """Partition tag -> stream route. Untagged / unknown-partition
        records go to stream 0 (the legacy layout)."""
        if self.nstreams <= 1 or not parts:
            return self.streams[0]
        return self.streams[self.stream_for_part(min(int(p) for p in parts))]

    # -- write path --------------------------------------------------------

    def log_step(
        self,
        step: int,
        owned,
        prev_view: Any,
        view: Any,
        delta: Any = None,
        blob: Optional[bytes] = None,
    ) -> int:
        """Append this step's join-decomposed delta (prev_view -> view)
        plus its ownership record. sync/group modes: MUST run before the
        step's publish (write-ahead: the record precedes any externally
        visible effect; in group mode the BOUNDARY flush completes it).
        async mode: the publish may overtake the fsync — the durability
        watermark and the certifier account for exactly that window.

        `delta`/`blob` let a caller that already cut this step's delta
        for gossip (DeltaPublisher.encode_delta) hand it over instead of
        paying a second extraction. Returns the appended payload size."""
        if obs_spans.ACTIVE:
            # The whole write-ahead cost — delta extraction (when not
            # reused from the publisher), encode, CRC framing, staging
            # or fsync — is one serial round phase.
            with obs_spans.span("round.wal_append", step=int(step)):
                return self._log_step(step, owned, prev_view, view, delta, blob)
        return self._log_step(step, owned, prev_view, view, delta, blob)

    def _log_step(
        self, step, owned, prev_view, view, delta, blob
    ) -> int:
        from ..parallel.delta import make_delta

        if delta is None:
            delta = make_delta(self.dense, prev_view, view)
            blob = None
        if blob is None:
            blob = serial.dumps_dense(f"{self.name}_delta", delta)
        payload, parts = self._encode_record(step, owned, view, delta, blob)
        stream = self._stream_for(parts)
        if self.durability == "sync":
            stream.append(step, payload, sync=True)
            self._last_appended = max(self._last_appended, int(step))
        else:
            stream.append(step, payload, sync=False)
            self._last_appended = max(self._last_appended, int(step))
            if not self._pending:
                # The undurable window opens when the FIRST record of a
                # group is staged, not at the previous flush: measuring
                # from _last_flush made any quiet period >= group_ms
                # flush the next append solo, so multi-append boundaries
                # could never form a group.
                self._first_staged = time.monotonic()
            self._pending.add(int(step))
            self._staged_bytes += len(payload)
            # Byte/time backstop: a run with sparse publish boundaries
            # still bounds its undurable window.
            if (
                self._staged_bytes >= self.group_bytes
                or (time.monotonic() - self._first_staged) * 1e3
                >= self.group_ms
            ):
                self.flush()
        self._publish_gauges()
        return len(payload)

    def _encode_record(
        self, step: int, owned, view: Any, delta: Any, blob: bytes
    ) -> Tuple[bytes, Tuple[int, ...]]:
        """Legacy 3-tuple record, or the partition-tagged 4-tuple when
        this WAL runs with a partition count. Also returns the tag (the
        stream route)."""
        base = (int(step), [int(r) for r in owned], blob)
        if not self.partitions:
            return serial.encode_term(base), ()
        from ..core import partition as pt

        try:
            parts = tuple(sorted(
                pt.delta_parts(self.dense, view, delta, self.partitions)
            ))
        except Exception:  # noqa: BLE001 — a tag failure must not block
            parts = ()     # durability; empty tag = "unknown partitions"
        return serial.encode_term(base + (list(parts),)), parts

    def flush(self) -> int:
        """Group commit: fsync every dirty stream (in parallel when
        several are dirty), then — async mode — fsync the advanced
        watermark into the wm mini-log. ONE `wal.fsync` fault fire
        covers the whole batch: an injected EIO poisons the entire
        group fail-stop BEFORE any stream fsyncs, so no subset of the
        batch is ever acked (the staged records stay pending and a
        retry re-commits them). Returns the group size acked."""
        if not self._pending:
            return 0
        if obs_spans.ACTIVE:
            # The group fsync is write-ahead cost too — bill it to the
            # same phase as the staged appends it commits.
            with obs_spans.span(
                "round.wal_append", via="flush", n=len(self._pending)
            ):
                return self._flush()
        return self._flush()

    def _flush(self) -> int:
        if faults.ACTIVE:
            # Raise => durable_seq does NOT advance, pending is kept.
            faults.fire("wal.fsync")
        dirty = [st for st in self.streams if st._dirty]
        if len(dirty) > 1:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=min(4, self.nstreams),
                    thread_name_prefix="wal-writer",
                )
            # Writer pool: independent partitions' fsyncs overlap in the
            # kernel instead of serializing behind one fd. Any failure
            # surfaces here and durable_seq does not advance.
            list(self._pool.map(WriteAheadLog.fsync_if_dirty, dirty))
        elif dirty:
            dirty[0].fsync_if_dirty()
        group = len(self._pending)
        self._pending.clear()
        self._staged_bytes = 0
        self._last_flush = time.monotonic()
        if self._wm is not None and self._last_appended >= 0:
            # The watermark record is itself fsync'd: after a crash its
            # last seq is exactly what recovery may trust.
            self._wm.append(self._last_appended, b"", sync=True)
        self.metrics.count("wal.flushes")
        self.metrics.observe("wal.group_size", group)
        self._publish_gauges()
        obs_events.emit(
            "wal.durable", through=int(self.durable_seq), group=group
        )
        return group

    def checkpoint(self, view: Any, step: int) -> None:
        """Anchor: durable full state at `step`, then compact every
        closed segment fully covered by it — PER STREAM, so a stream
        whose every record is covered compacts independently of its
        busier siblings. Call only for state already PUBLISHED at this
        step — the watermark must never pass what gossip has seen."""
        self.flush()  # compaction must never outrun durability acks
        save_dense_checkpoint(
            os.path.join(self.dir, self.SNAP), self.name, view, step=step
        )
        for st in self.streams:
            st.compact(step)
        if self._wm is not None:
            self._wm.compact(step)
        self.metrics.count("wal.checkpoints")
        obs_events.emit("wal.checkpoint", step=step)

    # -- recovery ----------------------------------------------------------

    def recover(self, like_view: Any) -> Tuple[Optional[Any], int, Set[int]]:
        """-> (recovered_view_or_None, last_step, owned_union).

        recovered_view = checkpoint ⊔ WAL-delta suffix; the suffix is
        the per-partition streams MERGED BY SEQ (global seq order, so
        the delta-chaining argument holds exactly as in the
        single-stream case); last_step is the highest durable step
        (-1 = nothing recovered); owned_union is every replica id the
        lost incarnation logged ownership of. In async mode the open
        already truncated every stream to the wm watermark, so what we
        replay here is precisely the certified-durable prefix.

        Pager spill blobs (core/pager.py) under this WAL dir are
        discarded first: a spill file is a residency cache of state that
        is durable here, and the dead incarnation may have been killed
        mid-spill — recovery rebuilds all-resident from checkpoint+WAL
        and must never resurrect a possibly-torn blob."""
        from ..core import pager as pg
        from ..parallel.delta import apply_any_delta, like_delta_for

        dropped = pg.discard_spill(self.dir)
        if dropped:
            self.metrics.count("pager.spills_discarded", dropped)

        state: Optional[Any] = None
        last_step = -1
        snap_path = os.path.join(self.dir, self.SNAP)
        if os.path.exists(snap_path):
            try:
                step, _name, state = load_dense_checkpoint(
                    snap_path, like_view, dense=self.dense
                )
                last_step = max(last_step, int(step))
                self.metrics.count("wal.recovered_snapshot")
            except Exception:  # noqa: BLE001 — a torn/foreign checkpoint
                state = None   # must not block WAL replay (total recovery)
        like_delta = like_delta_for(self.dense, like_view)
        owned: Set[int] = set()
        parts_touched: Set[int] = set()
        n = 0
        merged: List[Tuple[int, bytes]] = []
        for st in self.streams:
            merged.extend(st.records())
        merged.sort(key=lambda sp: sp[0])
        for seq, payload in merged:
            try:
                rec = serial.decode_term(payload)
                # Arity is the version marker: legacy records are
                # (step, owned, blob); partition-tagged ones append the
                # partition list. Both replay identically — the tag is
                # metadata, the delta blob is the state.
                if len(rec) == 4:
                    step, rec_owned, blob, rec_parts = rec
                else:
                    step, rec_owned, blob = rec
                    rec_parts = ()
                _name, delta = serial.loads_dense(blob, like_delta)
                base = like_view if state is None else state
                state = apply_any_delta(self.dense, base, delta)
            except Exception:  # noqa: BLE001 — skip undecodable record,
                continue       # the join tolerates gaps (next snapshot wins)
            owned.update(int(r) for r in rec_owned)
            parts_touched.update(int(p) for p in rec_parts)
            last_step = max(last_step, int(step))
            n += 1
        if n:
            self.metrics.count("wal.recovered_records", n)
        obs_events.emit(
            "wal.recover",
            records=n,
            last_step=last_step,
            owned=sorted(owned),
            parts=sorted(parts_touched),
            had_checkpoint=os.path.exists(snap_path),
            durable_through=int(self.durable_seq),
            mode=self.durability,
        )
        return state, last_step, owned

    def close(self) -> None:
        if self._pending:
            self.flush()
        for st in self.streams:
            st.close()
        if self._wm is not None:
            self._wm.close()
            self._wm = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
