"""Python binding for the native (C++) corpus tokenizer / data-loader.

``native/ccrdt_tokenizer.cpp`` implements the wordcount ingest hot loop —
tokenize on '\\n'/' ' keeping empties (wordcount.erl:76-85 parity),
per-document dedup (worddocumentcount.erl:76-86), FNV-1a hashed or exact
grow-on-demand vocabulary encoding — over whole corpus chunks in one C
call. Same build-on-demand + ctypes pattern as `native_host`; falls back
cleanly when the toolchain is unavailable (`available()` is False and the
pure-Python `VocabEncoder` / `hash_token` path in models/wordcount.py
remains the ingest).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Source checkout puts native/ two levels up; installed wheels don't ship
# it, so CCRDT_NATIVE_DIR lets an installed package point at a built tree.
_NATIVE_DIR = os.environ.get(
    "CCRDT_NATIVE_DIR",
    os.path.join(os.path.dirname(__file__), "..", "..", "native"),
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libccrdt_tokenizer.so")

_lib = None
_build_error: Optional[str] = None


def _ensure_lib():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        # Run make whenever the source tree is present — it is a no-op
        # when the .so is current, and it rebuilds a STALE one (a cached
        # build from before a symbol was added would otherwise load and
        # crash the bindings below). A prebuilt .so without sources
        # (CCRDT_NATIVE_DIR at an installed tree) skips the build.
        if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=not os.path.exists(_LIB_PATH),
                capture_output=True,
                text=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        # Belt and braces: an old library that survived the rebuild (or a
        # prebuilt one) must fail CLEANLY into the pure-Python fallback,
        # not AttributeError out of available().
        for sym in (
            "ccrdt_tok_new", "ccrdt_tok_free", "ccrdt_tok_encode",
            "ccrdt_tok_encode_batch", "ccrdt_tok_encode_batch_mt",
            "ccrdt_tok_vocab_size", "ccrdt_tok_vocab_dump",
        ):
            if not hasattr(lib, sym):
                raise OSError(
                    f"{_LIB_PATH} is stale: missing {sym} (make clean "
                    "&& make in native/)"
                )
    except (subprocess.CalledProcessError, OSError) as e:
        _build_error = str(e)
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ccrdt_tok_new.restype = ctypes.c_void_p
    lib.ccrdt_tok_new.argtypes = [ctypes.c_int32]
    lib.ccrdt_tok_free.argtypes = [ctypes.c_void_p]
    lib.ccrdt_tok_encode.restype = ctypes.c_int64
    lib.ccrdt_tok_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int, i32p, ctypes.c_int64,
    ]
    lib.ccrdt_tok_encode_batch.restype = ctypes.c_int64
    lib.ccrdt_tok_encode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64p, ctypes.c_int,
        ctypes.c_int, i32p, ctypes.c_int64, i64p,
    ]
    lib.ccrdt_tok_encode_batch_mt.restype = ctypes.c_int64
    lib.ccrdt_tok_encode_batch_mt.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64p, ctypes.c_int,
        ctypes.c_int, i32p, ctypes.c_int64, i64p, ctypes.c_int,
    ]
    lib.ccrdt_tok_vocab_size.restype = ctypes.c_int64
    lib.ccrdt_tok_vocab_size.argtypes = [ctypes.c_void_p]
    lib.ccrdt_tok_vocab_dump.restype = ctypes.c_int64
    lib.ccrdt_tok_vocab_dump.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _ensure_lib() is not None


def build_error() -> Optional[str]:
    _ensure_lib()
    return _build_error


class NativeTokenizer:
    """Corpus tokenizer over the C++ library.

    n_buckets > 0: hashed vocabulary (FNV-1a % n_buckets, byte-identical to
    models/wordcount.py:hash_token). n_buckets == 0: exact vocabulary grown
    on demand, ids dense in first-appearance order (VocabEncoder parity up
    to per-document ordering: the native encoder emits deduped tokens in
    first-appearance rather than sorted order — counts are unaffected).
    """

    def __init__(self, n_buckets: int = 0):
        lib = _ensure_lib()
        if lib is None:
            raise RuntimeError(f"native tokenizer unavailable: {_build_error}")
        self._lib = lib
        self._h = lib.ccrdt_tok_new(n_buckets)
        self.n_buckets = n_buckets

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ccrdt_tok_free(h)
            self._h = None

    def encode_batch(
        self,
        docs: Sequence[str],
        per_document: bool = False,
        threads: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Tokenize+encode a document batch in one C call.

        `threads`: 0 = hardware thread count, 1 = serial, N = pool of N.
        Documents are independent, so the pool splits the batch by byte
        ranges; exact-mode vocabulary ids stay bit-identical to the serial
        encode (thread-local vocabs folded in document order — see the
        .cpp header). The C call releases the GIL either way.

        Returns (token_ids i32[N], doc_end i64[n_docs]) where document i's
        tokens span token_ids[doc_end[i-1]:doc_end[i]].
        """
        if not docs:
            return np.zeros(0, np.int32), np.zeros(0, np.int64)
        blobs = [d.encode("utf-8") for d in docs]
        offsets = np.zeros(len(blobs) + 1, np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        buf = b"".join(blobs)
        # Worst case one token per byte plus one trailing empty per doc.
        cap = len(buf) + len(blobs)
        out = np.empty(cap, np.int32)
        doc_end = np.empty(len(blobs), np.int64)
        n = self._lib.ccrdt_tok_encode_batch_mt(
            self._h,
            buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(blobs),
            1 if per_document else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap,
            doc_end.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            threads,
        )
        assert n <= cap, (n, cap)  # cap is a proven upper bound
        return out[:n].copy(), doc_end

    def vocab_size(self) -> int:
        return int(self._lib.ccrdt_tok_vocab_size(self._h))

    def vocab(self) -> List[str]:
        """Exact-mode id-ordered token list (hashed mode has no vocab)."""
        if self.n_buckets > 0:
            raise ValueError("hashed tokenizer has no materialized vocab")
        if self.vocab_size() == 0:
            return []
        need = self._lib.ccrdt_tok_vocab_dump(self._h, None, 0)
        buf = ctypes.create_string_buffer(int(need))
        self._lib.ccrdt_tok_vocab_dump(self._h, buf, need)
        return buf.raw[:need].decode("utf-8").split("\n")


def wordcount_ops_from_docs(
    docs_per_replica: Sequence[Sequence[str]],
    n_buckets: int,
    per_document: bool = False,
    key: int = 0,
):
    """Data-loader: corpus -> dense `WordcountOps` (one padded token batch
    per replica) through the native tokenizer. The standing replacement for
    per-document Python encoding on the streaming-corpus benchmark config
    (BASELINE.md: wordcount, 64 replicas, ragged vocab)."""
    import jax.numpy as jnp

    from ..models.wordcount import WordcountOps

    tok = NativeTokenizer(n_buckets)
    encoded = [
        tok.encode_batch(docs, per_document=per_document, threads=0)[0]
        for docs in docs_per_replica
    ]
    B = max((len(e) for e in encoded), default=0)
    R = len(encoded)
    tokens = np.full((R, B), -1, np.int32)  # -1 = padding
    for r, e in enumerate(encoded):
        tokens[r, : len(e)] = e
    return WordcountOps(
        key=jnp.full((R, B), key, jnp.int32),
        token=jnp.asarray(tokens),
    )


def fnv1a_buckets(words: Sequence[str], n_buckets: int) -> np.ndarray:
    """Vectorized FNV-1a % n_buckets over a word list, byte-identical to
    `models.wordcount.hash_token`. Cost is O(|vocab| * max_len) numpy ops
    — applied to the *vocabulary*, not the corpus, it is negligible."""
    if not words:
        return np.zeros(0, np.int32)
    blobs = [w.encode("utf-8") for w in words]
    L = max((len(b) for b in blobs), default=0)
    mat = np.zeros((len(blobs), L), np.uint32)
    lens = np.asarray([len(b) for b in blobs])
    for i, b in enumerate(blobs):
        mat[i, : len(b)] = np.frombuffer(b, np.uint8)
    h = np.full(len(blobs), 2166136261, np.uint32)
    for j in range(L):
        live = j < lens
        hj = ((h ^ mat[:, j]) * np.uint32(16777619)) & np.uint32(0xFFFFFFFF)
        h = np.where(live, hj, h)
    return (h % np.uint32(n_buckets)).astype(np.int32)


def _worddoc_encode(docs_per_replica, n_buckets):
    """Shared encode core of the raw and compact worddoc array builders:
    EXACT-mode tokenize (no host dedup — the tokenizer only splits and
    ids, cheap on this 1-CPU host) + one vectorized FNV pass over the
    vocabulary. Returns ([(token_ids, per_doc_lengths)] per replica,
    bucket_of)."""
    tok = NativeTokenizer(0)  # exact mode
    encoded = []
    for docs in docs_per_replica:
        toks, doc_end = tok.encode_batch(docs, per_document=False, threads=0)
        lengths = np.diff(np.concatenate([[0], doc_end]))
        encoded.append((toks, lengths))
    return encoded, fnv1a_buckets(tok.vocab(), n_buckets)


def worddoc_arrays_from_docs(
    docs_per_replica: Sequence[Sequence[str]],
    n_buckets: int,
    key: int = 0,
):
    """Numpy core of `worddoc_ops_from_docs` (the benchmark times the host
    phase separately, so it needs the arrays before any device upload).

    Encodes in EXACT mode (see `_worddoc_encode`): the exact id is the
    dedup identity `uniq`, so the device dedup is string-level exactly
    like the scalar reference (two distinct words that hash-collide still
    count twice in their shared bucket). Returns dict of [R, B] i32
    arrays (key/doc/uniq/token); token -1 marks padding."""
    enc, bucket_of = _worddoc_encode(docs_per_replica, n_buckets)
    encoded = [
        (toks, np.repeat(np.arange(len(lens)), lens)) for toks, lens in enc
    ]
    B = max((len(t) for t, _ in encoded), default=0)
    R = len(encoded)
    uniq = np.full((R, B), -1, np.int32)
    tokens = np.full((R, B), -1, np.int32)  # -1 = padding
    doc_ids = np.zeros((R, B), np.int32)
    for r, (t, d) in enumerate(encoded):
        uniq[r, : len(t)] = t
        tokens[r, : len(t)] = bucket_of[t]
        doc_ids[r, : len(d)] = d
    return {
        "key": np.full((R, B), key, np.int32),
        "doc": doc_ids,
        "uniq": uniq,
        "token": tokens,
    }


def worddoc_compact_arrays_from_docs(
    docs_per_replica: Sequence[Sequence[str]],
    n_buckets: int,
    key: int = 0,
):
    """COMPACT ingest wire for `WordcountDense.apply_doc_ops_compact`
    (VERDICT-r3 item 6): of `worddoc_arrays_from_docs`'s three [R, B]
    planes, `doc` is the run-length expansion of per-document lengths and
    `token` is bucket_of[uniq] — both recomputable device-side. Ships
    only what carries information:

    * uniq      [R, B]    exact-vocab id stream (0-padded; live via counts)
    * doc_lens  [R, DOCS] tokens per document (0-padded)
    * counts    [R]       live tokens per replica
    * bucket_table [Vexact] exact id -> hashed bucket (resident upload,
      once per corpus — ~2 bytes per vocabulary WORD, not per token)

    All values fit u16 whenever the raw wire's `fits` check passes plus
    doc lengths < 65536 (the caller packs; this returns i32)."""
    encoded, bucket_of = _worddoc_encode(docs_per_replica, n_buckets)
    R = len(encoded)
    B = max((len(t) for t, _ in encoded), default=0)
    DOCS = max((len(ln) for _, ln in encoded), default=0)
    uniq = np.zeros((R, B), np.int32)
    doc_lens = np.zeros((R, DOCS), np.int32)
    counts = np.zeros((R,), np.int32)
    for r, (t, ln) in enumerate(encoded):
        uniq[r, : len(t)] = t
        doc_lens[r, : len(ln)] = ln
        counts[r] = len(t)
    return {
        "uniq": uniq,
        "doc_lens": doc_lens,
        "counts": counts,
        "bucket_table": bucket_of.astype(np.int32),
        "key": np.int32(key),  # scalar NK row, like the raw key plane
    }


def worddoc_ops_from_docs(
    docs_per_replica: Sequence[Sequence[str]],
    n_buckets: int,
    key: int = 0,
):
    """Data-loader for `WordcountDense.apply_doc_ops`: raw per-token
    records with NO host-side dedup; the per-document dedup of
    worddocumentcount (worddocumentcount.erl:76-86) happens on device as
    one sort over the batch, on string identity (see
    `worddoc_arrays_from_docs`)."""
    import jax.numpy as jnp

    from ..models.wordcount import WordDocOps

    arrs = worddoc_arrays_from_docs(docs_per_replica, n_buckets, key=key)
    return WordDocOps(**{k: jnp.asarray(v) for k, v in arrs.items()})
