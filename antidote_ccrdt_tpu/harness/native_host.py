"""Python binding for the native (C++) host runtime.

``native/ccrdt_host.cpp`` implements the op-log store, causal delivery
scheduler, and dense batch builder — the host services Antidote provides to
the reference library (SURVEY.md §1) — as a shared library. This module
builds it on demand (``make`` in ``native/``), binds it via ctypes (no
pybind11 in this image), and adapts drained batches to the dense op structs
the TPU kernels consume.

The boundary is batched in both directions: ``submit_batch`` hands N ops to
C++ in one call; ``drain`` returns a struct-of-arrays batch ready to wrap as
``TopkRmvOps``. Python never loops over individual ops on the hot path.

If the toolchain is unavailable the import still succeeds; ``available()``
reports False and the pure-Python ``ScalarReplay`` pipeline remains the
fallback host.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

# Source checkout puts native/ two levels up; installed wheels don't ship
# it, so CCRDT_NATIVE_DIR lets an installed package point at a built tree.
_NATIVE_DIR = os.environ.get(
    "CCRDT_NATIVE_DIR",
    os.path.join(os.path.dirname(__file__), "..", "..", "native"),
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libccrdt_host.so")

# Op kinds shared with ops/compaction.py (KIND_* there) and, by convention,
# reinterpreted per type: for average, score=value aux=n; for wordcount,
# id=token score=count.
KIND_ADD = 0
KIND_ADD_R = 1
KIND_RMV = 2
KIND_RMV_R = 3

_lib = None
_build_error: Optional[str] = None


def _ensure_lib():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                text=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
    except (subprocess.CalledProcessError, OSError) as e:
        _build_error = str(e)
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ccrdt_host_new.restype = ctypes.c_void_p
    lib.ccrdt_host_new.argtypes = [ctypes.c_int]
    lib.ccrdt_host_free.argtypes = [ctypes.c_void_p]
    lib.ccrdt_host_submit.restype = ctypes.c_int32
    lib.ccrdt_host_submit.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p,
    ]
    lib.ccrdt_host_submit_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        i32p, i32p, i32p, i32p, i32p, i32p, i32p,
    ]
    lib.ccrdt_host_drain.restype = ctypes.c_int
    lib.ccrdt_host_drain.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        i32p, i32p, i32p, i32p, i32p, i32p, i32p, i32p,
    ]
    lib.ccrdt_host_backlog.restype = ctypes.c_int64
    lib.ccrdt_host_backlog.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ccrdt_host_stats.argtypes = [ctypes.c_void_p, i64p]
    _lib = lib
    return _lib


def available() -> bool:
    """True iff the native library built (or was already built)."""
    return _ensure_lib() is not None


def build_error() -> Optional[str]:
    _ensure_lib()
    return _build_error


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeHost:
    """One multi-master host: D replicas, each a DC.

    Ops are effect ops (already through ``downstream``); the host stamps
    adds with the origin's lamport time, tracks causal dependencies, and
    delivers per replica in causal order, exactly once.
    """

    def __init__(self, n_dcs: int):
        lib = _ensure_lib()
        if lib is None:
            raise RuntimeError(f"native host unavailable: {_build_error}")
        self._lib = lib
        self.D = n_dcs
        self._h = lib.ccrdt_host_new(n_dcs)
        if not self._h:
            raise RuntimeError("ccrdt_host_new failed")
        # Delivered-but-not-yet-batched ops per replica (SoA dicts): the
        # drain is exactly-once, so overflow from a batch split must be
        # carried, never dropped or re-requested (see drain_topk_rmv_ops).
        self._carry: dict = {}

    def close(self) -> None:
        if self._h:
            self._lib.ccrdt_host_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- submit ------------------------------------------------------------

    def submit(self, origin: int, kind: int, key: int, id_: int,
               score: int = 0, aux: int = 0,
               vc: Optional[np.ndarray] = None) -> int:
        """Submit one effect op at `origin`; returns the lamport stamp."""
        vcp = None
        if vc is not None:
            vc = np.ascontiguousarray(vc, dtype=np.int32)
            assert vc.shape == (self.D,)
            vcp = _i32(vc)
        return self._lib.ccrdt_host_submit(
            self._h, origin, kind, key, id_, score, aux, vcp
        )

    def submit_batch(self, origin: int, kinds, keys, ids, scores=None,
                     auxs=None, vcs=None) -> np.ndarray:
        """Submit N ops in one native call; returns their lamport stamps."""
        kinds = np.ascontiguousarray(kinds, dtype=np.int32)
        n = kinds.shape[0]

        def arr(x):
            if x is None:
                return np.zeros(n, np.int32)
            return np.ascontiguousarray(x, dtype=np.int32)

        keys, ids, scores, auxs = arr(keys), arr(ids), arr(scores), arr(auxs)
        vcp = None
        if vcs is not None:
            vcs = np.ascontiguousarray(vcs, dtype=np.int32)
            assert vcs.shape == (n, self.D)
            vcp = _i32(vcs)
        out_ts = np.zeros(n, np.int32)
        self._lib.ccrdt_host_submit_batch(
            self._h, origin, n, _i32(kinds), _i32(keys), _i32(ids),
            _i32(scores), _i32(auxs), vcp, _i32(out_ts),
        )
        return out_ts

    # -- drain -------------------------------------------------------------

    def drain(self, replica: int, max_n: int):
        """Deliver up to max_n causally-ready ops for `replica`.

        Returns a dict of SoA numpy arrays sliced to the delivered count:
        kind, key, id, score, aux, dc, ts ([n]) and vc ([n, D]).
        """
        bufs = {name: np.zeros(max_n, np.int32)
                for name in ("kind", "key", "id", "score", "aux", "dc", "ts")}
        vc = np.zeros((max_n, self.D), np.int32)
        n = self._lib.ccrdt_host_drain(
            self._h, replica, max_n,
            _i32(bufs["kind"]), _i32(bufs["key"]), _i32(bufs["id"]),
            _i32(bufs["score"]), _i32(bufs["aux"]), _i32(bufs["dc"]),
            _i32(bufs["ts"]), _i32(vc),
        )
        out = {k: v[:n] for k, v in bufs.items()}
        out["vc"] = vc[:n]
        return out

    def drain_topk_rmv_ops(self, replica: int, batch_adds: int,
                           batch_rmvs: int) -> Tuple[object, int, int]:
        """Drain into a padded single-replica ``TopkRmvOps`` batch (leading
        replica axis of 1 — vmap-ready). Returns (ops, n_adds, n_rmvs).

        Delivers at most batch_adds adds and batch_rmvs rmvs per call
        (backpressure; the rest arrives next call). The drain itself is
        exactly-once, so when the drained window's add/rmv split overflows
        one side, the excess is CARRIED to the next call — never dropped.
        Both the adds/rmvs split and the carry delay are safe because the
        dense kernel applies batches as a lattice join: tombstone
        domination (``ts > vc[dc]``) is order-independent, so delivering a
        removal before a causally-prior add converges identically.
        """
        import jax.numpy as jnp

        from ..models.topk_rmv_dense import TopkRmvOps

        carry = self._carry.pop(replica, None)
        room = batch_adds + batch_rmvs - (len(carry["kind"]) if carry else 0)
        got = self.drain(replica, max(room, 0))
        if carry is not None:
            got = {
                k: np.concatenate([carry[k], got[k]], axis=0) for k in got
            }
        is_add = got["kind"] <= KIND_ADD_R
        a_idx = np.flatnonzero(is_add)
        r_idx = np.flatnonzero(~is_add)
        # A kind with zero capacity can never leave the carry — the
        # caller's drain loop would livelock on a stuck backlog. Park the
        # WHOLE window back in the carry (exactly-once: nothing may be
        # lost) and fail loudly so the caller retries with usable sizes.
        if (batch_adds == 0 and a_idx.size) or (batch_rmvs == 0 and r_idx.size):
            self._carry[replica] = {k: v.copy() for k, v in got.items()}
            raise ValueError(
                "zero-capacity batch side for ops present in the stream "
                f"(batch_adds={batch_adds}, batch_rmvs={batch_rmvs}); "
                "carried ops retained — retry with nonzero capacities"
            )
        over = np.concatenate([a_idx[batch_adds:], r_idx[batch_rmvs:]])
        if len(over):
            over.sort()  # keep the carried ops in delivery order
            self._carry[replica] = {k: got[k][over].copy() for k in got}
            keep = np.ones(len(is_add), bool)
            keep[over] = False
            got = {k: got[k][keep] for k in got}
            is_add = got["kind"] <= KIND_ADD_R
        adds = {k: got[k][is_add] for k in ("key", "id", "score", "dc", "ts")}
        rmvs = {k: got[k][~is_add] for k in ("key", "id")}
        rmv_vc = got["vc"][~is_add]
        na, nr = int(is_add.sum()), int((~is_add).sum())

        def pad(a, n, fill):
            out = np.full(n, fill, np.int32)
            out[: len(a)] = a
            return out[None]  # [1, n]

        ops = TopkRmvOps(
            add_key=jnp.asarray(pad(adds["key"], batch_adds, 0)),
            add_id=jnp.asarray(pad(adds["id"], batch_adds, 0)),
            add_score=jnp.asarray(pad(adds["score"], batch_adds, 0)),
            add_dc=jnp.asarray(pad(adds["dc"], batch_adds, 0)),
            add_ts=jnp.asarray(pad(adds["ts"], batch_adds, 0)),  # 0 pad = invalid
            rmv_key=jnp.asarray(pad(rmvs["key"], batch_rmvs, 0)),
            rmv_id=jnp.asarray(pad(rmvs["id"], batch_rmvs, -1)),  # -1 pad
            rmv_vc=jnp.asarray(
                np.concatenate(
                    [rmv_vc, np.zeros((batch_rmvs - nr, self.D), np.int32)], axis=0
                )[None]
            ),
        )
        return ops, na, nr

    # -- introspection -----------------------------------------------------

    def backlog(self, replica: int) -> int:
        """Undelivered-to-batch ops: native causal backlog plus any ops
        carried over from a previous drain's batch-split overflow."""
        carry = self._carry.get(replica)
        return int(self._lib.ccrdt_host_backlog(self._h, replica)) + (
            len(carry["kind"]) if carry else 0
        )

    def stats(self):
        out = np.zeros(3, np.int64)
        self._lib.ccrdt_host_stats(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        )
        return {"submitted": int(out[0]), "delivered": int(out[1]),
                "pending": int(out[2])}
