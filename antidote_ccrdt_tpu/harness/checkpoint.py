"""Checkpoint / resume: write-ahead op journal + state snapshots.

The reference's only persistence is whole-state ``to_binary/1`` with no
journal and no versioning (SURVEY.md §5): a crashed materializer loses
every op since the last snapshot. Here the harness gets the full recipe a
host database would use:

* `Journal` — a write-ahead log of (origin, prepare_op) records, file-backed
  or in-memory, length-prefix framed. Prepare ops (not effects) are
  journaled because replay re-derives effects deterministically: replica
  clocks are `LogicalClock`s whose counters the snapshot captures, so
  re-running `downstream` after restore stamps identical (dc, ts) pairs.

* `CheckpointingReplay` — a `ScalarReplay` that journals every submission
  and can `snapshot()` to versioned bytes (per-replica state blobs via the
  type's own ``to_binary`` + clock counters + journal position + pending
  effect queue).

* `resume` — restore the snapshot and replay the journal suffix; the result
  is bit-identical to a run that never stopped (tested both mid-epoch and
  at sync boundaries).

Dense states checkpoint through `core.serial.dumps_dense` (npz + treedef
manifest) — see `save_dense_checkpoint` / `load_dense_checkpoint`.

The partitioned variants below (`save_partitioned_checkpoint`,
`RejoinStreamer`) make the PARTITION the unit of durability: one shard
file per partition plus a manifest commit marker, and rejoin streams
divergent partitions in lag order. This is deliberately the same axis
`harness/wal.py` shards its per-partition segment streams on (PR 11):
a partition's whole durable footprint — its checkpoint shard and its
WAL stream — can be recovered, compacted, or streamed to a rejoining
worker without touching its siblings.
"""

from __future__ import annotations

import io
import os
import struct
from typing import Any, BinaryIO, Iterator, List, Optional, Tuple

from ..core import serial
from ..core.behaviour import ScalarCCRDT
from ..core.clock import LogicalClock, ReplicaContext
from ..utils import faults
from .replay import ScalarReplay

SNAP_MAGIC = b"CCKP"
SNAP_VERSION = 1


class Journal:
    """Append-only write-ahead log of (origin, prepare_op) records."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mem: List[bytes] = []
        self._fh: Optional[BinaryIO] = None
        if path is not None:
            self._fh = open(path, "ab")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def append(self, origin: int, op: Any) -> None:
        rec = serial.encode_term((origin, op))
        frame = struct.pack("<I", len(rec)) + rec
        if self.path is not None:
            if self._fh is None:
                raise ValueError("journal is closed")
            self._fh.write(frame)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        else:
            self._mem.append(rec)

    def entries(self, start: int = 0) -> Iterator[Tuple[int, Any]]:
        """Yield (origin, prepare_op) from record index `start` on."""
        if self.path is None:
            for rec in self._mem[start:]:
                yield serial.decode_term(rec)
            return
        if self._fh is not None:
            self._fh.flush()
        with open(self.path, "rb") as f:
            i = 0
            while True:
                hdr = f.read(4)
                if not hdr:
                    return
                if len(hdr) != 4:
                    raise ValueError("truncated journal frame header")
                (n,) = struct.unpack("<I", hdr)
                rec = f.read(n)
                if len(rec) != n:
                    raise ValueError("truncated journal record")
                if i >= start:
                    yield serial.decode_term(rec)
                i += 1

    def __len__(self) -> int:
        if self.path is None:
            return len(self._mem)
        return sum(1 for _ in self.entries())

    def repair(self) -> int:
        """Crash-consistent open: truncate a torn tail in place.

        A process killed mid-append leaves a partial final frame (the
        header or record cut short). `entries()` stays STRICT — a torn
        read in the middle of normal operation is a real error — but
        recovery (`resume`) calls this first: scan frames from the
        start, find the end of the last complete record, truncate the
        file there, and return the number of bytes discarded. The intact
        prefix is exactly what was durable (appends fsync per record),
        and truncating — rather than skipping — matters because later
        appends must land after the last good frame, not after garbage.
        """
        if self.path is None:
            return 0
        if self._fh is not None:
            self._fh.flush()
        size = os.path.getsize(self.path)
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) != 4:
                    break
                (n,) = struct.unpack("<I", hdr)
                rec = f.read(n)
                if len(rec) != n:
                    break
                good += 4 + n
        torn = size - good
        if torn:
            os.truncate(self.path, good)
            if self._fh is not None:
                # Reopen the append handle: a buffered position past the
                # truncation point would resurrect the torn bytes.
                self._fh.close()
                self._fh = open(self.path, "ab")
        return torn


class CheckpointingReplay(ScalarReplay):
    """ScalarReplay with a write-ahead journal and snapshot/resume."""

    def __init__(
        self,
        crdt: ScalarCCRDT,
        n_replicas: int,
        new_args: tuple = (),
        journal: Optional[Journal] = None,
    ):
        super().__init__(crdt, n_replicas, new_args=new_args)
        self.journal = journal if journal is not None else Journal()
        self.seq = 0  # journal records reflected in this replay's state
        self.new_args = new_args

    def submit(self, origin: int, prepare_op: Any):
        self.journal.append(origin, prepare_op)
        self.seq += 1
        return super().submit(origin, prepare_op)

    def sync(self) -> None:
        # Sync points must be journaled: effects re-derived on replay pass
        # through `downstream`, whose output depends on the origin state,
        # which depends on *when* remote effects were delivered. Marker
        # records (origin = -1) make replay re-sync at the same boundaries.
        self.journal.append(-1, None)
        self.seq += 1
        super().sync()

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> bytes:
        """Versioned snapshot of everything `resume` needs. The journal
        itself is not embedded — it is the durable log living beside the
        snapshot, exactly how a database pairs WAL + checkpoint."""
        clocks = [ctx.clock.get_time() for ctx in self.ctxs]
        shared = all(c is self.ctxs[0].clock for c in (ctx.clock for ctx in self.ctxs))
        body = serial.encode_term(
            {
                "name": self.crdt.type_name,
                "new_args": tuple(self.new_args),
                "states": [self.crdt.to_binary(s) for s in self.states],
                "clocks": clocks,
                "shared_clock": shared,
                "seq": self.seq,
                "pending": [
                    (o, serial.encode_term(e)) for (o, e) in self.effect_log
                ],
                "ops_applied": self.ops_applied,
            }
        )
        return SNAP_MAGIC + bytes([SNAP_VERSION]) + body


def _restore(crdt: ScalarCCRDT, snap: bytes, journal: Journal) -> CheckpointingReplay:
    if snap[:4] != SNAP_MAGIC:
        raise ValueError("not a CCRDT checkpoint (bad magic)")
    if snap[4] > SNAP_VERSION:
        raise ValueError(f"checkpoint version {snap[4]} newer than {SNAP_VERSION}")
    d = serial.decode_term(snap[5:])
    if d["name"] != crdt.type_name:
        raise ValueError(f"checkpoint is for {d['name']!r}, not {crdt.type_name!r}")
    rp = CheckpointingReplay(crdt, len(d["states"]), new_args=d["new_args"], journal=journal)
    rp.states = [crdt.from_binary(b) for b in d["states"]]
    rp.seq = d["seq"]
    rp.ops_applied = d["ops_applied"]
    rp.effect_log = [(o, serial.decode_term(e)) for (o, e) in d["pending"]]
    if d["shared_clock"]:
        clk = LogicalClock(max(d["clocks"]))
        for ctx in rp.ctxs:
            ctx.clock = clk
    else:
        for ctx, t in zip(rp.ctxs, d["clocks"]):
            ctx.clock = LogicalClock(t)
    return rp


def resume(
    crdt: ScalarCCRDT,
    snapshot: Optional[bytes],
    journal: Journal,
    n_replicas: Optional[int] = None,
    new_args: tuple = (),
) -> CheckpointingReplay:
    """Restore from `snapshot` (or fresh state if None) and replay the
    journal suffix. Deterministic: replayed prepare ops re-derive the same
    effect ops because the snapshot restored the logical clocks.

    Recovery is crash-consistent: a torn final journal record (the crash
    landed mid-append) is truncated away first (`Journal.repair`) — the
    intact prefix replays, the tail is discarded."""
    journal.repair()
    if snapshot is None:
        if n_replicas is None:
            raise ValueError("n_replicas required when starting without a snapshot")
        rp = CheckpointingReplay(crdt, n_replicas, new_args=new_args, journal=journal)
        start = 0
    else:
        rp = _restore(crdt, snapshot, journal)
        start = rp.seq
    for origin, op in journal.entries(start):
        # bypass self.journal.append — these records are already durable
        if origin == -1:
            ScalarReplay.sync(rp)
        else:
            ScalarReplay.submit(rp, origin, op)
        rp.seq += 1
    return rp


# -- dense checkpoints -----------------------------------------------------


def save_dense_checkpoint(path: str, name: str, state: Any, step: int = 0) -> None:
    """Atomic (write+rename) dense-state checkpoint file."""
    blob = serial.dumps_dense(name, state)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", step))
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    # Fault point `ckpt.replace`: a raise here is a crash between the
    # durable tmp write and the commit — the previous checkpoint must
    # survive untouched (the .tmp is harmless debris).
    if faults.ACTIVE:
        faults.fire("ckpt.replace")
    os.replace(tmp, path)


def load_dense_checkpoint(
    path: str, like: Any, dense: Any = None
) -> Tuple[int, str, Any]:
    """Returns (step, name, state) with `state` in the structure of `like`.

    Pass the dense engine as `dense` to structurally validate the restored
    state against the engine config (utils.validate.check_state) — a
    checkpoint written under different capacities (I/M/D/K) otherwise
    surfaces only as silent wrong answers deep in the kernels."""
    with open(path, "rb") as f:
        data = f.read()
    (step,) = struct.unpack("<Q", data[:8])
    name, state = serial.loads_dense(data[8:], like)
    if dense is not None:
        from ..utils.validate import check_state

        check_state(dense, state)
    return step, name, state


# -- partitioned (sharded) dense checkpoints --------------------------------
#
# One file per partition (`shard-<part>.ckpt`, a CCPT psnap container —
# core/partition.py) plus a `manifest.json` commit marker. The unit of
# durability is the PARTITION: a rejoining worker streams and persists
# state shard by shard, and a crash mid-stream (SIGKILL between shards)
# costs only the partition in flight — restart resumes from the last
# durable shard instead of refetching one giant blob.

_SHARD_PREFIX = "shard-"
_SHARD_SUFFIX = ".ckpt"
_MANIFEST = "manifest.json"


def _shard_path(root: str, part: int) -> str:
    return os.path.join(root, f"{_SHARD_PREFIX}{part:04d}{_SHARD_SUFFIX}")


def _write_shard(
    root: str, name: str, dense: Any, state: Any, part: int, P: int,
    step: int, pager: Any = None,
) -> int:
    """Atomically persist partition `part` of `state`; returns bytes.
    With a pager, a demoted partition's shard is written straight from
    its stored CCPT payload (transfer format is storage format) — no
    hydration to checkpoint."""
    from ..core import partition as pt

    if pager is not None:
        payload = pager.psnap_payload(state, part)
    else:
        payload = serial.dumps_dense(
            f"{name}_psnap", pt.restrict_psnap(dense, state, part, P)
        )
    blob = pt.encode_psnap_blob(step, part, payload)
    path = _shard_path(root, part)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    if faults.ACTIVE:
        faults.fire("ckpt.replace")
    os.replace(tmp, path)
    return len(blob)


def save_partitioned_checkpoint(
    root: str, name: str, state: Any, dense: Any, step: int,
    partitions: Optional[int] = None,
    parts: Optional[List[int]] = None,
    pager: Any = None,
) -> int:
    """Shard `state` into per-partition checkpoint files (P id
    partitions + the meta partition) and commit with a manifest.
    Returns total bytes written. Shards first, manifest last: the
    manifest is the whole-checkpoint commit point, but each shard is
    individually durable the moment it lands (what the rejoin streamer
    relies on). `parts` restricts the write to a subset — the mesh
    path (`save_mesh_checkpoint`) saves each key shard's owned
    partitions separately; because `_write_shard` is a pure function of
    (state, part), the union of per-shard saves is byte-identical to
    one whole save. A subset save writes no manifest (it is a slice,
    not a commit point)."""
    import json

    from ..core import partition as pt

    P = partitions if partitions else pt.n_partitions()
    os.makedirs(root, exist_ok=True)
    total = 0
    todo = sorted(int(p) for p in parts) if parts is not None else range(P + 1)
    for part in todo:
        total += _write_shard(root, name, dense, state, part, P, step,
                              pager=pager)
    if parts is not None:
        return total
    if pager is not None and pager.has_cold():
        digests = pager.digest_vector(state)
    else:
        digests = pt.state_digests(state, P)
    _write_manifest(root, name, step, P, digests)
    return total


def _write_manifest(
    root: str, name: str, step: int, P: int, digests: Any
) -> None:
    import json

    manifest = {
        "name": name,
        "step": int(step),
        "partitions": int(P),
        "digests": [int(d) for d in digests],
    }
    tmp = os.path.join(root, f"{_MANIFEST}.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, _MANIFEST))


def save_mesh_checkpoint(
    root: str, name: str, state: Any, dense: Any, step: int, plan: Any,
    pager: Any = None,
) -> int:
    """Shard-grouped checkpoint: each key shard of a `mesh.MeshPlan`
    persists exactly the partitions it owns (`parts=owned_parts(s)`),
    then the manifest commits the whole — the mesh counterpart of
    `save_partitioned_checkpoint`, producing byte-identical files
    (pinned by test_mesh.py). The digest vector in the manifest is
    produced shard-by-shard and stitched (mesh/gossip.py)."""
    from ..mesh import gossip as mesh_gossip

    total = 0
    for s in range(plan.n_key):
        total += save_partitioned_checkpoint(
            root, name, state, dense, step,
            partitions=plan.P, parts=plan.owned_parts(s), pager=pager,
        )
    digests = mesh_gossip.sharded_digest_vector(state, plan, pager=pager)
    _write_manifest(root, name, step, plan.P, digests)
    return total


def load_partitioned_checkpoint(
    root: str, base: Any, dense: Any
) -> Tuple[int, Optional[str], Any, List[int]]:
    """-> (step, name, state, durable_parts): join every decodable shard
    under `root` into `base`. Tolerates a PARTIAL checkpoint — missing,
    torn, or foreign shards are skipped, not fatal (the streamer resumes
    exactly from what this reports durable). `step` is the highest shard
    seq seen (-1 = nothing durable). Falls back to a legacy single-file
    whole-instance checkpoint (`snap.ckpt`) when no shards exist, so
    pre-partition checkpoint directories keep restoring."""
    from ..core import partition as pt
    from ..parallel.delta import apply_any_delta, like_delta_for

    state, step, name = base, -1, None
    durable: List[int] = []
    if not os.path.isdir(root):
        return step, name, state, durable
    shards = sorted(
        f for f in os.listdir(root)
        if f.startswith(_SHARD_PREFIX) and f.endswith(_SHARD_SUFFIX)
    )
    if not shards:
        legacy = os.path.join(root, "snap.ckpt")
        if os.path.exists(legacy):
            try:
                step, name, state = load_dense_checkpoint(
                    legacy, base, dense=dense
                )
            except Exception:  # noqa: BLE001 — torn legacy file: nothing
                pass           # durable, same contract as missing shards
        return step, name, state, durable
    like_delta = like_delta_for(dense, base)
    for fname in shards:
        try:
            with open(os.path.join(root, fname), "rb") as f:
                blob = f.read()
            seq, part, payload = pt.decode_psnap_blob(blob)
            got_name, delta = serial.loads_dense(payload, like_delta)
            state = apply_any_delta(dense, state, delta)
        except Exception:  # noqa: BLE001 — skip the torn shard; the
            continue       # streamer refetches it
        durable.append(int(part))
        step = max(step, int(seq))
        if name is None and got_name.endswith("_psnap"):
            name = got_name[: -len("_psnap")]
    return step, name, state, durable


class RejoinStreamer:
    """Incremental, resumable rejoin: instead of swallowing a peer's
    whole snapshot, stream state PARTITION BY PARTITION, persisting each
    one durably (`_write_shard`) before moving to the next.

    Order is lowest-lag first: partitions whose digests already agree
    with the peer complete immediately (persisted from local state, zero
    transfer), then divergent partitions stream in ascending order. A
    SIGKILL between shards costs only the partition in flight — the next
    incarnation's `start()` loads the durable shards, re-diffs digests,
    and plans only what is still missing (tests pin the drill).

    Counters: `rejoin.parts_streamed`, `rejoin.parts_skipped` (already
    durable/agreeing), `rejoin.stream_bytes`."""

    def __init__(
        self, root: str, name: str, dense: Any, store: Any, peer: str,
        partitions: Optional[int] = None, metrics: Any = None,
    ):
        from ..core import partition as pt

        self.root = root
        self.name = name
        self.dense = dense
        self.store = store
        self.peer = peer
        self.partitions = partitions if partitions else pt.n_partitions()
        self.metrics = metrics if metrics is not None else store.metrics
        self.plan: List[int] = []
        self.peer_seq: int = -1
        self._pt = pt
        os.makedirs(root, exist_ok=True)

    def start(self, base: Any) -> Any:
        """Join durable shards into `base`, diff digests against the
        peer, and plan the remaining stream. Returns the restored state
        (call `step`/`run` next). With no peer digest vector (legacy
        peer), the plan covers every partition — still streamed and
        persisted one at a time."""
        from ..obs import events as obs_events

        pt, P = self._pt, self.partitions
        step, _name, state, durable = load_partitioned_checkpoint(
            self.root, base, self.dense
        )
        got = self.store.fetch_digests(self.peer)
        if got is None:
            self.plan = [p for p in range(P + 1)]
            self.peer_seq = -1
        else:
            self.peer_seq, peer_vec = got
            own_vec = pt.state_digests(state, P)
            div = set(pt.divergent_parts(own_vec, peer_vec))
            # Lowest-lag first: agreeing partitions are done — persist
            # any not yet durable straight from local state.
            for p in range(P + 1):
                if p in div:
                    continue
                if p not in durable:
                    _write_shard(
                        self.root, self.name, self.dense, state, p, P,
                        max(0, self.peer_seq),
                    )
                self.metrics.count("rejoin.parts_skipped")
            self.plan = sorted(div)
        self.store.request_psnaps(self.peer, self.plan)
        obs_events.emit(
            "rejoin.plan", origin=self.peer, parts=list(self.plan),
            durable=sorted(durable),
        )
        return state

    def step(self, state: Any) -> Tuple[Any, Optional[int], bool]:
        """Stream ONE partition: fetch its psnap, join it, persist the
        shard. -> (state, part_streamed_or_None, finished). `None` with
        finished=False means the psnap is still in flight (push media) —
        advance the medium and call again."""
        from ..obs import events as obs_events
        from ..parallel.delta import delta_in_bounds, like_delta_for

        if not self.plan:
            return state, None, True
        p = self.plan[0]
        like = like_delta_for(self.dense, state)
        r = self.store.fetch_psnap(
            self.peer, p, like,
            validate=lambda d: delta_in_bounds(self.dense, state, d),
        )
        if r is None:
            self.store.request_psnaps(self.peer, [p])
            return state, None, False
        seq, payload = r
        from ..parallel.delta import apply_any_delta

        state = apply_any_delta(self.dense, state, payload)
        nbytes = _write_shard(
            self.root, self.name, self.dense, state, p, self.partitions,
            max(seq, self.peer_seq, 0),
        )
        self.plan.pop(0)
        self.metrics.count("rejoin.parts_streamed")
        self.metrics.count("rejoin.stream_bytes", nbytes)
        obs_events.emit(
            "rejoin.part", origin=self.peer, part=p, bytes=nbytes,
            remaining=len(self.plan),
        )
        return state, p, not self.plan

    def run(self, state: Any, max_stalls: int = 64,
            advance=None) -> Any:
        """Drain the plan. `advance` (optional callable) pumps the
        medium between stalled fetches — the sim drill passes
        `lambda: net.advance(dt)`; real transports just retry."""
        stalls = 0
        while self.plan and stalls < max_stalls:
            state, part, _done = self.step(state)
            if part is None:
                stalls += 1
                if advance is not None:
                    advance()
            else:
                stalls = 0
        return state
