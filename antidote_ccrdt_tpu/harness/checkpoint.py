"""Checkpoint / resume: write-ahead op journal + state snapshots.

The reference's only persistence is whole-state ``to_binary/1`` with no
journal and no versioning (SURVEY.md §5): a crashed materializer loses
every op since the last snapshot. Here the harness gets the full recipe a
host database would use:

* `Journal` — a write-ahead log of (origin, prepare_op) records, file-backed
  or in-memory, length-prefix framed. Prepare ops (not effects) are
  journaled because replay re-derives effects deterministically: replica
  clocks are `LogicalClock`s whose counters the snapshot captures, so
  re-running `downstream` after restore stamps identical (dc, ts) pairs.

* `CheckpointingReplay` — a `ScalarReplay` that journals every submission
  and can `snapshot()` to versioned bytes (per-replica state blobs via the
  type's own ``to_binary`` + clock counters + journal position + pending
  effect queue).

* `resume` — restore the snapshot and replay the journal suffix; the result
  is bit-identical to a run that never stopped (tested both mid-epoch and
  at sync boundaries).

Dense states checkpoint through `core.serial.dumps_dense` (npz + treedef
manifest) — see `save_dense_checkpoint` / `load_dense_checkpoint`.
"""

from __future__ import annotations

import io
import os
import struct
from typing import Any, BinaryIO, Iterator, List, Optional, Tuple

from ..core import serial
from ..core.behaviour import ScalarCCRDT
from ..core.clock import LogicalClock, ReplicaContext
from ..utils import faults
from .replay import ScalarReplay

SNAP_MAGIC = b"CCKP"
SNAP_VERSION = 1


class Journal:
    """Append-only write-ahead log of (origin, prepare_op) records."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mem: List[bytes] = []
        self._fh: Optional[BinaryIO] = None
        if path is not None:
            self._fh = open(path, "ab")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def append(self, origin: int, op: Any) -> None:
        rec = serial.encode_term((origin, op))
        frame = struct.pack("<I", len(rec)) + rec
        if self.path is not None:
            if self._fh is None:
                raise ValueError("journal is closed")
            self._fh.write(frame)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        else:
            self._mem.append(rec)

    def entries(self, start: int = 0) -> Iterator[Tuple[int, Any]]:
        """Yield (origin, prepare_op) from record index `start` on."""
        if self.path is None:
            for rec in self._mem[start:]:
                yield serial.decode_term(rec)
            return
        if self._fh is not None:
            self._fh.flush()
        with open(self.path, "rb") as f:
            i = 0
            while True:
                hdr = f.read(4)
                if not hdr:
                    return
                if len(hdr) != 4:
                    raise ValueError("truncated journal frame header")
                (n,) = struct.unpack("<I", hdr)
                rec = f.read(n)
                if len(rec) != n:
                    raise ValueError("truncated journal record")
                if i >= start:
                    yield serial.decode_term(rec)
                i += 1

    def __len__(self) -> int:
        if self.path is None:
            return len(self._mem)
        return sum(1 for _ in self.entries())

    def repair(self) -> int:
        """Crash-consistent open: truncate a torn tail in place.

        A process killed mid-append leaves a partial final frame (the
        header or record cut short). `entries()` stays STRICT — a torn
        read in the middle of normal operation is a real error — but
        recovery (`resume`) calls this first: scan frames from the
        start, find the end of the last complete record, truncate the
        file there, and return the number of bytes discarded. The intact
        prefix is exactly what was durable (appends fsync per record),
        and truncating — rather than skipping — matters because later
        appends must land after the last good frame, not after garbage.
        """
        if self.path is None:
            return 0
        if self._fh is not None:
            self._fh.flush()
        size = os.path.getsize(self.path)
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) != 4:
                    break
                (n,) = struct.unpack("<I", hdr)
                rec = f.read(n)
                if len(rec) != n:
                    break
                good += 4 + n
        torn = size - good
        if torn:
            os.truncate(self.path, good)
            if self._fh is not None:
                # Reopen the append handle: a buffered position past the
                # truncation point would resurrect the torn bytes.
                self._fh.close()
                self._fh = open(self.path, "ab")
        return torn


class CheckpointingReplay(ScalarReplay):
    """ScalarReplay with a write-ahead journal and snapshot/resume."""

    def __init__(
        self,
        crdt: ScalarCCRDT,
        n_replicas: int,
        new_args: tuple = (),
        journal: Optional[Journal] = None,
    ):
        super().__init__(crdt, n_replicas, new_args=new_args)
        self.journal = journal if journal is not None else Journal()
        self.seq = 0  # journal records reflected in this replay's state
        self.new_args = new_args

    def submit(self, origin: int, prepare_op: Any):
        self.journal.append(origin, prepare_op)
        self.seq += 1
        return super().submit(origin, prepare_op)

    def sync(self) -> None:
        # Sync points must be journaled: effects re-derived on replay pass
        # through `downstream`, whose output depends on the origin state,
        # which depends on *when* remote effects were delivered. Marker
        # records (origin = -1) make replay re-sync at the same boundaries.
        self.journal.append(-1, None)
        self.seq += 1
        super().sync()

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> bytes:
        """Versioned snapshot of everything `resume` needs. The journal
        itself is not embedded — it is the durable log living beside the
        snapshot, exactly how a database pairs WAL + checkpoint."""
        clocks = [ctx.clock.get_time() for ctx in self.ctxs]
        shared = all(c is self.ctxs[0].clock for c in (ctx.clock for ctx in self.ctxs))
        body = serial.encode_term(
            {
                "name": self.crdt.type_name,
                "new_args": tuple(self.new_args),
                "states": [self.crdt.to_binary(s) for s in self.states],
                "clocks": clocks,
                "shared_clock": shared,
                "seq": self.seq,
                "pending": [
                    (o, serial.encode_term(e)) for (o, e) in self.effect_log
                ],
                "ops_applied": self.ops_applied,
            }
        )
        return SNAP_MAGIC + bytes([SNAP_VERSION]) + body


def _restore(crdt: ScalarCCRDT, snap: bytes, journal: Journal) -> CheckpointingReplay:
    if snap[:4] != SNAP_MAGIC:
        raise ValueError("not a CCRDT checkpoint (bad magic)")
    if snap[4] > SNAP_VERSION:
        raise ValueError(f"checkpoint version {snap[4]} newer than {SNAP_VERSION}")
    d = serial.decode_term(snap[5:])
    if d["name"] != crdt.type_name:
        raise ValueError(f"checkpoint is for {d['name']!r}, not {crdt.type_name!r}")
    rp = CheckpointingReplay(crdt, len(d["states"]), new_args=d["new_args"], journal=journal)
    rp.states = [crdt.from_binary(b) for b in d["states"]]
    rp.seq = d["seq"]
    rp.ops_applied = d["ops_applied"]
    rp.effect_log = [(o, serial.decode_term(e)) for (o, e) in d["pending"]]
    if d["shared_clock"]:
        clk = LogicalClock(max(d["clocks"]))
        for ctx in rp.ctxs:
            ctx.clock = clk
    else:
        for ctx, t in zip(rp.ctxs, d["clocks"]):
            ctx.clock = LogicalClock(t)
    return rp


def resume(
    crdt: ScalarCCRDT,
    snapshot: Optional[bytes],
    journal: Journal,
    n_replicas: Optional[int] = None,
    new_args: tuple = (),
) -> CheckpointingReplay:
    """Restore from `snapshot` (or fresh state if None) and replay the
    journal suffix. Deterministic: replayed prepare ops re-derive the same
    effect ops because the snapshot restored the logical clocks.

    Recovery is crash-consistent: a torn final journal record (the crash
    landed mid-append) is truncated away first (`Journal.repair`) — the
    intact prefix replays, the tail is discarded."""
    journal.repair()
    if snapshot is None:
        if n_replicas is None:
            raise ValueError("n_replicas required when starting without a snapshot")
        rp = CheckpointingReplay(crdt, n_replicas, new_args=new_args, journal=journal)
        start = 0
    else:
        rp = _restore(crdt, snapshot, journal)
        start = rp.seq
    for origin, op in journal.entries(start):
        # bypass self.journal.append — these records are already durable
        if origin == -1:
            ScalarReplay.sync(rp)
        else:
            ScalarReplay.submit(rp, origin, op)
        rp.seq += 1
    return rp


# -- dense checkpoints -----------------------------------------------------


def save_dense_checkpoint(path: str, name: str, state: Any, step: int = 0) -> None:
    """Atomic (write+rename) dense-state checkpoint file."""
    blob = serial.dumps_dense(name, state)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", step))
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    # Fault point `ckpt.replace`: a raise here is a crash between the
    # durable tmp write and the commit — the previous checkpoint must
    # survive untouched (the .tmp is harmless debris).
    if faults.ACTIVE:
        faults.fire("ckpt.replace")
    os.replace(tmp, path)


def load_dense_checkpoint(
    path: str, like: Any, dense: Any = None
) -> Tuple[int, str, Any]:
    """Returns (step, name, state) with `state` in the structure of `like`.

    Pass the dense engine as `dense` to structurally validate the restored
    state against the engine config (utils.validate.check_state) — a
    checkpoint written under different capacities (I/M/D/K) otherwise
    surfaces only as silent wrong answers deep in the kernels."""
    with open(path, "rb") as f:
        data = f.read()
    (step,) = struct.unpack("<Q", data[:8])
    name, state = serial.loads_dense(data[8:], like)
    if dense is not None:
        from ..utils.validate import check_state

        check_state(dense, state)
    return step, name, state
