"""Orbax-backed dense-state checkpointing: sharded, retained, step-indexed.

`harness.checkpoint` covers the single-process story (WAL journal +
versioned npz snapshots, the reference's ``to_binary`` descendants —
topk_rmv.erl:156-163). This module is the multi-host/distributed tier the
reference never had: dense pytree states that live *sharded across a
`jax.sharding.Mesh`* checkpoint through Orbax, which writes each shard from
the host that owns it and restores with the same shardings — the standard
recipe for TPU-pod state. A `CheckpointManager` adds step indexing and
retention (`max_to_keep`), pairing with the WAL exactly like
checkpoint.resume: restore latest step, then replay the journal suffix.

Gated: `available()` is False when orbax-checkpoint is not installed and
everything degrades to the npz path (pyproject extra ``checkpoint``).
"""

from __future__ import annotations

from typing import Any, Optional

try:
    import orbax.checkpoint as _ocp

    _IMPORT_ERROR: Optional[str] = None
except Exception as e:  # pragma: no cover - exercised only without orbax
    _ocp = None
    _IMPORT_ERROR = str(e)


def available() -> bool:
    return _ocp is not None


def _require():
    if _ocp is None:
        raise RuntimeError(
            f"orbax-checkpoint unavailable ({_IMPORT_ERROR}); "
            "use harness.checkpoint.save_dense_checkpoint instead"
        )
    return _ocp


class DenseCheckpointManager:
    """Step-indexed, retention-managed checkpoints of one dense-state pytree.

    The state may be fully replicated, host-local, or sharded over a mesh;
    Orbax records shardings in the checkpoint and `restore(like=...)`
    re-lays the arrays out to match `like`'s shardings (so a checkpoint
    written on an 8-device mesh restores onto a differently-shaped mesh —
    elastic recovery for the id-sharded instances in parallel/sharded.py).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        ocp = self._ocp = _require()
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        ocp = self._ocp
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, like: Any, step: Optional[int] = None) -> Any:
        """Restore `step` (default: latest) into the structure/shardings of
        `like` (an abstract or concrete pytree of the same treedef)."""
        ocp = self._ocp
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError("no checkpoint steps in directory")
        return self._mngr.restore(step, args=ocp.args.StandardRestore(like))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def reload(self) -> None:
        """Refresh the cached step list. Orbax caches it at construction
        and updates it only on this manager's own saves — a READER of a
        directory another process (or manager) writes must reload before
        `latest_step`/`restore`, or it pins the steps it saw first."""
        self._mngr.reload()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
