"""Synthetic workload generation for the multi-DC replay harness.

Replaces the reference's hand-written EUnit scenarios and the absent host
with parameterized op streams per BASELINE.md configs: Zipf-skewed id
popularity, configurable add/remove mix, per-DC logical clocks.

Two product shapes:

* `prepare_stream` — prepare ops (("add", (id, score)) / ("rmv", id)) to be
  run through each type's `downstream` at an origin replica: the faithful
  op-based pipeline, used for parity replay and the CPU baseline.
* `effect_batches` — pre-stamped dense effect-op batches (TopkRmvOps etc.)
  for the TPU kernels: timestamps are assigned from per-DC logical clocks
  and removal vcs track the generator's global delivery frontier, which
  models causal broadcast (every op is delivered in generation order).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Workload:
    n_replicas: int
    n_ids: int
    rmv_frac: float = 0.0
    rmv_kind: str = "rmv"  # "ban" for leaderboard
    zipf_a: float = 1.2  # Zipf exponent; <= 1.0 means uniform
    score_max: int = 10_000
    seed: int = 0


def _draw_ids(rng: np.random.Generator, wl: Workload, n: int) -> np.ndarray:
    if wl.zipf_a <= 1.0:
        return rng.integers(0, wl.n_ids, size=n).astype(np.int32)
    # Zipf over the id space: rejection-free via truncated zipf mod n_ids.
    raw = rng.zipf(wl.zipf_a, size=n)
    return ((raw - 1) % wl.n_ids).astype(np.int32)


def prepare_stream(wl: Workload, n_ops: int) -> Iterator[Tuple[int, tuple]]:
    """Yield (origin_replica, prepare_op) pairs."""
    rng = np.random.default_rng(wl.seed)
    origins = rng.integers(0, wl.n_replicas, size=n_ops)
    ids = _draw_ids(rng, wl, n_ops)
    scores = rng.integers(1, wl.score_max, size=n_ops)
    rmv = rng.random(n_ops) < wl.rmv_frac
    for j in range(n_ops):
        if rmv[j]:
            yield int(origins[j]), (wl.rmv_kind, int(ids[j]))
        else:
            yield int(origins[j]), ("add", (int(ids[j]), int(scores[j])))


class TopkRmvEffectGen:
    """Pre-stamped topk_rmv effect batches for the dense kernels.

    Each replica r is a DC with its own monotone clock; removal vcs carry
    the generator's frontier (max ts emitted per DC before the rmv), which
    is exactly the state vc a replica would hold under in-order broadcast
    delivery (the reference ships `Vc` from downstream, topk_rmv.erl:121).
    """

    def __init__(self, wl: Workload):
        assert wl.n_replicas >= 1
        self.wl = wl
        self.rng = np.random.default_rng(wl.seed)
        self.clock = np.zeros(wl.n_replicas, dtype=np.int64)  # per-DC ts
        self.frontier = np.zeros(wl.n_replicas, dtype=np.int32)

    def next_batch(self, adds_per_replica: int, rmvs_per_replica: int):
        """Build one TopkRmvOps batch [R, B] / [R, Br]."""
        from ..models.topk_rmv_dense import TopkRmvOps
        import jax.numpy as jnp

        wl, rng = self.wl, self.rng
        R, B, Br = wl.n_replicas, adds_per_replica, rmvs_per_replica
        add_id = np.stack([_draw_ids(rng, wl, B) for _ in range(R)])
        add_score = rng.integers(1, wl.score_max, size=(R, B)).astype(np.int32)
        add_dc = np.broadcast_to(
            np.arange(R, dtype=np.int32)[:, None], (R, B)
        ).copy()
        add_ts = np.empty((R, B), dtype=np.int32)
        for r in range(R):
            add_ts[r] = np.arange(1, B + 1, dtype=np.int32) + self.clock[r]
            self.clock[r] += B
        rmv_id = np.stack([_draw_ids(rng, wl, Br) for _ in range(R)]) if Br else np.zeros((R, 0), np.int32)
        # Removal vc: the emitting DC's causal frontier — everything emitted
        # in earlier batches (all DCs) plus its own adds in this batch.
        rmv_vc = np.broadcast_to(self.frontier[None, None, :], (R, Br, R)).copy()
        for r in range(R):
            rmv_vc[r, :, r] = self.clock[r]
        self.frontier = self.clock.astype(np.int32).copy()
        return TopkRmvOps(
            add_key=jnp.zeros((R, B), jnp.int32),
            add_id=jnp.asarray(add_id),
            add_score=jnp.asarray(add_score),
            add_dc=jnp.asarray(add_dc),
            add_ts=jnp.asarray(add_ts),
            # Br == 0 still needs one (padded) rmv column: XLA shapes are
            # static, so an all-invalid row stands in for "no removals".
            rmv_key=jnp.zeros((R, max(Br, 1)), jnp.int32),
            rmv_id=jnp.asarray(rmv_id) if Br else jnp.full((R, 1), -1, jnp.int32),
            rmv_vc=jnp.asarray(rmv_vc) if Br else jnp.zeros((R, 1, R), jnp.int32),
        )
