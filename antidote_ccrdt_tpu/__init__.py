"""antidote_ccrdt_tpu: a TPU-native computational-CRDT framework.

A from-scratch rebuild of the capabilities of the Erlang library
``antidote_ccrdt`` (see SURVEY.md) designed for JAX/XLA on TPU:

* **Scalar level** — faithful single-op semantics of the six reference data
  types (average, topk, topk_rmv, leaderboard, wordcount,
  worddocumentcount) behind the 12-callback behaviour contract
  (``antidote_ccrdt.erl:47-59``). Ground truth for tests and the CPU
  baseline for benchmarks.

* **Dense level** — states as fixed-shape array pytrees with
  ``[n_replicas, n_keys, ...]`` batch axes; ``apply_ops`` / ``merge`` as
  jitted batched kernels, plus the north-star ``batch_merge`` entry point
  (``core/batch_merge.py``) joining N scalar states in one device pass.

* **Harness** — synthetic multi-DC replay standing in for the Antidote
  host: op generation, causal delivery, convergence checking, fault
  injection, benchmarking.

* **Parallel** — replica/key sharding over a ``jax.sharding.Mesh`` with
  collective merges riding ICI.
"""

from .core.batch_merge import batch_merge  # noqa: F401
from .core.behaviour import (  # noqa: F401
    DenseCCRDT,
    MergeKind,
    Registry,
    ScalarCCRDT,
    registry,
)
from .core.clock import LogicalClock, ReplicaContext, WallClock, make_contexts  # noqa: F401

# Importing the model modules registers every type.
from .models import average, leaderboard, topk, topk_rmv, wordcount  # noqa: F401


def is_type(name) -> bool:
    """Rebuild of ``antidote_ccrdt:is_type/1`` (``antidote_ccrdt.erl:61-62``)."""
    return registry.is_type(name)


def generates_extra_operations(name) -> bool:
    """Rebuild of ``antidote_ccrdt:generates_extra_operations/1``
    (``antidote_ccrdt.erl:64-65``)."""
    return registry.generates_extra_operations(name)


__version__ = "0.1.0"
