"""Intra-slice reconciliation as ICI collectives.

The dryrun harness (surface [1] of __graft_entry__.py) proved the
pattern: `shard_map` the state over a (dc, key) mesh and run
`parallel.dist.lattice_all_reduce` — recursive-doubling `ppermute`
exchanges whose combiner is the engine's own JOIN merge — over the dc
axis, so every replica row becomes the join of its dc-block *in one
device dispatch* instead of N gossip rounds. This module lifts that
into the product with the `core/batch_merge` slot discipline: one
cached jitted compilation per (merge fn, plan, tree structure), a
plain and a donating variant.

Correctness: the dc all-reduce replaces each replica row r with
join({rows in r's dc block}). JOIN merges are associative, commutative,
and idempotent, so (a) the reduce is itself idempotent — re-reducing a
reduced state is a no-op; (b) the observable state (fold of all rows)
is unchanged — the fold already joined every row; and (c) gossip
convergence arguments are untouched: peers exchange pre-joined rows and
the fleet fixpoint is still the global join. MONOID engines are
excluded (`supports`): + is not idempotent, so pre-summing rows that
gossip will sum again double-counts (the same reason psnaps refuse bare
monoids). MONOID reconciliation over the mesh is a `psum` — exposed as
`psum_reduce` for the bench/dryrun surface — but it must consume
disjoint op histories, which the elastic worker's row-per-replica
gossip does not provide.

Fault point: `mesh.reduce` fires before each collective dispatch
(utils/faults.py). The reduce is a pure optimization — callers treat an
injected failure as "skip this round's reduce" (`try_ici_reduce`),
counting `mesh.reduce_failures`; convergence falls back to plain
gossip.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..core import batch_merge
from ..obs import devprof, profile
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.jaxcompat import shard_map

SPAN_ICI = obs_spans.ICI_REDUCE  # "round.ici_reduce"

# (merge identity, plan identity, treedef) -> {"plain": fn, "donate": fn}
# Same pinning rule as batch_merge._SLOTS: the value keeps the bound
# method + plan alive so the id()-based parts of the key stay valid.
_SLOTS: Dict[Any, Any] = {}


def _slots(dense: Any, plan: Any, state: Any) -> Dict[str, Any]:
    import jax

    merge = dense.merge
    key = (
        batch_merge.merge_slot_key(merge),
        plan.slot_key(),
        jax.tree.structure(state),
    )
    hit = _SLOTS.get(key)
    if hit is None:
        specs = plan.specs(state)

        def _local(s):
            from ..parallel.dist import lattice_all_reduce

            return lattice_all_reduce(s, "dc", merge, plan.n_dc)

        mapped = shard_map(
            _local, mesh=plan.mesh, in_specs=(specs,), out_specs=specs,
            check_vma=False,
        )
        hit = (
            (merge, plan),  # pinned — see _SLOTS comment
            {
                "plain": jax.jit(mapped),
                # Donate only when the caller owns the operand outright
                # (serial round loop); the overlap pipeline's host stage
                # may still be serializing the previous buffers.
                "donate": jax.jit(mapped, donate_argnums=(0,)),
            },
        )
        _SLOTS[key] = hit
    return hit[1]


def supports(dense: Any) -> bool:
    """JOIN engines only — see the MONOID caveat in the module doc."""
    from ..core.behaviour import MergeKind
    from ..parallel.monoid import MonoidLift

    if isinstance(dense, MonoidLift):
        return False
    return getattr(dense, "merge_kind", None) != MergeKind.MONOID


def ici_reduce(
    dense: Any, plan: Any, state: Any, *, donate: bool = False,
    metrics: Optional[Any] = None,
) -> Any:
    """One batched JOIN all-reduce of `state` over the dc axis. May
    raise `faults.InjectedFault` (point `mesh.reduce`); a "drop" action
    skips the collective and returns the state unchanged."""
    if faults.ACTIVE:
        act = faults.fire("mesh.reduce")
        if act == "drop":
            if metrics is not None:
                metrics.count("mesh.reduce_skipped")
            return state
    state = plan.ensure_placed(state)
    fn = _slots(dense, plan, state)["donate" if donate else "plain"]
    tok = (
        obs_spans.begin(SPAN_ICI, n_dc=plan.n_dc, n_key=plan.n_key)
        if obs_spans.ACTIVE
        else None
    )
    try:
        if profile.ACTIVE or devprof.ACTIVE:
            with profile.dispatch(
                "mesh.ici_reduce",
                fn=fn,
                operands=(state,),
                donation="donate" if donate else "plain",
            ):
                if metrics is not None:
                    with metrics.timer("mesh.ici_reduce"):
                        out = fn(state)
                else:
                    out = fn(state)
        elif metrics is not None:
            with metrics.timer("mesh.ici_reduce"):
                out = fn(state)
        else:
            out = fn(state)
    finally:
        obs_spans.end(tok)
    if metrics is not None:
        metrics.count("mesh.ici_reduces")
    return out


def try_ici_reduce(
    dense: Any, plan: Any, state: Any, *, donate: bool = False,
    metrics: Optional[Any] = None,
) -> Any:
    """Total variant: an injected/real reduce failure degrades to plain
    gossip (the reduce is an optimization, never load-bearing)."""
    try:
        return ici_reduce(
            dense, plan, state, donate=donate, metrics=metrics
        )
    except faults.InjectedFault:
        if metrics is not None:
            metrics.count("mesh.reduce_failures")
        return state


# -- MONOID psum (bench / dryrun parity) ------------------------------------

_PSUM_SLOTS: Dict[Any, Any] = {}


def psum_reduce(plan: Any, tree: Any) -> Any:
    """All-reduce a MONOID accumulator pytree (leading axis = replica
    rows, sharded over dc) with `lax.psum` — the collective MONOID
    merges lower to when histories are disjoint. Bench surface only;
    the elastic worker path is JOIN-gated by `supports`."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    key = (plan.slot_key(), jax.tree.structure(tree))
    fn = _PSUM_SLOTS.get(key)
    if fn is None:
        def spec_of(leaf):
            dims = [None] * leaf.ndim
            if leaf.ndim and leaf.shape[0] % plan.n_dc == 0:
                dims[0] = "dc"
            while dims and dims[-1] is None:
                dims.pop()
            return P(*dims)

        specs = jax.tree.map(spec_of, tree)
        fn = jax.jit(
            shard_map(
                lambda t: jax.tree.map(
                    lambda a: lax.psum(a, "dc"), t
                ),
                mesh=plan.mesh, in_specs=(specs,), out_specs=specs,
                check_vma=False,
            )
        )
        _PSUM_SLOTS[key] = fn
    return fn(tree)
