"""`MeshPlan`: the partition→shard map and the physical device layout.

The partition plane (core/partition.py) made the PARTITION the unit of
digests, psnaps, WAL tags, and checkpoint shards. This module makes it
the unit of *placement*: a (dc, key) device mesh where

* the **dc** axis shards the replica rows (axis 0 of every state leaf)
  — intra-slice reconciliation is a JOIN lattice all-reduce over this
  axis (mesh/reduce.py), the real-collective version of what gossip
  does between workers;
* the **key** axis shards the item axis of every item-indexed leaf
  (`core.partition._item_plan` names it per engine) — instances/ids are
  independent, so this axis needs no collectives.

Ownership vs placement: `shard_of(part) = part % n_key` assigns every
digest partition (including the meta partition P) to exactly one key
shard. It is a pure function of (P, n_key) — independent of member
names, device order, or the alive set — so it is stable under worker
churn by construction, and every anchor in a fleet agrees on it without
coordination. Hash partitions (Knuth `part_of`) interleave ids across
the item axis, so a key shard's *owned partitions* are not a contiguous
block of its *resident rows*; ownership governs which shard PRODUCES
and publishes each per-partition artifact (digest entry, psnap blob,
WAL stream, checkpoint shard), which is a host-side responsibility
split — the artifacts themselves are byte-identical to the unsharded
ones because they are computed by the same partition-plane code from
the same (global) state values. Making the physical block layout
partition-affine (so a chip's HBM holds exactly its owned ids) is the
out-of-core follow-up on the ROADMAP, not a correctness requirement
here.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import partition as pt

ENV_DC = "CCRDT_MESH_DC"
ENV_KEY = "CCRDT_MESH_KEY"


def _axis_factorization(n: int) -> tuple:
    """Default (n_dc, n_key) for n devices: the dc axis takes the largest
    power of two ≤ min(n, 2) — reconciliation cost grows with dc (log2
    rounds of full-state exchange) while the key axis is collective-free,
    so keep dc small and give the rest to key."""
    if n < 2:
        return 1, max(1, n)
    n_dc = 2
    return n_dc, n // n_dc


class MeshPlan:
    """Partitions pinned to key-axis shards of a (dc, key) device mesh.

    `mesh` is a `jax.sharding.Mesh` with axes ("dc", "key"); `P` is the
    fleet partition count (a wire/digest parameter — every member must
    agree, same contract as `core.partition.n_partitions`)."""

    def __init__(self, mesh: Any, partitions: Optional[int] = None) -> None:
        self.mesh = mesh
        self.n_dc = int(mesh.shape["dc"])
        self.n_key = int(mesh.shape["key"])
        self.P = int(partitions) if partitions else pt.n_partitions()
        self._sharding_cache: Dict[Any, Any] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def build(
        cls,
        n_dc: Optional[int] = None,
        n_key: Optional[int] = None,
        partitions: Optional[int] = None,
        devices: Optional[List[Any]] = None,
    ) -> "MeshPlan":
        from ..parallel.dist import make_mesh

        import jax

        devs = devices if devices is not None else jax.devices()
        if n_dc is None and n_key is None:
            n_dc, n_key = _axis_factorization(len(devs))
        elif n_dc is None:
            n_dc = max(1, len(devs) // int(n_key))
        elif n_key is None:
            n_key = max(1, len(devs) // int(n_dc))
        return cls(make_mesh(int(n_dc), int(n_key), devices=devs),
                   partitions=partitions)

    @classmethod
    def from_env(
        cls,
        partitions: Optional[int] = None,
        devices: Optional[List[Any]] = None,
    ) -> "MeshPlan":
        """Axis extents from `CCRDT_MESH_DC` / `CCRDT_MESH_KEY` (unset =
        the default factorization of the device count)."""
        def _env_int(name):
            try:
                v = int(os.environ.get(name, "0"))
            except ValueError:
                v = 0
            return v if v > 0 else None

        return cls.build(
            n_dc=_env_int(ENV_DC), n_key=_env_int(ENV_KEY),
            partitions=partitions, devices=devices,
        )

    # -- ownership (partition -> shard) -------------------------------------

    def shard_of(self, part: int) -> int:
        """The key shard that owns digest partition `part` (0..P, the
        meta partition P included). Pure in (part, n_key)."""
        if not (0 <= int(part) <= self.P):
            raise ValueError(f"partition {part} outside 0..{self.P}")
        return int(part) % self.n_key

    def owned_parts(self, shard: int) -> List[int]:
        """Every digest partition (including meta) owned by `shard`."""
        if not (0 <= int(shard) < self.n_key):
            raise ValueError(f"shard {shard} outside 0..{self.n_key - 1}")
        return [p for p in range(self.P + 1) if p % self.n_key == int(shard)]

    def owner_map(self) -> Dict[int, int]:
        return {p: self.shard_of(p) for p in range(self.P + 1)}

    # -- physical layout (NamedSharding per leaf) ----------------------------

    def specs(self, state: Any):
        """A pytree of `PartitionSpec`s congruent with `state`: replica
        axis 0 over "dc", the engine's item axis over "key", everything
        else replicated. Axes that don't divide evenly stay replicated
        (correct, just less parallel) so odd geometries never crash."""
        import jax
        from jax.sharding import PartitionSpec as P

        items, _whole, _extent = pt._item_plan(state)
        item_axes = {id(leaf): axis for _path, leaf, axis in items}

        def spec_of(leaf):
            ndim = getattr(leaf, "ndim", 0)
            if not ndim:
                return P()
            dims: List[Optional[str]] = [None] * ndim
            if leaf.shape[0] % self.n_dc == 0 and leaf.shape[0] > 0:
                dims[0] = "dc"
            axis = item_axes.get(id(leaf))
            if (
                axis is not None
                and axis != 0
                and leaf.shape[axis] % self.n_key == 0
                and leaf.shape[axis] > 0
            ):
                dims[axis] = "key"
            while dims and dims[-1] is None:
                dims.pop()
            return P(*dims)

        return jax.tree.map(spec_of, state)

    def shardings(self, state: Any):
        """`NamedSharding` pytree for `state` (cached per spec)."""
        import jax
        from jax.sharding import NamedSharding

        def sh(spec):
            hit = self._sharding_cache.get(spec)
            if hit is None:
                hit = self._sharding_cache[spec] = NamedSharding(
                    self.mesh, spec
                )
            return hit

        return jax.tree.map(sh, self.specs(state))

    def place(self, state: Any) -> Any:
        """Pin `state` onto the mesh (device_put per leaf)."""
        import jax

        return jax.tree.map(
            lambda leaf, s: jax.device_put(leaf, s),
            state, self.shardings(state),
        )

    def ensure_placed(self, state: Any) -> Any:
        """Re-pin only the leaves whose sharding drifted (merges with
        host-materialized peers produce unsharded outputs); leaves
        already on-plan pass through untouched — no copy, no dispatch."""
        import jax

        def fix(leaf, sh):
            if getattr(leaf, "sharding", None) == sh:
                return leaf
            return jax.device_put(leaf, sh)

        return jax.tree.map(fix, state, self.shardings(state))

    # -- out-of-core (core/pager.py) ----------------------------------------

    def build_pager(
        self, dense: Any, state: Any, shard: int, *,
        name: str = "state", metrics: Optional[Any] = None,
        spill_dir: Optional[str] = None,
    ) -> Optional[Any]:
        """A `PartitionPager` scoped to `shard`'s owned partitions — the
        per-chip hot/cold residency manager for this plan. Budgets come
        from `CCRDT_PAGER_HBM_BUDGET` / `CCRDT_PAGER_HOST_BUDGET`;
        returns None when paging is disabled, unconfigured, or the
        engine is unpageable (lifted rows / bare monoids)."""
        from ..core import pager as pg

        return pg.maybe_pager(
            dense, state, owned=self.owned_parts(shard), metrics=metrics,
            spill_dir=spill_dir, P=self.P, name=name,
        )

    # -- identity ------------------------------------------------------------

    def slot_key(self):
        """Hashable identity for jit-slot caching (mesh/reduce.py)."""
        return (self.mesh, self.P, self.n_dc, self.n_key)

    def describe(self) -> Dict[str, Any]:
        return {
            "n_dc": self.n_dc,
            "n_key": self.n_key,
            "partitions": self.P,
            "devices": int(np.prod([self.mesh.shape[a] for a in ("dc", "key")])),
            "parts_per_shard": {
                s: len(self.owned_parts(s)) for s in range(self.n_key)
            },
        }

    def export_gauges(self, metrics: Any) -> None:
        """Per-shard gauges for the obs plane."""
        metrics.set("mesh.n_dc", float(self.n_dc))
        metrics.set("mesh.n_key", float(self.n_key))
        for s in range(self.n_key):
            metrics.set(
                f"mesh.shard{s:02d}.parts", float(len(self.owned_parts(s)))
            )
