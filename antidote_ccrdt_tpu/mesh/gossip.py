"""Cross-slice anti-entropy over the existing net/ + topo/ planes.

A *slice* is one mesh-sharded worker process (its own (dc, key) device
mesh); slices gossip exactly like unsharded workers — same transports,
same delta chains, same digest/psnap wire blobs — so mixed fleets
(sharded next to unsharded, mesh shape A next to shape B) interoperate
with no wire change. What the mesh adds:

* **per-shard production** — at anchor time each key shard produces the
  digest entries and psnap blobs for the partitions it owns
  (`MeshPlan.shard_of`), and `stitch_digests` reassembles the full
  P+1 vector. The stitched artifacts are byte-identical to the
  unsharded ones (`core.partition.digest_entries` is the same byte walk
  `state_digests` does), which tests/test_mesh.py pins.
* **per-shard fetch grouping** — `group_parts_by_shard` orders a
  divergent-partition fetch set shard by shard, so a repairing slice
  pulls only the shard-local psnap slices it is missing;
  `parallel.elastic.PartialAntiEntropy` uses it to stitch per-shard
  fetches back together and bills `mesh.cross_slice_fetches` /
  `mesh.cross_slice_bytes`.
* **resharded ingest** — a fetched snapshot (any origin shape) joins
  into the local state and the result is re-pinned onto the local plan
  (`device_put` onto the plan's shardings — the dryrun's resharding
  path, surface [3]), so mesh shape A → B rejoin works mid-flight.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..core import partition as pt


def shard_digest_entries(
    state: Any, plan: Any, shard: int, pager: Optional[Any] = None
) -> Dict[int, int]:
    """Digest entries for the partitions `shard` owns — the shard-local
    slice of the P+1 vector. With a pager, demoted partitions answer
    from their cached blob digests instead of the (cleared) device
    slices, so the stitched vector still describes the logical state."""
    owned = plan.owned_parts(shard)
    if pager is not None and pager.has_cold():
        return pager.digest_entries_for(state, owned)
    return pt.digest_entries(state, plan.P, owned)


def stitch_digests(plan: Any, entries: Dict[int, int]) -> np.ndarray:
    """Reassemble per-shard digest slices into the full ``uint32[P+1]``
    vector. Every partition must be covered exactly once (the ownership
    property the plan guarantees); a gap is a bug, not a degraded mode."""
    vec = np.zeros(plan.P + 1, np.uint32)
    seen = set()
    for part, crc in entries.items():
        p = int(part)
        if p in seen:
            raise ValueError(f"partition {p} stitched twice")
        seen.add(p)
        vec[p] = np.uint32(int(crc) & 0xFFFFFFFF)
    missing = [p for p in range(plan.P + 1) if p not in seen]
    if missing:
        raise ValueError(f"digest stitch missing partitions {missing}")
    return vec


def sharded_digest_vector(
    state: Any, plan: Any, metrics: Optional[Any] = None,
    pager: Optional[Any] = None,
) -> np.ndarray:
    """The full digest vector, produced shard by shard and stitched —
    bitwise equal to `core.partition.state_digests(state, P)` of the
    logical (pager-reassembled) state."""
    entries: Dict[int, int] = {}
    for s in range(plan.n_key):
        entries.update(shard_digest_entries(state, plan, s, pager=pager))
        if metrics is not None:
            metrics.count("mesh.shard_digest_slices")
    return stitch_digests(plan, entries)


def group_parts_by_shard(
    plan: Any, parts: Iterable[int]
) -> List[Tuple[int, List[int]]]:
    """[(shard, [parts…])…] in shard order — the fetch schedule for a
    divergent set: each tuple is one shard-local slice of the repair."""
    by: Dict[int, List[int]] = {}
    for p in parts:
        by.setdefault(plan.shard_of(int(p)), []).append(int(p))
    return [(s, sorted(by[s])) for s in sorted(by)]


def shard_psnap_blobs(
    name: str, state: Any, seq: int, dense: Any, plan: Any, shard: int,
    parts: Optional[Iterable[int]] = None, pager: Optional[Any] = None,
) -> List[Tuple[int, bytes]]:
    """[(part, CCPT blob)…] for the owned partitions of `shard` (or the
    subset `parts` ∩ owned). Same encode path as the unsharded anchor
    (`restrict_psnap` → `dumps_dense` → `encode_psnap_blob`), so the
    blobs are byte-identical to the whole-producer's. With a pager,
    demoted partitions are served straight from their stored payloads
    (transfer format is storage format — no hydration to publish)."""
    from ..core import serial

    owned = set(plan.owned_parts(shard))
    todo = sorted(owned if parts is None else owned & {int(p) for p in parts})
    out = []
    for part in todo:
        if pager is not None:
            out.append((part, pager.psnap_blob(state, seq, part)))
            continue
        payload = serial.dumps_dense(
            f"{name}_psnap", pt.restrict_psnap(dense, state, part, plan.P)
        )
        out.append((part, pt.encode_psnap_blob(seq, part, payload)))
    return out


# -- resharded snapshot ingest (mesh shape A -> B) ---------------------------


def reshard_state(state: Any, plan: Any) -> Any:
    """Re-pin a state pytree onto `plan`'s device layout — the ingest
    half of heterogeneous-fleet interop: a snapshot produced under any
    mesh shape (or none) lands on the local shape with one device_put
    per drifted leaf."""
    return plan.ensure_placed(state)


def ingest_snapshot(
    dense: Any, state: Any, fetched: Any, plan: Any,
    metrics: Optional[Any] = None,
) -> Any:
    """Join a fetched whole snapshot into the local state and reshard
    the result onto the local plan. `fetched` may come from an
    unsharded worker or a slice with a different mesh shape — the join
    is layout-blind, and the re-pin restores the local layout."""
    from ..core import batch_merge

    merged = batch_merge.merge_into(dense.merge, state, fetched)
    if metrics is not None:
        metrics.count("mesh.resharded_ingests")
    return reshard_state(merged, plan)
