"""mesh/: device-sharded elastic workers (the promoted multichip dryrun).

Partitions (core/partition.py) pinned to key-axis shards of a (dc, key)
device mesh (`plan.MeshPlan`), intra-slice reconciliation as batched
ICI JOIN collectives (`reduce.ici_reduce`), cross-slice anti-entropy
through the existing gossip plane (`gossip`). Armed by `CCRDT_MESH=1`
on a multi-device backend; otherwise every caller takes today's exact
single-device path — `install_from_env` returns None and nothing else
in the worker changes (the zero-cost default the tests pin
bit-identically).
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .plan import MeshPlan  # noqa: F401
from . import gossip, reduce  # noqa: F401
from .reduce import ici_reduce, psum_reduce, supports, try_ici_reduce  # noqa: F401

ENV_FLAG = "CCRDT_MESH"


def enabled(override: Optional[bool] = None) -> bool:
    """True when mesh sharding should arm: explicit override, else
    `CCRDT_MESH=1` — and, either way, only on a multi-device backend
    (a 1-device mesh is the single-device path; arming it would only
    add dispatch overhead for bit-identical results)."""
    if override is None:
        if os.environ.get(ENV_FLAG, "0") != "1":
            return False
    elif not override:
        return False
    import jax

    return len(jax.devices()) > 1


def install_from_env(
    dense: Any,
    partitions: Optional[int] = None,
    override: Optional[bool] = None,
    metrics: Optional[Any] = None,
) -> Optional[MeshPlan]:
    """The worker's single mesh entry point: a ready `MeshPlan` when the
    mesh should arm for this engine, None otherwise (single device,
    `CCRDT_MESH` unset, or a MONOID engine the JOIN reduce excludes)."""
    if not enabled(override) or not supports(dense):
        return None
    plan = MeshPlan.from_env(partitions=partitions)
    if metrics is not None:
        plan.export_gauges(metrics)
    return plan
