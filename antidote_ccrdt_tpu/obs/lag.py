"""Replication-lag tracking: how far behind is each peer's delta stream?

Delta gossip gives every replica a natural per-origin progress axis: the
publisher's delta sequence number. Each worker's sweep loop already
maintains two views of that axis per peer —

* the PUBLISHED watermark: the highest delta seq visible on the
  transport for that origin (the tip of what they have shipped), and
* the APPLIED cursor: the highest seq this process has merged
  (`sweep_deltas`' per-peer cursor).

`LagTracker` turns the pair into convergence lag in both units the
operator cares about:

* **ops**: `published - applied` — how many deltas of theirs we have
  not yet merged;
* **seconds**: age of the oldest unapplied seq, measured from when WE
  first saw it published (single-clock, so cross-host clock skew cannot
  manufacture lag);
* **staleness**: seconds since the peer last showed ANY progress
  evidence (a new published watermark or an apply) on OUR monotonic
  clock. Lag can read zero while a peer is silently wedged — caught up,
  then stopped publishing; staleness is the signal that catches that,
  and it is monotonic-clock-based so a wall-clock step (NTP slew,
  manual reset) cannot fake or hide a stall.

Peer death mid-window is explicit: `drop(peer)` freezes-and-forgets a
DEAD peer so its stale watermark stops inflating fleet lag (SWIM's DEAD
verdict, not silence, is the trigger — a slow peer still counts).

The fleet-wide `digest_agreement` probe answers the other convergence
question — "do we all hold the same state?" — by comparing per-member
payload digests (crc32 over the snapshot bytes after the 8-byte header,
the same digest `elastic_demo` verdicts use) and reporting the disagreeing
partitions, if any.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple


class LagTracker:
    """Per-peer delta-seq watermark vs applied-cursor lag, ops + seconds.

    Not thread-safe by design: it is fed from the single sweep loop of
    one worker (the same thread that owns the delta cursors)."""

    def __init__(
        self,
        member: str,
        clock: Callable[[], float] = time.time,
        mono: Callable[[], float] = time.monotonic,
    ):
        self.member = member
        self._clock = clock
        self._mono = mono  # staleness clock; injectable for tests
        self._published: Dict[str, int] = {}   # peer -> highest seq seen shipped
        self._applied: Dict[str, int] = {}     # peer -> highest seq merged here
        # peer -> {seq: first-seen t} for seqs published but not yet applied;
        # bounded: entries leave as soon as the applied cursor passes them.
        self._pending: Dict[str, Dict[int, float]] = {}
        # peer -> monotonic stamp of the last progress evidence (publish
        # watermark advance or apply) — the staleness baseline.
        self._last_update: Dict[str, float] = {}

    # -- feeding ------------------------------------------------------------

    def observe_published(self, peer: str, seq: int) -> None:
        """The transport shows `peer` has shipped deltas up through `seq`.
        Gaps are fine (anchors skip seqs): every seq in (old, seq] is
        stamped now so lag-seconds starts from first sighting."""
        if peer == self.member:
            return
        old = self._published.get(peer, -1)
        if seq <= old:
            return
        self._published[peer] = seq
        self._last_update[peer] = self._mono()
        pend = self._pending.setdefault(peer, {})
        now = self._clock()
        lo = max(old, self._applied.get(peer, -1))
        for s in range(lo + 1, seq + 1):
            pend.setdefault(s, now)

    def observe_applied(self, peer: str, seq: int) -> None:
        """This process has merged `peer`'s deltas up through `seq`
        (a full-snapshot adoption counts: pass the snapshot's seq)."""
        if peer == self.member:
            return
        old = self._applied.get(peer, -1)
        if seq <= old:
            return
        self._applied[peer] = seq
        self._last_update[peer] = self._mono()
        # published can never trail applied (an applied delta was shipped)
        if seq > self._published.get(peer, -1):
            self._published[peer] = seq
        pend = self._pending.get(peer)
        if pend:
            for s in [s for s in pend if s <= seq]:
                del pend[s]

    def drop(self, peer: str) -> None:
        """Forget a DEAD peer: its frozen watermark must not read as
        ever-growing lag. Re-observing the peer later re-creates it."""
        self._published.pop(peer, None)
        self._applied.pop(peer, None)
        self._pending.pop(peer, None)
        self._last_update.pop(peer, None)

    # -- reporting ----------------------------------------------------------

    def lag(self, peer: str) -> Tuple[int, float]:
        """(lag_ops, lag_seconds) for one peer; (0, 0.0) when caught up."""
        ops = max(0, self._published.get(peer, -1) - self._applied.get(peer, -1))
        pend = self._pending.get(peer)
        secs = (self._clock() - min(pend.values())) if pend else 0.0
        return ops, max(0.0, secs)

    def staleness(self, peer: str) -> float:
        """Seconds since `peer` last showed progress evidence (watermark
        advance or apply), on this process's monotonic clock. A peer that
        is caught up but has gone silent reads increasingly stale here
        while its lag reads zero — the wedged-peer signal. 0.0 for a
        peer never observed."""
        stamp = self._last_update.get(peer)
        if stamp is None:
            return 0.0
        return max(0.0, self._mono() - stamp)

    def report(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for peer in sorted(self._published):
            ops, secs = self.lag(peer)
            out[peer] = {
                "published": self._published.get(peer, -1),
                "applied": self._applied.get(peer, -1),
                "lag_ops": ops,
                "lag_s": round(secs, 6),
                "staleness_s": round(self.staleness(peer), 6),
            }
        return out

    def export_to(self, metrics: Any) -> None:
        """Mirror the current lag view into `Metrics` gauges so the
        Prometheus exporter picks it up: ``lag.<peer>.ops`` /
        ``lag.<peer>.seconds`` / ``lag.<peer>.staleness_seconds`` plus
        fleet maxima."""
        rep = self.report()
        worst_ops, worst_s, worst_stale = 0, 0.0, 0.0
        for peer, r in rep.items():
            metrics.set(f"lag.{peer}.ops", float(r["lag_ops"]))
            metrics.set(f"lag.{peer}.seconds", float(r["lag_s"]))
            metrics.set(
                f"lag.{peer}.staleness_seconds", float(r["staleness_s"])
            )
            worst_ops = max(worst_ops, r["lag_ops"])
            worst_s = max(worst_s, r["lag_s"])
            worst_stale = max(worst_stale, r["staleness_s"])
        metrics.set("lag.max_ops", float(worst_ops))
        metrics.set("lag.max_seconds", float(worst_s))
        metrics.set("lag.max_staleness_seconds", float(worst_stale))


# -- fleet digest agreement --------------------------------------------------


def payload_digest(blob: bytes) -> int:
    """crc32 over a gossip snapshot's payload (past the 8-byte length
    header) — the digest the drill verdicts already compare."""
    return zlib.crc32(blob[8:]) & 0xFFFFFFFF


def digest_agreement(
    digests: Dict[str, Any]
) -> Dict[str, Any]:
    """Fleet-wide convergence probe over per-member digests (None =
    member unreadable). Returns agreement plus the disagreeing groups so
    an operator can see WHICH members split, not just that they did.

    Values may be scalar whole-instance digests (legacy) or per-
    partition digest VECTORS (`core.partition.state_digests`). With
    vectors the report gains `divergent_parts`: the partition indices on
    which any two live members disagree — the exact set partial
    anti-entropy will transfer — so the probe answers "how big is the
    repair" and not just "are we split"."""
    groups: Dict[Any, List[str]] = {}
    vectors = False
    for m, d in sorted(digests.items()):
        if d is None:
            key: Any = None
        elif isinstance(d, (list, tuple)) or hasattr(d, "__len__"):
            key = tuple(int(x) for x in d)
            vectors = True
        else:
            key = int(d)
        groups.setdefault(key, []).append(m)
    live = {d: ms for d, ms in groups.items() if d is not None}

    def _label(d: Any) -> str:
        if isinstance(d, tuple):
            return "-".join("%08x" % e for e in d)
        return "%08x" % d

    out = {
        "agree": len(live) == 1 and len(groups) == len(live),
        "n_members": len(digests),
        "n_digests": len(live),
        "groups": {_label(d): ms for d, ms in live.items()},
        "unreadable": groups.get(None, []),
    }
    if vectors:
        vecs = [d for d in live if isinstance(d, tuple)]
        divergent: set = set()
        if vecs:
            width = max(len(v) for v in vecs)
            ref = vecs[0]
            for v in vecs[1:]:
                if len(v) != len(ref):
                    divergent.update(range(width))
                    break
                divergent.update(
                    i for i in range(width) if v[i] != ref[i]
                )
        if any(not isinstance(d, tuple) for d in live):
            # A scalar mixed in with vectors (mixed-version fleet):
            # incomparable shapes — every partition is suspect.
            divergent.update(range(max((len(v) for v in vecs), default=0)))
        out["divergent_parts"] = sorted(divergent)
    return out
