"""XLA hot-path profiler: what the batched dispatch actually costs.

The whole point of the TPU rebuild is that CRDT update/merge work runs
as batched XLA dispatches (`core.batch_merge`, the elastic sweeps) —
yet until now nothing measured them. This module wraps those dispatch
sites and feeds `Metrics` with:

* ``profile.dispatch.<site>`` latency histograms — wall time of each
  dispatch, split into ``profile.compile.<site>`` (first trace of a new
  shape: jit cache grew) vs ``profile.execute.<site>`` (cache hit), so
  a scrape distinguishes steady-state throughput from recompile storms;
* ``profile.jit_hits`` / ``profile.jit_misses`` counters — cache-size
  deltas around each dispatch (a miss means XLA just compiled);
* ``profile.h2d_bytes`` — bytes of host-resident operands handed to a
  dispatch (the host→device transfer a TPU step pays for).

Overhead discipline copies `utils.faults` exactly: a module-level
``ACTIVE`` bool that call sites check FIRST (``if profile.ACTIVE:``), so
the disabled path costs one global load and a branch — no function
call, no context-manager allocation, nothing on the per-merge hot path.
Enable per-process with `install(metrics)` / `installed()` /
`install_from_env` (``CCRDT_PROFILE=1``, same supervisor->worker env
propagation as ``CCRDT_FAULTS``/``CCRDT_OBS_DIR``/``CCRDT_HTTP_PORT``).

Since ISSUE 19 the compile/execute classification itself lives in
`obs/devprof.py` (the device observatory): `dispatch` delegates to
:func:`devprof.observe`, which samples the jit cache ONCE and feeds
both the legacy ``profile.*`` family (names unchanged for scrape
compat — the parity test pins them) and the devprof compile events.
One source of truth, no double counting.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterable, Optional

from ..utils.metrics import Metrics
from . import devprof
from .devprof import _cache_size, _leaf_nbytes  # noqa: F401 — re-exported

ENV_FLAG = "CCRDT_PROFILE"

# Hot-path gate — call sites must check `if profile.ACTIVE:` before
# touching anything else in this module.
ACTIVE = False

_METRICS: Optional[Metrics] = None


def install(metrics: Metrics) -> None:
    """Route profiler samples into `metrics` and flip the gate on."""
    global ACTIVE, _METRICS
    _METRICS = metrics
    ACTIVE = True


def uninstall() -> None:
    global ACTIVE, _METRICS
    ACTIVE = False
    _METRICS = None


@contextlib.contextmanager
def installed(metrics: Metrics):
    """Scoped enable for tests: always restores the previous state."""
    prev = (ACTIVE, _METRICS)
    install(metrics)
    try:
        yield metrics
    finally:
        uninstall()
        if prev[0]:
            install(prev[1])


def install_from_env(
    metrics: Metrics, env: Optional[dict] = None
) -> bool:
    """Enable iff ``CCRDT_PROFILE`` is set to a truthy value ("1",
    "true", ...). Returns whether profiling was armed."""
    raw = (env if env is not None else os.environ).get(ENV_FLAG, "")
    if raw.strip().lower() not in ("1", "true", "yes", "on"):
        return False
    install(metrics)
    return True


# -- the dispatch wrapper ---------------------------------------------------
#
# The cache-introspection helpers (`_cache_size`, `_leaf_nbytes`) moved
# to obs/devprof.py and are re-exported above unchanged.


@contextlib.contextmanager
def dispatch(
    name: str,
    fn: Any = None,
    operands: Iterable[Any] = (),
    donation: str = "",
):
    """Time one dispatch of `name`. Guard the call site with
    ``if profile.ACTIVE or devprof.ACTIVE:`` — this context manager
    assumes at least one plane is on (it silently no-ops if raced with
    `uninstall`).

    Thin delegation to `devprof.observe`: the observatory samples the
    jit cache once, classifies compile vs execute, and — when profiling
    is installed — emits the legacy ``profile.dispatch.<name>`` /
    ``profile.compile.<name>`` / ``profile.execute.<name>`` histograms,
    ``profile.jit_hits``/``profile.jit_misses`` counters, and
    ``profile.h2d_bytes`` exactly as before."""
    m = _METRICS
    if m is None and not devprof.ACTIVE:
        yield
        return
    with devprof.observe(
        name, fn=fn, operands=operands, donation=donation, profile_metrics=m
    ):
        yield
