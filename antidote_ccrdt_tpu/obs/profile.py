"""XLA hot-path profiler: what the batched dispatch actually costs.

The whole point of the TPU rebuild is that CRDT update/merge work runs
as batched XLA dispatches (`core.batch_merge`, the elastic sweeps) —
yet until now nothing measured them. This module wraps those dispatch
sites and feeds `Metrics` with:

* ``profile.dispatch.<site>`` latency histograms — wall time of each
  dispatch, split into ``profile.compile.<site>`` (first trace of a new
  shape: jit cache grew) vs ``profile.execute.<site>`` (cache hit), so
  a scrape distinguishes steady-state throughput from recompile storms;
* ``profile.jit_hits`` / ``profile.jit_misses`` counters — cache-size
  deltas around each dispatch (a miss means XLA just compiled);
* ``profile.h2d_bytes`` — bytes of host-resident operands handed to a
  dispatch (the host→device transfer a TPU step pays for).

Overhead discipline copies `utils.faults` exactly: a module-level
``ACTIVE`` bool that call sites check FIRST (``if profile.ACTIVE:``), so
the disabled path costs one global load and a branch — no function
call, no context-manager allocation, nothing on the per-merge hot path.
Enable per-process with `install(metrics)` / `installed()` /
`install_from_env` (``CCRDT_PROFILE=1``, same supervisor->worker env
propagation as ``CCRDT_FAULTS``/``CCRDT_OBS_DIR``/``CCRDT_HTTP_PORT``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Iterable, Optional

from ..utils.metrics import Metrics

ENV_FLAG = "CCRDT_PROFILE"

# Hot-path gate — call sites must check `if profile.ACTIVE:` before
# touching anything else in this module.
ACTIVE = False

_METRICS: Optional[Metrics] = None


def install(metrics: Metrics) -> None:
    """Route profiler samples into `metrics` and flip the gate on."""
    global ACTIVE, _METRICS
    _METRICS = metrics
    ACTIVE = True


def uninstall() -> None:
    global ACTIVE, _METRICS
    ACTIVE = False
    _METRICS = None


@contextlib.contextmanager
def installed(metrics: Metrics):
    """Scoped enable for tests: always restores the previous state."""
    prev = (ACTIVE, _METRICS)
    install(metrics)
    try:
        yield metrics
    finally:
        uninstall()
        if prev[0]:
            install(prev[1])


def install_from_env(
    metrics: Metrics, env: Optional[dict] = None
) -> bool:
    """Enable iff ``CCRDT_PROFILE`` is set to a truthy value ("1",
    "true", ...). Returns whether profiling was armed."""
    raw = (env if env is not None else os.environ).get(ENV_FLAG, "")
    if raw.strip().lower() not in ("1", "true", "yes", "on"):
        return False
    install(metrics)
    return True


# -- introspection helpers ----------------------------------------------------


def _cache_size(fn: Any) -> Optional[int]:
    """Size of a jitted callable's compilation cache, or None when the
    callable doesn't expose one (plain functions, partials, older JAX).
    Defensive on purpose: profiling must never break a dispatch."""
    try:
        sizer = fn._cache_size  # jax.jit-wrapped callables
    except AttributeError:
        return None
    try:
        return int(sizer())
    except Exception:  # noqa: BLE001 — any introspection failure = unknown
        return None


def _leaf_nbytes(operands: Iterable[Any]) -> int:
    """Total .nbytes across array leaves of `operands`. Dispatch sites
    pass registered pytrees (the dense engine states), so flattening
    goes through jax when available; without jax, plain containers
    still traverse."""
    try:
        import jax

        leaves = jax.tree.leaves(list(operands))
    except Exception:  # noqa: BLE001 — profiling must never break a dispatch
        leaves = []
        stack = list(operands)
        while stack:
            x = stack.pop()
            if isinstance(x, (tuple, list)):
                stack.extend(x)
            elif isinstance(x, dict):
                stack.extend(x.values())
            else:
                leaves.append(x)
    total = 0
    for x in leaves:
        nb = getattr(x, "nbytes", None)
        if isinstance(nb, int):
            total += nb
    return total


@contextlib.contextmanager
def dispatch(name: str, fn: Any = None, operands: Iterable[Any] = ()):
    """Time one dispatch of `name`. Guard the call site with
    ``if profile.ACTIVE:`` — this context manager assumes profiling is
    on (it records into the installed registry, or silently no-ops if
    raced with `uninstall`).

    With `fn` (the jitted callable), the jit cache size is sampled
    before/after to classify the dispatch as compile (cache grew) or
    execute, and counted as a jit hit/miss. With `operands`, host->
    device bytes are accumulated from array leaves."""
    m = _METRICS
    if m is None:
        yield
        return
    before = _cache_size(fn) if fn is not None else None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _record(m, f"profile.dispatch.{name}", dt)
        if before is not None:
            after = _cache_size(fn)
            if after is not None and after > before:
                m.count("profile.jit_misses")
                _record(m, f"profile.compile.{name}", dt)
            else:
                m.count("profile.jit_hits")
                _record(m, f"profile.execute.{name}", dt)
        nbytes = _leaf_nbytes(operands)
        if nbytes:
            m.count("profile.h2d_bytes", nbytes)


def _record(m: Metrics, name: str, dt: float) -> None:
    m.merge({"counters": {}, "latencies": {name: [dt]}})
