"""The certified-convergence plane: lattice-law auditing, flight-log
replay certification, and a live divergence watchdog.

Three verdict surfaces over the same machinery the fleet already runs:

* **LawChecker** — machine-checks merge commutativity/associativity/
  idempotence and the delta-composition law for every op type on the
  registry (batched on-device; kernels + reachable-state fixtures live
  in `ops/laws.py`). A type without a registered fixture is reported as
  unaudited, never silently skipped.

* **certify / verify_certificate** — replay certification of a real
  run: consume the ``(origin, dseq)`` flight-recorder spill
  (`obs.events.scan_dir`), audit causal delivery per process
  incarnation, reconcile published-vs-covered op counts per
  (applier, origin) pair, compare per-worker partition-digest vectors
  (`obs.lag.digest_agreement`) and optionally a sequential reference —
  then emit a signed-digest *convergence certificate* (sha256 over the
  canonical JSON body), or a minimal counterexample slice naming the
  divergent partitions when certification fails.

* **DivergenceWatchdog** — rides the per-partition digest vectors the
  partial anti-entropy tier already exchanges (`PartialAntiEntropy`
  feeds `observe_peer` on every digest fetch): per-peer divergence
  state machine (ok → diverged → wedged), time-to-agreement samples,
  and a wedged-divergence alarm when digests disagree AND no repair
  progress lands within the bound. Gauges/counters ride the ordinary
  `utils.metrics.Metrics` object, so all three scrape surfaces (HTTP,
  in-band frame, bridge op) export them for free; `health_fields()`
  extends ``/healthz`` via the never-fatal `health_extra` probe.

Module discipline: top-level imports are stdlib + the stdlib-only obs
siblings, so `scripts/ccrdt_trace.py` (and any cold CLI) can import the
causal auditor without paying for jax; `LawChecker.run` pulls
`ops.laws` lazily.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import events as obs_events
from .lag import digest_agreement

CERTIFICATE_KIND = "ccrdt-convergence-certificate"
CERTIFICATE_VERSION = 1


# -- causal apply-order audit ------------------------------------------------
# Canonical home of the auditor scripts/ccrdt_trace.py `audit` exposes
# (the CLI imports it from here); kept stdlib-only on purpose.


def audit_apply_order(
    logs: Dict[str, List[Dict[str, Any]]]
) -> List[Dict[str, Any]]:
    """Causal-order violations in the apply streams, one row each.

    Within ONE flight log (= one process incarnation) the `delta.apply`
    events for a given origin must carry contiguous ascending dseqs:
    `sweep_deltas` only emits the event after advancing its cursor by
    exactly one; a `snap.apply` at step S or a partial resync
    (`psnap.resync` at dig_seq S) are the only legitimate jumps (the
    cursor resumes from max(cur, S)). The baseline is the
    FIRST dseq seen in the log, not 0 — the ring truncates and a worker
    may join mid-stream, so absolute position proves nothing; ordering
    within the log does. Events replay in the recorder's own `seq`
    order (per-process lamport axis), so wall-clock skew cannot
    manufacture violations. A `gap-skip` (dseq jumped past cur+1 with no
    snapshot) means ops were silently lost; a `double-apply` (dseq at or
    below the cursor) means the cursor went backwards. Different
    incarnations of the same member audit independently: recovery
    legitimately re-applies."""
    violations: List[Dict[str, Any]] = []
    for fname, evs in sorted(logs.items()):
        applier = next(
            (str(e["member"]) for e in evs if e.get("member")), fname
        )
        ordered = sorted(
            (
                e for e in evs
                if e.get("kind") in ("delta.apply", "snap.apply",
                                     "psnap.resync")
                and e.get("origin") is not None
            ),
            key=lambda e: int(e.get("seq", 0)),
        )
        cur: Dict[str, int] = {}
        for ev in ordered:
            origin = str(ev["origin"])
            if ev["kind"] in ("snap.apply", "psnap.resync"):
                s = ev.get("step") if ev["kind"] == "snap.apply" \
                    else ev.get("dig_seq")
                if s is not None:
                    prev = cur.get(origin)
                    cur[origin] = int(s) if prev is None else max(prev, int(s))
                continue
            d = ev.get("dseq")
            if d is None:
                continue
            d = int(d)
            # Compacted range frames apply as one event covering
            # [lo..dseq]; chaining holds iff the frame's LOW edge meets
            # the cursor (overlap below it is idempotent re-coverage,
            # not a violation). Legacy events carry no lo: lo == dseq.
            lo = int(ev.get("lo", d))
            prev = cur.get(origin)
            if prev is None or (lo <= prev + 1 and d > prev):
                cur[origin] = d
                continue
            violations.append(
                {
                    "log": fname,
                    "applier": applier,
                    "origin": origin,
                    "kind": "double-apply" if d <= prev else "gap-skip",
                    "prev_dseq": prev,
                    "dseq": d,
                    "seq": int(ev.get("seq", -1)),
                }
            )
            cur[origin] = max(prev, d)
    return violations


# -- op-count reconciliation -------------------------------------------------


def reconcile_op_counts(
    logs: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Published-vs-covered reconciliation over a QUIESCED run's spill.

    For every origin with `delta.publish` events, each OTHER member's
    final coverage — the max of its applied dseqs, `snap.apply` steps,
    and partial-resync digest seqs (all on the publisher's one seq axis)
    — must reach the origin's highest published dseq. A member below
    that watermark at end of run has silently lost ops (the causal audit
    catches mis-ordering; this catches truncation). Members are judged
    on the union of their incarnations, so a recovered worker's
    coverage carries across its restart.

    A member DEAD at quiesce — its last incarnation is a crash dump (no
    `proc.exit`, see obs/events.py) with no successor — is excluded
    from the applier side and reported in `dead_members`: its final
    state never existed, so "covered through" is not defined for it.
    Its PUBLISHED stream stays fully audited — the survivors must still
    cover everything it shipped before dying (replica adoption), which
    is exactly the loss this check hunts. The exclusion activates only
    when some log carries the proc lifecycle discipline (a `proc.exit`
    somewhere); in-process sim spills without lifecycle events keep
    every member on the hook."""
    published: Dict[str, List[int]] = {}
    for evs in logs.values():
        for e in evs:
            if e.get("kind") == "delta.publish" and e.get("dseq") is not None:
                o = str(e.get("origin") or e.get("member") or "?")
                published.setdefault(o, []).append(int(e["dseq"]))

    lifecycle = any(
        e.get("kind") == "proc.exit" for evs in logs.values() for e in evs
    )
    incarnations: Dict[str, List[Tuple[float, bool]]] = {}
    for fname, evs in sorted(logs.items()):
        member = next(
            (str(e["member"]) for e in evs if e.get("member")), fname
        )
        start_t = next(
            (
                float(e.get("t", 0.0))
                for e in evs
                if e.get("kind") == "proc.start"
            ),
            min((float(e.get("t", 0.0)) for e in evs), default=0.0),
        )
        exited = any(e.get("kind") == "proc.exit" for e in evs)
        incarnations.setdefault(member, []).append((start_t, exited))
    dead = {
        m for m, incs in incarnations.items()
        if lifecycle and not sorted(incs)[-1][1]
    }

    coverage: Dict[str, Dict[str, int]] = {}
    applied_n: Dict[str, Dict[str, int]] = {}
    for fname, evs in sorted(logs.items()):
        member = next(
            (str(e["member"]) for e in evs if e.get("member")), fname
        )
        cov = coverage.setdefault(member, {})
        nap = applied_n.setdefault(member, {})
        for e in sorted(evs, key=lambda e: int(e.get("seq", 0))):
            kind, origin = e.get("kind"), e.get("origin")
            if origin is None:
                continue
            o = str(origin)
            if kind == "delta.apply" and e.get("dseq") is not None:
                cov[o] = max(cov.get(o, -1), int(e["dseq"]))
                nap[o] = nap.get(o, 0) + 1
            elif kind == "snap.apply" and e.get("step") is not None:
                cov[o] = max(cov.get(o, -1), int(e["step"]))
            elif kind == "psnap.resync" and e.get("dig_seq") is not None:
                cov[o] = max(cov.get(o, -1), int(e["dig_seq"]))

    uncovered: List[Dict[str, Any]] = []
    pairs = 0
    for origin, seqs in sorted(published.items()):
        want = max(seqs)
        for member, cov in sorted(coverage.items()):
            if member == origin or member in dead:
                continue
            pairs += 1
            have = cov.get(origin, -1)
            if have < want:
                uncovered.append(
                    {
                        "applier": member,
                        "origin": origin,
                        "covered_through": have,
                        "published_through": want,
                        "applied": applied_n.get(member, {}).get(origin, 0),
                    }
                )
    return {
        "ok": not uncovered,
        "origins": {
            o: {"published": len(s), "max_dseq": max(s)}
            for o, s in sorted(published.items())
        },
        "pairs_checked": pairs,
        "dead_members": sorted(dead),
        "uncovered": uncovered,
    }


# -- durability-watermark reconciliation -------------------------------------


def reconcile_durability(
    logs: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Published-vs-durable reconciliation on the `wal.*` flight-event
    axis (PR 11 async durability: gossip may ship a delta BEFORE its
    fsync, so a crash can lose appended-but-unacked records — legal
    ONLY if the loss is visible here and covered by a successor).

    Per crashed incarnation (a flight log with no `proc.exit`): its
    exposure is S = max `wal.append` wseq — everything it might have
    published — against W = its last `wal.durable` watermark. S > W
    means the crash could have dropped (W, S]. That exposure is COVERED
    iff a later incarnation of the same member (ordered by `proc.start`
    time) re-derives the range: its own `wal.append` trail reaches S
    (it resumed at W+1 and re-ran the steps) or its `wal.recover`
    found last_step >= S (the tail survived — group-mode under-claims).
    Anything else is unaudited loss and fails the certificate with a
    counterexample naming the uncovered seq range.

    The check activates only when some log carries `wal.durable`
    events (group/async modes emit them per flush; a sync-mode or
    WAL-less run has no watermark discipline to audit)."""
    incs: List[Any] = []
    for fname, evs in sorted(logs.items()):
        member = next(
            (str(e["member"]) for e in evs if e.get("member")), fname
        )
        start_t = next(
            (
                float(e.get("t", 0.0))
                for e in evs
                if e.get("kind") == "proc.start"
            ),
            min((float(e.get("t", 0.0)) for e in evs), default=0.0),
        )
        incs.append((member, start_t, fname, evs))
    active = any(
        e.get("kind") == "wal.durable" for _, _, _, evs in incs for e in evs
    )
    exposed: List[Dict[str, Any]] = []
    covered = 0
    checked = 0
    for member, t0, fname, evs in incs:
        if any(e.get("kind") == "proc.exit" for e in evs):
            continue  # clean exit: close() flushed, nothing exposed
        appends = [
            int(e["wseq"])
            for e in evs
            if e.get("kind") == "wal.append" and e.get("wseq") is not None
        ]
        if not appends:
            continue
        checked += 1
        s_hi = max(appends)
        w = max(
            (
                int(e["through"])
                for e in evs
                if e.get("kind") == "wal.durable"
                and e.get("through") is not None
            ),
            default=-1,
        )
        if s_hi <= w:
            continue  # everything appended was acked durable
        ok = False
        for m2, t2, f2, evs2 in incs:
            if m2 != member or f2 == fname or t2 <= t0:
                continue
            a2 = max(
                (
                    int(e["wseq"])
                    for e in evs2
                    if e.get("kind") == "wal.append"
                    and e.get("wseq") is not None
                ),
                default=-1,
            )
            r2 = max(
                (
                    int(e.get("last_step", -1))
                    for e in evs2
                    if e.get("kind") == "wal.recover"
                ),
                default=-1,
            )
            if a2 >= s_hi or r2 >= s_hi:
                ok = True
                break
        if ok:
            covered += 1
        else:
            exposed.append(
                {
                    "member": member,
                    "durable_through": w,
                    "exposed_through": s_hi,
                    "uncovered": [w + 1, s_hi],
                }
            )
    return {
        "ok": not exposed,
        "active": active,
        "crashed_checked": checked,
        "covered": covered,
        "exposed": exposed,
    }


# -- convergence certificates ------------------------------------------------


def _digest_key(d: Any) -> Any:
    if d is None:
        return None
    if isinstance(d, (list, tuple)) or hasattr(d, "__len__"):
        return tuple(int(x) for x in d)
    return int(d)


def _digest_label(d: Any) -> Optional[str]:
    k = _digest_key(d)
    if k is None:
        return None
    if isinstance(k, tuple):
        return "-".join("%08x" % e for e in k)
    return "%08x" % k


def _canonical(body: Dict[str, Any]) -> bytes:
    return json.dumps(
        body, sort_keys=True, separators=(",", ":"), default=str
    ).encode()


def sign_certificate(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp `signature` = sha256 over the canonical JSON of everything
    else. Not cryptographic authentication (no key) — a tamper-evident
    content digest, the same trust model as the repo's crc32 state
    digests but collision-resistant enough to archive."""
    body = {k: v for k, v in doc.items() if k != "signature"}
    doc["signature"] = hashlib.sha256(_canonical(body)).hexdigest()
    return doc


def verify_certificate(doc: Dict[str, Any]) -> bool:
    sig = doc.get("signature")
    if not isinstance(sig, str):
        return False
    body = {k: v for k, v in doc.items() if k != "signature"}
    return hashlib.sha256(_canonical(body)).hexdigest() == sig


def _counterexample(
    causal: List[Dict[str, Any]],
    recon: Dict[str, Any],
    agreement: Optional[Dict[str, Any]],
    reference: Optional[Dict[str, Any]],
    durability: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The minimal slice an operator needs to localize the failure:
    WHICH partitions split, WHICH member groups hold which digest, the
    first causal violations, the first uncovered (applier, origin)
    ranges, the first pre-fsync-loss exposures with their seq ranges."""
    out: Dict[str, Any] = {}
    if agreement is not None and not agreement.get("agree", True):
        out["divergent_parts"] = agreement.get("divergent_parts", [])
        out["digest_groups"] = agreement.get("groups", {})
    if reference is not None and not reference.get("ok", True):
        out["reference_mismatch"] = reference.get("mismatched", {})
    if causal:
        out["causal_violations"] = causal[:5]
    if recon.get("uncovered"):
        out["uncovered"] = recon["uncovered"][:5]
    if durability is not None and durability.get("exposed"):
        out["durability_exposures"] = durability["exposed"][:5]
    return out


def certify(
    obs_dir: Optional[str] = None,
    logs: Optional[Dict[str, List[Dict[str, Any]]]] = None,
    digests: Optional[Dict[str, Any]] = None,
    reference: Any = None,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Replay-certify a finished run into a signed convergence
    certificate.

    Inputs: the flight-log spill (`obs_dir` or a pre-scanned `logs`
    dict), per-worker final digests (scalar or per-partition vectors),
    and optionally the sequential-reference digest the fleet must match.
    The certificate's `ok` is the conjunction of every check it could
    run; a check with no evidence (no digests, no reference) is absent,
    not vacuously true. On failure the doc gains a `counterexample`
    slice naming the divergent partitions / members / seq ranges."""
    if logs is None:
        logs = obs_events.scan_dir(obs_dir) if obs_dir else {}
    causal = audit_apply_order(logs)
    recon = reconcile_op_counts(logs)
    durability = reconcile_durability(logs)
    agreement = digest_agreement(digests) if digests else None
    reference_section: Optional[Dict[str, Any]] = None
    if reference is not None and digests:
        ref_key = _digest_key(reference)
        mismatched = {
            m: _digest_label(d)
            for m, d in sorted(digests.items())
            if _digest_key(d) != ref_key
        }
        reference_section = {
            "ok": not mismatched,
            "reference": _digest_label(reference),
            "mismatched": mismatched,
        }

    checks: Dict[str, bool] = {
        "causal_delivery": not causal,
        "op_count_reconciliation": bool(recon["ok"]),
    }
    if agreement is not None:
        checks["partition_digest_agreement"] = bool(agreement["agree"])
    if reference_section is not None:
        checks["matches_reference"] = bool(reference_section["ok"])
    if durability["active"]:
        # Only when some log carries a durability watermark: a run
        # without group/async WAL has no published-vs-durable gap to
        # audit, and an absent check must stay absent, not vacuously
        # true.
        checks["durability_watermark"] = bool(durability["ok"])
    ok = all(checks.values())

    doc: Dict[str, Any] = {
        "kind": CERTIFICATE_KIND,
        "version": CERTIFICATE_VERSION,
        "t": round(time.time(), 3),
        "ok": ok,
        "checks": checks,
        "worker_digests": (
            {m: _digest_label(d) for m, d in sorted(digests.items())}
            if digests else {}
        ),
        "causal": {
            "ok": not causal,
            "n_violations": len(causal),
            "violations": causal[:16],
        },
        "reconciliation": recon,
        "durability": durability,
        "agreement": agreement,
        "reference": reference_section,
        "n_flight_logs": len(logs),
        "meta": meta or {},
    }
    if not ok:
        doc["counterexample"] = _counterexample(
            causal, recon, agreement, reference_section, durability
        )
    sign_certificate(doc)
    obs_events.emit(
        "audit.certificate", ok=ok,
        signature=doc["signature"][:16],
        divergent_parts=(
            doc.get("counterexample", {}).get("divergent_parts", [])
        ),
    )
    return doc


# -- session-guarantee certification -----------------------------------------


SESSION_CERTIFICATE_KIND = "ccrdt-session-certificate"
SESSION_CERTIFICATE_VERSION = 1


def certify_sessions(
    obs_dir: Optional[str] = None,
    logs: Optional[Dict[str, List[Dict[str, Any]]]] = None,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Replay the flight log's ``session.write`` / ``session.read``
    events and certify the read tier's two session guarantees — the
    replication-aware-spec replay idea of arxiv 2502.19967 applied to
    the session taxonomy of arxiv 2310.18220:

    * **read-your-writes**: every read in a session must be served with
      watermarks covering every (origin, wseq) the session wrote
      BEFORE it — replayed as a running per-origin write floor;
    * **monotonic-reads**: every read must cover the pointwise max of
      the watermarks every earlier read in the session observed.

    The floors are recomputed here independently from the raw events —
    the router's in-flight `require` stamps are NOT trusted (a router
    in ``session_mode="ignore"``, the deliberately-violating arm, still
    records truthful writes/reads, and this replay is what convicts it).
    Events are ordered per session by (log file, recorder seq): a
    session lives in one process, so the process-local recorder order
    IS its program order.

    Returns a signed certificate (`sign_certificate`); on violation,
    `ok` is False and `counterexample` names the minimal first offense
    per guarantee: session, peer, origin, and the [have, want] seq
    range."""
    if logs is None:
        logs = obs_events.scan_dir(obs_dir) if obs_dir else {}
    # Gather each session's events in replay order.
    per_session: Dict[str, List[Tuple[str, int, Dict[str, Any]]]] = {}
    for fname in sorted(logs):
        for ev in logs[fname]:
            k = ev.get("kind")
            if k not in ("session.write", "session.read"):
                continue
            sid = str(ev.get("session"))
            per_session.setdefault(sid, []).append(
                (fname, int(ev.get("seq", 0)), ev)
            )
    violations: List[Dict[str, Any]] = []
    n_reads = n_writes = 0
    for sid in sorted(per_session):
        evs = sorted(per_session[sid], key=lambda x: (x[0], x[1]))
        wfloor: Dict[str, int] = {}  # writes this session has seen land
        rfloor: Dict[str, int] = {}  # watermarks earlier reads observed
        for _f, _s, ev in evs:
            if ev["kind"] == "session.write":
                n_writes += 1
                o = str(ev.get("origin"))
                w = int(ev.get("wseq", -1))
                if w > wfloor.get(o, -1):
                    wfloor[o] = w
                continue
            n_reads += 1
            served = {
                str(o): int(s)
                for o, s in (ev.get("served") or {}).items()
            }
            checks = []
            if ev.get("rw", True):
                checks.append(("read_your_writes", wfloor))
            if ev.get("mono", True):
                checks.append(("monotonic_reads", rfloor))
            for guarantee, floor in checks:
                for o, want in floor.items():
                    have = int(served.get(o, -1))
                    if have < want:
                        violations.append({
                            "guarantee": guarantee,
                            "session": sid,
                            "peer": str(ev.get("peer")),
                            "origin": o,
                            "have": have,
                            "want": want,
                        })
            if ev.get("mono", True):
                for o, s in served.items():
                    if s > rfloor.get(o, -1):
                        rfloor[o] = s
    by_guarantee = {
        g: [v for v in violations if v["guarantee"] == g]
        for g in ("read_your_writes", "monotonic_reads")
    }
    checks = {g: not vs for g, vs in by_guarantee.items()}
    ok = all(checks.values())
    doc: Dict[str, Any] = {
        "kind": SESSION_CERTIFICATE_KIND,
        "version": SESSION_CERTIFICATE_VERSION,
        "t": round(time.time(), 3),
        "ok": ok,
        "checks": checks,
        "n_sessions": len(per_session),
        "n_reads": n_reads,
        "n_writes": n_writes,
        "n_violations": len(violations),
        "n_flight_logs": len(logs),
        "meta": meta or {},
    }
    if not ok:
        # The minimal counterexample: the FIRST violation per guarantee
        # (replay order), enough to name the offending token scope.
        doc["counterexample"] = {
            g: vs[0] for g, vs in by_guarantee.items() if vs
        }
        doc["violations"] = violations[:16]
    sign_certificate(doc)
    obs_events.emit(
        "audit.session_certificate", ok=ok,
        n_violations=len(violations),
        signature=doc["signature"][:16],
    )
    return doc


# -- write-ack durability certification --------------------------------------


WRITE_CERTIFICATE_KIND = "ccrdt-write-durability-certificate"
WRITE_CERTIFICATE_VERSION = 1


def certify_writes(
    obs_dir: Optional[str] = None,
    logs: Optional[Dict[str, List[Dict[str, Any]]]] = None,
    meta: Optional[Dict[str, Any]] = None,
    strict_exactly_once: bool = False,
) -> Dict[str, Any]:
    """Replay the flight log's ``ingest.ack`` events (what the write
    tier TOLD clients they hold) against the fleet's durability and
    replication evidence, and certify **zero acked-but-lost writes** —
    the write-path twin of `certify_sessions`, and the check the
    acceptance drill SIGKILLs a partition owner against.

    An ack at level ``durable`` or ``replicated_to_k`` for ``(origin,
    wseq)`` is a contract: the write must survive the origin's death.
    Coverage is recomputed here from raw events — the acking plane is
    NOT trusted (the deliberately-violating ack-before-fsync arm still
    records truthful ``wal.durable`` watermarks, and this replay is
    what convicts it). ``(origin, s)`` is covered iff any of:

    * every incarnation of `origin` exited cleanly (``proc.exit``:
      close() flushed, nothing was lost);
    * some incarnation of `origin` fsynced through s (``wal.durable``
      through >= s — the honest plane never acks ``durable`` before
      this watermark passes);
    * a restarted `origin` recovered its WAL tail through s
      (``wal.recover`` last_step >= s: the record survived on disk —
      `harness.wal.log_step` serialized the post-fold view, so the
      client write is inside it);
    * a SURVIVOR holds it: another member applied origin's delta/snap
      stream through s (``delta.apply`` dseq / ``snap.apply`` step /
      ``psnap.resync`` dig_seq for that origin) — the state outlives
      the owner in the fleet even if the owner's disk burned.

    Note what does NOT count: a later incarnation's own re-run
    ``wal.append`` trail (valid for the step-replay audit in
    `reconcile_durability`, but a re-run regenerates DRILL load, not
    client writes). ``applied``-level acks promise nothing across a
    crash and are reported but never convicted.

    The certificate also audits DUPLICATION, the converse of loss: the
    planes emit one ``ingest.fold`` event per folded write_id, so a
    write_id folded more than once — the at-least-once owner-failover
    case, where the first owner applied the batch but its ack was lost
    and the successor applied it again — lands in the ``duplicates``
    section with the folding (member, wseq) sites. By default this is
    reported, not convicted (the registered CRDT types absorb duplicate
    folds under their stamped join); pass ``strict_exactly_once=True``
    to make any duplicate application fail certification — the right
    setting when the op stream is not duplicate-tolerant.

    Returns a signed certificate; on failure `ok` is False and
    `counterexample` names the lost seq range per origin plus the
    acked write_ids inside it (and, under strict mode, the duplicated
    write_ids with their fold sites)."""
    if logs is None:
        logs = obs_events.scan_dir(obs_dir) if obs_dir else {}
    # -- the promises: client-side acks, grouped by origin ------------
    acks: Dict[str, List[Tuple[int, str, str]]] = {}
    n_acks = 0
    by_level: Dict[str, int] = {}
    for fname in sorted(logs):
        for ev in logs[fname]:
            if ev.get("kind") != "ingest.ack":
                continue
            n_acks += 1
            lvl = str(ev.get("level", ""))
            by_level[lvl] = by_level.get(lvl, 0) + 1
            o = str(ev.get("origin"))
            s = int(ev.get("wseq", -1))
            if s >= 0:
                acks.setdefault(o, []).append(
                    (s, lvl, str(ev.get("write_id", "")))
                )
    # -- the evidence: per-origin coverage floors ---------------------
    exposures: List[Dict[str, Any]] = []
    per_origin: Dict[str, Dict[str, Any]] = {}
    for origin in sorted(acks):
        hard = [
            (s, lvl, wid) for s, lvl, wid in acks[origin]
            if lvl in ("durable", "replicated_to_k")
        ]
        own_logs = [
            evs for evs in logs.values()
            if any(str(e.get("member")) == origin for e in evs
                   if e.get("member"))
        ]
        clean = bool(own_logs) and all(
            any(e.get("kind") == "proc.exit" for e in evs)
            for evs in own_logs
        )
        durable_floor = max(
            (
                int(e["through"])
                for evs in own_logs for e in evs
                if e.get("kind") == "wal.durable"
                and e.get("through") is not None
            ),
            default=-1,
        )
        recover_floor = max(
            (
                int(e.get("last_step", -1))
                for evs in own_logs for e in evs
                if e.get("kind") == "wal.recover"
            ),
            default=-1,
        )
        survivor_floor = -1
        for fname, evs in logs.items():
            applier = next(
                (str(e["member"]) for e in evs if e.get("member")), fname
            )
            if applier == origin:
                continue
            for e in evs:
                k = e.get("kind")
                if str(e.get("origin")) != origin:
                    continue
                s = None
                if k == "delta.apply":
                    s = e.get("dseq")
                elif k == "snap.apply":
                    s = e.get("step")
                elif k == "psnap.resync":
                    s = e.get("dig_seq")
                if s is not None:
                    survivor_floor = max(survivor_floor, int(s))
        max_acked = max((s for s, _l, _w in hard), default=-1)
        cover = max(durable_floor, recover_floor, survivor_floor)
        if clean:
            cover = max(cover, max_acked)
        per_origin[origin] = {
            "acked_through": max_acked,
            "n_hard_acks": len(hard),
            "clean_exit": clean,
            "durable_floor": durable_floor,
            "recover_floor": recover_floor,
            "survivor_floor": survivor_floor,
            "covered_through": cover,
        }
        if max_acked > cover:
            exposures.append({
                "origin": origin,
                "acked_through": max_acked,
                "covered_through": cover,
                "uncovered": [cover + 1, max_acked],
                "lost_write_ids": sorted(
                    wid for s, _l, wid in hard if s > cover and wid
                )[:8],
            })
    # -- duplication: one ingest.fold per write_id, fleet-wide ---------
    folds: Dict[str, List[Dict[str, Any]]] = {}
    for fname in sorted(logs):
        evs = logs[fname]
        applier = next(
            (str(e["member"]) for e in evs if e.get("member")), fname
        )
        for e in evs:
            if e.get("kind") != "ingest.fold" or not e.get("write_id"):
                continue
            folds.setdefault(str(e["write_id"]), []).append(
                {"member": str(e.get("member") or applier),
                 "wseq": int(e.get("wseq", -1))}
            )
    dup_examples = [
        {"write_id": wid, "folds": sites}
        for wid, sites in sorted(folds.items())
        if len(sites) > 1
    ]
    duplicates = {
        "n_folded_write_ids": len(folds),
        "n_duplicated": len(dup_examples),
        "examples": dup_examples[:8],
    }
    checks = {"acked_durability_coverage": not exposures}
    if strict_exactly_once:
        checks["exactly_once_application"] = not dup_examples
    ok = all(checks.values())
    doc: Dict[str, Any] = {
        "kind": WRITE_CERTIFICATE_KIND,
        "version": WRITE_CERTIFICATE_VERSION,
        "t": round(time.time(), 3),
        "ok": ok,
        "checks": checks,
        "n_acks": n_acks,
        "acks_by_level": by_level,
        "n_origins": len(acks),
        "origins": per_origin,
        "duplicates": duplicates,
        "n_flight_logs": len(logs),
        "meta": meta or {},
    }
    if not ok:
        cx: Dict[str, Any] = {}
        if exposures:
            cx["acked_but_lost"] = exposures[:5]
        if strict_exactly_once and dup_examples:
            cx["duplicate_applications"] = dup_examples[:5]
        doc["counterexample"] = cx
    sign_certificate(doc)
    obs_events.emit(
        "audit.write_certificate", ok=ok,
        n_exposed=len(exposures),
        n_duplicated=len(dup_examples),
        signature=doc["signature"][:16],
    )
    return doc


# -- lattice-law checking ----------------------------------------------------


class LawChecker:
    """Run the merge/delta law suite for every registered dense type.

    Fixtures come from the registry (`Registry.law_fixture`), so each
    type supplies its own reachable-state generator; `extra_fixtures`
    lets a caller inject unregistered ones — the negative selftest
    (`ops.laws.broken_merge_fixture`) enters that way and MUST fail.
    `pairs` is the instance-grid width: one merge dispatch checks that
    many instance pairs. Types on the registry with no fixture land in
    `unaudited` and flip `ok` False — a new type cannot silently skip
    the gate."""

    def __init__(
        self,
        types: Optional[Sequence[str]] = None,
        seed: int = 0,
        pairs: int = 512,
        extra_fixtures: Optional[Dict[str, Callable[..., Any]]] = None,
        metrics: Any = None,
    ) -> None:
        self.types = list(types) if types is not None else None
        self.seed = int(seed)
        self.pairs = max(1, int(pairs))
        self.extra_fixtures = dict(extra_fixtures or {})
        self.metrics = metrics

    def run(self) -> Dict[str, Any]:
        from ..core.behaviour import registry
        from ..ops import laws  # lazy: pulls jax + registers fixtures

        wanted = (
            set(self.types) if self.types is not None
            else set(registry.dense_types()) | set(self.extra_fixtures)
        )
        fixtures: Dict[str, Any] = {
            name: fx
            for name, fx in registry.law_fixtures().items()
            if name in wanted
        }
        fixtures.update(
            (n, f) for n, f in self.extra_fixtures.items() if n in wanted
        )
        unaudited = sorted(wanted - set(fixtures))

        types_out: Dict[str, Any] = {}
        n_checks = n_failures = 0
        for name in sorted(fixtures):
            spec = fixtures[name](self.seed, self.pairs)
            rep = laws.check_engine_laws(
                spec["dense"], spec["states"], spec.get("chain")
            )
            types_out[name] = rep
            n_checks += len(rep["laws"])
            n_failures += sum(
                1 for e in rep["laws"].values() if not e["ok"]
            )
        report = {
            "ok": not unaudited and all(r["ok"] for r in types_out.values()),
            "pairs": self.pairs,
            "seed": self.seed,
            "n_types": len(types_out),
            "n_law_checks": n_checks,
            "n_law_failures": n_failures,
            "unaudited": unaudited,
            "types": types_out,
        }
        if self.metrics is not None:
            self.metrics.count("audit.law_checks", float(n_checks))
            if n_failures:
                self.metrics.count("audit.law_failures", float(n_failures))
        obs_events.emit(
            "audit.laws", ok=report["ok"], n_types=len(types_out),
            n_checks=n_checks, n_failures=n_failures,
            unaudited=unaudited,
        )
        return report


# -- live divergence watchdog ------------------------------------------------


def _div_parts(own: Any, peer: Any) -> List[int]:
    """Indices where two digest vectors disagree (scalar digests compare
    as 1-vectors; incomparable lengths flag every index)."""
    a = list(own) if hasattr(own, "__len__") else [own]
    b = list(peer) if hasattr(peer, "__len__") else [peer]
    if len(a) != len(b):
        return list(range(max(len(a), len(b))))
    return [i for i in range(len(a)) if int(a[i]) != int(b[i])]


class DivergenceWatchdog:
    """Per-peer divergence state machine over the digest vectors the
    partial anti-entropy tier already fetches.

    States: 0 ok, 1 diverged, 2 wedged. A peer enters `diverged` the
    first observation its vector disagrees with ours — i.e. within one
    digest-exchange round of the divergence existing. Divergence is
    NORMAL in steady state (ops in flight); the alarm condition is
    *wedged*: still diverged after `wedge_after_s` seconds with no
    repair progress (progress = the divergent set shrinking, or the
    anti-entropy tier reporting applied psnaps via
    `note_repair_progress`). Agreement closes the episode and records a
    time-to-agreement sample.

    Everything is monotonic-clock based (injectable for tests); gauges
    and counters land on the supplied `Metrics` so the existing scrape
    surfaces export them; transitions emit `audit.*` flight events."""

    STATE_OK, STATE_DIVERGED, STATE_WEDGED = 0, 1, 2
    _STATE_NAMES = {0: "ok", 1: "diverged", 2: "wedged"}

    def __init__(
        self,
        member: str,
        wedge_after_s: float = 5.0,
        mono: Callable[[], float] = time.monotonic,
        metrics: Any = None,
        max_tta_samples: int = 256,
    ) -> None:
        self.member = member
        self.wedge_after_s = float(wedge_after_s)
        self._mono = mono
        self.metrics = metrics
        self._max_tta = max(1, int(max_tta_samples))
        # peer -> {"state", "since", "progress", "parts", "seq"}
        self._peers: Dict[str, Dict[str, Any]] = {}
        self._tta: List[float] = []
        self.last_certificate: Optional[Dict[str, Any]] = None

    # -- feeding ----------------------------------------------------------

    def observe_peer(
        self, peer: str, own_vec: Any, peer_vec: Any,
        seq: Optional[int] = None,
    ) -> int:
        """One digest exchange with `peer`: compare vectors, advance the
        state machine, export gauges. Returns the peer's state."""
        now = self._mono()
        div = _div_parts(own_vec, peer_vec)
        rec = self._peers.get(peer)
        if div:
            if rec is None or rec["state"] == self.STATE_OK:
                rec = {
                    "state": self.STATE_DIVERGED, "since": now,
                    "progress": now, "parts": div, "seq": seq,
                }
                self._peers[peer] = rec
                self._count("audit.divergences")
                obs_events.emit(
                    "audit.divergence", peer=peer, parts=div, dig_seq=seq,
                )
            else:
                if set(div) < set(rec["parts"]):
                    # Strictly shrinking divergence = repair landing.
                    rec["progress"] = now
                rec["parts"], rec["seq"] = div, seq
                if (
                    rec["state"] == self.STATE_DIVERGED
                    and now - rec["progress"] > self.wedge_after_s
                ):
                    rec["state"] = self.STATE_WEDGED
                    self._count("audit.wedge_alarms")
                    obs_events.emit(
                        "audit.wedged", peer=peer, parts=div,
                        age_s=round(now - rec["since"], 3), dig_seq=seq,
                    )
        else:
            if rec is not None and rec["state"] != self.STATE_OK:
                tta = now - rec["since"]
                self._tta.append(tta)
                del self._tta[: -self._max_tta]
                self._count("audit.agreements")
                obs_events.emit(
                    "audit.agreement", peer=peer,
                    tta_s=round(tta, 6), dig_seq=seq,
                )
            self._peers[peer] = {
                "state": self.STATE_OK, "since": now, "progress": now,
                "parts": [], "seq": seq,
            }
        self._export()
        return self._peers[peer]["state"]

    def note_repair_progress(self, peer: str) -> None:
        """The anti-entropy tier applied repair payloads for `peer` —
        resets the wedge clock (a slow-but-moving repair is not wedged)."""
        rec = self._peers.get(peer)
        if rec is not None:
            rec["progress"] = self._mono()

    def drop(self, peer: str) -> None:
        """Forget a DEAD peer (SWIM verdict): its frozen digest vector
        must not age into a phantom wedge alarm."""
        self._peers.pop(peer, None)
        self._export()

    def note_certificate(self, cert: Dict[str, Any]) -> None:
        """Record the last convergence certificate for the health/status
        surfaces."""
        self.last_certificate = {
            "ok": bool(cert.get("ok")),
            "signature": str(cert.get("signature", ""))[:16],
            "t": cert.get("t"),
        }
        if self.metrics is not None:
            self.metrics.set(
                "audit.certificate_ok", 1.0 if cert.get("ok") else 0.0
            )

    # -- reading ----------------------------------------------------------

    def state(self) -> int:
        return max(
            (r["state"] for r in self._peers.values()),
            default=self.STATE_OK,
        )

    def divergence_age_s(self) -> float:
        now = self._mono()
        return max(
            (
                now - r["since"] for r in self._peers.values()
                if r["state"] != self.STATE_OK
            ),
            default=0.0,
        )

    def divergent_parts(self) -> List[int]:
        parts: set = set()
        for r in self._peers.values():
            if r["state"] != self.STATE_OK:
                parts.update(r["parts"])
        return sorted(parts)

    def tta_p50_s(self) -> Optional[float]:
        if not self._tta:
            return None
        vals = sorted(self._tta)
        return vals[(len(vals) - 1) // 2]

    def peers(self) -> Dict[str, Dict[str, Any]]:
        return {
            p: {
                "state": self._STATE_NAMES[r["state"]],
                "parts": list(r["parts"]),
                "dig_seq": r["seq"],
            }
            for p, r in sorted(self._peers.items())
        }

    # -- exporting --------------------------------------------------------

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.count(name)

    def _export(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set("audit.watchdog_state", float(self.state()))
        self.metrics.set(
            "audit.divergence_age_seconds", round(self.divergence_age_s(), 6)
        )
        p50 = self.tta_p50_s()
        if p50 is not None:
            self.metrics.set("audit.tta_p50_seconds", round(p50, 6))

    def health_fields(self) -> Dict[str, Any]:
        """/healthz verdict fields (merged via the never-fatal
        `health_extra` probe in obs/http.py)."""
        out: Dict[str, Any] = {
            "audit_watchdog_state": self._STATE_NAMES[self.state()],
            "audit_divergence_age_s": round(self.divergence_age_s(), 3),
            "audit_divergent_parts": self.divergent_parts(),
        }
        p50 = self.tta_p50_s()
        if p50 is not None:
            out["audit_tta_p50_ms"] = round(1000.0 * p50, 3)
        if self.last_certificate is not None:
            out["audit_last_certificate"] = dict(self.last_certificate)
        return out

    def status_fields(self) -> Dict[str, Any]:
        """Compact block for the per-worker status drops the dashboard
        scrapes (obs-<member>.json)."""
        p50 = self.tta_p50_s()
        return {
            "state": self._STATE_NAMES[self.state()],
            "age_s": round(self.divergence_age_s(), 3),
            "tta_p50_ms": (
                round(1000.0 * p50, 3) if p50 is not None else None
            ),
            "ttas": len(self._tta),
            "cert_ok": (
                None if self.last_certificate is None
                else self.last_certificate["ok"]
            ),
        }
