"""Round-phase span tracing: where a gossip round's wall time goes.

`obs.profile` times device dispatches in isolation; `obs.events` records
WHAT happened. Neither can answer the ROADMAP's top question — of the
~141ms e2e round (BENCH_r05), how much is WAL append vs delta encode vs
gossip I/O vs device sync, and how much is unattributed host slop? This
module adds the missing layer: named begin/end spans with monotonic
timestamps, span ids and parent links, recorded into a bounded ring and
(when ``CCRDT_OBS_DIR`` is set) a line-buffered crash-durable JSONL
spill, exactly mirroring the flight recorder's conventions.

The worker round is cut into ten load-bearing phases::

    round.wal_append       harness.wal.ElasticWal.log_step
    round.delta_encode     parallel.elastic.DeltaPublisher (delta branch,
                           including wire-window coalescing at flush)
    round.snapshot         parallel.elastic.DeltaPublisher (full branch)
    round.gossip_send      net.transport.GossipNode publish paths + the
                           tcp sender thread's actual wire write
    round.gossip_recv      GossipNode fetch paths (wire bytes only) + the
                           tcp reader thread
    round.delta_decode     GossipNode decode/validate of fetched blobs —
                           snapshot loads and the prefetcher's batched
                           frame decode both bill here
    round.delta_apply      parallel.elastic.sweep_deltas (delta + snap)
    round.device_dispatch  core.batch_merge folds, drill op application
    round.device_sync      explicit block_until_ready (only taken when
                           spans are ACTIVE — an honest sync point, the
                           off path is untouched)
    round.lag_update       obs.lag export in the worker loop

plus a ``round.e2e`` wrapper span per worker step that attribution
reconciles the phase sums against. Delta-flavoured spans carry the same
``(origin, dseq)`` trace context as the flight-recorder events, so a
span joins its events.

Overhead discipline copies `utils.faults`/`obs.profile`: a module-level
``ACTIVE`` bool call sites check FIRST — the disabled path is one global
load and a branch. Span durations are optionally mirrored into `Metrics`
as ``span.<name>`` latencies so the live scrape surfaces (HTTP /metrics,
in-band ``{metrics_req}``) carry the span plane without reading spills.

Cross-worker alignment: workers timestamp with ``time.monotonic()``,
whose epoch is per-process. `ClockSync` holds NTP-style per-peer offset
estimates — from an exchange (t1 = local send, t2 = remote clock at
receipt, t3 = local receive): ``offset = t2 - (t1 + t3)/2`` with error
bounded by the RTT asymmetry, keeping the minimum-RTT sample per peer
(the classic NTP filter). `net.tcp` piggybacks these timestamps on the
existing ``{hello}``/``{hello_ack}`` and ``{metrics_req}`` frames;
`net.sim` exposes a deterministic ``clock_exchange``. Offsets are
spilled as ``{"k": "offset"}`` records; `align_offsets` BFSes the
offset graph from a reference member so every fleet member's monotonic
clock maps onto one timeline, and `to_chrome_trace` emits Chrome
trace-event JSON (Perfetto-loadable) on that timeline.

This module is stdlib-only and must stay import-cycle-free: `net.tcp`,
`harness.wal`, `parallel.elastic`, and `core.batch_merge` all import it
at module load. The Metrics mirror is duck-typed for that reason.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

ENV_FLAG = "CCRDT_SPANS"
ENV_DIR = "CCRDT_OBS_DIR"  # shared with obs.events — one spill dir per fleet

DEFAULT_RING = 8192

# The load-bearing phases chaos_gate requires to stay lit. round.e2e is
# deliberately not here: it is the denominator, not a phase.
PHASES = (
    "round.wal_append",
    "round.delta_encode",
    "round.gossip_send",
    "round.gossip_recv",
    "round.delta_decode",
    "round.delta_apply",
    "round.device_dispatch",
    "round.device_sync",
    "round.snapshot",
    "round.lag_update",
)

E2E = "round.e2e"

# Conditional phases, deliberately NOT in PHASES: they only light when
# their subsystem is armed, so requiring them fleet-wide would fail
# every non-mesh / non-serve run. `round.serve_swap` is emitted by
# serve/replica.py; `round.ici_reduce` (ICI_REDUCE) by mesh/reduce.py —
# chaos_gate's mesh leg requires the latter lit *in mesh drills only*;
# `round.pager_hydrate` (PAGER_HYDRATE) by core/pager.py page-ins —
# chaos_gate's working-set leg requires it lit *in pager drills only*.
ICI_REDUCE = "round.ici_reduce"
PAGER_HYDRATE = "round.pager_hydrate"

# Hot-path gate — call sites must check `if spans.ACTIVE:` first.
ACTIVE = False

_TRACER: Optional["_Tracer"] = None


class ClockSync:
    """Minimum-RTT NTP-style offset filter.

    ``note(peer, t1, t2, t3)`` ingests one exchange (local-clock send
    time t1, remote-clock receipt time t2, local-clock receive time t3)
    and keeps, per peer, the offset estimate from the exchange with the
    smallest RTT seen so far — ``offset ~= remote_clock - local_clock``,
    accurate to half the RTT asymmetry."""

    def __init__(self):
        self.peers: Dict[str, Tuple[float, float]] = {}  # peer -> (offset, rtt)
        self._lock = threading.Lock()

    def note(
        self, peer: str, t1: float, t2: float, t3: float
    ) -> Optional[Tuple[float, float]]:
        rtt = t3 - t1
        if rtt < 0:  # clock went backwards / garbled frame: discard
            return None
        offset = t2 - (t1 + t3) / 2.0
        with self._lock:
            cur = self.peers.get(peer)
            if cur is None or rtt < cur[1]:
                self.peers[peer] = (offset, rtt)
        return offset, rtt

    def snapshot(self) -> Dict[str, Tuple[float, float]]:
        with self._lock:
            return dict(self.peers)


class _Tracer:
    """One per-process span recorder: bounded ring + optional spill."""

    def __init__(
        self,
        member: str,
        metrics: Any = None,
        ring: int = DEFAULT_RING,
        spill_dir: Optional[str] = None,
    ):
        self.member = member
        self.metrics = metrics
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.clock = ClockSync()
        self._lock = threading.Lock()
        self._sid = 0
        self._tids: Dict[int, int] = {}  # thread ident -> small stable index
        self._tls = threading.local()
        self._fh = None
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            path = os.path.join(
                spill_dir, f"spans-{member}-{os.getpid()}.jsonl"
            )
            # Line-buffered like the flight recorder: every completed
            # span reaches the file before a SIGKILL.
            self._fh = open(path, "a", buffering=1)
        # The wall<->monotonic anchor: lets readers place this process's
        # monotonic timeline on the wall clock (and each other's, via
        # offset records).
        self._write(
            {
                "k": "clock",
                "member": member,
                "pid": os.getpid(),
                "wall": round(time.time(), 6),
                "mono": time.monotonic(),
            }
        )

    # -- record plumbing ---------------------------------------------------

    def _write(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self.ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                except (OSError, ValueError):
                    pass  # spill is best-effort; the ring stays whole

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids)
                self._tids[ident] = tid
            return tid

    # -- span primitives ---------------------------------------------------

    def begin(self, name: str, fields: Dict[str, Any]) -> Dict[str, Any]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        with self._lock:
            self._sid += 1
            sid = self._sid
        frame = {
            "sid": sid,
            "parent": stack[-1]["sid"] if stack else None,
            "name": name,
            "m0": time.monotonic(),
            "fields": fields,
        }
        stack.append(frame)
        return frame

    def end(self, frame: Dict[str, Any]) -> None:
        m1 = time.monotonic()
        stack = getattr(self._tls, "stack", None)
        # Pop through any frames abandoned by exceptions between begin
        # and end (non-lexical begin/end users): the frame must leave
        # the stack exactly once.
        if stack:
            while stack and stack[-1]["sid"] != frame["sid"]:
                stack.pop()
            if stack:
                stack.pop()
        rec = {
            "k": "span",
            "name": frame["name"],
            "sid": frame["sid"],
            "parent": frame["parent"],
            "member": self.member,
            "tid": self._tid(),
            "m0": frame["m0"],
            "m1": m1,
        }
        if frame["fields"]:
            rec.update(frame["fields"])
        self._write(rec)
        m = self.metrics
        if m is not None:
            try:
                m.merge(
                    {
                        "counters": {},
                        "latencies": {f"span.{frame['name']}": [m1 - frame["m0"]]},
                    }
                )
            except Exception:  # noqa: BLE001 — tracing must never break a round
                pass

    def observe_exchange(
        self, peer: str, t1: float, t2: float, t3: float
    ) -> None:
        est = self.clock.note(peer, t1, t2, t3)
        if est is None:
            return
        offset, rtt = est
        self._write(
            {
                "k": "offset",
                "member": self.member,
                "peer": peer,
                "offset": offset,
                "rtt": rtt,
                "mono": time.monotonic(),
            }
        )
        m = self.metrics
        if m is not None:
            try:
                m.count("clock.exchanges")
                m.set(f"clock.offset_seconds.{peer}", offset)
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


# -- lifecycle ----------------------------------------------------------------


def install(
    member: str,
    metrics: Any = None,
    ring: int = DEFAULT_RING,
    spill_dir: Optional[str] = None,
) -> None:
    """Arm the span plane for this process."""
    global ACTIVE, _TRACER
    if _TRACER is not None:
        _TRACER.close()
    _TRACER = _Tracer(member, metrics=metrics, ring=ring, spill_dir=spill_dir)
    ACTIVE = True


def uninstall() -> None:
    global ACTIVE, _TRACER
    ACTIVE = False
    tr, _TRACER = _TRACER, None
    if tr is not None:
        tr.close()


@contextlib.contextmanager
def installed(
    member: str,
    metrics: Any = None,
    ring: int = DEFAULT_RING,
    spill_dir: Optional[str] = None,
):
    """Scoped enable for tests: always restores the previous state."""
    global ACTIVE, _TRACER
    prev = _TRACER
    _TRACER = None  # detach so install() doesn't close the restorable tracer
    install(member, metrics=metrics, ring=ring, spill_dir=spill_dir)
    try:
        yield _TRACER
    finally:
        uninstall()
        if prev is not None:
            _TRACER = prev
            ACTIVE = True


def set_metrics(metrics: Any) -> None:
    """Attach (or replace) the Metrics mirror on the active tracer — for
    workers that must arm the plane before their Metrics object exists
    (the tcp drills install before the transport so the first hello
    exchange's clock offset is not lost). No-op when the plane is down."""
    tr = _TRACER
    if tr is not None:
        tr.metrics = metrics


def install_from_env(
    member: str, metrics: Any = None, env: Optional[dict] = None
) -> bool:
    """Arm iff ``CCRDT_SPANS`` is truthy; spill under ``CCRDT_OBS_DIR``
    when set (same supervisor->worker propagation as the flight
    recorder). Returns whether the plane was armed."""
    e = env if env is not None else os.environ
    raw = e.get(ENV_FLAG, "")
    if raw.strip().lower() not in ("1", "true", "yes", "on"):
        return False
    install(member, metrics=metrics, spill_dir=e.get(ENV_DIR) or None)
    return True


# -- recording API ------------------------------------------------------------


@contextlib.contextmanager
def span(name: str, **fields):
    """Record one span around the body. Call sites guard with
    ``if spans.ACTIVE:``; this tolerates a concurrent `uninstall`."""
    tr = _TRACER
    if tr is None:
        yield
        return
    frame = tr.begin(name, fields)
    try:
        yield
    finally:
        tr.end(frame)


def begin(name: str, **fields) -> Optional[Dict[str, Any]]:
    """Non-lexical begin: returns a token for `end`, or None when the
    plane is down (pass it to `end` unconditionally; None is a no-op)."""
    tr = _TRACER
    if tr is None:
        return None
    return tr.begin(name, fields)


def end(token: Optional[Dict[str, Any]]) -> None:
    tr = _TRACER
    if tr is None or token is None:
        return
    tr.end(token)


def observe_exchange(peer: str, t1: float, t2: float, t3: float) -> None:
    """Feed one NTP-style exchange into the active tracer (no-op when
    the plane is down)."""
    tr = _TRACER
    if tr is not None:
        tr.observe_exchange(peer, t1, t2, t3)


def drain() -> List[Dict[str, Any]]:
    """Snapshot of the in-memory ring (oldest first). Empty when down."""
    tr = _TRACER
    if tr is None:
        return []
    with tr._lock:
        return list(tr.ring)


# -- readers (post-mortem / merge side; work without ACTIVE) ------------------


def read_spans(path: str) -> List[Dict[str, Any]]:
    """All records in one spill file; a torn tail line (SIGKILL mid-
    write) is skipped, mirroring `obs.events.read_log`."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def scan_dir(dirpath: str) -> Dict[str, List[Dict[str, Any]]]:
    """All span spills under `dirpath`, keyed by member (a member that
    restarted contributes all its pids' records, concatenated)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("spans-") and fn.endswith(".jsonl")):
            continue
        recs = read_spans(os.path.join(dirpath, fn))
        if not recs:
            continue
        member = next(
            (r["member"] for r in recs if "member" in r),
            fn[len("spans-"):].rsplit("-", 1)[0],
        )
        out.setdefault(member, []).extend(recs)
    return out


def clock_offsets(
    by_member: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Dict[str, Tuple[float, float]]]:
    """Min-RTT offset per (member, peer) from the spilled offset
    records: ``offsets[a][b] ~= mono_b - mono_a``."""
    out: Dict[str, Dict[str, Tuple[float, float]]] = {}
    for member, recs in by_member.items():
        best: Dict[str, Tuple[float, float]] = {}
        for r in recs:
            if r.get("k") != "offset":
                continue
            peer, off, rtt = r.get("peer"), r.get("offset"), r.get("rtt")
            if peer is None or off is None or rtt is None:
                continue
            cur = best.get(peer)
            if cur is None or rtt < cur[1]:
                best[peer] = (float(off), float(rtt))
        if best:
            out[member] = best
    return out


def align_offsets(
    offsets: Dict[str, Dict[str, Tuple[float, float]]],
    members: Iterable[str],
    ref: Optional[str] = None,
) -> Dict[str, float]:
    """Per-member shift mapping local monotonic time onto the reference
    member's monotonic timeline: ``aligned = mono + shift[member]``,
    ``shift[ref] == 0``. BFS over the (bidirectional) offset graph,
    preferring low-RTT edges; unreachable members get shift 0 (their
    spans still render, just unaligned — the CLI reports them)."""
    members = sorted(set(members))
    if not members:
        return {}
    if ref is None or ref not in members:
        ref = members[0]
    # Build symmetric edge list: offsets[a][b] = mono_b - mono_a, so an
    # observation at b about a also yields an a->b edge with sign flip.
    edges: Dict[str, Dict[str, Tuple[float, float]]] = {m: {} for m in members}
    for a, peers in offsets.items():
        for b, (off, rtt) in peers.items():
            if a not in edges or b not in edges:
                continue
            cur = edges[a].get(b)
            if cur is None or rtt < cur[1]:
                edges[a][b] = (off, rtt)
            cur = edges[b].get(a)
            if cur is None or rtt < cur[1]:
                edges[b][a] = (-off, rtt)
    shift: Dict[str, float] = {ref: 0.0}
    frontier = [ref]
    while frontier:
        nxt: List[str] = []
        for a in frontier:
            for b, (off, _rtt) in sorted(
                edges.get(a, {}).items(), key=lambda kv: kv[1][1]
            ):
                if b in shift:
                    continue
                # aligned(a) = mono_a + shift[a]; mono_b ~= mono_a + off
                # => shift[b] = shift[a] - off.
                shift[b] = shift[a] - off
                nxt.append(b)
        frontier = nxt
    for m in members:
        shift.setdefault(m, 0.0)
    return shift


def anchor_of(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    for r in recs:
        if r.get("k") == "clock":
            return r
    return None


def to_chrome_trace(
    by_member: Dict[str, List[Dict[str, Any]]],
    shifts: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Merge per-member span records into one Chrome trace-event JSON
    object (Perfetto loads it directly). Timestamps are microseconds on
    the aligned reference timeline, zero-based at the earliest span."""
    if shifts is None:
        shifts = align_offsets(clock_offsets(by_member), by_member.keys())
    events: List[Dict[str, Any]] = []
    base: Optional[float] = None
    for member in sorted(by_member):
        sh = shifts.get(member, 0.0)
        for r in by_member[member]:
            if r.get("k") == "span":
                t = r["m0"] + sh
                if base is None or t < base:
                    base = t
    base = base or 0.0
    for pid, member in enumerate(sorted(by_member), start=1):
        sh = shifts.get(member, 0.0)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": member},
            }
        )
        for r in by_member[member]:
            if r.get("k") != "span":
                continue
            args = {
                k: v
                for k, v in r.items()
                if k not in ("k", "name", "member", "tid", "m0", "m1")
            }
            events.append(
                {
                    "name": r["name"],
                    "cat": "round",
                    "ph": "X",
                    "ts": round((r["m0"] + sh - base) * 1e6, 3),
                    "dur": round((r["m1"] - r["m0"]) * 1e6, 3),
                    "pid": pid,
                    "tid": int(r.get("tid", 0)),
                    "args": args,
                }
            )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"aligned_members": sorted(by_member)},
    }


# -- attribution --------------------------------------------------------------


def _union(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [lo, hi) intervals."""
    total, cur_lo, cur_hi = 0.0, None, None
    for lo, hi in sorted(intervals):
        if hi <= lo:
            continue
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def _pctl(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(q * len(ys)))]


def attribute(
    by_member: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Per-round dispatch-gap attribution.

    For every ``round.e2e`` span E on a worker: phase spans of the same
    member are clipped to E's window; those on E's own thread are the
    SERIAL host time, phases on other threads (tcp sender/reader, the
    overlap pipeline's host stage and prefetcher) are OVERLAPPABLE —
    work the round did not have to wait for. Coverage and the
    unattributed gap are measured against the union of BOTH classes
    (covered = serial ∪ overlappable clipped to E): an overlapped round
    is explained by phases regardless of which thread owns them, and
    the residue is time no instrumented phase accounts for. Per-phase
    TOTALS are summed over the phases' full (unclipped) extents — in
    overlap mode host stages run between e2e windows too, and totals
    must show where wall time went, not just the slice inside a window
    — while per-round ``phases_ms_p50`` samples stay clipped. Returns
    per-member and fleet aggregates: per-phase totals/p50s,
    serial/overlap/gap p50s, coverage p50, and the critical-path
    ranking (phases by total time)."""
    members_out: Dict[str, Any] = {}
    fleet_cov: List[float] = []
    fleet_phase_totals: Dict[str, float] = {}
    fleet_e2e: List[float] = []
    fleet_serial: List[float] = []
    fleet_overlap: List[float] = []
    fleet_gap: List[float] = []
    fleet_rounds = 0
    for member, recs in sorted(by_member.items()):
        spans_ = [r for r in recs if r.get("k") == "span"]
        e2es = sorted(
            (r for r in spans_ if r.get("name") == E2E),
            key=lambda r: r["m0"],
        )
        phases = [r for r in spans_ if r.get("name") in PHASES]
        rounds: List[Dict[str, Any]] = []
        phase_totals: Dict[str, float] = {}
        phase_samples: Dict[str, List[float]] = {}
        for e in e2es:
            lo, hi, tid = e["m0"], e["m1"], e.get("tid", 0)
            dur = hi - lo
            if dur <= 0:
                continue
            serial_iv: List[Tuple[float, float]] = []
            overlap_iv: List[Tuple[float, float]] = []
            by_phase: Dict[str, float] = {}
            for p in phases:
                plo, phi = max(p["m0"], lo), min(p["m1"], hi)
                if phi <= plo:
                    continue
                by_phase[p["name"]] = by_phase.get(p["name"], 0.0) + (phi - plo)
                if p.get("tid", 0) == tid:
                    serial_iv.append((plo, phi))
                else:
                    overlap_iv.append((plo, phi))
            serial = _union(serial_iv)
            overlap = _union(overlap_iv)
            covered = _union(serial_iv + overlap_iv)
            gap = max(0.0, dur - covered)
            rounds.append(
                {
                    "e2e": dur,
                    "serial": serial,
                    "overlap": overlap,
                    "gap": gap,
                    "coverage": covered / dur,
                    "phases": by_phase,
                }
            )
            for name, v in by_phase.items():
                phase_samples.setdefault(name, []).append(v)
        # Totals over the phases' full extents (NOT clipped to e2e
        # windows): overlapped host stages run during the inter-round
        # sleep as well, and that work must still show in the ledger.
        for p in phases:
            d = p["m1"] - p["m0"]
            if d > 0:
                phase_totals[p["name"]] = phase_totals.get(p["name"], 0.0) + d
        if not rounds:
            continue
        cov = [r["coverage"] for r in rounds]
        e2e_s = [r["e2e"] for r in rounds]
        ser_s = [r["serial"] for r in rounds]
        ovl_s = [r["overlap"] for r in rounds]
        gap_s = [r["gap"] for r in rounds]
        members_out[member] = {
            "rounds": len(rounds),
            "e2e_ms_p50": _pctl(e2e_s, 0.5) * 1e3,
            "serial_ms_p50": _pctl(ser_s, 0.5) * 1e3,
            "overlap_ms_p50": _pctl(ovl_s, 0.5) * 1e3,
            "gap_ms_p50": _pctl(gap_s, 0.5) * 1e3,
            "coverage_p50": _pctl(cov, 0.5),
            "phases_ms_p50": {
                n: _pctl(v, 0.5) * 1e3 for n, v in sorted(phase_samples.items())
            },
            "phases_ms_total": {
                n: v * 1e3 for n, v in sorted(phase_totals.items())
            },
            "critical_path": [
                n
                for n, _v in sorted(
                    phase_totals.items(), key=lambda kv: -kv[1]
                )
            ],
        }
        fleet_cov.extend(cov)
        fleet_e2e.extend(e2e_s)
        fleet_serial.extend(ser_s)
        fleet_overlap.extend(ovl_s)
        fleet_gap.extend(gap_s)
        fleet_rounds += len(rounds)
        for n, v in phase_totals.items():
            fleet_phase_totals[n] = fleet_phase_totals.get(n, 0.0) + v
    return {
        "members": members_out,
        "fleet": {
            "rounds": fleet_rounds,
            "e2e_ms_p50": _pctl(fleet_e2e, 0.5) * 1e3,
            "serial_ms_p50": _pctl(fleet_serial, 0.5) * 1e3,
            "overlap_ms_p50": _pctl(fleet_overlap, 0.5) * 1e3,
            "gap_ms_p50": _pctl(fleet_gap, 0.5) * 1e3,
            "coverage_p50": _pctl(fleet_cov, 0.5),
            "phases_ms_total": {
                n: v * 1e3 for n, v in sorted(fleet_phase_totals.items())
            },
            "critical_path": [
                n
                for n, _v in sorted(
                    fleet_phase_totals.items(), key=lambda kv: -kv[1]
                )
            ],
        },
    }


def format_report(att: Dict[str, Any]) -> str:
    """Human-readable attribute report (the CLI and demos print this)."""
    lines: List[str] = []
    fleet = att.get("fleet", {})
    lines.append(
        f"rounds={fleet.get('rounds', 0)} "
        f"e2e p50 {fleet.get('e2e_ms_p50', 0.0):.2f}ms | "
        f"serial {fleet.get('serial_ms_p50', 0.0):.2f}ms "
        f"overlappable {fleet.get('overlap_ms_p50', 0.0):.2f}ms "
        f"gap {fleet.get('gap_ms_p50', 0.0):.2f}ms "
        f"(coverage {fleet.get('coverage_p50', 0.0):.1%})"
    )
    totals = fleet.get("phases_ms_total", {})
    path = fleet.get("critical_path", [])
    if path:
        lines.append("critical path (by total phase time):")
        for name in path:
            lines.append(f"  {name:<22} {totals.get(name, 0.0):10.2f} ms")
    for member, row in sorted(att.get("members", {}).items()):
        lines.append(
            f"{member}: rounds={row['rounds']} "
            f"e2e p50 {row['e2e_ms_p50']:.2f}ms "
            f"serial {row['serial_ms_p50']:.2f}ms "
            f"gap {row['gap_ms_p50']:.2f}ms "
            f"coverage {row['coverage_p50']:.1%}"
        )
    return "\n".join(lines)
