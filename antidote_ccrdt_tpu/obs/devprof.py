"""Device observatory: compile-churn attribution, per-kernel cost
accounting, and device-memory telemetry (ISSUE 19).

PR 18's tail attribution proved the stepping-fleet p99 is inflated by
per-step JIT recompiles, but `obs/profile.py`'s cache-size delta only
says "a compile occurred somewhere". This module is the device-level
observatory that names the WHICH and the WHY: every jit slot cache in
the tree (`core/batch_merge.py`, `mesh/reduce.py`, `serve/kernels.py`,
`core/pager.py`, the elastic sweeps) dispatches through
:func:`observe`, and each compile event records

* the **site** (the dispatch call site's stable name),
* the full **abstract signature** that triggered it — per-leaf shapes,
  dtypes, shardings, and the donation mode of the slot,
* a structural **DIFF against the site's previous signature** naming
  the axis that changed (``arg0.slot_score axis3 4->8`` is topk_rmv
  capacity growth), ``first_trace`` for a site's first compile and
  ``retrace`` when the signature is unchanged but the cache still grew,
* **compile-vs-execute wall time** and the jit-cache depth after the
  compile,

emitted three ways at once: a typed ``devprof.compile`` flight-recorder
event (request-plane ring + SIGKILL-surviving spill), per-site
OpenMetrics histograms/counters (``devprof.compile.<site>`` /
``devprof.execute.<site>`` / ``devprof.compiles.<site>`` — the normal
Metrics registry, so all three scrape surfaces pick them up), and
`/healthz` fields via :func:`health_fields`.

Device-memory telemetry rides along: ``devprof.live_buffer_bytes``
(+peak high-watermark, sampled from ``jax.live_arrays()`` only on
compile events — compiles are rare, so the walk is off the hot path),
``devprof.retained_bytes.<site>`` (operand bytes pinned per slot
cache), and pager HBM occupancy vs ``CCRDT_PAGER_HBM_BUDGET`` pushed in
by :func:`note_pager` from the pager's gauge export.

Overhead discipline copies `obs/profile.py` exactly: ``CCRDT_DEVPROF=0``
is a zero-cost kill switch behind the module-level ``ACTIVE`` bool that
call sites check FIRST; the disabled path costs one global load and a
branch. Unlike ``CCRDT_PROFILE`` (opt-in), the observatory defaults ON
when `install_from_env` runs — set ``CCRDT_DEVPROF=0`` to kill it.
Every record path is additionally guarded by the ``devprof.record``
fault point and a blanket except: an injected or real recording failure
degrades to ``devprof.unobserved`` and NEVER blocks the dispatch.

`obs/profile.py`'s compile/execute split now delegates here
(:func:`observe`'s ``profile_metrics`` parameter) so one cache-size
sample is the single source of truth for both counter families.

``CCRDT_DEVPROF_WARMUP=1`` arms the boot-time warm-up: `batch_merge`
pads topk_rmv capacities to the next power of two (bit-identity safe —
padding carries the absent-entry sentinels its extraction loops already
skip) and `prewarm_topk_rmv` pre-traces the bucket ladder, collapsing
the stepping-fleet recompile storm the devprof demo measures.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

from ..utils.metrics import Metrics
from ..utils import faults
from . import events

ENV_FLAG = "CCRDT_DEVPROF"
ENV_WARMUP = "CCRDT_DEVPROF_WARMUP"

FAULT_RECORD = "devprof.record"

# Hot-path gates — call sites check `if devprof.ACTIVE:` (or
# `profile.ACTIVE or devprof.ACTIVE`) before touching anything else.
ACTIVE = False
# Warm-up arm: batch_merge pads topk_rmv capacities to power-of-two
# buckets and boot code may call prewarm_topk_rmv. Independent of
# ACTIVE — padding changes dispatch shapes (never results), observation
# does not.
WARMUP = False

# Timeline entries kept per site and recent-compile entries kept for
# rtrace window matching. Bounded so a pathological storm cannot grow
# the observatory itself without bound.
_TIMELINE_MAX = 256
_RECENT_MAX = 4096


class _Observatory:
    """One process's device observatory state (metrics + per-site map)."""

    def __init__(self, metrics: Metrics) -> None:
        self.metrics = metrics
        self.lock = threading.Lock()
        # site -> {"sig", "compiles", "dispatches", "retained_bytes",
        #          "timeline": [{"t", "axis", "ms", "depth"}...]}
        self.sites: Dict[str, Dict[str, Any]] = {}
        # (monotonic stamp, site, compile_ms) — rtrace hop-window lookup.
        self.recent: Deque[Tuple[float, str, float]] = collections.deque(
            maxlen=_RECENT_MAX
        )
        self.live_bytes = 0.0
        self.peak_live_bytes = 0.0
        self.hbm_used = 0.0
        self.hbm_budget = 0.0
        self.peak_hbm_used = 0.0


_OBS: Optional[_Observatory] = None


def install(metrics: Metrics) -> None:
    """Route observatory records into `metrics` and flip the gate on."""
    global ACTIVE, _OBS
    _OBS = _Observatory(metrics)
    ACTIVE = True


def uninstall() -> None:
    global ACTIVE, _OBS
    ACTIVE = False
    _OBS = None


def set_warmup(flag: bool) -> None:
    global WARMUP
    WARMUP = bool(flag)


def _restore(prev) -> None:
    global ACTIVE, _OBS, WARMUP
    ACTIVE, _OBS, WARMUP = prev


@contextlib.contextmanager
def installed(metrics: Metrics):
    """Scoped enable for tests: always restores the previous state."""
    prev = (ACTIVE, _OBS, WARMUP)
    install(metrics)
    try:
        yield metrics
    finally:
        _restore(prev)


def _killed(raw: Optional[str]) -> bool:
    return (raw or "").strip().lower() in ("0", "false", "off", "no")


def install_from_env(
    metrics: Metrics, env: Optional[dict] = None
) -> bool:
    """Default-armed kill-switch semantics (the opposite polarity of
    ``CCRDT_PROFILE``): the observatory installs unless
    ``CCRDT_DEVPROF`` is explicitly "0"/"false"/"off". Also arms the
    warm-up bucket padding when ``CCRDT_DEVPROF_WARMUP`` is truthy.
    Returns whether the observatory was armed."""
    e = env if env is not None else os.environ
    set_warmup(
        e.get(ENV_WARMUP, "").strip().lower() in ("1", "true", "yes", "on")
    )
    if _killed(e.get(ENV_FLAG)):
        return False
    install(metrics)
    return True


# -- introspection helpers (shared with obs/profile.py) ---------------------


def _cache_size(fn: Any) -> Optional[int]:
    """Size of a jitted callable's compilation cache, or None when the
    callable doesn't expose one (plain functions, partials, older JAX).
    Defensive on purpose: observation must never break a dispatch."""
    try:
        sizer = fn._cache_size  # jax.jit-wrapped callables
    except AttributeError:
        return None
    try:
        return int(sizer())
    except Exception:  # noqa: BLE001 — any introspection failure = unknown
        return None


def _leaf_nbytes(operands: Iterable[Any]) -> int:
    """Total .nbytes across array leaves of `operands`. Dispatch sites
    pass registered pytrees (the dense engine states), so flattening
    goes through jax when available; without jax, plain containers
    still traverse."""
    try:
        import jax

        leaves = jax.tree.leaves(list(operands))
    except Exception:  # noqa: BLE001 — must never break a dispatch
        leaves = []
        stack = list(operands)
        while stack:
            x = stack.pop()
            if isinstance(x, (tuple, list)):
                stack.extend(x)
            elif isinstance(x, dict):
                stack.extend(x.values())
            else:
                leaves.append(x)
    total = 0
    for x in leaves:
        nb = getattr(x, "nbytes", None)
        if isinstance(nb, int):
            total += nb
    return total


def pad_dim(n: int) -> int:
    """Next power of two >= n (min 1): the warm-up capacity bucket."""
    n = max(int(n), 1)
    p = 1
    while p < n:
        p <<= 1
    return p


# -- abstract signatures and structural diffs -------------------------------


def _describe(x: Any) -> Tuple[Tuple[int, ...], str, str]:
    shape = tuple(int(d) for d in (getattr(x, "shape", ()) or ()))
    dtype = str(getattr(x, "dtype", type(x).__name__))
    sh = getattr(x, "sharding", None)
    return shape, dtype, (str(sh) if sh is not None else "")


def signature(operands: Iterable[Any], donation: str = "") -> Dict[str, Any]:
    """The abstract signature of a dispatch: per-leaf (name, shape,
    dtype, sharding) across the operand pytrees, plus the slot's
    donation mode. Leaf names come from the registered pytree paths
    (``arg0.slot_score``), so a diff can name the exact field whose
    axis grew."""
    leaves: List[Tuple[str, Tuple[int, ...], str, str]] = []
    try:
        import jax

        for i, op in enumerate(operands):
            flat, _ = jax.tree_util.tree_flatten_with_path(op)
            for path, leaf in flat:
                name = f"arg{i}{jax.tree_util.keystr(path)}"
                leaves.append((name, *_describe(leaf)))
    except Exception:  # noqa: BLE001 — degrade to opaque per-operand leaves
        for i, op in enumerate(operands):
            leaves.append((f"arg{i}", *_describe(op)))
    return {"leaves": tuple(leaves), "donation": donation}


def signature_diff(
    prev: Optional[Dict[str, Any]], cur: Dict[str, Any]
) -> List[str]:
    """Structural diff of two signatures as human-readable change
    strings, most significant first. ``["first_trace"]`` when the site
    had no previous signature; ``["retrace"]`` when nothing structural
    changed but the cache still grew (a new static argument — e.g. a
    fresh engine instance bound as jit static self)."""
    if prev is None:
        return ["first_trace"]
    changed: List[str] = []
    pd = {l[0]: l[1:] for l in prev["leaves"]}
    cd = {l[0]: l[1:] for l in cur["leaves"]}
    for name, (shape, dtype, shard) in cd.items():
        old = pd.get(name)
        if old is None:
            changed.append(f"+{name} {list(shape)}")
            continue
        oshape, odtype, oshard = old
        if oshape != shape:
            if len(oshape) == len(shape):
                for ax, (a, b) in enumerate(zip(oshape, shape)):
                    if a != b:
                        changed.append(f"{name} axis{ax} {a}->{b}")
            else:
                changed.append(f"{name} rank {len(oshape)}->{len(shape)}")
        if odtype != dtype:
            changed.append(f"{name} dtype {odtype}->{dtype}")
        if oshard != shard:
            changed.append(f"{name} sharding {oshard or '-'}->{shard or '-'}")
    for name in pd:
        if name not in cd:
            changed.append(f"-{name}")
    if prev.get("donation", "") != cur.get("donation", ""):
        changed.append(
            f"donation {prev.get('donation', '') or '-'}"
            f"->{cur.get('donation', '') or '-'}"
        )
    return changed or ["retrace"]


# -- the dispatch observer --------------------------------------------------


@contextlib.contextmanager
def observe(
    site: str,
    fn: Any = None,
    operands: Iterable[Any] = (),
    donation: str = "",
    profile_metrics: Optional[Metrics] = None,
):
    """Observe one dispatch at `site`. Guard the call site with
    ``if devprof.ACTIVE:`` (or ``profile.ACTIVE or devprof.ACTIVE``
    when going through `profile.dispatch`).

    With `fn` (the jitted callable actually dispatched), the jit cache
    size is sampled before/after to classify compile (cache grew) vs
    execute — ONE sample pair serving both the devprof record and, when
    `profile_metrics` is given, the legacy ``profile.*`` counter family
    (obs/profile.py delegates here; no double bookkeeping)."""
    obs = _OBS if ACTIVE else None
    if obs is None and profile_metrics is None:
        yield
        return
    before = _cache_size(fn) if fn is not None else None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        compiled: Optional[bool] = None
        depth: Optional[int] = None
        if before is not None:
            depth = _cache_size(fn)
            if depth is not None:
                compiled = depth > before
        if profile_metrics is not None:
            _profile_record(
                profile_metrics, site, dt, before, compiled, operands
            )
        if obs is not None:
            try:
                if faults.ACTIVE and faults.fire(FAULT_RECORD) == "drop":
                    obs.metrics.count("devprof.unobserved")
                else:
                    _record(obs, site, dt, compiled, depth, operands, donation)
            except Exception:  # noqa: BLE001 — degrade to unobserved,
                try:  # never block the dispatch
                    obs.metrics.count("devprof.unobserved")
                except Exception:  # noqa: BLE001
                    pass


def _lat(m: Metrics, name: str, dt: float) -> None:
    # observe(), not merge(): this sits on the execute hot path, where
    # the dict-and-generator cost of a one-sample merge is measurable.
    m.observe(name, dt)


def _profile_record(
    m: Metrics,
    name: str,
    dt: float,
    before: Optional[int],
    compiled: Optional[bool],
    operands: Iterable[Any],
) -> None:
    """The legacy ``profile.*`` family, emitted from the same cache-size
    sample devprof classified with — names and semantics unchanged from
    the pre-devprof obs/profile.py (the parity test pins this)."""
    _lat(m, f"profile.dispatch.{name}", dt)
    if before is not None:
        if compiled:
            m.count("profile.jit_misses")
            _lat(m, f"profile.compile.{name}", dt)
        else:
            m.count("profile.jit_hits")
            _lat(m, f"profile.execute.{name}", dt)
    nbytes = _leaf_nbytes(operands)
    if nbytes:
        m.count("profile.h2d_bytes", nbytes)


def _live_buffer_bytes() -> Optional[float]:
    try:
        import jax

        return float(
            sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
        )
    except Exception:  # noqa: BLE001 — telemetry only
        return None


def _record(
    obs: _Observatory,
    site: str,
    dt: float,
    compiled: Optional[bool],
    depth: Optional[int],
    operands: Iterable[Any],
    donation: str,
) -> None:
    m = obs.metrics
    ms = dt * 1e3
    m.count("devprof.dispatches")
    if not compiled:
        # Execute path (or no cache introspection): one histogram sample,
        # nothing else — this is the ≤2% overhead budget's hot case.
        _lat(m, f"devprof.execute.{site}", dt)
        with obs.lock:
            st = obs.sites.get(site)
            if st is not None:
                st["dispatches"] += 1
        return
    # Compile path: capture the signature that triggered it (operands are
    # still live), diff against the site's previous one, and emit on all
    # three surfaces. Compiles are rare, so the pytree walk and the
    # live-buffer sweep are off the steady-state hot path.
    sig = signature(operands, donation)
    nbytes = _leaf_nbytes(operands)
    now_mono = time.monotonic()
    with obs.lock:
        st = obs.sites.get(site)
        if st is None:
            st = obs.sites[site] = {
                "sig": None,
                "compiles": 0,
                "dispatches": 0,
                "retained_bytes": 0,
                "timeline": [],
            }
        changed = signature_diff(st["sig"], sig)
        axis = changed[0]
        st["sig"] = sig
        st["compiles"] += 1
        st["dispatches"] += 1
        st["retained_bytes"] += nbytes
        tl = st["timeline"]
        tl.append(
            {"t": round(time.time(), 6), "axis": axis,
             "ms": round(ms, 3), "depth": depth}
        )
        if len(tl) > _TIMELINE_MAX:
            del tl[: len(tl) - _TIMELINE_MAX]
        retained = st["retained_bytes"]
        obs.recent.append((now_mono, site, ms))
        live = _live_buffer_bytes()
        if live is not None:
            obs.live_bytes = live
            if live > obs.peak_live_bytes:
                obs.peak_live_bytes = live
        peak_live = obs.peak_live_bytes
    m.count("devprof.compiles")
    m.count(f"devprof.compiles.{site}")
    _lat(m, f"devprof.compile.{site}", dt)
    if depth is not None:
        m.set(f"devprof.cache_depth.{site}", float(depth))
    m.set(f"devprof.retained_bytes.{site}", float(retained))
    if live is not None:
        m.set("devprof.live_buffer_bytes", float(live))
        m.set("devprof.live_buffer_peak_bytes", float(peak_live))
    events.emit(
        "devprof.compile",
        site=site,
        ms=round(ms, 3),
        axis=axis,
        changed=changed[:8],
        cache_depth=depth,
        mono=round(now_mono, 6),
        signature=[
            [name, list(shape), dtype, shard]
            for name, shape, dtype, shard in sig["leaves"]
        ],
        donation=donation,
    )


# -- device-memory telemetry ------------------------------------------------


def note_pager(resident_bytes: int, budget: int) -> None:
    """Pager HBM occupancy push (core/pager.py gauge export): resident
    device bytes vs ``CCRDT_PAGER_HBM_BUDGET``, with a high-watermark."""
    obs = _OBS
    if obs is None:
        return
    try:
        used = float(resident_bytes)
        cap = float(budget or 0)
        with obs.lock:
            obs.hbm_used = used
            obs.hbm_budget = cap
            if used > obs.peak_hbm_used:
                obs.peak_hbm_used = used
            peak = obs.peak_hbm_used
        m = obs.metrics
        m.set("devprof.hbm_used_bytes", used)
        m.set("devprof.hbm_budget_bytes", cap)
        m.set("devprof.hbm_occupancy", round(used / cap, 6) if cap else 0.0)
        m.set("devprof.hbm_peak_bytes", peak)
    except Exception:  # noqa: BLE001 — telemetry must never break paging
        pass


# -- rtrace integration -----------------------------------------------------


def compile_ms_in_window(t0: float, t1: float) -> float:
    """Total compile milliseconds whose monotonic stamp landed inside
    [t0, t1] — the rtrace hop window. The serve/ingest echo sites attach
    this as the ``compile_ms`` extra so tail attribution can split
    compile-storm latency out of the ``kernel`` bucket."""
    obs = _OBS
    if obs is None:
        return 0.0
    total = 0.0
    with obs.lock:
        for mono, _site, ms in obs.recent:
            if t0 <= mono <= t1:
                total += ms
    return round(total, 3)


# -- reporting surfaces -----------------------------------------------------


def _totals(obs: _Observatory) -> Tuple[int, int, str, int]:
    compiles = dispatches = 0
    worst, worst_n = "", 0
    for site, st in obs.sites.items():
        compiles += st["compiles"]
        dispatches += st["dispatches"]
        if st["compiles"] > worst_n:
            worst, worst_n = site, st["compiles"]
    return compiles, dispatches, worst, worst_n


def health_fields() -> Dict[str, Any]:
    """`/healthz` block: compile totals, worst churn site, and the
    device-memory gauges (live buffers, HBM occupancy, watermarks)."""
    obs = _OBS
    if obs is None:
        return {}
    with obs.lock:
        compiles, dispatches, worst, worst_n = _totals(obs)
        out = {
            "devprof_compiles": compiles,
            "devprof_dispatches": dispatches,
            "devprof_worst_site": worst,
            "devprof_worst_site_compiles": worst_n,
            "devprof_live_buffer_bytes": int(obs.live_bytes),
            "devprof_live_buffer_peak_bytes": int(obs.peak_live_bytes),
            "devprof_hbm_used_bytes": int(obs.hbm_used),
            "devprof_hbm_budget_bytes": int(obs.hbm_budget),
            "devprof_hbm_peak_bytes": int(obs.peak_hbm_used),
            "devprof_hbm_occupancy": (
                round(obs.hbm_used / obs.hbm_budget, 4)
                if obs.hbm_budget
                else 0.0
            ),
        }
    return out


def status_fields() -> Dict[str, Any]:
    """Dashboard block (obs-<member>.json "devprof"): recompiles/min
    over the trailing minute, worst site, HBM occupancy."""
    obs = _OBS
    if obs is None:
        return {}
    cutoff = time.monotonic() - 60.0
    with obs.lock:
        compiles, _disp, worst, worst_n = _totals(obs)
        per_min = sum(1 for mono, _s, _ms in obs.recent if mono >= cutoff)
        occ = (
            round(obs.hbm_used / obs.hbm_budget, 4) if obs.hbm_budget else 0.0
        )
    return {
        "compiles": compiles,
        "recompiles_per_min": per_min,
        "worst_site": worst,
        "worst_site_compiles": worst_n,
        "hbm_occupancy": occ,
    }


def sites_report() -> Dict[str, Dict[str, Any]]:
    """Per-site snapshot for tests/CLI: compiles, dispatches, retained
    bytes, latest axis, bounded timeline."""
    obs = _OBS
    if obs is None:
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    with obs.lock:
        for site, st in obs.sites.items():
            tl = list(st["timeline"])
            out[site] = {
                "compiles": st["compiles"],
                "dispatches": st["dispatches"],
                "retained_bytes": st["retained_bytes"],
                "last_axis": tl[-1]["axis"] if tl else "",
                "timeline": tl,
            }
    return out
