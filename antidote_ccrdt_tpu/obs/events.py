"""Structured event tracing: a bounded flight-recorder ring + JSONL spill.

The cluster's "what happened" plane. Every runtime layer (net transports,
membership, WAL, bridge, fault registry) emits small typed events into a
per-process `FlightRecorder`:

    {"seq": 17, "t": 1754380000.123456, "member": "w1",
     "kind": "delta.apply", "origin": "w0", "dseq": 4}

* ``seq`` is a per-process monotonic ordinal (the recorder's own lamport
  axis — wall clocks across workers need not agree);
* ``member`` is the process identity (set once via `configure`);
* ``kind`` is a dotted type name; the wired kinds are listed below;
* remaining keys are the event's typed payload.

Trace context: delta events carry ``(origin, dseq)`` — the publishing
replica and its delta sequence number, the same pair the `{packet,4}`
gossip frames already ship in their `{delta, Member, Seq, ...}` terms —
so one logical delta can be followed end to end across every process's
log: ``delta.publish`` (origin) -> ``frame.send``/``transport.delta_write``
(medium) -> ``frame.recv``/``delta.fetch`` (receiver) -> ``delta.apply``
(each peer). `scripts/obs_dashboard.py --demo` reconstructs exactly this
path as its acceptance check.

Durability model (the crash part of "flight recorder"):

* the RING is always on: a bounded `collections.deque` (default 4096) —
  cheap appends, never grows, inspectable in-process via `events()`;
* when ``CCRDT_OBS_DIR`` is set (`install_from_env`, mirroring how
  `utils.faults` propagates `CCRDT_FAULTS` to drill subprocesses), every
  event is ALSO appended, line-buffered, to
  ``<dir>/flight-<member>-<pid>.jsonl``. Line buffering flushes each
  event to the kernel as it happens, so even a SIGKILL — which no
  handler can observe — leaves every emitted event on disk; the `make
  crash-demo` drill asserts the victim's dump ends just before its kill
  point. One file per (member, pid): a restarted incarnation never
  appends to its dead predecessor's log;
* `atexit` + SIGTERM/SIGINT hooks write a final ``proc.exit`` event and
  close the spill — its ABSENCE marks a log as a crash dump.

Wired event kinds:

    delta.publish / delta.fetch / delta.apply / snap.publish / snap.apply
    frame.send / frame.recv / frame.relay  (tcp+sim; origin+dseq trace
                                        context; relay = topo/ anchors)
    topo.anchor_change                 (zone anchor election / failover)
    transport.delta_write              (fs medium; the frame-send analog)
    peer.suspect / peer.dead / peer.realive   (SWIM transitions, with age)
    wal.append / wal.rotate / wal.checkpoint / wal.recover / wal.torn
    wal.durable / wal.truncate         (group-commit flush acks and
                                        watermark truncation: the
                                        published-vs-durable audit axis)
    fault.hit                          (utils.faults firings)
    bridge.request / bridge.reconnect
    serve.query / serve.swap            (read-serving plane: batched
                                        reads answered, replica swaps)
    sim.drop / sim.crash / sim.partition / sim.heal
    proc.start / proc.exit

This module is stdlib-only and imported by nearly every runtime layer —
it must never import back into the package.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

ENV_DIR = "CCRDT_OBS_DIR"
DEFAULT_RING = 4096


class FlightRecorder:
    """One process's bounded event ring + optional line-buffered spill."""

    def __init__(
        self,
        member: str = "?",
        ring: int = DEFAULT_RING,
        spill_path: Optional[str] = None,
    ):
        self.member = member
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.spill_path = spill_path
        self._seq = 0
        self._lock = threading.Lock()
        self._fh = None
        if spill_path is not None:
            os.makedirs(os.path.dirname(spill_path) or ".", exist_ok=True)
            # buffering=1: line-buffered — each event reaches the kernel
            # when its newline is written, which is what makes the spill
            # a usable post-SIGKILL flight record.
            self._fh = open(spill_path, "a", buffering=1)

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        ev: Dict[str, Any] = {"kind": kind, "member": self.member}
        ev.update(fields)
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            ev["t"] = round(time.time(), 6)
            self.ring.append(ev)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(ev, default=str) + "\n")
                except (OSError, ValueError):
                    pass  # a full/closed spill must never crash the caller
        return ev

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self.ring)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def dump(self, path: str) -> int:
        """Write the current ring contents as JSONL; returns event count.
        (The spill file, when enabled, is already the durable record —
        this is for explicit post-mortems and tests.)"""
        evs = self.events()
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev, default=str) + "\n")
        return len(evs)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# -- module-level recorder (the surface the runtime layers use) -------------

_recorder = FlightRecorder()
_hooks_installed = False


def recorder() -> FlightRecorder:
    return _recorder


def emit(kind: str, **fields: Any) -> Dict[str, Any]:
    """Record one event on the process recorder (thread-safe, bounded)."""
    return _recorder.emit(kind, **fields)


def events(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    return _recorder.events(kind)


def dump(path: str) -> int:
    return _recorder.dump(path)


def configure(
    member: str,
    ring: int = DEFAULT_RING,
    spill_dir: Optional[str] = None,
    crash_hooks: bool = True,
) -> FlightRecorder:
    """Replace the process recorder: set its identity, ring bound, and
    (optionally) the spill directory. Emits ``proc.start`` so every log
    opens with the incarnation's identity and pid."""
    global _recorder
    old, spill = _recorder, None
    if spill_dir is not None:
        spill = os.path.join(spill_dir, f"flight-{member}-{os.getpid()}.jsonl")
    _recorder = FlightRecorder(member=member, ring=ring, spill_path=spill)
    old.close()
    if crash_hooks and spill is not None:
        _install_exit_hooks()
    _recorder.emit("proc.start", pid=os.getpid())
    return _recorder


def install_from_env(
    member: str, env: Optional[Dict[str, str]] = None
) -> bool:
    """Enable the disk spill iff ``CCRDT_OBS_DIR`` is set (the same
    supervisor->worker propagation pattern `utils.faults` uses for
    ``CCRDT_FAULTS``). Returns whether a spill was enabled; without the
    env var the in-memory ring still records under `member`'s name."""
    d = (env if env is not None else os.environ).get(ENV_DIR)
    configure(member, spill_dir=d or None)
    return bool(d)


def reset(member: str = "?", ring: int = DEFAULT_RING) -> FlightRecorder:
    """Fresh in-memory recorder (tests)."""
    return configure(member, ring=ring, crash_hooks=False)


def _install_exit_hooks() -> None:
    """atexit + SIGTERM/SIGINT: stamp ``proc.exit`` and close the spill.
    A log WITHOUT a trailing proc.exit is a crash dump (SIGKILL / torn
    process) — the discriminator `crash_recovery_demo` keys on. Handlers
    chain to any previously-installed ones; installation is idempotent
    and skipped off the main thread (signal module restriction)."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    def _finalize() -> None:
        _recorder.emit("proc.exit", pid=os.getpid())
        _recorder.close()

    atexit.register(_finalize)
    if threading.current_thread() is not threading.main_thread():
        return
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev = signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                _finalize()
                if callable(_prev):
                    _prev(signum, frame)
                else:
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(sig, _handler)
        except (OSError, ValueError):
            pass  # non-main interpreter contexts


# -- log readers (dashboard / drills / tests) --------------------------------


def read_log(path: str) -> List[Dict[str, Any]]:
    """Parse one flight JSONL file, skipping any torn tail line (a
    SIGKILL can land mid-write of the final event)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail
    except OSError:
        pass
    return out


def scan_dir(obs_dir: str) -> Dict[str, List[Dict[str, Any]]]:
    """All flight logs in a spill dir: {filename: [events...]}."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return out
    for fn in names:
        if fn.startswith("flight-") and fn.endswith(".jsonl"):
            out[fn] = read_log(os.path.join(obs_dir, fn))
    return out


def delta_paths(
    logs: Dict[str, List[Dict[str, Any]]]
) -> Dict[tuple, Dict[str, List[Dict[str, Any]]]]:
    """Group delta trace events across a fleet's logs by their trace
    context: {(origin, dseq): {stage: [events]}} where stage is one of
    publish/send/write/relay/recv/fetch/apply — the cross-replica
    propagation path of each logical delta (relay = a zone anchor
    forwarding a routed frame, topo/)."""
    stages = {
        "delta.publish": "publish",
        "frame.send": "send",
        "transport.delta_write": "write",
        "frame.relay": "relay",
        "frame.recv": "recv",
        "delta.fetch": "fetch",
        "delta.apply": "apply",
    }
    out: Dict[tuple, Dict[str, List[Dict[str, Any]]]] = {}
    for evs in logs.values():
        for ev in evs:
            stage = stages.get(ev.get("kind", ""))
            if stage is None or "dseq" not in ev or "origin" not in ev:
                continue
            key = (ev["origin"], int(ev["dseq"]))
            out.setdefault(key, {}).setdefault(stage, []).append(ev)
    return out


def iter_kinds(
    logs: Dict[str, List[Dict[str, Any]]], kind: str
) -> Iterator[Dict[str, Any]]:
    for evs in logs.values():
        for ev in evs:
            if ev.get("kind") == kind:
                yield ev
