"""Structured event tracing: a bounded flight-recorder ring + JSONL spill.

The cluster's "what happened" plane. Every runtime layer (net transports,
membership, WAL, bridge, fault registry) emits small typed events into a
per-process `FlightRecorder`:

    {"seq": 17, "t": 1754380000.123456, "member": "w1",
     "kind": "delta.apply", "origin": "w0", "dseq": 4}

* ``seq`` is a per-process monotonic ordinal (the recorder's own lamport
  axis — wall clocks across workers need not agree);
* ``member`` is the process identity (set once via `configure`);
* ``kind`` is a dotted type name; the wired kinds are listed below;
* remaining keys are the event's typed payload.

Trace context: delta events carry ``(origin, dseq)`` — the publishing
replica and its delta sequence number, the same pair the `{packet,4}`
gossip frames already ship in their `{delta, Member, Seq, ...}` terms —
so one logical delta can be followed end to end across every process's
log: ``delta.publish`` (origin) -> ``frame.send``/``transport.delta_write``
(medium) -> ``frame.recv``/``delta.fetch`` (receiver) -> ``delta.apply``
(each peer). `scripts/obs_dashboard.py --demo` reconstructs exactly this
path as its acceptance check.

Durability model (the crash part of "flight recorder"):

* the RING is always on: a bounded `collections.deque` (default 4096) —
  cheap appends, never grows, inspectable in-process via `events()`;
* when ``CCRDT_OBS_DIR`` is set (`install_from_env`, mirroring how
  `utils.faults` propagates `CCRDT_FAULTS` to drill subprocesses), every
  event is ALSO appended, line-buffered, to
  ``<dir>/flight-<member>-<pid>.jsonl``. Line buffering flushes each
  event to the kernel as it happens, so even a SIGKILL — which no
  handler can observe — leaves every emitted event on disk; the `make
  crash-demo` drill asserts the victim's dump ends just before its kill
  point. One file per (member, pid): a restarted incarnation never
  appends to its dead predecessor's log;
* `atexit` + SIGTERM/SIGINT hooks write a final ``proc.exit`` event and
  close the spill — its ABSENCE marks a log as a crash dump.

Wired event kinds:

    delta.publish / delta.fetch / delta.apply / snap.publish / snap.apply
    frame.send / frame.recv / frame.relay  (tcp+sim; origin+dseq trace
                                        context; relay = topo/ anchors)
    topo.anchor_change                 (zone anchor election / failover)
    transport.delta_write              (fs medium; the frame-send analog)
    peer.suspect / peer.dead / peer.realive   (SWIM transitions, with age)
    wal.append / wal.rotate / wal.checkpoint / wal.recover / wal.torn
    wal.durable / wal.truncate         (group-commit flush acks and
                                        watermark truncation: the
                                        published-vs-durable audit axis)
    fault.hit                          (utils.faults firings)
    bridge.request / bridge.reconnect
    serve.query / serve.swap            (read-serving plane: batched
                                        reads answered, replica swaps)
    sim.drop / sim.crash / sim.partition / sim.heal
    proc.start / proc.exit
    ingest.write / ingest.fold / ingest.ack   (write tier; request plane)
    session.write / session.read              (read-tier session audit feed)
    rtrace.trace                              (request tracing, obs/rtrace.py)

Request plane: the high-rate per-request kinds (`REQUEST_KINDS` +
``rtrace.*``) are isolated into per-kind rings and their own
``flight-req-<member>-<pid>.jsonl`` spill — a request flood can never
evict another kind's audit evidence (certify_sessions/certify_writes
replay session.* and ingest.ack/ingest.fold) nor anything in the main
ring. `events()` merges both planes on the shared seq axis; `scan_dir`
picks up both spill streams; lifecycle events are written to both files
so each is self-describing about clean exit vs crash.

This module is stdlib-only and imported by nearly every runtime layer —
it must never import back into the package.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

ENV_DIR = "CCRDT_OBS_DIR"
DEFAULT_RING = 4096
DEFAULT_REQUEST_RING = 1 << 16

# High-rate per-REQUEST kinds: one (or more) of these fires for every
# routed read/write the fleet serves, so a request storm arrives at
# 10^4-10^5 events while the control-plane kinds above trickle. They
# live in their OWN per-kind rings (+ their own spill stream) so a
# flood of one kind can never evict another kind's audit evidence —
# certify_sessions replays session.write/session.read, certify_writes
# replays ingest.ack/ingest.fold, and the PR 14/16 failover drills used
# to work around exactly this eviction with oversize fresh recorders.
# `rtrace.*` events (obs/rtrace.py) are request-plane by prefix.
REQUEST_KINDS = frozenset({
    "serve.query", "ingest.write", "ingest.ack", "ingest.fold",
    "session.write", "session.read", "router.give_up",
    "router.write_give_up", "fault.hit",
})


def _is_request_kind(kind: str) -> bool:
    # `devprof.*` (compile events, obs/devprof.py) ride the request
    # plane too: a recompile storm is exactly the burst shape the
    # per-kind rings exist to isolate, and the line-buffered req spill
    # is what makes compile evidence survive a SIGKILL.
    return (
        kind in REQUEST_KINDS
        or kind.startswith("rtrace.")
        or kind.startswith("devprof.")
    )


class FlightRecorder:
    """One process's bounded event rings + optional line-buffered spill.

    Two planes share one seq axis (so merged replay order is total):

    * the MAIN ring holds control-plane events (gossip, SWIM, WAL,
      topo, ...) at `ring` capacity;
    * request-plane kinds (`REQUEST_KINDS` + ``rtrace.*``) get one ring
      EACH at `req_ring` capacity and spill to a separate
      ``flight-req-*`` stream — per-kind isolation means a serve.query
      flood can never evict ingest.fold/ingest.ack audit evidence, and
      nothing request-shaped can touch the main ring at all.
    """

    def __init__(
        self,
        member: str = "?",
        ring: int = DEFAULT_RING,
        spill_path: Optional[str] = None,
        req_ring: int = DEFAULT_REQUEST_RING,
        req_spill_path: Optional[str] = None,
    ):
        self.member = member
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.req_ring_max = int(req_ring)
        self.req_rings: Dict[str, collections.deque] = {}
        self.spill_path = spill_path
        self.req_spill_path = req_spill_path
        self._seq = 0
        self._lock = threading.Lock()
        self._fh = None
        self._req_fh = None
        if spill_path is not None:
            os.makedirs(os.path.dirname(spill_path) or ".", exist_ok=True)
            # buffering=1: line-buffered — each event reaches the kernel
            # when its newline is written, which is what makes the spill
            # a usable post-SIGKILL flight record.
            self._fh = open(spill_path, "a", buffering=1)
        if req_spill_path is not None:
            os.makedirs(
                os.path.dirname(req_spill_path) or ".", exist_ok=True
            )
            self._req_fh = open(req_spill_path, "a", buffering=1)

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        ev: Dict[str, Any] = {"kind": kind, "member": self.member}
        ev.update(fields)
        req = _is_request_kind(kind)
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            ev["t"] = round(time.time(), 6)
            if req:
                ring = self.req_rings.get(kind)
                if ring is None:
                    ring = self.req_rings[kind] = collections.deque(
                        maxlen=self.req_ring_max
                    )
                ring.append(ev)
            else:
                self.ring.append(ev)
            fh = self._req_fh if req else self._fh
            if fh is None and req:
                fh = self._fh  # request spill follows the main spill
            fhs = [fh] if fh is not None else []
            if kind in ("proc.start", "proc.exit") \
                    and self._req_fh is not None:
                # Lifecycle events land in BOTH spills: every flight
                # file must be self-describing about whether its
                # incarnation exited cleanly (certify_writes reads the
                # absence of proc.exit as a crash dump, per file).
                fhs.append(self._req_fh)
            for f in fhs:
                try:
                    f.write(json.dumps(ev, default=str) + "\n")
                except (OSError, ValueError):
                    pass  # a full/closed spill must never crash the caller
        return ev

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if kind is not None:
                src = self.req_rings.get(kind) if _is_request_kind(kind) \
                    else self.ring
                evs = list(src) if src is not None else []
            else:
                evs = list(self.ring)
                for ring in self.req_rings.values():
                    evs.extend(ring)
                evs.sort(key=lambda e: e["seq"])
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def dump(self, path: str) -> int:
        """Write the current ring contents as JSONL; returns event count.
        (The spill file, when enabled, is already the durable record —
        this is for explicit post-mortems and tests.)"""
        evs = self.events()
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev, default=str) + "\n")
        return len(evs)

    def close(self) -> None:
        with self._lock:
            for attr in ("_fh", "_req_fh"):
                fh = getattr(self, attr)
                if fh is not None:
                    try:
                        fh.close()
                    except OSError:
                        pass
                    setattr(self, attr, None)


# -- module-level recorder (the surface the runtime layers use) -------------

_recorder = FlightRecorder()
_hooks_installed = False


def recorder() -> FlightRecorder:
    return _recorder


def emit(kind: str, **fields: Any) -> Dict[str, Any]:
    """Record one event on the process recorder (thread-safe, bounded)."""
    return _recorder.emit(kind, **fields)


def events(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    return _recorder.events(kind)


def dump(path: str) -> int:
    return _recorder.dump(path)


def configure(
    member: str,
    ring: int = DEFAULT_RING,
    spill_dir: Optional[str] = None,
    crash_hooks: bool = True,
    req_ring: int = DEFAULT_REQUEST_RING,
) -> FlightRecorder:
    """Replace the process recorder: set its identity, ring bounds, and
    (optionally) the spill directory. Emits ``proc.start`` so every log
    opens with the incarnation's identity and pid."""
    global _recorder
    old, spill, req_spill = _recorder, None, None
    if spill_dir is not None:
        spill = os.path.join(spill_dir, f"flight-{member}-{os.getpid()}.jsonl")
        req_spill = os.path.join(
            spill_dir, f"flight-req-{member}-{os.getpid()}.jsonl"
        )
    _recorder = FlightRecorder(
        member=member, ring=ring, spill_path=spill,
        req_ring=req_ring, req_spill_path=req_spill,
    )
    old.close()
    if crash_hooks and spill is not None:
        _install_exit_hooks()
    _recorder.emit("proc.start", pid=os.getpid())
    return _recorder


def install_from_env(
    member: str, env: Optional[Dict[str, str]] = None
) -> bool:
    """Enable the disk spill iff ``CCRDT_OBS_DIR`` is set (the same
    supervisor->worker propagation pattern `utils.faults` uses for
    ``CCRDT_FAULTS``). Returns whether a spill was enabled; without the
    env var the in-memory ring still records under `member`'s name."""
    d = (env if env is not None else os.environ).get(ENV_DIR)
    configure(member, spill_dir=d or None)
    return bool(d)


def reset(
    member: str = "?",
    ring: int = DEFAULT_RING,
    req_ring: int = DEFAULT_REQUEST_RING,
) -> FlightRecorder:
    """Fresh in-memory recorder (tests)."""
    return configure(member, ring=ring, crash_hooks=False, req_ring=req_ring)


def _install_exit_hooks() -> None:
    """atexit + SIGTERM/SIGINT: stamp ``proc.exit`` and close the spill.
    A log WITHOUT a trailing proc.exit is a crash dump (SIGKILL / torn
    process) — the discriminator `crash_recovery_demo` keys on. Handlers
    chain to any previously-installed ones; installation is idempotent
    and skipped off the main thread (signal module restriction)."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    def _finalize() -> None:
        _recorder.emit("proc.exit", pid=os.getpid())
        _recorder.close()

    atexit.register(_finalize)
    if threading.current_thread() is not threading.main_thread():
        return
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev = signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                _finalize()
                if callable(_prev):
                    _prev(signum, frame)
                else:
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(sig, _handler)
        except (OSError, ValueError):
            pass  # non-main interpreter contexts


# -- log readers (dashboard / drills / tests) --------------------------------


def read_log(path: str) -> List[Dict[str, Any]]:
    """Parse one flight JSONL file, skipping any torn tail line (a
    SIGKILL can land mid-write of the final event)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail
    except OSError:
        pass
    return out


def scan_dir(obs_dir: str) -> Dict[str, List[Dict[str, Any]]]:
    """All flight logs in a spill dir: {filename: [events...]}."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return out
    for fn in names:
        if fn.startswith("flight-") and fn.endswith(".jsonl"):
            out[fn] = read_log(os.path.join(obs_dir, fn))
    return out


def delta_paths(
    logs: Dict[str, List[Dict[str, Any]]]
) -> Dict[tuple, Dict[str, List[Dict[str, Any]]]]:
    """Group delta trace events across a fleet's logs by their trace
    context: {(origin, dseq): {stage: [events]}} where stage is one of
    publish/send/write/relay/recv/fetch/apply — the cross-replica
    propagation path of each logical delta (relay = a zone anchor
    forwarding a routed frame, topo/)."""
    stages = {
        "delta.publish": "publish",
        "frame.send": "send",
        "transport.delta_write": "write",
        "frame.relay": "relay",
        "frame.recv": "recv",
        "delta.fetch": "fetch",
        "delta.apply": "apply",
    }
    out: Dict[tuple, Dict[str, List[Dict[str, Any]]]] = {}
    for evs in logs.values():
        for ev in evs:
            stage = stages.get(ev.get("kind", ""))
            if stage is None or "dseq" not in ev or "origin" not in ev:
                continue
            key = (ev["origin"], int(ev["dseq"]))
            out.setdefault(key, {}).setdefault(stage, []).append(ev)
    return out


def iter_kinds(
    logs: Dict[str, List[Dict[str, Any]]], kind: str
) -> Iterator[Dict[str, Any]]:
    for evs in logs.values():
        for ev in evs:
            if ev.get("kind") == kind:
                yield ev
