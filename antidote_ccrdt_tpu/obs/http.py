"""OpenMetrics HTTP scrape endpoint: one stdlib server thread per worker.

`obs.export` made exit dumps mergeable; this makes a RUNNING worker
scrapeable. Each worker starts one `MetricsHttpServer` (a
`ThreadingHTTPServer` on its own daemon thread — stdlib only, no
framework) serving:

* ``GET /metrics``  — the live `Metrics` rendered by
  `export.prometheus_text` (histogram buckets included), with a
  per-worker ``member`` label so a Prometheus scraping the whole fleet
  can tell the series apart. Content-Type is the Prometheus text
  exposition type. When the rtrace plane is armed the read/write
  latency histograms carry OpenMetrics exemplars (``#
  {trace_id="..."}``) pointing at the stored request trace behind the
  worst observed latency.
* ``GET /healthz``  — `{"ok": true, "member": ..., "uptime_s": ...}`,
  the liveness probe a supervisor or k8s deployment points at. With a
  ``health_extra`` callable installed, the doc gains serving-readiness
  fields (max peer staleness, applied watermark, overlap queue depth,
  serve-plane snapshot seq) so a load balancer can drain a worker whose
  replica lags instead of routing stale reads to it.
* ``POST /query``   — the serve plane's HTTP surface: the request body
  is the canonical query payload, the response the canonical answer
  bytes (byte-identical to the tcp ``{query}`` frame and the bridge op
  for the same request). 404 until a handler is installed.
* ``POST /write``   — the ingest plane's HTTP surface (PR 16): the body
  is the canonical write payload (bare JSON or a ``CCRF`` range frame),
  the response the canonical tiered ack bytes — byte-identical to the
  tcp ``{write}`` frame and the bridge op. 404 until installed.

Both POST surfaces carry an rtrace ``"trace"`` context in the request
doc and the ``"rtrace"`` echo in the response opaquely — the body bytes
are handed to the plane verbatim, so request tracing works identically
over HTTP, tcp, sim, and the bridge.

Failure behavior mirrors the transports' "degrade, never hang" rule: a
snapshot/render failure returns a 500 with the error text — the scrape
fails loudly, the NEXT scrape sees a clean registry (`Metrics.snapshot`
hands out copies under its lock, so a failed render can never corrupt
the live counters), and request handling stays bounded by the socket
timeout.

Workers opt in via ``CCRDT_HTTP_PORT`` (`install_from_env` — same
supervisor->worker env propagation as ``CCRDT_FAULTS`` /
``CCRDT_OBS_DIR``). Port ``0`` asks the kernel for a free port; the
bound address is dropped as ``http-<member>`` into `addr_dir` (atomic
replace, like the TCP drill's ``addr-<member>`` rendezvous files) so
the supervisor can discover scrape targets it spawned with port 0.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

from . import export as obs_export

ENV_PORT = "CCRDT_HTTP_PORT"

# The classic Prometheus text exposition content type (version 0.0.4 is
# what every Prometheus accepts; the OpenMetrics negotiation upgrade is
# backward compatible with this payload).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHttpServer:
    """Serve one worker's metrics over HTTP from a daemon thread.

    `source` is a `Metrics` instance or a zero-arg callable returning
    one (or a snapshot dict) — called per scrape, so the text always
    reflects the registry at scrape time."""

    def __init__(
        self,
        source: Union[Any, Callable[[], Any]],
        member: str,
        host: str = "127.0.0.1",
        port: int = 0,
        labels: Optional[Dict[str, str]] = None,
        query_handler: Optional[Callable[[bytes], bytes]] = None,
        health_extra: Optional[Callable[[], Dict[str, Any]]] = None,
        write_handler: Optional[Callable[[bytes], bytes]] = None,
    ):
        self.member = member
        self._source = source
        self._labels = dict(labels) if labels else {"member": member}
        self._t0 = time.time()
        self.query_handler = query_handler
        self.write_handler = write_handler
        self.health_extra = health_extra
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # Bound per-request: a wedged scraper releases the handler
            # thread instead of pinning it forever.
            timeout = 10.0

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] == "/metrics":
                    outer._serve_metrics(self)
                elif self.path.split("?", 1)[0] == "/healthz":
                    outer._serve_health(self)
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def do_POST(self):  # noqa: N802 - http.server API
                path = self.path.split("?", 1)[0]
                if path == "/query":
                    outer._serve_query(self)
                elif path == "/write":
                    outer._serve_write(self)
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam worker stdout

        class Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name=f"ccrdt-http-{member}",
            daemon=True,
        )

    # -- handlers ----------------------------------------------------------

    def _snapshot_source(self) -> Any:
        return self._source() if callable(self._source) else self._source

    def _serve_metrics(self, handler) -> None:
        try:
            text = obs_export.prometheus_text(
                self._snapshot_source(), labels=self._labels
            )
        except Exception as e:  # noqa: BLE001 — degrade to an error
            # response; the registry itself is untouched (snapshot() is
            # a copy) and the next scrape starts clean.
            handler._reply(
                500, f"# scrape failed: {e}\n".encode("utf-8"), "text/plain"
            )
            return
        handler._reply(200, text.encode("utf-8"), CONTENT_TYPE)

    def _serve_health(self, handler) -> None:
        doc = {
            "ok": True,
            "member": self.member,
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._t0, 3),
        }
        extra = self.health_extra
        if extra is not None:
            try:
                doc.update(extra())
            except Exception as e:  # noqa: BLE001 — a broken readiness
                # probe must not take liveness down with it; flag it.
                doc["health_extra_error"] = str(e)
        handler._reply(
            200, (json.dumps(doc) + "\n").encode("utf-8"), "application/json"
        )

    def _serve_query(self, handler) -> None:
        fn = self.query_handler
        if fn is None:
            handler._reply(404, b"no serve plane\n", "text/plain")
            return
        try:
            n = int(handler.headers.get("Content-Length", "0"))
            body = handler.rfile.read(n) if n > 0 else b""
            resp = bytes(fn(body))
        except Exception as e:  # noqa: BLE001 — degrade to an error
            # response; the plane's registry/caches are lock-protected
            # and the next query starts clean.
            handler._reply(
                500, f"query failed: {e}\n".encode("utf-8"), "text/plain"
            )
            return
        handler._reply(200, resp, "application/json")

    def _serve_write(self, handler) -> None:
        fn = self.write_handler
        if fn is None:
            handler._reply(404, b"no ingest plane\n", "text/plain")
            return
        try:
            n = int(handler.headers.get("Content-Length", "0"))
            body = handler.rfile.read(n) if n > 0 else b""
            resp = bytes(fn(body))
        except Exception as e:  # noqa: BLE001 — degrade to an error
            # response; the writer retries idempotently by write_id.
            handler._reply(
                500, f"write failed: {e}\n".encode("utf-8"), "text/plain"
            )
            return
        handler._reply(200, resp, "application/json")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MetricsHttpServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def write_addr_file(addr_dir: str, member: str, addr: Tuple[str, int]) -> str:
    """Drop ``http-<member>`` = "host:port" (atomic replace) so a
    supervisor can discover a port-0 endpoint; returns the path."""
    os.makedirs(addr_dir, exist_ok=True)
    path = os.path.join(addr_dir, f"http-{member}")
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{addr[0]}:{addr[1]}")
    os.replace(tmp, path)
    return path


def read_addr_files(addr_dir: str) -> Dict[str, Tuple[str, int]]:
    """{member: (host, port)} for every ``http-<member>`` drop in a dir
    (torn writes skipped — the next poll sees them whole)."""
    out: Dict[str, Tuple[str, int]] = {}
    try:
        names = os.listdir(addr_dir)
    except OSError:
        return out
    for fn in names:
        if not fn.startswith("http-") or ".tmp" in fn:
            continue
        try:
            with open(os.path.join(addr_dir, fn)) as f:
                host, port = f.read().strip().rsplit(":", 1)
            out[fn[len("http-"):]] = (host, int(port))
        except (OSError, ValueError):
            continue
    return out


def install_from_env(
    source: Any,
    member: str,
    env: Optional[Dict[str, str]] = None,
    addr_dir: Optional[str] = None,
    query_handler: Optional[Callable[[bytes], bytes]] = None,
    health_extra: Optional[Callable[[], Dict[str, Any]]] = None,
    write_handler: Optional[Callable[[bytes], bytes]] = None,
) -> Optional[MetricsHttpServer]:
    """Start a metrics endpoint iff ``CCRDT_HTTP_PORT`` is set (port 0 =
    kernel-assigned). Returns the running server, or None when the env
    var is absent/unparseable — workers call this unconditionally, like
    `faults.install_from_env`. With `addr_dir`, the bound address is
    dropped as ``http-<member>`` for supervisor discovery."""
    raw = (env if env is not None else os.environ).get(ENV_PORT)
    if raw is None or raw == "":
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    srv = MetricsHttpServer(
        source,
        member,
        port=port,
        query_handler=query_handler,
        health_extra=health_extra,
        write_handler=write_handler,
    ).start()
    if addr_dir:
        write_addr_file(addr_dir, member, srv.address)
    return srv
