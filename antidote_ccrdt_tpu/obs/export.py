"""Metrics export: Prometheus text + JSONL, and cross-process merge.

`utils.metrics.Metrics` is process-local by design; a drill fleet is
many processes. This module closes both gaps:

* **Formats** — `prometheus_text()` renders a `Metrics.snapshot()` in
  Prometheus/OpenMetrics exposition format (names `ccrdt_`-prefixed,
  dots to underscores, HELP/TYPE lines, latencies as CUMULATIVE
  histograms: per-bucket `_bucket{le="..."}` counts over a fixed
  exponential-ish bound ladder plus `_sum`/`_count`); `jsonl_lines()`
  renders the same snapshot one-metric-per-line for log pipelines.
  Histograms (not summaries) because a real Prometheus scraping many
  workers must be able to AGGREGATE latency across the fleet — bucket
  counts sum across scrape targets, per-worker quantiles do not.

* **Aggregation** — workers dump a snapshot at exit to
  ``$CCRDT_METRICS_DIR/metrics-<member>-<pid>.json``
  (`install_atexit_dump`, gated on the env var exactly like
  `utils.faults`' ``CCRDT_FAULTS``), and the supervising parent folds
  every dump into one fleet-wide `Metrics` via `merge_dir` — counters
  sum, latency samples concatenate, so fleet percentiles are computed
  over the union of samples rather than averaging per-worker
  percentiles (which would be wrong).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.metrics import Metrics

ENV_DIR = "CCRDT_METRICS_DIR"

# Histogram bucket upper bounds, in seconds. Spans a sub-millisecond jit
# cache hit through a multi-second convergence round; the ladder is fixed
# (not data-derived) so bucket counts from different workers and different
# scrapes of the same worker line up and can be summed by Prometheus.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _san(name: str, prefix: str) -> str:
    return f"{prefix}_{_NAME_RE.sub('_', name)}"


def _labels(labels: Optional[Dict[str, str]], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted((labels or {}).items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _as_snapshot(src: Any) -> Dict[str, Any]:
    return src.snapshot() if isinstance(src, Metrics) else src


def prometheus_text(
    src: Any,
    prefix: str = "ccrdt",
    labels: Optional[Dict[str, str]] = None,
    buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    exemplars: Optional[Dict[str, Tuple[str, float]]] = None,
) -> str:
    """Render a `Metrics` (or a `snapshot()` dict) as Prometheus
    exposition text. Counters/gauges share one value dict upstream, so
    every scalar is exported as a gauge (monotonic-by-construction names
    still read correctly; Prometheus treats TYPE as advisory). Latency
    series become cumulative histograms: `_bucket{le="..."}` counts over
    `buckets` (each bucket includes everything at or below its bound,
    `+Inf` always equals `_count`), plus `_sum`/`_count` — derived from
    the raw samples `Metrics` keeps, so fleet aggregation can sum bucket
    counts across workers.

    `exemplars` maps a latency family name to ``(trace_id, ms)``; each
    gets an OpenMetrics exemplar (`` # {trace_id="..."} value``) on the
    bucket its value falls in, so a dashboard's p99 panel links to the
    STORED request trace that latency came from (``scripts/
    ccrdt_rtrace.py waterfall <id>`` decomposes it). By default the live
    rtrace plane's exemplars are used — dark plane, no exemplars, and
    the output is byte-identical to the pre-exemplar format."""
    if exemplars is None:
        from . import rtrace

        exemplars = rtrace.exemplars()
    snap = _as_snapshot(src)
    lines: List[str] = []
    for name in sorted(snap.get("counters", {})):
        m = _san(name, prefix)
        lines.append(f"# HELP {m} ccrdt counter/gauge {name}")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{_labels(labels)} {_num(snap['counters'][name])}")
    for name in sorted(snap.get("latencies", {})):
        samples = snap["latencies"][name]
        m = _san(name, prefix) + "_seconds"
        lines.append(f"# HELP {m} ccrdt latency {name}")
        lines.append(f"# TYPE {m} histogram")
        if samples:
            a = np.sort(np.asarray(samples, dtype=float))
            # Cumulative count at each bound: index of the first sample
            # strictly above it (le is inclusive).
            cum = np.searchsorted(a, np.asarray(buckets), side="right")
            total, count = float(a.sum()), int(a.size)
        else:
            cum = np.zeros(len(buckets), dtype=int)
            total, count = 0.0, 0
        ex = (exemplars or {}).get(name)
        ex_s = float(ex[1]) / 1e3 if ex else None  # exemplar ms -> s
        ex_bucket = None
        if ex_s is not None:
            # The exemplar annotates the first bucket that contains its
            # value (OpenMetrics requires value <= le); past the ladder
            # it rides +Inf.
            ex_bucket = next(
                (le for le in buckets if ex_s <= le), "+Inf"
            )
        for le, c in zip(buckets, cum):
            ll = 'le="%g"' % le
            suffix = ""
            if ex_bucket == le:
                suffix = f' # {{trace_id="{ex[0]}"}} {_num_f(ex_s)}'
            lines.append(f"{m}_bucket{_labels(labels, ll)} {int(c)}{suffix}")
        inf = 'le="+Inf"'
        suffix = ""
        if ex_bucket == "+Inf":
            suffix = f' # {{trace_id="{ex[0]}"}} {_num_f(ex_s)}'
        lines.append(f"{m}_bucket{_labels(labels, inf)} {count}{suffix}")
        lines.append(f"{m}_sum{_labels(labels)} {_num(total)}")
        lines.append(f"{m}_count{_labels(labels)} {count}")
    return "\n".join(lines) + "\n"


def _num_f(v: float) -> str:
    return "%g" % float(v)


def _num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def jsonl_lines(
    src: Any, member: Optional[str] = None
) -> List[str]:
    """One JSON object per metric — counters as {"metric", "value"},
    latencies as {"metric", "summary": {...percentiles...}}."""
    snap = _as_snapshot(src)
    base: Dict[str, Any] = {} if member is None else {"member": member}
    out: List[str] = []
    for name in sorted(snap.get("counters", {})):
        out.append(json.dumps(
            {**base, "metric": name, "value": snap["counters"][name]}
        ))
    for name in sorted(snap.get("latencies", {})):
        samples = snap["latencies"][name]
        summ: Dict[str, Any] = {"n": len(samples)}
        if samples:
            a = np.asarray(samples, dtype=float)
            summ.update(
                p50_ms=float(np.percentile(a, 50) * 1e3),
                p90_ms=float(np.percentile(a, 90) * 1e3),
                p99_ms=float(np.percentile(a, 99) * 1e3),
                total_s=float(a.sum()),
            )
        out.append(json.dumps({**base, "metric": name, "summary": summ}))
    return out


# -- cross-process aggregation (CCRDT_METRICS_DIR) ---------------------------


def dump_snapshot(
    metrics: Metrics, member: str, metrics_dir: str
) -> str:
    """Write this process's snapshot to
    ``<dir>/metrics-<member>-<pid>.json``; returns the path. Write is
    atomic (tmp + replace) so a parent merging mid-dump never reads a
    torn file."""
    os.makedirs(metrics_dir, exist_ok=True)
    path = os.path.join(metrics_dir, f"metrics-{member}-{os.getpid()}.json")
    doc = {"member": member, "pid": os.getpid(), "t": time.time()}
    doc.update(metrics.snapshot())
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def install_atexit_dump(
    metrics: Metrics, member: str, env: Optional[Dict[str, str]] = None
) -> bool:
    """Register an atexit snapshot dump iff ``CCRDT_METRICS_DIR`` is set
    (same supervisor->worker env propagation as ``CCRDT_FAULTS``).
    Returns whether a dump was armed. A SIGKILLed worker leaves no
    metrics dump — by design; its flight-recorder spill (obs.events) is
    the crash-durable record."""
    d = (env if env is not None else os.environ).get(ENV_DIR)
    if not d:
        return False
    atexit.register(lambda: dump_snapshot(metrics, member, d))
    return True


def load_snapshots(metrics_dir: str) -> Dict[str, Dict[str, Any]]:
    """{filename: snapshot-doc} for every metrics dump in a dir."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(metrics_dir))
    except OSError:
        return out
    for fn in names:
        if fn.startswith("metrics-") and fn.endswith(".json"):
            try:
                with open(os.path.join(metrics_dir, fn)) as f:
                    out[fn] = json.load(f)
            except (OSError, ValueError):
                continue
    return out


def merge_dir(metrics_dir: str) -> Tuple[Metrics, List[str]]:
    """Fold every worker dump in `dir` into one fleet-wide `Metrics`.
    Returns (merged, member-names-merged)."""
    merged = Metrics()
    members: List[str] = []
    for doc in load_snapshots(metrics_dir).values():
        merged.merge(doc)
        members.append(str(doc.get("member", "?")))
    return merged, members
