"""Cluster observability plane: tracing, lag, export.

Three layers, threaded through every runtime subsystem:

* `obs.events` — structured flight-recorder ring + crash-durable JSONL
  spill (``CCRDT_OBS_DIR``); delta trace-context for end-to-end
  propagation-path reconstruction.
* `obs.lag` — per-peer replication lag (ops + seconds) from delta-seq
  watermarks, and the fleet digest-agreement probe.
* `obs.export` — Prometheus/JSONL rendering of `Metrics` snapshots and
  cross-process aggregation (``CCRDT_METRICS_DIR``).

`obs.events` stays stdlib-only so transports, WAL, bridge, and the
fault registry can import it without cycles; `obs.lag`/`obs.export`
may import package code and are pulled in lazily by the layers that
need them.
"""

from . import events  # noqa: F401  (stdlib-only, safe for all importers)

__all__ = ["events", "lag", "export"]
