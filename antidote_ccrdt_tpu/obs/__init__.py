"""Cluster observability plane: tracing, lag, export, live scraping.

Five layers, threaded through every runtime subsystem:

* `obs.events` — structured flight-recorder ring + crash-durable JSONL
  spill (``CCRDT_OBS_DIR``); delta trace-context for end-to-end
  propagation-path reconstruction.
* `obs.lag` — per-peer replication lag (ops + seconds) from delta-seq
  watermarks, and the fleet digest-agreement probe.
* `obs.export` — Prometheus/JSONL rendering of `Metrics` snapshots
  (latencies as cumulative histograms) and cross-process aggregation
  (``CCRDT_METRICS_DIR``).
* `obs.http` — per-worker OpenMetrics HTTP endpoint (``/metrics`` +
  ``/healthz``, gated on ``CCRDT_HTTP_PORT``) so a live fleet is
  scrapeable without waiting for exit dumps.
* `obs.profile` — XLA hot-path profiler for the batched update/merge
  dispatch (wall time, jit compile/execute split, transfer bytes),
  ACTIVE-gated to zero cost when off (``CCRDT_PROFILE``).
* `obs.spans` — round-phase span tracer (``CCRDT_SPANS``): begin/end
  monotonic spans over the nine worker-round phases, NTP-style per-peer
  clock offsets for fleet-wide timeline alignment, Perfetto/Chrome
  trace-event export, and dispatch-gap attribution.

`obs.events` and `obs.spans` stay stdlib-only so transports, WAL,
bridge, and the fault registry can import them without cycles; the
other modules may import package code and are pulled in lazily by the
layers that need them.
"""

from . import events  # noqa: F401  (stdlib-only, safe for all importers)
from . import spans  # noqa: F401  (stdlib-only, safe for all importers)

__all__ = ["events", "lag", "export", "http", "profile", "spans"]
