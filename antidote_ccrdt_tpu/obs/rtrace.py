"""Request-scoped tracing: per-request hop records + tail attribution.

The spans plane (obs/spans.py) is worker-centric: it explains where a
*round* spends its time inside one process. Since the read tier (PR 14)
and write tier (PR 16) made queries and writes fleet products, a
request's latency is born ACROSS processes — HRW route decisions,
breaker verdicts, hedged attempts, serve-plane queue time, kernel
folds, WAL-durability waits, ack-tier probes — and a p99 scraped off
one worker decomposes none of it. This module is the request-scoped
counterpart:

* `FleetRouter.query()` / `WriteRouter.write()` call `begin()` to mint
  a trace context ``(trace_id, hop_seq)`` and record typed client hops
  (`route`, `attempt`, `hedge_launch`, `dead_reroute`, `backoff`,
  `ack_probe`) as the request progresses;
* when the trace is head-sampled, `Trace.wire()` returns a small
  ``{"id", "hs"}`` doc the router embeds in the request's canonical
  JSON — the payload is transport-opaque, so the SAME bytes propagate
  unchanged over the tcp `{query}`/`{write}` frames, the sim's in-band
  messages, the bridge ops, and `POST /query`·`/write`;
* the serve/ingest planes call `server_trace()` on a traced request,
  stamp their stage marks on THEIR monotonic clock (enqueue → drain →
  kernel for reads; stage → fold → durable wait for writes), and
  attach the echo to the response — an UNtraced request produces a
  byte-identical response to the pre-trace wire format (the tri-surface
  parity tests pin this);
* the client absorbs each echo together with the attempt's local
  send/recv times; that pair IS an NTP exchange, so the PR 6
  `ClockSync` min-RTT filter recovers per-peer clock offsets and the
  waterfall assembles on ONE aligned timeline without scraping any
  worker.

Storage: committed traces are bounded three ways — a main ring, a
slow-request ring (the N slowest survive even a flood of fast ones),
and one ``rtrace.trace`` flight-recorder event per commit, which the
request-event stream (obs/events.py) spills to disk for the CLI
(`scripts/ccrdt_rtrace.py`) and post-mortems. Head sampling
(``CCRDT_RTRACE_SAMPLE``) bounds the server-side cost; requests that
end shed / failed / deadline-exceeded are ALWAYS committed (their
client hops need no server cooperation). ``CCRDT_RTRACE=0`` is the
kill switch: no mint, no echo, byte-identical wire traffic.

Degradation: every record path is guarded by the ``rtrace.record``
fault point and a bare except — tracing can degrade a request to
untraced but can never block or fail it.

Attribution decomposes client-observed latency into SEVEN buckets that
sum to the observed total (coverage ~1.0 by construction, lost only to
clock-mapping clips)::

    route         client-side routing decisions (candidate order,
                  breaker verdicts, staleness demotion)
    backoff       sleeps between retry rounds
    wire          attempt time not explained by the server (network +
                  connect + router poll slop)
    queue_wait    serve-plane enqueue->drain / ingest stage->fold wait
    kernel        device fold / materialize inside the winning server
    ack_probe     durability wait + replicated_to_k probes (writes)
    hedge_overlap duplicated in-flight time (Σ attempts − their union;
                  reported alongside, not double-counted in the sum)

Stdlib-only (numpy/jax-free); imports only sibling obs modules that
are themselves stdlib-only.
"""

from __future__ import annotations

import collections
import heapq
import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from . import events as obs_events
from .spans import ClockSync, _pctl, _union
from ..utils import faults

ENV = "CCRDT_RTRACE"
ENV_SAMPLE = "CCRDT_RTRACE_SAMPLE"

DEFAULT_RING = 2048
DEFAULT_SLOW = 64

BUCKETS = (
    "route", "backoff", "wire", "queue_wait", "kernel", "ack_probe",
    "hedge_overlap",
)

# Outcomes that force a commit regardless of the head-sample decision:
# failures are exactly the traces nobody can afford to have sampled out.
FORCED_OUTCOMES = ("shed", "failed", "deadline", "uncovered")

# Hot-path gate — call sites check `if rtrace.ACTIVE:` first.
ACTIVE = False

_PLANE: Optional["_Plane"] = None


def _killed(env: Optional[Dict[str, str]] = None) -> bool:
    return (env if env is not None else os.environ).get(ENV, "") == "0"


class Trace:
    """One request's client-side trace: id, ordered hops, server echoes.

    Thread-safe (attempt threads record concurrently); every mutator is
    wrapped so a failure degrades the trace to dead, never the request.
    """

    __slots__ = (
        "id", "kind", "key", "member", "sampled", "t0", "hops", "server",
        "dead", "outcome", "ms", "_hs", "_lock",
    )

    def __init__(self, tid: str, kind: str, key: str, member: str,
                 sampled: bool, t0: float):
        self.id = tid
        self.kind = kind          # "read" | "write"
        self.key = key
        self.member = member
        self.sampled = sampled
        self.t0 = t0              # client monotonic at mint
        self.hops: List[Dict[str, Any]] = []
        self.server: List[Dict[str, Any]] = []
        self.dead = False         # degraded: stop recording, stay silent
        self.outcome = ""
        self.ms = 0.0
        self._hs = 0
        self._lock = threading.Lock()

    def hop(self, kind: str, t0: float, t1: Optional[float] = None,
            **fields: Any) -> None:
        """Record one typed client hop [t0, t1] (point events pass only
        t0). Guarded by the ``rtrace.record`` fault point: an injected
        drop/raise degrades THIS trace to untraced and returns."""
        if self.dead:
            return
        try:
            if faults.ACTIVE and faults.fire("rtrace.record") != "ok":
                raise OSError("injected rtrace drop")
            h = {"k": kind, "t0": round(t0, 6),
                 "t1": round(t1 if t1 is not None else t0, 6), **fields}
            with self._lock:
                h["hs"] = self._hs
                self._hs += 1
                self.hops.append(h)
        except Exception:  # noqa: BLE001 — degrade, never fail the request
            self.dead = True
            p = _PLANE
            if p is not None:
                p.bump("degraded")

    def wire(self) -> Optional[Dict[str, Any]]:
        """The context embedded in the request doc — only head-sampled
        traces ask the servers to do work, so the fleet-side cost scales
        with the sample rate, not the request rate."""
        if self.dead or not self.sampled:
            return None
        with self._lock:
            return {"id": self.id, "hs": self._hs}

    def absorb_echo(self, echo: Dict[str, Any], t_send: float,
                    t_recv: float) -> None:
        """Attach one server echo, and feed the (send, server-mid,
        recv) triple to the ClockSync — every traced response doubles
        as an NTP exchange."""
        if self.dead or not isinstance(echo, dict):
            return
        try:
            e = dict(echo)
            e["t_send"] = round(t_send, 6)
            e["t_recv"] = round(t_recv, 6)
            with self._lock:
                self.server.append(e)
            p = _PLANE
            peer = e.get("peer")
            m_in, m_out = e.get("m_in"), e.get("m_out")
            if p is not None and peer and m_in is not None \
                    and m_out is not None:
                p.clock.note(str(peer), t_send,
                             (float(m_in) + float(m_out)) / 2.0, t_recv)
        except Exception:  # noqa: BLE001
            self.dead = True

    def doc(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "id": self.id, "kind": self.kind, "key": self.key,
                "member": self.member, "outcome": self.outcome,
                "sampled": self.sampled, "t0": round(self.t0, 6),
                "ms": round(self.ms, 3), "hops": list(self.hops),
                "server": list(self.server),
            }


class _Plane:
    """Per-process trace store: mint counter, rings, offsets, counters."""

    def __init__(self, member: str, sample: float = 1.0,
                 ring: int = DEFAULT_RING, slow: int = DEFAULT_SLOW,
                 metrics: Any = None):
        self.member = member
        self.sample = max(0.0, min(1.0, float(sample)))
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.slow_cap = int(slow)
        self.slow: List[Tuple[float, int, Dict[str, Any]]] = []  # min-heap
        self.clock = ClockSync()
        self.metrics = metrics
        self.counters: Dict[str, int] = collections.defaultdict(int)
        self.exemplars: Dict[str, Tuple[str, float]] = {}
        self._n = 0
        self._tb = 0  # slow-heap tiebreak (heapq must never compare docs)
        self._lock = threading.Lock()
        self._pid = os.getpid()

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n
        if self.metrics is not None:
            try:
                self.metrics.count(f"rtrace.{name}", n)
            except Exception:  # noqa: BLE001
                pass

    def mint(self, kind: str, key: str, t0: float) -> Trace:
        with self._lock:
            self._n += 1
            tid = f"{self.member}-{self._pid:x}-{self._n:x}"
            # Deterministic head sampling: a pure function of the trace
            # id, so a request is sampled identically no matter who asks.
            sampled = (
                zlib.crc32(tid.encode()) % 1000000
            ) / 1e6 < self.sample
            self.counters["minted"] += 1
            if sampled:
                self.counters["sampled"] += 1
        m = self.metrics
        if m is not None:
            try:
                m.count("rtrace.minted")
                if sampled:
                    m.count("rtrace.sampled")
            except Exception:  # noqa: BLE001
                pass
        return Trace(tid, kind, key, self.member, sampled, t0)

    def commit(self, tr: Trace, outcome: str, ms: float) -> bool:
        """Store a finished trace. Sampled traces and forced outcomes
        always commit; unsampled completions survive only through the
        slow ring (the tail is the point)."""
        if tr.dead:
            return False
        tr.outcome = outcome
        tr.ms = float(ms)
        forced = outcome in FORCED_OUTCOMES
        slow_kept = False
        with self._lock:
            floor = self.slow[0][0] if len(self.slow) >= self.slow_cap \
                else -1.0
            if not (tr.sampled or forced) and tr.ms <= floor:
                self.counters["skipped"] += 1
                return False
        d = tr.doc()
        with self._lock:
            if tr.sampled or forced:
                self.ring.append(d)
            if tr.ms > floor:
                heapq.heappush(self.slow, (tr.ms, self._tb, d))
                self._tb += 1
                while len(self.slow) > self.slow_cap:
                    heapq.heappop(self.slow)
                slow_kept = True
            fam = f"{'router.read' if tr.kind == 'read' else 'router.write'}"
            cur = self.exemplars.get(fam)
            if outcome == "ok" and (cur is None or tr.ms >= cur[1]):
                self.exemplars[fam] = (tr.id, tr.ms)
            self.counters["committed"] += 1
            if forced:
                self.counters["forced"] += 1
            if slow_kept:
                self.counters["slow_kept"] += 1
        m = self.metrics
        if m is not None:
            try:
                m.count("rtrace.committed")
                if forced:
                    m.count("rtrace.forced")
                if slow_kept:
                    m.count("rtrace.slow_kept")
            except Exception:  # noqa: BLE001
                pass
        try:
            # NB: `kind` is the event-kind positional — the trace's own
            # read/write kind rides inside the stored doc.
            obs_events.emit("rtrace.trace", id=tr.id, outcome=outcome,
                            ms=round(tr.ms, 3), trace=d)
        except Exception:  # noqa: BLE001
            pass
        return True


# -- module surface (the one the routers/planes use) --------------------------


def install(member: str, sample: float = 1.0, ring: int = DEFAULT_RING,
            slow: int = DEFAULT_SLOW, metrics: Any = None,
            env: Optional[Dict[str, str]] = None) -> Optional[_Plane]:
    """Arm the plane for this process. Returns None (and disarms) when
    the ``CCRDT_RTRACE=0`` kill switch is set."""
    global ACTIVE, _PLANE
    if _killed(env):
        ACTIVE, _PLANE = False, None
        return None
    _PLANE = _Plane(member, sample=sample, ring=ring, slow=slow,
                    metrics=metrics)
    ACTIVE = True
    return _PLANE


def install_from_env(member: str, env: Optional[Dict[str, str]] = None,
                     metrics: Any = None) -> bool:
    """Arm iff ``CCRDT_RTRACE`` is set truthy (same supervisor->worker
    propagation pattern as CCRDT_FAULTS / CCRDT_SPANS); ``=0`` disarms
    even over an explicit install."""
    e = env if env is not None else os.environ
    v = e.get(ENV, "")
    if not v or v == "0":
        uninstall()
        return False
    sample = 1.0
    try:
        sample = float(e.get(ENV_SAMPLE, "1") or 1.0)
    except ValueError:
        pass
    return install(member, sample=sample, metrics=metrics, env=env) \
        is not None


def installed() -> bool:
    return ACTIVE and _PLANE is not None


def uninstall() -> None:
    global ACTIVE, _PLANE
    ACTIVE, _PLANE = False, None


def begin(kind: str, key: str = "", t0: float = 0.0) -> Optional[Trace]:
    """Mint a trace for one client request (None when the plane is
    dark — call sites treat a None trace as 'record nothing')."""
    p = _PLANE
    if not ACTIVE or p is None:
        return None
    try:
        return p.mint(kind, key, t0)
    except Exception:  # noqa: BLE001
        return None


def commit(tr: Optional[Trace], outcome: str, ms: float) -> bool:
    p = _PLANE
    if tr is None or p is None:
        return False
    try:
        return p.commit(tr, outcome, ms)
    except Exception:  # noqa: BLE001
        return False


def counters() -> Dict[str, int]:
    p = _PLANE
    return dict(p.counters) if p is not None else {}


def exemplars() -> Dict[str, Tuple[str, float]]:
    """{metric family: (trace_id, ms)} — the stored trace behind each
    family's worst observed latency, for OpenMetrics exemplar lines."""
    p = _PLANE
    if p is None:
        return {}
    with p._lock:
        return dict(p.exemplars)


def traces(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    p = _PLANE
    if p is None:
        return []
    with p._lock:
        out = list(p.ring)
    if kind is not None:
        out = [t for t in out if t.get("kind") == kind]
    return out


def slowest(n: int = 10) -> List[Dict[str, Any]]:
    p = _PLANE
    if p is None:
        return []
    with p._lock:
        ranked = sorted(p.slow, key=lambda e: -e[0])
    return [doc for _ms, _tb, doc in ranked[:n]]


def find(tid: str) -> Optional[Dict[str, Any]]:
    for d in traces():
        if d.get("id") == tid:
            return d
    for d in slowest(DEFAULT_SLOW):
        if d.get("id") == tid:
            return d
    return None


def offsets() -> Dict[str, Tuple[float, float]]:
    p = _PLANE
    return p.clock.snapshot() if p is not None else {}


def health_fields() -> Dict[str, Any]:
    p = _PLANE
    if p is None:
        return {}
    with p._lock:
        c = dict(p.counters)
        n_slow = len(p.slow)
    return {
        "rtrace_minted": int(c.get("minted", 0)),
        "rtrace_committed": int(c.get("committed", 0)),
        "rtrace_degraded": int(c.get("degraded", 0)),
        "rtrace_slow_ring": n_slow,
    }


# -- server side --------------------------------------------------------------


def server_trace(doc: Any) -> Optional[Dict[str, Any]]:
    """The trace context carried by a parsed request doc, or None.
    Stateless on purpose: a worker echoes hop timings for any traced
    request whether or not its own plane is armed — the CLIENT decided
    to pay for this trace. Honors the kill switch."""
    if _killed():
        return None
    t = doc.get("trace") if isinstance(doc, dict) else None
    if isinstance(t, dict) and isinstance(t.get("id"), str):
        return t
    return None


def server_echo(ctx: Dict[str, Any], member: str,
                marks: Dict[str, float], **extra: Any) -> Dict[str, Any]:
    """Build the response-borne echo: the request's trace id, this
    worker's identity, and the stage marks on ITS monotonic clock (the
    client's ClockSync maps them onto the client axis).

    The echo is the ONLY artifact — the client folds it into the trace
    doc and the ``rtrace.trace`` commit event carries it to disk, so
    the serve/ingest hot path pays no per-request flight-recorder
    write of its own."""
    e: Dict[str, Any] = {"id": ctx.get("id"), "peer": member}
    for k, v in marks.items():
        if v is not None:
            e[k] = round(float(v), 6)
    e.update(extra)
    return e


# -- merge / attribution engine ----------------------------------------------


def _shift_for(peer: str, offs: Dict[str, Any]) -> Optional[float]:
    o = offs.get(peer)
    if o is None:
        return None
    # ClockSync stores (offset, rtt); stored trace docs keep plain floats.
    return float(o[0]) if isinstance(o, (tuple, list)) else float(o)


def _winner_echo(tr: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The echo of the attempt that produced the answer: the last echo
    whose peer matches the winning attempt hop (dedup'd write retries
    echo more than once; the last delivery is the one that returned)."""
    win = None
    for h in tr.get("hops", ()):
        if h.get("k") == "attempt" and h.get("ok"):
            win = h
    if win is None:
        return None
    for e in reversed(tr.get("server", ())):
        if e.get("peer") == win.get("peer"):
            return e
    return None


def attribute(tr: Dict[str, Any],
              offs: Optional[Dict[str, Any]] = None) -> Dict[str, float]:
    """Decompose one stored trace into the seven buckets (ms).

    By construction route+backoff+wire+queue_wait+kernel+ack_probe sums
    to the client-observed total minus clock-mapping clips;
    hedge_overlap is duplicated parallel time reported alongside."""
    total = float(tr.get("ms", 0.0))
    out = {b: 0.0 for b in BUCKETS}
    out["total"] = total
    out["kernel_compile"] = 0.0  # sub-annotation of kernel, not a bucket
    hops = tr.get("hops", ())
    atts: List[Tuple[float, float]] = []
    for h in hops:
        d = max(0.0, (float(h.get("t1", 0)) - float(h.get("t0", 0))) * 1e3)
        k = h.get("k")
        if k == "route":
            out["route"] += d
        elif k == "backoff":
            out["backoff"] += d
        elif k == "ack_probe":
            out["ack_probe"] += d
        elif k == "attempt":
            atts.append((float(h["t0"]), float(h["t1"])))
    union_ms = _union(atts) * 1e3
    out["hedge_overlap"] = max(
        0.0, sum((b - a) for a, b in atts) * 1e3 - union_ms
    )
    e = _winner_echo(tr)
    server_ms = 0.0
    if e is not None:
        qw = kn = ap = 0.0
        if "m_drain" in e and "m_q" in e:       # read echo
            qw = max(0.0, (float(e["m_drain"]) - float(e["m_q"])) * 1e3)
            kn = float(e.get("kernel_ms", 0.0))
        elif "m_fold" in e and "m_stage" in e:  # write echo
            qw = max(0.0, (float(e["m_fold"]) - float(e["m_stage"])) * 1e3)
            kn = float(e.get("kernel_ms", 0.0))
            ap = max(0.0, float(e.get("durable_wait_ms", 0.0)))
        # The server can only explain time inside the attempt that
        # carried it — clip so a skewed echo never exceeds the wire gap.
        att_ms = max(
            0.0,
            (float(e.get("t_recv", 0)) - float(e.get("t_send", 0))) * 1e3,
        )
        qw = min(qw, att_ms)
        kn = min(kn, max(0.0, att_ms - qw))
        ap = min(ap, max(0.0, att_ms - qw - kn))
        out["queue_wait"], out["kernel"] = qw, kn
        # Attribution honesty (ISSUE 19): when the device observatory
        # saw compiles inside this hop's window, the echo carries their
        # total as `compile_ms` and the kernel bucket gets a
        # sub-annotation splitting compile-storm latency from genuine
        # kernel time. Clipped to the kernel bucket — compile time IS
        # kernel-bucket time, just dishonestly labeled before this.
        cms = max(0.0, float(e.get("compile_ms", 0.0)))
        out["kernel_compile"] = min(cms, kn)
        out["ack_probe"] += ap
        server_ms = qw + kn + ap
    # Wire = time the request was genuinely in flight (the attempts'
    # union — launch to settle as the CLIENT saw it, which includes the
    # router's poll granularity) minus what the server explained. It is
    # measured, not a residual: if hops go missing, coverage DROPS and
    # the gates see it.
    out["wire"] = max(0.0, union_ms - server_ms)
    known = out["route"] + out["backoff"] + out["wire"] \
        + out["queue_wait"] + out["kernel"] + out["ack_probe"]
    out["coverage"] = known / total if total > 0 else 1.0
    return out


def complete(tr: Dict[str, Any]) -> Tuple[bool, str]:
    """Is this stored trace a gap-free waterfall? Requires a dense hop
    sequence (no evicted/err-dropped hops), a route decision, at least
    one attempt, and — for sampled completed requests — a server echo
    from the winning attempt."""
    hops = tr.get("hops", ())
    hss = sorted(int(h.get("hs", -1)) for h in hops)
    if hss != list(range(len(hops))):
        return False, "hop sequence has holes"
    kinds = [h.get("k") for h in hops]
    if "route" not in kinds:
        return False, "no route hop"
    if tr.get("outcome") == "ok":
        if "attempt" not in kinds:
            return False, "no attempt hop"
        if tr.get("sampled") and _winner_echo(tr) is None:
            return False, "winning attempt carried no server echo"
    return True, ""


def waterfall(tr: Dict[str, Any],
              offs: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
    """The trace as ordered [t0_ms, t1_ms] segments relative to the
    request start, server stages mapped onto the client's clock via the
    ClockSync offsets (live plane offsets by default)."""
    offs = offs if offs is not None else offsets()
    base = float(tr.get("t0", 0.0))
    rows: List[Dict[str, Any]] = []

    def _row(name: str, a: float, b: float, **f: Any) -> None:
        rows.append(dict(
            name=name, t0_ms=round((a - base) * 1e3, 3),
            t1_ms=round((b - base) * 1e3, 3), **f,
        ))

    for h in tr.get("hops", ()):
        _row(h.get("k", "?"), float(h.get("t0", base)),
             float(h.get("t1", base)),
             **{k: v for k, v in h.items()
                if k not in ("k", "t0", "t1", "hs")})
    for e in tr.get("server", ()):
        peer = str(e.get("peer"))
        shift = _shift_for(peer, offs)
        if shift is None:
            # No offset sample yet: anchor the server window onto the
            # attempt's midpoint so the waterfall still renders.
            m_in, m_out = e.get("m_in"), e.get("m_out")
            if m_in is None or m_out is None:
                continue
            mid = (float(e.get("t_send", base))
                   + float(e.get("t_recv", base))) / 2.0
            shift = (float(m_in) + float(m_out)) / 2.0 - mid
        pairs = (("server", "m_in", "m_out"),
                 ("queue_wait", "m_q", "m_drain"),
                 ("kernel", "m_drain", "m_done"),
                 ("queue_wait", "m_stage", "m_fold"))
        for name, ka, kb in pairs:
            a, b = e.get(ka), e.get(kb)
            if a is None or b is None:
                continue
            _row(name, float(a) - shift, float(b) - shift, peer=peer)
    rows.sort(key=lambda r: (r["t0_ms"], r["t1_ms"]))
    return rows


def attribution_report(
    trs: List[Dict[str, Any]],
    offs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Fleet-level tail attribution over stored traces: per-bucket p50 /
    p99 milliseconds, coverage percentiles, and the p99 request's
    dominant bucket — the 'where did the tail go' answer."""
    rows = [attribute(t, offs) for t in trs if t.get("outcome") == "ok"]
    if not rows:
        return {"n": 0}
    totals = [r["total"] for r in rows]
    p99_total = _pctl(totals, 0.99)
    # The p99 exemplar request: the slowest at-or-under the p99 mark.
    under = [(r, t) for r, t in zip(rows, trs)
             if t.get("outcome") == "ok" and r["total"] <= p99_total + 1e-9]
    ex_row, ex_tr = max(under, key=lambda rt: rt[0]["total"])
    dom = max(BUCKETS, key=lambda b: ex_row.get(b, 0.0)
              if b != "hedge_overlap" else -1.0)
    doc: Dict[str, Any] = {
        "n": len(rows),
        "total_ms_p50": round(_pctl(totals, 0.50), 3),
        "total_ms_p99": round(p99_total, 3),
        "coverage_p50": round(_pctl([r["coverage"] for r in rows], 0.50), 4),
        "coverage_p99_req": round(ex_row["coverage"], 4),
        "p99_trace_id": ex_tr.get("id"),
        "p99_dominant_bucket": dom,
        "p99_dominant_ms": round(ex_row.get(dom, 0.0), 3),
        # The p99 trace's compile share (devprof sub-annotation of the
        # kernel bucket): how much of the tail was recompile churn.
        "p99_kernel_compile_ms": round(
            ex_row.get("kernel_compile", 0.0), 3
        ),
        "p99_compile_share": round(
            ex_row.get("kernel_compile", 0.0) / ex_row["total"], 4
        ) if ex_row["total"] > 0 else 0.0,
        "buckets_ms_p50": {
            b: round(_pctl([r[b] for r in rows], 0.50), 3) for b in BUCKETS
        },
        "buckets_ms_p99": {
            b: round(_pctl([r[b] for r in rows], 0.99), 3) for b in BUCKETS
        },
    }
    return doc


def format_report(rep: Dict[str, Any]) -> str:
    if not rep.get("n"):
        return "rtrace: no completed traces"
    lines = [
        f"rtrace attribution over {rep['n']} completed requests: "
        f"p50 {rep['total_ms_p50']:.2f}ms p99 {rep['total_ms_p99']:.2f}ms "
        f"(coverage p50 {rep['coverage_p50']:.1%})",
        f"  p99 trace {rep['p99_trace_id']}: dominant bucket "
        f"{rep['p99_dominant_bucket']} ({rep['p99_dominant_ms']:.2f}ms), "
        f"compile share {rep.get('p99_compile_share', 0.0):.1%} "
        f"({rep.get('p99_kernel_compile_ms', 0.0):.2f}ms)",
    ]
    for b in BUCKETS:
        lines.append(
            f"  {b:<13} p50 {rep['buckets_ms_p50'][b]:>9.3f}ms   "
            f"p99 {rep['buckets_ms_p99'][b]:>9.3f}ms"
        )
    return "\n".join(lines)


# -- offline readers (CLI / demos) -------------------------------------------


def scan_traces(obs_dir: str) -> List[Dict[str, Any]]:
    """All committed traces found in a spill dir's request-event
    streams (each `rtrace.trace` event carries the full trace doc)."""
    out: List[Dict[str, Any]] = []
    for evs in obs_events.scan_dir(obs_dir).values():
        for ev in evs:
            if ev.get("kind") == "rtrace.trace" \
                    and isinstance(ev.get("trace"), dict):
                out.append(ev["trace"])
    return out


def to_json(doc: Any) -> str:
    return json.dumps(doc, indent=2, sort_keys=True)
