"""Explicit, injectable replica identity + logical time.

The reference obtains both from ambient process state: ``?TIME:system_time/1``
and ``?DC_META_DATA:get_my_dc_id/0`` resolve to two gen_servers in test mode
(``src/mock_time.erl:59-62``, ``src/mock_dc_meta_data.erl:49-56``) and to
``erlang`` / Antidote's ``dc_meta_data_utilities`` in production
(``src/antidote_ccrdt_topk_rmv.erl:28-35``). That hidden state is the *only*
nondeterminism in the entire library.

Here both are plain values threaded through `ReplicaContext`, which makes
`downstream` a pure function of (op, state, ctx) — and therefore batchable:
a batch of timestamps is just an array the harness allocates up front.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Callable, Protocol, Tuple, runtime_checkable

DcId = int
Timestamp = int


@runtime_checkable
class ClockContext(Protocol):
    """What `downstream` actually requires of its context: a fresh
    (dc, ts) origin stamp. `ReplicaContext` is the standard provider; the
    bridge supplies `_FixedCtx` (caller-provided dc/ts over the wire —
    the host owns the clock there, as Antidote does), so the callback
    annotations use this Protocol, not the concrete class."""

    def stamp(self) -> Tuple[DcId, Timestamp]: ...


class LogicalClock:
    """Deterministic monotone clock: each `system_time()` call returns the
    next integer. Mirrors ``mock_time``'s gen_server counter
    (``mock_time.erl:59-62``: reply State+1, store State+1)."""

    def __init__(self, start: int = 0) -> None:
        self._t = start
        self._lock = threading.Lock()

    def system_time(self) -> Timestamp:
        with self._lock:
            self._t += 1
            return self._t

    def get_time(self) -> Timestamp:
        """Peek without advancing (``mock_time.erl:61-62``)."""
        return self._t


class WallClock:
    """Production clock: milliseconds since epoch, monotonicized. The
    reference's prod binding is ``erlang:system_time(milli_seconds)``."""

    def __init__(self) -> None:
        self._last = 0
        self._lock = threading.Lock()

    def system_time(self) -> Timestamp:
        with self._lock:
            now = time.time_ns() // 1_000_000
            self._last = max(self._last, now)
            return self._last

    def get_time(self) -> Timestamp:
        return self._last


@dataclasses.dataclass
class ReplicaContext:
    """Everything `downstream` may read besides (op, state).

    In the reference this is the pair of shim calls at
    ``antidote_ccrdt_topk_rmv.erl:104-105``. `dc_index` is the dense integer
    used by the array kernels (vector clocks are arrays indexed by DC);
    `dc_id` is the opaque identity used at the scalar level, kept separate so
    scalar states compare exactly like reference terms.
    """

    dc_id: DcId
    clock: LogicalClock
    dc_index: int = 0

    def stamp(self) -> Tuple[DcId, Timestamp]:
        """A fresh (dc, ts) origin stamp for an add op."""
        return (self.dc_id, self.clock.system_time())


def make_contexts(n_replicas: int, shared_clock: bool = True) -> list[ReplicaContext]:
    """Contexts for a simulated multi-DC deployment.

    shared_clock=True reproduces the reference test rig (one mock_time
    gen_server shared by every simulated DC), which yields globally unique
    timestamps; False gives each DC its own clock — realistic, and exercises
    the vc-domination logic harder (equal timestamps across DCs).
    """
    if shared_clock:
        clk = LogicalClock()
        return [ReplicaContext(dc_id=i, clock=clk, dc_index=i) for i in range(n_replicas)]
    return [
        ReplicaContext(dc_id=i, clock=LogicalClock(), dc_index=i)
        for i in range(n_replicas)
    ]
