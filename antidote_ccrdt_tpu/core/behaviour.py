"""The CCRDT behaviour contract: the interface every computational CRDT implements.

This is the TPU-native re-design of the reference's Erlang behaviour
(``src/antidote_ccrdt.erl:47-59``), which defines 12 callbacks:

    new/0, value/1, downstream/2, update/2, require_state_downstream/1,
    is_operation/1, can_compact/2, compact_ops/2, is_replicate_tagged/1,
    equal/2, to_binary/1, from_binary/1

We keep the same surface at two levels:

* **Scalar level** (`ScalarCCRDT`): one CRDT instance, one op at a time,
  pure Python. Semantically faithful to the reference — used for golden
  tests, differential testing against the dense kernels, and as the
  CPU baseline the benchmarks compare against.

* **Dense level** (`DenseCCRDT`): states are pytrees of fixed-shape arrays
  with leading batch axes ``[n_replicas, n_keys, ...]``; `apply_ops` and
  `merge` are jit-compiled batched kernels that process thousands of
  (replica, key) instances in one XLA dispatch. This is the north-star
  entry point (`batch_merge`).

Two deliberate departures from the reference (documented in SURVEY.md §2
"Quirks"):

1. The reference marks dead op-log slots inconsistently — ``{noop}`` tuple
   in average/topk_rmv/leaderboard (``antidote_ccrdt_average.erl:127``)
   but bare ``noop`` atom in topk/wordcount (``antidote_ccrdt_topk.erl:138``)
   — and separately uses ``noop`` for "no downstream effect". Here ``None``
   uniformly means both "no effect" (downstream) and "dead slot" (compaction).

2. The reference has no state-merge (it is op-based only; replication is
   delegated to the Antidote host). The dense level adds an explicit
   ``merge`` with a declared algebra (`MergeKind`), which is what lets
   replica-state reconciliation become one batched XLA reduction.
"""

from __future__ import annotations

import enum
from typing import Any, Iterable, Optional, Protocol, Sequence, Tuple, runtime_checkable

from .clock import ClockContext

# A prepare-side operation submitted by a client, e.g. ("add", (id, score)).
PrepareOp = Tuple[str, Any]
# A downstream effect op, e.g. ("add", (id, score, (dc, ts))). Effects are
# what gets logged, shipped between DCs, and applied via `update`.
EffectOp = Tuple[str, Any]


class MergeKind(enum.Enum):
    """Algebra of the dense `merge` operator.

    JOIN: idempotent join-semilattice — merging full replica states is safe
        under duplication and reordering (topk, topk_rmv, leaderboard).
    MONOID: non-idempotent commutative monoid — per-replica states are
        *deltas* (accumulations of locally-applied ops since the last
        exchange) and merge combines deltas exactly once (average,
        wordcount, worddocumentcount). Merging full states would
        double-count, mirroring how the reference relies on the host's
        exactly-once op delivery (SURVEY.md §1).
    """

    JOIN = "join"
    MONOID = "monoid"


@runtime_checkable
class ScalarCCRDT(Protocol):
    """Single-instance, single-op semantics. Mirrors the reference callbacks.

    All methods are pure; replica identity and time come in explicitly via
    `ReplicaContext` (the reference reads them from ambient gen_servers —
    ``?TIME`` / ``?DC_META_DATA``, ``antidote_ccrdt_topk_rmv.erl:28-35`` —
    which is the only nondeterminism in the whole library; making the
    context an argument is what lets everything batch later).
    """

    type_name: str

    def new(self, *args: Any) -> Any:
        """Fresh state. Per-type parameters (e.g. top-K size) mirror new/1,2."""
        ...

    def value(self, state: Any) -> Any:
        """Observable value of the state (the 'computation' in CCRDT)."""
        ...

    def downstream(
        self, op: PrepareOp, state: Any, ctx: ClockContext
    ) -> Optional[EffectOp]:
        """Turn a prepare op into an effect op at the origin replica.

        Returns None when the op cannot change any replica's state
        (the reference's ``{ok, noop}``).
        """
        ...

    def update(self, effect: EffectOp, state: Any) -> Tuple[Any, list]:
        """Apply an effect op. Returns (new_state, extra_effect_ops).

        extra_effect_ops must re-enter the replication pipeline — the
        reference returns ``{ok, S'}`` or ``{ok, S', [Ops]}``
        (``antidote_ccrdt.erl:50``); here the list is always present
        (empty when there is nothing to propagate).
        """
        ...

    def require_state_downstream(self, op: PrepareOp) -> bool:
        ...

    def is_operation(self, op: Any) -> bool:
        ...

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        ...

    def compact_ops(
        self, e1: EffectOp, e2: EffectOp
    ) -> Tuple[Optional[EffectOp], Optional[EffectOp]]:
        """Pairwise op-log compaction; None marks a deleted slot."""
        ...

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        """True for non-observable effects that must still ship inter-DC."""
        ...

    def equal(self, a: Any, b: Any) -> bool:
        ...

    def to_binary(self, state: Any) -> bytes:
        ...

    def from_binary(self, data: bytes) -> Any:
        ...


@runtime_checkable
class DenseCCRDT(Protocol):
    """Batched dense-array semantics: the TPU compute path.

    States are pytrees whose leaves all carry leading batch axes
    ``[n_replicas, n_keys, ...]`` (some types collapse n_keys into the
    state, e.g. leaderboard's player table). `apply_ops` and `merge` must
    be jit-compatible: static shapes, no Python control flow on traced
    values.
    """

    type_name: str
    merge_kind: MergeKind

    def init(self, n_replicas: int, n_keys: int, **params: Any) -> Any:
        """Batched fresh state for a [n_replicas, n_keys] grid of instances."""
        ...

    def apply_ops(self, state: Any, ops: Any) -> Tuple[Any, Any]:
        """Apply a dense batch of effect ops in one dispatch.

        `ops` is a per-type struct-of-arrays with a [n_replicas, batch]
        layout (see each type's OpBatch). Returns (new_state, extras) where
        extras encodes generated extra ops (dense, fixed capacity) for the
        types that produce them (topk_rmv, leaderboard — mirror of
        ``antidote_ccrdt.erl:37-40``).
        """
        ...

    def merge(self, a: Any, b: Any) -> Any:
        """Two-way merge with `merge_kind` algebra. Associative+commutative;
        idempotent iff JOIN."""
        ...

    def observe(self, state: Any) -> Any:
        """Dense observable value (e.g. top-K ids/scores arrays)."""
        ...


class Registry:
    """Type registry: the rebuild of ``antidote_ccrdt:is_type/1`` and
    ``generates_extra_operations/1`` (``antidote_ccrdt.erl:61-65``)."""

    def __init__(self) -> None:
        self._scalar: dict[str, ScalarCCRDT] = {}
        self._dense: dict[str, DenseCCRDT] = {}
        self._dense_factory: dict[str, Any] = {}
        self._extra_ops: set[str] = set()
        self._law_fixture: dict[str, Any] = {}

    def register(
        self,
        name: str,
        scalar: Optional[ScalarCCRDT] = None,
        dense: Optional[DenseCCRDT] = None,
        dense_factory: Optional[Any] = None,
        generates_extra_operations: bool = False,
        law_fixture: Optional[Any] = None,
    ) -> None:
        if scalar is not None:
            self._scalar[name] = scalar
        if dense is not None:
            self._dense[name] = dense
        if dense_factory is not None:
            self._dense_factory[name] = dense_factory
        if generates_extra_operations:
            self._extra_ops.add(name)
        if law_fixture is not None:
            self._law_fixture[name] = law_fixture

    def is_type(self, name: Any) -> bool:
        return isinstance(name, str) and (
            name in self._scalar
            or name in self._dense
            or name in self._dense_factory
        )

    def generates_extra_operations(self, name: Any) -> bool:
        return self.is_type(name) and name in self._extra_ops

    def scalar(self, name: str) -> ScalarCCRDT:
        return self._scalar[name]

    def dense(self, name: str) -> DenseCCRDT:
        return self._dense[name]

    def make_dense(self, name: str, **params: Any) -> DenseCCRDT:
        """Construct a dense engine with explicit capacities (the rebuild of
        ``new/1,2`` per-instance parameters, SURVEY.md §5 config row)."""
        return self._dense_factory[name](**params)

    def scalar_types(self) -> Iterable[str]:
        return self._scalar.keys()

    def dense_types(self) -> Iterable[str]:
        return set(self._dense) | set(self._dense_factory)

    # -- lattice-law audit hooks (obs/audit.py LawChecker) -----------------
    # A law fixture is `fn(seed, n) -> {"dense": engine, "states": [A, B,
    # C], "chain": (prev, cur) | None}` generating REACHABLE batched
    # states (a [1, n] instance grid built from real op applications) for
    # the merge/delta law checker in ops/laws.py. Types without a fixture
    # are reported as unaudited, so a new type can't silently skip the
    # certification gate.

    def law_fixture(self, name: str) -> Optional[Any]:
        return self._law_fixture.get(name)

    def law_fixtures(self) -> dict[str, Any]:
        return dict(self._law_fixture)


registry = Registry()
