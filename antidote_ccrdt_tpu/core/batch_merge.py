"""`batch_merge`: merge many scalar CRDT states in one batched device pass.

The north-star entry point (BASELINE.json): a host ships N replica states
(live scalar states or their `to_binary` blobs) to the persistent worker,
which joins them all on the accelerator and returns one merged state of
the same scalar shape. State join is the CRDT lattice the dense engines
already implement:

  average      (s, n) pairs          combine = +   (MONOID — see below)
  wordcount(s) word -> count         combine = +   (MONOID — see below)
  topk         id -> best score      join = per-id max, keep top size
  leaderboard  scores + bans         join = max / or, observable re-derived
  topk_rmv     full add-wins state   join = slot lattice + vc max

MONOID caveat: the + combiners are NOT idempotent — average and the
wordcounts require the input states' op histories to be DISJOINT (each op
reflected in exactly one input: delta/exactly-once semantics, the same
causal-delivery contract the reference assumes of its host, SURVEY.md §1).
Overlapping histories double-count. The JOIN types (topk, leaderboard,
topk_rmv) are idempotent lattices and tolerate arbitrary overlap.

Scalar states key by arbitrary (orderable) Python terms; the converter
builds the sorted id/dc universes host-side (O(total entries) — the same
work any serializer pays), lays states out as one [N, ...] dense batch,
and the device folds the join pairwise in log2(N) batched dispatches.
Conversion is exact: capacities are sized from the inputs, so the dense
lossy flag can never set.

Reference anchor: the per-type merge this batches is the state-level
counterpart of `update/2` convergence (SURVEY.md §1 — op-based states
that saw op sets A and B join to the state that saw A ∪ B; the tests pin
exactly that property).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, List, Sequence

import numpy as np

from .behaviour import registry
from ..obs import devprof
from ..obs import profile
from ..obs import spans as obs_spans

_I32_MIN, _I32_MAX = -(2**31 - 1), 2**31 - 1


def _check_i32(x: int) -> int:
    # Exclusive lower bound: _I32_MIN is the dense engines' "never seen"
    # sentinel, so a real score equal to it would silently vanish in the
    # merged state — reject it loudly instead.
    if not (_I32_MIN < x <= _I32_MAX):
        raise ValueError(
            f"value {x} outside the dense engines' usable int32 range "
            f"({_I32_MIN} is the absent-entry sentinel)"
        )
    return int(x)


# Jitted merge entry points keyed per engine merge fn. Bound-method ids
# are unstable (a fresh wrapper per attribute access), so the key is the
# underlying (__func__, __self__) identity pair and the cache value pins
# the bound method itself to keep those ids live.
_SLOTS: Dict[Any, Any] = {}


def merge_slot_key(merge) -> Any:
    """The cache identity of an engine merge fn — the (__func__,
    __self__) id pair described above. Shared with mesh/reduce.py's
    collective slots so every jit cache in the tree keys merges the
    same way; any cache using it must pin the bound method itself to
    keep the ids live."""
    return (
        id(getattr(merge, "__func__", merge)),
        id(getattr(merge, "__self__", None)),
    )


def merge_slots(merge):
    """The double-buffer device slots of the overlap pipeline (PR 7):
    three cached jitted compilations of one engine merge —

      plain        no aliasing (the serial path's semantics, jitted)
      donate_rhs   arg1's buffers alias into the output: for
                   state ⊔ incoming where `incoming` is a freshly
                   materialized window the pipeline owns. arg0 (the
                   carried state) is NEVER donated — DeltaPublisher
                   keeps `_prev` and the WAL keeps pre-images aliased
                   to it.
      donate_both  both operands donated: only for `_batched_fold`'s
                   internal rounds, where lhs/rhs are fresh slices of a
                   stack this module just built.

    Donation is what lets window N+1's merge dispatch while window N's
    result is still being read back: XLA reuses the dead operand's
    buffers instead of allocating + waiting. On backends that cannot
    alias (CPU) donation is a silent no-op — semantics are unchanged
    either way, which tests/test_overlap.py pins bit-identically."""
    import jax

    key = merge_slot_key(merge)
    hit = _SLOTS.get(key)
    if hit is None:
        hit = (
            merge,  # pinned: the key's ids must outlive the cache entry
            {
                "plain": jax.jit(merge),
                "donate_rhs": jax.jit(merge, donate_argnums=(1,)),
                "donate_both": jax.jit(merge, donate_argnums=(0, 1)),
            },
        )
        _SLOTS[key] = hit
    return hit[1]


# One jitted whole-tree copy, shared by every state shape (jit re-traces
# per treedef/shape, so a single cache slot covers all engines).
_COPY_SLOT: List[Any] = []


def snapshot_state(state):
    """One-dispatch device copy of a state pytree: the serve plane's
    read-replica buffer (PR 9). The copy — not a reference — is what
    makes a held snapshot immune to the donated jit slots above: a
    buffer the replica owns can never be aliased away by a later
    donate_rhs/donate_both merge of the live state. Same slot
    discipline as `merge_slots`: jitted once, cached for the process."""
    import jax
    import jax.numpy as jnp

    if not _COPY_SLOT:
        _COPY_SLOT.append(jax.jit(lambda s: jax.tree.map(jnp.copy, s)))
    tok = (
        obs_spans.begin("round.device_dispatch", site="batch_merge.snapshot")
        if obs_spans.ACTIVE
        else None
    )
    try:
        if profile.ACTIVE or devprof.ACTIVE:
            with profile.dispatch(
                "batch_merge.snapshot", fn=_COPY_SLOT[0], operands=(state,)
            ):
                return _COPY_SLOT[0](state)
        return _COPY_SLOT[0](state)
    finally:
        obs_spans.end(tok)


def merge_into(
    merge,
    state,
    incoming,
    donate_incoming: bool = True,
    site: str = "batch_merge.into",
):
    """One window's merge through the donated slot: `state ⊔ incoming`,
    with `incoming`'s buffers donated to the result. The caller must own
    `incoming` outright (an expanded peer delta / fetched snapshot it
    will never touch again); `state` is left intact. `site` labels the
    dispatch for spans/devprof — the pager relabels its cold-fold and
    full-join calls so compile churn attributes to the right tier."""
    donation = "donate_rhs" if donate_incoming else "plain"
    slot = merge_slots(merge)[donation]
    tok = (
        obs_spans.begin("round.device_dispatch", site=site, n=2)
        if obs_spans.ACTIVE
        else None
    )
    try:
        if profile.ACTIVE or devprof.ACTIVE:
            # fn=slot: the jit wrapper actually dispatched, so the
            # compile classification watches the right cache.
            with profile.dispatch(
                site, fn=slot, operands=(incoming,), donation=donation
            ):
                return slot(state, incoming)
        return slot(state, incoming)
    finally:
        obs_spans.end(tok)


@contextlib.contextmanager
def host_device() -> Iterator[None]:
    """Pin jit dispatch + array creation to the host CPU backend for the
    enclosed region — the pager's cold-fold tier (core/pager.py) runs the
    SAME jitted merge slots as the device hot path, just compiled for and
    executed on CPU-backed arrays. On a CPU-only process (tests, drills)
    this is a no-op by construction."""
    import jax

    try:
        cpus = jax.devices("cpu")
    except RuntimeError:
        cpus = []
    if not cpus:
        yield
        return
    with jax.default_device(cpus[0]):
        yield


def host_merge_into(
    merge,
    state,
    incoming,
    donate_incoming: bool = True,
    site: str = "batch_merge.into",
):
    """`merge_into`, but dispatched on the host CPU backend: the cold
    tier's fold primitive. `state`/`incoming` created inside the
    `host_device` region stay CPU-committed, so the jit slot compiles a
    CPU executable and the fold never touches HBM."""
    with host_device():
        return merge_into(
            merge, state, incoming, donate_incoming=donate_incoming, site=site
        )


def fold_states(merge, states: Sequence[Any]):
    """Multi-window batched dispatch: fold N same-shape state pytrees
    (e.g. the carried state plus every mergeable window in the overlap
    apply queue) in log2(N) batched dispatches instead of N-1 serial
    ones. Stacks to [N, ...] — engine merges are rank-polymorphic over
    the leading axis — folds with donation (the stack and its slices are
    fresh buffers this function owns), and unstacks the single row."""
    import jax
    import jax.numpy as jnp

    if not states:
        raise ValueError("fold_states needs at least one state")
    if len(states) == 1:
        return states[0]
    batch = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *states)
    folded = _batched_fold(merge, batch, donate=True)
    return jax.tree.map(lambda x: x[0], folded)


def stage_to_device(tree: Any) -> Any:
    """Async h2d pre-staging for the ingest fast path: `jax.device_put`
    enqueues the transfers and returns immediately, so a prefetcher
    thread can ship decoded window leaves toward the accelerator while
    the round thread is still mid-dispatch — by the time `fold_states`
    stacks them, the operands are device-resident and the fold pays no
    inline h2d. Leaves already on device pass through untouched (the
    CPU backend therefore makes this a no-op, which is exactly the
    bit-identity the CCRDT_INGEST_COMPACT=0 drills assert)."""
    import jax

    return jax.device_put(tree)


def tree_nbytes(tree: Any) -> int:
    """Total payload bytes across a pytree's array leaves (the
    `ingest.staged_bytes` accounting for staged windows)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree.leaves(tree)
    )


def _batched_fold(merge, batch: Any, donate: bool = False):
    """Fold a [N, ...] state pytree down to [1, ...]: each round merges the
    first half against the second half in ONE dispatch (log2(N) dispatches
    total), carrying the odd row. With `donate`, rounds run through the
    donate-both jit slot — safe here because lhs/rhs are eagerly
    materialized slices nothing else references."""
    import jax
    import jax.numpy as jnp

    donation = "donate_both" if donate else ""
    step = merge_slots(merge)["donate_both"] if donate else merge
    n = jax.tree.leaves(batch)[0].shape[0]
    while n > 1:
        half = n // 2
        lhs = jax.tree.map(lambda x: x[:half], batch)
        rhs = jax.tree.map(lambda x: x[half : 2 * half], batch)
        tok = (
            obs_spans.begin("round.device_dispatch", site="batch_merge.fold", n=n)
            if obs_spans.ACTIVE
            else None
        )
        try:
            if profile.ACTIVE or devprof.ACTIVE:
                # fn=step: the callable actually dispatched (the donated
                # jit slot, or the engine's class-level jitted merge).
                with profile.dispatch(
                    "batch_merge.fold",
                    fn=step,
                    operands=(lhs, rhs),
                    donation=donation,
                ):
                    merged = step(lhs, rhs)
            else:
                merged = step(lhs, rhs)
        finally:
            obs_spans.end(tok)
        if n % 2:
            batch = jax.tree.map(
                lambda m, t: jnp.concatenate([m, t], axis=0),
                merged,
                jax.tree.map(lambda x: x[2 * half :], batch),
            )
        else:
            batch = merged
        n = (n + 1) // 2
    return batch


# Dense engines keyed by (type, capacities). The converter mergers below
# size capacities exactly from their inputs, so before this memo every
# call built a FRESH engine — and the engines' class-level jitted methods
# key their caches on the static `self`, meaning every call recompiled
# even at identical shapes. Reusing one engine per capacity tuple is the
# recompile-churn fix the devprof observatory measures (ISSUE 19).
# Entries are tiny (capacity ints + a bound-method pin); the jit caches
# they key live on the CLASS attributes and grow either way.
_DENSE_MEMO: Dict[Any, Any] = {}


def _memo_dense(type_name: str, **caps):
    key = (type_name, tuple(sorted(caps.items())))
    eng = _DENSE_MEMO.get(key)
    if eng is None:
        eng = registry.make_dense(type_name, **caps)
        _DENSE_MEMO[key] = eng
    return eng


def prewarm_topk_rmv(
    size: int, n_ids: int = 1, n_dcs: int = 1, max_slots: int = 1
) -> int:
    """Boot-time warm-up (``CCRDT_DEVPROF_WARMUP=1``): pre-trace the
    topk_rmv fold dispatch across the padded capacity ladder up to
    `max_slots` live adds per id, so a stepping fleet's first rounds —
    and every later bucket crossing — hit a warm jit cache instead of
    provoking an inline recompile. Shapes match `_merge_topk_rmv`'s
    fold dispatches exactly: [1, 1, U, M] halves of a power-of-two
    padded batch. Returns the number of ladder rungs traced."""
    import jax.numpy as jnp

    from ..models.topk_rmv_dense import TopkRmvDenseState

    U, D = devprof.pad_dim(n_ids), devprof.pad_dim(n_dcs)
    rungs = 0
    m = 1
    while True:
        m = devprof.pad_dim(m)
        dense = _memo_dense(
            "topk_rmv", n_ids=U, n_dcs=D, size=size, slots_per_id=m
        )

        def blank():
            return TopkRmvDenseState(
                slot_score=jnp.full((1, 1, U, m), _I32_MIN, jnp.int32),
                slot_dc=jnp.zeros((1, 1, U, m), jnp.int32),
                slot_ts=jnp.zeros((1, 1, U, m), jnp.int32),
                rmv_vc=jnp.zeros((1, 1, U, D), jnp.int32),
                vc=jnp.zeros((1, 1, D), jnp.int32),
                lossy=jnp.zeros((1, 1), bool),
            )

        lhs, rhs = blank(), blank()
        if profile.ACTIVE or devprof.ACTIVE:
            # Boot compiles attribute to their own site, so steady-state
            # churn gates can exclude the deliberate warm-up cost.
            with profile.dispatch(
                "batch_merge.prewarm", fn=dense.merge, operands=(lhs, rhs)
            ):
                dense.merge(lhs, rhs)
        else:
            dense.merge(lhs, rhs)
        rungs += 1
        if m >= max_slots:
            return rungs
        m *= 2


def batch_merge(type_name: str, states: Sequence[Any]) -> Any:
    """Join N scalar states of `type_name` into one. Accepts live scalar
    states or `to_binary` blobs (mixed is fine); returns a live scalar
    state (call the type's `to_binary` to ship it back)."""
    if not states:
        raise ValueError("batch_merge needs at least one state")
    eng = registry.scalar(type_name)

    def decode(blob):
        if blob[:1] == b"\x83":  # Erlang term_to_binary (ETF magic)
            from . import wire

            return wire.from_reference_binary(type_name, bytes(blob))
        return eng.from_binary(blob)  # framework CCRD snapshot

    states = [
        decode(s) if isinstance(s, (bytes, bytearray)) else s for s in states
    ]
    if len(states) == 1:
        return states[0]
    fn = _MERGERS.get(type_name)
    if fn is None:
        raise ValueError(f"no batch_merge for type {type_name!r}")
    return fn(states)


# -- simple monoids --------------------------------------------------------


def _merge_average(states):
    # Two ints per state: host arithmetic (unbounded Python ints — the
    # scalar average has no i32 range limit, and shipping 2N ints to the
    # device would be all transfer).
    return (sum(s for s, _ in states), sum(n for _, n in states))


def _merge_wordcount(states):
    import jax.numpy as jnp

    vocab = sorted({w for st in states for w in st})
    idx = {w: i for i, w in enumerate(vocab)}
    # i32 like the dense engine's count tables (x64 is disabled; per-entry
    # range is checked, totals share the dense path's i32 assumption).
    table = np.zeros((len(states), len(vocab)), np.int32)
    for r, st in enumerate(states):
        for w, c in st.items():
            table[r, idx[w]] = _check_i32(c)
    if not vocab:
        return {}
    total = np.asarray(jnp.sum(jnp.asarray(table), axis=0))
    return {w: int(total[i]) for w, i in idx.items() if total[i]}


# -- score tables ----------------------------------------------------------


def _merge_topk(states):
    from ..models.topk import TopkState, _join

    size = states[0].size
    if any(s.size != size for s in states):
        raise ValueError("cannot merge topk states of different sizes")
    ids = sorted({i for st in states for i in st.entries})
    if not ids:
        return TopkState({}, size)
    dense = _memo_dense("topk", n_ids=len(ids), size=size)
    import jax.numpy as jnp

    from ..models.topk import TopkDenseState

    idx = {w: i for i, w in enumerate(ids)}
    table = np.full((len(states), 1, len(ids)), _I32_MIN, np.int32)
    for r, st in enumerate(states):
        for w, c in st.entries.items():
            table[r, 0, idx[w]] = _check_i32(c)
    folded = _batched_fold(
        dense.merge, TopkDenseState(best_score=jnp.asarray(table))
    )
    best = np.asarray(folded.best_score)[0, 0]
    # _join applies the scalar type's own top-`size` truncation rule.
    return TopkState(
        _join({}, ((w, int(best[i])) for w, i in idx.items() if best[i] > _I32_MIN), size),
        size,
    )


def _merge_leaderboard(states):
    import jax.numpy as jnp

    from ..models.leaderboard import (
        LeaderboardDenseState,
        LeaderboardState,
        NIL,
        _min_pair,
    )

    size = states[0].size
    if any(s.size != size for s in states):
        raise ValueError("cannot merge leaderboard states of different sizes")
    ids = sorted(
        {i for st in states for i in (*st.observed, *st.masked, *st.bans)}
    )
    if not ids:
        return LeaderboardState({}, {}, frozenset(), NIL, size)
    dense = _memo_dense("leaderboard", n_players=len(ids), size=size)
    idx = {w: i for i, w in enumerate(ids)}
    score = np.full((len(states), 1, len(ids)), _I32_MIN, np.int32)
    banned = np.zeros((len(states), 1, len(ids)), bool)
    for r, st in enumerate(states):
        for src in (st.observed, st.masked):
            for w, c in src.items():
                score[r, 0, idx[w]] = max(score[r, 0, idx[w]], _check_i32(c))
        for w in st.bans:
            banned[r, 0, idx[w]] = True
    folded = _batched_fold(
        dense.merge,
        LeaderboardDenseState(
            best_score=jnp.asarray(score), banned=jnp.asarray(banned)
        ),
    )
    f_score = np.asarray(folded.best_score)[0, 0]
    f_ban = np.asarray(folded.banned)[0, 0]
    live = [
        (w, int(f_score[i]))
        for w, i in idx.items()
        if f_score[i] > _I32_MIN and not f_ban[i]
    ]
    live.sort(key=lambda p: (p[1], p[0]), reverse=True)
    observed = dict(live[:size])
    masked = dict(live[size:])
    bans = frozenset(w for w, i in idx.items() if f_ban[i])
    return LeaderboardState(observed, masked, bans, _min_pair(observed), size)


# -- topk_rmv (full add-wins state) ----------------------------------------


def _merge_topk_rmv(states):
    import jax.numpy as jnp

    from ..models.topk_rmv import NIL, TopkRmvState, _min_observed
    from ..models.topk_rmv_dense import TopkRmvDenseState, _sort_slots

    size = states[0].size
    if any(s.size != size for s in states):
        raise ValueError("cannot merge topk_rmv states of different sizes")
    ids = sorted({i for st in states for i in (*st.masked, *st.removals)})
    dcs = sorted(
        {
            d
            for st in states
            for d in (
                *st.vc,
                *(d for vc in st.removals.values() for d in vc),
                *(e[2][0] for es in st.masked.values() for e in es),
            )
        }
    )
    if not ids and not dcs:
        return TopkRmvState({}, {}, {}, {}, NIL, size)
    U, D = max(len(ids), 1), max(len(dcs), 1)
    # Exact capacity: the union multiset of live adds per id.
    union: Dict[Any, set] = {}
    for st in states:
        for w, es in st.masked.items():
            union.setdefault(w, set()).update(es)
    M = max((len(es) for es in union.values()), default=1)
    if devprof.WARMUP:
        # Warm-up buckets (CCRDT_DEVPROF_WARMUP=1): pad capacities to
        # the next power of two so a stepping fleet's growing shapes
        # stay inside one jit bucket instead of recompiling per step.
        # Bit-identity safe: padded slots carry the absent-entry
        # sentinels (_I32_MIN score / 0 ts / 0 vc) that the extraction
        # loops below already skip.
        U, D, M = devprof.pad_dim(U), devprof.pad_dim(D), devprof.pad_dim(M)
    id_idx = {w: i for i, w in enumerate(ids)}
    dc_idx = {d: i for i, d in enumerate(dcs)}

    N = len(states)
    slot_score = np.full((N, 1, U, M), _I32_MIN, np.int32)
    slot_dc = np.zeros((N, 1, U, M), np.int32)
    slot_ts = np.zeros((N, 1, U, M), np.int32)
    rmv_vc = np.zeros((N, 1, U, D), np.int32)
    vc = np.zeros((N, 1, D), np.int32)
    for r, st in enumerate(states):
        for w, es in st.masked.items():
            for j, (s, _i, (d, t)) in enumerate(sorted(es)):
                slot_score[r, 0, id_idx[w], j] = _check_i32(s)
                slot_dc[r, 0, id_idx[w], j] = dc_idx[d]
                slot_ts[r, 0, id_idx[w], j] = _check_i32(t)
        for w, v in st.removals.items():
            for d, t in v.items():
                rmv_vc[r, 0, id_idx[w], dc_idx[d]] = _check_i32(t)
        for d, t in st.vc.items():
            vc[r, 0, dc_idx[d]] = _check_i32(t)

    dense = _memo_dense("topk_rmv", n_ids=U, n_dcs=D, size=size, slots_per_id=M)
    # Canonicalize rows to the slot invariant (sorted desc, dup-free) that
    # the rank-arithmetic merge requires, then fold.
    s_, d_, t_, _ = _sort_slots(
        jnp.asarray(slot_score), jnp.asarray(slot_dc), jnp.asarray(slot_ts), M
    )
    batch = TopkRmvDenseState(
        slot_score=s_, slot_dc=d_, slot_ts=t_,
        rmv_vc=jnp.asarray(rmv_vc), vc=jnp.asarray(vc),
        lossy=jnp.zeros((N, 1), bool),
    )
    folded = _batched_fold(dense.merge, batch)
    assert not bool(np.asarray(folded.lossy).any())  # capacity sized exactly

    f_score = np.asarray(folded.slot_score)[0, 0]
    f_dc = np.asarray(folded.slot_dc)[0, 0]
    f_ts = np.asarray(folded.slot_ts)[0, 0]
    f_rmv = np.asarray(folded.rmv_vc)[0, 0]
    f_vc = np.asarray(folded.vc)[0, 0]

    masked = {}
    for w, i in id_idx.items():
        es = frozenset(
            (int(f_score[i, j]), w, (dcs[f_dc[i, j]], int(f_ts[i, j])))
            for j in range(M)
            if f_ts[i, j] > 0
        )
        if es:
            masked[w] = es
    removals = {}
    for w, i in id_idx.items():
        v = {dcs[d]: int(f_rmv[i, d]) for d in range(D) if f_rmv[i, d]}
        if v:
            removals[w] = v
    out_vc = {dcs[d]: int(f_vc[d]) for d in range(D) if f_vc[d]}
    # Observed: top `size` per-id bests by cmp order (derived, like the
    # dense engine's observe).
    bests = [max(es) for es in masked.values()]
    bests.sort(reverse=True)
    observed = {e[1]: e for e in bests[:size]}
    return TopkRmvState(
        observed, masked, removals, out_vc, _min_observed(observed), size
    )


_MERGERS = {
    "average": _merge_average,
    "wordcount": _merge_wordcount,
    "worddocumentcount": _merge_wordcount,
    "topk": _merge_topk,
    "leaderboard": _merge_leaderboard,
    "topk_rmv": _merge_topk_rmv,
}
