from . import behaviour, clock, serial  # noqa: F401
