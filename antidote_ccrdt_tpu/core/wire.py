"""Reference-wire state conversion: our scalar states <-> the exact Erlang
terms the reference's ``to_binary/1`` produces.

Each CRDT's reference state shape (SURVEY.md §2):

    average            {Sum, Num}                       average.erl:57-58
    topk               {#{Id => Score}, Size}           topk.erl:55-58
    topk_rmv           {Obs, Masked, Removals, Vc,      topk_rmv.erl:67-74
                        Min, Size}
                         Obs      #{Id => {S,Id,{Dc,Ts}}}
                         Masked   #{Id => gb_set({S,Id,{Dc,Ts}})}
                         Removals #{Id => #{Dc => Ts}}
                         Vc       #{Dc => Ts}
                         Min      {S,Id,{Dc,Ts}} | {nil,nil,nil}
    leaderboard        {Obs, Masked, Bans, Min, Size}   leaderboard.erl:62-68
                         Obs/Masked #{Id => Score},
                         Bans sets:set(), Min {Id,S} | {nil,nil}
    wordcount          #{Word(binary) => Count}         wordcount.erl:44-48
    worddocumentcount  same shape                       worddocumentcount.erl

So a state snapshotted by a BEAM node via ``term_to_binary`` loads here
with ``from_reference_binary``, and states written by ``to_reference_binary``
load on the BEAM side with ``binary_to_term``. DC ids and element ids pass
through opaquely (ints, atoms, tuples, binaries all work — Antidote dcids
are arbitrary terms).
"""

from __future__ import annotations

from typing import Any, Dict

from . import etf
from .etf import Atom, NIL_ATOM

_NIL3 = (NIL_ATOM, NIL_ATOM, NIL_ATOM)
_NIL2 = (NIL_ATOM, NIL_ATOM)


def _id_to_term(x: Any) -> Any:
    return x.encode("utf-8") if isinstance(x, str) and not isinstance(x, Atom) else x


def _id_from_term(x: Any) -> Any:
    # Erlang has no string type — str ids encode as utf-8 binaries, so
    # utf-8 binaries decode back to str (non-utf-8 binaries stay bytes).
    # This makes state round-trips identity for str-keyed states and
    # term-level identity for BEAM snapshots (b"x" normalizes to "x" in
    # Python but re-encodes to the same binary).
    if isinstance(x, bytes):
        try:
            return x.decode("utf-8")
        except UnicodeDecodeError:
            return x
    return x


def _elem_to_term(e: Any) -> Any:
    if e is None or e == (None, None, None):
        return _NIL3
    s, i, (dc, ts) = e
    return (s, _id_to_term(i), (dc, ts))


def _elem_from_term(t: Any) -> Any:
    if t == _NIL3:
        return (None, None, None)
    s, i, (dc, ts) = t
    return (s, _id_from_term(i), (dc, ts))


# --- per-type converters --------------------------------------------------


def _average_to_term(state: Any) -> Any:
    s, n = state
    return (s, n)


def _average_from_term(term: Any) -> Any:
    s, n = term
    return (int(s), int(n))


def _topk_to_term(state: Any) -> Any:
    return ({_id_to_term(k): v for k, v in state.entries.items()}, state.size)


def _topk_from_term(term: Any) -> Any:
    from ..models.topk import TopkState

    entries, size = term
    return TopkState({_id_from_term(k): int(v) for k, v in entries.items()}, int(size))


def _topk_rmv_to_term(state: Any) -> Any:
    obs = {_id_to_term(k): _elem_to_term(v) for k, v in state.observed.items()}
    masked = {
        _id_to_term(k): etf.gb_set_from_list([_elem_to_term(e) for e in v])
        for k, v in state.masked.items()
    }
    removals = {_id_to_term(k): dict(v) for k, v in state.removals.items()}
    return (obs, masked, removals, dict(state.vc), _elem_to_term(state.min), state.size)


def _topk_rmv_from_term(term: Any) -> Any:
    from ..models.topk_rmv import TopkRmvState

    obs_t, masked_t, removals_t, vc_t, min_t, size = term
    obs = {_id_from_term(k): _elem_from_term(v) for k, v in obs_t.items()}
    masked = {
        _id_from_term(k): frozenset(_elem_from_term(e) for e in etf.gb_set_to_list(v))
        for k, v in masked_t.items()
    }
    removals = {_id_from_term(k): {dc: int(ts) for dc, ts in v.items()} for k, v in removals_t.items()}
    vc = {dc: int(ts) for dc, ts in vc_t.items()}
    return TopkRmvState(obs, masked, removals, vc, _elem_from_term(min_t), int(size))


def _leaderboard_to_term(state: Any) -> Any:
    obs = {_id_to_term(k): v for k, v in state.observed.items()}
    masked = {_id_to_term(k): v for k, v in state.masked.items()}
    bans = etf.set_from_list(_id_to_term(x) for x in state.bans)
    mn = _NIL2 if state.min == (None, None) else (_id_to_term(state.min[0]), state.min[1])
    return (obs, masked, bans, mn, state.size)


def _leaderboard_from_term(term: Any) -> Any:
    from ..models.leaderboard import LeaderboardState

    obs_t, masked_t, bans_t, min_t, size = term
    mn = (None, None) if min_t == _NIL2 else (_id_from_term(min_t[0]), int(min_t[1]))
    return LeaderboardState(
        {_id_from_term(k): int(v) for k, v in obs_t.items()},
        {_id_from_term(k): int(v) for k, v in masked_t.items()},
        frozenset(_id_from_term(x) for x in etf.set_to_list(bans_t)),
        mn,
        int(size),
    )


def _wordcount_to_term(state: Dict[str, int]) -> Any:
    return {_id_to_term(k): v for k, v in state.items()}


def _wordcount_from_term(term: Any) -> Any:
    return {_id_from_term(k): int(v) for k, v in term.items()}


_TO = {
    "average": _average_to_term,
    "topk": _topk_to_term,
    "topk_rmv": _topk_rmv_to_term,
    "leaderboard": _leaderboard_to_term,
    "wordcount": _wordcount_to_term,
    "worddocumentcount": _wordcount_to_term,
}

_FROM = {
    "average": _average_from_term,
    "topk": _topk_from_term,
    "topk_rmv": _topk_rmv_from_term,
    "leaderboard": _leaderboard_from_term,
    "wordcount": _wordcount_from_term,
    "worddocumentcount": _wordcount_from_term,
}


def state_to_term(name: str, state: Any) -> Any:
    """Our scalar state -> the reference's internal state term."""
    return _TO[name](state)


def state_from_term(name: str, term: Any) -> Any:
    """The reference's internal state term -> our scalar state."""
    return _FROM[name](term)


def to_reference_binary(name: str, state: Any, compressed: bool = False) -> bytes:
    """``Mod:to_binary(State)``-compatible bytes for our scalar state."""
    return etf.encode(state_to_term(name, state), compressed=compressed)


def from_reference_binary(name: str, data: bytes) -> Any:
    """Load bytes produced by the reference's ``to_binary/1`` (or ours)."""
    return state_from_term(name, etf.decode(data))
